"""BallotProtocol: PREPARE → CONFIRM → EXTERNALIZE federated voting.

Role parity: reference `src/scp/BallotProtocol.{h,cpp}` (2,244 lines; state
machine entry points attemptAcceptPrepared / attemptConfirmPrepared /
attemptAcceptCommit / attemptConfirmCommit, BallotProtocol.h:183-200).
Implemented from the SCP internet-draft semantics:

- a ballot is (counter, value); ballots totally ordered lexicographically,
  "compatible" = same value.
- PREPARE statement (b, p, p', nC, nH): votes prepare(b); accepts
  prepare(p) and prepare(p'); votes commit(counters [nC, nH], b.value)
  when nC > 0.
- CONFIRM statement (b, nPrepared, nCommit, nH): accepts
  prepare((nPrepared, b.value)); votes commit([nCommit, ∞), b.value);
  accepts commit([nCommit, nH], b.value).
- EXTERNALIZE statement (commit, nH): accepts commit([commit.counter, ∞)),
  accepts prepare((∞, commit.value)).

federated-accept(stmt-votes, stmt-accepts) = v-blocking set accepts, OR a
quorum votes-or-accepts. federated-ratify = quorum accepts.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from ..xdr import (
    SCPBallot, SCPConfirm, SCPEnvelope, SCPExternalize, SCPPledges,
    SCPPrepare, SCPStatement, SCPStatementType, Value,
)
from .local_node import LocalNode

UINT32_MAX = 2**32 - 1

Ballot = Tuple[int, bytes]  # (counter, value)


def _bt(b: SCPBallot) -> Ballot:
    return (b.counter, b.value)


def _mk(b: Ballot) -> SCPBallot:
    return SCPBallot(counter=b[0], value=b[1])


def compatible(a: Ballot, b: Ballot) -> bool:
    return a[1] == b[1]


def less_and_compatible(a: Ballot, b: Ballot) -> bool:
    return a <= b and compatible(a, b)


def less_and_incompatible(a: Ballot, b: Ballot) -> bool:
    return a <= b and not compatible(a, b)


class SCPPhase:
    PREPARE = 0
    CONFIRM = 1
    EXTERNALIZE = 2


class BallotProtocol:
    def __init__(self, slot) -> None:
        self.slot = slot
        self.phase = SCPPhase.PREPARE
        self.b: Optional[Ballot] = None          # current ballot
        self.p: Optional[Ballot] = None          # prepared
        self.pp: Optional[Ballot] = None         # prepared prime
        self.c: Optional[Ballot] = None          # commit (low)
        self.h: Optional[Ballot] = None          # high
        self.value_override: Optional[bytes] = None
        self.latest_envelopes: Dict[bytes, SCPEnvelope] = {}
        self.last_envelope: Optional[SCPEnvelope] = None
        self.last_envelope_emit: Optional[SCPEnvelope] = None
        self.heard_from_quorum = False
        self.current_message_level = 0

    # ------------------------------------------------------------------ util
    def _driver(self):
        return self.slot.scp.driver

    def _journal_phase(self, phase_name: str, **tags) -> None:
        """Ballot phase transitions (PREPARE→CONFIRM→EXTERNALIZE) into
        the per-slot timeline (util/slot_timeline.py)."""
        tl = getattr(self.slot.scp.driver, "timeline", None)
        if tl is not None:
            tl.record(self.slot.slot_index, "ballot.phase." + phase_name,
                      dedupe=True, **tags)

    def _local(self) -> LocalNode:
        return self.slot.scp.local_node

    def _qset_of(self, st: SCPStatement):
        return self.slot.get_quorum_set_from_statement(st)

    # -------------------------------------------------- statement predicates
    @staticmethod
    def statement_ballot_counter(st: SCPStatement) -> int:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            return st.pledges.value.ballot.counter
        if t == SCPStatementType.SCP_ST_CONFIRM:
            return st.pledges.value.ballot.counter
        return UINT32_MAX  # EXTERNALIZE

    @staticmethod
    def is_statement_sane(st: SCPStatement, is_self: bool) -> bool:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            b, pr, ppr = _bt(p.ballot), p.prepared, p.preparedPrime
            if not (is_self or b[0] > 0):
                return False
            if pr is not None and ppr is not None:
                if not (_bt(ppr) < _bt(pr) and
                        not compatible(_bt(ppr), _bt(pr))):
                    return False
            if ppr is not None and pr is None:
                return False
            if p.nH > 0 and (pr is None or p.nH > pr.counter):
                return False
            if p.nC > 0 and not (p.nH > 0 and b[0] >= p.nH >= p.nC):
                return False
            return True
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            return (c.ballot.counter > 0 and c.nH <= c.ballot.counter and
                    c.nCommit <= c.nH)
        if t == SCPStatementType.SCP_ST_EXTERNALIZE:
            e = st.pledges.value
            return e.commit.counter > 0 and e.nH >= e.commit.counter
        return False

    # "st accepts prepare(ballot)"
    @staticmethod
    def has_prepared_ballot(ballot: Ballot, st: SCPStatement) -> bool:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            return ((p.prepared is not None and
                     less_and_compatible(ballot, _bt(p.prepared))) or
                    (p.preparedPrime is not None and
                     less_and_compatible(ballot, _bt(p.preparedPrime))))
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            prepared = (c.nPrepared, c.ballot.value)
            return less_and_compatible(ballot, prepared)
        e = st.pledges.value
        return compatible(ballot, (0, e.commit.value))

    # "st votes prepare(ballot)" (vote-or-accept)
    @staticmethod
    def votes_prepared(ballot: Ballot, st: SCPStatement) -> bool:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            return (less_and_compatible(ballot, _bt(p.ballot)) or
                    BallotProtocol.has_prepared_ballot(ballot, st))
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            return compatible(ballot, (0, c.ballot.value))
        e = st.pledges.value
        return compatible(ballot, (0, e.commit.value))

    # commit interval predicates for value v over [lo, hi]
    @staticmethod
    def accepts_commit(v: bytes, lo: int, hi: int,
                       st: SCPStatement) -> bool:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            return (c.ballot.value == v and
                    c.nCommit <= lo and hi <= c.nH)
        if t == SCPStatementType.SCP_ST_EXTERNALIZE:
            e = st.pledges.value
            return e.commit.value == v and e.commit.counter <= lo
        return False

    @staticmethod
    def votes_commit(v: bytes, lo: int, hi: int, st: SCPStatement) -> bool:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            return (p.ballot.value == v and p.nC > 0 and
                    p.nC <= lo and hi <= p.nH)
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            return c.ballot.value == v and c.nCommit <= lo
        e = st.pledges.value
        return e.commit.value == v and e.commit.counter <= lo

    # ------------------------------------------------------ federated voting
    def _federated_accept(self, votes_pred: Callable, accepted_pred) -> bool:
        local = self._local()
        if LocalNode.is_v_blocking_filter(
                local.qset, self.latest_envelopes.values(), accepted_pred):
            return True

        def vote_or_accept(st: SCPStatement) -> bool:
            return votes_pred(st) or accepted_pred(st)
        return LocalNode.is_quorum(
            local.qset, self.latest_envelopes, self._qset_of,
            vote_or_accept)

    def _federated_ratify(self, accepted_pred: Callable) -> bool:
        return LocalNode.is_quorum(
            self._local().qset, self.latest_envelopes, self._qset_of,
            accepted_pred)

    # --------------------------------------------------------------- intake
    class EnvelopeState:
        INVALID = 0
        VALID = 1

    def process_envelope(self, envelope: SCPEnvelope, is_self: bool) -> int:
        st = envelope.statement
        nb = st.nodeID.key_bytes
        if not self.is_statement_sane(st, is_self):
            return self.EnvelopeState.INVALID
        old = self.latest_envelopes.get(nb)
        if old is not None and not self._is_newer(st, old.statement):
            return self.EnvelopeState.INVALID
        if not is_self and not self._validate_values(st):
            return self.EnvelopeState.INVALID
        self.latest_envelopes[nb] = envelope
        self.advance_slot(st)
        return self.EnvelopeState.VALID

    @staticmethod
    def _is_newer(st: SCPStatement, old: SCPStatement) -> bool:
        tn, to = st.pledges.disc, old.pledges.disc
        if tn != to:
            order = {SCPStatementType.SCP_ST_PREPARE: 0,
                     SCPStatementType.SCP_ST_CONFIRM: 1,
                     SCPStatementType.SCP_ST_EXTERNALIZE: 2}
            return order[tn] > order[to]
        if tn == SCPStatementType.SCP_ST_PREPARE:
            a, b = st.pledges.value, old.pledges.value
            key_a = (_bt(a.ballot),
                     _bt(a.prepared) if a.prepared else (0, b""),
                     _bt(a.preparedPrime) if a.preparedPrime else (0, b""),
                     a.nH)
            key_b = (_bt(b.ballot),
                     _bt(b.prepared) if b.prepared else (0, b""),
                     _bt(b.preparedPrime) if b.preparedPrime else (0, b""),
                     b.nH)
            return key_a > key_b
        if tn == SCPStatementType.SCP_ST_CONFIRM:
            a, b = st.pledges.value, old.pledges.value
            ka = (_bt(a.ballot), a.nPrepared, a.nCommit, a.nH)
            kb = (_bt(b.ballot), b.nPrepared, b.nCommit, b.nH)
            return ka > kb
        return False  # EXTERNALIZE statements are final

    def _validate_values(self, st: SCPStatement) -> bool:
        from .driver import ValidationLevel
        values = set()
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            if p.ballot.counter:
                values.add(p.ballot.value)
            if p.prepared is not None:
                values.add(p.prepared.value)
        elif t == SCPStatementType.SCP_ST_CONFIRM:
            values.add(st.pledges.value.ballot.value)
        else:
            values.add(st.pledges.value.commit.value)
        for v in values:
            lvl = self._driver().validate_value(self.slot.slot_index, v,
                                                False)
            if lvl == ValidationLevel.INVALID:
                return False
        return True

    # -------------------------------------------------------------- bumping
    def bump_state(self, value: bytes, force: bool = True,
                   counter: Optional[int] = None) -> bool:
        """Move to ballot (counter, value) — reference bumpState. The value
        is overridden by value_override once a confirmed-prepared /
        accepted-commit value is locked in."""
        if counter is None:
            if not force and self.b is not None:
                return False
            counter = 1 if self.b is None else self.b[0] + 1
        if self.phase not in (SCPPhase.PREPARE, SCPPhase.CONFIRM):
            return False
        new_b = (counter, self.value_override
                 if self.value_override is not None else value)
        updated = self._update_current_value(new_b)
        if updated:
            self._emit_current_statement()
            self._check_heard_from_quorum()
        return updated

    def _update_current_value(self, ballot: Ballot) -> bool:
        if self.phase not in (SCPPhase.PREPARE, SCPPhase.CONFIRM):
            return False
        updated = False
        if self.b is None:
            updated = True
        else:
            # never change the value once committed to one
            if self.c is not None and not compatible(self.c, ballot):
                return False
            if self.b < ballot:
                updated = True
            elif self.b > ballot:
                return False  # never go backwards
        if updated:
            self._bump_to_ballot(ballot, True)
        return updated

    def _bump_to_ballot(self, ballot: Ballot, check: bool) -> None:
        assert self.phase != SCPPhase.EXTERNALIZE
        if check:
            assert self.b is None or ballot >= self.b
        got_bumped = self.b is None or self.b[0] != ballot[0]
        if self.b is None:
            self._driver().started_ballot_protocol(
                self.slot.slot_index, _mk(ballot))
        self.b = ballot
        if got_bumped:
            # a new counter starts a new "heard from quorum" round
            self.heard_from_quorum = False
            ss = getattr(self._driver(), "scp_stats", None)
            if ss is not None:
                # consensus cockpit (ISSUE 19): ballot-round inflation
                # (counter climb) per slot
                ss.ballot_bumped(self.slot.slot_index, ballot[0])

    def abandon_ballot(self, n: int = 0) -> bool:
        """Timer fired or v-blocking ahead: move to a higher counter with
        the best known value (reference abandonBallot)."""
        v = self.slot.get_latest_composite_candidate()
        if not v and self.b is not None:
            v = self.b[1]
        if not v:
            return False
        if n == 0:
            return self.bump_state(v, True)
        return self.bump_state(v, True, n)

    # ------------------------------------------------------- advance engine
    def advance_slot(self, hint: SCPStatement) -> None:
        """One pass of the protocol steps, in whitepaper order. State
        changes re-enter via self-processing in _emit_current_statement;
        the emitted envelope is consolidated: only the LATEST statement is
        sent, once, when the outermost advance pass unwinds (reference
        advanceSlot/sendLatestEnvelope — this is why cascaded transitions
        produce exactly one wire message)."""
        self.current_message_level += 1
        if self.current_message_level >= 50:
            raise RuntimeError("maximum number of transitions reached")
        did = self.attempt_accept_prepared(hint)
        did = self.attempt_confirm_prepared(hint) or did
        did = self.attempt_accept_commit(hint) or did
        did = self.attempt_confirm_commit(hint) or did
        if self.current_message_level == 1:
            did_bump = True
            while did_bump:
                did_bump = self._attempt_bump()
                did = did_bump or did
            self._check_heard_from_quorum()
        self.current_message_level -= 1
        if did:
            self._send_latest_envelope()

    # prepare candidates: ballots from the hint, intersected downward with
    # everything nodes have claimed (reference getPrepareCandidates)
    def _prepare_candidates(self, hint: SCPStatement) -> List[Ballot]:
        hint_ballots: Set[Ballot] = set()
        t = hint.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = hint.pledges.value
            hint_ballots.add(_bt(p.ballot))
            if p.prepared is not None:
                hint_ballots.add(_bt(p.prepared))
            if p.preparedPrime is not None:
                hint_ballots.add(_bt(p.preparedPrime))
        elif t == SCPStatementType.SCP_ST_CONFIRM:
            c = hint.pledges.value
            hint_ballots.add((c.nPrepared, c.ballot.value))
            hint_ballots.add((UINT32_MAX, c.ballot.value))
        else:
            e = hint.pledges.value
            hint_ballots.add((UINT32_MAX, e.commit.value))

        out: Set[Ballot] = set()
        for top in hint_ballots:
            val = top[1]
            for env in self.latest_envelopes.values():
                st = env.statement
                tt = st.pledges.disc
                if tt == SCPStatementType.SCP_ST_PREPARE:
                    pp_ = st.pledges.value
                    if less_and_compatible(_bt(pp_.ballot), top):
                        out.add(_bt(pp_.ballot))
                    if pp_.prepared is not None and \
                            less_and_compatible(_bt(pp_.prepared), top):
                        out.add(_bt(pp_.prepared))
                    if pp_.preparedPrime is not None and \
                            less_and_compatible(_bt(pp_.preparedPrime), top):
                        out.add(_bt(pp_.preparedPrime))
                elif tt == SCPStatementType.SCP_ST_CONFIRM:
                    cc = st.pledges.value
                    if compatible(top, _bt(cc.ballot)):
                        out.add(top)
                        if cc.nPrepared < top[0]:
                            out.add((cc.nPrepared, val))
                else:
                    ee = st.pledges.value
                    if compatible(top, _bt(ee.commit)):
                        out.add(top)
        return sorted(out, reverse=True)

    def attempt_accept_prepared(self, hint: SCPStatement) -> bool:
        if self.phase not in (SCPPhase.PREPARE, SCPPhase.CONFIRM):
            return False
        for cand in self._prepare_candidates(hint):
            if self.phase == SCPPhase.CONFIRM:
                # can only augment the prepared interval around the commit
                if not (self.p is not None and
                        less_and_compatible(self.p, cand)):
                    continue
            if self.pp is not None and cand <= self.pp:
                continue  # would help neither p nor p'
            if self.p is not None and less_and_compatible(cand, self.p):
                continue  # already covered by p
            if self._federated_accept(
                    lambda st, c=cand: self.votes_prepared(c, st),
                    lambda st, c=cand: self.has_prepared_ballot(c, st)):
                return self._set_accept_prepared(cand)
        return False

    def _set_accept_prepared(self, ballot: Ballot) -> bool:
        did = self._set_prepared(ballot)
        # an accepted-prepared ballot above h and incompatible with it
        # aborts the pending commit votes
        if self.c is not None and self.h is not None:
            if (self.p is not None and
                    less_and_incompatible(self.h, self.p)) or \
                    (self.pp is not None and
                     less_and_incompatible(self.h, self.pp)):
                assert self.phase == SCPPhase.PREPARE
                self.c = None
                did = True
        if did:
            self._driver().accepted_ballot_prepared(self.slot.slot_index,
                                                    _mk(ballot))
            self._emit_current_statement()
        return did

    def _set_prepared(self, ballot: Ballot) -> bool:
        did = False
        if self.p is not None:
            if self.p < ballot:
                if not compatible(self.p, ballot):
                    self.pp = self.p  # displaced p becomes p'
                self.p = ballot
                did = True
            elif self.p > ballot:
                if self.pp is None or (self.pp < ballot and
                                       not compatible(self.p, ballot)):
                    self.pp = ballot
                    did = True
        else:
            self.p = ballot
            did = True
        return did

    def attempt_confirm_prepared(self, hint: SCPStatement) -> bool:
        if self.phase != SCPPhase.PREPARE or self.p is None:
            return False
        candidates = self._prepare_candidates(hint)
        new_h = None
        idx = 0
        for i, cand in enumerate(candidates):
            if self.h is not None and self.h >= cand:
                break  # can't raise h
            if self._federated_ratify(
                    lambda st, c=cand: self.has_prepared_ballot(c, st)):
                new_h = cand
                idx = i
                break
        if new_h is None:
            return False
        # extend downward to the lowest ratified c >= b (step 3), unless a
        # commit is already set or h is aborted by p/p'
        new_c: Optional[Ballot] = None
        b = self.b if self.b is not None else (0, b"")
        if self.c is None and \
                (self.p is None or
                 not less_and_incompatible(new_h, self.p)) and \
                (self.pp is None or
                 not less_and_incompatible(new_h, self.pp)):
            for cand in candidates[idx:]:
                if cand < b:
                    break
                if not less_and_compatible(cand, new_h):
                    continue
                if self._federated_ratify(
                        lambda st, c=cand: self.has_prepared_ballot(c, st)):
                    new_c = cand
                else:
                    break
        return self._set_confirm_prepared(new_c, new_h)

    def _set_confirm_prepared(self, new_c: Optional[Ballot],
                              new_h: Ballot) -> bool:
        did = False
        self.value_override = new_h[1]
        # c/h only move while we're on a compatible ballot
        if self.b is None or compatible(self.b, new_h):
            if self.h is None or new_h > self.h:
                self.h = new_h
                did = True
            if new_c is not None:
                assert self.c is None
                self.c = new_c
                did = True
            if did:
                self._driver().confirmed_ballot_prepared(
                    self.slot.slot_index, _mk(new_h))
        # always perform step (8) with the computed h
        did = self._update_current_if_needed(new_h) or did
        if did:
            self._emit_current_statement()
        return did

    def _update_current_if_needed(self, h: Ballot) -> bool:
        if self.b is None or self.b < h:
            self._bump_to_ballot(h, True)
            return True
        return False

    # commit boundaries for statements compatible with ballot's value
    def _commit_boundaries(self, ballot: Ballot) -> List[int]:
        out: Set[int] = set()
        v = ballot[1]
        for env in self.latest_envelopes.values():
            st = env.statement
            t = st.pledges.disc
            if t == SCPStatementType.SCP_ST_PREPARE:
                p = st.pledges.value
                if p.ballot.value == v and p.nC:
                    out.add(p.nC)
                    out.add(p.nH)
            elif t == SCPStatementType.SCP_ST_CONFIRM:
                c = st.pledges.value
                if c.ballot.value == v:
                    out.add(c.nCommit)
                    out.add(c.nH)
            else:
                e = st.pledges.value
                if e.commit.value == v:
                    out.add(e.commit.counter)
                    out.add(e.nH)
                    out.add(UINT32_MAX)  # externalize accepts [c, ∞)
        return sorted(out)

    def _find_extended_interval(self, ballot: Ballot, pred) -> Optional[
            Tuple[int, int]]:
        """Largest [lo, hi] over the boundary grid where pred holds,
        scanning from the top (reference findExtendedInterval)."""
        best: Optional[Tuple[int, int]] = None
        for bval in reversed(self._commit_boundaries(ballot)):
            if best is None:
                cand = (bval, bval)
            elif bval > best[1]:
                continue
            else:
                cand = (bval, best[1])
            if pred(cand[0], cand[1]):
                best = cand
            elif best is not None:
                break
        return best

    @staticmethod
    def _hint_commit_ballot(hint: SCPStatement) -> Optional[Ballot]:
        """(nH, value) the hint pushes toward committing; None if none."""
        t = hint.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = hint.pledges.value
            if p.nC == 0:
                return None
            return (p.nH, p.ballot.value)
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = hint.pledges.value
            return (c.nH, c.ballot.value)
        e = hint.pledges.value
        return (e.nH, e.commit.value)

    def attempt_accept_commit(self, hint: SCPStatement) -> bool:
        if self.phase not in (SCPPhase.PREPARE, SCPPhase.CONFIRM):
            return False
        ballot = self._hint_commit_ballot(hint)
        if ballot is None:
            return False
        if self.phase == SCPPhase.CONFIRM and \
                not compatible(ballot, self.h):
            return False
        v = ballot[1]

        def pred(lo: int, hi: int) -> bool:
            return self._federated_accept(
                lambda st: self.votes_commit(v, lo, hi, st),
                lambda st: self.accepts_commit(v, lo, hi, st))

        interval = self._find_extended_interval(ballot, pred)
        if interval is None or interval[0] == 0:
            return False  # reference rejects lo=0 (nCommit=0 statements)
        lo, hi = interval
        if self.phase == SCPPhase.CONFIRM and hi <= self.h[0]:
            return False  # nothing gained
        return self._set_accept_commit((lo, v), (hi, v))

    def _set_accept_commit(self, c: Ballot, h: Ballot) -> bool:
        did = False
        self.value_override = h[1]
        if self.h != h or self.c != c:
            self.c = c
            self.h = h
            did = True
        if self.phase == SCPPhase.PREPARE:
            self.phase = SCPPhase.CONFIRM
            self._journal_phase("confirm", counter=h[0])
            if self.b is not None and not less_and_compatible(h, self.b):
                self._bump_to_ballot(h, False)
            self.pp = None
            did = True
        if did:
            self._update_current_if_needed(self.h)
            self._driver().accepted_commit(self.slot.slot_index, _mk(h))
            self._emit_current_statement()
        return did

    def attempt_confirm_commit(self, hint: SCPStatement) -> bool:
        if self.phase != SCPPhase.CONFIRM or \
                self.h is None or self.c is None:
            return False
        if hint.pledges.disc == SCPStatementType.SCP_ST_PREPARE:
            return False
        ballot = self._hint_commit_ballot(hint)
        if ballot is None or not compatible(ballot, self.c):
            return False
        v = ballot[1]

        def pred(lo: int, hi: int) -> bool:
            return self._federated_ratify(
                lambda st: self.accepts_commit(v, lo, hi, st))

        interval = self._find_extended_interval(ballot, pred)
        if interval is None or interval[0] == 0:
            return False  # reference rejects lo=0
        lo, hi = interval
        return self._set_confirm_commit((lo, v), (hi, v))

    def _set_confirm_commit(self, c: Ballot, h: Ballot) -> bool:
        self.c = c
        self.h = h
        self._update_current_if_needed(h)
        self.phase = SCPPhase.EXTERNALIZE
        self._journal_phase("externalize", counter=c[0])
        self._emit_current_statement()
        self.slot.stop_nomination()
        self._driver().value_externalized(self.slot.slot_index, c[1])
        return True

    def _attempt_bump(self) -> bool:
        """A v-blocking set is strictly ahead → jump to the minimal counter
        at which that stops being true (reference attemptBump)."""
        if self.phase not in (SCPPhase.PREPARE, SCPPhase.CONFIRM):
            return False
        local_counter = self.b[0] if self.b is not None else 0

        def vblocking_ahead_of(n: int) -> bool:
            return LocalNode.is_v_blocking_filter(
                self._local().qset, self.latest_envelopes.values(),
                lambda st, n=n: self.statement_ballot_counter(st) > n)

        if not vblocking_ahead_of(local_counter):
            return False
        counters = sorted({self.statement_ballot_counter(e.statement)
                           for e in self.latest_envelopes.values()
                           if self.statement_ballot_counter(e.statement)
                           > local_counter})
        for n in counters:
            if not vblocking_ahead_of(n):
                return self.abandon_ballot(n)
        return False

    # ------------------------------------------------------ timers / quorum
    def _check_heard_from_quorum(self) -> None:
        """Reference semantics (BallotProtocol.cpp:2163-2213): a node has
        "heard from quorum" when a quorum is at-or-past its ballot counter —
        PREPARE statements filter by counter, CONFIRM/EXTERNALIZE always
        count (their counters only move forward). The ballot timer starts
        only on the not-heard → heard transition and is cancelled when the
        quorum falls behind (local counter bumped) or on EXTERNALIZE."""
        if self.b is None:
            return
        bn = self.b[0]

        def pred(st: SCPStatement) -> bool:
            if st.pledges.disc == SCPStatementType.SCP_ST_PREPARE:
                return bn <= st.pledges.value.ballot.counter
            return True
        if LocalNode.is_quorum(self._local().qset, self.latest_envelopes,
                               self._qset_of, pred):
            was = self.heard_from_quorum
            self.heard_from_quorum = True
            if not was:
                self._driver().ballot_did_hear_from_quorum(
                    self.slot.slot_index, _mk(self.b))
                if self.phase != SCPPhase.EXTERNALIZE:
                    self._start_timer()
            if self.phase == SCPPhase.EXTERNALIZE:
                self._stop_timer()
        else:
            self.heard_from_quorum = False
            self._stop_timer()

    def _start_timer(self) -> None:
        from .driver import SCPTimerID
        timeout = self._driver().compute_timeout(self.b[0])
        self._driver().setup_timer(
            self.slot.slot_index, SCPTimerID.BALLOT, timeout,
            self._on_timeout)

    def _stop_timer(self) -> None:
        from .driver import SCPTimerID
        self._driver().setup_timer(
            self.slot.slot_index, SCPTimerID.BALLOT, 0.0, None)

    def _on_timeout(self) -> None:
        self.abandon_ballot(0)

    # ------------------------------------------------------------- emission
    def _make_statement(self) -> SCPStatement:
        local = self._local()
        qh = local.qset_hash
        if self.phase == SCPPhase.PREPARE:
            pl = SCPPledges(
                SCPStatementType.SCP_ST_PREPARE,
                SCPPrepare(
                    quorumSetHash=qh,
                    ballot=_mk(self.b) if self.b else SCPBallot(
                        counter=0, value=b""),
                    prepared=_mk(self.p) if self.p else None,
                    preparedPrime=_mk(self.pp) if self.pp else None,
                    nC=self.c[0] if self.c else 0,
                    nH=self.h[0] if self.h else 0))
        elif self.phase == SCPPhase.CONFIRM:
            pl = SCPPledges(
                SCPStatementType.SCP_ST_CONFIRM,
                SCPConfirm(ballot=_mk(self.b),
                           nPrepared=self.p[0],
                           nCommit=self.c[0], nH=self.h[0],
                           quorumSetHash=qh))
        else:
            pl = SCPPledges(
                SCPStatementType.SCP_ST_EXTERNALIZE,
                SCPExternalize(commit=_mk(self.c), nH=self.h[0],
                               commitQuorumSetHash=qh))
        return SCPStatement(nodeID=local.node_id,
                            slotIndex=self.slot.slot_index, pledges=pl)

    def _emit_current_statement(self) -> None:
        """Record the new statement and process it as our own (re-entering
        advance_slot). The envelope is only SENT when the outermost advance
        pass unwinds — see advance_slot."""
        st = self._make_statement()
        env = self.slot.create_envelope(st)
        can_emit = self.b is not None
        own = self.latest_envelopes.get(self._local().node_id.key_bytes)
        if own is not None and own.statement.to_xdr() == st.to_xdr():
            return  # same statement; h.value can differ while h.n doesn't
        if self.process_envelope(env, is_self=True) != \
                self.EnvelopeState.VALID:
            # The statement total order is (type, b, p, p', h) — it does not
            # cover nC. A c-only update (e.g. confirm-prepared sets c after
            # an incompatible-b pass already emitted the same (b,p,p',h))
            # ties in that order. The reference's own test vectors require
            # the new commit vote to be visible to subsequent quorum math in
            # the same cascade, so record it for ourselves; it is never sent
            # (last_envelope keeps the strict order), and a genuinely
            # regressed statement is a protocol bug.
            if own is not None and self.is_statement_sane(st, True) and \
                    not self._is_newer(own.statement, st):
                self.latest_envelopes[
                    self._local().node_id.key_bytes] = env
                return
            raise RuntimeError("moved to a bad state (ballot protocol)")
        if can_emit and (self.last_envelope is None or
                         self._is_newer(st, self.last_envelope.statement)):
            self.last_envelope = env
            self._send_latest_envelope()

    def _send_latest_envelope(self) -> None:
        if self.current_message_level == 0 and \
                self.last_envelope is not None and self.slot.fully_validated:
            if self.last_envelope_emit is not self.last_envelope:
                self.last_envelope_emit = self.last_envelope
                if self._local().is_validator:
                    self._driver().emit_envelope(self.last_envelope)

    def set_state_from_envelope(self, envelope: SCPEnvelope) -> None:
        """Restore persisted own state directly (reference
        setStateFromEnvelope) — no federated processing, just the statement
        fields back into b/p/p'/c/h and the phase."""
        if self.b is not None:
            raise RuntimeError(
                "cannot set state after starting ballot protocol")
        st = envelope.statement
        self.latest_envelopes[st.nodeID.key_bytes] = envelope
        self.last_envelope = envelope
        self.last_envelope_emit = envelope
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            b = _bt(p.ballot)
            self._bump_to_ballot(b, True)
            if p.prepared is not None:
                self.p = _bt(p.prepared)
            if p.preparedPrime is not None:
                self.pp = _bt(p.preparedPrime)
            if p.nH:
                self.h = (p.nH, b[1])
            if p.nC:
                self.c = (p.nC, b[1])
            self.phase = SCPPhase.PREPARE
        elif t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            v = c.ballot.value
            self._bump_to_ballot(_bt(c.ballot), True)
            self.p = (c.nPrepared, v)
            self.h = (c.nH, v)
            self.c = (c.nCommit, v)
            self.phase = SCPPhase.CONFIRM
        else:
            e = st.pledges.value
            v = e.commit.value
            self._bump_to_ballot((UINT32_MAX, v), True)
            self.p = (UINT32_MAX, v)
            self.h = (e.nH, v)
            self.c = _bt(e.commit)
            self.phase = SCPPhase.EXTERNALIZE

    # --------------------------------------------------------------- state
    def get_json_info(self) -> dict:
        phase_names = {0: "PREPARE", 1: "CONFIRM", 2: "EXTERNALIZE"}
        return {
            "phase": phase_names[self.phase],
            "ballot": {"counter": self.b[0]} if self.b else None,
            "prepared": {"counter": self.p[0]} if self.p else None,
            "heard": self.heard_from_quorum,
        }

    def externalized_value(self) -> Optional[bytes]:
        if self.phase == SCPPhase.EXTERNALIZE:
            return self.c[1]
        return None
