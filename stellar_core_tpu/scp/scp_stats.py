"""ScpStats: the consensus cockpit's shared aggregation (ISSUE 19
tentpole; docs/observability.md#consensus-cockpit).

The seventh cockpit. Six cockpits aim every subsystem *except the one
the paper is about*: SCP itself had only the slot-timeline event
journal. This module turns those journaled stamps into attribution —

- **per-slot phase latencies** (nominate-trigger → first-candidate →
  prepare → confirm → externalize), DERIVED from the same stamps the
  slot timeline journals (`_phase_report` reads them back via
  `SlotTimeline.first`), so the cockpit and the journal reconcile by
  construction — there is one slot-latency definition, anchored at the
  `nominate.trigger` stamp (docs/observability.md#slot-latency-anchor);
- **nomination/ballot round counts** and **timer-fire attribution**:
  which timer (nomination vs ballot), which round it was armed for, and
  whether it fired or was cancelled/re-armed — ballot-round inflation
  and timer-fire storms are the stuck-slot smoke signals;
- **per-statement-type envelopes-per-slot** (sent AND received) — the
  committed O(n²) flood baseline that ROADMAP item 1's BLS aggregate
  quorum certificates must beat (EdDSA-vs-BLS committee study,
  PAPERS.md 2302.00418);
- **per-peer envelope lag**: each peer's first arrival for a slot
  relative to the slot-local first arrival — straggler attribution at
  the consensus layer;
- **quorum health**: validators missing entirely or behind by
  latest-seen ledger seq, and stuck-slot diagnosis naming WHICH
  quorum-slice members are absent from an open slot.

Pattern parity with the other cockpits (ApplyStats et al.): injected
app clock (`now_fn` — sctlint D1 holds, virtual-clock simulations stay
deterministic), private-registry default so direct constructions stay
app-registry-free while every registration uses the literal `new_*`
idiom the M1 scanner catalogs, TrackedLock, bounded per-slot ring,
`reset()` zeroing aggregates while registry metrics stay monotonic.

Consumers: admin `scpstats` endpoint (`to_json`, `?slot=N`,
`?action=reset`), the `health` rollup's consensus leg, the metrics
registry (`scp.*` → `sct_scp_*` in the Prometheus exposition), and the
fleet view (`fleet_json()` merged by util/fleet.py into fleet-wide
envelopes-per-slot — the `bench.py --fleet-scale` record).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional, Set

from ..history.checkpoints import checkpoint_containing, first_in_checkpoint
from ..util.metrics import MetricsRegistry
from ..util.threads import TrackedLock
from ..util.timer import real_monotonic
from ..xdr import SCPStatementType

# statement type -> short kind; the same vocabulary as the slot
# timeline's `<kind>.seen` events, so the two surfaces line up
STATEMENT_KIND = {
    SCPStatementType.SCP_ST_NOMINATE: "nominate",
    SCPStatementType.SCP_ST_PREPARE: "prepare",
    SCPStatementType.SCP_ST_CONFIRM: "confirm",
    SCPStatementType.SCP_ST_EXTERNALIZE: "externalize",
}
STATEMENT_KINDS = ("nominate", "prepare", "confirm", "externalize")

# SCPTimerID -> timer name (scp/driver.py: NOMINATION=0, BALLOT=1)
TIMER_NAMES = {0: "nomination", 1: "ballot"}

# phase -> (start stamp, end stamp) in the slot-timeline journal; the
# edges chain, so the phase durations telescope to exactly
# externalize - nominate.trigger when every stamp is present
PHASES = ("nominate", "prepare", "confirm", "externalize")
PHASE_EDGES = (
    ("nominate", "nominate.trigger", "nominate.candidate"),
    ("prepare", "nominate.candidate", "ballot.phase.confirm"),
    ("confirm", "ballot.phase.confirm", "ballot.phase.externalize"),
    ("externalize", "ballot.phase.externalize", "externalize"),
)


def _new_peer() -> dict:
    return {"lag_sum": 0.0, "lag_max": 0.0, "samples": 0,
            "latest_slot": 0}


class ScpStats:
    """Consensus-cockpit aggregation; see module docstring."""

    MAX_SLOTS = 64       # per-slot records retained (ring, like the timeline)
    MAX_PEERS = 256      # per-peer lag/latest-seen entries retained
    MAX_FIRES = 32       # timer-fire attributions retained per slot
    BEHIND_SLOTS = 2     # latest-seen lag before a validator is "behind"

    def __init__(self, metrics=None, tracer=None, now_fn=None,
                 self_id: Optional[str] = None, timeline=None) -> None:
        self._now = now_fn or real_monotonic
        # a private registry when none is injected keeps direct
        # constructions (tests, harnesses) app-registry-free while
        # letting every registration below use the new_* idiom the M1
        # metric-catalog scanner keys on
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.tracer = tracer
        self.self_id = self_id or ""
        self.timeline = timeline
        self._lock = TrackedLock("scp.scp-stats")
        self.quorum_members: Set[str] = set()
        m = self.metrics
        self._t_phase = {p: m.new_timer("scp.phase.%s" % p)
                         for p in PHASES}
        self._t_wall = m.new_timer("scp.slot.wall")
        self._h_rounds = {k: m.new_histogram("scp.rounds.%s" % k)
                          for k in ("nomination", "ballot")}
        self._m_fired = {k: m.new_meter("scp.timer.%s.fired" % k)
                         for k in TIMER_NAMES.values()}
        self._m_cancelled = {k: m.new_meter("scp.timer.%s.cancelled" % k)
                             for k in TIMER_NAMES.values()}
        self._h_sent = {k: m.new_histogram("scp.envelopes.sent.%s" % k)
                        for k in STATEMENT_KINDS}
        self._h_recv = {k: m.new_histogram("scp.envelopes.recv.%s" % k)
                        for k in STATEMENT_KINDS}
        self._t_peer_lag = m.new_timer("scp.peer.lag")
        self._g_missing = m.new_gauge("scp.quorum.missing")
        self._g_behind = m.new_gauge("scp.quorum.behind")
        self._g_slots = m.new_gauge("scp.slots.tracked")
        self._m_pruned = m.new_meter("scp.slots.pruned")
        self.reset()

    # -- lifecycle -----------------------------------------------------------
    def reset(self) -> None:
        """Zero the aggregates (admin `scpstats?action=reset`; registry
        metrics keep their monotonic histories)."""
        with self._lock:
            # slot -> per-slot record (ring bounded at MAX_SLOTS)
            self._slots: "OrderedDict[int, dict]" = OrderedDict()
            self.peers: Dict[str, dict] = {}
            self.totals = {"sent": 0, "recv": 0,
                           "timer_fired": 0, "timer_cancelled": 0,
                           "pruned": 0, "dropped_slots": 0}
            # (slot, timer_id) -> round the pending timer was armed for
            self._pending_timers: Dict[tuple, int] = {}

    def set_quorum(self, members_hex) -> None:
        """Install the local quorum-slice membership (node-id hex) the
        health tracking diagnoses against; the local node is excluded
        (it cannot be absent from itself)."""
        self.quorum_members = set(members_hex) - {self.self_id}

    # -- per-slot record -----------------------------------------------------
    def _slot_locked(self, slot: int) -> Optional[dict]:
        rec = self._slots.get(slot)
        if rec is None:
            if len(self._slots) >= self.MAX_SLOTS:
                oldest = min(self._slots)
                if slot < oldest:
                    # a straggler for an already-evicted slot must not
                    # resurrect it (same rule as the timeline ring)
                    return None
                del self._slots[oldest]
                self.totals["dropped_slots"] += 1
            rec = self._slots[slot] = {
                "rounds": {"nomination": 0, "ballot": 0},
                "timers": {k: {"armed": 0, "fired": 0, "cancelled": 0}
                           for k in TIMER_NAMES.values()},
                "fires": [],
                "sent": {}, "recv": {},
                "first_t": None,       # slot-local first peer arrival
                "peer_first": {},      # peer -> its first arrival t
                "senders": set(),      # peers heard from for this slot
                "phases": None,
                "externalized": False,
            }
            self._g_slots.set(len(self._slots))
        return rec

    # -- round hooks (scp/nomination.py, scp/ballot.py) ----------------------
    def nomination_round(self, slot: int, round_number: int,
                         timed_out: bool) -> None:
        with self._lock:
            rec = self._slot_locked(slot)
            if rec is not None:
                r = rec["rounds"]
                r["nomination"] = max(r["nomination"], round_number)

    def ballot_bumped(self, slot: int, counter: int) -> None:
        if counter >= 0xFFFFFFFF:
            # the externalize bump sets the counter to the protocol's
            # "infinity" sentinel — that is phase progress, not a round
            return
        with self._lock:
            rec = self._slot_locked(slot)
            if rec is not None:
                r = rec["rounds"]
                r["ballot"] = max(r["ballot"], counter)

    # -- timer attribution (Herder.setup_scp_timer) --------------------------
    def _round_for_locked(self, rec: dict, timer_id: int) -> int:
        key = "nomination" if timer_id == 0 else "ballot"
        return rec["rounds"][key]

    def timer_armed(self, slot: int, timer_id: int) -> None:
        name = TIMER_NAMES.get(timer_id)
        if name is None:
            return
        cancelled = False
        with self._lock:
            rec = self._slot_locked(slot)
            if rec is None:
                return
            key = (slot, timer_id)
            if key in self._pending_timers:
                # re-armed before firing: the previous schedule was
                # cancelled (nomination re-arms per round)
                rec["timers"][name]["cancelled"] += 1
                self.totals["timer_cancelled"] += 1
                cancelled = True
            self._pending_timers[key] = self._round_for_locked(
                rec, timer_id)
            rec["timers"][name]["armed"] += 1
        if cancelled:
            self._m_cancelled[name].mark()

    def timer_cancelled(self, slot: int, timer_id: int) -> None:
        """Explicit cancel (setup_timer with cb=None); a no-op unless a
        timer was actually pending — cancelling an idle slot's timer is
        not an event."""
        name = TIMER_NAMES.get(timer_id)
        if name is None:
            return
        fire = False
        with self._lock:
            if self._pending_timers.pop((slot, timer_id), None) is None:
                return
            rec = self._slots.get(slot)
            if rec is not None:
                rec["timers"][name]["cancelled"] += 1
            self.totals["timer_cancelled"] += 1
            fire = True
        if fire:
            self._m_cancelled[name].mark()

    def timer_fired(self, slot: int, timer_id: int) -> None:
        name = TIMER_NAMES.get(timer_id)
        if name is None:
            return
        with self._lock:
            rnd = self._pending_timers.pop((slot, timer_id), None)
            rec = self._slots.get(slot)
            if rec is not None:
                rec["timers"][name]["fired"] += 1
                if len(rec["fires"]) < self.MAX_FIRES:
                    rec["fires"].append({"timer": name, "round": rnd})
            self.totals["timer_fired"] += 1
        self._m_fired[name].mark()

    # -- envelope accounting (Herder.emit_envelope, Slot.process_envelope) ---
    def envelope_sent(self, slot: int, kind: str) -> None:
        with self._lock:
            rec = self._slot_locked(slot)
            if rec is None:
                return
            rec["sent"][kind] = rec["sent"].get(kind, 0) + 1
            self.totals["sent"] += 1

    def envelope_received(self, slot: int, kind: str, peer: str,
                          is_self: bool = False) -> None:
        """Every peer envelope arrival for `slot` (NOT deduped — the
        timeline keeps first-arrivals only; the cockpit counts the full
        O(n²) flood the BLS quorum-certificate work must shrink).
        `is_self` skips our own emissions echoed back through the
        processing path."""
        if is_self:
            return
        t = self._now()
        with self._lock:
            rec = self._slot_locked(slot)
            if rec is None:
                return
            rec["recv"][kind] = rec["recv"].get(kind, 0) + 1
            self.totals["recv"] += 1
            if rec["first_t"] is None or t < rec["first_t"]:
                rec["first_t"] = t
            pf = rec["peer_first"]
            if peer not in pf and len(pf) < self.MAX_PEERS:
                pf[peer] = t
            if len(rec["senders"]) < self.MAX_PEERS:
                rec["senders"].add(peer)
            p = self.peers.get(peer)
            if p is None:
                if len(self.peers) >= self.MAX_PEERS:
                    return   # bounded: beyond the cap only totals count
                p = self.peers[peer] = _new_peer()
            p["latest_slot"] = max(p["latest_slot"], slot)

    # -- phase attribution (derived from the slot-timeline stamps) -----------
    def _phase_report(self, slot: int) -> Optional[dict]:
        """Phase latencies for `slot`, read back from the SAME stamps
        the slot timeline journaled — reconciliation between the
        cockpit and the journal is by construction, not by luck. A
        missing stamp (non-validator, restored slot) nulls the phases
        it bounds; `wall_s` is the canonical slot latency
        externalize - nominate.trigger (the unified anchor)."""
        tl = self.timeline
        if tl is None:
            return None
        stamps: Dict[str, float] = {}
        for _, start, end in PHASE_EDGES:
            for name in (start, end):
                if name not in stamps:
                    ev = tl.first(slot, name)
                    if ev is not None:
                        stamps[name] = ev["t"]
        phases: Dict[str, Optional[float]] = {}
        for name, start, end in PHASE_EDGES:
            if start in stamps and end in stamps:
                phases[name] = round(
                    max(0.0, stamps[end] - stamps[start]), 6)
            else:
                phases[name] = None
        wall = None
        if "nominate.trigger" in stamps and "externalize" in stamps:
            wall = round(max(
                0.0, stamps["externalize"] - stamps["nominate.trigger"]), 6)
        return {"phase_s": phases, "wall_s": wall,
                "stamps": {k: v for k, v in sorted(stamps.items())}}

    def slot_externalized(self, slot: int) -> None:
        """The slot externalized (Herder.value_externalized, after the
        timeline's `externalize` stamp lands): derive and latch the
        phase report, feed the round/envelope histograms, and settle
        per-peer lag against the slot-local first arrival."""
        report = self._phase_report(slot)
        with self._lock:
            rec = self._slot_locked(slot)
            if rec is None:
                return
            rec["externalized"] = True
            rec["phases"] = report
            nrounds = rec["rounds"]["nomination"]
            brounds = rec["rounds"]["ballot"]
            sent = dict(rec["sent"])
            recv = dict(rec["recv"])
            first = rec["first_t"]
            lags = {}
            if first is not None:
                for peer, t in rec["peer_first"].items():
                    lag = max(0.0, t - first)
                    lags[peer] = lag
                    p = self.peers.get(peer)
                    if p is not None:
                        p["lag_sum"] += lag
                        p["lag_max"] = max(p["lag_max"], lag)
                        p["samples"] += 1
        if report is not None:
            for name, v in report["phase_s"].items():
                if v is not None:
                    self._t_phase[name].update(v)
            if report["wall_s"] is not None:
                self._t_wall.update(report["wall_s"])
        self._h_rounds["nomination"].update(nrounds)
        self._h_rounds["ballot"].update(brounds)
        for k, n in sent.items():
            if k in self._h_sent:
                self._h_sent[k].update(n)
        for k, n in recv.items():
            if k in self._h_recv:
                self._h_recv[k].update(n)
        for lag in lags.values():
            self._t_peer_lag.update(lag)

    # -- quorum health -------------------------------------------------------
    def quorum_health(self, current_slot: int) -> dict:
        """Validators missing entirely (never heard from) or behind by
        latest-seen slot — the `health` rollup's quorum-gap signal."""
        with self._lock:
            missing = sorted(m for m in self.quorum_members
                             if m not in self.peers)
            behind = sorted(
                m for m in self.quorum_members
                if m in self.peers and
                self.peers[m]["latest_slot"] <
                current_slot - self.BEHIND_SLOTS)
        self._g_missing.set(len(missing))
        self._g_behind.set(len(behind))
        return {"members": len(self.quorum_members),
                "missing": missing, "behind": behind}

    def stuck_slots(self, current_slot: int,
                    include_open: bool = False) -> list:
        """Non-externalized slots the chain has moved past, each
        diagnosing WHICH quorum-slice members are absent — the names an
        operator chases when consensus stalls. `include_open` also
        inspects the current in-flight slot (pass it when the node has
        LOST sync — a healthy mid-nomination slot is not stuck)."""
        limit = current_slot if include_open else current_slot - 1
        out = []
        with self._lock:
            for slot in sorted(self._slots):
                rec = self._slots[slot]
                if rec["externalized"] or slot > limit:
                    continue
                absent = sorted(self.quorum_members - rec["senders"])
                out.append({"slot": slot, "absent": absent,
                            "heard_from": len(rec["senders"])})
        return out

    def health(self, current_slot: int,
               ballot_inflation_threshold: int = 3,
               include_open: bool = False) -> dict:
        """The consensus leg of the admin `health` rollup: stuck slots
        (with absent-member diagnosis), quorum gaps, and ballot-round
        inflation over the retained ring. `include_open` extends the
        stuck-slot sweep to the in-flight slot (set when out of sync)."""
        stuck = self.stuck_slots(current_slot, include_open=include_open)
        quorum = self.quorum_health(current_slot)
        with self._lock:
            worst_ballot = max(
                (rec["rounds"]["ballot"] for rec in self._slots.values()),
                default=0)
        return {
            "stuck_slots": stuck,
            "quorum": quorum,
            "ballot_rounds_worst": worst_ballot,
            "ballot_inflated": worst_ballot >= ballot_inflation_threshold,
        }

    # -- pruning (ledger_closed hook) ----------------------------------------
    def slot_closed(self, ledger_seq: int) -> None:
        """Prune per-slot records from before the current checkpoint's
        first slot (history/checkpoints.py) — the same explicit memory
        bound every cockpit ring observes."""
        cutoff = first_in_checkpoint(checkpoint_containing(ledger_seq))
        pruned = 0
        with self._lock:
            for s in [s for s in self._slots if s < cutoff]:
                del self._slots[s]
                pruned += 1
            for key in [k for k in self._pending_timers if k[0] < cutoff]:
                del self._pending_timers[key]
            self.totals["pruned"] += pruned
            self._g_slots.set(len(self._slots))
        if pruned:
            self._m_pruned.mark(pruned)

    # -- exports -------------------------------------------------------------
    def _slot_json_locked(self, slot: int, rec: dict) -> dict:
        return {
            "slot": slot,
            "externalized": rec["externalized"],
            "rounds": dict(rec["rounds"]),
            "timers": {k: dict(v) for k, v in rec["timers"].items()},
            "fires": [dict(f) for f in rec["fires"]],
            "envelopes": {"sent": dict(rec["sent"]),
                          "recv": dict(rec["recv"])},
            "heard_from": len(rec["senders"]),
            "phases": rec["phases"],
        }

    def slot_report(self, slot: int) -> Optional[dict]:
        """One slot's full attribution (admin `scpstats?slot=N`)."""
        with self._lock:
            rec = self._slots.get(slot)
            if rec is None:
                return None
            return self._slot_json_locked(slot, rec)

    def _peers_json_locked(self) -> dict:
        out = {}
        for pid, p in self.peers.items():
            n = p["samples"]
            out[pid] = {
                "latest_slot": p["latest_slot"],
                "lag_mean_ms": round(p["lag_sum"] / n * 1e3, 3) if n
                else None,
                "lag_max_ms": round(p["lag_max"] * 1e3, 3),
                "samples": n,
            }
        return out

    def to_json(self) -> dict:
        """The admin `scpstats` cockpit blob."""
        with self._lock:
            slots = {str(s): self._slot_json_locked(s, rec)
                     for s, rec in sorted(self._slots.items())}
            ext = [s for s, rec in self._slots.items()
                   if rec["externalized"]]
            last_ext = max(ext) if ext else None
            out = {
                "totals": dict(self.totals),
                "slots_tracked": len(self._slots),
                "last_externalized": last_ext,
                "slots": slots,
                "peers": self._peers_json_locked(),
            }
        wall = self._t_wall.snapshot()
        out["slot_wall_ms"] = {
            "count": wall["count"],
            "p50": round(wall["median"] * 1e3, 3),
            "p95": round(wall["p95"] * 1e3, 3),
        }
        out["phase_p95_ms"] = {
            p: round(self._t_phase[p].snapshot()["p95"] * 1e3, 3)
            for p in PHASES}
        return out

    def fleet_json(self) -> dict:
        """Compact per-node export the FleetAggregator merges into the
        fleet-wide envelopes-per-slot baseline (one shape for in-process
        `add_app` and HTTP `add_http` intake)."""
        with self._lock:
            return {
                "self": self.self_id,
                "totals": dict(self.totals),
                "slots": {str(s): {
                    "externalized": rec["externalized"],
                    "rounds": dict(rec["rounds"]),
                    "sent": dict(rec["sent"]),
                    "recv": dict(rec["recv"]),
                    "phases": rec["phases"],
                } for s, rec in sorted(self._slots.items())},
            }
