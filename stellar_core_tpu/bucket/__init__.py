"""Bucket layer: content-addressed LSM of canonical ledger entries.

Role parity: reference `src/bucket` (BucketList.h:14)."""

from .bucket import (
    Bucket, bucket_entry_sort_key, merge_buckets,
    FIRST_PROTOCOL_SHADOWS_REMOVED,
    FIRST_PROTOCOL_SUPPORTING_INITENTRY_AND_METAENTRY,
)
from .bucket_list import (
    BucketLevel, BucketList, FutureBucket, K_NUM_LEVELS, keep_dead_entries,
    level_half, level_should_spill, level_size, mask, oldest_ledger_in_curr,
    oldest_ledger_in_snap, size_of_curr, size_of_snap,
)
from .bucket_manager import BucketManager
from .applicator import BucketApplicator, apply_buckets
from .bucket_index import (
    BloomFilter, BucketDB, BucketDbStats, BucketIndex, IndexLoadError,
    sidecar_path,
)

__all__ = [
    "BloomFilter", "Bucket", "BucketApplicator", "BucketDB",
    "BucketDbStats", "BucketIndex", "BucketLevel", "BucketList",
    "BucketManager", "FutureBucket", "IndexLoadError", "K_NUM_LEVELS",
    "apply_buckets", "sidecar_path",
    "bucket_entry_sort_key", "keep_dead_entries", "level_half",
    "level_should_spill", "level_size", "mask", "merge_buckets",
    "oldest_ledger_in_curr", "oldest_ledger_in_snap", "size_of_curr",
    "size_of_snap",
    "FIRST_PROTOCOL_SHADOWS_REMOVED",
    "FIRST_PROTOCOL_SUPPORTING_INITENTRY_AND_METAENTRY",
]
