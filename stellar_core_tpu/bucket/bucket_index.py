"""BucketDB: bloom-filtered, bucket-backed point reads over the bucket
list (ISSUE 14 tentpole; ROADMAP item 4).

Role parity: stellar-core's BucketListDB direction (src/bucket/
BucketIndex.{h,cpp} + SearchableBucketListSnapshot) — serve apply-path
state reads from the immutable bucket files themselves and demote SQL to
a write-behind query index. Three layers:

- `BloomFilter`: per-bucket k-hash bloom over the bucket's LedgerKey
  XDR bytes, so a point read touches only the O(levels) buckets that
  MIGHT hold the key. Key fingerprints are one SHA-256 per lookup
  (process-stable — filters are persisted), double-hashed into k probes.
- `BucketIndex`: per-bucket sorted key index — for every payload entry,
  its canonical LedgerKey bytes plus (ordinal, file offset, length) of
  the LedgerEntry XDR inside the bucket file (DEAD tombstones carry
  length 0). Built at bucket write/merge time (adopt), memoized by the
  immutable bucket hash, persisted as a checksummed sidecar
  (`bucket-<hex>.xdr.idx`) beside the bucket file and rebuilt on any
  checksum/shape mismatch — a corrupt sidecar can degrade startup time,
  never correctness.
- `BucketDB`: the read facade. `lookup(kb)` walks the live bucket list
  newest-level-first (level 0 curr, level 0 snap, level 1 curr, ...)
  — bloom check, then index bisect, DEADENTRY short-circuits to
  "authoritatively absent". `prefetch_batch(kbs)` resolves a whole
  txset's touched keys in ONE pass per level (the txset_prefetch_keys
  bulk-warm seam from PR 8), feeding the native engine its entry blobs
  directly through the warmed root cache. Blob bytes come from the
  bucket FILE via pread when the bucket is disk-backed (offsets are
  exercised for real, `bucketdb.bytes-read` is honest) and from the
  in-memory entry records otherwise.

`BucketDbStats` is the fifth cockpit in the ApplyStats/VerifierStats
pattern (docs/observability.md#bucketdb-cockpit): one aggregation,
private-registry default so `new_*` literals stay M1-scannable, admin
`bucketdb[?action=reset]` endpoint, `sct_bucketdb_*` Prometheus series.

Fault sites (util.faults, docs/robustness.md): `bucketdb.index-corrupt`
treats a sidecar load as corrupt (exercises the rebuild path);
`bucketdb.read-fail` makes a read non-authoritative, degrading that
lookup to the SQL fallback in LedgerTxnRoot.

Threading: index builds run wherever buckets are adopted — the close
path (level-0 fresh buckets) and the bucket-merge worker pool — so the
memo and stats are lock-guarded; file reads use os.pread on cached fds
(no shared seek pointer).
"""

from __future__ import annotations

import hashlib
import os
import struct
from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

from ..util.faults import check_faults
from ..util.log import get_logger
from ..util.metrics import MetricsRegistry
from ..util.threads import TrackedLock
from ..util.timer import real_monotonic
from ..xdr import BucketEntryType, ledger_entry_key

log = get_logger("Bucket")

_DEAD = BucketEntryType.DEADENTRY
_META = BucketEntryType.METAENTRY

# sidecar format: MAGIC | bucket hash | payload | SHA256(everything before)
_IDX_MAGIC = b"SCTIDX01"
_IDX_HEAD = struct.Struct("<IQB")      # n_keys, bloom bits, bloom k
_IDX_ROW = struct.Struct("<HIQI")      # key len, ordinal, offset, length

# a DEAD tombstone has no LedgerEntry payload; its row length is 0
_TOMBSTONE_LEN = 0


class IndexLoadError(Exception):
    """Sidecar missing/truncated/corrupt/mismatched — rebuild, don't
    trust (callers warn once and rebuild from the bucket itself)."""


def key_fingerprint(kb: bytes) -> Tuple[int, int]:
    """(h1, h2) bloom fingerprint of one LedgerKey XDR — computed ONCE
    per lookup and reused across every level's filter (double hashing:
    probe i is (h1 + i*h2) mod nbits). SHA-256 so persisted filters are
    stable across processes and PYTHONHASHSEED."""
    d = hashlib.sha256(kb).digest()
    return (int.from_bytes(d[:8], "little"),
            int.from_bytes(d[8:16], "little") | 1)


class BloomFilter:
    """Fixed-size k-hash bloom over key fingerprints."""

    __slots__ = ("nbits", "k", "bits", "_density")

    def __init__(self, nbits: int, k: int,
                 bits: Optional[bytearray] = None) -> None:
        assert nbits % 8 == 0 and nbits > 0 and k > 0
        self.nbits = nbits
        self.k = k
        self.bits = bits if bits is not None else bytearray(nbits // 8)
        self._density: Optional[float] = None

    @classmethod
    def for_capacity(cls, n: int, bits_per_key: int = 10) -> "BloomFilter":
        nbits = max(64, n * bits_per_key)
        nbits = (nbits + 7) & ~7
        # k = ln(2) * bits/key is the optimal probe count
        k = max(1, round(0.693 * bits_per_key))
        return cls(nbits, k)

    def add(self, fp: Tuple[int, int]) -> None:
        h1, h2 = fp
        bits, nbits = self.bits, self.nbits
        for i in range(self.k):
            b = (h1 + i * h2) % nbits
            bits[b >> 3] |= 1 << (b & 7)
        self._density = None

    def might_contain(self, fp: Tuple[int, int]) -> bool:
        h1, h2 = fp
        bits, nbits = self.bits, self.nbits
        for i in range(self.k):
            b = (h1 + i * h2) % nbits
            if not bits[b >> 3] & (1 << (b & 7)):
                return False
        return True

    def bit_density(self) -> float:
        """Fraction of set bits — the saturation signal the cockpit
        exposes (≈0.5 at design load for the optimal k). Memoized after
        the first call: filters are only mutated while their index is
        being built, and a million-key filter's popcount is ~1.25 MB of
        work that must never recur per close (the shape gauges refresh
        on every adopted bucket)."""
        if self._density is None:
            ones = bin(int.from_bytes(bytes(self.bits),
                                      "little")).count("1")
            self._density = ones / self.nbits
        return self._density


class BucketIndex:
    """Sorted (key -> ordinal/offset/length) map for one immutable
    bucket, plus its bloom filter. `ordinal` indexes the bucket's FULL
    entry tuple (META included) for the in-memory read path; `offset`/
    `length` locate the LedgerEntry XDR inside the on-disk framed
    stream for the pread path. length 0 marks a DEADENTRY."""

    __slots__ = ("bucket_hash", "keys", "ordinals", "offsets", "lengths",
                 "bloom")

    def __init__(self, bucket_hash: bytes, keys: List[bytes],
                 ordinals: List[int], offsets: List[int],
                 lengths: List[int], bloom: BloomFilter) -> None:
        self.bucket_hash = bucket_hash
        self.keys = keys
        self.ordinals = ordinals
        self.offsets = offsets
        self.lengths = lengths
        self.bloom = bloom

    def __len__(self) -> int:
        return len(self.keys)

    @classmethod
    def build(cls, bucket, bits_per_key: int = 10) -> "BucketIndex":
        """Index one bucket from its in-memory entries, computing each
        record's position in the on-disk framed stream (the exact bytes
        write_to/entry_record produce — 4-byte mark, 4-byte union disc,
        then the LedgerEntry/LedgerKey XDR)."""
        from .bucket import entry_record
        rows: List[Tuple[bytes, int, int, int]] = []
        off = 0
        for ordinal, e in enumerate(bucket.entries):
            rec_len = len(entry_record(e))
            t = e.disc
            if t == _META:
                off += rec_len
                continue
            if t == _DEAD:
                kb = e.value.to_xdr()
                rows.append((kb, ordinal, off + 8, _TOMBSTONE_LEN))
            else:
                kb = ledger_entry_key(e.value).to_xdr()
                rows.append((kb, ordinal, off + 8, rec_len - 8))
            off += rec_len
        rows.sort(key=lambda r: r[0])
        bloom = BloomFilter.for_capacity(len(rows), bits_per_key)
        keys: List[bytes] = []
        ordinals: List[int] = []
        offsets: List[int] = []
        lengths: List[int] = []
        for kb, ordinal, o, ln in rows:
            keys.append(kb)
            ordinals.append(ordinal)
            offsets.append(o)
            lengths.append(ln)
            bloom.add(key_fingerprint(kb))
        return cls(bucket.get_hash(), keys, ordinals, offsets, lengths,
                   bloom)

    def lookup(self, kb: bytes) -> Optional[Tuple[int, int, int]]:
        """(ordinal, offset, length) of the entry for `kb`, or None."""
        i = bisect_left(self.keys, kb)
        if i < len(self.keys) and self.keys[i] == kb:
            return (self.ordinals[i], self.offsets[i], self.lengths[i])
        return None

    # -- sidecar persistence --------------------------------------------------
    def to_bytes(self) -> bytes:
        parts = [_IDX_MAGIC, self.bucket_hash,
                 _IDX_HEAD.pack(len(self.keys), self.bloom.nbits,
                                self.bloom.k),
                 bytes(self.bloom.bits)]
        pack = _IDX_ROW.pack
        for kb, ordinal, off, ln in zip(self.keys, self.ordinals,
                                        self.offsets, self.lengths):
            parts.append(pack(len(kb), ordinal, off, ln))
            parts.append(kb)
        body = b"".join(parts)
        return body + hashlib.sha256(body).digest()

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(self.to_bytes())
        os.replace(tmp, path)

    @classmethod
    def from_bytes(cls, raw: bytes,
                   expected_hash: Optional[bytes] = None) -> "BucketIndex":
        if len(raw) < len(_IDX_MAGIC) + 32 + _IDX_HEAD.size + 32:
            raise IndexLoadError("sidecar truncated (%d bytes)" % len(raw))
        body, csum = raw[:-32], raw[-32:]
        if hashlib.sha256(body).digest() != csum:
            raise IndexLoadError("sidecar checksum mismatch")
        if not raw.startswith(_IDX_MAGIC):
            raise IndexLoadError("bad sidecar magic")
        p = len(_IDX_MAGIC)
        bucket_hash = body[p:p + 32]
        p += 32
        if expected_hash is not None and bucket_hash != expected_hash:
            raise IndexLoadError(
                "sidecar indexes bucket %s, expected %s"
                % (bucket_hash.hex()[:8], expected_hash.hex()[:8]))
        n, nbits, k = _IDX_HEAD.unpack_from(body, p)
        p += _IDX_HEAD.size
        nbytes = nbits // 8
        if p + nbytes > len(body):
            raise IndexLoadError("sidecar bloom truncated")
        bloom = BloomFilter(nbits, k, bytearray(body[p:p + nbytes]))
        p += nbytes
        keys: List[bytes] = []
        ordinals: List[int] = []
        offsets: List[int] = []
        lengths: List[int] = []
        unpack = _IDX_ROW.unpack_from
        row = _IDX_ROW.size
        for _ in range(n):
            if p + row > len(body):
                raise IndexLoadError("sidecar row table truncated")
            klen, ordinal, off, ln = unpack(body, p)
            p += row
            if p + klen > len(body):
                raise IndexLoadError("sidecar key bytes truncated")
            keys.append(body[p:p + klen])
            p += klen
            ordinals.append(ordinal)
            offsets.append(off)
            lengths.append(ln)
        if p != len(body):
            raise IndexLoadError("sidecar trailing garbage")
        return cls(bucket_hash, keys, ordinals, offsets, lengths, bloom)

    @classmethod
    def load(cls, path: str,
             expected_hash: Optional[bytes] = None) -> "BucketIndex":
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError as e:
            raise IndexLoadError("sidecar unreadable: %s" % e)
        return cls.from_bytes(raw, expected_hash)


def sidecar_path(bucket_path: str) -> str:
    return bucket_path + ".idx"


class BucketDbStats:
    """BucketDB cockpit aggregation (the fifth cockpit; see module
    docstring). Private registry when none is injected so the `new_*`
    literals stay M1-scannable in direct constructions."""

    def __init__(self, metrics=None, tracer=None, now_fn=None) -> None:
        self._now = now_fn or real_monotonic
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.tracer = tracer
        self._lock = TrackedLock("bucketdb-stats")
        m = self.metrics
        self._m_reads = m.new_meter("bucketdb.reads")
        self._m_hit = m.new_meter("bucketdb.read.hit")
        self._m_miss = m.new_meter("bucketdb.read.miss")
        self._m_tomb = m.new_meter("bucketdb.read.tombstone")
        self._m_bloom_skip = m.new_meter("bucketdb.bloom.skips")
        self._m_bytes = m.new_meter("bucketdb.bytes-read")
        self._m_builds = m.new_meter("bucketdb.index.builds")
        self._m_loads = m.new_meter("bucketdb.index.loads")
        self._m_loadfail = m.new_meter("bucketdb.index.load-failures")
        self._m_sql_fallback = m.new_meter("bucketdb.fallback.sql")
        self._h_build = m.new_histogram("bucketdb.index.build.seconds")
        self._h_load = m.new_histogram("bucketdb.index.load.seconds")
        self._h_batch = m.new_histogram("bucketdb.prefetch.batch-keys")
        self._g_indexes = m.new_gauge("bucketdb.indexes")
        self._g_entries = m.new_gauge("bucketdb.index.entries")
        self._g_density = m.new_gauge("bucketdb.bloom.bit-density-pct")
        # per-level probe attribution, memoized (bounded: K_NUM_LEVELS
        # levels x {curr,snap} share one level number)
        self._m_level: Dict[Tuple[int, str], object] = {}
        self.reset()

    def reset(self) -> None:
        """Zero the cumulative aggregates (admin `bucketdb?action=reset`;
        registry metrics keep their monotonic histories)."""
        with self._lock:
            self.reads = {"total": 0, "hits": 0, "misses": 0,
                          "tombstones": 0}
            self.levels: Dict[int, dict] = {}
            self.bloom = {"checks": 0, "skips": 0}
            self.index = {"builds": 0, "loads": 0, "load_failures": 0,
                          "build_seconds": 0.0, "load_seconds": 0.0}
            self.prefetch = {"batches": 0, "keys": 0, "resolved": 0}
            self.bytes_read = 0
            self.sql_fallbacks = 0

    def _level_meter(self, level: int, kind: str):
        key = (level, kind)
        mtr = self._m_level.get(key)
        if mtr is None:
            mtr = self.metrics.new_meter(
                "bucketdb.level.%d.%s" % (level, kind))
            self._m_level[key] = mtr
        return mtr

    def record_read(self, outcome: str, levels_probed,
                    bytes_read: int = 0) -> None:
        """One point read: outcome in hit|miss|tombstone, `levels_probed`
        is [(level, probe_outcome)] with probe_outcome in
        bloom-skip|hit|false-positive — folded into one lock
        acquisition (this hook sits inside the path it measures)."""
        self._m_reads.mark()
        if outcome == "hit":
            self._m_hit.mark()
        elif outcome == "tombstone":
            self._m_tomb.mark()
        else:
            self._m_miss.mark()
        if bytes_read:
            self._m_bytes.mark(bytes_read)
        for level, po in levels_probed:
            if po == "bloom-skip":
                self._m_bloom_skip.mark()
            else:
                self._level_meter(
                    level, "hits" if po == "hit" else "false-positives"
                ).mark()
            self._level_meter(level, "probes").mark()
        with self._lock:
            r = self.reads
            r["total"] += 1
            r["hits" if outcome == "hit" else
              "tombstones" if outcome == "tombstone" else "misses"] += 1
            self.bytes_read += bytes_read
            for level, po in levels_probed:
                lv = self.levels.setdefault(
                    level, {"probes": 0, "hits": 0, "false_positives": 0,
                            "bloom_skips": 0})
                lv["probes"] += 1
                if po == "bloom-skip":
                    lv["bloom_skips"] += 1
                    self.bloom["skips"] += 1
                elif po == "hit":
                    lv["hits"] += 1
                else:
                    lv["false_positives"] += 1
                self.bloom["checks"] += 1

    def record_build(self, seconds: float) -> None:
        self._m_builds.mark()
        self._h_build.update(seconds)
        with self._lock:
            self.index["builds"] += 1
            self.index["build_seconds"] += seconds

    def record_load(self, seconds: float) -> None:
        self._m_loads.mark()
        self._h_load.update(seconds)
        with self._lock:
            self.index["loads"] += 1
            self.index["load_seconds"] += seconds

    def record_load_failure(self) -> None:
        self._m_loadfail.mark()
        with self._lock:
            self.index["load_failures"] += 1

    def record_prefetch_batch(self, keys: int, resolved: int,
                              level_probes=(),
                              bytes_read: int = 0) -> None:
        """One batched prefetch pass; `level_probes` is
        [(level, bloom_skips, hits, false_positives)] aggregated over
        the pass, so batched reads feed the same per-level probe
        attribution (and the false-positive rate) as point lookups."""
        self._h_batch.update(keys)
        if bytes_read:
            self._m_bytes.mark(bytes_read)
        for level, skips, hits, fps in level_probes:
            if skips:
                self._m_bloom_skip.mark(skips)
            if hits:
                self._level_meter(level, "hits").mark(hits)
            if fps:
                self._level_meter(level, "false-positives").mark(fps)
            self._level_meter(level, "probes").mark(skips + hits + fps)
        with self._lock:
            self.prefetch["batches"] += 1
            self.prefetch["keys"] += keys
            self.prefetch["resolved"] += resolved
            self.bytes_read += bytes_read
            for level, skips, hits, fps in level_probes:
                lv = self.levels.setdefault(
                    level, {"probes": 0, "hits": 0, "false_positives": 0,
                            "bloom_skips": 0})
                lv["probes"] += skips + hits + fps
                lv["bloom_skips"] += skips
                lv["hits"] += hits
                lv["false_positives"] += fps
                self.bloom["checks"] += skips + hits + fps
                self.bloom["skips"] += skips

    def record_sql_fallback(self) -> None:
        self._m_sql_fallback.mark()
        with self._lock:
            self.sql_fallbacks += 1

    def set_index_shape(self, n_indexes: int, n_entries: int,
                        density_pct: float) -> None:
        self._g_indexes.set(n_indexes)
        self._g_entries.set(n_entries)
        self._g_density.set(round(density_pct, 3))

    def false_positive_rate(self) -> float:
        """False positives over bloom-passed probes (the filters' lie
        rate — ≈1% at 10 bits/key)."""
        with self._lock:
            fp = sum(lv["false_positives"] for lv in self.levels.values())
            passed = fp + sum(lv["hits"] for lv in self.levels.values())
        return fp / passed if passed else 0.0

    def to_json(self) -> dict:
        with self._lock:
            return {
                "reads": dict(self.reads),
                "levels": {str(k): dict(v)
                           for k, v in sorted(self.levels.items())},
                "bloom": dict(self.bloom),
                "index": {k: (round(v, 6) if isinstance(v, float) else v)
                          for k, v in self.index.items()},
                "prefetch": dict(self.prefetch),
                "bytes_read": self.bytes_read,
                "sql_fallbacks": self.sql_fallbacks,
            }


class BucketDB:
    """The bucket-backed read facade over one BucketManager's live
    bucket list; see module docstring. `lookup`/`prefetch_batch` return
    authoritative answers (found blob, or None = authoritatively
    absent) unless degraded by `bucketdb.read-fail`, in which case the
    caller (LedgerTxnRoot) falls back to SQL."""

    def __init__(self, manager, stats: Optional[BucketDbStats] = None,
                 faults=None, bits_per_key: int = 10,
                 eager_index: bool = True) -> None:
        self._manager = manager
        self.stats = stats if stats is not None else BucketDbStats()
        self.faults = faults
        self.bits_per_key = bits_per_key
        # eager_index=False (BUCKETDB_READS pinned off) skips indexing
        # at adopt time — nothing would ever read the indexes, and a
        # later direct lookup still builds lazily via index_for
        self.eager_index = eager_index
        self._lock = TrackedLock("bucketdb-indexes")
        self._indexes: Dict[bytes, BucketIndex] = {}
        self._fds: Dict[bytes, int] = {}
        # warn once per process on sidecar rebuilds, not once per bucket
        # (a corrupt bucket dir would otherwise spam the log at startup)
        self._warned_rebuild = False

    # -- index lifecycle -----------------------------------------------------
    def on_adopt(self, bucket) -> None:
        """Index an adopted bucket (close path for level-0 fresh
        buckets, merge workers for level merges): load the persisted
        sidecar if one matches, else build and persist."""
        if bucket.is_empty() or not self.eager_index:
            return
        self.index_for(bucket)

    def index_for(self, bucket) -> BucketIndex:
        h = bucket.get_hash()
        with self._lock:
            idx = self._indexes.get(h)
        if idx is not None:
            return idx
        idx = self._load_or_build(bucket)
        with self._lock:
            # first build wins on a race; both results are identical
            # (content-addressed input)
            existing = self._indexes.setdefault(h, idx)
        self._refresh_shape_gauges()
        return existing

    def _load_or_build(self, bucket) -> BucketIndex:
        h = bucket.get_hash()
        side = sidecar_path(bucket.path) if bucket.path else None
        if side is not None and os.path.exists(side):
            t0 = real_monotonic()
            try:
                if check_faults(self, "bucketdb.index-corrupt"):
                    raise IndexLoadError("injected index corruption")
                idx = BucketIndex.load(side, expected_hash=h)
                self.stats.record_load(real_monotonic() - t0)
                return idx
            except IndexLoadError as e:
                self.stats.record_load_failure()
                if not self._warned_rebuild:
                    self._warned_rebuild = True
                    log.warning("bucket index sidecar %s invalid (%s) — "
                                "rebuilding (further rebuilds logged at "
                                "debug)", side, e)
                else:
                    log.debug("bucket index sidecar %s invalid (%s) — "
                              "rebuilding", side, e)
        if not bucket.entries:
            # nonzero hash + no resident entries + no loadable sidecar:
            # building would produce an EMPTY index that silently
            # answers "absent" for every key in the bucket
            raise RuntimeError(
                "bucket %s has no resident entries and no valid sidecar "
                "to index from" % h.hex()[:8])
        t0 = real_monotonic()
        idx = BucketIndex.build(bucket, self.bits_per_key)
        self.stats.record_build(real_monotonic() - t0)
        if side is not None:
            try:
                idx.save(side)
            except OSError as e:
                log.warning("could not persist bucket index %s: %s",
                            side, e)
        return idx

    def invalidate(self, bucket_hash: bytes,
                   bucket_path: Optional[str] = None) -> None:
        """Drop a bucket's index + cached fd + sidecar — the GC hook
        (BucketManager.forget_unreferenced_buckets) and the
        replaced-after-catchup path."""
        with self._lock:
            self._indexes.pop(bucket_hash, None)
            fd = self._fds.pop(bucket_hash, None)
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
        if bucket_path:
            side = sidecar_path(bucket_path)
            try:
                os.remove(side)
            except OSError:
                pass
        self._refresh_shape_gauges()

    def close(self) -> None:
        with self._lock:
            fds = list(self._fds.values())
            self._fds.clear()
            self._indexes.clear()
        for fd in fds:
            try:
                os.close(fd)
            except OSError:
                pass

    def _refresh_shape_gauges(self) -> None:
        with self._lock:
            idxs = list(self._indexes.values())
        n_entries = sum(len(i) for i in idxs)
        dens = [i.bloom.bit_density() for i in idxs if len(i)]
        avg = 100.0 * sum(dens) / len(dens) if dens else 0.0
        self.stats.set_index_shape(len(idxs), n_entries, avg)

    # -- reads ---------------------------------------------------------------
    def _live_buckets(self):
        """The live list newest-first: level 0 curr, level 0 snap,
        level 1 curr, ... (in-flight merges' INPUTS are exactly these
        curr/snap buckets, so the walk is complete). Liveness is by
        nonzero HASH, not entry presence: a file-backed bucket whose
        entries are not resident (the million-account bench generator
        installs those) still serves reads via its index + pread."""
        zero = b"\x00" * 32
        for lev in self._manager.bucket_list.levels:
            if lev.curr.get_hash() != zero:
                yield lev.level, lev.curr
            if lev.snap.get_hash() != zero:
                yield lev.level, lev.snap

    def _read_blob(self, bucket, ordinal: int, offset: int,
                   length: int) -> Tuple[bytes, int]:
        """(LedgerEntry XDR, file bytes read). Disk-backed buckets pread
        from a cached fd — the offsets the sidecar committed to are
        exercised on every read; memory-only buckets slice the memoized
        framed record."""
        if bucket.path:
            fd = self._fd_for(bucket)
            if fd is not None:
                blob = os.pread(fd, length, offset)
                if len(blob) == length:
                    return blob, length
                log.warning("short bucket read %s@%d: %d < %d — falling "
                            "back to in-memory entries",
                            bucket.path, offset, len(blob), length)
        if not bucket.entries:
            # a file-backed bucket without resident entries has no
            # fallback — fail loudly rather than serve a wrong answer
            raise RuntimeError(
                "bucket %s unreadable at %d+%d and not memory-resident"
                % (bucket.get_hash().hex()[:8], offset, length))
        from .bucket import entry_record
        return entry_record(bucket.entries[ordinal])[8:], 0

    def _fd_for(self, bucket) -> Optional[int]:
        h = bucket.get_hash()
        with self._lock:
            fd = self._fds.get(h)
        if fd is not None:
            return fd
        try:
            fd = os.open(bucket.path, os.O_RDONLY)
        except OSError as e:
            log.warning("cannot open bucket file %s: %s", bucket.path, e)
            return None
        with self._lock:
            other = self._fds.setdefault(h, fd)
        if other is not fd and other != fd:
            os.close(fd)
            return other
        return fd

    def lookup(self, kb: bytes) -> Tuple[bool, Optional[bytes]]:
        """(served, blob): served=False degrades this read to the SQL
        fallback (`bucketdb.read-fail`); served=True answers
        authoritatively — blob None means absent (clean miss on every
        level, or a DEADENTRY tombstone short-circuit)."""
        if check_faults(self, "bucketdb.read-fail"):
            self.stats.record_sql_fallback()
            return False, None
        fp = key_fingerprint(kb)
        probes: List[Tuple[int, str]] = []
        for level, bucket in self._live_buckets():
            idx = self.index_for(bucket)
            if not idx.bloom.might_contain(fp):
                probes.append((level, "bloom-skip"))
                continue
            pos = idx.lookup(kb)
            if pos is None:
                probes.append((level, "false-positive"))
                continue
            ordinal, offset, length = pos
            probes.append((level, "hit"))
            if length == _TOMBSTONE_LEN:
                self.stats.record_read("tombstone", probes)
                return True, None
            blob, file_bytes = self._read_blob(bucket, ordinal, offset,
                                               length)
            self.stats.record_read("hit", probes, file_bytes)
            return True, blob
        self.stats.record_read("miss", probes)
        return True, None

    def prefetch_batch(self, kbs) -> Tuple[bool, Dict[bytes,
                                                      Optional[bytes]]]:
        """Resolve a whole txset's touched keys in ONE pass per level
        (newest-first): each level's bloom filters the still-pending
        keys, survivors bisect the level's indexes, hits and tombstones
        drop out of the pending set. Returns (served, {kb: blob|None});
        served=False degrades the whole batch to per-key SQL loads."""
        if check_faults(self, "bucketdb.read-fail"):
            self.stats.record_sql_fallback()
            return False, {}
        pending: Dict[bytes, Tuple[int, int]] = {
            kb: key_fingerprint(kb) for kb in kbs}
        out: Dict[bytes, Optional[bytes]] = {}
        requested = len(pending)
        resolved = 0
        file_bytes = 0
        level_probes: List[Tuple[int, int, int, int]] = []
        for level, bucket in self._live_buckets():
            if not pending:
                break
            idx = self.index_for(bucket)
            bloom = idx.bloom
            skips = hits = fps = 0
            for kb in list(pending):
                fp = pending[kb]
                if not bloom.might_contain(fp):
                    skips += 1
                    continue
                pos = idx.lookup(kb)
                if pos is None:
                    fps += 1
                    continue
                hits += 1
                ordinal, offset, length = pos
                if length == _TOMBSTONE_LEN:
                    out[kb] = None
                else:
                    blob, fb = self._read_blob(bucket, ordinal, offset,
                                               length)
                    out[kb] = blob
                    file_bytes += fb
                resolved += 1
                del pending[kb]
            level_probes.append((level, skips, hits, fps))
        for kb in pending:
            out[kb] = None     # clean miss on every level: absent
        self.stats.record_prefetch_batch(requested, resolved,
                                         level_probes, file_bytes)
        return True, out

    # -- exports -------------------------------------------------------------
    def to_json(self) -> dict:
        with self._lock:
            idxs = {h: i for h, i in self._indexes.items()}
        per_index = [
            {"bucket": h.hex()[:16], "entries": len(i),
             "bloom_bits": i.bloom.nbits, "bloom_k": i.bloom.k,
             "bloom_density_pct": round(100.0 * i.bloom.bit_density(), 3)}
            for h, i in sorted(idxs.items())]
        return {
            "indexes": len(idxs),
            "indexed_entries": sum(len(i) for i in idxs.values()),
            "bits_per_key": self.bits_per_key,
            "false_positive_rate": round(
                self.stats.false_positive_rate(), 6),
            "per_index": per_index[:32],
            **self.stats.to_json(),
        }
