"""BucketManager: content-addressed bucket store + the node's BucketList.

Role parity: reference `src/bucket/BucketManager{,Impl}.{h,cpp}` — owns the
bucket directory (files named bucket-<hex>.xdr), dedups adopted buckets by
hash, tracks referenced hashes for GC (forgetUnreferencedBuckets), and runs
level merges on a shared worker pool (reference worker threads;
ThreadPoolExecutor here).
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

from ..util.log import get_logger
from ..util.threads import main_thread_only
from ..xdr import LedgerEntry, LedgerKey
from .bucket import Bucket
from .bucket_list import BucketList, K_NUM_LEVELS

log = get_logger("Bucket")

ZERO_HASH = b"\x00" * 32

# skip-list stride constants (reference BucketManager.h): every SKIP_1
# ledgers the header's skipList[0] takes the close's bucket-list hash,
# cascading the older values down at the larger strides
SKIP_1 = 50
SKIP_2 = 5000
SKIP_3 = 50000
SKIP_4 = 500000


def calculate_skip_values(header) -> None:
    """Advance the header's skipList in place (reference
    BucketManagerImpl::calculateSkipValues, BucketManagerImpl.cpp:726-752).
    Consensus-visible: every node must shift the same values at the same
    sequence numbers or header hashes fork."""
    if header.ledgerSeq % SKIP_1 != 0:
        return
    v = header.ledgerSeq - SKIP_1
    if v > 0 and v % SKIP_2 == 0:
        v = header.ledgerSeq - SKIP_2 - SKIP_1
        if v > 0 and v % SKIP_3 == 0:
            v = header.ledgerSeq - SKIP_3 - SKIP_2 - SKIP_1
            if v > 0 and v % SKIP_4 == 0:
                header.skipList[3] = header.skipList[2]
            header.skipList[2] = header.skipList[1]
        header.skipList[1] = header.skipList[0]
    header.skipList[0] = header.bucketListHash


class BucketManager:
    def __init__(self, bucket_dir: Optional[str] = None,
                 background_merges: bool = True,
                 num_workers: int = 2, stats=None,
                 bucketdb_stats=None, faults=None,
                 bloom_bits_per_key: int = 10,
                 eager_index: bool = True) -> None:
        self.bucket_dir = bucket_dir
        if bucket_dir:
            os.makedirs(bucket_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._shared: Dict[bytes, Bucket] = {}
        self._executor = (ThreadPoolExecutor(
            max_workers=num_workers,
            thread_name_prefix="bucket-merge") if background_merges else None)
        # close cockpit (ledger/apply_stats.py): per-level sizes recorded
        # at every snapshot, merge durations from the worker pool
        self._stats = stats
        self.bucket_list = BucketList(self._executor, adopt=self.adopt_bucket,
                                      stats=stats)
        # BucketDB (ISSUE 14): bloom-filtered per-bucket indexes over the
        # live list, built at adopt time (close path + merge workers),
        # sidecars persisted beside the bucket files; LedgerTxnRoot
        # point reads route through it (bucket/bucket_index.py)
        from .bucket_index import BucketDB
        self.bucketdb = BucketDB(self, stats=bucketdb_stats, faults=faults,
                                 bits_per_key=bloom_bits_per_key,
                                 eager_index=eager_index)

    # -- store ---------------------------------------------------------------
    def bucket_filename(self, hash_: bytes) -> Optional[str]:
        if not self.bucket_dir:
            return None
        return os.path.join(self.bucket_dir, "bucket-%s.xdr" % hash_.hex())

    def adopt_bucket(self, b: Bucket) -> Bucket:
        """Deduplicate by hash and persist to the bucket dir (reference
        BucketManagerImpl::adoptFileAsBucket). Adoption also indexes the
        bucket for BucketDB (load the persisted sidecar, else build and
        persist one) — OUTSIDE the store lock, so a large merge output's
        index build never blocks concurrent bucket lookups."""
        h = b.get_hash()
        if h == ZERO_HASH:
            return b
        with self._lock:
            existing = self._shared.get(h)
            if existing is not None:
                return existing
            path = self.bucket_filename(h)
            if path and not os.path.exists(path):
                b.write_to(path + ".tmp")
                os.replace(path + ".tmp", path)
                b.path = path
            elif path:
                # bucket file already on disk (restart / catchup
                # re-download): serve reads from it
                b.path = path
            self._shared[h] = b
        self.bucketdb.on_adopt(b)
        return b

    def get_bucket_by_hash(self, hash_: bytes) -> Optional[Bucket]:
        if hash_ == ZERO_HASH:
            return Bucket()
        with self._lock:
            b = self._shared.get(hash_)
        if b is not None:
            return b
        path = self.bucket_filename(hash_)
        if path and os.path.exists(path):
            b = Bucket.read_from(path)
            return self.adopt_bucket(b)
        return None

    # -- the list ------------------------------------------------------------
    @main_thread_only
    def add_batch(self, curr_ledger: int, curr_ledger_protocol: int,
                  init_entries: Sequence[LedgerEntry],
                  live_entries: Sequence[LedgerEntry],
                  dead_entries: Sequence[LedgerKey]) -> None:
        self.bucket_list.add_batch(curr_ledger, curr_ledger_protocol,
                                   init_entries, live_entries, dead_entries)

    def get_hash(self) -> bytes:
        return self.bucket_list.get_hash()

    def snapshot_ledger(self, header) -> None:
        """Stamp the closing header with the bucket-list hash and advance
        its skipList (reference BucketManagerImpl::snapshotLedger)."""
        header.bucketListHash = self.get_hash()
        calculate_skip_values(header)
        if self._stats is not None:
            # per-level curr+snap entry counts — the close cockpit's
            # bucket-size view (bounded: K_NUM_LEVELS gauges)
            self._stats.record_level_sizes(
                (lev.level, len(lev.curr) + len(lev.snap))
                for lev in self.bucket_list.levels)

    def get_referenced_hashes(self) -> List[bytes]:
        refs: List[bytes] = []
        for lev in self.bucket_list.levels:
            for b in (lev.curr, lev.snap):
                if b.get_hash() != ZERO_HASH:
                    refs.append(b.get_hash())
            if lev.next.is_live():
                if lev.next.merge_complete():
                    refs.append(lev.next.resolve().get_hash())
                else:
                    if lev.next.input_curr_hash:
                        refs.append(lev.next.input_curr_hash)
                    if lev.next.input_snap_hash:
                        refs.append(lev.next.input_snap_hash)
                    refs.extend(lev.next.input_shadow_hashes)
        return refs

    def forget_unreferenced_buckets(
            self, extra_refs: Sequence[bytes] = ()) -> int:
        """GC: drop in-memory and on-disk buckets not referenced by the
        list (or by pending publish work via extra_refs) — reference
        BucketManagerImpl::forgetUnreferencedBuckets."""
        keep = set(self.get_referenced_hashes()) | set(extra_refs)
        dropped = 0
        victims = []
        with self._lock:
            for h in list(self._shared):
                if h not in keep:
                    b = self._shared.pop(h)
                    if b.path and os.path.exists(b.path):
                        os.remove(b.path)
                    victims.append((h, b.path))
                    dropped += 1
        # BucketDB index lifetime follows the bucket's (ISSUE 14
        # satellite): a GC'd bucket's in-memory index, cached fd and
        # persisted sidecar all go with it — a stale sidecar left behind
        # would be adopted verbatim if the same content hash ever
        # returns, which is exactly why it must match the file's fate
        for h, path in victims:
            self.bucketdb.invalidate(h, path)
        return dropped

    # -- state restore (catchup / restart) -----------------------------------
    def assume_state(self, level_hashes: Sequence[Dict[str, object]],
                     curr_ledger: int, max_protocol_version: int) -> None:
        """Adopt a full set of level hashes (from a HistoryArchiveState)
        as the current bucket list, then resume merges (reference
        BucketManagerImpl::assumeState). Each level dict carries curr/
        snap plus the serialized next merge: "next_output" (resolved) or
        "next_curr"/"next_snap"/"next_shadows" (in flight) — the latter
        is the only way to resume a shadowed pre-12 merge exactly;
        restarting it shadowless forks the bucket hash chain."""
        from .bucket_list import FutureBucket, keep_dead_entries
        assert len(level_hashes) == K_NUM_LEVELS
        # resolve every bucket BEFORE mutating any level: a missing file
        # must not leave the list half-adopted
        resolved = []
        for i, lh in enumerate(level_hashes):
            curr = self.get_bucket_by_hash(lh["curr"])
            snap = self.get_bucket_by_hash(lh["snap"])
            if curr is None or snap is None:
                raise KeyError("missing bucket for level %d" % i)
            nxt = None
            if lh.get("next_output"):
                out = self.get_bucket_by_hash(lh["next_output"])
                if out is None:
                    raise KeyError("missing next output for level %d" % i)
                nxt = ("output", out)
            elif lh.get("next_curr"):
                mc = self.get_bucket_by_hash(lh["next_curr"])
                ms = self.get_bucket_by_hash(lh["next_snap"])
                sh = [self.get_bucket_by_hash(h)
                      for h in lh.get("next_shadows", [])]
                if mc is None or ms is None or any(s is None for s in sh):
                    raise KeyError("missing next inputs for level %d" % i)
                nxt = ("inputs", (mc, ms, sh))
            resolved.append((curr, snap, nxt))
        for i, (curr, snap, nxt) in enumerate(resolved):
            lev = self.bucket_list.get_level(i)
            lev.curr = curr
            lev.snap = snap
            lev.next.clear()
            if nxt is None:
                continue
            kind, payload = nxt
            if kind == "output":
                lev.next = FutureBucket.resolved(payload)
            else:
                mc, ms, sh = payload
                on_done = None
                if self._stats is not None:
                    on_done = (lambda secs, n, _s=self._stats, _l=i:
                               _s.record_merge(_l, secs, n))
                lev.next = FutureBucket.start(
                    self._executor, mc, ms, sh,
                    keep_dead=keep_dead_entries(i),
                    max_protocol_version=max_protocol_version,
                    adopt=self.adopt_bucket, on_done=on_done)
        self.bucket_list.restart_merges(curr_ledger)

    def shutdown(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True)
        self.bucketdb.close()
