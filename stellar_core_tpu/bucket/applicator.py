"""BucketApplicator: stream a bucket's entries into ledger state.

Role parity: reference `src/bucket/BucketApplicator.{h,cpp}` — used by
catchup's ApplyBucketsWork to load a downloaded bucket-list snapshot into
the database in bounded chunks, newest level first, so the main loop stays
responsive.
"""

from __future__ import annotations

from typing import Iterable, Optional

from ..ledger.ledgertxn import LedgerTxn
from ..xdr import BucketEntryType, ledger_entry_key
from .bucket import Bucket


class BucketApplicator:
    def __init__(self, root, bucket: Bucket,
                 chunk_size: int = 0x1000) -> None:
        self._root = root
        self._entries = bucket.payload_entries()
        self._pos = 0
        self._chunk = chunk_size

    def __bool__(self) -> bool:
        return self._pos < len(self._entries)

    def advance(self) -> int:
        """Apply up to chunk_size entries in one nested commit; returns
        entries applied this step."""
        if not self:
            return 0
        n = 0
        # `with` rolls back on error: an abandoned-but-registered child
        # would otherwise block every future LedgerTxn over this root
        with LedgerTxn(self._root) as ltx:
            while self._pos < len(self._entries) and n < self._chunk:
                e = self._entries[self._pos]
                self._pos += 1
                t = e.disc
                if t in (BucketEntryType.LIVEENTRY,
                         BucketEntryType.INITENTRY):
                    key = ledger_entry_key(e.value)
                    cur = ltx.load(key)
                    if cur is not None:
                        cur.lastModifiedLedgerSeq = \
                            e.value.lastModifiedLedgerSeq
                        cur.data = e.value.data
                        cur.ext = e.value.ext
                    else:
                        ltx.create(e.value)
                elif t == BucketEntryType.DEADENTRY:
                    if ltx.load(e.value) is not None:
                        ltx.erase(e.value)
                n += 1
        return n


def apply_buckets(root, buckets: Iterable[Bucket]) -> int:
    """Apply a sequence of buckets newest-first (reference ApplyBucketsWork
    order: level 0 curr, level 0 snap, level 1 curr, ...). Entries already
    present (set by a newer bucket) must win, hence the load-before-create
    check in advance(); dead entries delete only if present."""
    total = 0
    seen = set()
    # Newest-first with a seen-key shield: the first bucket to mention a key
    # decides its final state; older buckets' entries for that key are noise.
    with LedgerTxn(root) as ltx:
        for b in buckets:
            for e in b.payload_entries():
                t = e.disc
                if t == BucketEntryType.METAENTRY:
                    continue
                if t in (BucketEntryType.LIVEENTRY,
                         BucketEntryType.INITENTRY):
                    key = ledger_entry_key(e.value)
                    kx = key.to_xdr()
                    if kx in seen:
                        continue
                    seen.add(kx)
                    cur = ltx.load(key)
                    if cur is not None:
                        cur.lastModifiedLedgerSeq = \
                            e.value.lastModifiedLedgerSeq
                        cur.data = e.value.data
                        cur.ext = e.value.ext
                    else:
                        ltx.create(e.value)
                elif t == BucketEntryType.DEADENTRY:
                    kx = e.value.to_xdr()
                    if kx in seen:
                        continue
                    seen.add(kx)
                    if ltx.load(e.value) is not None:
                        ltx.erase(e.value)
                total += 1
    return total
