"""BucketList: LSM-like temporal leveling of canonical ledger entries.

Role parity: reference `src/bucket/BucketList.{h,cpp}` — kNumLevels=11
levels, each (curr, snap); level i spills every levelHalf(i) ledgers; merges
run in the background as futures (reference FutureBucket,
`bucket/FutureBucket.{h,cpp}`) and are committed (next→curr) when the level
above spills into them. The whole-list hash is
SHA256(concat_i SHA256(curr_i.hash ‖ snap_i.hash)) and lands in
`LedgerHeader.bucketListHash`.

TPU-native note: merges are pure CPU/IO (sorted-run merge) and stay on the
host worker pool, exactly like the reference's worker threads — device
batches are for signature verification only.
"""

from __future__ import annotations

from concurrent.futures import Executor, Future
from functools import lru_cache
from typing import Callable, List, Optional, Sequence

from ..crypto.hashing import SHA256
from ..util.log import get_logger
from ..xdr import LedgerEntry, LedgerKey
from .bucket import Bucket, merge_buckets

log = get_logger("Bucket")

K_NUM_LEVELS = 11
UINT32_MAX = 0xFFFFFFFF


# -- level arithmetic (reference BucketList.cpp:199-353) ---------------------

def level_size(level: int) -> int:
    """Idealized level size: 4^(level+1) (BucketList.cpp:210-215)."""
    assert level < K_NUM_LEVELS
    return 1 << (2 * (level + 1))


def level_half(level: int) -> int:
    return level_size(level) >> 1


def mask(v: int, m: int) -> int:
    return v & ~(m - 1) & UINT32_MAX


def level_should_spill(ledger: int, level: int) -> bool:
    """True at ledgers where `level` snaps curr and spills into level+1
    (BucketList.cpp:386-398); the deepest level never spills."""
    if level == K_NUM_LEVELS - 1:
        return False
    return (ledger == mask(ledger, level_half(level)) or
            ledger == mask(ledger, level_size(level)))


def keep_dead_entries(level: int) -> bool:
    """Tombstones are elided only when merging into the deepest level
    (BucketList.cpp:401-405)."""
    return level < K_NUM_LEVELS - 1


@lru_cache(maxsize=1 << 16)
def size_of_curr(ledger: int, level: int) -> int:
    """Number of ledgers covered by curr at `level` as of `ledger`
    (BucketList.cpp:245-283; validated by reference BucketListTests).
    Memoized: the recurrence branches into both (prev_relevant, level)
    and every lower level, which is exponential uncached (the reference
    caches the same way via BucketListDepth tables)."""
    assert ledger != 0 and level < K_NUM_LEVELS
    if level == 0:
        return 1 if ledger == 1 else 1 + ledger % 2
    size = level_size(level)
    half = level_half(level)
    if level != K_NUM_LEVELS - 1 and mask(ledger, half) != 0:
        size_delta = 1 << (2 * level - 1)
        if mask(ledger, half) == ledger or mask(ledger, size) == ledger:
            return size_delta
        prev_size = level_size(level - 1)
        prev_half = level_half(level - 1)
        prev_relevant = max(mask(ledger - 1, prev_half),
                            mask(ledger - 1, prev_size),
                            mask(ledger - 1, half),
                            mask(ledger - 1, size))
        if mask(ledger, prev_half) == ledger or \
                mask(ledger, prev_size) == ledger:
            return size_of_curr(prev_relevant, level) + size_delta
        return size_of_curr(prev_relevant, level)
    blsize = 0
    for lv in range(level):
        blsize += size_of_curr(ledger, lv)
        blsize += size_of_snap(ledger, lv)
    return ledger - blsize


@lru_cache(maxsize=1 << 16)
def size_of_snap(ledger: int, level: int) -> int:
    """(BucketList.cpp:286-310)."""
    assert ledger != 0 and level < K_NUM_LEVELS
    if level == K_NUM_LEVELS - 1:
        return 0
    if mask(ledger, level_size(level)) != 0:
        return level_half(level)
    size = 0
    for lv in range(level):
        size += size_of_curr(ledger, lv)
        size += size_of_snap(ledger, lv)
    size += size_of_curr(ledger, level)
    return ledger - size


def oldest_ledger_in_curr(ledger: int, level: int) -> int:
    """(BucketList.cpp:313-335)."""
    if size_of_curr(ledger, level) == 0:
        return UINT32_MAX
    count = ledger
    for lv in range(level):
        count -= size_of_curr(ledger, lv)
        count -= size_of_snap(ledger, lv)
    count -= size_of_curr(ledger, level)
    return count + 1


def oldest_ledger_in_snap(ledger: int, level: int) -> int:
    """(BucketList.cpp:337-354)."""
    if size_of_snap(ledger, level) == 0:
        return UINT32_MAX
    count = ledger
    for lv in range(level + 1):
        count -= size_of_curr(ledger, lv)
        count -= size_of_snap(ledger, lv)
    return count + 1


# -- FutureBucket ------------------------------------------------------------

class FutureBucket:
    """A pending (or resolved) merge producing a level's next curr
    (reference bucket/FutureBucket.h:54-63). States: clear, merging
    (future in flight), or live-resolved. Input hashes are retained so
    merges can be re-kicked after restart (restartMerges parity)."""

    FB_CLEAR = 0
    FB_MERGING = 1
    FB_RESOLVED = 2

    def __init__(self) -> None:
        self._state = FutureBucket.FB_CLEAR
        self._future: Optional[Future] = None
        self._result: Optional[Bucket] = None
        self.input_curr_hash: Optional[bytes] = None
        self.input_snap_hash: Optional[bytes] = None
        self.input_shadow_hashes: List[bytes] = []

    @classmethod
    def start(cls, executor: Optional[Executor], curr: Bucket, snap: Bucket,
              shadows: Sequence[Bucket], keep_dead: bool,
              max_protocol_version: int,
              adopt: Callable[[Bucket], Bucket],
              on_done: Optional[Callable[[float, int], None]] = None
              ) -> "FutureBucket":
        """`on_done(seconds, out_entries)` fires when the merge finishes
        (on the worker thread when an executor runs it) — the close
        cockpit's bucket-merge duration telemetry."""
        fb = cls()
        fb._state = FutureBucket.FB_MERGING
        fb.input_curr_hash = curr.get_hash()
        fb.input_snap_hash = snap.get_hash()
        fb.input_shadow_hashes = [s.get_hash() for s in shadows]

        def run() -> Bucket:
            from ..util.timer import real_monotonic
            t0 = real_monotonic()
            out = adopt(merge_buckets(
                curr, snap, shadows, keep_dead_entries=keep_dead,
                max_protocol_version=max_protocol_version))
            if on_done is not None:
                on_done(real_monotonic() - t0, len(out))
            return out

        if executor is not None:
            fb._future = executor.submit(run)
        else:
            fb._result = run()
        return fb

    @classmethod
    def resolved(cls, b: Bucket) -> "FutureBucket":
        fb = cls()
        fb._state = FutureBucket.FB_RESOLVED
        fb._result = b
        return fb

    def is_clear(self) -> bool:
        return self._state == FutureBucket.FB_CLEAR

    def is_live(self) -> bool:
        return self._state != FutureBucket.FB_CLEAR

    def is_merging(self) -> bool:
        return self._state == FutureBucket.FB_MERGING

    def merge_complete(self) -> bool:
        if self._state == FutureBucket.FB_RESOLVED:
            return True
        return self._future is not None and self._future.done()

    def resolve(self) -> Bucket:
        """Block until the merged bucket is available (reference
        FutureBucket::resolve)."""
        assert self.is_live()
        if self._state == FutureBucket.FB_MERGING:
            if self._future is not None:
                self._result = self._future.result()
                self._future = None
            self._state = FutureBucket.FB_RESOLVED
        assert self._result is not None
        return self._result

    def clear(self) -> None:
        self._state = FutureBucket.FB_CLEAR
        self._future = None
        self._result = None
        self.input_curr_hash = None
        self.input_snap_hash = None
        self.input_shadow_hashes = []

    def has_hashes(self) -> bool:
        return self.input_curr_hash is not None


# -- levels ------------------------------------------------------------------

class BucketLevel:
    """(curr, snap) pair plus the in-flight next curr
    (reference BucketLevel, BucketList.cpp:22-178)."""

    def __init__(self, level: int) -> None:
        self.level = level
        self.curr = Bucket()
        self.snap = Bucket()
        self.next = FutureBucket()
        # (curr_hash, snap_hash) -> level hash: most levels change only
        # at their spill boundaries, so a close re-hashes O(changed
        # levels), not all 11 (ISSUE 12 — the incremental half of the
        # state commitment, applied to the consensus hash chain too)
        self._hash_cache: tuple = ()

    def get_hash(self) -> bytes:
        key = (self.curr.get_hash(), self.snap.get_hash())
        if len(self._hash_cache) == 2 and self._hash_cache[0] == key:
            return self._hash_cache[1]
        h = SHA256()
        h.add(key[0])
        h.add(key[1])
        out = h.finish()
        self._hash_cache = (key, out)
        return out

    def commit(self) -> None:
        """Promote a live next merge into curr (BucketList.cpp:80-89)."""
        if self.next.is_live():
            self.curr = self.next.resolve()
            self.next.clear()

    def snap_level(self) -> Bucket:
        """curr→snap, fresh empty curr (BucketList.cpp:168-178)."""
        self.snap = self.curr
        self.curr = Bucket()
        return self.snap

    def prepare(self, executor: Optional[Executor], curr_ledger: int,
                curr_ledger_protocol: int, snap: Bucket,
                shadows: Sequence[Bucket],
                adopt: Callable[[Bucket], Bucket],
                stats=None) -> None:
        """Kick off the merge for this level's next curr
        (BucketList.cpp:127-166). If this level's own curr is one
        prev-level-spill away from snapping, merge against an empty curr
        instead (the pending-snapshot subtlety). `stats` (ApplyStats)
        records the merge's duration against this level."""
        assert not self.next.is_merging(), "double prepare"
        curr = self.curr
        if self.level != 0:
            next_change = curr_ledger + level_half(self.level - 1)
            if level_should_spill(next_change, self.level):
                curr = Bucket()
        # at-and-after protocol 12 the snap determines shadow removal
        from .bucket import FIRST_PROTOCOL_SHADOWS_REMOVED
        use_shadows = [] if snap.get_version() >= \
            FIRST_PROTOCOL_SHADOWS_REMOVED else list(shadows)
        on_done = None
        if stats is not None:
            level = self.level
            on_done = (lambda secs, n, _s=stats, _l=level:
                       _s.record_merge(_l, secs, n))
        self.next = FutureBucket.start(
            executor, curr, snap, use_shadows,
            keep_dead=keep_dead_entries(self.level),
            max_protocol_version=curr_ledger_protocol, adopt=adopt,
            on_done=on_done)


class BucketList:
    def __init__(self, executor: Optional[Executor] = None,
                 adopt: Optional[Callable[[Bucket], Bucket]] = None,
                 stats=None) -> None:
        self.levels = [BucketLevel(i) for i in range(K_NUM_LEVELS)]
        self._executor = executor
        self._adopt = adopt or (lambda b: b)
        self._stats = stats   # ApplyStats: merge durations per level

    def get_level(self, i: int) -> BucketLevel:
        return self.levels[i]

    def get_hash(self) -> bytes:
        h = SHA256()
        for lev in self.levels:
            h.add(lev.get_hash())
        return h.finish()

    def resolve_any_ready_futures(self) -> None:
        for lev in self.levels:
            if lev.next.is_merging() and lev.next.merge_complete():
                lev.next.resolve()

    def futures_all_resolved(self, max_level: int = K_NUM_LEVELS - 1) -> bool:
        return not any(self.levels[i].next.is_merging()
                       for i in range(max_level + 1))

    def resolve_all_futures(self) -> None:
        for lev in self.levels:
            if lev.next.is_merging():
                lev.next.resolve()

    def get_max_merge_level(self, curr_ledger: int) -> int:
        i = 0
        while i < K_NUM_LEVELS - 1 and level_should_spill(curr_ledger, i):
            i += 1
        return i

    def add_batch(self, curr_ledger: int, curr_ledger_protocol: int,
                  init_entries: Sequence[LedgerEntry],
                  live_entries: Sequence[LedgerEntry],
                  dead_entries: Sequence[LedgerKey]) -> None:
        """One ledger close's delta enters level 0; spills cascade downward
        (reference BucketList::addBatch, BucketList.cpp:458-586). Processed
        deepest-level-first so a curr is snapped the moment it is
        half-a-level full."""
        assert curr_ledger > 0
        shadows: List[Bucket] = []
        for lev in self.levels:
            shadows.append(lev.curr)
            shadows.append(lev.snap)
        # levels i-1 and i never shadow their own merge (see reference
        # comment at BucketList.cpp:466-498): drop two per descent
        shadows = shadows[:-2]
        for i in range(K_NUM_LEVELS - 1, 0, -1):
            shadows = shadows[:-2]
            if level_should_spill(curr_ledger, i - 1):
                snap = self.levels[i - 1].snap_level()
                self.levels[i].commit()
                self.levels[i].prepare(self._executor, curr_ledger,
                                       curr_ledger_protocol, snap, shadows,
                                       self._adopt, stats=self._stats)
        assert not shadows
        fresh = self._adopt(Bucket.fresh(curr_ledger_protocol, init_entries,
                                         live_entries, dead_entries))
        self.levels[0].prepare(self._executor, curr_ledger,
                               curr_ledger_protocol, fresh, [], self._adopt,
                               stats=self._stats)
        self.levels[0].commit()
        self.resolve_any_ready_futures()

    def restart_merges(self, curr_ledger: int) -> None:
        """Re-kick merges whose inputs we still hold after a restart
        (reference BucketList::restartMerges, BucketList.cpp:588-640).
        Only valid with shadows removed (protocol >= 12), where the next
        state for level i+1 is recomputable from level i's snap alone; a
        clear next over a pre-12 nonempty snap means the serialized merge
        state was lost — restarting it shadowless would fork the bucket
        hash chain, so it is an error (reference :625-648)."""
        from .bucket import FIRST_PROTOCOL_SHADOWS_REMOVED
        for i in range(1, K_NUM_LEVELS):
            lev = self.levels[i]
            if lev.next.is_clear():
                snap = self.levels[i - 1].snap
                if snap.is_empty():
                    continue
                version = snap.get_version()
                if version < FIRST_PROTOCOL_SHADOWS_REMOVED:
                    raise RuntimeError(
                        "invalid state: level %d has clear future bucket "
                        "but pre-%d snap" % (i,
                                             FIRST_PROTOCOL_SHADOWS_REMOVED))
                # round the ledger down to when the merge was STARTED and
                # merge at the snap's own version — prepare()'s
                # pending-snapshot branch keys off the merge-start ledger,
                # and a mid-window restart ledger could flip its curr-vs-
                # empty decision (reference restartMerges:650-654)
                merge_start = mask(curr_ledger, level_half(i - 1))
                lev.prepare(self._executor, merge_start,
                            version, snap, [], self._adopt,
                            stats=self._stats)
