"""Bucket: an immutable, content-addressed, sorted file of ledger entries.

Role parity: reference `src/bucket/Bucket.{h,cpp}` — a bucket is a sorted
run of BucketEntry records (META first, then LIVE/INIT/DEAD by entry
identity) whose SHA256 over the file bytes is its name; `fresh()` builds
one from a ledger close's delta (Bucket.cpp:136-167) and `merge()` combines
an older and newer bucket under the protocol-versioned INITENTRY/shadow
rules (Bucket.cpp:455-638).

Buckets persist in the reference's on-disk format: RFC 5531 record-marked
XDR stream (util/xdrstream framing), so history archives interop with the
same byte layout the hash chain commits to.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence, Tuple

from ..util.xdrstream import XDRInputFileStream, XDROutputFileStream
from ..xdr import (
    BucketEntry, BucketEntryType, LedgerEntry, LedgerKey, ledger_entry_key,
    ledger_key_sort_key,
)

# Protocol feature gates (reference src/bucket/Bucket.h:40-46).
FIRST_PROTOCOL_SUPPORTING_INITENTRY_AND_METAENTRY = 11
FIRST_PROTOCOL_SHADOWS_REMOVED = 12

_META = BucketEntryType.METAENTRY
_LIVE = BucketEntryType.LIVEENTRY
_DEAD = BucketEntryType.DEADENTRY
_INIT = BucketEntryType.INITENTRY


def bucket_entry_sort_key(e: BucketEntry):
    """Reference BucketEntryIdCmp (src/bucket/LedgerCmp.h:90-140):
    METAENTRY below everything, others ordered by ledger-entry identity
    (LIVE/INIT expose liveEntry.data, DEAD exposes deadEntry)."""
    t = e.disc
    if t == _META:
        return ((-1,),)
    if t in (_LIVE, _INIT):
        return (ledger_key_sort_key(ledger_entry_key(e.value)),)
    if t == _DEAD:
        return (ledger_key_sort_key(e.value),)
    raise ValueError("malformed bucket entry type %d" % t)


def check_protocol_legality(e: BucketEntry, protocol_version: int) -> None:
    """INIT/META entries are illegal below protocol 11
    (reference Bucket.cpp:190-200)."""
    if protocol_version < FIRST_PROTOCOL_SUPPORTING_INITENTRY_AND_METAENTRY \
            and e.disc in (_INIT, _META):
        raise ValueError(
            "unsupported entry type %d in protocol %d bucket"
            % (e.disc, protocol_version))


class Bucket:
    """An immutable sorted entry run. Empty buckets have the zero hash and
    no backing file (reference Bucket() default ctor)."""

    __slots__ = ("_entries", "_hash", "path")

    def __init__(self, entries: Sequence[BucketEntry] = (),
                 hash_: Optional[bytes] = None,
                 path: Optional[str] = None) -> None:
        self._entries: Tuple[BucketEntry, ...] = tuple(entries)
        if hash_ is None:
            hash_ = _hash_entries(self._entries)
        self._hash = hash_
        self.path = path

    # -- identity ------------------------------------------------------------
    def get_hash(self) -> bytes:
        return self._hash

    def is_empty(self) -> bool:
        return not self._entries

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def entries(self) -> Tuple[BucketEntry, ...]:
        return self._entries

    def __iter__(self):
        return iter(self._entries)

    # -- metadata ------------------------------------------------------------
    def get_version(self) -> int:
        """Protocol version from the META entry; 0 for empty/pre-11 buckets
        (reference Bucket::getBucketVersion, Bucket.cpp:641-647)."""
        if self._entries and self._entries[0].disc == _META:
            return self._entries[0].value.ledgerVersion
        return 0

    def payload_entries(self) -> Tuple[BucketEntry, ...]:
        """Entries excluding the leading META (what input iterators yield)."""
        if self._entries and self._entries[0].disc == _META:
            return self._entries[1:]
        return self._entries

    # -- persistence ---------------------------------------------------------
    def write_to(self, path: str) -> None:
        # the memoized framed records the hash already serialized —
        # a bucket file write never re-serializes its entries
        with XDROutputFileStream(path) as out:
            for e in self._entries:
                out.write_record(entry_record(e))
        self.path = path

    @classmethod
    def read_from(cls, path: str) -> "Bucket":
        with XDRInputFileStream(path) as ins:
            entries = list(ins.read_all(BucketEntry))
        return cls(entries, path=path)

    # -- constructors --------------------------------------------------------
    @classmethod
    def fresh(cls, protocol_version: int,
              init_entries: Iterable[LedgerEntry],
              live_entries: Iterable[LedgerEntry],
              dead_entries: Iterable[LedgerKey]) -> "Bucket":
        """Build a level-0 batch bucket from one ledger close's delta
        (reference Bucket::fresh, Bucket.cpp:136-167). Below protocol 11,
        inits demote to LIVE and no META entry is written."""
        use_init = (protocol_version >=
                    FIRST_PROTOCOL_SUPPORTING_INITENTRY_AND_METAENTRY)
        entries: List[BucketEntry] = []
        for e in init_entries:
            entries.append(BucketEntry.init(e) if use_init
                           else BucketEntry.live(e))
        for e in live_entries:
            entries.append(BucketEntry.live(e))
        for k in dead_entries:
            entries.append(BucketEntry.dead(k))
        entries.sort(key=bucket_entry_sort_key)
        for a, b in zip(entries, entries[1:]):
            if bucket_entry_sort_key(a) == bucket_entry_sort_key(b):
                raise ValueError("duplicate identity in fresh batch")
        out = _OutputRun(keep_dead=True,
                         meta_version=protocol_version if use_init else None)
        for e in entries:
            out.put(e)
        return out.bucket()


class _OutputRun:
    """Sorted, deduplicating output accumulator (reference
    BucketOutputIterator, BucketOutputIterator.cpp:65-108): later entries
    with the same identity replace buffered ones; DEAD entries are elided
    when keep_dead is false (oldest level); META goes first when the merge
    protocol supports it."""

    def __init__(self, keep_dead: bool, meta_version: Optional[int]) -> None:
        self._entries: List[BucketEntry] = []
        self._buf: Optional[BucketEntry] = None
        self._buf_key = None
        self._keep_dead = keep_dead
        self._meta_version = meta_version
        self._put_meta = meta_version is not None

    def put(self, e: BucketEntry, k=None) -> None:
        if not self._keep_dead and e.disc == _DEAD:
            return
        if k is None:
            k = bucket_entry_sort_key(e)
        if self._buf is not None:
            assert not (k < self._buf_key), "entries out of order"
            if self._buf_key < k:
                self._entries.append(self._buf)
        self._buf = e
        self._buf_key = k

    def bucket(self) -> Bucket:
        if self._buf is not None:
            self._entries.append(self._buf)
            self._buf = None
        if not self._entries:
            return Bucket()          # empty output drops the meta too
        entries = self._entries
        if self._put_meta:
            entries = [BucketEntry.meta(self._meta_version)] + entries
        return Bucket(entries)


def merge_buckets(old_bucket: Bucket, new_bucket: Bucket,
                  shadows: Sequence[Bucket] = (),
                  keep_dead_entries: bool = True,
                  max_protocol_version: int = 0xFFFFFFFF) -> Bucket:
    """Merge an older and a newer bucket into one (reference Bucket::merge,
    Bucket.cpp:599-638 + mergeCasesWithEqualKeys :460-597 + maybePut
    :203-275).

    Same-key lifecycle table (protocol >= 11):
        old DEAD + new INIT=x -> LIVE=x
        old INIT + new LIVE=y -> INIT=y
        old INIT + new DEAD   -> (annihilate)
        otherwise             -> newer wins
    Shadow elision only below protocol 12; below 11 it elides every shadowed
    entry, at 11 it keeps INIT/DEAD lifecycle entries.
    """
    protocol_version = max(old_bucket.get_version(), new_bucket.get_version())
    for s in shadows:
        v = s.get_version()
        if v < FIRST_PROTOCOL_SHADOWS_REMOVED:
            protocol_version = max(protocol_version, v)
    if protocol_version > max_protocol_version:
        raise ValueError("bucket protocol %d exceeds max %d"
                         % (protocol_version, max_protocol_version))

    keep_shadowed_lifecycle = (
        protocol_version >= FIRST_PROTOCOL_SUPPORTING_INITENTRY_AND_METAENTRY)
    if protocol_version >= FIRST_PROTOCOL_SHADOWS_REMOVED:
        shadow_runs: List[Tuple[BucketEntry, ...]] = []
    else:
        shadow_runs = [s.payload_entries() for s in shadows]

    put_meta = (protocol_version >=
                FIRST_PROTOCOL_SUPPORTING_INITENTRY_AND_METAENTRY)
    out = _OutputRun(keep_dead=keep_dead_entries,
                     meta_version=protocol_version if put_meta else None)
    # precompute sort keys once per entry; comparisons dominate the merge
    shadow_keys = [[bucket_entry_sort_key(e) for e in run]
                   for run in shadow_runs]
    shadow_pos = [0] * len(shadow_runs)

    def maybe_put(e: BucketEntry, ek) -> None:
        if keep_shadowed_lifecycle and e.disc in (_INIT, _DEAD):
            out.put(e, ek)
            return
        for i, keys in enumerate(shadow_keys):
            p = shadow_pos[i]
            while p < len(keys) and keys[p] < ek:
                p += 1
            shadow_pos[i] = p
            if p < len(keys) and not (ek < keys[p]):
                return               # shadowed: elide
        out.put(e, ek)

    oe = old_bucket.payload_entries()
    ne = new_bucket.payload_entries()
    ok = [bucket_entry_sort_key(e) for e in oe]
    nk = [bucket_entry_sort_key(e) for e in ne]
    i = j = 0
    while i < len(oe) or j < len(ne):
        if j >= len(ne) or (i < len(oe) and ok[i] < nk[j]):
            check_protocol_legality(oe[i], protocol_version)
            maybe_put(oe[i], ok[i])
            i += 1
            continue
        if i >= len(oe) or nk[j] < ok[i]:
            check_protocol_legality(ne[j], protocol_version)
            maybe_put(ne[j], nk[j])
            j += 1
            continue
        # equal identity: lifecycle merge
        o, n = oe[i], ne[j]
        check_protocol_legality(o, protocol_version)
        check_protocol_legality(n, protocol_version)
        if n.disc == _INIT:
            if o.disc != _DEAD:
                raise ValueError("malformed bucket: old non-DEAD + new INIT")
            maybe_put(BucketEntry.live(n.value), nk[j])
        elif o.disc == _INIT:
            if n.disc == _LIVE:
                maybe_put(BucketEntry.init(n.value), nk[j])
            elif n.disc == _DEAD:
                pass                 # create+delete annihilate
            else:
                raise ValueError("malformed bucket: old INIT + new non-DEAD")
        else:
            maybe_put(n, nk[j])
        i += 1
        j += 1

    return out.bucket()


def entry_record(e: BucketEntry) -> bytes:
    """One entry's on-disk framed record (RFC 5531 mark + XDR body),
    MEMOIZED on the entry object. Bucket entries are immutable
    snapshots by construction (the ledgertxn layer hands the close
    delta out as structural copies, and buckets never mutate their
    entries), so one serialization serves the bucket's identity hash,
    its file write, AND every later merge that re-hashes the same
    entry objects into a new bucket — the `bucket add` close-phase
    win the BENCH_r11 leg gates."""
    rec = e.__dict__.get("_sct_rec")
    if rec is None:
        from ..util.xdrstream import frame_record
        rec = frame_record(e.to_xdr())
        e.__dict__["_sct_rec"] = rec
    return rec


def entry_record_chunks(entries: Sequence[BucketEntry]):
    """The bucket's on-disk byte stream as chunks — the exact bytes
    XDROutputFileStream writes, so the stream digest IS the file
    identity."""
    for e in entries:
        yield entry_record(e)


def _hash_entries(entries: Sequence[BucketEntry]) -> bytes:
    """Hash over the serialized stream exactly as it sits on disk
    (reference hashes the XDR file bytes including record marks via
    SHA256 in XDROutputFileStream::writeOne). Routed through the
    bounded-join stream digest (ISSUE 12): one C-level hashlib update
    per ~1 MiB group, over memoized per-entry records — registry-free
    (merge worker threads call this)."""
    if not entries:
        return b"\x00" * 32
    from ..crypto.batch_hasher import stream_digest
    return stream_digest(entry_record_chunks(entries))
