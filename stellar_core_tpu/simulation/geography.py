"""Geographic latency profiles: seeded per-link latency matrices.

Role parity: the reference's Simulation connects loopback peers with
zero latency, so cross-region effects (externalize skew, straggler
regions, partition-heal convergence) are invisible; the
committee-consensus measurements (PAPERS.md, arXiv:2302.00418) show
commit latency at scale is dominated by exactly those effects. A
`LatencyMatrix` assigns every node a named region round-robin and draws
one deterministic per-link latency from the profile's intra/inter-region
band using a seeded stream — the same (seed, profile, node set) always
yields the same matrix, so scenario runs replay identically.

The matrix feeds `ChaosTransport.link_delay_s` (OVER_PEERS) or
`LoopbackChannel.latency_s` (OVER_LOOPBACK) via
`Simulation.apply_latency_matrix`; delays ride the sender's virtual
clock, so they are deterministic and free of wall time.
"""

from __future__ import annotations

import random
from typing import Dict, Iterable, List, Tuple

# name -> {regions, intra_ms (lo, hi), inter_ms (lo, hi)}
PROFILES: Dict[str, dict] = {
    # one datacenter: sub-millisecond everywhere
    "single-dc": {"regions": ["dc"],
                  "intra_ms": (0.1, 0.5), "inter_ms": (0.1, 0.5)},
    # three continents: fast inside a region, slow across
    "three-region": {"regions": ["us", "eu", "ap"],
                     "intra_ms": (1.0, 5.0), "inter_ms": (30.0, 120.0)},
    # five regions, long tails — the internet-scale shape
    "global": {"regions": ["us-east", "us-west", "eu", "ap", "sa"],
               "intra_ms": (1.0, 8.0), "inter_ms": (40.0, 180.0)},
}


class LatencyMatrix:
    """Seeded symmetric per-link latency assignment over named nodes."""

    def __init__(self, names: Iterable[str], profile: str = "three-region",
                 seed: int = 0) -> None:
        if profile not in PROFILES:
            raise ValueError("unknown latency profile %r; known: %s"
                             % (profile, ", ".join(sorted(PROFILES))))
        self.profile = profile
        self.seed = seed
        self._spec = PROFILES[profile]
        # per-matrix stream: one seed replays one matrix exactly,
        # independent of the global RNG state (D2: seeded, never ambient)
        self._rng = random.Random("geo:%d:%s" % (seed, profile))
        self.region: Dict[str, str] = {}
        self._lat: Dict[Tuple[str, str], float] = {}
        for n in sorted(names):
            self.ensure(n)

    def ensure(self, name: str) -> None:
        """Assign `name` a region (round-robin over the profile's list,
        in assignment order) and draw latencies to every known node —
        late-joining nodes get deterministic links too."""
        if name in self.region:
            return
        regions: List[str] = self._spec["regions"]
        self.region[name] = regions[len(self.region) % len(regions)]
        for other in sorted(self.region):
            if other == name:
                continue
            band = (self._spec["intra_ms"]
                    if self.region[other] == self.region[name]
                    else self._spec["inter_ms"])
            lo, hi = band
            key = (min(name, other), max(name, other))
            self._lat[key] = self._rng.uniform(lo, hi) / 1000.0

    def latency_s(self, a: str, b: str) -> float:
        """One-way link latency in seconds (symmetric); 0.0 for an
        unknown pair (e.g. a node outside the matrix)."""
        return self._lat.get((min(a, b), max(a, b)), 0.0)

    def to_json(self) -> dict:
        return {
            "profile": self.profile,
            "seed": self.seed,
            "regions": dict(self.region),
            "links_ms": {"%s|%s" % k: round(v * 1000.0, 3)
                         for k, v in sorted(self._lat.items())},
        }
