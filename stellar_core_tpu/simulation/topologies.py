"""Topologies: canned quorum/network shapes for simulations.

Role parity: reference `src/simulation/Topologies.{h,cpp}` (core4, cycle,
branched, hierarchical).
"""

from __future__ import annotations

from typing import List

from ..crypto.hashing import sha256
from ..crypto.keys import SecretKey
from ..xdr import SCPQuorumSet
from .simulation import Simulation


def _keys(n: int, tag: bytes) -> List[SecretKey]:
    return [SecretKey.from_seed(sha256(tag + bytes([i])))
            for i in range(n)]


def core(n: int, threshold: int,
         passphrase: str = "(sct) simulation network",
         mode: int = Simulation.OVER_LOOPBACK,
         cfg_tweak=None) -> Simulation:
    """Fully-connected core of n validators all trusting each other."""
    sim = Simulation(mode=mode, network_passphrase=passphrase)
    keys = _keys(n, b"core")
    qset = SCPQuorumSet(threshold=threshold,
                        validators=[k.public_key for k in keys],
                        innerSets=[])
    names = []
    for k in keys:
        node = sim.add_node(k, qset, cfg_tweak=cfg_tweak)
        names.append(node.name)
    for i in range(n):
        for j in range(i + 1, n):
            sim.connect(names[i], names[j])
    return sim


def core4(passphrase: str = "(sct) simulation network") -> Simulation:
    return core(4, 3, passphrase)


def cycle(n: int = 4) -> Simulation:
    """Ring: each node trusts itself + both neighbours (threshold 2)."""
    sim = Simulation()
    keys = _keys(n, b"cycle")
    names = []
    for i, k in enumerate(keys):
        left = keys[(i - 1) % n].public_key
        right = keys[(i + 1) % n].public_key
        qset = SCPQuorumSet(threshold=2,
                            validators=[k.public_key, left, right],
                            innerSets=[])
        node = sim.add_node(k, qset,
                            cfg_tweak=lambda c: setattr(
                                c, "UNSAFE_QUORUM", True))
        names.append(node.name)
    for i in range(n):
        sim.connect(names[i], names[(i + 1) % n])
    return sim


def branched_core(n_core: int = 3) -> Simulation:
    """Core + one leaf validator attached to each core node."""
    sim = Simulation()
    core_keys = _keys(n_core, b"bcore")
    core_q = SCPQuorumSet(
        threshold=(n_core * 2 + 2) // 3,
        validators=[k.public_key for k in core_keys], innerSets=[])
    core_names = [sim.add_node(k, core_q).name for k in core_keys]
    for i in range(n_core):
        for j in range(i + 1, n_core):
            sim.connect(core_names[i], core_names[j])
    leaf_keys = _keys(n_core, b"leaf")
    for i, lk in enumerate(leaf_keys):
        q = SCPQuorumSet(threshold=2, validators=[
            lk.public_key, core_keys[i].public_key], innerSets=[])
        leaf = sim.add_node(lk, q)
        sim.connect(leaf.name, core_names[i])
    return sim
