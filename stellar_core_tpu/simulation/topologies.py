"""Topologies: canned quorum/network shapes for simulations.

Role parity: reference `src/simulation/Topologies.{h,cpp}` (core4, cycle,
branched, hierarchical).
"""

from __future__ import annotations

from typing import List

from ..crypto.hashing import sha256
from ..crypto.keys import SecretKey
from ..xdr import SCPQuorumSet
from .simulation import Simulation


def _keys(n: int, tag: bytes) -> List[SecretKey]:
    return [SecretKey.from_seed(sha256(tag + bytes([i])))
            for i in range(n)]


def core(n: int, threshold: int,
         passphrase: str = "(sct) simulation network",
         mode: int = Simulation.OVER_LOOPBACK,
         cfg_tweak=None) -> Simulation:
    """Fully-connected core of n validators all trusting each other."""
    sim = Simulation(mode=mode, network_passphrase=passphrase)
    keys = _keys(n, b"core")
    qset = SCPQuorumSet(threshold=threshold,
                        validators=[k.public_key for k in keys],
                        innerSets=[])
    names = []
    for k in keys:
        node = sim.add_node(k, qset, cfg_tweak=cfg_tweak)
        names.append(node.name)
    for i in range(n):
        for j in range(i + 1, n):
            sim.connect(names[i], names[j])
    return sim


def core4(passphrase: str = "(sct) simulation network") -> Simulation:
    return core(4, 3, passphrase)


def cycle(n: int = 4) -> Simulation:
    """Ring: each node trusts itself + both neighbours (threshold 2)."""
    sim = Simulation()
    keys = _keys(n, b"cycle")
    names = []
    for i, k in enumerate(keys):
        left = keys[(i - 1) % n].public_key
        right = keys[(i + 1) % n].public_key
        qset = SCPQuorumSet(threshold=2,
                            validators=[k.public_key, left, right],
                            innerSets=[])
        node = sim.add_node(k, qset,
                            cfg_tweak=lambda c: setattr(
                                c, "UNSAFE_QUORUM", True))
        names.append(node.name)
    for i in range(n):
        sim.connect(names[i], names[(i + 1) % n])
    return sim


def branched_core(n_core: int = 3) -> Simulation:
    """Core + one leaf validator attached to each core node."""
    sim = Simulation()
    core_keys = _keys(n_core, b"bcore")
    core_q = SCPQuorumSet(
        threshold=(n_core * 2 + 2) // 3,
        validators=[k.public_key for k in core_keys], innerSets=[])
    core_names = [sim.add_node(k, core_q).name for k in core_keys]
    for i in range(n_core):
        for j in range(i + 1, n_core):
            sim.connect(core_names[i], core_names[j])
    leaf_keys = _keys(n_core, b"leaf")
    for i, lk in enumerate(leaf_keys):
        q = SCPQuorumSet(threshold=2, validators=[
            lk.public_key, core_keys[i].public_key], innerSets=[])
        leaf = sim.add_node(lk, q)
        sim.connect(leaf.name, core_names[i])
    return sim


def hierarchical(n_branches: int = 3,
                 mode: int = Simulation.OVER_LOOPBACK) -> Simulation:
    """Core-4 top tier + per-branch middle-tier validators whose qsets
    are {self} + an inner 2-of-4 top-tier set (reference
    Topologies::hierarchicalQuorum, "Figure 3 from the paper")."""
    sim = Simulation(mode=mode)
    core_keys = _keys(4, b"hcore")
    core_q = SCPQuorumSet(
        threshold=3, validators=[k.public_key for k in core_keys],
        innerSets=[])
    core_names = [sim.add_node(k, core_q).name for k in core_keys]
    for i in range(4):
        for j in range(i + 1, 4):
            sim.connect(core_names[i], core_names[j])
    top_tier_inner = SCPQuorumSet(
        threshold=2, validators=[k.public_key for k in core_keys],
        innerSets=[])
    mid_keys = _keys(n_branches, b"hmid")
    for b in range(n_branches):
        mk = mid_keys[b]
        q = SCPQuorumSet(threshold=2, validators=[mk.public_key],
                         innerSets=[top_tier_inner])
        node = sim.add_node(mk, q)
        # round-robin connections into the core
        sim.connect(node.name, core_names[b % 4])
        sim.connect(node.name, core_names[(b + 1) % 4])
    return sim


def hierarchical_simplified(core_size: int = 4, n_outer: int = 4,
                            mode: int = Simulation.OVER_LOOPBACK
                            ) -> Simulation:
    """Core + outer validators whose flat qsets are {self + core} at
    Byzantine-safe threshold (reference
    Topologies::hierarchicalQuorumSimplified)."""
    sim = Simulation(mode=mode)
    core_keys = _keys(core_size, b"hsimp")
    core_q = SCPQuorumSet(
        threshold=(core_size * 3 + 3) // 4,
        validators=[k.public_key for k in core_keys], innerSets=[])
    core_names = [sim.add_node(k, core_q).name for k in core_keys]
    for i in range(core_size):
        for j in range(i + 1, core_size):
            sim.connect(core_names[i], core_names[j])
    n = core_size + 1
    outer_keys = _keys(n_outer, b"houter")
    for i in range(n_outer):
        ok = outer_keys[i]
        q = SCPQuorumSet(
            threshold=n - (n - 1) // 3,
            validators=[k.public_key for k in core_keys] + [ok.public_key],
            innerSets=[])
        node = sim.add_node(ok, q)
        sim.connect(node.name, core_names[i % core_size])
        sim.connect(node.name, core_names[(i + 1) % core_size])
    return sim
