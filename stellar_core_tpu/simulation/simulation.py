"""Simulation: N full Application nodes in one process, virtual time.

Role parity: reference `src/simulation/Simulation.{h,cpp}:27-111` — each
node has its own VirtualClock + Application; nodes connect over loopback
pipes (OVER_LOOPBACK) or real TCP (OVER_TCP); tests crank all nodes in
lock-step deterministic time and assert haveAllExternalized.

The loopback transport delivers StellarMessages directly between herders
(message-level loopback); the TCP mode uses the real overlay layer.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..crypto.hashing import sha256
from ..crypto.keys import SecretKey
from ..main.application import Application
from ..main.config import Config
from ..util.log import get_logger
from ..util.timer import ClockMode, VirtualClock
from ..xdr import (
    MessageType, PublicKey, SCPQuorumSet, StellarMessage,
)

log = get_logger("LoadGen")


class LoopbackChannel:
    """Symmetric message pipe between two nodes with optional fault
    injection (reference overlay/test/LoopbackPeer.h:24-94 damage knobs)."""

    def __init__(self, sim: "Simulation", a: str, b: str) -> None:
        self.sim = sim
        self.ends = (a, b)
        self.drop_probability = 0.0
        self.damage_probability = 0.0
        # deterministic geographic one-way delay (virtual seconds on the
        # RECEIVING node's clock) — fed by Simulation.apply_latency_matrix
        self.latency_s = 0.0
        self.enabled = True

    def send(self, from_node: str, msg: StellarMessage) -> None:
        if not self.enabled:
            return
        from ..util import rnd
        if self.drop_probability and \
                rnd.g_random.random() < self.drop_probability:
            return
        raw = msg.to_xdr()
        if self.damage_probability and \
                rnd.g_random.random() < self.damage_probability:
            b = bytearray(raw)
            b[rnd.g_random.randrange(len(b))] ^= 0xFF
            raw = bytes(b)
        to = self.ends[0] if from_node == self.ends[1] else self.ends[1]
        node = self.sim.nodes[to]
        if node.stopped:
            return
        if self.latency_s > 0:
            from ..util.timer import VirtualTimer
            t = VirtualTimer(node.app.clock)
            t.expires_from_now(self.latency_s)
            t.async_wait(lambda: self.sim._deliver(to, from_node, raw))
        else:
            node.app.clock.post(
                lambda: self.sim._deliver(to, from_node, raw))


class SimNode:
    def __init__(self, name: str, app: Application) -> None:
        self.name = name
        self.app = app
        self.channels: List[LoopbackChannel] = []
        self.stopped = False
        # preserved across restarts (restart_node rebuilds the app)
        self.cfg_tweak = None


class Simulation:
    # Message-level loopback: herders wired directly (fastest; default for
    # protocol-focused tests).
    OVER_LOOPBACK = 0
    # Full overlay stack over in-process pipes: real Peer handshake, HMAC,
    # flood, item fetch (reference Simulation OVER_LOOPBACK with
    # LoopbackPeer, simulation/Simulation.h:30-34).
    OVER_PEERS = 1

    def __init__(self, mode: int = OVER_LOOPBACK,
                 network_passphrase: str = "(sct) simulation network"
                 ) -> None:
        self.mode = mode
        self.network_passphrase = network_passphrase
        self.nodes: Dict[str, SimNode] = {}
        self._chaos_links: Dict[tuple, tuple] = {}
        # (a, b, chaos) per connect_peers call — restart_node rewires from
        # this record after the old transports died with the old app
        self._peer_links: List[tuple] = []
        # seeded geographic latency matrix (simulation/geography.py);
        # applied to every existing and future link when set
        self.latency_matrix = None

    # -- topology -----------------------------------------------------------
    def add_node(self, secret: SecretKey, qset: SCPQuorumSet,
                 name: Optional[str] = None,
                 cfg_tweak: Optional[Callable[[Config], None]] = None
                 ) -> SimNode:
        name = name or secret.strkey_public()[:5]
        cfg = Config()
        cfg.NETWORK_PASSPHRASE = self.network_passphrase
        cfg.NODE_SEED = secret
        # sim node name flows into flight-recorder filenames and the
        # fleet aggregator's process lanes
        cfg.NODE_NAME = name
        cfg.NODE_IS_VALIDATOR = True
        cfg.QUORUM_SET = qset
        cfg.UNSAFE_QUORUM = True
        cfg.RUN_STANDALONE = True   # no real overlay sockets
        cfg.FORCE_SCP = True
        cfg.MANUAL_CLOSE = False
        cfg.DATABASE = "in-memory"
        cfg.INVARIANT_CHECKS = [".*"]
        cfg.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING = True
        if cfg_tweak:
            cfg_tweak(cfg)
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        app = Application(clock, cfg)
        node = SimNode(name, app)
        node.cfg_tweak = cfg_tweak
        self.nodes[name] = node
        if self.mode == Simulation.OVER_LOOPBACK:
            self._wire_loopback_shim(node)
        return node

    def _wire_loopback_shim(self, node: SimNode) -> None:
        # message-loopback broadcast shim standing in for OverlayManager;
        # detach the real manager's item fetchers or their trackers
        # would keep re-arming timers against a manager with no peers
        node.app.overlay_manager = _SimOverlayShim(self, node.name)
        node.app.herder.pending.set_fetchers(None, None)

    def connect(self, a: str, b: str):
        if self.mode == Simulation.OVER_PEERS:
            return self.connect_peers(a, b)
        ch = LoopbackChannel(self, a, b)
        if self.latency_matrix is not None:
            self.latency_matrix.ensure(a)
            self.latency_matrix.ensure(b)
            ch.latency_s = self.latency_matrix.latency_s(a, b)
        self.nodes[a].channels.append(ch)
        self.nodes[b].channels.append(ch)
        return ch

    def connect_peers(self, a: str, b: str, chaos: bool = False):
        """Real overlay connection over an in-process pipe: `a` plays the
        initiator (WE_CALLED_REMOTE). With chaos=True each end is wrapped
        in a ChaosTransport driven by its own app's fault injector
        (overlay.drop/delay/duplicate/reorder sites + hard partition),
        registered under `self._chaos_links[(a, b)]`."""
        if (a, b, chaos) not in self._peer_links:
            self._peer_links.append((a, b, chaos))
        return self._wire_peer_link(a, b, chaos)

    def reconnect_peers(self, a: str, b: str, chaos: bool = False):
        """Tear down any stale Peer pair between `a` and `b` and wire a
        fresh link (fresh handshake, fresh MAC chain). A ChaosTransport
        partition eats frames while the per-message HMAC sequence keeps
        advancing on the sender, so a healed link is cryptographically
        dead — exactly like a real partition killing TCP connections.
        Reality redials; simulations reconnect explicitly."""
        app_a = self.nodes[a].app
        app_b = self.nodes[b].app
        for app, other in ((app_a, app_b), (app_b, app_a)):
            om = app.overlay_manager
            peer = om.get_peer(other.config.node_id().to_xdr())
            if peer is not None:
                peer.drop("partition healed: reconnecting")
        return self.connect_peers(a, b, chaos)

    def _wire_peer_link(self, a: str, b: str, chaos: bool):
        from ..overlay.transport import ChaosTransport, LoopbackTransport
        app_a = self.nodes[a].app
        app_b = self.nodes[b].app
        # each end is owned by (and delivers onto the clock of) one app
        ta, tb = LoopbackTransport.pair(app_a.clock, app_b.clock)
        if self.latency_matrix is not None and not chaos:
            # geographic delay needs the ChaosTransport wrapper (it owns
            # the per-frame delay timer); wrap even non-chaos links
            chaos = True
        if chaos:
            ta = ChaosTransport(ta, app_a.clock,
                                faults=getattr(app_a, "faults", None))
            tb = ChaosTransport(tb, app_b.clock,
                                faults=getattr(app_b, "faults", None))
            self._chaos_links[tuple(sorted((a, b)))] = (ta, tb)
            if self.latency_matrix is not None:
                self.latency_matrix.ensure(a)
                self.latency_matrix.ensure(b)
                lat = self.latency_matrix.latency_s(a, b)
                ta.link_delay_s = lat
                tb.link_delay_s = lat
        app_b.overlay_manager.add_loopback_peer(tb, outbound=False,
                                                address=(a, 0))
        app_a.overlay_manager.add_loopback_peer(ta, outbound=True,
                                                address=(b, 0))
        return ta, tb

    # -- geography -----------------------------------------------------------
    def apply_latency_matrix(self, matrix) -> None:
        """Install a seeded per-link latency matrix
        (simulation/geography.LatencyMatrix): every existing link gets
        its deterministic one-way delay now, and links wired later
        (add_late_node, restart_node) inherit theirs on creation."""
        self.latency_matrix = matrix
        for name in self.nodes:
            matrix.ensure(name)
        if self.mode == Simulation.OVER_LOOPBACK:
            seen = set()
            for node in self.nodes.values():
                for ch in node.channels:
                    key = tuple(sorted(ch.ends))
                    if key in seen:
                        continue
                    seen.add(key)
                    ch.latency_s = matrix.latency_s(*ch.ends)
        else:
            for (a, b), pair in self._chaos_links.items():
                lat = matrix.latency_s(a, b)
                for t in pair:
                    t.link_delay_s = lat

    # -- chaos ---------------------------------------------------------------
    def set_partition(self, a: str, b: str, on: bool = True) -> None:
        """Sever (or heal) the a<->b link in either simulation mode — the
        chaos soak's partition-and-heal scenario."""
        if self.mode == Simulation.OVER_PEERS:
            link = self._chaos_links.get(tuple(sorted((a, b))))
            assert link is not None, \
                "partition needs connect_peers(..., chaos=True)"
            for t in link:
                t.set_partitioned(on)
            return
        for ch in self.nodes[a].channels:
            if set(ch.ends) == {a, b}:
                ch.enabled = not on

    def heal_partition(self, a: str, b: str) -> None:
        self.set_partition(a, b, on=False)

    def start_all_nodes(self) -> None:
        for node in self.nodes.values():
            if not node.stopped:
                node.app.start()

    # -- node lifecycle (ISSUE 8) --------------------------------------------
    def stop_node(self, name: str) -> None:
        """Kill one node mid-run: its links go dark, its clock stops, the
        Application shuts down. Persistent state (a file-backed DATABASE /
        BUCKET_DIR_PATH) survives for restart_node; an in-memory node
        restarts from genesis."""
        node = self.nodes[name]
        if node.stopped:
            return
        node.stopped = True
        for ch in node.channels:
            ch.enabled = False
        if self.mode == Simulation.OVER_PEERS:
            # chaos wrappers of dead links must not linger: set_partition
            # after a restart should find the NEW link's wrappers
            for key in [k for k in self._chaos_links if name in k]:
                del self._chaos_links[key]
        node.app.stop()
        node.app.clock.stop()
        log.info("sim node %s stopped at lcl %d", name,
                 node.app.ledger_manager.last_closed_ledger_num())

    def _max_virtual_time(self) -> float:
        return max((n.app.clock.now() for n in self.nodes.values()),
                   default=0.0)

    def restart_node(self, name: str) -> SimNode:
        """Bring a stopped node back: a FRESH Application over the same
        Config (same NODE_SEED, DATABASE, BUCKET_DIR_PATH, HISTORY), a new
        virtual clock fast-forwarded to the fleet's time (the close-time
        drift guard must not reject live values), links rewired. With a
        file-backed DATABASE the node resumes from its persisted LCL and
        rejoins via the Herder's out-of-sync recovery + catchup under
        live traffic."""
        node = self.nodes[name]
        assert node.stopped, "restart_node on a running node"
        cfg = node.app.config
        had_buckets = node.app.bucket_manager is not None
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        clock.set_virtual_time(self._max_virtual_time())
        app = Application(clock, cfg)
        if had_buckets:
            app.enable_buckets()
        node.app = app
        node.stopped = False
        if self.mode == Simulation.OVER_LOOPBACK:
            self._wire_loopback_shim(node)
            for ch in node.channels:
                ch.enabled = True
        else:
            for (a, b, chaos) in self._peer_links:
                if name in (a, b) and not self.nodes[
                        b if a == name else a].stopped:
                    self._wire_peer_link(a, b, chaos)
        app.start()
        log.info("sim node %s restarted at lcl %d (fleet time %.3f)",
                 name, app.ledger_manager.last_closed_ledger_num(),
                 clock.now())
        return node

    def add_late_node(self, secret: SecretKey, qset: SCPQuorumSet,
                      name: Optional[str] = None,
                      cfg_tweak: Optional[Callable[[Config], None]] = None,
                      connect_to: Optional[List[str]] = None) -> SimNode:
        """Join a node to an already-running network: clock fast-forwarded
        to fleet time, linked to `connect_to` (default: every running
        node), started last so its first act is catching up under live
        traffic."""
        node = self.add_node(secret, qset, name=name, cfg_tweak=cfg_tweak)
        node.app.clock.set_virtual_time(self._max_virtual_time())
        if self.latency_matrix is not None:
            self.latency_matrix.ensure(node.name)
        peers = connect_to if connect_to is not None else [
            n for n in self.nodes
            if n != node.name and not self.nodes[n].stopped]
        for other in peers:
            self.connect(node.name, other)
        node.app.start()
        return node

    # -- message routing ----------------------------------------------------
    def broadcast_from(self, name: str, msg: StellarMessage) -> None:
        for ch in self.nodes[name].channels:
            ch.send(name, msg)

    def _deliver(self, to: str, frm: str, raw: bytes) -> None:
        if self.nodes[to].stopped:
            return  # delivery raced a node stop
        try:
            msg = StellarMessage.from_xdr(raw)
        except Exception:
            return  # damaged message dropped at decode
        app = self.nodes[to].app
        t = msg.disc
        if t == MessageType.SCP_MESSAGE:
            env = msg.value
            # deliver txset dependencies on demand via direct lookup
            app.herder.recv_scp_envelope(env)
            self._satisfy_deps(to, frm, env)
            app.overlay_manager.rebroadcast(msg, frm)
        elif t == MessageType.TRANSACTION:
            from ..transactions.transaction_frame import TransactionFrame
            frame = TransactionFrame.make_from_wire(
                app.config.network_id, msg.value)
            app.herder.recv_transaction(frame)
            app.overlay_manager.rebroadcast(msg, frm)
        elif t == MessageType.TX_SET:
            from ..herder.txset import TxSetFrame
            ts = TxSetFrame.from_wire(app.config.network_id, msg.value)
            app.herder.recv_tx_set(ts.get_contents_hash(), ts)
        elif t == MessageType.SCP_QUORUMSET:
            q = msg.value
            app.herder.recv_scp_quorum_set(sha256(q.to_xdr()), q)

    def _satisfy_deps(self, to: str, frm: str, env) -> None:
        """Loopback dependency resolution: pull missing txsets/qsets
        straight from the sending node's herder caches."""
        to_app = self.nodes[to].app
        frm_app = self.nodes[frm].app
        from ..herder.pending_envelopes import (
            statement_qset_hash, statement_txset_hashes,
        )
        st = env.statement
        qh = statement_qset_hash(st)
        if to_app.herder.pending.get_quorum_set(qh) is None:
            q = frm_app.herder.pending.get_quorum_set(qh)
            if q is not None:
                to_app.herder.recv_scp_quorum_set(qh, q)
        for th in statement_txset_hashes(st):
            if to_app.herder.pending.get_tx_set(th) is None:
                ts = frm_app.herder.pending.get_tx_set(th)
                if ts is not None:
                    to_app.herder.recv_tx_set(th, ts)

    # -- cranking -----------------------------------------------------------
    def crank_all_nodes(self, rounds: int = 1) -> int:
        n = 0
        for _ in range(rounds):
            for node in list(self.nodes.values()):
                if not node.stopped:
                    n += node.app.clock.crank(False)
        return n

    def crank_until(self, pred: Callable[[], bool],
                    max_rounds: int = 5000) -> bool:
        for _ in range(max_rounds):
            if pred():
                return True
            if self.crank_all_nodes(1) == 0:
                # idle: advance every clock to its next timer
                pass
        return pred()

    def have_all_externalized(self, seq: int) -> bool:
        """Every RUNNING node has closed >= seq (stopped nodes are by
        definition behind; churn scenarios assert on the survivors, then
        on the restarted node once it heals)."""
        return all(n.app.ledger_manager.last_closed_ledger_num() >= seq
                   for n in self.nodes.values() if not n.stopped)

    # -- fleet observability (util/fleet.py) --------------------------------
    def fleet(self):
        """FleetAggregator over every node: merged Chrome trace (one
        lane per node) + per-slot cross-node stats. In-process nodes
        share one perf_counter, so no rebasing is needed here."""
        from ..util.fleet import FleetAggregator
        agg = FleetAggregator()
        for name, node in self.nodes.items():
            if not node.stopped:
                agg.add_app(name, node.app)
        return agg

    def merged_chrome_trace(self) -> dict:
        return self.fleet().merged_chrome_trace()

    def fleet_stats(self) -> dict:
        return self.fleet().fleet_stats()

    def stop_all_nodes(self) -> None:
        for n in self.nodes.values():
            if not n.stopped:
                n.app.stop()


class _SimOverlayShim:
    """Minimal OverlayManager stand-in for loopback simulations: floods
    with dedup (reference Floodgate role)."""

    def __init__(self, sim: Simulation, name: str) -> None:
        self.sim = sim
        self.name = name
        self._seen: set = set()

    def broadcast_message(self, msg: StellarMessage,
                          force: bool = False) -> None:
        h = sha256(msg.to_xdr())
        if h in self._seen and not force:
            return
        self._seen.add(h)
        self.sim.broadcast_from(self.name, msg)

    def rebroadcast(self, msg: StellarMessage, exclude: str) -> None:
        h = sha256(msg.to_xdr())
        if h in self._seen:
            return
        self._seen.add(h)
        for ch in self.sim.nodes[self.name].channels:
            to = ch.ends[0] if self.name == ch.ends[1] else ch.ends[1]
            if to != exclude:
                ch.send(self.name, msg)

    def start(self) -> None:
        pass

    def shutdown(self) -> None:
        pass
