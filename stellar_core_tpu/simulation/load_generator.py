"""LoadGenerator: synthetic account-creation / payment load.

Role parity: reference `src/simulation/LoadGenerator.{h,cpp}:29-120` —
driven by the HTTP `generateload` admin command; creates accounts then
issues payments at a target rate, injecting through the Herder. This is the
standard flood driver for the TransactionQueue verify path (a TPU batch
measurement config in BASELINE.md).

ISSUE 18 adds the **open-loop mode** the ingress tier's overload story
needs: seeded generation over an arbitrarily large submitter keyspace
(10^6 distinct keys cost nothing — keys derive on demand) with Zipf
hot-key skew and target-rate pacing on the app clock (virtual in
simulations, so a 5x-oversubscribed minute replays deterministically).
Open-loop means the generator never waits for outcomes: it submits at
the target rate regardless, and *counts* the backpressure it receives —
`TRY_AGAIN_LATER` answers land in `backpressured` (with the herder's
retry-after hint recorded) instead of being retried, which is exactly
the submitter behavior an admission tier must survive.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

from ..crypto.hashing import sha256
from ..crypto.keys import SecretKey
from ..testing import TestAccount
from ..util.log import get_logger
from ..util.timer import VirtualTimer

log = get_logger("LoadGen")


class ZipfSampler:
    """Seeded Zipf(s) sampler over [1..n] via Hörmann/Derflinger
    rejection-inversion — O(1) per sample with no precomputed tables,
    so a 10^6-key skew costs a handful of floats (sctlint D2: the RNG
    is the caller's seeded stream)."""

    def __init__(self, n: int, s: float, rng: random.Random) -> None:
        assert n >= 1 and s > 0.0
        self.n = n
        self.s = float(s)
        self.rng = rng
        self._h_x1 = self._h_integral(1.5) - 1.0
        self._h_n = self._h_integral(n + 0.5)
        self._s_const = 2.0 - self._h_integral_inverse(
            self._h_integral(2.5) - self._h(2.0))

    def _h_integral(self, x: float) -> float:
        lg = math.log(x)
        if self.s == 1.0:
            return lg
        return ((math.exp((1.0 - self.s) * lg) - 1.0) / (1.0 - self.s))

    def _h(self, x: float) -> float:
        return math.exp(-self.s * math.log(x))

    def _h_integral_inverse(self, x: float) -> float:
        if self.s == 1.0:
            return math.exp(x)
        t = x * (1.0 - self.s)
        if t < -1.0:
            t = -1.0
        return math.exp(math.log1p(t) / (1.0 - self.s))

    def sample(self) -> int:
        while True:
            u = self._h_n + self.rng.random() * (self._h_x1 - self._h_n)
            x = self._h_integral_inverse(u)
            k = int(x + 0.5)
            if k < 1:
                k = 1
            elif k > self.n:
                k = self.n
            if k - x <= self._s_const or \
                    u >= self._h_integral(k + 0.5) - self._h(k):
                return k


class LoadGenerator:
    def __init__(self, app) -> None:
        self.app = app
        self._accounts: List[SecretKey] = []
        self._timer = VirtualTimer(app.clock)
        self._running = False
        self.submitted = 0
        self.failed = 0
        # open-loop overload mode (ISSUE 18): armed by start_open_loop
        self._ol: Optional[dict] = None
        self._ol_timer: Optional[VirtualTimer] = None

    # -- account book -------------------------------------------------------
    def _account_key(self, i: int) -> SecretKey:
        return SecretKey.from_seed(
            sha256(b"loadgen-%d-" % i + self.app.config.network_id))

    def _adapter(self):
        from ..testing import AppLedgerAdapter
        return AppLedgerAdapter(self.app)

    # -- phases -------------------------------------------------------------
    def generate_accounts(self, n: int,
                          balance: int = 10**9) -> List[SecretKey]:
        """Submit create-account txs from the root (batched 100 ops/tx)."""
        adapter = self._adapter()
        root = adapter.root_account()
        keys = [self._account_key(i) for i in range(n)]
        created = []
        i = 0
        seq = root.next_seq()
        while i < n:
            chunk = keys[i:i + 100]
            ops = [root.op_create_account(k.public_key, balance)
                   for k in chunk]
            frame = root.tx(ops, seq=seq)
            seq += 1
            status = self.app.submit_transaction(frame)
            if status == 0:
                self.submitted += 1
            else:
                self.failed += 1
            created.extend(chunk)
            i += 100
        self._accounts = keys
        return keys

    def generate_payments(self, n_txs: int) -> int:
        """Submit n payment txs round-robin among generated accounts."""
        assert self._accounts, "generate accounts first"
        adapter = self._adapter()
        count = 0
        seqs = {}
        for i in range(n_txs):
            src_k = self._accounts[i % len(self._accounts)]
            dst_k = self._accounts[(i + 1) % len(self._accounts)]
            acc = TestAccount(adapter, src_k)
            seq = seqs.get(src_k.seed)
            if seq is None:
                seq = acc.next_seq()
            frame = acc.tx([acc.op_payment(dst_k.public_key, 1000)],
                           seq=seq)
            seqs[src_k.seed] = seq + 1
            status = self.app.submit_transaction(frame)
            if status == 0:
                self.submitted += 1
                count += 1
            else:
                self.failed += 1
        return count

    # -- open-loop overload mode (ISSUE 18) ---------------------------------
    def _submitter_key(self, i: int) -> SecretKey:
        """The i-th key of the open-loop submitter keyspace; derived on
        demand, so a 10^6-submitter run never materializes the set."""
        return SecretKey.from_seed(
            sha256(b"open-loop-%d-" % i + self.app.config.network_id))

    def _open_loop_frame(self, idx: int, nonce: int):
        """A distinct, cheap-to-build payment from submitter `idx`
        (unsigned: admission-shed txs must cost the ingress tier
        nothing; the no-ingress control leg pays full validation and
        rejects it — exactly the asymmetry the overload scenario
        measures)."""
        from ..transactions.transaction_frame import TransactionFrame
        from ..xdr import (
            Asset, Memo, MuxedAccount, Operation, OperationBody,
            OperationType, PaymentOp, Transaction, TransactionEnvelope,
            _Ext,
        )
        sk = self._submitter_key(idx)
        dst = self._submitter_key(0)
        op = Operation(sourceAccount=None, body=OperationBody(
            OperationType.PAYMENT,
            PaymentOp(destination=MuxedAccount.from_account_id(
                dst.public_key),
                asset=Asset.native(), amount=1 + nonce)))
        t = Transaction(
            sourceAccount=MuxedAccount.from_account_id(sk.public_key),
            fee=100, seqNum=nonce + 1, timeBounds=None, memo=Memo.none(),
            operations=[op], ext=_Ext.v0())
        return TransactionFrame.make_from_wire(
            self.app.config.network_id, TransactionEnvelope.for_tx(t))

    def start_open_loop(self, txs_per_sec: float, duration_s: float,
                        submitters: int = 1_000_000,
                        zipf_s: float = 1.1, seed: int = 0,
                        tick: float = 0.25) -> None:
        """Arm open-loop generation: every `tick` app-clock seconds
        submit `txs_per_sec * tick` txs (fractions carry) from
        Zipf(zipf_s)-skewed submitters out of a `submitters`-key
        keyspace, for `duration_s`. No retries, no waiting — outcomes
        are only counted (see `open_loop_status`)."""
        assert txs_per_sec > 0 and duration_s > 0
        rng = random.Random("open-loop:%d" % seed)
        self._ol = {
            "rate": float(txs_per_sec),
            "deadline": self.app.clock.now() + duration_s,
            "tick": float(tick),
            "carry": 0.0,
            "sampler": ZipfSampler(submitters, zipf_s, rng),
            "nonces": {},     # submitter idx -> submissions so far
            "submitted": 0, "accepted": 0, "backpressured": 0,
            "rejected": 0, "duplicate": 0,
            "last_retry_after": None,
        }
        self._ol_timer = VirtualTimer(self.app.clock)
        self._arm_open_loop_tick()

    def _arm_open_loop_tick(self) -> None:
        self._ol_timer.expires_from_now(self._ol["tick"])
        self._ol_timer.async_wait(self._open_loop_tick)

    def _open_loop_tick(self) -> None:
        ol = self._ol
        if ol is None:
            return
        want = ol["rate"] * ol["tick"] + ol["carry"]
        n = int(want)
        ol["carry"] = want - n
        for _ in range(n):
            idx = ol["sampler"].sample()
            nonce = ol["nonces"].get(idx, 0)
            ol["nonces"][idx] = nonce + 1
            status = self.app.submit_transaction(
                self._open_loop_frame(idx, nonce))
            ol["submitted"] += 1
            self.submitted += 1
            if status == 0:
                ol["accepted"] += 1
            elif status == 3:
                # open-loop: backpressure is COUNTED, never obeyed —
                # the admission tier must hold against exactly this
                ol["backpressured"] += 1
                hint = getattr(self.app.herder, "last_retry_after", None)
                if hint is not None:
                    ol["last_retry_after"] = hint
                self.failed += 1
            elif status == 1:
                ol["duplicate"] += 1
                self.failed += 1
            else:
                ol["rejected"] += 1
                self.failed += 1
        if self.app.clock.now() < ol["deadline"]:
            self._arm_open_loop_tick()

    def stop_open_loop(self) -> None:
        if self._ol_timer is not None:
            self._ol_timer.cancel()
        self._ol = None

    def open_loop_running(self) -> bool:
        return self._ol is not None and \
            self.app.clock.now() < self._ol["deadline"]

    def open_loop_status(self) -> Optional[dict]:
        ol = self._ol
        if ol is None:
            return None
        return {k: ol[k] for k in
                ("submitted", "accepted", "backpressured", "rejected",
                 "duplicate", "last_retry_after")} | {
                    "distinct_submitters": len(ol["nonces"])}

    def status(self) -> dict:
        out = {"accounts": len(self._accounts),
               "submitted": self.submitted, "failed": self.failed}
        ol = self.open_loop_status()
        if ol is not None:
            out["open_loop"] = ol
        return out
