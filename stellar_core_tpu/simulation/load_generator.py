"""LoadGenerator: synthetic account-creation / payment load.

Role parity: reference `src/simulation/LoadGenerator.{h,cpp}:29-120` —
driven by the HTTP `generateload` admin command; creates accounts then
issues payments at a target rate, injecting through the Herder. This is the
standard flood driver for the TransactionQueue verify path (a TPU batch
measurement config in BASELINE.md).
"""

from __future__ import annotations

from typing import List, Optional

from ..crypto.hashing import sha256
from ..crypto.keys import SecretKey
from ..testing import TestAccount
from ..util.log import get_logger
from ..util.timer import VirtualTimer

log = get_logger("LoadGen")


class LoadGenerator:
    def __init__(self, app) -> None:
        self.app = app
        self._accounts: List[SecretKey] = []
        self._timer = VirtualTimer(app.clock)
        self._running = False
        self.submitted = 0
        self.failed = 0

    # -- account book -------------------------------------------------------
    def _account_key(self, i: int) -> SecretKey:
        return SecretKey.from_seed(
            sha256(b"loadgen-%d-" % i + self.app.config.network_id))

    def _adapter(self):
        from ..testing import AppLedgerAdapter
        return AppLedgerAdapter(self.app)

    # -- phases -------------------------------------------------------------
    def generate_accounts(self, n: int,
                          balance: int = 10**9) -> List[SecretKey]:
        """Submit create-account txs from the root (batched 100 ops/tx)."""
        adapter = self._adapter()
        root = adapter.root_account()
        keys = [self._account_key(i) for i in range(n)]
        created = []
        i = 0
        seq = root.next_seq()
        while i < n:
            chunk = keys[i:i + 100]
            ops = [root.op_create_account(k.public_key, balance)
                   for k in chunk]
            frame = root.tx(ops, seq=seq)
            seq += 1
            status = self.app.submit_transaction(frame)
            if status == 0:
                self.submitted += 1
            else:
                self.failed += 1
            created.extend(chunk)
            i += 100
        self._accounts = keys
        return keys

    def generate_payments(self, n_txs: int) -> int:
        """Submit n payment txs round-robin among generated accounts."""
        assert self._accounts, "generate accounts first"
        adapter = self._adapter()
        count = 0
        seqs = {}
        for i in range(n_txs):
            src_k = self._accounts[i % len(self._accounts)]
            dst_k = self._accounts[(i + 1) % len(self._accounts)]
            acc = TestAccount(adapter, src_k)
            seq = seqs.get(src_k.seed)
            if seq is None:
                seq = acc.next_seq()
            frame = acc.tx([acc.op_payment(dst_k.public_key, 1000)],
                           seq=seq)
            seqs[src_k.seed] = seq + 1
            status = self.app.submit_transaction(frame)
            if status == 0:
                self.submitted += 1
                count += 1
            else:
                self.failed += 1
        return count

    def status(self) -> dict:
        return {"accounts": len(self._accounts),
                "submitted": self.submitted, "failed": self.failed}
