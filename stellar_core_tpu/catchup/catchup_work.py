"""CatchupWork: the recovery DAG.

Role parity: reference `src/catchup/CatchupWork.cpp:33-305` —
  GetHistoryArchiveStateWork (archive tip)
  → [bucket mode] GetHistoryArchiveStateWork at the apply checkpoint
  → BatchDownloadWork(ledger headers) + VerifyLedgerChainWork
  → [bucket mode] DownloadBucketsWork → ApplyBucketsWork
  → DownloadApplyTxsWork (download ‖ apply pipeline)
On success the LedgerManager is synced at the target ledger.
"""

from __future__ import annotations

import os
from typing import Optional

from ..history.archive_state import HistoryArchiveState
from ..history.checkpoints import checkpoint_containing
from ..historywork.apply_works import (ApplyBucketsWork,
                                       DownloadApplyTxsWork)
from ..historywork.works import (BatchDownloadWork, DownloadBucketsWork,
                                 GetHistoryArchiveStateWork,
                                 VerifyLedgerChainWork)
from ..util.log import get_logger
from ..util.tmpdir import TmpDir
from ..util.xdrstream import XDRInputFileStream
from ..work.basic_work import (FAILURE, RETRY_NEVER, RUNNING, SUCCESS,
                               WAITING, BasicWork, State)
from ..xdr import LedgerHeaderHistoryEntry
from .range import CatchupConfiguration, CatchupRange, \
    calculate_catchup_range

log = get_logger("History")


class CatchupWork(BasicWork):
    """Phased orchestrator; each phase adds child works and waits for
    them (reference CatchupWork's WorkSequence of the same steps)."""

    GET_HAS, GET_APPLY_HAS, DOWNLOAD_VERIFY, BUCKETS, APPLY_TXS, DONE = \
        range(6)

    def __init__(self, app, config: Optional[CatchupConfiguration] = None,
                 archive=None,
                 trusted_hash: Optional[tuple] = None) -> None:
        super().__init__(app.clock, "catchup", RETRY_NEVER)
        self.app = app
        self.config = config or CatchupConfiguration.complete()
        # default to the health-scored failover pool over every readable
        # archive; an explicit single archive (tests, CLI) still works
        self.archive = archive or app.history_manager.readable_pool()
        self.trusted_hash = trusted_hash     # optional (seq, hash) pin
        self.download_dir = TmpDir("catchup")
        self._phase = self.GET_HAS
        self._child: Optional[BasicWork] = None
        self._children: list = []
        self.remote_has: Optional[HistoryArchiveState] = None
        self.apply_has: Optional[HistoryArchiveState] = None
        self.range: Optional[CatchupRange] = None

    # -- child plumbing ------------------------------------------------------
    def _run_children(self) -> Optional[State]:
        """Crank children; None while still running, else aggregate."""
        for c in self._children:
            if c.state == State.PENDING:
                c._parent = self
                c.start()
        for c in self._children:
            if c.is_crankable():
                c.crank_work()
        if any(c.state in (State.FAILURE, State.ABORTED)
               for c in self._children):
            return FAILURE
        if all(c.is_done() for c in self._children):
            return SUCCESS
        return None

    # -- phases --------------------------------------------------------------
    def on_run(self) -> State:
        if self.archive is None:
            log.warning("catchup: no readable history archive")
            return FAILURE
        if self._children:
            st = self._run_children()
            if st is None:
                # park when every child is blocked (WAITING on a
                # subprocess or RETRYING on a backoff timer); the child
                # wake chain re-arms this work
                if any(c.is_crankable() for c in self._children):
                    return RUNNING
                return WAITING
            self._children = []
            if st == FAILURE:
                return FAILURE
            return self._advance()
        return self._enter_phase()

    def _advance(self) -> State:
        """Called when the current phase's children all succeeded."""
        if self._phase == self.GET_HAS:
            self.remote_has = self._get_has.has
            cfg = self.config.resolve(self.remote_has.current_ledger)
            lcl = self.app.ledger_manager.last_closed_ledger_num()
            if cfg.to_ledger <= lcl:
                log.info("catchup: already at %d >= target %d", lcl,
                         cfg.to_ledger)
                self._phase = self.DONE
                return SUCCESS
            self.range = calculate_catchup_range(
                lcl, cfg, self.app.config.CHECKPOINT_FREQUENCY)
            log.info("catchup plan: %r (lcl %d)", self.range, lcl)
            self._phase = (self.GET_APPLY_HAS if self.range.apply_buckets
                           else self.DOWNLOAD_VERIFY)
        elif self._phase == self.GET_APPLY_HAS:
            self.apply_has = self._get_apply_has.has
            self._phase = self.DOWNLOAD_VERIFY
        elif self._phase == self.DOWNLOAD_VERIFY:
            self._phase = (self.BUCKETS if self.range.apply_buckets
                           else self.APPLY_TXS)
        elif self._phase == self.BUCKETS:
            self._phase = self.APPLY_TXS
        elif self._phase == self.APPLY_TXS:
            self._phase = self.DONE
            return self._finish_catchup()
        return self._enter_phase()

    def _enter_phase(self) -> State:
        ph = self._phase
        if ph == self.DONE:
            return self._finish_catchup()
        if ph == self.GET_HAS:
            self._get_has = GetHistoryArchiveStateWork(
                self.app, self.archive, self.download_dir.path)
            self._children = [self._get_has]
        elif ph == self.GET_APPLY_HAS:
            self._get_apply_has = GetHistoryArchiveStateWork(
                self.app, self.archive, self.download_dir.path,
                checkpoint=self.range.apply_buckets_at)
            self._children = [self._get_apply_has]
        elif ph == self.DOWNLOAD_VERIFY:
            lm = self.app.ledger_manager
            # headers from the bucket-apply checkpoint (or LCL+1) to target
            lo = (self.range.apply_buckets_at if self.range.apply_buckets
                  else self.range.replay_first)
            hi = self.range.replay_last
            dl = BatchDownloadWork(self.app, self.archive, "ledger", lo,
                                   max(hi, lo), self.download_dir.path)
            genesis_link = None
            if not self.range.apply_buckets:
                genesis_link = (lm.last_closed_ledger_num(), lm.lcl_hash)
            self._verify = VerifyLedgerChainWork(
                self.app, self.download_dir.path, lo, max(hi, lo),
                trusted=self.trusted_hash, local_genesis=genesis_link)
            # verify strictly after download (chain needs all files)
            from ..work.work import WorkSequence
            self._children = [WorkSequence(
                self.clock, "download+verify-ledgers",
                [dl, self._verify], max_retries=0)]
        elif ph == self.BUCKETS:
            self._children = [self._make_bucket_works()]
            if self._children == [None]:
                return FAILURE
        elif ph == self.APPLY_TXS:
            if self.range.replay_count() == 0:
                self._phase = self.DONE
                return self._finish_catchup()
            self._children = [DownloadApplyTxsWork(
                self.app, self.archive, self.download_dir.path,
                self.range.replay_first, self.range.replay_last)]
        return RUNNING

    def _make_bucket_works(self):
        from ..work.work import WorkSequence
        c = self.range.apply_buckets_at
        entry = self._header_entry_at(c)
        if entry is None:
            log.warning("catchup: no downloaded header for checkpoint %d",
                        c)
            return None
        dl = DownloadBucketsWork(self.app, self.archive,
                                 self.apply_has.bucket_hashes(),
                                 self.download_dir.path)
        ap = ApplyBucketsWork(self.app, self.apply_has, entry)
        return WorkSequence(self.clock, "download+apply-buckets", [dl, ap],
                            max_retries=0)

    def _header_entry_at(self, seq: int):
        path = os.path.join(self.download_dir.path,
                            "ledger-%08x.xdr"
                            % checkpoint_containing(
                                seq, self.app.config.CHECKPOINT_FREQUENCY))
        if not os.path.exists(path):
            return None
        with XDRInputFileStream(path) as ins:
            for e in ins.read_all(LedgerHeaderHistoryEntry):
                if e.header.ledgerSeq == seq:
                    return e
        return None

    def _finish_catchup(self) -> State:
        from ..ledger.ledger_manager import LedgerManagerState
        lm = self.app.ledger_manager
        lm.state = LedgerManagerState.LM_SYNCED_STATE
        log.info("catchup complete at ledger %d",
                 lm.last_closed_ledger_num())
        return SUCCESS

    def _finish(self, st: State) -> None:
        self.download_dir.remove()   # no temp-dir leak across attempts
        super()._finish(st)
