"""Catchup range arithmetic.

Role parity: reference `src/catchup/CatchupConfiguration.{h,cpp}` and
`src/catchup/CatchupRange.{h,cpp}` — given (lcl, target ledger, count),
decide whether to fast-forward state by applying a bucket snapshot at a
checkpoint boundary and how many ledgers to replay after it.

Modes (reference CommandLine catchup `<to>/<count>` syntax):
  count >= target  → CATCHUP_COMPLETE: replay everything from the LCL.
  count == 0       → CATCHUP_MINIMAL: buckets at the newest possible
                      checkpoint, replay only the tail.
  else             → CATCHUP_RECENT: buckets then replay `count` ledgers.
"""

from __future__ import annotations

from ..history.checkpoints import (DEFAULT_FREQUENCY, checkpoint_containing,
                                   is_last_in_checkpoint)

CURRENT = 0xFFFFFFFF  # "catch up to the archive tip" sentinel


class CatchupConfiguration:
    def __init__(self, to_ledger: int = CURRENT, count: int = CURRENT
                 ) -> None:
        self.to_ledger = to_ledger
        self.count = count

    @classmethod
    def complete(cls) -> "CatchupConfiguration":
        return cls(CURRENT, CURRENT)

    @classmethod
    def minimal(cls) -> "CatchupConfiguration":
        return cls(CURRENT, 0)

    @classmethod
    def recent(cls, count: int) -> "CatchupConfiguration":
        return cls(CURRENT, count)

    def resolve(self, archive_tip: int) -> "CatchupConfiguration":
        to = archive_tip if self.to_ledger == CURRENT else self.to_ledger
        return CatchupConfiguration(to, self.count)


class CatchupRange:
    """The resolved plan: optionally apply buckets at `apply_buckets_at`
    (a checkpoint ledger), then replay [replay_first..replay_last]."""

    def __init__(self, apply_buckets: bool, apply_buckets_at: int,
                 replay_first: int, replay_last: int) -> None:
        self.apply_buckets = apply_buckets
        self.apply_buckets_at = apply_buckets_at
        self.replay_first = replay_first
        self.replay_last = replay_last

    def replay_count(self) -> int:
        if self.replay_first > self.replay_last:
            return 0
        return self.replay_last - self.replay_first + 1

    def __repr__(self) -> str:
        return ("CatchupRange(buckets@%s, replay %d..%d)"
                % (self.apply_buckets_at if self.apply_buckets else "-",
                   self.replay_first, self.replay_last))


def calculate_catchup_range(lcl: int, cfg: CatchupConfiguration,
                            freq: int = DEFAULT_FREQUENCY) -> CatchupRange:
    """Reference `CatchupRange::CatchupRange` (CatchupRange.cpp): prefer
    pure replay when the LCL is close enough (or count covers the gap);
    otherwise bucket-apply at the newest checkpoint that still leaves
    >= count ledgers to replay."""
    target = cfg.to_ledger
    assert target > lcl, "nothing to catch up (target %d <= lcl %d)" \
        % (target, lcl)
    gap = target - lcl
    if cfg.count >= gap:
        return CatchupRange(False, 0, lcl + 1, target)

    # earliest ledger we are obliged to replay
    first_replay = target - cfg.count + 1 if cfg.count > 0 else target + 1
    # bucket-apply point: a checkpoint ledger strictly before first_replay,
    # as late as possible
    c = checkpoint_containing(first_replay - 1, freq)
    if c >= first_replay:
        c -= freq
    if c <= lcl:
        # LCL already past every usable checkpoint: pure replay
        return CatchupRange(False, 0, lcl + 1, target)
    assert is_last_in_checkpoint(c, freq)
    return CatchupRange(True, c, c + 1, target)
