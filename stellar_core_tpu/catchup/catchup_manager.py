"""CatchupManager: online recovery — buffer externalized ledgers while a
CatchupWork heals the gap, then drain the buffer.

Role parity: reference `src/catchup/CatchupManagerImpl.cpp:79-140`
(`processLedger` buffers `LedgerCloseData` keyed by seq, trims below the
LCL, starts catchup at checkpoint boundaries) and
`CatchupWork.cpp:296-305` (`ApplyBufferedLedgersWork` drains the buffer
after the work DAG completes).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..util.log import get_logger
from .catchup_work import CatchupWork
from .range import CatchupConfiguration

log = get_logger("History")


class CatchupManager:
    def __init__(self, app) -> None:
        self.app = app
        self._buffered: Dict[int, object] = {}   # seq -> LedgerCloseData
        self._work: Optional[CatchupWork] = None
        self.catchups_started = 0
        self.catchups_succeeded = 0
        self.catchups_failed = 0
        # wire the gap trigger
        app.ledger_manager.catchup_trigger = self.process_ledger

    # -- externalized-value entry point (reference processLedger) ------------
    def process_ledger(self, lcd) -> None:
        from ..ledger.ledger_manager import LedgerManagerState
        lm = self.app.ledger_manager
        lcl = lm.last_closed_ledger_num()
        if lcd.ledger_seq <= lcl:
            return
        if lcd.ledger_seq == lcl + 1 and not self.catchup_running() \
                and not getattr(lm, "entries_invalidated", False):
            # contiguous and no work in flight: close directly, even while
            # nominally catching up (reference CatchupManagerImpl closes
            # the next ledger and exits catchup when the buffer drains) —
            # this also keeps archive-less nodes alive
            if self._close_one(lcd) and self._drain_buffer() \
                    and not self._buffered:
                lm.state = LedgerManagerState.LM_SYNCED_STATE
            self._update_catchup_status()
            return
        self._buffered[lcd.ledger_seq] = lcd
        self._trim_buffer()
        if self._work is None or self._work.is_done():
            self.start_catchup()
        self._update_catchup_status()

    def _update_catchup_status(self) -> None:
        """Rolled-up catchup progress line (reference CatchupManagerImpl::
        logAndUpdateCatchupStatus:180-206)."""
        from ..util.status_manager import StatusCategory
        sm = getattr(self.app, "status_manager", None)
        if sm is None:
            return
        if self.catchup_running() or self._buffered:
            lcl = self.app.ledger_manager.last_closed_ledger_num()
            sm.set_status_message(
                StatusCategory.HISTORY_CATCHUP,
                "Catching up from ledger %d: buffered %d externalized "
                "ledgers" % (lcl, len(self._buffered)))
        else:
            sm.remove_status_message(StatusCategory.HISTORY_CATCHUP)

    def buffered_count(self) -> int:
        return len(self._buffered)

    def max_buffered_seq(self) -> Optional[int]:
        """Highest externalized ledger buffered — one of the recovery
        path's network-tracked-slot signals (Herder.network_tracked_slot)."""
        return max(self._buffered) if self._buffered else None

    def catchup_running(self) -> bool:
        return self._work is not None and not self._work.is_done()

    # -- catchup lifecycle ---------------------------------------------------
    def start_catchup(self,
                      config: Optional[CatchupConfiguration] = None,
                      on_done=None) -> Optional[CatchupWork]:
        hm = getattr(self.app, "history_manager", None)
        if hm is None or hm.readable_archive() is None:
            log.warning("catchup needed but no readable archive configured")
            return None
        if config is None:
            cfg = self.app.config
            if cfg.CATCHUP_COMPLETE:
                config = CatchupConfiguration.complete()
            elif cfg.CATCHUP_RECENT > 0:
                config = CatchupConfiguration.recent(cfg.CATCHUP_RECENT)
            else:
                config = CatchupConfiguration.minimal()
        self.catchups_started += 1
        trusted = self._consensus_anchor()
        self._work = CatchupWork(self.app, config, trusted_hash=trusted)

        def done(state) -> None:
            from ..work.basic_work import State
            if state == State.SUCCESS:
                self.catchups_succeeded += 1
                ok = self._drain_buffer()
                self._check_gap_closed(drained_ok=ok)
            else:
                self.catchups_failed += 1
                log.warning("catchup failed; will retry on next gap")
            self._update_catchup_status()
            if on_done is not None:
                on_done(state)

        self.app.work_scheduler.schedule_work(self._work, done)
        return self._work

    def _consensus_anchor(self):
        """The oldest buffered externalized value pins the archive chain:
        its txset's previousLedgerHash IS the consensus hash of ledger
        seq-1, so a forged archive cannot graft a fake chain under real
        SCP traffic (reference anchors catchup at the trigger ledger's
        consensus hash)."""
        if not self._buffered:
            return None
        seq = min(self._buffered)
        lcd = self._buffered[seq]
        prev = getattr(lcd.tx_set, "previous_ledger_hash", None)
        return (seq - 1, prev) if prev is not None else None

    # -- buffered-ledger drain (reference ApplyBufferedLedgersWork) ----------
    def _close_one(self, lcd) -> bool:
        """Close one ledger; on failure log loudly, stay catching-up, and
        never let the exception kill the caller's crank loop (reference:
        prevHash divergence is fatal-loud, LedgerManagerImpl.cpp:463-468)."""
        from ..ledger.ledger_manager import LedgerManagerState
        lm = self.app.ledger_manager
        try:
            lm.close_ledger(lcd)
            return True
        except Exception as e:
            log.error("ledger %d failed to close: %s — discarding and "
                      "staying in catchup", lcd.ledger_seq, e)
            lm.state = LedgerManagerState.LM_CATCHING_UP_STATE
            return False

    def _drain_buffer(self) -> bool:
        """Apply contiguous buffered ledgers; False if a close failed."""
        lm = self.app.ledger_manager
        self._trim_buffer()
        while True:
            nxt = lm.last_closed_ledger_num() + 1
            lcd = self._buffered.pop(nxt, None)
            if lcd is None:
                return True
            if not self._close_one(lcd):
                return False

    def _trim_buffer(self) -> None:
        lcl = self.app.ledger_manager.last_closed_ledger_num()
        for seq in [s for s in self._buffered if s <= lcl]:
            del self._buffered[seq]
        # bound the buffer: keep only the newest window (older ledgers are
        # in — or will be in — the archive; reference keeps a bounded
        # buffered-ledger window)
        cap = max(4 * self.app.config.CHECKPOINT_FREQUENCY, 128)
        if len(self._buffered) > cap:
            for seq in sorted(self._buffered)[:len(self._buffered) - cap]:
                del self._buffered[seq]

    def _check_gap_closed(self, drained_ok: bool = True) -> bool:
        """After a catchup + drain: if buffered ledgers remain beyond a
        hole, go around again (reference: catchup restarts until the node
        reconnects with the live stream)."""
        from ..ledger.ledger_manager import LedgerManagerState
        lm = self.app.ledger_manager
        if not drained_ok:
            return False
        if self._buffered:
            # a hole below min(buffered) isn't in the archive yet; stay in
            # catching-up state — the next externalized ledger re-triggers
            # catchup once the archive has published past the hole
            log.info("gap remains after catchup (lcl %d, %d buffered)",
                     lm.last_closed_ledger_num(), len(self._buffered))
            lm.state = LedgerManagerState.LM_CATCHING_UP_STATE
            return False
        lm.state = LedgerManagerState.LM_SYNCED_STATE
        return True
