"""Catchup: archive-driven recovery (reference `src/catchup`)."""

from .catchup_manager import CatchupManager
from .catchup_work import CatchupWork
from .range import (CURRENT, CatchupConfiguration, CatchupRange,
                    calculate_catchup_range)

__all__ = [
    "CURRENT", "CatchupConfiguration", "CatchupManager", "CatchupRange",
    "CatchupWork", "calculate_catchup_range",
]
