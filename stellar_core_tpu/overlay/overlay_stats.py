"""OverlayStats: the wire cockpit's shared aggregation (ISSUE 10
tentpole; docs/observability.md#overlay-cockpit).

One instance per OverlayManager, shared by every layer that touches the
wire — `Peer` (per-message-type byte accounting on both directions,
duplicate-frame detection), `Floodgate` (flood dedup: unique vs
duplicate receipts, broadcast fanout), the Herder's envelope intake
(receive → signature-verify → herder-process pipeline latency, tagged
with the verify backend) and the overlay tick (send-queue depth). The
same aggregate objects feed four consumers:

- the admin `overlaystats` endpoint (`to_json`, `?action=reset`);
- the metrics registry (`overlay.*` names), which makes the whole
  cockpit scrapeable as `sct_overlay_*` via `metrics?format=prometheus`;
- the tracer: `overlay.envelope.pipeline` instants carry per-envelope
  verify/process latency + backend into Chrome traces and flight dumps;
- the fleet view: `fleet_json()` is the compact per-node export the
  FleetAggregator merges into per-slot fleet bandwidth totals and the
  `overlay_breakdown` block `bench.py --fleet` / `--scenario` emit
  (normalized by tools/bench_compare.py into direction-aware records —
  `flood_duplication_ratio` is the O(n²) waste ROADMAP item 3 wants to
  shrink, measured before the BLS aggregate-signature variant can be
  judged).

Clocks: every stamp and rate reads the injected app clock (`now_fn` =
clock.now via OverlayManager), so chaos soaks under a virtual clock
stay deterministic — there are no wall-clock reads here (sctlint D1).
Recording happens on the main loop only (the overlay delivers frames
via post_to_main); the lock still guards the aggregates because the
admin HTTP thread snapshots them via handle_command hops and direct
test access.

Duplication ratio: `duplicates / unique`, where a duplicate is either
a flooded message the Floodgate had already recorded (the flood-layer
O(n²) waste) or a verified duplicate FRAME delivered by the transport
(ChaosTransport `overlay.duplicate` injection; `Peer` detects these at
the MAC layer instead of dropping the link).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..util.metrics import MetricsRegistry
from ..util.threads import TrackedLock
from ..util.timer import real_monotonic
from ..xdr import MessageType

# MessageType value -> kebab-case metric segment ("scp-message").
# Bounded: the dynamic `overlay.recv.<type>.*` / `overlay.send.<type>.*`
# name space can never exceed the wire message types (+ "malformed").
MSG_TYPE_NAMES: Dict[int, str] = {
    v: k.lower().replace("_", "-")
    for k, v in vars(MessageType).items()
    if isinstance(v, int) and not k.startswith("_") and k.isupper()
}


def msg_type_name(msg_type) -> str:
    if msg_type is None:
        return "malformed"
    return MSG_TYPE_NAMES.get(msg_type, "unknown-%d" % msg_type)


def _new_dir_totals() -> dict:
    return {"recv_bytes": 0, "recv_msgs": 0, "send_bytes": 0,
            "send_msgs": 0}


class OverlayStats:
    """Wire-cockpit aggregation; see module docstring."""

    TOP_K = 8            # peers shown in the admin blob
    MAX_PEERS = 256      # per-peer attribution entries retained
    SLOT_WINDOW = 64     # per-slot bandwidth deltas retained

    def __init__(self, metrics=None, tracer=None, now_fn=None) -> None:
        self._now = now_fn or real_monotonic
        # a private registry when none is injected keeps direct
        # constructions (tests, harnesses) app-registry-free while
        # letting every registration below use the new_* idiom the M1
        # metric-catalog scanner keys on
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.tracer = tracer
        self._lock = TrackedLock("overlay.overlay-stats")
        # fixed-name registry metrics, created eagerly so the Prometheus
        # export carries the full cockpit shape from the first scrape
        m = self.metrics
        self._m_funique = m.new_meter("overlay.flood.unique")
        self._m_fdup = m.new_meter("overlay.flood.duplicate")
        self._h_fanout = m.new_histogram("overlay.flood.fanout")
        self._m_dupframe = m.new_meter("overlay.recv.duplicate-frame")
        self._g_queue = m.new_gauge("overlay.send-queue.depth")
        self._g_queue_peers = m.new_gauge("overlay.send-queue.backlogged")
        self._t_verify = m.new_timer("overlay.envelope.verify-latency")
        self._t_process = m.new_timer("overlay.envelope.process-latency")
        self._m_erejected = m.new_meter("overlay.envelope.rejected")
        # per-message-type / per-backend metrics, resolved once — the
        # frame hot path must not pay a name format + registry lookup
        # per message (both name spaces are small and bounded)
        self._m_type: Dict[tuple, tuple] = {}
        self._t_backend: Dict[str, object] = {}
        self.reset()

    # -- lifecycle -----------------------------------------------------------
    def reset(self) -> None:
        """Zero the cumulative aggregates (admin
        `overlaystats?action=reset`; registry metrics keep their
        monotonic histories — Prometheus counters must never go
        backwards)."""
        with self._lock:
            self.totals = _new_dir_totals()
            self.by_type: Dict[str, dict] = {}
            self.peers: Dict[str, dict] = {}
            self.flood = {"unique": 0, "duplicates": 0, "broadcasts": 0,
                          "fanout_total": 0}
            self.envelope = {"count": 0, "rejected": 0,
                             "verify_seconds": 0.0, "process_seconds": 0.0,
                             "by_backend": {}}
            self.queue = {"bytes": 0, "backlogged": 0}
            self.per_slot: Dict[int, dict] = {}
            self._slot_base = _new_dir_totals()

    # -- per-message accounting ----------------------------------------------
    def _type_metrics(self, direction: str, name: str) -> tuple:
        key = (direction, name)
        mt = self._m_type.get(key)
        if mt is None:
            if direction == "recv":
                mt = (self.metrics.new_meter(
                          "overlay.recv.%s.count" % name),
                      self.metrics.new_histogram(
                          "overlay.recv.%s.bytes" % name))
            else:
                mt = (self.metrics.new_meter(
                          "overlay.send.%s.count" % name),
                      self.metrics.new_histogram(
                          "overlay.send.%s.bytes" % name))
            self._m_type[key] = mt
        return mt

    def _record_msg(self, direction: str, msg_type, nbytes: int,
                    peer_key: Optional[bytes]) -> None:
        name = msg_type_name(msg_type)
        meter, hist = self._type_metrics(direction, name)
        meter.mark()
        hist.update(nbytes)
        bkey = direction + "_bytes"
        mkey = direction + "_msgs"
        with self._lock:
            self.totals[bkey] += nbytes
            self.totals[mkey] += 1
            t = self.by_type.setdefault(name, _new_dir_totals())
            t[bkey] += nbytes
            t[mkey] += 1
            if peer_key is not None:
                pid = peer_key.hex()[:16]
                p = self.peers.get(pid)
                if p is None:
                    if len(self.peers) >= self.MAX_PEERS:
                        return   # bounded: new peers beyond the cap are
                        # not individually attributed (totals still count)
                    p = self.peers[pid] = _new_dir_totals()
                p[bkey] += nbytes
                p[mkey] += 1

    def record_recv(self, msg_type, nbytes: int,
                    peer_key: Optional[bytes] = None) -> None:
        """One inbound frame of `msg_type` (None = unparseable)."""
        self._record_msg("recv", msg_type, nbytes, peer_key)

    def record_send(self, msg_type, nbytes: int,
                    peer_key: Optional[bytes] = None) -> None:
        self._record_msg("send", msg_type, nbytes, peer_key)

    def record_duplicate_frame(self, msg_type, flooded: bool) -> None:
        """A transport-level duplicate frame detected at the MAC layer
        (ChaosTransport `overlay.duplicate` injection, or a genuinely
        duplicating network). Flooded types additionally count into the
        flood duplication ratio — injected duplicates must show up in
        the same waste number operators watch."""
        self._m_dupframe.mark()
        if flooded:
            self._m_fdup.mark()
            with self._lock:
                self.flood["duplicates"] += 1

    # -- flood dedup accounting (Floodgate hooks) ----------------------------
    def record_flood(self, unique: bool) -> None:
        """One flooded message through Floodgate.add_record: unique
        (first sight) or a duplicate receipt from another peer."""
        if unique:
            self._m_funique.mark()
        else:
            self._m_fdup.mark()
        with self._lock:
            self.flood["unique" if unique else "duplicates"] += 1

    def record_broadcast(self, fanout: int) -> None:
        """One Floodgate.broadcast: `fanout` peers actually sent to."""
        self._h_fanout.update(fanout)
        with self._lock:
            self.flood["broadcasts"] += 1
            self.flood["fanout_total"] += fanout

    def _duplication_ratio_locked(self) -> float:
        u = self.flood["unique"]
        return self.flood["duplicates"] / u if u else 0.0

    # -- envelope pipeline (Herder hook) -------------------------------------
    def record_envelope(self, verify_s: float, process_s: float,
                        backend: str, ok: bool) -> None:
        """One SCP envelope through the intake pipeline: receive →
        signature-verify (`verify_s`, app-clock) → herder process
        (`process_s`), attributed to the verify backend that served the
        stack (bounded backend name space)."""
        self._t_verify.update(verify_s)
        self._t_process.update(process_s)
        if not ok:
            self._m_erejected.mark()
        t = self._t_backend.get(backend)
        if t is None:
            t = self.metrics.new_timer(
                "overlay.envelope.verify-latency.%s" % backend)
            self._t_backend[backend] = t
        t.update(verify_s)
        with self._lock:
            e = self.envelope
            e["count"] += 1
            e["rejected"] += int(not ok)
            e["verify_seconds"] += verify_s
            e["process_seconds"] += process_s
            b = e["by_backend"].setdefault(
                backend, {"count": 0, "verify_seconds": 0.0})
            b["count"] += 1
            b["verify_seconds"] += verify_s
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.instant(
                "overlay.envelope.pipeline", cat="overlay",
                backend=backend, ok=ok,
                verify_s=round(verify_s, 6),
                process_s=round(process_s, 6))

    # -- send-queue pressure (overlay tick hook) -----------------------------
    def set_queue_depth(self, total_bytes: int, backlogged: int) -> None:
        self._g_queue.set(total_bytes)
        self._g_queue_peers.set(backlogged)
        with self._lock:
            self.queue["bytes"] = total_bytes
            self.queue["backlogged"] = backlogged

    # -- per-slot bandwidth (ledger_closed hook) -----------------------------
    def slot_closed(self, ledger_seq: int) -> None:
        """Attribute the bytes moved since the previous close to this
        slot — the per-slot fleet bandwidth series the FleetAggregator
        sums across nodes (bounded ring of SLOT_WINDOW slots)."""
        with self._lock:
            delta = {k: self.totals[k] - self._slot_base[k]
                     for k in self.totals}
            self._slot_base = dict(self.totals)
            self.per_slot[ledger_seq] = delta
            while len(self.per_slot) > self.SLOT_WINDOW:
                del self.per_slot[min(self.per_slot)]

    # -- exports -------------------------------------------------------------
    def _top_peers_locked(self) -> list:
        ranked = sorted(
            self.peers.items(),
            key=lambda kv: -(kv[1]["recv_bytes"] + kv[1]["send_bytes"]))
        return [{"peer": pid, **dict(t)} for pid, t in ranked[:self.TOP_K]]

    def to_json(self) -> dict:
        """The admin `overlaystats` cockpit blob (overlay half)."""
        verify = self._t_verify.snapshot()
        process = self._t_process.snapshot()
        with self._lock:
            return {
                "totals": dict(self.totals),
                "by_type": {n: dict(t)
                            for n, t in sorted(self.by_type.items())},
                "peers": {"tracked": len(self.peers),
                          "top": self._top_peers_locked()},
                "flood": {
                    "unique": self.flood["unique"],
                    "duplicates": self.flood["duplicates"],
                    "duplication_ratio": round(
                        self._duplication_ratio_locked(), 4),
                    "broadcasts": self.flood["broadcasts"],
                    "fanout_total": self.flood["fanout_total"],
                },
                "envelope": {
                    **{k: (round(v, 6) if isinstance(v, float) else v)
                       for k, v in self.envelope.items()
                       if k != "by_backend"},
                    "by_backend": {
                        n: {"count": b["count"],
                            "verify_seconds":
                                round(b["verify_seconds"], 6)}
                        for n, b in sorted(
                            self.envelope["by_backend"].items())},
                    "verify_p95_ms": round(verify["p95"] * 1e3, 3),
                    "process_p95_ms": round(process["p95"] * 1e3, 3),
                },
                "send_queue": dict(self.queue),
                "per_slot": {str(s): dict(d) for s, d in
                             sorted(self.per_slot.items())},
            }

    def fleet_json(self) -> dict:
        """Compact per-node export for the FleetAggregator (one shape
        for in-process `add_app` and HTTP `add_http` intake)."""
        with self._lock:
            return {
                "totals": dict(self.totals),
                "flood": {"unique": self.flood["unique"],
                          "duplicates": self.flood["duplicates"]},
                "per_slot": {str(s): dict(d) for s, d in
                             sorted(self.per_slot.items())},
            }
