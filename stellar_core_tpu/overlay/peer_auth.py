"""PeerAuth: per-connection identity certs and session MAC keys.

Role parity: reference `src/overlay/PeerAuth.{h,cpp}` — each node keeps one
X25519 ECDH keypair; its public half is published in an AuthCert signed by
the node's ed25519 identity key, valid one hour, reissued after half an
hour (PeerAuth.cpp:19-54). Session MAC keys come from ECDH → HKDF-extract,
then HKDF-expand over a direction byte and both handshake nonces
(PeerAuth.cpp:92-135), giving distinct sending/receiving keys per
direction.
"""

from __future__ import annotations

import struct
from typing import Tuple

from ..crypto.curve25519 import (
    curve25519_derive_public, curve25519_derive_shared,
    curve25519_random_secret, hkdf_expand_key,
)
from ..crypto.hashing import sha256
from ..crypto.keys import PubKeyUtils
from ..util.cache import RandomEvictionCache
from ..xdr import AuthCert, EnvelopeType, PublicKey

CERT_EXPIRATION_SECONDS = 3600


def _cert_sign_bytes(network_id: bytes, expiration: int,
                     pubkey32: bytes) -> bytes:
    """xdr(networkID ‖ ENVELOPE_TYPE_AUTH ‖ expiration ‖ cert.pubkey)
    (reference PeerAuth.cpp:29-31)."""
    return (network_id +
            struct.pack(">i", EnvelopeType.ENVELOPE_TYPE_AUTH) +
            struct.pack(">Q", expiration) + pubkey32)


class PeerRole:
    WE_CALLED_REMOTE = 0
    REMOTE_CALLED_US = 1


class PeerAuth:
    def __init__(self, app) -> None:
        self.app = app
        self._secret = curve25519_random_secret()
        self.public = curve25519_derive_public(self._secret)
        self._cert: AuthCert = self._make_cert()
        self._shared_cache = RandomEvictionCache(0xFFFF)

    def _make_cert(self) -> AuthCert:
        expiration = self.app.clock.system_now() + CERT_EXPIRATION_SECONDS
        h = sha256(_cert_sign_bytes(self.app.config.network_id, expiration,
                                    self.public))
        sig = self.app.config.NODE_SEED.sign(h)
        return AuthCert(pubkey=self.public, expiration=expiration, sig=sig)

    def get_auth_cert(self) -> AuthCert:
        if self._cert.expiration < self.app.clock.system_now() + \
                CERT_EXPIRATION_SECONDS // 2:
            self._cert = self._make_cert()
        return self._cert

    def verify_remote_cert(self, remote_node: PublicKey,
                           cert: AuthCert) -> bool:
        if cert.expiration < self.app.clock.system_now():
            return False
        h = sha256(_cert_sign_bytes(self.app.config.network_id,
                                    cert.expiration, cert.pubkey))
        return PubKeyUtils.verify_sig(remote_node, cert.sig, h)

    # -- session keys --------------------------------------------------------
    def _shared_key(self, remote_public: bytes, we_called: bool) -> bytes:
        ck = (remote_public, we_called)
        got = self._shared_cache.maybe_get(ck)
        if got is not None:
            return got
        if we_called:
            a, b = self.public, remote_public
        else:
            a, b = remote_public, self.public
        k = curve25519_derive_shared(self._secret, remote_public, a, b)
        self._shared_cache.put(ck, k)
        return k

    def get_sending_mac_key(self, remote_public: bytes, local_nonce: bytes,
                            remote_nonce: bytes, we_called: bool) -> bytes:
        """K_AB when we called (A=local), K_BA when they called (B=local)
        (reference PeerAuth.cpp:92-113)."""
        prefix = b"\x00" if we_called else b"\x01"
        k = self._shared_key(remote_public, we_called)
        return hkdf_expand_key(k, prefix + local_nonce + remote_nonce)

    def get_receiving_mac_key(self, remote_public: bytes, local_nonce: bytes,
                              remote_nonce: bytes, we_called: bool) -> bytes:
        """Mirror of the remote's sending key: their direction byte with
        their (remote) nonce first (reference PeerAuth.cpp:116-135)."""
        prefix = b"\x01" if we_called else b"\x00"
        k = self._shared_key(remote_public, we_called)
        return hkdf_expand_key(k, prefix + remote_nonce + local_nonce)
