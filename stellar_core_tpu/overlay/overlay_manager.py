"""OverlayManager: the p2p mesh controller.

Role parity: reference `src/overlay/OverlayManagerImpl.{h,cpp}` — owns the
listening door, the pending/authenticated peer sets, the periodic tick that
tops connections up to TARGET_PEER_CONNECTIONS (OverlayManagerImpl.cpp:497),
the Floodgate (broadcastMessage :891, recvFloodedMsg :878), the two
ItemFetchers wired into the Herder's PendingEnvelopes, PeerManager and
BanManager. Transport-agnostic: real TCP via TCPReactor/TCPDoor, or
loopback pipes inside a Simulation.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..util import rnd
from ..util.log import get_logger
from ..util.timer import VirtualTimer
from ..xdr import DontHave, MessageType, StellarMessage
from .floodgate import Floodgate
from .item_fetcher import ItemFetcher
from .peer import Peer, PeerState
from .peer_auth import PeerAuth, PeerRole
from .peer_manager import BanManager, PeerManager
from .transport import LoopbackTransport, TCPDoor, TCPReactor, TCPTransport

log = get_logger("Overlay")

TICK_SECONDS = 2.0


class OverlayManager:
    def __init__(self, app) -> None:
        self.app = app
        self.peer_auth = PeerAuth(app)
        self.peer_manager = PeerManager(app)
        self.ban_manager = BanManager(app)
        # wire cockpit (ISSUE 10): ONE aggregation shared by Peer frame
        # accounting, Floodgate dedup, the Herder's envelope pipeline
        # and the tick's queue-depth gauge — constructed before any peer
        # so the first frame is already attributed
        # (docs/observability.md#overlay-cockpit)
        from .overlay_stats import OverlayStats
        self.stats = OverlayStats(
            metrics=getattr(app, "metrics", None),
            tracer=getattr(app, "tracer", None),
            now_fn=app.clock.now)
        # propagation cockpit (ISSUE 17): causal hop records + per-peer
        # usefulness, fed by the Floodgate (recv/send hops, origins) and
        # the Peer MAC-layer duplicate branch; None when the operator
        # runs the propagation-disabled control leg
        # (docs/observability.md#propagation-cockpit)
        self.prop_stats = None
        if getattr(app.config, "PROPAGATION_STATS_ENABLED", True):
            from .propagation_stats import PropagationStats
            self.prop_stats = PropagationStats(
                metrics=getattr(app, "metrics", None),
                tracer=getattr(app, "tracer", None),
                now_fn=app.clock.now,
                self_id=app.config.node_id().key_bytes.hex())
        self.floodgate = Floodgate()
        self.floodgate.stats = self.stats
        self.floodgate.prop = self.prop_stats
        from .flood_control import FloodControl
        self.flood_control = FloodControl(app)
        # hash-keyed peer registry: id_key (nodeid xdr) -> Peer
        self.pending_peers: List[Peer] = []
        self.authenticated_peers: Dict[bytes, Peer] = {}
        self.tx_set_fetcher = ItemFetcher(
            self, lambda h: StellarMessage(MessageType.GET_TX_SET, h))
        self.qset_fetcher = ItemFetcher(
            self, lambda h: StellarMessage(MessageType.GET_SCP_QUORUMSET, h))
        from .survey_manager import SurveyManager
        self.survey_manager = SurveyManager(app, self)
        from .load_manager import LoadManager
        self.load_manager = LoadManager(app)
        self._reactor: Optional[TCPReactor] = None
        self._door: Optional[TCPDoor] = None
        self._tick_timer = VirtualTimer(app.clock)
        self._shutting_down = False
        self._wire_herder_fetchers()

    # -- herder wiring -------------------------------------------------------
    def _wire_herder_fetchers(self) -> None:
        # PendingEnvelopes buffers envelopes and re-feeds them itself when
        # items arrive; the fetchers only drive the ask-a-peer loop.
        herder = getattr(self.app, "herder", None)
        if herder is not None and hasattr(herder, "pending"):
            herder.pending.set_fetchers(self.tx_set_fetcher.fetch,
                                        self.qset_fetcher.fetch)

    def item_fetched_txset(self, item_hash: bytes) -> None:
        self.tx_set_fetcher.recv(item_hash, lambda env: None)

    def item_fetched_qset(self, item_hash: bytes) -> None:
        self.qset_fetcher.recv(item_hash, lambda env: None)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        cfg = self.app.config
        if not cfg.RUN_STANDALONE:
            self._reactor = TCPReactor(self.app.clock)
            self._reactor.start()
            self._door = TCPDoor(self._reactor, cfg.PEER_PORT,
                                 self._on_inbound_connection)
            if self._door.port != cfg.PEER_PORT:
                cfg.PEER_PORT = self._door.port
        self._arm_tick()

    def shutdown(self) -> None:
        self._shutting_down = True
        self._tick_timer.cancel()
        self.floodgate.shutdown()
        for p in list(self.authenticated_peers.values()) + \
                list(self.pending_peers):
            p.transport.close()
        self.authenticated_peers.clear()
        self.pending_peers.clear()
        if self._door is not None:
            self._door.close()
        if self._reactor is not None:
            self._reactor.stop()
        self.peer_manager.store()

    # -- tick ----------------------------------------------------------------
    def _arm_tick(self) -> None:
        if self._shutting_down:
            return
        self._tick_timer.expires_from_now(TICK_SECONDS)
        self._tick_timer.async_wait(self.tick)

    def tick(self) -> None:
        """Maintain target connections, drop stragglers
        (reference OverlayManagerImpl::tick, :497)."""
        if self._shutting_down:
            return
        cfg = self.app.config
        now = self.app.clock.now()
        # drop peers that never authenticated in time
        for p in list(self.pending_peers):
            if now - p.connected_at > cfg.PEER_AUTHENTICATION_TIMEOUT:
                p.drop("auth timeout")
        for p in list(self.authenticated_peers.values()):
            # keepalive ping at half-timeout so a quiet-but-healthy link
            # refreshes both sides' read clocks; drop only when BOTH
            # directions have been silent past the timeout (reference
            # Peer idle-timer semantics)
            if now - p.last_write > cfg.PEER_TIMEOUT / 2:
                p.send_message(StellarMessage(MessageType.GET_PEERS, None))
            if now - p.last_read > cfg.PEER_TIMEOUT and \
                    now - p.last_write > cfg.PEER_TIMEOUT:
                p.drop("idle timeout")
            elif now - p.last_read > cfg.PEER_STRAGGLER_TIMEOUT:
                # our pings keep last_write fresh; a peer that answers
                # nothing for the straggler window is dead or stuck
                p.drop("straggling (no reads)")
            elif p.transport.oldest_unsent_age() > \
                    cfg.PEER_STRAGGLER_TIMEOUT:
                # a peer that won't drain our writes can't keep up
                # (reference Peer::idleTimerExpired straggler branch)
                p.drop("straggling (cannot keep up)")
        missing = cfg.TARGET_PEER_CONNECTIONS - self.num_connections()
        if missing > 0 and self._reactor is not None:
            exclude = [(p.address[0], p.remote_listening_port)
                       for p in self.authenticated_peers.values()
                       if p.address]
            # a dial still mid-handshake must not be re-dialed
            exclude += [p.address for p in self.pending_peers if p.address]
            for rec in self.peer_manager.candidates_to_connect(
                    missing, exclude):
                # strict mode would reject a non-preferred peer right
                # after its handshake anyway — dialing it would redial
                # every tick forever (the policy drop happens post-auth,
                # outside the connect-failure backoff)
                if cfg.PREFERRED_PEERS_ONLY and not rec.preferred:
                    continue
                self.connect_to(rec.host, rec.port)
        self.load_manager.maybe_shed_excess_load(self)
        # send-queue pressure gauges: total queued-but-unsent bytes and
        # how many peers have a backlog (TCP transports; loopback pipes
        # have no queue and report 0)
        total, backlogged = self.send_queue_depth()
        self.stats.set_queue_depth(total, backlogged)
        self._arm_tick()

    def num_connections(self) -> int:
        return len(self.pending_peers) + len(self.authenticated_peers)

    def send_queue_depth(self) -> tuple:
        """(total queued-but-unsent bytes, peers with a backlog) across
        every connection — the cockpit's send-queue pressure signal."""
        total = 0
        backlogged = 0
        for p in list(self.authenticated_peers.values()) + \
                list(self.pending_peers):
            t = p.transport
            qb = getattr(t, "_wqueue_bytes",
                         getattr(getattr(t, "inner", None),
                                 "_wqueue_bytes", 0)) or 0
            total += qb
            backlogged += qb > 0
        return total, backlogged

    # -- connections ---------------------------------------------------------
    def connect_to(self, host: str, port: int) -> Optional[Peer]:
        if self._reactor is None:
            return None
        try:
            t = TCPTransport.connect(self._reactor, host, port)
        except OSError as e:
            log.debug("connect to %s:%d failed: %s", host, port, e)
            self.peer_manager.on_connect_failure(host, port)
            return None
        self._apply_transport_limits(t)
        peer = Peer(self.app, self, t, PeerRole.WE_CALLED_REMOTE,
                    address=(host, port))
        self.pending_peers.append(peer)
        # the dial is async (non-blocking connect): success is recorded
        # when the peer authenticates, failure when it closes pre-auth
        # (accept_authenticated_peer / remove_peer), keeping the
        # peer-table backoff accurate
        peer.connect_handshake()
        return peer

    def _apply_transport_limits(self, t) -> None:
        cfg = self.app.config
        t.max_batch_write_count = cfg.MAX_BATCH_WRITE_COUNT
        t.max_batch_write_bytes = cfg.MAX_BATCH_WRITE_BYTES
        t.send_queue_limit_bytes = cfg.PEER_SEND_QUEUE_LIMIT_BYTES
        # overflow drops are counted, and the overlay.send-overflow
        # fault site can force them deterministically
        t.metrics = getattr(self.app, "metrics", None)
        t.faults = getattr(self.app, "faults", None)

    def _on_inbound_connection(self, transport, addr) -> None:
        if self.num_connections() >= \
                self.app.config.MAX_PENDING_CONNECTIONS + \
                self.app.config.TARGET_PEER_CONNECTIONS:
            transport.close()
            return
        self._apply_transport_limits(transport)
        peer = Peer(self.app, self, transport, PeerRole.REMOTE_CALLED_US,
                    address=(addr[0], addr[1]))
        self.pending_peers.append(peer)

    def add_loopback_peer(self, transport: LoopbackTransport,
                          outbound: bool, address=None) -> Peer:
        """Attach one end of an in-process pipe as a peer (simulation)."""
        role = (PeerRole.WE_CALLED_REMOTE if outbound
                else PeerRole.REMOTE_CALLED_US)
        peer = Peer(self.app, self, transport, role, address=address)
        self.pending_peers.append(peer)
        if outbound:
            peer.connect_handshake()
        return peer

    def _preferred_key_set(self) -> frozenset:
        """PREFERRED_PEER_KEYS strkeys decoded once (invalid entries are
        logged once and skipped)."""
        cfg_keys = tuple(self.app.config.PREFERRED_PEER_KEYS)
        if getattr(self, "_pref_keys_src", None) != cfg_keys:
            from ..crypto import strkey
            decoded = []
            for s in cfg_keys:
                try:
                    decoded.append(strkey.decode_public_key(s))
                except Exception:
                    log.warning("ignoring invalid PREFERRED_PEER_KEYS "
                                "entry %r", s)
            self._pref_keys_src = cfg_keys
            self._pref_keys = frozenset(decoded)
        return self._pref_keys

    def is_preferred(self, peer: Peer) -> bool:
        """Preferred by configured address or by node key (reference
        OverlayManagerImpl::isPreferred). Inbound peers match on their
        LISTENING port from HELLO, not the ephemeral socket port."""
        if peer.address is not None:
            for port in (peer.address[1], peer.remote_listening_port):
                rec = self.peer_manager._peers.get((peer.address[0], port))
                if rec is not None and rec.preferred:
                    return True
        if peer.peer_id is not None and \
                peer.peer_id.key_bytes in self._preferred_key_set():
            return True
        return False

    def accept_authenticated_peer(self, peer: Peer) -> bool:
        """Handshake finished: move pending → authenticated
        (reference moveToAuthenticated/acceptAuthenticatedPeer)."""
        # the transport + handshake worked: whatever happens next (ban,
        # duplicate-connection tiebreak, policy rejection) must NOT count
        # toward the connect-failure backoff
        peer.ever_authenticated = True
        key = peer.peer_id.to_xdr()
        if self.ban_manager.is_banned(peer.peer_id):
            peer.drop("banned")
            return False
        # connection policy (reference acceptAuthenticatedPeer:178-215):
        # preferred peers always win a slot — evicting a non-preferred
        # victim at capacity — and strict mode rejects everyone else.
        # Capacity matches the load manager's shedding limit: target
        # plus the operator's additional inbound headroom.
        cfg = self.app.config
        max_auth = cfg.TARGET_PEER_CONNECTIONS + \
            max(0, cfg.MAX_ADDITIONAL_PEER_CONNECTIONS)
        if self.is_preferred(peer):
            if len(self.authenticated_peers) >= max_auth and \
                    self.authenticated_peers.get(key) is None:
                for vk, victim in list(self.authenticated_peers.items()):
                    if not self.is_preferred(victim):
                        log.info("evicting non-preferred peer %s for "
                                 "preferred %s", victim.id_str(),
                                 peer.id_str())
                        victim.drop("preferred peer selected instead")
                        break
        elif cfg.PREFERRED_PEERS_ONLY or \
                (len(self.authenticated_peers) >= max_auth and
                 self.authenticated_peers.get(key) is None):
            peer.drop("peer rejected")
            return False
        existing = self.authenticated_peers.get(key)
        if existing is not None and existing is not peer:
            # One connection per node id. Simultaneous connects create one
            # in each direction; both sides must pick the SAME survivor or
            # they keep killing each other's link. Tiebreak: keep the
            # connection initiated by the smaller node id.
            we_called_survives = self.app.config.node_id().to_xdr() < key
            new_is_survivor = (
                existing.role != peer.role and
                (peer.role == PeerRole.WE_CALLED_REMOTE) == we_called_survives)
            if not new_is_survivor:
                peer.drop("duplicate connection")
                return False
            existing.drop("duplicate connection (tiebreak)")
        if peer in self.pending_peers:
            self.pending_peers.remove(peer)
        self.authenticated_peers[key] = peer
        if peer.role == PeerRole.WE_CALLED_REMOTE and peer.address:
            self.peer_manager.on_connect_success(*peer.address)
        m = getattr(self.app, "metrics", None)
        if m is not None:
            m.new_meter("overlay.connection.authenticated").mark()
            m.new_counter("overlay.connection.count").set_count(
                len(self.authenticated_peers))
        log.debug("peer %s authenticated (%d total)", peer.id_str(),
                  len(self.authenticated_peers))
        return True

    def remove_peer(self, peer: Peer) -> None:
        if peer in self.pending_peers:
            self.pending_peers.remove(peer)
        if peer.role == PeerRole.WE_CALLED_REMOTE and peer.address and \
                not peer.ever_authenticated:
            # an outbound dial that died before authenticating (incl.
            # async connect failures) counts toward the backoff
            self.peer_manager.on_connect_failure(*peer.address)
        if peer.peer_id is not None:
            key = peer.peer_id.to_xdr()
            if self.authenticated_peers.get(key) is peer:
                del self.authenticated_peers[key]
                self.load_manager.forget(key)
                self.flood_control.forget(key)

    # -- registry views ------------------------------------------------------
    def authenticated_peer_ids(self) -> List[bytes]:
        return list(self.authenticated_peers.keys())

    def get_peer(self, key: bytes) -> Optional[Peer]:
        return self.authenticated_peers.get(key)

    def random_authenticated_peers(self, n: int = 0) -> List[Peer]:
        peers = list(self.authenticated_peers.values())
        rnd.g_random.shuffle(peers)
        return peers[:n] if n else peers

    def get_authenticated_peers_count(self) -> int:
        return len(self.authenticated_peers)

    # -- flooding ------------------------------------------------------------
    def _current_ledger_seq(self) -> int:
        return self.app.ledger_manager.last_closed_ledger_num()

    def flood_rate_limited(self, peer: Peer) -> bool:
        """Token-bucket admission for one flooded message from `peer`
        (overlay/flood_control.py): True = drop it before any processing
        or relay. Escalation (ban score → BanManager + peer drop) happens
        inside the flood controller."""
        return self.flood_control.limited(peer)

    def flood_backpressure(self, peer: Peer) -> None:
        """The ingress tier shed/throttled a tx this peer relayed
        (ISSUE 18): score it fractionally toward the flood ban so
        sustained useless relay escalates, without punishing one-offs."""
        self.flood_control.note_backpressure(peer)

    def recv_flooded_msg(self, msg: StellarMessage, peer: Peer) -> bool:
        """Returns False if this flooded message was seen before."""
        return self.floodgate.add_record(
            msg, peer.peer_id.to_xdr(), self._current_ledger_seq(),
            from_hex=peer.peer_id.key_bytes.hex())

    def broadcast_message(self, msg: StellarMessage,
                          force: bool = False) -> int:
        m = getattr(self.app, "metrics", None)
        if m is not None:
            m.new_meter("overlay.message.broadcast").mark()
        return self.floodgate.broadcast(
            msg, force, self.authenticated_peers,
            self._current_ledger_seq())

    def forget_flooded_msg(self, msg: StellarMessage) -> None:
        self.floodgate.forget_record(msg)

    def ledger_closed(self, ledger_seq: int) -> None:
        # per-slot bandwidth attribution: bytes moved since the previous
        # close belong to this slot (fleet view sums them across nodes)
        self.stats.slot_closed(ledger_seq)
        if self.prop_stats is not None:
            # prune propagation hop rings below the checkpoint window
            # (ISSUE 17 satellite: explicit memory bound)
            self.prop_stats.slot_closed(ledger_seq)
        self.floodgate.clear_below(ledger_seq)
        self.flood_control.ledger_closed()
        self.tx_set_fetcher.stop_fetching_below(ledger_seq)
        self.qset_fetcher.stop_fetching_below(ledger_seq)

    # -- fetch plumbing ------------------------------------------------------
    def recv_dont_have(self, peer: Peer, dh: DontHave) -> None:
        if dh.type == MessageType.TX_SET:
            self.tx_set_fetcher.doesnt_have(dh.reqHash, peer.peer_id.to_xdr())
        elif dh.type == MessageType.SCP_QUORUMSET:
            self.qset_fetcher.doesnt_have(dh.reqHash, peer.peer_id.to_xdr())

    # -- introspection -------------------------------------------------------
    def get_peers_info(self) -> dict:
        def one(p: Peer) -> dict:
            return {
                "id": p.id_str(), "address": str(p.address),
                "version": p.remote_version_str,
                "olver": p.remote_overlay_version,
                "in": p.messages_read, "out": p.messages_written,
            }
        return {
            "authenticated_count": len(self.authenticated_peers),
            "pending_count": len(self.pending_peers),
            "authenticated": [one(p)
                              for p in self.authenticated_peers.values()],
            # per-peer flood-defense state (token levels, ban scores)
            "flood": self.flood_control.to_json(),
        }
