"""PropagationStats: the propagation cockpit's shared aggregation
(ISSUE 17 tentpole; docs/observability.md#propagation-cockpit).

The sixth cockpit. Where OverlayStats answers "how many bytes moved",
this one answers "which edges moved them, and which were wasted": every
flooded message (SCP envelopes, tx broadcasts) is stamped into a causal
hop record as it crosses the node —

- a **recv hop** when a peer delivers it (OverlayManager.recv_flooded_msg
  and the Peer MAC-layer duplicate branch), classified *first delivery*
  (useful — the edge that actually propagated the message) or
  *redundant edge* (wasted bytes, attributed to the sending peer);
- a **send hop** per peer the Floodgate relays it to;
- an **origin** marker when this node is the broadcaster (Herder
  externalize / tx submission), the root the fleet-level relay-tree
  reconstruction hangs everything off.

Each hop carries `(from_peer, t, pc, first, bytes)` where `t` is the
injected app clock (virtual in tests — sctlint D1 holds) and `pc` the
shared `real_perf_counter` stamp routed through util/timer.py (the ONE
sanctioned escape hatch): in-process simulations share one perf_counter,
so cross-node hop latencies are directly comparable, and real fleets are
rebased on the externalize epochs by FleetAggregator exactly like the
slot-timeline stamps.

Consumers:

- admin `propagation` endpoint (`to_json`, `?hash=H` hop trace,
  `?peer=P` detail, `?action=reset`);
- the metrics registry (`overlay.prop.*` names → `sct_overlay_prop_*`
  in the Prometheus exposition);
- the fleet view: `fleet_json()` is what FleetAggregator merges by
  msg_hash into propagation trees (origin, first-delivery spanning
  tree, per-edge hop latency, redundant-edge overlay) and the
  `propagation` bench block;
- per-peer usefulness `firsts / (firsts + duplicates)` — the ranking
  the planned structured-relay "have"-filter will aim advert targets
  with (ROADMAP item 1).

Bounded: at most MAX_HASHES per-hash records (LRU), MAX_HOPS_PER_HASH
hops each, MAX_PEERS attributed peers; `slot_closed` prunes records
below the current checkpoint's first slot (history/checkpoints.py), so
a long-running node's rings never outgrow one checkpoint window.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional

from ..history.checkpoints import checkpoint_containing, first_in_checkpoint
from ..util.metrics import MetricsRegistry
from ..util.threads import TrackedLock
from ..util.timer import real_monotonic, real_perf_counter
from .overlay_stats import msg_type_name


def _new_peer_score() -> dict:
    return {"firsts": 0, "duplicates": 0, "wasted_bytes": 0}


class PropagationStats:
    """Propagation-cockpit aggregation; see module docstring."""

    MAX_HASHES = 4096         # per-hash records retained (LRU)
    MAX_HOPS_PER_HASH = 256   # hop ring per record
    MAX_PEERS = 256           # per-peer usefulness entries retained
    TOP_K = 8                 # peers shown per ranking in the admin blob
    MIN_SAMPLES = 4           # deliveries before a peer is rankable

    def __init__(self, metrics=None, tracer=None, now_fn=None,
                 self_id: Optional[str] = None) -> None:
        self._now = now_fn or real_monotonic
        # a private registry when none is injected keeps direct
        # constructions (tests, harnesses) app-registry-free while
        # letting every registration below use the new_* idiom the M1
        # metric-catalog scanner keys on
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.tracer = tracer
        self.self_id = self_id or ""
        self._lock = TrackedLock("overlay.propagation-stats")
        m = self.metrics
        # edge classes are the two-value bounded name space the
        # test_metrics_catalog drift guard covers as a dynamic prefix
        self._m_edge = {
            "first": m.new_meter("overlay.prop.edge.%s" % "first"),
            "duplicate": m.new_meter("overlay.prop.edge.%s" % "duplicate"),
        }
        self._c_wasted = m.new_counter("overlay.prop.wasted-bytes")
        self._m_pruned = m.new_meter("overlay.prop.pruned")
        self._g_hashes = m.new_gauge("overlay.prop.hashes")
        self._g_worst = m.new_gauge("overlay.prop.usefulness.worst")
        self.reset()

    # -- lifecycle -----------------------------------------------------------
    def reset(self) -> None:
        """Zero the aggregates (admin `propagation?action=reset`;
        registry metrics keep their monotonic histories)."""
        with self._lock:
            # msg_hash -> {"ledger_seq", "type", "origin", "firsts",
            #              "duplicates", "bytes", "hops": [hop...]}
            self._hashes: "OrderedDict[bytes, dict]" = OrderedDict()
            self.peers: Dict[str, dict] = {}
            self.totals = {"firsts": 0, "duplicates": 0,
                           "wasted_bytes": 0, "flood_bytes": 0,
                           "pruned": 0, "dropped_hops": 0}

    # -- hop recording -------------------------------------------------------
    def _record_locked(self, msg_hash: bytes, msg_type,
                       ledger_seq: int) -> dict:
        rec = self._hashes.get(msg_hash)
        if rec is None:
            rec = self._hashes[msg_hash] = {
                "ledger_seq": ledger_seq,
                "type": msg_type_name(msg_type),
                "origin": False,
                "firsts": 0, "duplicates": 0, "bytes": 0,
                "hops": [],
            }
            while len(self._hashes) > self.MAX_HASHES:
                self._hashes.popitem(last=False)
        else:
            self._hashes.move_to_end(msg_hash)
        return rec

    def _append_hop_locked(self, rec: dict, hop: dict) -> None:
        if len(rec["hops"]) >= self.MAX_HOPS_PER_HASH:
            self.totals["dropped_hops"] += 1
            return
        rec["hops"].append(hop)

    def record_recv_hop(self, msg_hash: bytes, from_peer: str, nbytes: int,
                        msg_type, first: bool, ledger_seq: int) -> None:
        """One flooded message delivered by `from_peer` (node-id hex):
        `first=True` is the useful edge that actually propagated it,
        `first=False` a redundant edge whose bytes are wasted and
        attributed to the sender. Exactly one call per
        Floodgate.add_record receipt, so firsts/duplicates summed over
        hop records reconcile with the flood duplication ratio."""
        cls = "first" if first else "duplicate"
        self._m_edge[cls].mark()
        if not first:
            self._c_wasted.inc(nbytes)
        with self._lock:
            rec = self._record_locked(msg_hash, msg_type, ledger_seq)
            self._append_hop_locked(rec, {
                "dir": "recv", "peer": from_peer,
                "t": round(self._now(), 6), "pc": real_perf_counter(),
                "first": first, "bytes": nbytes,
            })
            rec["firsts" if first else "duplicates"] += 1
            rec["bytes"] += nbytes
            self.totals["firsts" if first else "duplicates"] += 1
            self.totals["flood_bytes"] += nbytes
            if not first:
                self.totals["wasted_bytes"] += nbytes
            p = self.peers.get(from_peer)
            if p is None:
                if len(self.peers) >= self.MAX_PEERS:
                    self._g_hashes.set(len(self._hashes))
                    return   # bounded: beyond the cap only totals count
                p = self.peers[from_peer] = _new_peer_score()
            p["firsts" if first else "duplicates"] += 1
            if not first:
                p["wasted_bytes"] += nbytes
            self._g_hashes.set(len(self._hashes))

    def record_send_hop(self, msg_hash: bytes, to_peer: str, nbytes: int,
                        msg_type, ledger_seq: int) -> None:
        """One relay of a flooded message to `to_peer`
        (Floodgate.broadcast fanout)."""
        with self._lock:
            rec = self._record_locked(msg_hash, msg_type, ledger_seq)
            self._append_hop_locked(rec, {
                "dir": "send", "peer": to_peer,
                "t": round(self._now(), 6), "pc": real_perf_counter(),
                "bytes": nbytes,
            })
            self._g_hashes.set(len(self._hashes))

    def record_origin(self, msg_hash: bytes, nbytes: int, msg_type,
                      ledger_seq: int) -> None:
        """This node is the broadcaster of `msg_hash` — the relay tree's
        root (Floodgate.broadcast creating a record with no receipt)."""
        with self._lock:
            rec = self._record_locked(msg_hash, msg_type, ledger_seq)
            rec["origin"] = True
            self._append_hop_locked(rec, {
                "dir": "origin", "peer": self.self_id,
                "t": round(self._now(), 6), "pc": real_perf_counter(),
                "bytes": nbytes,
            })
            self._g_hashes.set(len(self._hashes))

    # -- usefulness ----------------------------------------------------------
    @staticmethod
    def _usefulness(score: dict) -> float:
        n = score["firsts"] + score["duplicates"]
        return score["firsts"] / n if n else 1.0

    def _ranked_locked(self) -> list:
        out = []
        for pid, s in self.peers.items():
            n = s["firsts"] + s["duplicates"]
            out.append({"peer": pid, "firsts": s["firsts"],
                        "duplicates": s["duplicates"],
                        "wasted_bytes": s["wasted_bytes"],
                        "deliveries": n,
                        "usefulness": round(self._usefulness(s), 4)})
        out.sort(key=lambda e: (-e["usefulness"], e["peer"]))
        return out

    def _worst_usefulness_locked(self) -> Optional[float]:
        vals = [self._usefulness(s) for s in self.peers.values()
                if s["firsts"] + s["duplicates"] >= self.MIN_SAMPLES]
        return min(vals) if vals else None

    # -- pruning (ledger_closed hook) ----------------------------------------
    def slot_closed(self, ledger_seq: int) -> None:
        """Prune hop records from before the current checkpoint's first
        slot — the explicit memory bound the `overlay.prop.pruned`
        meter and `overlay.prop.hashes` gauge watch — and refresh the
        worst-peer usefulness gauge off the hot path."""
        cutoff = first_in_checkpoint(checkpoint_containing(ledger_seq))
        pruned = 0
        with self._lock:
            for h in [h for h, r in self._hashes.items()
                      if r["ledger_seq"] < cutoff]:
                del self._hashes[h]
                pruned += 1
            self.totals["pruned"] += pruned
            self._g_hashes.set(len(self._hashes))
            worst = self._worst_usefulness_locked()
        if pruned:
            self._m_pruned.mark(pruned)
        if worst is not None:
            self._g_worst.set(round(worst, 4))

    # -- exports -------------------------------------------------------------
    def _hash_json_locked(self, h: bytes, rec: dict) -> dict:
        return {
            "hash": h.hex(),
            "ledger_seq": rec["ledger_seq"],
            "type": rec["type"],
            "origin": rec["origin"],
            "firsts": rec["firsts"],
            "duplicates": rec["duplicates"],
            "bytes": rec["bytes"],
            "hops": [dict(hop) for hop in rec["hops"]],
        }

    def hash_trace(self, hash_hex: str) -> Optional[dict]:
        """The full hop trace for one message (admin
        `propagation?hash=H`; H may be a unique hex prefix)."""
        with self._lock:
            for h, rec in self._hashes.items():
                if h.hex().startswith(hash_hex.lower()):
                    return self._hash_json_locked(h, rec)
        return None

    def peer_detail(self, peer: str) -> Optional[dict]:
        """One peer's usefulness score (admin `propagation?peer=P`; P
        may be a unique hex prefix of the node id)."""
        with self._lock:
            for pid, s in self.peers.items():
                if pid.startswith(peer.lower()):
                    n = s["firsts"] + s["duplicates"]
                    return {"peer": pid, **dict(s), "deliveries": n,
                            "usefulness": round(self._usefulness(s), 4)}
        return None

    def to_json(self) -> dict:
        """The admin `propagation` cockpit blob."""
        with self._lock:
            ranked = self._ranked_locked()
            worst = self._worst_usefulness_locked()
            fb = self.totals["flood_bytes"]
            return {
                "totals": dict(self.totals),
                "redundant_bandwidth_share": round(
                    self.totals["wasted_bytes"] / fb, 4) if fb else 0.0,
                "hashes": {"tracked": len(self._hashes),
                           "cap": self.MAX_HASHES},
                "peers": {
                    "tracked": len(self.peers),
                    "worst_usefulness": (round(worst, 4)
                                         if worst is not None else None),
                    "top": ranked[:self.TOP_K],
                    "bottom": ranked[-self.TOP_K:][::-1],
                },
            }

    def fleet_json(self) -> dict:
        """Compact per-node export the FleetAggregator merges by
        msg_hash into relay trees (one shape for in-process `add_app`
        and HTTP `add_http` intake)."""
        with self._lock:
            return {
                "self": self.self_id,
                "totals": dict(self.totals),
                "peers": {pid: dict(s) for pid, s in self.peers.items()},
                "hashes": {h.hex(): self._hash_json_locked(h, rec)
                           for h, rec in self._hashes.items()},
            }
