"""ItemFetcher/Tracker: anycast fetch of txsets and quorum sets.

Role parity: reference `src/overlay/ItemFetcher.{h,cpp}` and
`Tracker.{h,cpp}` — one Tracker per wanted item hash holds the envelopes
waiting on it, asks one random authenticated peer at a time, rotates to the
next peer on timeout (MS_TO_WAIT_FOR_FETCH_REPLY) or DONT_HAVE, and when
the item arrives re-feeds the waiting envelopes to the Herder.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..util import rnd
from ..util.log import get_logger
from ..util.timer import VirtualTimer
from ..xdr import SCPEnvelope, StellarMessage

log = get_logger("Overlay")

MS_TO_WAIT_FOR_FETCH_REPLY = 1.5
MAX_REBUILD_FETCH_LIST = 1000
# retry-delay growth cap (multiplier saturates here) and the give-up
# bound: after this many full candidate-list rebuilds with no answer the
# tracker stops polling and counts an `overlay.item-fetcher.giveup` —
# an unfetchable txset becomes a visible metric instead of an eternal
# silent poll (docs/robustness.md)
MAX_DELAY_REBUILDS = 10
GIVEUP_REBUILDS = 32


class Tracker:
    """Fetch state for one item (reference Tracker.h)."""

    def __init__(self, overlay, item_hash: bytes,
                 make_request: Callable[[bytes], StellarMessage]) -> None:
        self.overlay = overlay
        self.item_hash = item_hash
        self.make_request = make_request
        self.waiting: List[SCPEnvelope] = []
        self.last_asked_peer: Optional[str] = None
        self.peers_asked: List[str] = []
        self.timer = VirtualTimer(overlay.app.clock)
        self.num_list_rebuild = 0
        self._stopped = False
        # called (with self) when the tracker abandons the fetch, so the
        # owning ItemFetcher can drop it from its registry
        self.on_giveup: Optional[Callable[["Tracker"], None]] = None

    def listen(self, env: SCPEnvelope) -> None:
        if len(self.waiting) < MAX_REBUILD_FETCH_LIST:
            self.waiting.append(env)

    def try_next_peer(self) -> None:
        """Ask one peer we haven't asked this round; when all are
        exhausted, rebuild the candidate list and back off slightly
        (reference Tracker::tryNextPeer). After GIVEUP_REBUILDS fruitless
        rebuilds the tracker gives up instead of polling forever."""
        if self._stopped:
            return
        peers = self.overlay.authenticated_peer_ids()
        candidates = [p for p in peers if p not in self.peers_asked]
        if not candidates:
            self.peers_asked = []
            self.num_list_rebuild += 1
            if self.num_list_rebuild >= GIVEUP_REBUILDS:
                self._give_up()
                return
            candidates = list(peers)
        if candidates:
            pid = candidates[rnd.g_random.randrange(len(candidates))]
            self.last_asked_peer = pid
            self.peers_asked.append(pid)
            peer = self.overlay.get_peer(pid)
            if peer is not None:
                peer.send_message(self.make_request(self.item_hash))
        delay = MS_TO_WAIT_FOR_FETCH_REPLY * (1 + min(
            self.num_list_rebuild, MAX_DELAY_REBUILDS))
        self.timer.expires_from_now(delay)
        self.timer.async_wait(self.try_next_peer)

    def _give_up(self) -> None:
        log.warning("giving up fetching %s after %d peer-list rebuilds "
                    "(%d envelopes waiting)", self.item_hash.hex()[:8],
                    self.num_list_rebuild, len(self.waiting))
        m = getattr(self.overlay.app, "metrics", None)
        if m is not None:
            m.new_meter("overlay.item-fetcher.giveup").mark()
        self.stop()
        if self.on_giveup is not None:
            self.on_giveup(self)

    def doesnt_have(self, peer_id: str) -> None:
        if peer_id == self.last_asked_peer:
            self.timer.cancel()
            self.try_next_peer()

    def stop(self) -> None:
        self._stopped = True
        self.timer.cancel()
        self.waiting.clear()


class ItemFetcher:
    """Hash → Tracker registry (reference ItemFetcher.h:41-96)."""

    def __init__(self, overlay,
                 make_request: Callable[[bytes], StellarMessage]) -> None:
        self.overlay = overlay
        self.make_request = make_request
        self.trackers: Dict[bytes, Tracker] = {}

    def fetch(self, item_hash: bytes,
              envelope: Optional[SCPEnvelope] = None) -> None:
        tr = self.trackers.get(item_hash)
        if tr is None:
            tr = Tracker(self.overlay, item_hash, self.make_request)
            tr.on_giveup = lambda t: self.trackers.pop(t.item_hash, None)
            self.trackers[item_hash] = tr
            if envelope is not None:
                tr.listen(envelope)
            tr.try_next_peer()
        elif envelope is not None:
            tr.listen(envelope)

    def recv(self, item_hash: bytes, feed: Callable[[SCPEnvelope], None]
             ) -> None:
        """Item arrived: stop tracking, re-feed waiting envelopes."""
        tr = self.trackers.pop(item_hash, None)
        if tr is None:
            return
        waiting = list(tr.waiting)
        tr.stop()
        for env in waiting:
            feed(env)

    def doesnt_have(self, item_hash: bytes, peer_id: str) -> None:
        tr = self.trackers.get(item_hash)
        if tr is not None:
            tr.doesnt_have(peer_id)

    def stop_fetching_below(self, slot_index: int) -> None:
        """Drop trackers whose every waiting envelope is below the slot
        (reference ItemFetcher::stopFetchingBelow)."""
        for h in list(self.trackers):
            tr = self.trackers[h]
            tr.waiting = [e for e in tr.waiting
                          if e.statement.slotIndex >= slot_index]
            if not tr.waiting:
                tr.stop()
                del self.trackers[h]

    def num_fetching(self) -> int:
        return len(self.trackers)
