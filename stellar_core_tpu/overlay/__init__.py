"""Overlay (p2p) layer.

Role parity: reference `src/overlay` — authenticated TCP mesh with XDR
framing, gossip flood, anycast item fetch, peer book."""

from .floodgate import Floodgate
from .item_fetcher import ItemFetcher, Tracker
from .overlay_manager import OverlayManager
from .peer import Peer, PeerState
from .peer_auth import PeerAuth, PeerRole
from .peer_manager import BanManager, PeerManager, parse_peer_address
from .transport import (
    LoopbackTransport, TCPDoor, TCPReactor, TCPTransport, Transport,
)

__all__ = [
    "BanManager", "Floodgate", "ItemFetcher", "LoopbackTransport",
    "OverlayManager", "Peer", "PeerAuth", "PeerManager", "PeerRole",
    "PeerState", "TCPDoor", "TCPReactor", "TCPTransport", "Tracker",
    "Transport", "parse_peer_address",
]
