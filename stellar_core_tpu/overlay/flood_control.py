"""FloodControl: per-peer token-bucket rate limiting on flooded messages.

Role parity: the reference's overlay survives envelope floods mostly by
luck (Floodgate dedup + LoadManager shedding); the committee-consensus
study (PAPERS.md, arXiv:2302.00418) shows envelope-flood cost is THE
scaling wall at large quorums, and DSig (2406.07215) only holds its
throughput claims under sustained adversarial load. This module makes
flood defense a first-class operating mode (ISSUE 8):

- every flooded message (TRANSACTION / SCP_MESSAGE) consumes one token
  from the sending peer's bucket; the bucket refills at
  `FLOOD_RATE_LIMIT_PER_PEER` msgs/s (app clock — virtual in tests) up
  to `FLOOD_RATE_BURST`;
- a message arriving on an empty bucket is dropped before any
  processing or relay (`overlay.flood.rate-limited` meter) and adds one
  point to the peer's ban score;
- a ban score reaching `FLOOD_BAN_SCORE_THRESHOLD` escalates into the
  existing `BanManager` (`overlay.flood.ban` meter): the node id is
  banned persistently and the connection dropped;
- ban scores halve on every ledger close, so a briefly-bursty honest
  peer decays back to zero instead of ratcheting toward a ban.

The `overlay.flood-limit` fault site forces the limited path for one
message — the deterministic way to exercise accounting and escalation
without an actual flood (docs/robustness.md#fault-points).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..util.faults import check_faults
from ..util.log import get_logger

log = get_logger("Overlay")


class _PeerFloodState:
    __slots__ = ("tokens", "last_refill", "ban_score", "limited", "banned")

    def __init__(self, tokens: float, now: float) -> None:
        self.tokens = tokens
        self.last_refill = now
        self.ban_score = 0.0
        self.limited = 0
        self.banned = False


class FloodControl:
    def __init__(self, app) -> None:
        self.app = app
        cfg = app.config
        self.rate = float(cfg.FLOOD_RATE_LIMIT_PER_PEER)   # <= 0 disables
        self.burst = float(cfg.FLOOD_RATE_BURST)
        self.ban_threshold = int(cfg.FLOOD_BAN_SCORE_THRESHOLD)
        self.faults = getattr(app, "faults", None)
        self._peers: Dict[bytes, _PeerFloodState] = {}

    def _metrics(self):
        return getattr(self.app, "metrics", None)

    def _state(self, key: bytes, now: float) -> _PeerFloodState:
        st = self._peers.get(key)
        if st is None:
            st = self._peers[key] = _PeerFloodState(self.burst, now)
        return st

    def _refill(self, st: _PeerFloodState, now: float) -> None:
        if self.rate > 0:
            st.tokens = min(self.burst,
                            st.tokens + (now - st.last_refill) * self.rate)
        st.last_refill = now

    def limited(self, peer) -> bool:
        """Consume one token for a flooded message from `peer`; True when
        the message must be dropped (bucket empty or fault-forced). Ban
        escalation happens here: the caller only sees the drop."""
        forced = check_faults(self, "overlay.flood-limit")
        if self.rate <= 0 and not forced:
            return False
        if peer.peer_id is None:
            return False
        key = peer.peer_id.to_xdr()
        now = self.app.clock.now()
        st = self._state(key, now)
        self._refill(st, now)
        if st.tokens >= 1.0 and not forced:
            st.tokens -= 1.0
            return False
        st.limited += 1
        st.ban_score += 1.0
        m = self._metrics()
        if m is not None:
            m.new_meter("overlay.flood.rate-limited").mark()
        self._maybe_ban(st, peer)
        return True

    def note_backpressure(self, peer) -> None:
        """A relayed tx the ingress tier threw back (ISSUE 18,
        TRY_AGAIN_LATER): the peer is pushing load past our admission
        capacity. Scores a fraction of a ban point, so a peer that
        relays nothing but sheddable load escalates exactly like a
        flooder — while an occasional backpressured relay decays away
        at the per-close halving."""
        if peer.peer_id is None:
            return
        now = self.app.clock.now()
        st = self._state(peer.peer_id.to_xdr(), now)
        st.ban_score += 0.25
        m = self._metrics()
        if m is not None:
            m.new_meter("overlay.flood.backpressure").mark()
        self._maybe_ban(st, peer)

    def _maybe_ban(self, st: _PeerFloodState, peer) -> None:
        if st.banned or self.ban_threshold <= 0 or \
                st.ban_score < self.ban_threshold:
            return
        st.banned = True
        m = self._metrics()
        if m is not None:
            m.new_meter("overlay.flood.ban").mark()
        log.warning("peer %s exceeded flood ban score (%d limited "
                    "messages): banning", peer.id_str(), st.limited)
        overlay = getattr(self.app, "overlay_manager", None)
        if overlay is not None:
            overlay.ban_manager.ban_node(peer.peer_id)
        peer.drop("flooding (rate limit exceeded)")

    def ledger_closed(self) -> None:
        """Decay: ban scores halve per close, idle states are reaped."""
        for key in list(self._peers):
            st = self._peers[key]
            st.ban_score /= 2.0
            if st.ban_score < 0.5:
                st.ban_score = 0.0
                if st.limited == 0 and st.tokens >= self.burst:
                    del self._peers[key]

    def forget(self, key: bytes) -> None:
        self._peers.pop(key, None)

    def score(self, peer_key: bytes) -> float:
        st = self._peers.get(peer_key)
        return st.ban_score if st is not None else 0.0

    def to_json(self) -> dict:
        return {
            "rate_per_s": self.rate,
            "burst": self.burst,
            "ban_threshold": self.ban_threshold,
            "peers": {
                key.hex()[:16]: {
                    "tokens": round(st.tokens, 2),
                    "ban_score": round(st.ban_score, 2),
                    "limited": st.limited,
                    "banned": st.banned,
                }
                for key, st in self._peers.items()
            },
        }
