"""Peer: the overlay protocol state machine over an abstract transport.

Role parity: reference `src/overlay/Peer.{h,cpp}` — handshake
(Hello ↔ Hello, Auth ↔ Auth), per-message HMAC with monotonically
increasing sequence numbers (Peer.cpp:436-439 send, :514 verify), and the
message dispatch switch (Peer.cpp:529-790) routing transactions and SCP
traffic into the Herder and serving GET_TX_SET / GET_SCP_QUORUMSET /
GET_PEERS / GET_SCP_STATE requests.

Transports: LoopbackTransport (in-process pipes with fault injection,
reference overlay/test/LoopbackPeer.h) and TCPTransport (real sockets,
reference TCPPeer.cpp). Both deliver whole XDR frames.
"""

from __future__ import annotations

from typing import Optional

from ..crypto.hashing import hmac_sha256, hmac_sha256_verify, sha256
from ..util import rnd
from ..util.log import get_logger
from ..xdr import (
    Auth, AuthenticatedMessage, AuthenticatedMessageV0, DontHave, Error,
    ErrorCode, Hello, MessageType, PeerAddress, SCPQuorumSet, StellarMessage,
)
from .peer_auth import PeerRole

log = get_logger("Overlay")


class PeerState:
    CONNECTING = 0
    CONNECTED = 1
    GOT_HELLO = 2
    GOT_AUTH = 3
    CLOSING = 4


class Peer:
    def __init__(self, app, overlay, transport,
                 role: int, address: Optional[tuple] = None) -> None:
        self.app = app
        self.overlay = overlay
        self.transport = transport
        self.role = role
        self.address = address            # (host, port) when known
        self.state = (PeerState.CONNECTING if role == PeerRole.WE_CALLED_REMOTE
                      else PeerState.CONNECTED)
        self.peer_id = None               # remote NodeID (PublicKey)
        self.remote_overlay_version = 0
        self.remote_version_str = ""
        self.remote_listening_port = 0
        self.local_nonce = rnd.rand_bytes(32)
        self.remote_nonce = b""
        self.send_mac_key = b""
        self.recv_mac_key = b""
        self.send_mac_seq = 0
        self.recv_mac_seq = 0
        self.last_read = app.clock.now()
        self.last_write = app.clock.now()
        self.last_empty_write = app.clock.now()
        self.messages_read = 0
        self.messages_written = 0
        self.bytes_read = 0
        self.bytes_written = 0
        self.connected_at = app.clock.now()
        self.dropped = False
        self.ever_authenticated = False
        # wire cockpit (ISSUE 10): per-message-type byte accounting on
        # both directions (docs/observability.md#overlay-cockpit)
        self._stats = getattr(overlay, "stats", None)
        # propagation cockpit (ISSUE 17): MAC-layer duplicate frames of
        # flooded types are redundant edges too — recorded here so
        # injected transport duplicates land in the same edge class the
        # Floodgate attributes (docs/observability.md#propagation-cockpit)
        self._prop = getattr(overlay, "prop_stats", None)
        # the last authenticated frame, for MAC-layer duplicate
        # detection (ChaosTransport overlay.duplicate injection)
        self._last_frame_seq: Optional[int] = None
        self._last_frame_mac = b""
        transport.on_frame = self._on_frame
        transport.on_closed = self._on_closed

    # -- identity ------------------------------------------------------------
    def id_str(self) -> str:
        if self.peer_id is not None:
            from ..crypto import strkey
            return strkey.encode_public_key(self.peer_id.value)[:8]
        return "peer@%s" % (self.address,)

    def is_authenticated(self) -> bool:
        return self.state == PeerState.GOT_AUTH

    # -- lifecycle -----------------------------------------------------------
    def connect_handshake(self) -> None:
        """Outbound side: transport is up, start with Hello."""
        self.state = PeerState.CONNECTED
        self.send_hello()

    def drop(self, reason: str = "", send_error: Optional[int] = None) -> None:
        if self.dropped:
            return
        if send_error is not None and self.state >= PeerState.CONNECTED:
            try:
                self.send_message(StellarMessage(
                    MessageType.ERROR_MSG,
                    Error(code=send_error, msg=reason[:100])))
            except Exception:
                pass
        self.dropped = True
        self.state = PeerState.CLOSING
        if reason:
            log.debug("dropping peer %s: %s", self.id_str(), reason)
        self.transport.close()
        self.overlay.remove_peer(self)

    def _on_closed(self) -> None:
        if not self.dropped:
            self.dropped = True
            self.state = PeerState.CLOSING
            self.overlay.remove_peer(self)

    # -- send path -----------------------------------------------------------
    def send_message(self, msg: StellarMessage) -> None:
        if self.dropped:
            return
        t = msg.disc
        if t in (MessageType.HELLO, MessageType.ERROR_MSG):
            am = AuthenticatedMessageV0(sequence=0, message=msg,
                                        mac=b"\x00" * 32)
        else:
            seq = self.send_mac_seq
            self.send_mac_seq += 1
            import struct
            mac = hmac_sha256(self.send_mac_key,
                              struct.pack(">Q", seq) + msg.to_xdr())
            am = AuthenticatedMessageV0(sequence=seq, message=msg, mac=mac)
        raw = AuthenticatedMessage(0, am).to_xdr()
        self.bytes_written += len(raw)
        self.messages_written += 1
        self.last_write = self.app.clock.now()
        key = self.peer_id.key_bytes if self.peer_id is not None else None
        if self._stats is not None:
            self._stats.record_send(t, len(raw), key)
        if self.peer_id is not None:
            # sent bytes feed the same per-peer cost vector the receive
            # path already feeds (reference LoadManager symmetry)
            self.overlay.load_manager.record_sent(
                self.peer_id.to_xdr(), len(raw))
        self.transport.send_frame(raw)

    def send_hello(self) -> None:
        cfg = self.app.config
        auth = self.overlay.peer_auth
        hello = Hello(
            ledgerVersion=cfg.LEDGER_PROTOCOL_VERSION,
            overlayVersion=cfg.OVERLAY_PROTOCOL_VERSION,
            overlayMinVersion=cfg.OVERLAY_PROTOCOL_MIN_VERSION,
            networkID=cfg.network_id,
            versionStr=cfg.VERSION_STR,
            listeningPort=cfg.PEER_PORT,
            peerID=cfg.node_id(),
            cert=auth.get_auth_cert(),
            nonce=self.local_nonce)
        self.send_message(StellarMessage(MessageType.HELLO, hello))

    def send_auth(self) -> None:
        self.send_message(StellarMessage(MessageType.AUTH, Auth(unused=0)))

    def send_dont_have(self, msg_type: int, item_hash: bytes) -> None:
        self.send_message(StellarMessage(
            MessageType.DONT_HAVE,
            DontHave(type=msg_type, reqHash=item_hash)))

    def send_peers(self) -> None:
        addrs = self.overlay.peer_manager.peers_to_send(50)
        if addrs:
            self.send_message(StellarMessage(MessageType.PEERS, addrs))

    # -- receive path --------------------------------------------------------
    def _on_frame(self, raw: bytes) -> None:
        if self.dropped:
            return
        self.bytes_read += len(raw)
        self.messages_read += 1
        self.last_read = self.app.clock.now()
        try:
            am = AuthenticatedMessage.from_xdr(raw)
        except Exception:
            if self._stats is not None:
                self._stats.record_recv(
                    None, len(raw),
                    self.peer_id.key_bytes if self.peer_id else None)
            self.drop("malformed frame")
            return
        v0 = am.value
        msg = v0.message
        t = msg.disc
        if self._stats is not None:
            self._stats.record_recv(
                t, len(raw),
                self.peer_id.key_bytes if self.peer_id else None)
        if t not in (MessageType.HELLO, MessageType.ERROR_MSG):
            if self.state < PeerState.GOT_HELLO:
                self.drop("message before handshake")
                return
            import struct
            data = struct.pack(">Q", v0.sequence) + msg.to_xdr()
            if v0.sequence != self.recv_mac_seq or not hmac_sha256_verify(
                    self.recv_mac_key, data, v0.mac):
                # a byte-identical replay of the PREVIOUS frame is a
                # transport-level duplicate (ChaosTransport
                # overlay.duplicate, or a duplicating network) — count
                # it into the duplication ratio and drop the FRAME, not
                # the link (the MAC chain proves it's a copy, not a
                # forgery)
                if v0.sequence == self._last_frame_seq and \
                        v0.mac == self._last_frame_mac and \
                        hmac_sha256_verify(self.recv_mac_key, data, v0.mac):
                    flooded = t in (MessageType.TRANSACTION,
                                    MessageType.SCP_MESSAGE)
                    if self._stats is not None:
                        self._stats.record_duplicate_frame(
                            t, flooded=flooded)
                    if self._prop is not None and flooded and \
                            self.peer_id is not None:
                        # the duplicate never reaches the Floodgate (the
                        # frame is dropped here), so stamp its redundant
                        # edge directly — wasted bytes attributed to the
                        # replaying peer
                        raw_msg = msg.to_xdr()
                        self._prop.record_recv_hop(
                            sha256(raw_msg), self.peer_id.key_bytes.hex(),
                            len(raw_msg), t, False,
                            self.app.ledger_manager.last_closed_ledger_num())
                    return
                self.drop("unexpected MAC/sequence",
                          send_error=ErrorCode.ERR_AUTH)
                return
            self._last_frame_seq = v0.sequence
            self._last_frame_mac = v0.mac
            self.recv_mac_seq += 1
        try:
            if self.peer_id is not None:
                # per-peer cost accounting (reference LoadManager contexts)
                lm = self.overlay.load_manager
                with lm.context(self.peer_id.to_xdr()):
                    self._dispatch(msg)
                lm.record_bytes(self.peer_id.to_xdr(), 0, len(raw))
            else:
                self._dispatch(msg)
        except Exception as e:       # noqa: BLE001 — peer input is hostile
            log.warning("error handling %d from %s: %s", t, self.id_str(), e)
            self.drop("internal error handling message")

    def _dispatch(self, msg: StellarMessage) -> None:
        t = msg.disc
        if t == MessageType.HELLO:
            self._recv_hello(msg.value)
            return
        if t == MessageType.ERROR_MSG:
            log.debug("peer %s sent error %d: %s", self.id_str(),
                      msg.value.code, msg.value.msg)
            self.drop("peer error")
            return
        if t == MessageType.AUTH:
            self._recv_auth()
            return
        if not self.is_authenticated():
            self.drop("message before auth", send_error=ErrorCode.ERR_AUTH)
            return
        herder = self.app.herder
        if t == MessageType.DONT_HAVE:
            self.overlay.recv_dont_have(self, msg.value)
        elif t == MessageType.GET_PEERS:
            self.send_peers()
        elif t == MessageType.PEERS:
            self.overlay.peer_manager.recv_peers(msg.value)
        elif t == MessageType.GET_TX_SET:
            ts = herder.pending.get_tx_set(msg.value)
            if ts is not None:
                self.send_message(StellarMessage(MessageType.TX_SET,
                                                 ts.to_wire()))
            else:
                self.send_dont_have(MessageType.TX_SET, msg.value)
        elif t == MessageType.TX_SET:
            from ..herder.txset import TxSetFrame
            frame = TxSetFrame.from_wire(self.app.config.network_id,
                                         msg.value)
            h = frame.get_contents_hash()
            herder.recv_tx_set(h, frame)
            self.overlay.item_fetched_txset(h)
        elif t == MessageType.TRANSACTION:
            if self.overlay.flood_rate_limited(self):
                # over the per-peer flood rate: dropped before any
                # validation or relay (docs/robustness.md#flood-control)
                return
            self.overlay.recv_flooded_msg(msg, self)
            from ..transactions.transaction_frame import TransactionFrame
            frame = TransactionFrame.make_from_wire(
                self.app.config.network_id, msg.value)
            status = herder.recv_transaction(frame)
            if status == 0:
                self.overlay.broadcast_message(msg)
            elif status == 3:
                # ingress backpressure on a relayed tx: not relayed
                # further, and the sender scores a fractional flood-ban
                # point (docs/robustness.md#ingress--overload)
                self.overlay.flood_backpressure(self)
        elif t == MessageType.GET_SCP_QUORUMSET:
            q = self._lookup_qset(msg.value)
            if q is not None:
                self.send_message(StellarMessage(MessageType.SCP_QUORUMSET, q))
            else:
                self.send_dont_have(MessageType.SCP_QUORUMSET, msg.value)
        elif t == MessageType.SCP_QUORUMSET:
            h = sha256(msg.value.to_xdr())
            herder.recv_scp_quorum_set(h, msg.value)
            self.overlay.item_fetched_qset(h)
        elif t == MessageType.SCP_MESSAGE:
            if self.overlay.flood_rate_limited(self):
                return
            self.overlay.recv_flooded_msg(msg, self)
            # only relay envelopes that verified (reference Peer.cpp
            # rebroadcasts unless the herder discarded the envelope); with
            # an async batch backend the flood is deferred until the
            # device batch completes on the main loop
            herder.recv_scp_envelope(
                msg.value,
                on_verified=lambda ok:
                    self.overlay.broadcast_message(msg) if ok else None)
        elif t == MessageType.GET_SCP_STATE:
            self._send_scp_state(msg.value)
        elif t in (MessageType.SURVEY_REQUEST, MessageType.SURVEY_RESPONSE):
            sm = getattr(self.overlay, "survey_manager", None)
            if sm is not None:
                sm.relay_or_process(msg, self)
        else:
            self.drop("unexpected message type %d" % t)

    def _lookup_qset(self, h: bytes) -> Optional[SCPQuorumSet]:
        herder = self.app.herder
        q = herder.pending.get_quorum_set(h)
        if q is not None:
            return q
        local = self.app.config.QUORUM_SET
        if local is not None and sha256(local.to_xdr()) == h:
            return local
        return None

    def _send_scp_state(self, ledger_seq: int) -> None:
        """Send our SCP state for slots >= seq (reference
        HerderImpl::sendSCPStateToPeer)."""
        herder = self.app.herder
        sent = 0
        for slot_index in sorted(herder.scp.known_slots):
            if ledger_seq and slot_index < ledger_seq:
                continue
            for env in herder.scp.get_current_state(slot_index):
                self.send_message(StellarMessage(MessageType.SCP_MESSAGE,
                                                 env))
                sent += 1
                if sent > 100:
                    return

    # -- handshake -----------------------------------------------------------
    def _recv_hello(self, hello: Hello) -> None:
        if self.state >= PeerState.GOT_HELLO:
            self.drop("duplicate HELLO")
            return
        cfg = self.app.config
        auth = self.overlay.peer_auth
        if hello.networkID != cfg.network_id:
            self.drop("wrong network", send_error=ErrorCode.ERR_CONF)
            return
        if hello.overlayVersion < cfg.OVERLAY_PROTOCOL_MIN_VERSION or \
                hello.overlayMinVersion > cfg.OVERLAY_PROTOCOL_VERSION:
            self.drop("incompatible overlay version",
                      send_error=ErrorCode.ERR_CONF)
            return
        if hello.peerID == cfg.node_id():
            self.drop("connecting to self", send_error=ErrorCode.ERR_CONF)
            return
        if not auth.verify_remote_cert(hello.peerID, hello.cert):
            self.drop("bad auth cert", send_error=ErrorCode.ERR_AUTH)
            return
        if self.overlay.ban_manager.is_banned(hello.peerID):
            self.drop("banned", send_error=ErrorCode.ERR_CONF)
            return
        self.peer_id = hello.peerID
        self.remote_nonce = hello.nonce
        self.remote_overlay_version = hello.overlayVersion
        self.remote_version_str = hello.versionStr
        self.remote_listening_port = hello.listeningPort
        we_called = (self.role == PeerRole.WE_CALLED_REMOTE)
        self.send_mac_key = auth.get_sending_mac_key(
            hello.cert.pubkey, self.local_nonce, self.remote_nonce, we_called)
        self.recv_mac_key = auth.get_receiving_mac_key(
            hello.cert.pubkey, self.local_nonce, self.remote_nonce, we_called)
        self.state = PeerState.GOT_HELLO
        if self.role == PeerRole.REMOTE_CALLED_US:
            self.send_hello()
        else:
            self.send_auth()

    def _recv_auth(self) -> None:
        if self.state != PeerState.GOT_HELLO:
            self.drop("AUTH out of order", send_error=ErrorCode.ERR_MISC)
            return
        self.state = PeerState.GOT_AUTH
        if self.role == PeerRole.REMOTE_CALLED_US:
            self.send_auth()
        if not self.overlay.accept_authenticated_peer(self):
            return
        self.send_message(StellarMessage(MessageType.GET_PEERS, None))
        # pull the peer's current SCP state so a late joiner (or a network
        # whose first nominations flooded into the void) catches up
        # (reference Peer.cpp sendGetScpState on auth completion)
        try:
            lcl = self.app.ledger_manager.last_closed_ledger_num()
        except Exception:
            lcl = 0                  # node not started yet: ask for all
        self.send_message(StellarMessage(MessageType.GET_SCP_STATE, lcl))
