"""Overlay transports: loopback pipes and real TCP sockets.

Role parity:
- LoopbackTransport ↔ reference `src/overlay/test/LoopbackPeer.{h,cpp}`:
  paired in-memory queues between two Applications, with the same fault
  knobs (drop/damage/duplicate/reorder probabilities) used by flood and
  herder tests.
- TCPTransport/TCPDoor ↔ reference `src/overlay/TCPPeer.cpp` +
  `PeerDoor.cpp`: length-framed XDR over asio sockets. Here a per-overlay
  reactor thread owns the sockets (the asio io thread role) and posts
  complete frames to the owning Application's VirtualClock via
  post_to_main, preserving the single-threaded consensus contract.

Framing: 4-byte big-endian record mark with the high bit set (single
fragment), matching the project's XDR stream framing.
"""

from __future__ import annotations

import errno
import socket
import struct
import threading
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple

from ..util import rnd
from ..util.log import get_logger

log = get_logger("Overlay")

_LAST_FRAG = 0x80000000
MAX_FRAME = 0x2000000        # 32 MiB hard cap on one message


class Transport:
    """Frame pipe interface: owner assigns on_frame/on_closed callbacks."""

    on_frame: Callable[[bytes], None]
    on_closed: Callable[[], None]

    def send_frame(self, raw: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class LoopbackTransport(Transport):
    """One end of an in-process pipe. Delivery is posted onto the RECEIVING
    side's clock so each node only touches its own state on its own crank
    (the simulation lock-step contract)."""

    def __init__(self, clock) -> None:
        self.clock = clock                 # receiving side's clock
        self.other: Optional["LoopbackTransport"] = None
        self.on_frame = lambda raw: None
        self.on_closed = lambda: None
        self.closed = False
        # fault injection on the SENDING side (reference LoopbackPeer.h:35-46)
        self.drop_probability = 0.0
        self.damage_probability = 0.0
        self.duplicate_probability = 0.0
        self.reorder_probability = 0.0
        self._reorder_held: Optional[bytes] = None

    @classmethod
    def pair(cls, clock_a, clock_b
             ) -> Tuple["LoopbackTransport", "LoopbackTransport"]:
        a, b = cls(clock_a), cls(clock_b)
        a.other, b.other = b, a
        return a, b

    def send_frame(self, raw: bytes) -> None:
        if self.closed or self.other is None:
            return
        r = rnd.g_random
        if self.drop_probability and r.random() < self.drop_probability:
            return
        if self.damage_probability and r.random() < self.damage_probability:
            buf = bytearray(raw)
            buf[r.randrange(len(buf))] ^= 0xFF
            raw = bytes(buf)
        frames = [raw]
        if self.duplicate_probability and \
                r.random() < self.duplicate_probability:
            frames.append(raw)
        if self.reorder_probability and r.random() < self.reorder_probability \
                and self._reorder_held is None:
            self._reorder_held = raw
            return
        if self._reorder_held is not None:
            frames.append(self._reorder_held)
            self._reorder_held = None
        other = self.other
        for f in frames:
            other.clock.post(lambda f=f: other._deliver(f))

    def _deliver(self, raw: bytes) -> None:
        if not self.closed:
            self.on_frame(raw)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        other = self.other
        if other is not None and not other.closed:
            other.clock.post(other._closed_by_peer)

    def _closed_by_peer(self) -> None:
        if not self.closed:
            self.closed = True
            self.on_closed()


class TCPReactor:
    """Minimal socket reactor thread (the asio io-thread role): reads frames
    off nonblocking sockets, posts them to the main clock; drains per-socket
    write queues; accepts inbound connections."""

    def __init__(self, clock) -> None:
        self.clock = clock
        self._lock = threading.Lock()
        self._transports: Dict[socket.socket, "TCPTransport"] = {}
        self._doors: Dict[socket.socket, Callable] = {}
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="overlay-io", daemon=True)
            self._thread.start()

    def wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def add_transport(self, t: "TCPTransport") -> None:
        with self._lock:
            self._transports[t.sock] = t
        self.wake()

    def remove_transport(self, t: "TCPTransport") -> None:
        with self._lock:
            self._transports.pop(t.sock, None)
        self.wake()

    def add_door(self, sock: socket.socket,
                 on_accept: Callable[[socket.socket, tuple], None]) -> None:
        with self._lock:
            self._doors[sock] = on_accept
        self.wake()

    def stop(self) -> None:
        self._stopped = True
        self.wake()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        with self._lock:
            for s in list(self._doors):
                try:
                    s.close()
                except OSError:
                    pass
            self._doors.clear()

    def _run(self) -> None:
        import select
        while not self._stopped:
            with self._lock:
                transports = dict(self._transports)
                doors = dict(self._doors)
            rlist = [self._wake_r] + list(doors) + list(transports)
            wlist = [s for s, t in transports.items() if t.wants_write()]
            try:
                r, w, _ = select.select(rlist, wlist, [], 0.25)
            except (OSError, ValueError):
                # a socket was closed mid-select; drop dead entries
                with self._lock:
                    for s in list(self._transports):
                        if s.fileno() < 0:
                            del self._transports[s]
                continue
            for s in r:
                if s is self._wake_r:
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except OSError:
                        pass
                elif s in doors:
                    try:
                        conn, addr = s.accept()
                        conn.setblocking(False)
                        conn.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                        doors[s](conn, addr)
                    except OSError:
                        pass
                else:
                    t = transports.get(s)
                    if t is not None:
                        t.handle_read()
            for s in w:
                t = transports.get(s)
                if t is not None:
                    t.handle_write()


class TCPTransport(Transport):
    def __init__(self, reactor: TCPReactor, sock: socket.socket) -> None:
        self.reactor = reactor
        self.sock = sock
        self.on_frame = lambda raw: None
        self.on_closed = lambda: None
        self.closed = False
        self._rbuf = b""
        self._wlock = threading.Lock()
        self._wqueue: Deque[bytes] = deque()

    @classmethod
    def connect(cls, reactor: TCPReactor, host: str,
                port: int) -> "TCPTransport":
        sock = socket.create_connection((host, port), timeout=5.0)
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        t = cls(reactor, sock)
        reactor.add_transport(t)
        return t

    def wants_write(self) -> bool:
        with self._wlock:
            return bool(self._wqueue)

    def send_frame(self, raw: bytes) -> None:
        if self.closed:
            return
        with self._wlock:
            self._wqueue.append(struct.pack(">I", len(raw) | _LAST_FRAG) + raw)
        self.reactor.wake()

    def handle_write(self) -> None:
        with self._wlock:
            while self._wqueue:
                buf = self._wqueue[0]
                try:
                    n = self.sock.send(buf)
                except OSError as e:
                    if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                        return
                    self._fail()
                    return
                if n < len(buf):
                    self._wqueue[0] = buf[n:]
                    return
                self._wqueue.popleft()

    def handle_read(self) -> None:
        try:
            data = self.sock.recv(65536)
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return
            self._fail()
            return
        if not data:
            self._fail()
            return
        self._rbuf += data
        while len(self._rbuf) >= 4:
            n = struct.unpack(">I", self._rbuf[:4])[0]
            if not (n & _LAST_FRAG):
                self._fail()
                return
            n &= ~_LAST_FRAG
            if n > MAX_FRAME:
                self._fail()
                return
            if len(self._rbuf) < 4 + n:
                break
            frame = self._rbuf[4:4 + n]
            self._rbuf = self._rbuf[4 + n:]
            self.reactor.clock.post_to_main(
                lambda f=frame: None if self.closed else self.on_frame(f))

    def _fail(self) -> None:
        if self.closed:
            return
        self.reactor.remove_transport(self)
        try:
            self.sock.close()
        except OSError:
            pass
        self.reactor.clock.post_to_main(self._notify_closed)

    def _notify_closed(self) -> None:
        if not self.closed:
            self.closed = True
            self.on_closed()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self.reactor.remove_transport(self)
        try:
            self.sock.close()
        except OSError:
            pass


class TCPDoor:
    """Listening socket (reference PeerDoor.cpp): accepts inbound
    connections and hands sockets to the overlay manager on the main
    thread."""

    def __init__(self, reactor: TCPReactor, port: int,
                 on_connection: Callable) -> None:
        self.reactor = reactor
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", port))
        self.port = self.sock.getsockname()[1]
        self.sock.listen(16)
        self.sock.setblocking(False)

        def accepted(conn: socket.socket, addr: tuple) -> None:
            t = TCPTransport(reactor, conn)
            reactor.add_transport(t)
            reactor.clock.post_to_main(lambda: on_connection(t, addr))

        reactor.add_door(self.sock, accepted)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
