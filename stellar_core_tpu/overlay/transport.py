"""Overlay transports: loopback pipes and real TCP sockets.

Role parity:
- LoopbackTransport ↔ reference `src/overlay/test/LoopbackPeer.{h,cpp}`:
  paired in-memory queues between two Applications, with the same fault
  knobs (drop/damage/duplicate/reorder probabilities) used by flood and
  herder tests.
- TCPTransport/TCPDoor ↔ reference `src/overlay/TCPPeer.cpp` +
  `PeerDoor.cpp`: length-framed XDR over asio sockets. Here a per-overlay
  reactor thread owns the sockets (the asio io thread role) and posts
  complete frames to the owning Application's VirtualClock via
  post_to_main, preserving the single-threaded consensus contract.

Framing: 4-byte big-endian record mark with the high bit set (single
fragment), matching the project's XDR stream framing.
"""

from __future__ import annotations

import errno
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple

from ..util import rnd
from ..util.log import get_logger
from ..util.threads import TrackedLock
from ..util.timer import VirtualTimer

log = get_logger("Overlay")

_LAST_FRAG = 0x80000000
MAX_FRAME = 0x2000000        # 32 MiB hard cap on one message


class Transport:
    """Frame pipe interface: owner assigns on_frame/on_closed callbacks."""

    on_frame: Callable[[bytes], None]
    on_closed: Callable[[], None]

    def send_frame(self, raw: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def oldest_unsent_age(self) -> float:
        """Seconds the oldest enqueued-but-unsent frame has been waiting
        (0 when the send queue is drained). Drives the straggler timeout
        (reference Peer::idleTimerExpired mEnqueueTimeOfLastWrite check)."""
        return 0.0


class LoopbackTransport(Transport):
    """One end of an in-process pipe. Delivery is posted onto the RECEIVING
    side's clock so each node only touches its own state on its own crank
    (the simulation lock-step contract)."""

    def __init__(self, clock) -> None:
        self.clock = clock                 # receiving side's clock
        self.other: Optional["LoopbackTransport"] = None
        self.on_frame = lambda raw: None
        self.on_closed = lambda: None
        self.closed = False
        # fault injection on the SENDING side (reference LoopbackPeer.h:35-46)
        self.drop_probability = 0.0
        self.damage_probability = 0.0
        self.duplicate_probability = 0.0
        self.reorder_probability = 0.0
        self._reorder_held: Optional[bytes] = None

    @classmethod
    def pair(cls, clock_a, clock_b
             ) -> Tuple["LoopbackTransport", "LoopbackTransport"]:
        a, b = cls(clock_a), cls(clock_b)
        a.other, b.other = b, a
        return a, b

    def send_frame(self, raw: bytes) -> None:
        if self.closed or self.other is None:
            return
        r = rnd.g_random
        if self.drop_probability and r.random() < self.drop_probability:
            return
        if self.damage_probability and r.random() < self.damage_probability:
            buf = bytearray(raw)
            buf[r.randrange(len(buf))] ^= 0xFF
            raw = bytes(buf)
        frames = [raw]
        if self.duplicate_probability and \
                r.random() < self.duplicate_probability:
            frames.append(raw)
        if self.reorder_probability and r.random() < self.reorder_probability \
                and self._reorder_held is None:
            self._reorder_held = raw
            return
        if self._reorder_held is not None:
            frames.append(self._reorder_held)
            self._reorder_held = None
        other = self.other
        for f in frames:
            other.clock.post(lambda f=f: other._deliver(f))

    def _deliver(self, raw: bytes) -> None:
        if not self.closed:
            self.on_frame(raw)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        other = self.other
        if other is not None and not other.closed:
            other.clock.post(other._closed_by_peer)

    def _closed_by_peer(self) -> None:
        if not self.closed:
            self.closed = True
            self.on_closed()


class ChaosTransport(Transport):
    """Fault-injecting wrapper around another Transport (loopback pipes in
    simulations, but any Transport works): drop / delay / duplicate /
    reorder outbound frames by seeded FaultInjector schedule
    (`overlay.drop` / `overlay.delay` / `overlay.duplicate` /
    `overlay.reorder` sites, util/faults.py), plus a hard `partitioned`
    toggle that severs BOTH directions until healed — the knob the chaos
    soak uses to run a partition-and-heal scenario. The owning Peer sees
    a normal Transport; all chaos happens underneath it."""

    # delay applied to frames the `overlay.delay` site selects; virtual
    # seconds in simulations
    delay_s = 0.25
    # deterministic geographic base delay applied to EVERY outbound frame
    # (seconds on the sender's clock) — fed by the simulation's seeded
    # per-link latency matrix (simulation/geography.py); 0 = co-located
    link_delay_s = 0.0

    def __init__(self, inner: Transport, clock, faults=None,
                 site_prefix: str = "overlay") -> None:
        self.inner = inner
        self.clock = clock            # the owning (sending) side's clock
        self.faults = faults
        self.site_prefix = site_prefix
        self.partitioned = False
        self.dropped = 0              # frames eaten (faults + partition)
        self.delayed = 0
        self.on_frame = lambda raw: None
        self.on_closed = lambda: None
        self._reorder_held: Optional[bytes] = None
        inner.on_frame = self._rx
        inner.on_closed = lambda: self.on_closed()

    def _fire(self, site: str) -> bool:
        from ..util.faults import check_faults
        return check_faults(self, self.site_prefix + "." + site)

    def send_frame(self, raw: bytes) -> None:
        if self.partitioned or self._fire("drop"):
            self.dropped += 1
            return
        frames = [raw]
        if self._fire("duplicate"):
            frames.append(raw)
        if self._fire("reorder") and self._reorder_held is None:
            # hold this frame; it rides behind the NEXT send
            self._reorder_held = raw
            return
        if self._reorder_held is not None:
            frames.append(self._reorder_held)
            self._reorder_held = None
        for f in frames:
            wait = self.link_delay_s
            if self._fire("delay"):
                self.delayed += 1
                wait += self.delay_s
            if wait > 0:
                t = VirtualTimer(self.clock)
                t.expires_from_now(wait)
                t.async_wait(lambda f=f: self._send_now(f))
            else:
                self._send_now(f)

    def _send_now(self, raw: bytes) -> None:
        # re-check the partition at (delayed) delivery time: a frame held
        # over a partition start must not leak through
        if not self.partitioned:
            self.inner.send_frame(raw)
        else:
            self.dropped += 1

    def _rx(self, raw: bytes) -> None:
        if self.partitioned:
            self.dropped += 1
            return
        self.on_frame(raw)

    def set_partitioned(self, on: bool) -> None:
        self.partitioned = on

    def close(self) -> None:
        self.inner.close()

    def oldest_unsent_age(self) -> float:
        return self.inner.oldest_unsent_age()

    @property
    def closed(self) -> bool:
        return getattr(self.inner, "closed", False)


class TCPReactor:
    """Minimal socket reactor thread (the asio io-thread role): reads frames
    off nonblocking sockets, posts them to the main clock; drains per-socket
    write queues; accepts inbound connections."""

    def __init__(self, clock) -> None:
        self.clock = clock
        self._lock = TrackedLock("overlay.reactor")
        self._transports: Dict[socket.socket, "TCPTransport"] = {}
        self._doors: Dict[socket.socket, Callable] = {}
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="overlay-io", daemon=True)
            self._thread.start()

    def wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def add_transport(self, t: "TCPTransport") -> None:
        with self._lock:
            self._transports[t.sock] = t
        self.wake()

    def remove_transport(self, t: "TCPTransport") -> None:
        with self._lock:
            self._transports.pop(t.sock, None)
        self.wake()

    def add_door(self, sock: socket.socket,
                 on_accept: Callable[[socket.socket, tuple], None]) -> None:
        with self._lock:
            self._doors[sock] = on_accept
        self.wake()

    def stop(self) -> None:
        self._stopped = True
        self.wake()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        with self._lock:
            for s in list(self._doors):
                try:
                    s.close()
                except OSError:
                    pass
            self._doors.clear()

    def _run(self) -> None:
        import select
        while not self._stopped:
            with self._lock:
                transports = dict(self._transports)
                doors = dict(self._doors)
            # in-progress connects: fail the ones past their deadline; the
            # rest are watched for writability (= connect completion)
            now = time.monotonic()
            for t in transports.values():
                if t.connecting and now > t.connect_deadline:
                    t._fail()
            rlist = [self._wake_r] + list(doors) + \
                [s for s, t in transports.items() if not t.connecting]
            wlist = [s for s, t in transports.items() if t.wants_write()]
            try:
                r, w, _ = select.select(rlist, wlist, [], 0.25)
            except (OSError, ValueError):
                # a socket was closed mid-select; drop dead entries
                with self._lock:
                    for s in list(self._transports):
                        if s.fileno() < 0:
                            del self._transports[s]
                continue
            for s in r:
                if s is self._wake_r:
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except OSError:
                        pass
                elif s in doors:
                    try:
                        conn, addr = s.accept()
                        conn.setblocking(False)
                        conn.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                        doors[s](conn, addr)
                    except OSError:
                        pass
                else:
                    t = transports.get(s)
                    if t is not None:
                        t.handle_read()
            for s in w:
                t = transports.get(s)
                if t is not None:
                    t.handle_write()


class TCPTransport(Transport):
    # write batching limits (reference Config MAX_BATCH_WRITE_COUNT/BYTES;
    # the overlay manager overrides these from its Config)
    max_batch_write_count = 1024
    max_batch_write_bytes = 1024 * 1024
    # hard cap on queued-but-unsent bytes: exceeding it drops the
    # connection (a peer this far behind is a straggler, and an unbounded
    # queue lets a stuck reader consume all memory)
    send_queue_limit_bytes = 32 * 1024 * 1024
    connect_timeout = 5.0
    # observability/fault wiring, installed by the overlay manager
    # (_apply_transport_limits); both optional — raw transports work bare
    metrics = None
    faults = None

    def __init__(self, reactor: TCPReactor, sock: socket.socket) -> None:
        self.reactor = reactor
        self.sock = sock
        self.on_frame = lambda raw: None
        self.on_closed = lambda: None
        self.closed = False
        self._failed = False
        self._rbuf = b""
        self._wlock = threading.Lock()
        # (framed bytes, enqueue monotonic ts) pairs not yet batched
        self._wqueue: Deque[Tuple[bytes, float]] = deque()
        self._wqueue_bytes = 0
        # coalesced in-flight batch (prefix of the former queue)
        self._wbatch: Optional[memoryview] = None
        self._wbatch_head_ts = 0.0
        self.connecting = False
        self.connect_deadline = 0.0

    @classmethod
    def connect(cls, reactor: TCPReactor, host: str,
                port: int) -> "TCPTransport":
        """Begin a NON-blocking connect; the reactor completes it (connect
        success = writable, failure = SO_ERROR / deadline). Frames queued
        meanwhile flush once connected. The caller never blocks (reference
        TCPPeer::initiate asio async_connect)."""
        # numeric addresses (either family) resolve without blocking; a
        # hostname falls back to a blocking getaddrinfo, as the previous
        # create_connection-based dial also did
        try:
            infos = socket.getaddrinfo(
                host, port, type=socket.SOCK_STREAM,
                flags=socket.AI_NUMERICHOST)
        except socket.gaierror:
            infos = socket.getaddrinfo(host, port, type=socket.SOCK_STREAM)
        # try each resolved address for an immediately-failing dial
        # (create_connection's fallback role); an address that fails only
        # asynchronously is retried via the peer-table backoff
        sock = None
        err = 0
        for family, stype, proto, _cn, addr in infos:
            try:
                sock = socket.socket(family, stype, proto)
            except OSError:
                continue
            sock.setblocking(False)
            err = sock.connect_ex(addr)
            if err in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
                break
            sock.close()
            sock = None
        if sock is None:
            raise OSError(err, "connect to %s:%d: %s"
                          % (host, port, errno.errorcode.get(err, err)))
        t = cls(reactor, sock)
        t.connecting = err != 0
        t.connect_deadline = time.monotonic() + cls.connect_timeout
        if not t.connecting:
            t._connected()
        reactor.add_transport(t)
        return t

    def _connected(self) -> None:
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass

    def wants_write(self) -> bool:
        if self.connecting:
            return True
        with self._wlock:
            return self._wbatch is not None or bool(self._wqueue)

    def oldest_unsent_age(self) -> float:
        with self._wlock:
            if self._wbatch is not None:
                return time.monotonic() - self._wbatch_head_ts
            if self._wqueue:
                return time.monotonic() - self._wqueue[0][1]
        return 0.0

    def send_frame(self, raw: bytes) -> None:
        from ..util.faults import check_faults
        framed = struct.pack(">I", len(raw) | _LAST_FRAG) + raw
        with self._wlock:
            # closed/_failed must be read under the lock: a frame racing
            # _fail()'s queue-clear would otherwise pin bytes on a dead
            # transport forever
            if self.closed or self._failed:
                return
            self._wqueue.append((framed, time.monotonic()))
            self._wqueue_bytes += len(framed)
            overflow = self._wqueue_bytes > self.send_queue_limit_bytes
        # fault site: force the overflow path without queuing 32 MB
        # (docs/robustness.md#fault-points)
        if not overflow and check_faults(self, "overlay.send-overflow"):
            overflow = True
        if overflow:
            # a stalled reader must not pin send_queue_limit_bytes per
            # peer indefinitely: count it and drop the connection
            log.warning("send queue overflow (> %d bytes), dropping peer",
                        self.send_queue_limit_bytes)
            if self.metrics is not None:
                self.metrics.new_meter("overlay.send-queue.overflow").mark()
            self._fail()
            return
        self.reactor.wake()

    def handle_write(self) -> None:
        if self.connecting:
            err = self.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err != 0:
                self._fail()
                return
            self.connecting = False
            self._connected()
        failed = False
        with self._wlock:
            while not failed:
                if self._wbatch is None:
                    if not self._wqueue:
                        break
                    # coalesce a queue prefix into ONE send, bounded by
                    # the batch limits (reference TCPPeer::messageSender
                    # scatter-gather snapshot, TCPPeer.cpp:225-267)
                    bufs = []
                    total = 0
                    self._wbatch_head_ts = self._wqueue[0][1]
                    while self._wqueue and \
                            len(bufs) < self.max_batch_write_count and \
                            total < self.max_batch_write_bytes:
                        b, _ts = self._wqueue.popleft()
                        bufs.append(b)
                        total += len(b)
                    self._wqueue_bytes -= total
                    self._wbatch = memoryview(b"".join(bufs))
                try:
                    n = self.sock.send(self._wbatch)
                except OSError as e:
                    if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                        break
                    failed = True   # _fail() re-takes the lock: call it
                    break           # only after leaving the locked region
                if n < len(self._wbatch):
                    self._wbatch = self._wbatch[n:]
                    break
                self._wbatch = None
        if failed:
            self._fail()

    def handle_read(self) -> None:
        try:
            data = self.sock.recv(65536)
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return
            self._fail()
            return
        if not data:
            self._fail()
            return
        self._rbuf += data
        while len(self._rbuf) >= 4:
            n = struct.unpack(">I", self._rbuf[:4])[0]
            if not (n & _LAST_FRAG):
                self._fail()
                return
            n &= ~_LAST_FRAG
            if n > MAX_FRAME:
                self._fail()
                return
            if len(self._rbuf) < 4 + n:
                break
            frame = self._rbuf[4:4 + n]
            self._rbuf = self._rbuf[4 + n:]
            self.reactor.clock.post_to_main(
                lambda f=frame: None if self.closed else self.on_frame(f))

    def _fail(self) -> None:
        with self._wlock:
            if self.closed or self._failed:
                return
            # mark failed immediately (the posted _notify_closed may not
            # run until the current main-loop handler returns) and release
            # the buffered backlog — a dead transport must neither accept
            # nor pin more bytes
            self._failed = True
            self._wqueue.clear()
            self._wqueue_bytes = 0
            self._wbatch = None
        self.reactor.remove_transport(self)
        try:
            self.sock.close()
        except OSError:
            pass
        self.reactor.clock.post_to_main(self._notify_closed)

    def _notify_closed(self) -> None:
        if not self.closed:
            self.closed = True
            self.on_closed()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self.reactor.remove_transport(self)
        try:
            self.sock.close()
        except OSError:
            pass


class TCPDoor:
    """Listening socket (reference PeerDoor.cpp): accepts inbound
    connections and hands sockets to the overlay manager on the main
    thread."""

    def __init__(self, reactor: TCPReactor, port: int,
                 on_connection: Callable) -> None:
        self.reactor = reactor
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", port))
        self.port = self.sock.getsockname()[1]
        self.sock.listen(16)
        self.sock.setblocking(False)

        def accepted(conn: socket.socket, addr: tuple) -> None:
            t = TCPTransport(reactor, conn)
            reactor.add_transport(t)
            reactor.clock.post_to_main(lambda: on_connection(t, addr))

        reactor.add_door(self.sock, accepted)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
