"""SurveyManager: authenticated network-topology survey.

Role parity: reference `src/overlay/SurveyManager.{h,cpp}` +
`SurveyMessageLimiter.cpp` — a surveyor broadcasts ed25519-signed
SURVEY_REQUEST messages naming one surveyed node each, carrying an
ephemeral curve25519 key; the surveyed node verifies, rate-limits,
encrypts its peer-topology stats to that key (sealed box), signs, and
broadcasts the SURVEY_RESPONSE back. Requests/responses are relayed by
flood, so surveys work across multi-hop topologies. Results accumulate
on the surveyor and are served via the `getsurveyresult` admin command.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..crypto.curve25519 import (curve25519_derive_public,
                                 curve25519_random_secret, curve25519_seal,
                                 curve25519_unseal)
from ..crypto.hashing import sha256
from ..crypto.keys import PubKeyUtils
from ..util.log import get_logger
from ..util.timer import VirtualTimer
from ..xdr import (MessageType, PeerStats, PublicKey,
                   SignedSurveyRequestMessage, SignedSurveyResponseMessage,
                   StellarMessage, SurveyMessageCommandType,
                   SurveyRequestMessage, SurveyResponseMessage,
                   TopologyResponseBody)

log = get_logger("Overlay")

SURVEY_THROTTLE = 0.5          # delay between backlog sends (s)
MAX_REQUESTS_PER_LEDGER = 10   # limiter: per-surveyor request budget


class SurveyManager:
    def __init__(self, app, overlay) -> None:
        self.app = app
        self.overlay = overlay
        self._timer = VirtualTimer(app.clock)
        self.running = False
        self._backlog: List[PublicKey] = []
        self._surveyed: Set[bytes] = set()
        self._secret: Optional[bytes] = None     # ephemeral x25519
        self.results: Dict[str, dict] = {}
        self.bad_responses = 0
        # limiter state: surveyor id -> requests seen this ledger
        self._limiter: Dict[bytes, int] = {}
        self._limiter_ledger = 0

    # -- surveyor side -------------------------------------------------------
    def start_survey(self, duration: float = 60.0) -> None:
        """Begin a survey of the whole known overlay (reference
        startSurvey; `surveytopology` admin command)."""
        if not self.running:
            self._secret = curve25519_random_secret()
            self.results = {}
            self._surveyed = set()
            self.running = True
        old = getattr(self, "_stop_timer", None)
        if old is not None:
            old.cancel()        # re-issue extends the deadline
        seen = set()
        for key in self.overlay.authenticated_peer_ids():
            p = self.overlay.get_peer(key)
            if p is not None and p.peer_id is not None and \
                    p.peer_id.key_bytes not in seen:
                seen.add(p.peer_id.key_bytes)
                self.add_node_to_backlog(p.peer_id)
        self._pump()
        stop_timer = VirtualTimer(self.app.clock)
        stop_timer.expires_from_now(duration)
        stop_timer.async_wait(self.stop_survey)
        self._stop_timer = stop_timer

    def add_node_to_backlog(self, node_id: PublicKey) -> None:
        if node_id.key_bytes == self._self_id().key_bytes:
            return
        if node_id.key_bytes not in self._surveyed:
            self._backlog.append(node_id)

    def stop_survey(self) -> None:
        self.running = False
        self._backlog = []

    def _self_id(self) -> PublicKey:
        return self.app.config.node_id()

    def _pump(self) -> None:
        """Send one backlogged request per throttle tick (reference
        topOffRequests)."""
        if not self.running or not self._backlog:
            return
        node = self._backlog.pop(0)
        if node.key_bytes not in self._surveyed:
            self._surveyed.add(node.key_bytes)
            self._send_request(node)
        self._timer.expires_from_now(SURVEY_THROTTLE)
        self._timer.async_wait(self._pump)

    def _send_request(self, node: PublicKey) -> None:
        req = SurveyRequestMessage(
            surveyorPeerID=self._self_id(),
            surveyedPeerID=node,
            ledgerNum=self.app.ledger_manager.last_closed_ledger_num(),
            encryptionKey=curve25519_derive_public(self._secret),
            commandType=SurveyMessageCommandType.SURVEY_TOPOLOGY)
        sig = self.app.config.NODE_SEED.sign(self._request_sign_bytes(req))
        msg = StellarMessage(
            MessageType.SURVEY_REQUEST,
            SignedSurveyRequestMessage(requestSignature=sig, request=req))
        self.overlay.broadcast_message(msg, force=True)

    def _request_sign_bytes(self, req: SurveyRequestMessage) -> bytes:
        return sha256(self.app.config.network_id + b"survey-request" +
                      req.to_xdr())

    def _response_sign_bytes(self, rsp: SurveyResponseMessage) -> bytes:
        return sha256(self.app.config.network_id + b"survey-response" +
                      rsp.to_xdr())

    # -- relay / process (both sides) ----------------------------------------
    def relay_or_process(self, msg: StellarMessage, peer) -> None:
        """Entry from Peer message dispatch; flood-dedup, verify, then
        answer if we are the target, else relay (reference
        relayOrProcessRequest/Response)."""
        if not self.overlay.recv_flooded_msg(msg, peer):
            return              # duplicate copy: already handled/relayed
        if msg.disc == MessageType.SURVEY_REQUEST:
            self._on_request(msg)
        else:
            self._on_response(msg)

    def _limiter_ok(self, surveyor: PublicKey) -> bool:
        lcl = self.app.ledger_manager.last_closed_ledger_num()
        if lcl != self._limiter_ledger:
            self._limiter_ledger = lcl
            self._limiter = {}
        n = self._limiter.get(surveyor.key_bytes, 0)
        self._limiter[surveyor.key_bytes] = n + 1
        return n < MAX_REQUESTS_PER_LEDGER

    def _on_request(self, msg: StellarMessage) -> None:
        signed: SignedSurveyRequestMessage = msg.value
        req = signed.request
        if not PubKeyUtils.verify_sig(req.surveyorPeerID,
                                      signed.requestSignature,
                                      self._request_sign_bytes(req)):
            self.bad_responses += 1
            return
        if req.surveyedPeerID.key_bytes != self._self_id().key_bytes:
            self.overlay.broadcast_message(msg)      # relay on
            return
        # budget consumed only by verified requests addressed to us
        # (reference SurveyMessageLimiter records after validation)
        if not self._limiter_ok(req.surveyorPeerID):
            return
        body = self._build_topology_body()
        sealed = curve25519_seal(req.encryptionKey, body.to_xdr())
        rsp = SurveyResponseMessage(
            surveyorPeerID=req.surveyorPeerID,
            surveyedPeerID=self._self_id(),
            ledgerNum=req.ledgerNum,
            commandType=SurveyMessageCommandType.SURVEY_TOPOLOGY,
            encryptedBody=sealed)
        sig = self.app.config.NODE_SEED.sign(self._response_sign_bytes(rsp))
        self.overlay.broadcast_message(
            StellarMessage(MessageType.SURVEY_RESPONSE,
                           SignedSurveyResponseMessage(
                               responseSignature=sig, response=rsp)),
            force=True)

    def _on_response(self, msg: StellarMessage) -> None:
        signed: SignedSurveyResponseMessage = msg.value
        rsp = signed.response
        if not PubKeyUtils.verify_sig(rsp.surveyedPeerID,
                                      signed.responseSignature,
                                      self._response_sign_bytes(rsp)):
            self.bad_responses += 1
            return
        if rsp.surveyorPeerID.key_bytes != self._self_id().key_bytes:
            self.overlay.broadcast_message(msg)      # relay on
            return
        if self._secret is None:
            return
        try:
            body = TopologyResponseBody.from_xdr(
                curve25519_unseal(self._secret, rsp.encryptedBody))
        except Exception:
            self.bad_responses += 1
            return
        self._record_result(rsp.surveyedPeerID, body)

    # -- topology assembly ---------------------------------------------------
    def _peer_stats(self, p) -> PeerStats:
        return PeerStats(
            id=p.peer_id or PublicKey.ed25519(b"\x00" * 32),
            versionStr=(p.remote_version_str or
                        self.app.config.VERSION_STR)[:100],
            messagesRead=p.messages_read,
            messagesWritten=p.messages_written,
            bytesRead=p.bytes_read,
            bytesWritten=p.bytes_written,
            secondsConnected=int(
                max(0.0, self.app.clock.now() -
                    getattr(p, "connected_at", self.app.clock.now()))))

    def _build_topology_body(self) -> TopologyResponseBody:
        inbound, outbound = [], []
        for key in self.overlay.authenticated_peer_ids():
            p = self.overlay.get_peer(key)
            if p is None:
                continue
            from .peer import PeerRole
            (outbound if p.role == PeerRole.WE_CALLED_REMOTE
             else inbound).append(self._peer_stats(p))
        return TopologyResponseBody(
            inboundPeers=inbound[:25], outboundPeers=outbound[:25],
            totalInboundPeerCount=len(inbound),
            totalOutboundPeerCount=len(outbound))

    def _record_result(self, node: PublicKey,
                       body: TopologyResponseBody) -> None:
        def stats(ps: PeerStats) -> dict:
            return {"nodeId": ps.id.key_bytes.hex(),
                    "version": ps.versionStr,
                    "messagesRead": ps.messagesRead,
                    "messagesWritten": ps.messagesWritten,
                    "bytesRead": ps.bytesRead,
                    "bytesWritten": ps.bytesWritten,
                    "secondsConnected": ps.secondsConnected}

        self.results[node.key_bytes.hex()] = {
            "inboundPeers": [stats(x) for x in body.inboundPeers],
            "outboundPeers": [stats(x) for x in body.outboundPeers],
            "totalInbound": body.totalInboundPeerCount,
            "totalOutbound": body.totalOutboundPeerCount,
        }
        # walk outward: newly-learned peers join the backlog
        if self.running:
            for ps in list(body.inboundPeers) + list(body.outboundPeers):
                if ps.id.key_bytes != b"\x00" * 32:
                    self.add_node_to_backlog(ps.id)
            self._pump()

    def get_results(self) -> dict:
        return {"surveyInProgress": self.running,
                "badResponses": self.bad_responses,
                "topology": self.results}

    def get_stats(self) -> dict:
        """Compact survey health for the fleet aggregate (util/fleet.py):
        enough to see, across N nodes at once, who surveyed whom and who
        dropped responses — without shipping full topologies."""
        out = {"running": self.running,
               "surveyed": len(self._surveyed),
               "results": len(self.results),
               "backlog": len(self._backlog),
               "bad_responses": self.bad_responses}
        # both-direction bandwidth totals (LoadManager now accounts the
        # send path too — ISSUE 10 satellite): the fleet aggregate's
        # survey block carries who moved how many bytes each way
        lm = getattr(self.overlay, "load_manager", None)
        if lm is not None:
            out.update(lm.totals())
        return out
