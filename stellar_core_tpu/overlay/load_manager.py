"""LoadManager: per-peer cost accounting and load shedding.

Role parity: reference `src/overlay/LoadManager.{h,cpp}` — each peer
accumulates a cost vector (main-thread time, bytes sent/received); when
the node is overloaded the costliest peer is dropped ("the least
deserving"). Accounting contexts wrap message processing.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..util.log import get_logger

log = get_logger("Overlay")


class PeerCosts:
    __slots__ = ("time_spent", "bytes_send", "bytes_recv", "msgs_send",
                 "msgs_recv")

    def __init__(self) -> None:
        self.time_spent = 0.0
        self.bytes_send = 0
        self.bytes_recv = 0
        self.msgs_send = 0
        self.msgs_recv = 0

    def to_json(self) -> dict:
        return {"time": round(self.time_spent, 6),
                "bytes_send": self.bytes_send,
                "bytes_recv": self.bytes_recv,
                "msgs_send": self.msgs_send,
                "msgs_recv": self.msgs_recv}


class LoadManager:
    def __init__(self, app) -> None:
        self.app = app
        self._costs: Dict[bytes, PeerCosts] = {}
        self.peers_shed = 0

    def peer_costs(self, peer_key: bytes) -> PeerCosts:
        c = self._costs.get(peer_key)
        if c is None:
            c = PeerCosts()
            self._costs[peer_key] = c
        return c

    def forget(self, peer_key: bytes) -> None:
        self._costs.pop(peer_key, None)

    # -- accounting context (reference LoadManager::PeerContext) -------------
    class PeerContext:
        def __init__(self, lm: "LoadManager", peer_key: bytes) -> None:
            self._lm = lm
            self._key = peer_key
            self._t0 = 0.0

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            c = self._lm.peer_costs(self._key)
            c.time_spent += time.perf_counter() - self._t0
            c.msgs_recv += 1
            return False

    def context(self, peer_key: bytes) -> "LoadManager.PeerContext":
        return LoadManager.PeerContext(self, peer_key)

    def record_bytes(self, peer_key: bytes, sent: int, received: int
                     ) -> None:
        c = self.peer_costs(peer_key)
        c.bytes_send += sent
        c.bytes_recv += received

    def record_sent(self, peer_key: bytes, nbytes: int) -> None:
        """One outbound message to `peer_key` (Peer.send_message) — the
        send-path twin of the receive accounting, so the cost vector and
        `_worst_peer_key` see both directions (ISSUE 10 satellite)."""
        c = self.peer_costs(peer_key)
        c.bytes_send += nbytes
        c.msgs_send += 1

    def totals(self) -> dict:
        """Both-direction byte/message totals across every tracked peer
        (SurveyManager.get_stats + the fleet aggregate surface these)."""
        out = {"bytes_send": 0, "bytes_recv": 0,
               "msgs_send": 0, "msgs_recv": 0}
        for c in self._costs.values():
            out["bytes_send"] += c.bytes_send
            out["bytes_recv"] += c.bytes_recv
            out["msgs_send"] += c.msgs_send
            out["msgs_recv"] += c.msgs_recv
        return out

    # -- shedding ------------------------------------------------------------
    def _worst_peer_key(self) -> Optional[bytes]:
        worst, worst_cost = None, -1.0
        for key, c in self._costs.items():
            cost = c.time_spent + (c.bytes_recv + c.bytes_send) * 1e-9
            if cost > worst_cost:
                worst, worst_cost = key, cost
        return worst

    def maybe_shed_excess_load(self, overlay) -> bool:
        """Drop the costliest authenticated peer when over capacity
        (reference maybeShedExcessLoad, gated on TARGET+extra)."""
        cfg = self.app.config
        limit = cfg.TARGET_PEER_CONNECTIONS + max(
            0, cfg.MAX_ADDITIONAL_PEER_CONNECTIONS)
        if overlay.get_authenticated_peers_count() <= limit:
            return False
        key = self._worst_peer_key()
        if key is None:
            return False
        p = overlay.get_peer(key)
        if p is None:
            self.forget(key)
            return False
        log.info("shedding excess load: dropping %s",
                 key.hex()[:8] if isinstance(key, bytes) else key)
        self.peers_shed += 1
        p.drop("load shed")
        return True

    def get_json_info(self) -> dict:
        return {k.hex()[:16] if isinstance(k, bytes) else str(k):
                c.to_json() for k, c in self._costs.items()}
