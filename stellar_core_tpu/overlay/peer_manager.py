"""PeerManager / BanManager: persistent peer book and node bans.

Role parity: reference `src/overlay/PeerManager.{h,cpp}` (peers table with
numFailures/nextAttempt backoff and preferred/outbound/inbound types,
PeerManager::getPeersToSend), `RandomPeerSource`, and
`src/overlay/BanManagerImpl.cpp` (bans keyed by node id, stored in DB).
"""

from __future__ import annotations

import socket as _socket
from typing import Dict, List, Optional, Tuple

from ..util import rnd
from ..util.log import get_logger
from ..xdr import IPAddr, PeerAddress, PublicKey

log = get_logger("Overlay")

MAX_FAILURES = 10
# decorrelated-jitter reconnect backoff (docs/robustness.md): delay_k is
# uniform in [BASE, 3 * delay_{k-1}] capped — reconnect attempts from many
# nodes that lost the same peer spread out instead of storming it in sync
RECONNECT_BACKOFF_BASE = 2.0
RECONNECT_BACKOFF_CAP = 120.0


def parse_peer_address(s: str, default_port: int = 11625
                       ) -> Tuple[str, int]:
    """"host[:port]" → (host, port)."""
    if ":" in s:
        host, port = s.rsplit(":", 1)
        return host, int(port)
    return s, default_port


def to_xdr_address(host: str, port: int, num_failures: int = 0
                   ) -> PeerAddress:
    try:
        raw = _socket.inet_aton(host)
    except OSError:
        raw = b"\x7f\x00\x00\x01"
    return PeerAddress(ip=IPAddr(IPAddr.IPv4, raw), port=port,
                       numFailures=num_failures)


def from_xdr_address(pa: PeerAddress) -> Tuple[str, int]:
    if pa.ip.disc == IPAddr.IPv4:
        return _socket.inet_ntoa(pa.ip.value), pa.port
    return ("::", pa.port)


class PeerRecord:
    __slots__ = ("host", "port", "num_failures", "next_attempt",
                 "preferred", "outbound", "last_backoff")

    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.port = port
        self.num_failures = 0
        self.next_attempt = 0.0
        self.preferred = False
        self.outbound = False
        self.last_backoff = 0.0


class PeerManager:
    def __init__(self, app) -> None:
        self.app = app
        self._peers: Dict[Tuple[str, int], PeerRecord] = {}
        cfg = app.config
        for s in cfg.KNOWN_PEERS:
            self.ensure_exists(*parse_peer_address(s, cfg.PEER_PORT))
        for s in cfg.PREFERRED_PEERS:
            rec = self.ensure_exists(*parse_peer_address(s, cfg.PEER_PORT))
            rec.preferred = True
        self._load_db()

    # -- persistence ---------------------------------------------------------
    def _db(self):
        return getattr(self.app, "database", None)

    def _load_db(self) -> None:
        db = self._db()
        if db is None:
            return
        try:
            rows = db.execute(
                "SELECT ip, port, numfailures FROM peers").fetchall()
        except Exception:
            return
        for host, port, nf in rows:
            rec = self.ensure_exists(host, port)
            rec.num_failures = nf

    def store(self) -> None:
        db = self._db()
        if db is None:
            return
        for rec in self._peers.values():
            db.execute(
                "INSERT OR REPLACE INTO peers (ip, port, numfailures) "
                "VALUES (?,?,?)", (rec.host, rec.port, rec.num_failures))
        db.commit()

    # -- book ----------------------------------------------------------------
    def ensure_exists(self, host: str, port: int) -> PeerRecord:
        key = (host, port)
        rec = self._peers.get(key)
        if rec is None:
            rec = PeerRecord(host, port)
            self._peers[key] = rec
        return rec

    def on_connect_failure(self, host: str, port: int) -> None:
        rec = self.ensure_exists(host, port)
        rec.num_failures += 1
        # exponential backoff with decorrelated jitter: the growth comes
        # from tripling the PREVIOUS delay, the desynchronization from the
        # uniform draw (deterministic under the seeded global RNG)
        prev = rec.last_backoff or RECONNECT_BACKOFF_BASE
        delay = min(RECONNECT_BACKOFF_CAP,
                    rnd.g_random.uniform(RECONNECT_BACKOFF_BASE,
                                         prev * 3.0))
        rec.last_backoff = delay
        rec.next_attempt = self.app.clock.now() + delay
        m = getattr(self.app, "metrics", None)
        if m is not None:
            m.new_meter("overlay.connection.failure").mark()

    def on_connect_success(self, host: str, port: int) -> None:
        rec = self.ensure_exists(host, port)
        rec.num_failures = 0
        rec.next_attempt = 0.0
        rec.last_backoff = 0.0
        rec.outbound = True

    def candidates_to_connect(self, n: int,
                              exclude: List[Tuple[str, int]]
                              ) -> List[PeerRecord]:
        now = self.app.clock.now()
        ex = set(exclude)
        cands = [r for r in self._peers.values()
                 if (r.host, r.port) not in ex and r.next_attempt <= now
                 and r.num_failures < MAX_FAILURES]
        # preferred first, then fewest failures, randomized within class
        rnd.g_random.shuffle(cands)
        cands.sort(key=lambda r: (not r.preferred, r.num_failures))
        return cands[:n]

    def recv_peers(self, addrs) -> None:
        for pa in addrs:
            host, port = from_xdr_address(pa)
            if port > 0:
                self.ensure_exists(host, port)

    def peers_to_send(self, n: int) -> List[PeerAddress]:
        recs = [r for r in self._peers.values()
                if r.num_failures < MAX_FAILURES]
        rnd.g_random.shuffle(recs)
        return [to_xdr_address(r.host, r.port, r.num_failures)
                for r in recs[:n]]

    def size(self) -> int:
        return len(self._peers)


class BanManager:
    """Reference src/overlay/BanManagerImpl.cpp."""

    def __init__(self, app) -> None:
        self.app = app
        self._banned: set = set()
        db = getattr(app, "database", None)
        if db is not None:
            try:
                for (nodeid,) in db.execute(
                        "SELECT nodeid FROM bans").fetchall():
                    self._banned.add(nodeid)
            except Exception:
                pass

    def ban_node(self, node_id: PublicKey) -> None:
        key = node_id.to_xdr().hex()
        if key in self._banned:
            return
        self._banned.add(key)
        db = getattr(self.app, "database", None)
        if db is not None:
            db.execute("INSERT OR REPLACE INTO bans (nodeid) VALUES (?)",
                       (key,))
            db.commit()

    def unban_node(self, node_id: PublicKey) -> None:
        key = node_id.to_xdr().hex()
        self._banned.discard(key)
        db = getattr(self.app, "database", None)
        if db is not None:
            db.execute("DELETE FROM bans WHERE nodeid = ?", (key,))
            db.commit()

    def unban_all(self) -> int:
        """Lift every ban (admin `bans?action=unban_all`); returns how
        many were lifted."""
        n = len(self._banned)
        self._banned.clear()
        db = getattr(self.app, "database", None)
        if db is not None:
            db.execute("DELETE FROM bans")
            db.commit()
        return n

    def is_banned(self, node_id: PublicKey) -> bool:
        return node_id.to_xdr().hex() in self._banned

    def banned(self) -> List[str]:
        return sorted(self._banned)
