"""Floodgate: de-duplicated gossip flooding.

Role parity: reference `src/overlay/Floodgate.{h,cpp}:38-107` — a record
per flooded message (SHA256 of its XDR) tracking which peers already have
it; broadcast sends to every authenticated peer not in the set; records are
garbage-collected by the ledger seq they were added at.
"""

from __future__ import annotations

from typing import Dict, Set

from ..crypto.hashing import sha256
from ..util.log import get_logger
from ..xdr import StellarMessage

log = get_logger("Overlay")


class _FloodRecord:
    __slots__ = ("ledger_seq", "message", "peers_told", "dupes")

    def __init__(self, ledger_seq: int, message: StellarMessage) -> None:
        self.ledger_seq = ledger_seq
        self.message = message
        self.peers_told: Set[str] = set()
        self.dupes = 0        # duplicate receipts (flood-layer waste)


class Floodgate:
    def __init__(self) -> None:
        self._map: Dict[bytes, _FloodRecord] = {}
        self._shutting_down = False
        # wire cockpit (ISSUE 10): dedup accounting — unique vs
        # duplicate receipts feed the flood duplication ratio, broadcast
        # fanout feeds its histogram (installed by OverlayManager)
        self.stats = None
        # propagation cockpit (ISSUE 17): causal hop records — recv
        # hops stamped per add_record receipt (first vs redundant edge,
        # in lockstep with record_flood so the two cockpits reconcile),
        # send hops per broadcast fanout, origin markers when this node
        # is the broadcaster (installed by OverlayManager; None = off)
        self.prop = None

    @staticmethod
    def msg_id(msg: StellarMessage) -> bytes:
        return sha256(msg.to_xdr())

    def add_record(self, msg: StellarMessage, from_peer_id: str,
                   ledger_seq: int, from_hex: str = "") -> bool:
        """Note an incoming flooded message; returns False if seen before
        (reference Floodgate::addRecord). `from_hex` (sender node-id
        hex) attributes the receipt as a propagation hop."""
        if self._shutting_down:
            return False
        raw = msg.to_xdr()
        h = sha256(raw)
        rec = self._map.get(h)
        unique = rec is None
        if unique:
            rec = _FloodRecord(ledger_seq, msg)
            self._map[h] = rec
        else:
            rec.dupes += 1
        rec.peers_told.add(from_peer_id)
        if self.stats is not None:
            self.stats.record_flood(unique=unique)
        if self.prop is not None and from_hex:
            self.prop.record_recv_hop(h, from_hex, len(raw), msg.disc,
                                      unique, ledger_seq)
        return unique

    def broadcast(self, msg: StellarMessage, force: bool, peers: Dict,
                  ledger_seq: int) -> int:
        """Send to every authenticated peer not already told; returns the
        number sent (reference Floodgate::broadcast, Floodgate.cpp:81-107)."""
        if self._shutting_down:
            return 0
        raw = msg.to_xdr()
        h = sha256(raw)
        rec = self._map.get(h)
        if rec is None:
            # no receipt preceded this broadcast: this node originated
            # the message — the relay tree's root (ISSUE 17)
            rec = _FloodRecord(ledger_seq, msg)
            self._map[h] = rec
            if self.prop is not None:
                self.prop.record_origin(h, len(raw), msg.disc, ledger_seq)
        n = 0
        for pid, peer in list(peers.items()):
            if pid in rec.peers_told:
                continue
            peer.send_message(msg)
            rec.peers_told.add(pid)
            n += 1
            if self.prop is not None and peer.peer_id is not None:
                self.prop.record_send_hop(
                    h, peer.peer_id.key_bytes.hex(), len(raw), msg.disc,
                    ledger_seq)
        if self.stats is not None:
            self.stats.record_broadcast(n)
        return n

    def forget_record(self, msg: StellarMessage) -> None:
        self._map.pop(self.msg_id(msg), None)

    def clear_below(self, ledger_seq: int, keep: int = 2) -> None:
        """GC records older than `keep` ledgers (reference
        Floodgate::clearBelow)."""
        cutoff = ledger_seq - keep
        for h in [h for h, r in self._map.items() if r.ledger_seq < cutoff]:
            del self._map[h]

    def shutdown(self) -> None:
        self._shutting_down = True
        self._map.clear()

    def size(self) -> int:
        return len(self._map)
