"""Floodgate: de-duplicated gossip flooding.

Role parity: reference `src/overlay/Floodgate.{h,cpp}:38-107` — a record
per flooded message (SHA256 of its XDR) tracking which peers already have
it; broadcast sends to every authenticated peer not in the set; records are
garbage-collected by the ledger seq they were added at.
"""

from __future__ import annotations

from typing import Dict, Set

from ..crypto.hashing import sha256
from ..util.log import get_logger
from ..xdr import StellarMessage

log = get_logger("Overlay")


class _FloodRecord:
    __slots__ = ("ledger_seq", "message", "peers_told", "dupes")

    def __init__(self, ledger_seq: int, message: StellarMessage) -> None:
        self.ledger_seq = ledger_seq
        self.message = message
        self.peers_told: Set[str] = set()
        self.dupes = 0        # duplicate receipts (flood-layer waste)


class Floodgate:
    def __init__(self) -> None:
        self._map: Dict[bytes, _FloodRecord] = {}
        self._shutting_down = False
        # wire cockpit (ISSUE 10): dedup accounting — unique vs
        # duplicate receipts feed the flood duplication ratio, broadcast
        # fanout feeds its histogram (installed by OverlayManager)
        self.stats = None

    @staticmethod
    def msg_id(msg: StellarMessage) -> bytes:
        return sha256(msg.to_xdr())

    def add_record(self, msg: StellarMessage, from_peer_id: str,
                   ledger_seq: int) -> bool:
        """Note an incoming flooded message; returns False if seen before
        (reference Floodgate::addRecord)."""
        if self._shutting_down:
            return False
        h = self.msg_id(msg)
        rec = self._map.get(h)
        if rec is None:
            rec = _FloodRecord(ledger_seq, msg)
            self._map[h] = rec
            rec.peers_told.add(from_peer_id)
            if self.stats is not None:
                self.stats.record_flood(unique=True)
            return True
        rec.peers_told.add(from_peer_id)
        rec.dupes += 1
        if self.stats is not None:
            self.stats.record_flood(unique=False)
        return False

    def broadcast(self, msg: StellarMessage, force: bool, peers: Dict,
                  ledger_seq: int) -> int:
        """Send to every authenticated peer not already told; returns the
        number sent (reference Floodgate::broadcast, Floodgate.cpp:81-107)."""
        if self._shutting_down:
            return 0
        h = self.msg_id(msg)
        rec = self._map.get(h)
        if rec is None:
            rec = _FloodRecord(ledger_seq, msg)
            self._map[h] = rec
        n = 0
        for pid, peer in list(peers.items()):
            if pid in rec.peers_told:
                continue
            peer.send_message(msg)
            rec.peers_told.add(pid)
            n += 1
        if self.stats is not None:
            self.stats.record_broadcast(n)
        return n

    def forget_record(self, msg: StellarMessage) -> None:
        self._map.pop(self.msg_id(msg), None)

    def clear_below(self, ledger_seq: int, keep: int = 2) -> None:
        """GC records older than `keep` ledgers (reference
        Floodgate::clearBelow)."""
        cutoff = ledger_seq - keep
        for h in [h for h, r in self._map.items() if r.ledger_seq < cutoff]:
            del self._map[h]

    def shutdown(self) -> None:
        self._shutting_down = True
        self._map.clear()

    def size(self) -> int:
        return len(self._map)
