/* Native batched host-prep for the TPU ed25519 verifier.
 *
 * Role: the host side of the batch-verify boundary (SURVEY.md §2.2 /
 * §5 "host↔TPU data path") — everything byte-level the device is bad at,
 * for a whole batch in ONE call with no Python in the loop:
 *   - SHA-512 of R‖A‖M per item (k derivation, RFC 8032)
 *   - 512-bit reduction mod the group order L (Barrett, 64-bit limbs)
 *   - canonicality prechecks (S < L, y < p) per item
 *   - bit-slicing: 13-bit field limbs and radix-16 scalar digits
 *
 * The reference does the equivalent work inside libsodium one signature
 * at a time (/root/reference/src/crypto/SecretKey.cpp:310-337); here it
 * feeds fixed-shape int32 arrays straight to the device kernel.
 *
 * Portable C11 + __int128 (gcc/clang on x86-64/aarch64). Constants are
 * generated exactly by gen_constants.py (see prep_constants.h).
 */

#include <stdint.h>
#include <string.h>

#include "prep_constants.h"

/* ------------------------------------------------------------- SHA-512 */

static inline uint64_t rotr64(uint64_t x, int n)
{
    return (x >> n) | (x << (64 - n));
}

static inline uint64_t load_be64(const uint8_t *p)
{
    uint64_t v = 0;
    for (int i = 0; i < 8; i++)
        v = (v << 8) | p[i];
    return v;
}

static void sha512_block(uint64_t st[8], const uint8_t *block)
{
    uint64_t w[80];
    for (int i = 0; i < 16; i++)
        w[i] = load_be64(block + 8 * i);
    for (int i = 16; i < 80; i++) {
        uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^
                      (w[i - 15] >> 7);
        uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^
                      (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint64_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 80; i++) {
        uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
        uint64_t ch = (e & f) ^ (~e & g);
        uint64_t t1 = h + S1 + ch + SHA512_K[i] + w[i];
        uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
        uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint64_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

/* digest of R(32) ‖ A(32) ‖ M(mlen) without building one buffer */
static void sha512_ram(const uint8_t *r, const uint8_t *a,
                       const uint8_t *m, uint64_t mlen, uint8_t out[64])
{
    uint64_t st[8];
    uint8_t buf[128];
    memcpy(st, SHA512_H0, sizeof st);

    uint64_t total = 64 + mlen;
    /* first block: R ‖ A ‖ first 64 bytes of M (if available) */
    memcpy(buf, r, 32);
    memcpy(buf + 32, a, 32);
    uint64_t fill = mlen < 64 ? mlen : 64;
    memcpy(buf + 64, m, fill);
    uint64_t used = 64 + fill;
    if (used == 128) {
        sha512_block(st, buf);
        m += fill;
        mlen -= fill;
        while (mlen >= 128) {
            sha512_block(st, m);
            m += 128;
            mlen -= 128;
        }
        memcpy(buf, m, mlen);
        used = mlen;
    }
    /* padding */
    buf[used++] = 0x80;
    if (used > 112) {
        memset(buf + used, 0, 128 - used);
        sha512_block(st, buf);
        used = 0;
    }
    memset(buf + used, 0, 112 - used);
    /* length in bits, big-endian 128-bit (message < 2^61 bytes) */
    uint64_t bits = total << 3;
    memset(buf + 112, 0, 8);
    for (int i = 0; i < 8; i++)
        buf[120 + i] = (uint8_t)(bits >> (8 * (7 - i)));
    sha512_block(st, buf);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = (uint8_t)(st[i] >> (8 * (7 - j)));
}

/* ----------------------------------------- 512-bit mod L (Barrett) */

typedef unsigned __int128 u128;

/* r = x mod L where x is 8 little-endian 64-bit limbs; r gets 4 limbs.
 * Barrett with mu = floor(2^512 / L): q = (x * mu) >> 512, r = x - q*L,
 * then at most two conditional subtracts. q fits in 5 limbs (q <= x/L
 * < 2^260). */
static void mod_L(const uint64_t x[8], uint64_t r[4])
{
    /* q = high 5 limbs of x * mu (only need columns >= 8) */
    uint64_t prod[14];
    memset(prod, 0, sizeof prod);
    u128 carry = 0;
    for (int k = 0; k < 13; k++) {
        u128 acc = carry;
        uint64_t acc_hi = 0;
        int lo = k >= 4 ? k - 4 : 0;
        int hi = k < 8 ? k : 8 - 1;
        for (int i = lo; i <= hi && i < 8; i++) {
            int j = k - i;
            if (j < 0 || j > 4)
                continue;
            u128 t = (u128)x[i] * ED_MU[j];
            acc += t;
            if (acc < t)
                acc_hi++; /* 128-bit overflow safeguard */
        }
        prod[k] = (uint64_t)acc;
        carry = (acc >> 64) + ((u128)acc_hi << 64);
    }
    prod[13] = (uint64_t)carry;
    uint64_t q[6];
    for (int i = 0; i < 6; i++)
        q[i] = prod[8 + i];

    /* r = x - q*L (low 5 limbs are enough; result < 3L < 2^254) */
    uint64_t ql[5];
    memset(ql, 0, sizeof ql);
    carry = 0;
    for (int k = 0; k < 5; k++) {
        u128 acc = carry;
        for (int i = 0; i <= k && i < 6; i++) {
            int j = k - i;
            if (j > 3)
                continue;
            acc += (u128)q[i] * ED_L[j];
        }
        ql[k] = (uint64_t)acc;
        carry = acc >> 64;
    }
    uint64_t rr[5];
    u128 borrow = 0;
    for (int i = 0; i < 5; i++) {
        u128 xi = i < 8 ? x[i] : 0;
        u128 rhs = (u128)ql[i] + borrow;
        if (xi >= rhs) {
            rr[i] = (uint64_t)(xi - rhs);
            borrow = 0;
        } else {
            rr[i] = (uint64_t)((((u128)1) << 64) + xi - rhs);
            borrow = 1;
        }
    }
    /* conditional subtract L while r >= L (at most twice) */
    for (int round = 0; round < 3; round++) {
        int ge = 0;
        if (rr[4]) {
            ge = 1;
        } else {
            ge = 1;
            for (int i = 3; i >= 0; i--) {
                if (rr[i] > ED_L[i])
                    break;
                if (rr[i] < ED_L[i]) {
                    ge = 0;
                    break;
                }
            }
        }
        if (!ge)
            break;
        u128 b2 = 0;
        for (int i = 0; i < 5; i++) {
            u128 rhs = (u128)(i < 4 ? ED_L[i] : 0) + b2;
            u128 xi = rr[i];
            if (xi >= rhs) {
                rr[i] = (uint64_t)(xi - rhs);
                b2 = 0;
            } else {
                rr[i] = (uint64_t)((((u128)1) << 64) + xi - rhs);
                b2 = 1;
            }
        }
    }
    for (int i = 0; i < 4; i++)
        r[i] = rr[i];
}

/* --------------------------------------------------------- bit slicing */

static void le_bytes_to_limbs13(const uint8_t b[32], int32_t out[20])
{
    for (int i = 0; i < 20; i++) {
        int bit = 13 * i;
        int k = bit >> 3, sh = bit & 7;
        uint32_t v = b[k] >> sh;
        if (k + 1 < 32)
            v |= (uint32_t)b[k + 1] << (8 - sh);
        if (k + 2 < 32)
            v |= (uint32_t)b[k + 2] << (16 - sh);
        out[i] = (int32_t)(v & 0x1fff);
    }
}

static void le_bytes_to_nibs(const uint8_t b[32], int32_t out[64])
{
    for (int i = 0; i < 32; i++) {
        out[2 * i] = b[i] & 15;
        out[2 * i + 1] = b[i] >> 4;
    }
}

/* little-endian 32-byte < 4×64-bit-limb constant */
static int lt_le(const uint8_t b[32], const uint64_t lim[4])
{
    for (int i = 3; i >= 0; i--) {
        uint64_t v = 0;
        for (int j = 7; j >= 0; j--)
            v = (v << 8) | b[8 * i + j];
        if (v < lim[i])
            return 1;
        if (v > lim[i])
            return 0;
    }
    return 0;
}

/* ------------------------------------------------------------ batch API */

int sct_prepare_batch(const uint8_t *pubs,      /* n*32 */
                      const uint8_t *sigs,      /* n*64 */
                      const uint8_t *msgs,      /* concatenated bodies */
                      const uint64_t *msg_off,  /* n+1 offsets */
                      int64_t n,
                      int32_t *ay, int32_t *a_sign,
                      int32_t *ry, int32_t *r_sign,
                      int32_t *s_nibs, int32_t *k_nibs,
                      uint8_t *pre_ok)
{
    for (int64_t i = 0; i < n; i++) {
        const uint8_t *pub = pubs + 32 * i;
        const uint8_t *sig = sigs + 64 * i;
        uint8_t ayb[32], ryb[32];
        memcpy(ayb, pub, 32);
        memcpy(ryb, sig, 32);
        a_sign[i] = ayb[31] >> 7;
        r_sign[i] = ryb[31] >> 7;
        ayb[31] &= 0x7f;
        ryb[31] &= 0x7f;

        int ok = lt_le(sig + 32, ED_L) && lt_le(ayb, ED_P) &&
                 lt_le(ryb, ED_P);
        pre_ok[i] = (uint8_t)ok;
        if (!ok) {
            memset(ay + 20 * i, 0, 20 * 4);
            memset(ry + 20 * i, 0, 20 * 4);
            memset(s_nibs + 64 * i, 0, 64 * 4);
            memset(k_nibs + 64 * i, 0, 64 * 4);
            continue;
        }
        le_bytes_to_limbs13(ayb, ay + 20 * i);
        le_bytes_to_limbs13(ryb, ry + 20 * i);
        le_bytes_to_nibs(sig + 32, s_nibs + 64 * i);

        uint8_t digest[64];
        sha512_ram(sig, pub, msgs + msg_off[i],
                   msg_off[i + 1] - msg_off[i], digest);
        uint64_t x[8], kred[4];
        for (int w = 0; w < 8; w++) {
            uint64_t v = 0;
            for (int j = 7; j >= 0; j--)
                v = (v << 8) | digest[8 * w + j];
            x[w] = v;
        }
        mod_L(x, kred);
        uint8_t kb[32];
        for (int w = 0; w < 4; w++)
            for (int j = 0; j < 8; j++)
                kb[8 * w + j] = (uint8_t)(kred[w] >> (8 * j));
        le_bytes_to_nibs(kb, k_nibs + 64 * i);
    }
    return 0;
}

/* ------------------------------------------------- verify-cache keys */

/* SHA-256 (FIPS 180-4), used only for the verify-cache keys below —
   the result cache in crypto/keys.py hashes (key ‖ sig ‖ msg) with
   SHA-256, and the whole-checkpoint drain computes one key per triple
   (hashlib per-call overhead is ~1/3 of the drain's host cost). */

static const uint32_t SHA256_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
    0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
    0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
    0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
    0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
    0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
    0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
    0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
    0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

static inline uint32_t rotr32(uint32_t x, int n)
{
    return (x >> n) | (x << (32 - n));
}

static void sha256_block(uint32_t st[8], const uint8_t *p)
{
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
               ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^
                      (w[i - 15] >> 3);
        uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^
                      (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint32_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + SHA256_K[i] + w[i];
        uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

/* digest of key32 ‖ sig64 ‖ msg (the _cache_key layout) */
static void sha256_ksm(const uint8_t *key, const uint8_t *sig,
                       const uint8_t *msg, uint64_t mlen, uint8_t out[32])
{
    static const uint32_t H0[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
    };
    uint32_t st[8];
    uint8_t buf[64];
    memcpy(st, H0, sizeof st);
    uint64_t total = 96 + mlen;

    /* block 1: key ‖ sig[0:32]; block 2: sig[32:64] ‖ msg[0:32] ... */
    memcpy(buf, key, 32);
    memcpy(buf + 32, sig, 32);
    sha256_block(st, buf);
    memcpy(buf, sig + 32, 32);
    uint64_t take = mlen < 32 ? mlen : 32;
    memcpy(buf + 32, msg, take);
    uint64_t used = 32 + take;
    const uint8_t *rest = msg + take;
    uint64_t rlen = mlen - take;
    if (used == 64) {
        sha256_block(st, buf);
        while (rlen >= 64) {
            sha256_block(st, rest);
            rest += 64;
            rlen -= 64;
        }
        memcpy(buf, rest, rlen);
        used = rlen;
    }
    buf[used++] = 0x80;
    if (used > 56) {
        memset(buf + used, 0, 64 - used);
        sha256_block(st, buf);
        used = 0;
    }
    memset(buf + used, 0, 56 - used);
    uint64_t bits = total * 8;
    for (int i = 0; i < 8; i++)
        buf[56 + i] = (uint8_t)(bits >> (56 - 8 * i));
    sha256_block(st, buf);
    for (int i = 0; i < 8; i++) {
        out[4 * i] = (uint8_t)(st[i] >> 24);
        out[4 * i + 1] = (uint8_t)(st[i] >> 16);
        out[4 * i + 2] = (uint8_t)(st[i] >> 8);
        out[4 * i + 3] = (uint8_t)st[i];
    }
}

/* one call per drain: n (key ‖ sig ‖ msg) triples -> n*32 digests.
   Layout matches sct_prepare_batch (pubs n*32, sigs n*64, msgs+offsets) */
int sct_cache_keys(const uint8_t *pubs, const uint8_t *sigs,
                   const uint8_t *msgs, const uint64_t *msg_off,
                   int64_t n, uint8_t *out)
{
    for (int64_t i = 0; i < n; i++)
        sha256_ksm(pubs + 32 * i, sigs + 64 * i, msgs + msg_off[i],
                   msg_off[i + 1] - msg_off[i], out + 32 * i);
    return 0;
}
