/* Self-contained ed25519 + X25519 for containers without OpenSSL bindings.
 *
 * Role: the synchronous CPU crypto floor under crypto/keys.py when the
 * `cryptography` package is absent — sign, public-key derivation, and
 * RFC 8032 cofactorless verify with EXACTLY the accept/reject semantics
 * of ops/ed25519.py's verify_oracle (strict S < L, non-canonical point
 * encodings rejected, affine compare against the decompressed R). The
 * pure-Python fallback (crypto/fallback.py) is the behavioral oracle;
 * tests/test_crypto.py asserts parity triple-wise with the TPU kernel.
 *
 * Field arithmetic: 5x51-bit limbs with unsigned __int128 products
 * (portable C11, same toolchain contract as prep.c). Not constant-time —
 * this backs tests and benchmarks, not production key handling.
 *
 * Shares prep_constants.h (SHA-512 round constants, L/P/mu limbs) with
 * prep.c via the generated build header.
 */

#include <stdint.h>
#include <string.h>

#include "prep_constants.h"

typedef unsigned __int128 u128;

/* ------------------------------------------------------------- SHA-512 */

static inline uint64_t rotr64(uint64_t x, int n)
{
    return (x >> n) | (x << (64 - n));
}

static inline uint64_t load_be64(const uint8_t *p)
{
    uint64_t v = 0;
    for (int i = 0; i < 8; i++)
        v = (v << 8) | p[i];
    return v;
}

static void sha512_block(uint64_t st[8], const uint8_t *block)
{
    uint64_t w[80];
    for (int i = 0; i < 16; i++)
        w[i] = load_be64(block + 8 * i);
    for (int i = 16; i < 80; i++) {
        uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^
                      (w[i - 15] >> 7);
        uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^
                      (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint64_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 80; i++) {
        uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
        uint64_t ch = (e & f) ^ (~e & g);
        uint64_t t1 = h + S1 + ch + SHA512_K[i] + w[i];
        uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
        uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint64_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

typedef struct {
    uint64_t st[8];
    uint8_t buf[128];
    uint64_t buflen;
    uint64_t total;
} sha512_ctx;

static void sha512_init(sha512_ctx *c)
{
    memcpy(c->st, SHA512_H0, sizeof c->st);
    c->buflen = 0;
    c->total = 0;
}

static void sha512_update(sha512_ctx *c, const uint8_t *p, uint64_t n)
{
    c->total += n;
    if (c->buflen) {
        uint64_t fill = 128 - c->buflen;
        if (fill > n)
            fill = n;
        memcpy(c->buf + c->buflen, p, fill);
        c->buflen += fill;
        p += fill;
        n -= fill;
        if (c->buflen == 128) {
            sha512_block(c->st, c->buf);
            c->buflen = 0;
        }
    }
    while (n >= 128) {
        sha512_block(c->st, p);
        p += 128;
        n -= 128;
    }
    if (n) {
        memcpy(c->buf, p, n);
        c->buflen = n;
    }
}

static void sha512_final(sha512_ctx *c, uint8_t out[64])
{
    uint64_t used = c->buflen;
    c->buf[used++] = 0x80;
    if (used > 112) {
        memset(c->buf + used, 0, 128 - used);
        sha512_block(c->st, c->buf);
        used = 0;
    }
    memset(c->buf + used, 0, 112 - used);
    uint64_t bits = c->total << 3;
    memset(c->buf + 112, 0, 8);
    for (int i = 0; i < 8; i++)
        c->buf[120 + i] = (uint8_t)(bits >> (8 * (7 - i)));
    sha512_block(c->st, c->buf);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = (uint8_t)(c->st[i] >> (8 * (7 - j)));
}

/* ----------------------------------------- 512-bit mod L (Barrett) */
/* identical algorithm to prep.c (same generated ED_MU / ED_L limbs) */

static void mod_L(const uint64_t x[8], uint64_t r[4])
{
    uint64_t prod[14];
    memset(prod, 0, sizeof prod);
    u128 carry = 0;
    for (int k = 0; k < 13; k++) {
        u128 acc = carry;
        uint64_t acc_hi = 0;
        int lo = k >= 4 ? k - 4 : 0;
        int hi = k < 8 ? k : 8 - 1;
        for (int i = lo; i <= hi && i < 8; i++) {
            int j = k - i;
            if (j < 0 || j > 4)
                continue;
            u128 t = (u128)x[i] * ED_MU[j];
            acc += t;
            if (acc < t)
                acc_hi++;
        }
        prod[k] = (uint64_t)acc;
        carry = (acc >> 64) + ((u128)acc_hi << 64);
    }
    prod[13] = (uint64_t)carry;
    uint64_t q[6];
    for (int i = 0; i < 6; i++)
        q[i] = prod[8 + i];

    uint64_t ql[5];
    memset(ql, 0, sizeof ql);
    carry = 0;
    for (int k = 0; k < 5; k++) {
        u128 acc = carry;
        for (int i = 0; i <= k && i < 6; i++) {
            int j = k - i;
            if (j > 3)
                continue;
            acc += (u128)q[i] * ED_L[j];
        }
        ql[k] = (uint64_t)acc;
        carry = acc >> 64;
    }
    uint64_t rr[5];
    u128 borrow = 0;
    for (int i = 0; i < 5; i++) {
        u128 xi = i < 8 ? x[i] : 0;
        u128 rhs = (u128)ql[i] + borrow;
        if (xi >= rhs) {
            rr[i] = (uint64_t)(xi - rhs);
            borrow = 0;
        } else {
            rr[i] = (uint64_t)((((u128)1) << 64) + xi - rhs);
            borrow = 1;
        }
    }
    for (int round = 0; round < 3; round++) {
        int ge = 0;
        if (rr[4]) {
            ge = 1;
        } else {
            ge = 1;
            for (int i = 3; i >= 0; i--) {
                if (rr[i] > ED_L[i])
                    break;
                if (rr[i] < ED_L[i]) {
                    ge = 0;
                    break;
                }
            }
        }
        if (!ge)
            break;
        u128 b2 = 0;
        for (int i = 0; i < 5; i++) {
            u128 rhs = (u128)(i < 4 ? ED_L[i] : 0) + b2;
            u128 xi = rr[i];
            if (xi >= rhs) {
                rr[i] = (uint64_t)(xi - rhs);
                b2 = 0;
            } else {
                rr[i] = (uint64_t)((((u128)1) << 64) + xi - rhs);
                b2 = 1;
            }
        }
    }
    for (int i = 0; i < 4; i++)
        r[i] = rr[i];
}

/* 256x256 -> 512 multiply then reduce: out = (a*b + c) mod L */
static void sc_muladd(const uint64_t a[4], const uint64_t b[4],
                      const uint64_t c[4], uint64_t out[4])
{
    uint64_t prod[8];
    memset(prod, 0, sizeof prod);
    u128 carry = 0;
    for (int k = 0; k < 8; k++) {
        u128 acc = carry;
        uint64_t acc_hi = 0;
        for (int i = 0; i < 4; i++) {
            int j = k - i;
            if (j < 0 || j > 3)
                continue;
            u128 t = (u128)a[i] * b[j];
            acc += t;
            if (acc < t)
                acc_hi++;
        }
        prod[k] = (uint64_t)acc;
        carry = (acc >> 64) + ((u128)acc_hi << 64);
    }
    u128 cc = 0;
    for (int i = 0; i < 4; i++) {
        cc += (u128)prod[i] + c[i];
        prod[i] = (uint64_t)cc;
        cc >>= 64;
    }
    for (int i = 4; i < 8 && cc; i++) {
        cc += prod[i];
        prod[i] = (uint64_t)cc;
        cc >>= 64;
    }
    mod_L(prod, out);
}

/* little-endian 32-byte < 4x64-bit-limb constant */
static int lt_le(const uint8_t b[32], const uint64_t lim[4])
{
    for (int i = 3; i >= 0; i--) {
        uint64_t v = 0;
        for (int j = 7; j >= 0; j--)
            v = (v << 8) | b[8 * i + j];
        if (v < lim[i])
            return 1;
        if (v > lim[i])
            return 0;
    }
    return 0;
}

/* --------------------------------------------- field: 5x51-bit limbs */

#define MASK51 0x7FFFFFFFFFFFFULL

typedef uint64_t fe[5];

static inline uint64_t load64(const uint8_t *p)
{
    uint64_t v = 0;
    for (int i = 7; i >= 0; i--)
        v = (v << 8) | p[i];
    return v;
}

static void fe_frombytes(fe h, const uint8_t s[32])
{
    h[0] = load64(s) & MASK51;
    h[1] = (load64(s + 6) >> 3) & MASK51;
    h[2] = (load64(s + 12) >> 6) & MASK51;
    h[3] = (load64(s + 19) >> 1) & MASK51;
    h[4] = (load64(s + 24) >> 12) & MASK51;
}

static void fe_copy(fe h, const fe f) { memcpy(h, f, sizeof(fe)); }

static void fe_0(fe h) { memset(h, 0, sizeof(fe)); }

static void fe_1(fe h) { fe_0(h); h[0] = 1; }

static void fe_add(fe h, const fe f, const fe g)
{
    uint64_t c;
    h[0] = f[0] + g[0];
    h[1] = f[1] + g[1];
    h[2] = f[2] + g[2];
    h[3] = f[3] + g[3];
    h[4] = f[4] + g[4];
    c = h[0] >> 51; h[0] &= MASK51; h[1] += c;
    c = h[1] >> 51; h[1] &= MASK51; h[2] += c;
    c = h[2] >> 51; h[2] &= MASK51; h[3] += c;
    c = h[3] >> 51; h[3] &= MASK51; h[4] += c;
    c = h[4] >> 51; h[4] &= MASK51; h[0] += 19 * c;
}

/* h = f - g, computed as f + 2p - g to stay non-negative */
static void fe_sub(fe h, const fe f, const fe g)
{
    uint64_t c;
    h[0] = f[0] + 0xFFFFFFFFFFFDAULL - g[0];
    h[1] = f[1] + 0xFFFFFFFFFFFFEULL - g[1];
    h[2] = f[2] + 0xFFFFFFFFFFFFEULL - g[2];
    h[3] = f[3] + 0xFFFFFFFFFFFFEULL - g[3];
    h[4] = f[4] + 0xFFFFFFFFFFFFEULL - g[4];
    c = h[0] >> 51; h[0] &= MASK51; h[1] += c;
    c = h[1] >> 51; h[1] &= MASK51; h[2] += c;
    c = h[2] >> 51; h[2] &= MASK51; h[3] += c;
    c = h[3] >> 51; h[3] &= MASK51; h[4] += c;
    c = h[4] >> 51; h[4] &= MASK51; h[0] += 19 * c;
}

static void fe_mul(fe h, const fe f, const fe g)
{
    u128 f0 = f[0], f1 = f[1], f2 = f[2], f3 = f[3], f4 = f[4];
    uint64_t g0 = g[0], g1 = g[1], g2 = g[2], g3 = g[3], g4 = g[4];
    uint64_t g1_19 = 19 * g1, g2_19 = 19 * g2;
    uint64_t g3_19 = 19 * g3, g4_19 = 19 * g4;
    u128 h0 = f0 * g0 + f1 * g4_19 + f2 * g3_19 + f3 * g2_19 + f4 * g1_19;
    u128 h1 = f0 * g1 + f1 * g0 + f2 * g4_19 + f3 * g3_19 + f4 * g2_19;
    u128 h2 = f0 * g2 + f1 * g1 + f2 * g0 + f3 * g4_19 + f4 * g3_19;
    u128 h3 = f0 * g3 + f1 * g2 + f2 * g1 + f3 * g0 + f4 * g4_19;
    u128 h4 = f0 * g4 + f1 * g3 + f2 * g2 + f3 * g1 + f4 * g0;
    uint64_t r0, r1, r2, r3, r4, c;
    r0 = (uint64_t)h0 & MASK51; h1 += (uint64_t)(h0 >> 51);
    r1 = (uint64_t)h1 & MASK51; h2 += (uint64_t)(h1 >> 51);
    r2 = (uint64_t)h2 & MASK51; h3 += (uint64_t)(h2 >> 51);
    r3 = (uint64_t)h3 & MASK51; h4 += (uint64_t)(h3 >> 51);
    r4 = (uint64_t)h4 & MASK51;
    r0 += 19 * (uint64_t)(h4 >> 51);
    c = r0 >> 51; r0 &= MASK51; r1 += c;
    c = r1 >> 51; r1 &= MASK51; r2 += c;
    h[0] = r0; h[1] = r1; h[2] = r2; h[3] = r3; h[4] = r4;
}

/* dedicated squaring: 15 wide products instead of fe_mul's 25 */
static void fe_sq(fe h, const fe f)
{
    uint64_t f0 = f[0], f1 = f[1], f2 = f[2], f3 = f[3], f4 = f[4];
    uint64_t f1_2 = 2 * f1, f2_2 = 2 * f2;
    uint64_t f3_2 = 2 * f3, f4_2 = 2 * f4;
    uint64_t f3_19 = 19 * f3, f4_19 = 19 * f4;
    u128 h0 = (u128)f0 * f0 + (u128)f1_2 * f4_19 + (u128)f2_2 * f3_19;
    u128 h1 = (u128)f0 * f1_2 + (u128)f2_2 * f4_19 + (u128)f3 * f3_19;
    u128 h2 = (u128)f0 * f2_2 + (u128)f1 * f1 + (u128)f3_2 * f4_19;
    u128 h3 = (u128)f0 * f3_2 + (u128)f1_2 * f2 + (u128)f4 * f4_19;
    u128 h4 = (u128)f0 * f4_2 + (u128)f1_2 * f3 + (u128)f2 * f2;
    uint64_t r0, r1, r2, r3, r4, c;
    r0 = (uint64_t)h0 & MASK51; h1 += (uint64_t)(h0 >> 51);
    r1 = (uint64_t)h1 & MASK51; h2 += (uint64_t)(h1 >> 51);
    r2 = (uint64_t)h2 & MASK51; h3 += (uint64_t)(h2 >> 51);
    r3 = (uint64_t)h3 & MASK51; h4 += (uint64_t)(h3 >> 51);
    r4 = (uint64_t)h4 & MASK51;
    r0 += 19 * (uint64_t)(h4 >> 51);
    c = r0 >> 51; r0 &= MASK51; r1 += c;
    c = r1 >> 51; r1 &= MASK51; r2 += c;
    h[0] = r0; h[1] = r1; h[2] = r2; h[3] = r3; h[4] = r4;
}

/* h = f^(2^k), k >= 1 */
static void fe_pow2k(fe h, const fe f, int k)
{
    fe_sq(h, f);
    for (int i = 1; i < k; i++)
        fe_sq(h, h);
}

/* freeze to fully-reduced form */
static void fe_tobytes(uint8_t s[32], const fe f)
{
    fe t;
    fe_copy(t, f);
    uint64_t c;
    for (int i = 0; i < 2; i++) {
        c = t[0] >> 51; t[0] &= MASK51; t[1] += c;
        c = t[1] >> 51; t[1] &= MASK51; t[2] += c;
        c = t[2] >> 51; t[2] &= MASK51; t[3] += c;
        c = t[3] >> 51; t[3] &= MASK51; t[4] += c;
        c = t[4] >> 51; t[4] &= MASK51; t[0] += 19 * c;
    }
    /* q = 1 iff t >= p */
    uint64_t q = (t[0] + 19) >> 51;
    q = (t[1] + q) >> 51;
    q = (t[2] + q) >> 51;
    q = (t[3] + q) >> 51;
    q = (t[4] + q) >> 51;
    t[0] += 19 * q;
    c = t[0] >> 51; t[0] &= MASK51; t[1] += c;
    c = t[1] >> 51; t[1] &= MASK51; t[2] += c;
    c = t[2] >> 51; t[2] &= MASK51; t[3] += c;
    c = t[3] >> 51; t[3] &= MASK51; t[4] += c;
    t[4] &= MASK51;
    uint64_t w0 = t[0] | (t[1] << 51);
    uint64_t w1 = (t[1] >> 13) | (t[2] << 38);
    uint64_t w2 = (t[2] >> 26) | (t[3] << 25);
    uint64_t w3 = (t[3] >> 39) | (t[4] << 12);
    for (int i = 0; i < 8; i++) {
        s[i] = (uint8_t)(w0 >> (8 * i));
        s[8 + i] = (uint8_t)(w1 >> (8 * i));
        s[16 + i] = (uint8_t)(w2 >> (8 * i));
        s[24 + i] = (uint8_t)(w3 >> (8 * i));
    }
}

static int fe_eq(const fe a, const fe b)
{
    uint8_t x[32], y[32];
    fe_tobytes(x, a);
    fe_tobytes(y, b);
    return memcmp(x, y, 32) == 0;
}

static int fe_iszero(const fe a)
{
    uint8_t x[32];
    static const uint8_t zero[32];
    fe_tobytes(x, a);
    return memcmp(x, zero, 32) == 0;
}

static int fe_parity(const fe a)
{
    uint8_t x[32];
    fe_tobytes(x, a);
    return x[0] & 1;
}

/* h = f^e where e is 32 little-endian bytes (MSB-first square&multiply) */
static void fe_pow(fe h, const fe f, const uint8_t e[32])
{
    fe acc, base;
    fe_1(acc);
    fe_copy(base, f);
    int started = 0;
    for (int i = 31; i >= 0; i--) {
        for (int b = 7; b >= 0; b--) {
            if (started)
                fe_sq(acc, acc);
            if ((e[i] >> b) & 1) {
                if (started)
                    fe_mul(acc, acc, base);
                else {
                    fe_copy(acc, base);
                    started = 1;
                }
            }
        }
    }
    fe_copy(h, acc);
}

/* exponent byte arrays (little-endian) */
static const uint8_t EXP_PM14[32] = {     /* (p - 1) / 4 = 2^253 - 5 */
    0xfb, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x1f};

/* f^(p-2) = f^(2^255 - 21) via the standard addition chain
   (254 squarings + 11 multiplies vs ~500 ops for generic fe_pow) */
static void fe_invert(fe out, const fe z)
{
    fe t0, t1, t2, t3;
    fe_sq(t0, z);                  /* 2 */
    fe_pow2k(t1, t0, 2);           /* 8 */
    fe_mul(t1, z, t1);             /* 9 */
    fe_mul(t0, t0, t1);            /* 11 */
    fe_sq(t2, t0);                 /* 22 */
    fe_mul(t1, t1, t2);            /* 31 = 2^5 - 1 */
    fe_pow2k(t2, t1, 5);
    fe_mul(t1, t2, t1);            /* 2^10 - 1 */
    fe_pow2k(t2, t1, 10);
    fe_mul(t2, t2, t1);            /* 2^20 - 1 */
    fe_pow2k(t3, t2, 20);
    fe_mul(t2, t3, t2);            /* 2^40 - 1 */
    fe_pow2k(t2, t2, 10);
    fe_mul(t1, t2, t1);            /* 2^50 - 1 */
    fe_pow2k(t2, t1, 50);
    fe_mul(t2, t2, t1);            /* 2^100 - 1 */
    fe_pow2k(t3, t2, 100);
    fe_mul(t2, t3, t2);            /* 2^200 - 1 */
    fe_pow2k(t2, t2, 50);
    fe_mul(t1, t2, t1);            /* 2^250 - 1 */
    fe_pow2k(t1, t1, 5);           /* 2^255 - 2^5 */
    fe_mul(out, t1, t0);           /* 2^255 - 21 */
}

/* f^(2^252 - 3): frombytes needs f^((p+3)/8) = pow22523(f) * f */
static void fe_pow22523(fe out, const fe z)
{
    fe t0, t1, t2;
    fe_sq(t0, z);                  /* 2 */
    fe_pow2k(t1, t0, 2);           /* 8 */
    fe_mul(t1, z, t1);             /* 9 */
    fe_mul(t0, t0, t1);            /* 11 */
    fe_sq(t0, t0);                 /* 22 */
    fe_mul(t0, t1, t0);            /* 31 = 2^5 - 1 */
    fe_pow2k(t1, t0, 5);
    fe_mul(t0, t1, t0);            /* 2^10 - 1 */
    fe_pow2k(t1, t0, 10);
    fe_mul(t1, t1, t0);            /* 2^20 - 1 */
    fe_pow2k(t2, t1, 20);
    fe_mul(t1, t2, t1);            /* 2^40 - 1 */
    fe_pow2k(t1, t1, 10);
    fe_mul(t0, t1, t0);            /* 2^50 - 1 */
    fe_pow2k(t1, t0, 50);
    fe_mul(t1, t1, t0);            /* 2^100 - 1 */
    fe_pow2k(t2, t1, 100);
    fe_mul(t1, t2, t1);            /* 2^200 - 1 */
    fe_pow2k(t1, t1, 50);
    fe_mul(t0, t1, t0);            /* 2^250 - 1 */
    fe_pow2k(t0, t0, 2);           /* 2^252 - 4 */
    fe_mul(out, t0, z);            /* 2^252 - 3 */
}

/* ------------------------------------------------ group: extended coords */

typedef struct {
    fe x, y, z, t;
} ge;

static fe FE_D2;       /* 2d */
static fe FE_SQRTM1;   /* sqrt(-1) */
static fe FE_D;
static ge GE_B;        /* base point */
static int g_init_done = 0;

static void ge_identity(ge *q)
{
    fe_0(q->x);
    fe_1(q->y);
    fe_1(q->z);
    fe_0(q->t);
}

static void ge_add(ge *out, const ge *p, const ge *q)
{
    fe a, b, c, d, e, f, g, h, t0, t1;
    fe_sub(t0, p->y, p->x);
    fe_sub(t1, q->y, q->x);
    fe_mul(a, t0, t1);
    fe_add(t0, p->y, p->x);
    fe_add(t1, q->y, q->x);
    fe_mul(b, t0, t1);
    fe_mul(c, p->t, FE_D2);
    fe_mul(c, c, q->t);
    fe_mul(d, p->z, q->z);
    fe_add(d, d, d);
    fe_sub(e, b, a);
    fe_sub(f, d, c);
    fe_add(g, d, c);
    fe_add(h, b, a);
    fe_mul(out->x, e, f);
    fe_mul(out->y, g, h);
    fe_mul(out->z, f, g);
    fe_mul(out->t, e, h);
}

static void ge_dbl(ge *out, const ge *p)
{
    fe a, b, c, e, f, g, h, t0;
    fe_sq(a, p->x);
    fe_sq(b, p->y);
    fe_sq(c, p->z);
    fe_add(c, c, c);
    fe_add(h, a, b);
    fe_add(t0, p->x, p->y);
    fe_sq(t0, t0);
    fe_sub(e, h, t0);
    fe_sub(g, a, b);
    fe_add(f, c, g);
    fe_mul(out->x, e, f);
    fe_mul(out->y, g, h);
    fe_mul(out->z, f, g);
    fe_mul(out->t, e, h);
}

/* t[v] = [v]p for v = 0..15 (evens by doubling, odds by one add; the
   unified hwcd add formula is complete on a=-1/ed25519 anyway) */
static void ge_table16(ge t[16], const ge *p)
{
    ge_identity(&t[0]);
    t[1] = *p;
    for (int v = 2; v < 16; v++) {
        if (v & 1)
            ge_add(&t[v], &t[v - 1], p);
        else
            ge_dbl(&t[v], &t[v / 2]);
    }
}

/* fixed-base comb: GE_BCOMB[j][v] = [v * 16^j]B, built once at init.
   A base mult is then ~60 additions and ZERO doublings — the dominant
   cost of sign/public and of verify's [S]B half. */
#define COMB_NIBS 64
static ge GE_BCOMB[COMB_NIBS][16];

static void ge_scalarmult_base(ge *q, const uint8_t n[32])
{
    ge_identity(q);
    for (int j = 0; j < COMB_NIBS; j++) {
        int nib = (n[j >> 1] >> ((j & 1) * 4)) & 15;
        if (nib)
            ge_add(q, q, &GE_BCOMB[j][nib]);
    }
}

/* q = [n]p for a variable point: 4-bit fixed window
   (252 doublings + ~60 adds vs 512 doublings + ~128 adds naive) */
static void ge_scalarmult_w4(ge *q, const ge *p, const uint8_t n[32])
{
    ge t[16];
    ge_table16(t, p);
    ge_identity(q);
    int started = 0;
    for (int j = COMB_NIBS - 1; j >= 0; j--) {
        if (started) {
            ge_dbl(q, q);
            ge_dbl(q, q);
            ge_dbl(q, q);
            ge_dbl(q, q);
        }
        int nib = (n[j >> 1] >> ((j & 1) * 4)) & 15;
        if (nib) {
            ge_add(q, q, &t[nib]);
            started = 1;
        }
    }
}

static void ge_tobytes(uint8_t s[32], const ge *p)
{
    fe zi, x, y;
    fe_invert(zi, p->z);
    fe_mul(x, p->x, zi);
    fe_mul(y, p->y, zi);
    fe_tobytes(s, y);
    s[31] |= (uint8_t)(fe_parity(x) << 7);
}

/* RFC 8032 decompression matching ops/ed25519.py _recover_x exactly.
 * Input bytes must already satisfy y < p (caller checks lt_le vs ED_P).
 * Returns 0 on failure. */
static int ge_frombytes(ge *p, const uint8_t s[32])
{
    uint8_t yb[32];
    memcpy(yb, s, 32);
    int sign = yb[31] >> 7;
    yb[31] &= 0x7f;
    fe y, y2, num, den, x2, x, chk;
    fe_frombytes(y, yb);
    fe_sq(y2, y);
    fe one;
    fe_1(one);
    fe_sub(num, y2, one);           /* y^2 - 1 */
    fe_mul(den, y2, FE_D);
    fe_add(den, den, one);          /* d y^2 + 1 */
    fe_invert(den, den);
    fe_mul(x2, num, den);
    if (fe_iszero(x2)) {
        if (sign)
            return 0;
        fe_0(x);
    } else {
        fe_pow22523(x, x2);
        fe_mul(x, x, x2);       /* x2^((p+3)/8) = x2^(2^252 - 2) */
        fe_sq(chk, x);
        if (!fe_eq(chk, x2)) {
            fe_mul(x, x, FE_SQRTM1);
            fe_sq(chk, x);
            if (!fe_eq(chk, x2))
                return 0;
        }
        if (fe_parity(x) != sign) {
            fe zero;
            fe_0(zero);
            fe_sub(x, zero, x);
        }
    }
    fe_copy(p->x, x);
    fe_copy(p->y, y);
    fe_1(p->z);
    fe_mul(p->t, x, y);
    return 1;
}

int sct_ed25519_init(void)
{
    if (g_init_done)
        return 0;
    /* d = -121665 / 121666 */
    fe n121665, n121666, zero;
    fe_0(n121665);
    n121665[0] = 121665;
    fe_0(n121666);
    n121666[0] = 121666;
    fe_0(zero);
    fe t;
    fe_invert(t, n121666);
    fe_mul(FE_D, n121665, t);
    fe_sub(FE_D, zero, FE_D);
    fe_add(FE_D2, FE_D, FE_D);
    /* sqrt(-1) = 2^((p-1)/4) */
    fe two;
    fe_0(two);
    two[0] = 2;
    fe_pow(FE_SQRTM1, two, EXP_PM14);
    /* B: y = 4/5, x = recover(y, 0) */
    fe four, five, by;
    fe_0(four);
    four[0] = 4;
    fe_0(five);
    five[0] = 5;
    fe_invert(t, five);
    fe_mul(by, four, t);
    uint8_t byb[32];
    fe_tobytes(byb, by);
    if (!ge_frombytes(&GE_B, byb))
        return -1;
    /* comb tables: GE_BCOMB[j] holds [0..15] * (16^j B) */
    ge cur = GE_B;
    for (int j = 0; j < COMB_NIBS; j++) {
        ge_table16(GE_BCOMB[j], &cur);
        if (j + 1 < COMB_NIBS)
            ge_dbl(&cur, &GE_BCOMB[j][8]);   /* 16^(j+1) B */
    }
    g_init_done = 1;
    return 0;
}

/* --------------------------------------------------------------- ed25519 */

static void scalar_tobytes(uint8_t out[32], const uint64_t r[4])
{
    for (int w = 0; w < 4; w++)
        for (int j = 0; j < 8; j++)
            out[8 * w + j] = (uint8_t)(r[w] >> (8 * j));
}

static void digest_mod_L(const uint8_t digest[64], uint8_t out[32])
{
    uint64_t x[8], red[4];
    for (int w = 0; w < 8; w++) {
        uint64_t v = 0;
        for (int j = 7; j >= 0; j--)
            v = (v << 8) | digest[8 * w + j];
        x[w] = v;
    }
    mod_L(x, red);
    scalar_tobytes(out, red);
}

static void clamp(uint8_t a[32])
{
    a[0] &= 248;
    a[31] &= 127;
    a[31] |= 64;
}

int sct_ed25519_public(const uint8_t seed[32], uint8_t out[32])
{
    sha512_ctx c;
    uint8_t h[64];
    sha512_init(&c);
    sha512_update(&c, seed, 32);
    sha512_final(&c, h);
    clamp(h);
    ge A;
    ge_scalarmult_base(&A, h);
    ge_tobytes(out, &A);
    return 0;
}

int sct_ed25519_sign(const uint8_t seed[32], const uint8_t *msg,
                     uint64_t mlen, uint8_t out_sig[64])
{
    sha512_ctx c;
    uint8_t h[64], a_enc[32], r_scalar[32], k_scalar[32], digest[64];
    sha512_init(&c);
    sha512_update(&c, seed, 32);
    sha512_final(&c, h);
    clamp(h);
    ge A;
    ge_scalarmult_base(&A, h);
    ge_tobytes(a_enc, &A);

    /* r = SHA512(prefix || msg) mod L */
    sha512_init(&c);
    sha512_update(&c, h + 32, 32);
    sha512_update(&c, msg, mlen);
    sha512_final(&c, digest);
    digest_mod_L(digest, r_scalar);

    ge R;
    ge_scalarmult_base(&R, r_scalar);
    ge_tobytes(out_sig, &R);

    /* k = SHA512(R || A || msg) mod L */
    sha512_init(&c);
    sha512_update(&c, out_sig, 32);
    sha512_update(&c, a_enc, 32);
    sha512_update(&c, msg, mlen);
    sha512_final(&c, digest);
    digest_mod_L(digest, k_scalar);

    /* S = (r + k*a) mod L */
    uint64_t ka[4], kk[4], aa[4], rr[4], ss[4];
    for (int w = 0; w < 4; w++) {
        uint64_t kv = 0, av = 0, rv = 0;
        for (int j = 7; j >= 0; j--) {
            kv = (kv << 8) | k_scalar[8 * w + j];
            av = (av << 8) | h[8 * w + j];
            rv = (rv << 8) | r_scalar[8 * w + j];
        }
        kk[w] = kv;
        aa[w] = av;
        rr[w] = rv;
    }
    (void)ka;
    sc_muladd(kk, aa, rr, ss);
    scalar_tobytes(out_sig + 32, ss);
    return 0;
}

int sct_ed25519_verify(const uint8_t pub[32], const uint8_t sig[64],
                       const uint8_t *msg, uint64_t mlen)
{
    uint8_t ayb[32], ryb[32];
    memcpy(ayb, pub, 32);
    memcpy(ryb, sig, 32);
    ayb[31] &= 0x7f;
    ryb[31] &= 0x7f;
    /* strict canonicality: S < L, yA < p, yR < p (oracle parity) */
    if (!lt_le(sig + 32, ED_L) || !lt_le(ayb, ED_P) || !lt_le(ryb, ED_P))
        return 0;
    ge A, R;
    if (!ge_frombytes(&A, pub) || !ge_frombytes(&R, sig))
        return 0;

    uint8_t digest[64], k_scalar[32];
    sha512_ctx c;
    sha512_init(&c);
    sha512_update(&c, sig, 32);
    sha512_update(&c, pub, 32);
    sha512_update(&c, msg, mlen);
    sha512_final(&c, digest);
    digest_mod_L(digest, k_scalar);

    /* Q = [S]B + [k](-A); accept iff Q == R affinely */
    ge negA = A;
    fe zero;
    fe_0(zero);
    fe_sub(negA.x, zero, A.x);
    fe_sub(negA.t, zero, A.t);
    ge sB, kA, Q;
    ge_scalarmult_base(&sB, sig + 32);
    ge_scalarmult_w4(&kA, &negA, k_scalar);
    ge_add(&Q, &sB, &kA);

    /* affine compare: X_q * Z_r == X_r * Z_q and same for Y */
    fe lhs, rhs;
    fe_mul(lhs, Q.x, R.z);
    fe_mul(rhs, R.x, Q.z);
    if (!fe_eq(lhs, rhs))
        return 0;
    fe_mul(lhs, Q.y, R.z);
    fe_mul(rhs, R.y, Q.z);
    return fe_eq(lhs, rhs);
}

int sct_ed25519_verify_batch(const uint8_t *pubs, const uint8_t *sigs,
                             const uint8_t *msgs, const uint64_t *msg_off,
                             int64_t n, uint8_t *out)
{
    for (int64_t i = 0; i < n; i++)
        out[i] = (uint8_t)sct_ed25519_verify(
            pubs + 32 * i, sigs + 64 * i, msgs + msg_off[i],
            msg_off[i + 1] - msg_off[i]);
    return 0;
}

/* ---------------------------------------------------------------- X25519 */

int sct_x25519(const uint8_t scalar[32], const uint8_t u[32],
               uint8_t out[32])
{
    uint8_t k[32], ub[32];
    memcpy(k, scalar, 32);
    k[0] &= 248;
    k[31] &= 127;
    k[31] |= 64;
    memcpy(ub, u, 32);
    ub[31] &= 0x7f;   /* RFC 7748: mask the top bit of u */

    fe x1, x2, z2, x3, z3;
    fe_frombytes(x1, ub);
    fe_1(x2);
    fe_0(z2);
    fe_copy(x3, x1);
    fe_1(z3);
    int swap = 0;
    fe a, aa, b, bb, e, cc, d, da, cb, t0, a24;
    fe_0(a24);
    a24[0] = 121665;
    for (int t = 254; t >= 0; t--) {
        int kt = (k[t >> 3] >> (t & 7)) & 1;
        if (swap ^ kt) {
            fe tmp;
            fe_copy(tmp, x2); fe_copy(x2, x3); fe_copy(x3, tmp);
            fe_copy(tmp, z2); fe_copy(z2, z3); fe_copy(z3, tmp);
        }
        swap = kt;
        fe_add(a, x2, z2);
        fe_sq(aa, a);
        fe_sub(b, x2, z2);
        fe_sq(bb, b);
        fe_sub(e, aa, bb);
        fe_add(cc, x3, z3);
        fe_sub(d, x3, z3);
        fe_mul(da, d, a);
        fe_mul(cb, cc, b);
        fe_add(t0, da, cb);
        fe_sq(x3, t0);
        fe_sub(t0, da, cb);
        fe_sq(t0, t0);
        fe_mul(z3, t0, x1);
        fe_mul(x2, aa, bb);
        fe_mul(t0, a24, e);
        fe_add(t0, t0, aa);
        fe_mul(z2, e, t0);
    }
    if (swap) {
        fe tmp;
        fe_copy(tmp, x2); fe_copy(x2, x3); fe_copy(x3, tmp);
        fe_copy(tmp, z2); fe_copy(z2, z3); fe_copy(z3, tmp);
    }
    fe_invert(z2, z2);
    fe_mul(x2, x2, z2);
    fe_tobytes(out, x2);
    return 0;
}
