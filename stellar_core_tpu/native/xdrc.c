/* Native XDR serializer: a flat-program interpreter over Python objects.
 *
 * Role parity: the reference gets generated C++ marshalling from xdrpp
 * (xdrc codegen); this module is that serializer for the TPU stack's
 * runtime — the declarative Python codec (xdr/codec.py) stays the source
 * of truth, compiles each type ONCE into a flat node program (see
 * native/__init__.py:_build_xdr_spec), and this extension walks values
 * against the program in C. Byte output and validation behavior are
 * bit-identical to xdr/fastcodec.py (property-tested across the whole
 * wire vocabulary in tests/test_native_xdr.py); fastcodec remains the
 * fallback when compilation is unavailable.
 *
 * Program node ops (built in native/__init__.py):
 *   0 INT    a=size(4|8)  b=signed(0|1)
 *   1 BOOL
 *   2 OPQF   a=n
 *   3 OPQV   a=max
 *   4 STR    a=max
 *   5 ARRF   a=n    b=child
 *   6 ARRV   a=max  b=child
 *   7 OPT    b=child
 *   8 ENUM   aux=sorted tuple of permitted ints
 *   9 STRUCT aux=tuple of (attr-name str, child) pairs
 *  10 UNION  a=switch-child  aux=(((disc, child|-1)...), default|-2)
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static PyObject *XdrError; /* set at module init from xdr.codec */

/* ---------------------------------------------------------------- buffer */

typedef struct {
    char *data;
    Py_ssize_t len, cap;
} Buf;

static int buf_grow(Buf *b, Py_ssize_t need)
{
    Py_ssize_t cap = b->cap ? b->cap : 256;
    while (cap < b->len + need)
        cap *= 2;
    if (cap != b->cap) {
        char *p = PyMem_Realloc(b->data, cap);
        if (!p)
            return -1;
        b->data = p;
        b->cap = cap;
    }
    return 0;
}

static int buf_put(Buf *b, const void *src, Py_ssize_t n)
{
    if (b->len + n > b->cap && buf_grow(b, n) < 0)
        return -1;
    memcpy(b->data + b->len, src, n);
    b->len += n;
    return 0;
}

static int buf_u32(Buf *b, uint32_t v)
{
    unsigned char w[4] = {(unsigned char)(v >> 24), (unsigned char)(v >> 16),
                          (unsigned char)(v >> 8), (unsigned char)v};
    return buf_put(b, w, 4);
}

static int buf_u64(Buf *b, uint64_t v)
{
    unsigned char w[8];
    int i;
    for (i = 0; i < 8; i++)
        w[i] = (unsigned char)(v >> (56 - 8 * i));
    return buf_put(b, w, 8);
}

static const char zeros[4] = {0, 0, 0, 0};

/* --------------------------------------------------------------- program */

typedef struct {
    int op;
    long long a;
    long long b; /* child index for containers */
    /* ENUM */
    long long *enum_vals;
    Py_ssize_t n_enum;
    /* STRUCT */
    PyObject **names; /* interned attr names (owned refs) */
    long long *children;
    Py_ssize_t n_fields;
    /* UNION */
    long long *arm_disc;
    long long *arm_child; /* -1 = void arm */
    Py_ssize_t n_arms;
    long long default_child; /* -1 void default, -2 no default */
    PyObject *cls; /* STRUCT/UNION: class to instantiate on unpack */
} Node;

typedef struct {
    Node *nodes;
    Py_ssize_t n;
} Prog;

static void prog_free(Prog *p)
{
    Py_ssize_t i, j;
    if (!p)
        return;
    for (i = 0; i < p->n; i++) {
        Node *nd = &p->nodes[i];
        if (nd->names) {
            for (j = 0; j < nd->n_fields; j++)
                Py_XDECREF(nd->names[j]);
            PyMem_Free(nd->names);
        }
        PyMem_Free(nd->children);
        PyMem_Free(nd->enum_vals);
        PyMem_Free(nd->arm_disc);
        PyMem_Free(nd->arm_child);
        Py_XDECREF(nd->cls);
    }
    PyMem_Free(p->nodes);
    PyMem_Free(p);
}

static void capsule_destructor(PyObject *cap)
{
    prog_free((Prog *)PyCapsule_GetPointer(cap, "sct.xdrprog"));
}

/* ----------------------------------------------------------------- pack */

static PyObject *str_disc, *str_value; /* interned at module init */

#define SCT_MAX_DEPTH 200 /* real wire types nest < 20 deep; adversarial
                             * self-nesting must raise, not smash the
                             * C stack (fastcodec raises RecursionError) */

static int pack_node(const Prog *p, long long idx, PyObject *v, Buf *b,
                     int depth);

static int pack_int(const Node *nd, PyObject *v, Buf *b)
{
    if (nd->a == 4) {
        long long x = PyLong_AsLongLong(v);
        if (x == -1 && PyErr_Occurred())
            goto bad;
        if (nd->b ? (x < INT32_MIN || x > INT32_MAX) : (x < 0 || x > (long long)UINT32_MAX))
            goto bad;
        return buf_u32(b, (uint32_t)x);
    }
    if (nd->b) { /* signed 64 */
        long long x = PyLong_AsLongLong(v);
        if (x == -1 && PyErr_Occurred())
            goto bad;
        return buf_u64(b, (uint64_t)x);
    } else {
        unsigned long long x = PyLong_AsUnsignedLongLong(v);
        if (x == (unsigned long long)-1 && PyErr_Occurred())
            goto bad;
        return buf_u64(b, (uint64_t)x);
    }
bad:
    PyErr_Clear();
    PyErr_Format(XdrError, "int out of range: %R", v);
    return -1;
}

static int pack_opaque(const Node *nd, PyObject *v, Buf *b, int fixed)
{
    char *data;
    Py_ssize_t n;
    if (PyBytes_Check(v)) {
        data = PyBytes_AS_STRING(v);
        n = PyBytes_GET_SIZE(v);
    } else if (PyByteArray_Check(v)) {
        data = PyByteArray_AS_STRING(v);
        n = PyByteArray_GET_SIZE(v);
    } else {
        PyErr_Format(XdrError, "opaque needs bytes, got %R", v);
        return -1;
    }
    if (fixed) {
        if (n != nd->a) {
            PyErr_Format(XdrError, "opaque[%lld] got %zd bytes", nd->a, n);
            return -1;
        }
    } else {
        if (n > nd->a) {
            PyErr_Format(XdrError, "opaque<%lld> got %zd bytes", nd->a, n);
            return -1;
        }
        if (buf_u32(b, (uint32_t)n) < 0)
            return -1;
    }
    if (buf_put(b, data, n) < 0)
        return -1;
    if (n % 4)
        return buf_put(b, zeros, 4 - n % 4);
    return 0;
}

static int pack_union(const Prog *p, const Node *nd, PyObject *v, Buf *b,
                      int depth)
{
    PyObject *dv, *vv, *dnum;
    long long disc, child = -3;
    Py_ssize_t i;
    int rc;
    dv = PyObject_GetAttr(v, str_disc);
    if (!dv)
        return -1;
    disc = PyLong_AsLongLong(dv);
    Py_DECREF(dv);
    if (disc == -1 && PyErr_Occurred()) {
        PyErr_Clear();
        PyErr_SetString(XdrError, "bad discriminant");
        return -1;
    }
    for (i = 0; i < nd->n_arms; i++) {
        if (nd->arm_disc[i] == disc) {
            child = nd->arm_child[i];
            break;
        }
    }
    if (child == -3) {
        if (nd->default_child == -2) {
            PyErr_Format(XdrError, "bad discriminant %lld", disc);
            return -1;
        }
        child = nd->default_child;
    }
    /* switch encode (validates enum membership when the switch is one) */
    dnum = PyLong_FromLongLong(disc);
    if (!dnum)
        return -1;
    rc = pack_node(p, nd->a, dnum, b, depth);
    Py_DECREF(dnum);
    if (rc < 0)
        return -1;
    if (child == -1)
        return 0; /* void arm */
    vv = PyObject_GetAttr(v, str_value);
    if (!vv)
        return -1;
    rc = pack_node(p, child, vv, b, depth);
    Py_DECREF(vv);
    return rc;
}

static int pack_node(const Prog *p, long long idx, PyObject *v, Buf *b,
                     int depth)
{
    const Node *nd = &p->nodes[idx];
    if (++depth > SCT_MAX_DEPTH) {
        PyErr_SetString(XdrError, "XDR value nested too deeply");
        return -1;
    }
    switch (nd->op) {
    case 0:
        return pack_int(nd, v, b);
    case 1: {
        int t = PyObject_IsTrue(v);
        if (t < 0)
            return -1;
        return buf_u32(b, t ? 1u : 0u);
    }
    case 2:
        return pack_opaque(nd, v, b, 1);
    case 3:
        return pack_opaque(nd, v, b, 0);
    case 4: { /* string: utf-8, bounded by a */
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(v, &n);
        if (!s)
            return -1;
        if (n > nd->a) {
            PyErr_Format(XdrError, "opaque<%lld> got %zd bytes", nd->a, n);
            return -1;
        }
        if (buf_u32(b, (uint32_t)n) < 0 || buf_put(b, s, n) < 0)
            return -1;
        if (n % 4)
            return buf_put(b, zeros, 4 - n % 4);
        return 0;
    }
    case 5:   /* fixed array */
    case 6: { /* var array */
        PyObject *fast = PySequence_Fast(v, "XDR array needs a sequence");
        Py_ssize_t n, i;
        if (!fast)
            return -1;
        n = PySequence_Fast_GET_SIZE(fast);
        if (nd->op == 5 && n != nd->a) {
            Py_DECREF(fast);
            PyErr_Format(XdrError, "array[%lld] got %zd", nd->a, n);
            return -1;
        }
        if (nd->op == 6) {
            if (n > nd->a) {
                Py_DECREF(fast);
                PyErr_Format(XdrError, "array<%lld> got %zd", nd->a, n);
                return -1;
            }
            if (buf_u32(b, (uint32_t)n) < 0) {
                Py_DECREF(fast);
                return -1;
            }
        }
        for (i = 0; i < n; i++) {
            if (pack_node(p, nd->b, PySequence_Fast_GET_ITEM(fast, i), b, depth) < 0) {
                Py_DECREF(fast);
                return -1;
            }
        }
        Py_DECREF(fast);
        return 0;
    }
    case 7: /* optional */
        if (v == Py_None)
            return buf_u32(b, 0u);
        if (buf_u32(b, 1u) < 0)
            return -1;
        return pack_node(p, nd->b, v, b, depth);
    case 8: { /* enum: membership then int32 */
        long long x = PyLong_AsLongLong(v);
        Py_ssize_t i;
        if (x == -1 && PyErr_Occurred()) {
            PyErr_Clear();
            PyErr_Format(XdrError, "bad enum value %R", v);
            return -1;
        }
        for (i = 0; i < nd->n_enum; i++)
            if (nd->enum_vals[i] == x)
                return buf_u32(b, (uint32_t)(int32_t)x);
        PyErr_Format(XdrError, "bad enum value %R", v);
        return -1;
    }
    case 9: { /* struct */
        Py_ssize_t i;
        for (i = 0; i < nd->n_fields; i++) {
            PyObject *fv = PyObject_GetAttr(v, nd->names[i]);
            int rc;
            if (!fv)
                return -1;
            rc = pack_node(p, nd->children[i], fv, b, depth);
            Py_DECREF(fv);
            if (rc < 0)
                return -1;
        }
        return 0;
    }
    case 10:
        return pack_union(p, nd, v, b, depth);
    default:
        PyErr_SetString(XdrError, "corrupt XDR program");
        return -1;
    }
}


/* ---------------------------------------------------------------- unpack */

typedef struct {
    const unsigned char *data;
    Py_ssize_t len, pos;
} Rdr;

static int rd_need(Rdr *r, Py_ssize_t n)
{
    if (r->pos + n > r->len) {
        PyErr_Format(XdrError, "XDR underflow at %zd", r->pos);
        return -1;
    }
    return 0;
}

static int rd_u32(Rdr *r, uint32_t *out)
{
    const unsigned char *p;
    if (rd_need(r, 4) < 0)
        return -1;
    p = r->data + r->pos;
    *out = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
    r->pos += 4;
    return 0;
}

static int rd_pad(Rdr *r, Py_ssize_t n)
{
    Py_ssize_t padn = (4 - n % 4) % 4, i;
    if (rd_need(r, padn) < 0)
        return -1;
    for (i = 0; i < padn; i++) {
        if (r->data[r->pos + i] != 0) {
            PyErr_SetString(XdrError, "nonzero padding");
            return -1;
        }
    }
    r->pos += padn;
    return 0;
}

static PyObject *unpack_node(const Prog *p, long long idx, Rdr *r,
                             int depth);

static PyObject *unpack_union(const Prog *p, const Node *nd, Rdr *r,
                              int depth)
{
    PyObject *dnum, *obj, *val;
    long long disc, child = -3;
    Py_ssize_t i;
    dnum = unpack_node(p, nd->a, r, depth); /* validates enum switches */
    if (!dnum)
        return NULL;
    disc = PyLong_AsLongLong(dnum);
    if (disc == -1 && PyErr_Occurred()) {
        Py_DECREF(dnum);
        return NULL;
    }
    for (i = 0; i < nd->n_arms; i++) {
        if (nd->arm_disc[i] == disc) {
            child = nd->arm_child[i];
            break;
        }
    }
    if (child == -3) {
        if (nd->default_child == -2) {
            Py_DECREF(dnum);
            PyErr_Format(XdrError, "bad discriminant %lld", disc);
            return NULL;
        }
        child = nd->default_child;
    }
    if (child == -1) {
        val = Py_None;
        Py_INCREF(val);
    } else {
        val = unpack_node(p, child, r, depth);
        if (!val) {
            Py_DECREF(dnum);
            return NULL;
        }
    }
    obj = ((PyTypeObject *)nd->cls)->tp_alloc((PyTypeObject *)nd->cls, 0);
    if (!obj) {
        Py_DECREF(dnum);
        Py_DECREF(val);
        return NULL;
    }
    if (PyObject_SetAttr(obj, str_disc, dnum) < 0 ||
        PyObject_SetAttr(obj, str_value, val) < 0) {
        Py_DECREF(dnum);
        Py_DECREF(val);
        Py_DECREF(obj);
        return NULL;
    }
    Py_DECREF(dnum);
    Py_DECREF(val);
    return obj;
}

static PyObject *unpack_node(const Prog *p, long long idx, Rdr *r,
                             int depth)
{
    const Node *nd = &p->nodes[idx];
    if (++depth > SCT_MAX_DEPTH) {
        PyErr_SetString(XdrError, "XDR value nested too deeply");
        return NULL;
    }
    switch (nd->op) {
    case 0: { /* int */
        if (nd->a == 4) {
            uint32_t w;
            if (rd_u32(r, &w) < 0)
                return NULL;
            if (nd->b)
                return PyLong_FromLong((long)(int32_t)w);
            return PyLong_FromUnsignedLong(w);
        } else {
            uint64_t v = 0;
            int i;
            if (rd_need(r, 8) < 0)
                return NULL;
            for (i = 0; i < 8; i++)
                v = (v << 8) | r->data[r->pos + i];
            r->pos += 8;
            if (nd->b)
                return PyLong_FromLongLong((long long)v);
            return PyLong_FromUnsignedLongLong(v);
        }
    }
    case 1: { /* bool */
        uint32_t w;
        if (rd_u32(r, &w) < 0)
            return NULL;
        if (w == 0)
            Py_RETURN_FALSE;
        if (w == 1)
            Py_RETURN_TRUE;
        PyErr_SetString(XdrError, "bad bool");
        return NULL;
    }
    case 2: { /* fixed opaque */
        PyObject *out;
        if (rd_need(r, nd->a) < 0)
            return NULL;
        out = PyBytes_FromStringAndSize((const char *)r->data + r->pos,
                                        nd->a);
        if (!out)
            return NULL;
        r->pos += nd->a;
        if (rd_pad(r, nd->a) < 0) {
            Py_DECREF(out);
            return NULL;
        }
        return out;
    }
    case 3:   /* var opaque */
    case 4: { /* string */
        uint32_t n;
        PyObject *out;
        if (rd_u32(r, &n) < 0)
            return NULL;
        if ((long long)n > nd->a) {
            PyErr_Format(XdrError, nd->op == 3 ?
                         "opaque<%lld> wire len %u" : "string<%lld> wire len %u",
                         nd->a, n);
            return NULL;
        }
        if (rd_need(r, n) < 0)
            return NULL;
        if (nd->op == 3)
            out = PyBytes_FromStringAndSize(
                (const char *)r->data + r->pos, n);
        else
            out = PyUnicode_DecodeUTF8(
                (const char *)r->data + r->pos, n, NULL);
        if (!out)
            return NULL;
        r->pos += n;
        if (rd_pad(r, n) < 0) {
            Py_DECREF(out);
            return NULL;
        }
        return out;
    }
    case 5:   /* fixed array */
    case 6: { /* var array */
        long long n = nd->a;
        PyObject *out;
        long long i;
        if (nd->op == 6) {
            uint32_t w;
            if (rd_u32(r, &w) < 0)
                return NULL;
            if ((long long)w > nd->a) {
                PyErr_Format(XdrError, "array<%lld> wire len %u", nd->a, w);
                return NULL;
            }
            n = w;
            /* The wire count is attacker-controlled; every XDR item
               encodes to >= 4 bytes, so a count that cannot fit in the
               remaining buffer must not drive the list pre-allocation.
               Grow incrementally instead — decoding then fails with a
               normal underflow without ever allocating n slots. */
            if (n > (r->len - r->pos) / 4) {
                out = PyList_New(0);
                if (!out)
                    return NULL;
                for (i = 0; i < n; i++) {
                    Py_ssize_t before = r->pos;
                    PyObject *e = unpack_node(p, nd->b, r, depth);
                    if (!e || PyList_Append(out, e) < 0) {
                        Py_XDECREF(e);
                        Py_DECREF(out);
                        return NULL;
                    }
                    Py_DECREF(e);
                    if (r->pos == before) {
                        /* zero-byte element x oversized claimed count:
                           refuse to spin the full count */
                        Py_DECREF(out);
                        PyErr_Format(XdrError, "XDR underflow at %zd",
                                     r->pos);
                        return NULL;
                    }
                }
                return out;
            }
        }
        out = PyList_New(n);
        if (!out)
            return NULL;
        for (i = 0; i < n; i++) {
            PyObject *e = unpack_node(p, nd->b, r, depth);
            if (!e) {
                Py_DECREF(out);
                return NULL;
            }
            PyList_SET_ITEM(out, i, e);
        }
        return out;
    }
    case 7: { /* optional */
        uint32_t w;
        if (rd_u32(r, &w) < 0)
            return NULL;
        if (w == 0)
            Py_RETURN_NONE;
        if (w != 1) {
            PyErr_SetString(XdrError, "bad optional flag");
            return NULL;
        }
        return unpack_node(p, nd->b, r, depth);
    }
    case 8: { /* enum */
        uint32_t w;
        long long x;
        Py_ssize_t i;
        if (rd_u32(r, &w) < 0)
            return NULL;
        x = (long long)(int32_t)w;
        for (i = 0; i < nd->n_enum; i++)
            if (nd->enum_vals[i] == x)
                return PyLong_FromLongLong(x);
        PyErr_Format(XdrError, "bad enum value %lld", x);
        return NULL;
    }
    case 9: { /* struct */
        PyObject *obj =
            ((PyTypeObject *)nd->cls)->tp_alloc((PyTypeObject *)nd->cls, 0);
        Py_ssize_t i;
        if (!obj)
            return NULL;
        for (i = 0; i < nd->n_fields; i++) {
            PyObject *fv = unpack_node(p, nd->children[i], r, depth);
            if (!fv || PyObject_SetAttr(obj, nd->names[i], fv) < 0) {
                Py_XDECREF(fv);
                Py_DECREF(obj);
                return NULL;
            }
            Py_DECREF(fv);
        }
        return obj;
    }
    case 10:
        return unpack_union(p, nd, r, depth);
    default:
        PyErr_SetString(XdrError, "corrupt XDR program");
        return NULL;
    }
}

static PyObject *py_unpack(PyObject *self, PyObject *args)
{
    PyObject *cap, *val, *out;
    Py_buffer view;
    Prog *p;
    Rdr r;
    Py_ssize_t start = 0;
    if (!PyArg_ParseTuple(args, "Oy*|n", &cap, &view, &start))
        return NULL;
    p = PyCapsule_GetPointer(cap, "sct.xdrprog");
    if (!p) {
        PyBuffer_Release(&view);
        return NULL;
    }
    if (start < 0 || start > view.len) {
        PyBuffer_Release(&view);
        PyErr_Format(XdrError, "bad start offset %zd", start);
        return NULL;
    }
    r.data = view.buf;
    r.len = view.len;
    r.pos = start;
    val = unpack_node(p, 0, &r, 0);
    PyBuffer_Release(&view);
    if (!val)
        return NULL;
    out = Py_BuildValue("(Nn)", val, r.pos);
    return out;
}

/* ------------------------------------------------------------ module API */

static PyObject *py_compile(PyObject *self, PyObject *arg)
{
    /* arg: tuple of node tuples as documented in the header comment */
    Py_ssize_t n, i, j;
    Prog *p;
    PyObject *cap;
    if (!PyTuple_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "program must be a tuple");
        return NULL;
    }
    n = PyTuple_GET_SIZE(arg);
    p = PyMem_Calloc(1, sizeof(Prog));
    if (!p)
        return PyErr_NoMemory();
    p->nodes = PyMem_Calloc(n ? n : 1, sizeof(Node));
    if (!p->nodes) {
        PyMem_Free(p);
        return PyErr_NoMemory();
    }
    p->n = n;
    for (i = 0; i < n; i++) {
        PyObject *t = PyTuple_GET_ITEM(arg, i);
        Node *nd = &p->nodes[i];
        long long op;
        PyObject *aux = NULL;
        if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) < 3)
            goto bad;
        op = PyLong_AsLongLong(PyTuple_GET_ITEM(t, 0));
        if (!PyErr_Occurred() && (op < 0 || op > 10))
            goto bad; /* reject before the (int) narrowing can alias */
        nd->op = (int)op;
        nd->a = PyLong_AsLongLong(PyTuple_GET_ITEM(t, 1));
        nd->b = PyLong_AsLongLong(PyTuple_GET_ITEM(t, 2));
        nd->default_child = -2;
        if (PyTuple_GET_SIZE(t) > 3)
            aux = PyTuple_GET_ITEM(t, 3);
        if (PyErr_Occurred())
            goto bad;
        if (op == 8) { /* enum */
            if (!aux || !PyTuple_Check(aux))
                goto bad;
            nd->n_enum = PyTuple_GET_SIZE(aux);
            nd->enum_vals = PyMem_Calloc(nd->n_enum ? nd->n_enum : 1,
                                         sizeof(long long));
            if (!nd->enum_vals)
                goto nomem;
            for (j = 0; j < nd->n_enum; j++) {
                nd->enum_vals[j] =
                    PyLong_AsLongLong(PyTuple_GET_ITEM(aux, j));
                if (PyErr_Occurred())
                    goto bad;
            }
        } else if (op == 9) { /* struct */
            if (PyTuple_GET_SIZE(t) < 5 ||
                !PyType_Check(PyTuple_GET_ITEM(t, 4)))
                goto bad;
            nd->cls = PyTuple_GET_ITEM(t, 4);
            Py_INCREF(nd->cls);
            if (!aux || !PyTuple_Check(aux))
                goto bad;
            nd->n_fields = PyTuple_GET_SIZE(aux);
            nd->names = PyMem_Calloc(nd->n_fields ? nd->n_fields : 1,
                                     sizeof(PyObject *));
            nd->children = PyMem_Calloc(nd->n_fields ? nd->n_fields : 1,
                                        sizeof(long long));
            if (!nd->names || !nd->children)
                goto nomem;
            for (j = 0; j < nd->n_fields; j++) {
                PyObject *pair = PyTuple_GET_ITEM(aux, j);
                PyObject *name;
                if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2)
                    goto bad;
                name = PyTuple_GET_ITEM(pair, 0);
                Py_INCREF(name);
                PyUnicode_InternInPlace(&name);
                nd->names[j] = name;
                nd->children[j] =
                    PyLong_AsLongLong(PyTuple_GET_ITEM(pair, 1));
                if (PyErr_Occurred())
                    goto bad;
            }
        } else if (op == 10) { /* union */
            PyObject *arms, *dflt;
            if (PyTuple_GET_SIZE(t) < 5 ||
                !PyType_Check(PyTuple_GET_ITEM(t, 4)))
                goto bad;
            nd->cls = PyTuple_GET_ITEM(t, 4);
            Py_INCREF(nd->cls);
            if (!aux || !PyTuple_Check(aux) || PyTuple_GET_SIZE(aux) != 2)
                goto bad;
            arms = PyTuple_GET_ITEM(aux, 0);
            dflt = PyTuple_GET_ITEM(aux, 1);
            if (!PyTuple_Check(arms))
                goto bad;
            nd->n_arms = PyTuple_GET_SIZE(arms);
            nd->arm_disc = PyMem_Calloc(nd->n_arms ? nd->n_arms : 1,
                                        sizeof(long long));
            nd->arm_child = PyMem_Calloc(nd->n_arms ? nd->n_arms : 1,
                                         sizeof(long long));
            if (!nd->arm_disc || !nd->arm_child)
                goto nomem;
            for (j = 0; j < nd->n_arms; j++) {
                PyObject *pair = PyTuple_GET_ITEM(arms, j);
                if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2)
                    goto bad;
                nd->arm_disc[j] =
                    PyLong_AsLongLong(PyTuple_GET_ITEM(pair, 0));
                nd->arm_child[j] =
                    PyLong_AsLongLong(PyTuple_GET_ITEM(pair, 1));
                if (PyErr_Occurred())
                    goto bad;
            }
            nd->default_child = PyLong_AsLongLong(dflt);
            if (PyErr_Occurred())
                goto bad;
        }
    }
    /* Second pass: compile() is the memory-safety boundary of the
       extension — validate every node/child index here so pack_node and
       unpack_node may index p->nodes unchecked. Sentinels: -1 = void arm,
       -2 = no default arm; anything else must land in [0, n). */
    if (n < 1)
        goto bad;
    for (i = 0; i < n; i++) {
        Node *nd = &p->nodes[i];
        if (nd->op < 0 || nd->op > 10)
            goto bad;
        switch (nd->op) {
        case 0:
            if (nd->a != 4 && nd->a != 8)
                goto bad;
            break;
        case 2:
        case 3:
        case 4:
            if (nd->a < 0)
                goto bad;
            break;
        case 5:
        case 6:
            if (nd->a < 0 || nd->b < 0 || nd->b >= n)
                goto bad;
            break;
        case 7:
            if (nd->b < 0 || nd->b >= n)
                goto bad;
            break;
        case 9:
            for (j = 0; j < nd->n_fields; j++)
                if (nd->children[j] < 0 || nd->children[j] >= n)
                    goto bad;
            break;
        case 10:
            if (nd->a < 0 || nd->a >= n)
                goto bad;
            for (j = 0; j < nd->n_arms; j++)
                if (nd->arm_child[j] >= n ||
                    (nd->arm_child[j] < 0 && nd->arm_child[j] != -1))
                    goto bad;
            if (nd->default_child >= n ||
                (nd->default_child < 0 && nd->default_child != -1 &&
                 nd->default_child != -2))
                goto bad;
            break;
        }
    }
    cap = PyCapsule_New(p, "sct.xdrprog", capsule_destructor);
    if (!cap) {
        prog_free(p);
        return NULL;
    }
    return cap;
bad:
    if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "malformed XDR program spec");
    prog_free(p);
    return NULL;
nomem:
    prog_free(p);
    return PyErr_NoMemory();
}

static PyObject *py_pack(PyObject *self, PyObject *args)
{
    PyObject *cap, *value, *out;
    Prog *p;
    Buf b = {NULL, 0, 0};
    if (!PyArg_ParseTuple(args, "OO", &cap, &value))
        return NULL;
    p = PyCapsule_GetPointer(cap, "sct.xdrprog");
    if (!p)
        return NULL;
    if (pack_node(p, 0, value, &b, 0) < 0) {
        PyMem_Free(b.data);
        return NULL;
    }
    out = PyBytes_FromStringAndSize(b.data, b.len);
    PyMem_Free(b.data);
    return out;
}

static PyMethodDef methods[] = {
    {"compile", py_compile, METH_O, "compile a flat XDR program spec"},
    {"pack", py_pack, METH_VARARGS, "serialize a value against a program"},
    {"unpack", py_unpack, METH_VARARGS,
     "parse (program, buffer[, start]) -> (value, end)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_sctxdr", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit__sctxdr(void)
{
    PyObject *m, *codec;
    str_disc = PyUnicode_InternFromString("disc");
    str_value = PyUnicode_InternFromString("value");
    if (!str_disc || !str_value)
        return NULL;
    codec = PyImport_ImportModule("stellar_core_tpu.xdr.codec");
    if (!codec)
        return NULL;
    XdrError = PyObject_GetAttrString(codec, "XdrError");
    Py_DECREF(codec);
    if (!XdrError)
        return NULL;
    m = PyModule_Create(&moduledef);
    return m;
}
