"""Native (C) batched host-prep for the ed25519 verifier.

Builds `prep.c` into a shared library on first use (cc -O2, cached under
build/) and exposes it through ctypes. The numpy/hashlib path in
ops/ed25519.py remains the fallback — the native path must produce
bit-identical arrays (tests/test_native_prep.py asserts parity).

Why C here: the per-item SHA-512 + mod-L loop is the one host-side cost
that can't be numpy-vectorized, and at the 100K sigs/s north star the
Python loop overhead alone would eat ~15% of a core (VERDICT r2 weak #7).
One C call per batch removes Python from the loop entirely.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _cc_build(src_path: str, so_path: str, include_dir: str) -> bool:
    """Try cc/gcc/g++ -O2 -shared -fPIC; atomic-rename into so_path.
    Shared by the prep library and the XDR extension builds."""
    import tempfile
    for cc in ("cc", "gcc", "g++"):
        tmp = tempfile.NamedTemporaryFile(
            dir=_BUILD, suffix=".so", delete=False)
        tmp.close()
        try:
            r = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-I", include_dir,
                 "-o", tmp.name, src_path],
                capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            os.unlink(tmp.name)
            continue
        if r.returncode == 0:
            os.rename(tmp.name, so_path)  # atomic: concurrent builders ok
            return True
        os.unlink(tmp.name)
    return False


def _compile() -> Optional[str]:
    import hashlib

    os.makedirs(_BUILD, exist_ok=True)
    src = os.path.join(_DIR, "prep.c")
    gen = os.path.join(_DIR, "gen_constants.py")
    from .gen_constants import header_text
    header = header_text()
    # hash ALL inputs into the artifact name: a constants or source change
    # can never silently reuse a stale library
    with open(src, "rb") as fh:
        digest = hashlib.sha256(
            fh.read() + header.encode() +
            open(gen, "rb").read()).hexdigest()[:16]
    so = os.path.join(_BUILD, "libsctprep-%s.so" % digest)
    if os.path.exists(so):
        return so
    hdr = os.path.join(_BUILD, "prep_constants.h")
    with open(hdr, "w") as fh:
        fh.write(header)
    return so if _cc_build(src, so, _BUILD) else None


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            so = _compile()
            if so is None:
                return None
            lib = ctypes.CDLL(so)
            lib.sct_prepare_batch.restype = ctypes.c_int
            lib.sct_prepare_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p]
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def available() -> bool:
    return _load() is not None


def prepare_batch_native(pub_arr: np.ndarray, sig_arr: np.ndarray,
                         msgs: list) -> Optional[dict]:
    """(n,32)/(n,64) uint8 + message list → device-ready arrays, or None
    when the native library is unavailable. Rows with wrong-length keys or
    sigs must be pre-zeroed by the caller (same contract as _pack32)."""
    lib = _load()
    if lib is None:
        return None
    n = pub_arr.shape[0]
    blob = b"".join(msgs)
    off = np.zeros(n + 1, np.uint64)
    np.cumsum([len(m) for m in msgs], out=off[1:])
    ay = np.empty((n, 20), np.int32)
    ry = np.empty((n, 20), np.int32)
    a_sign = np.empty(n, np.int32)
    r_sign = np.empty(n, np.int32)
    s_nibs = np.empty((n, 64), np.int32)
    k_nibs = np.empty((n, 64), np.int32)
    pre_ok = np.empty(n, np.uint8)
    pub_c = np.ascontiguousarray(pub_arr)
    sig_c = np.ascontiguousarray(sig_arr)
    msg_c = np.frombuffer(blob, np.uint8) if blob else \
        np.zeros(1, np.uint8)
    lib.sct_prepare_batch(
        pub_c.ctypes.data, sig_c.ctypes.data, msg_c.ctypes.data,
        off.ctypes.data, n,
        ay.ctypes.data, a_sign.ctypes.data,
        ry.ctypes.data, r_sign.ctypes.data,
        s_nibs.ctypes.data, k_nibs.ctypes.data, pre_ok.ctypes.data)
    return {"ay": ay, "a_sign": a_sign, "ry": ry, "r_sign": r_sign,
            "s_nibs": s_nibs, "k_nibs": k_nibs,
            "pre_ok": pre_ok.astype(bool)}


# --------------------------------------------------------------------------
# Native XDR serializer (_sctxdr extension): compiles codec type trees into
# flat programs interpreted in C. xdr_bytes() prefers this engine; the
# pure-Python fastcodec stays the fallback and the behavioral oracle.

_XDR_MOD = None
_XDR_TRIED = False


def _compile_xdr_ext() -> None:
    """Build native/xdrc.c into an importable CPython extension, cached
    under build/ keyed by (source hash, interpreter ABI tag) — extension
    modules are not ABI-stable across CPython versions, so a cached build
    must never be reused by a different interpreter."""
    global _XDR_MOD, _XDR_TRIED
    with _LOCK:
        if _XDR_TRIED:
            return
        if os.environ.get("SCT_NATIVE_XDR", "1") == "0":
            _XDR_TRIED = True
            return
        import hashlib
        import importlib.util
        import sysconfig

        os.makedirs(_BUILD, exist_ok=True)
        src = os.path.join(_DIR, "xdrc.c")
        with open(src, "rb") as fh:
            digest = hashlib.sha256(fh.read()).hexdigest()[:16]
        tag = getattr(sys.implementation, "cache_tag", "py")
        so = os.path.join(_BUILD, "_sctxdr-%s-%s.so" % (tag, digest))
        if not os.path.exists(so):
            inc = sysconfig.get_paths()["include"]
            if not _cc_build(src, so, inc):
                _XDR_TRIED = True
                return
        try:
            spec = importlib.util.spec_from_file_location("_sctxdr", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _XDR_MOD = mod
        except Exception:
            _XDR_MOD = None
        _XDR_TRIED = True


def _build_xdr_spec(t, nodes, memo):
    """Flatten a codec type combinator into the C program's node list;
    returns the node index. `memo` breaks recursion (SCPQuorumSet nests
    itself) by reserving an index before children compile."""
    from ..xdr import codec as C

    key = id(t)
    if key in memo:
        return memo[key]
    idx = len(nodes)
    memo[key] = idx
    nodes.append(None)  # reserve

    if isinstance(t, C._Int):
        size = t._s.size
        signed = 1 if t._lo < 0 else 0
        nodes[idx] = (0, size, signed)
    elif isinstance(t, C._Bool):
        nodes[idx] = (1, 0, 0)
    elif isinstance(t, C.Opaque):
        nodes[idx] = (2, t.n, 0)
    elif isinstance(t, C.VarOpaque):
        nodes[idx] = (3, t.maxn, 0)
    elif isinstance(t, C.XdrString):
        nodes[idx] = (4, t._o.maxn, 0)
    elif isinstance(t, C.FixedArray):
        c = _build_xdr_spec(t.elem, nodes, memo)
        nodes[idx] = (5, t.n, c)
    elif isinstance(t, C.VarArray):
        c = _build_xdr_spec(t.elem, nodes, memo)
        nodes[idx] = (6, t.maxn, c)
    elif isinstance(t, C.OptionalT):
        c = _build_xdr_spec(t.elem, nodes, memo)
        nodes[idx] = (7, 0, c)
    elif isinstance(t, C.EnumT):
        nodes[idx] = (8, 0, 0, tuple(sorted(t.values)))
    elif isinstance(t, type) and issubclass(t, C.XdrStruct):
        fields = tuple(
            (n, _build_xdr_spec(ft, nodes, memo)) for n, ft in t.xdr_fields)
        nodes[idx] = (9, 0, 0, fields, t)
    elif isinstance(t, type) and issubclass(t, C.XdrUnion):
        sw = _build_xdr_spec(t.xdr_switch_type, nodes, memo)
        arms = tuple(
            (d, -1 if at is None else _build_xdr_spec(at, nodes, memo))
            for d, (an, at) in t.xdr_arms.items())
        if t.xdr_default is None:
            default = -2
        elif t.xdr_default[1] is None:
            default = -1
        else:
            default = _build_xdr_spec(t.xdr_default[1], nodes, memo)
        nodes[idx] = (10, sw, 0, (arms, default), t)
    else:
        raise TypeError("no native program for %r" % (t,))
    return idx


def _xdr_program(t):
    """Compiled program for a type, memoized on the class (pack and
    unpack share one program)."""
    _compile_xdr_ext()
    if _XDR_MOD is None:
        return None
    cached = t.__dict__.get("_native_prog") if isinstance(t, type) \
        else getattr(t, "_native_prog", None)
    if cached is not None:
        return cached or None
    try:
        nodes = []
        _build_xdr_spec(t, nodes, {})
        prog = _XDR_MOD.compile(tuple(nodes))
    except TypeError:
        prog = None
    try:
        t._native_prog = prog if prog is not None else False
    except (AttributeError, TypeError):
        pass
    return prog


def xdr_pack_fn(t):
    """Native pack function for a codec type, or None when the extension
    is unavailable or the type has a combinator the program can't express
    (callers fall back to fastcodec)."""
    prog = _xdr_program(t)
    if prog is None:
        return None
    pack = _XDR_MOD.pack

    def f(v, prog=prog, pack=pack):
        return pack(prog, v)
    return f


def xdr_unpack_fn(t):
    """Native unpack: f(buf, pos=0) -> (value, end), or None (fallback)."""
    prog = _xdr_program(t)
    if prog is None:
        return None
    unpack = _XDR_MOD.unpack

    def f(buf, pos=0, prog=prog, unpack=unpack):
        return unpack(prog, buf, pos)
    return f
