"""Native (C) batched host-prep for the ed25519 verifier.

Builds `prep.c` into a shared library on first use (cc -O2, cached under
build/) and exposes it through ctypes. The numpy/hashlib path in
ops/ed25519.py remains the fallback — the native path must produce
bit-identical arrays (tests/test_native_prep.py asserts parity).

Why C here: the per-item SHA-512 + mod-L loop is the one host-side cost
that can't be numpy-vectorized, and at the 100K sigs/s north star the
Python loop overhead alone would eat ~15% of a core (VERDICT r2 weak #7).
One C call per batch removes Python from the loop entirely.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _compile() -> Optional[str]:
    import hashlib
    import tempfile

    os.makedirs(_BUILD, exist_ok=True)
    src = os.path.join(_DIR, "prep.c")
    gen = os.path.join(_DIR, "gen_constants.py")
    from .gen_constants import header_text
    header = header_text()
    # hash ALL inputs into the artifact name: a constants or source change
    # can never silently reuse a stale library
    with open(src, "rb") as fh:
        digest = hashlib.sha256(
            fh.read() + header.encode() +
            open(gen, "rb").read()).hexdigest()[:16]
    so = os.path.join(_BUILD, "libsctprep-%s.so" % digest)
    if os.path.exists(so):
        return so
    hdr = os.path.join(_BUILD, "prep_constants.h")
    with open(hdr, "w") as fh:
        fh.write(header)
    for cc in ("cc", "gcc", "g++"):
        tmp = tempfile.NamedTemporaryFile(
            dir=_BUILD, suffix=".so", delete=False)
        tmp.close()
        try:
            r = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-I", _BUILD,
                 "-o", tmp.name, src],
                capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            os.unlink(tmp.name)
            continue
        if r.returncode == 0:
            os.rename(tmp.name, so)  # atomic: concurrent builders race-free
            return so
        os.unlink(tmp.name)
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            so = _compile()
            if so is None:
                return None
            lib = ctypes.CDLL(so)
            lib.sct_prepare_batch.restype = ctypes.c_int
            lib.sct_prepare_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p]
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def available() -> bool:
    return _load() is not None


def prepare_batch_native(pub_arr: np.ndarray, sig_arr: np.ndarray,
                         msgs: list) -> Optional[dict]:
    """(n,32)/(n,64) uint8 + message list → device-ready arrays, or None
    when the native library is unavailable. Rows with wrong-length keys or
    sigs must be pre-zeroed by the caller (same contract as _pack32)."""
    lib = _load()
    if lib is None:
        return None
    n = pub_arr.shape[0]
    blob = b"".join(msgs)
    off = np.zeros(n + 1, np.uint64)
    np.cumsum([len(m) for m in msgs], out=off[1:])
    ay = np.empty((n, 20), np.int32)
    ry = np.empty((n, 20), np.int32)
    a_sign = np.empty(n, np.int32)
    r_sign = np.empty(n, np.int32)
    s_nibs = np.empty((n, 64), np.int32)
    k_nibs = np.empty((n, 64), np.int32)
    pre_ok = np.empty(n, np.uint8)
    pub_c = np.ascontiguousarray(pub_arr)
    sig_c = np.ascontiguousarray(sig_arr)
    msg_c = np.frombuffer(blob, np.uint8) if blob else \
        np.zeros(1, np.uint8)
    lib.sct_prepare_batch(
        pub_c.ctypes.data, sig_c.ctypes.data, msg_c.ctypes.data,
        off.ctypes.data, n,
        ay.ctypes.data, a_sign.ctypes.data,
        ry.ctypes.data, r_sign.ctypes.data,
        s_nibs.ctypes.data, k_nibs.ctypes.data, pre_ok.ctypes.data)
    return {"ay": ay, "a_sign": a_sign, "ry": ry, "r_sign": r_sign,
            "s_nibs": s_nibs, "k_nibs": k_nibs,
            "pre_ok": pre_ok.astype(bool)}
