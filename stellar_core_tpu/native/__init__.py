"""Native (C) batched host-prep for the ed25519 verifier.

Builds `prep.c` into a shared library on first use (cc -O2, cached under
build/) and exposes it through ctypes. The numpy/hashlib path in
ops/ed25519.py remains the fallback — the native path must produce
bit-identical arrays (tests/test_native_prep.py asserts parity).

Why C here: the per-item SHA-512 + mod-L loop is the one host-side cost
that can't be numpy-vectorized, and at the 100K sigs/s north star the
Python loop overhead alone would eat ~15% of a core (VERDICT r2 weak #7).
One C call per batch removes Python from the loop entirely.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
# SCT_SANITIZE reroutes every native build into a sanitizer-specific
# build dir: "1" (or "address") -> build/sanitized/ with
# -fsanitize=address,undefined, "thread" -> build/tsan/ with
# -fsanitize=thread. tools/build_native_sanitized.sh compiles all the
# extensions there, and the `sanitize`-marked differential tests run
# under them with libasan/libtsan preloaded (docs/static-analysis.md
# "Sanitized native builds"). Read at import so one process is wholly
# sanitized or wholly not — mixing sanitized and plain libs in-process
# is UB, and ASan and TSan are mutually exclusive per process.
_SAN_RAW = os.environ.get("SCT_SANITIZE", "")
_SAN_MODES = {"": "", "0": "", "1": "address", "address": "address",
              "thread": "thread"}
if _SAN_RAW not in _SAN_MODES:
    # fail LOUDLY: a typo ('tsan', 'asan') silently producing a plain
    # build would make the sanitizer run vacuously clean
    raise RuntimeError(
        "SCT_SANITIZE=%r is not a sanitize mode (use 1/address for "
        "ASan+UBSan, thread for TSan, 0/unset for none)" % _SAN_RAW)
SANITIZE_MODE = _SAN_MODES[_SAN_RAW]
SANITIZE = SANITIZE_MODE != ""   # truthy back-compat alias
if SANITIZE_MODE == "thread":
    _BUILD = os.path.join(_DIR, "build", "tsan")
    _SANITIZE_FLAGS = ["-fsanitize=thread",
                       "-fno-omit-frame-pointer", "-g"]
elif SANITIZE_MODE == "address":
    _BUILD = os.path.join(_DIR, "build", "sanitized")
    _SANITIZE_FLAGS = ["-fsanitize=address,undefined",
                       "-fno-omit-frame-pointer", "-g"]
else:
    _BUILD = os.path.join(_DIR, "build")
    _SANITIZE_FLAGS = []
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _cc_build(src_path: str, so_path: str, include_dir: str) -> bool:
    """Try cc/gcc/g++ -O2 -shared -fPIC; atomic-rename into so_path.
    Shared by the prep library and the XDR extension builds."""
    import tempfile
    extra = list(_SANITIZE_FLAGS)
    # the compiler must NOT inherit a sanitizer-runtime LD_PRELOAD: the
    # preload is for loading the built .so into THIS process, and a
    # TSan-preloaded python forking gcc can deadlock in the runtime's
    # fork interceptor (observed: 5-minute wedge under SCT_SANITIZE=thread)
    cc_env = {k: v for k, v in os.environ.items() if k != "LD_PRELOAD"}
    for cc in ("cc", "gcc", "g++"):
        tmp = tempfile.NamedTemporaryFile(
            dir=_BUILD, suffix=".so", delete=False)
        tmp.close()
        try:
            # -pthread: applyc.c's parallel close spawns worker threads;
            # harmless for the single-threaded extensions
            r = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-pthread"] + extra +
                ["-I", include_dir, "-o", tmp.name, src_path],
                capture_output=True, text=True, timeout=300, env=cc_env)
        except (OSError, subprocess.TimeoutExpired):
            os.unlink(tmp.name)
            continue
        if r.returncode == 0:
            os.rename(tmp.name, so_path)  # atomic: concurrent builders ok
            return True
        os.unlink(tmp.name)
    return False


def _compile() -> Optional[str]:
    import hashlib

    os.makedirs(_BUILD, exist_ok=True)
    src = os.path.join(_DIR, "prep.c")
    gen = os.path.join(_DIR, "gen_constants.py")
    from .gen_constants import header_text
    header = header_text()
    # hash ALL inputs into the artifact name: a constants or source change
    # can never silently reuse a stale library
    with open(src, "rb") as fh:
        digest = hashlib.sha256(
            fh.read() + header.encode() +
            open(gen, "rb").read()).hexdigest()[:16]
    so = os.path.join(_BUILD, "libsctprep-%s.so" % digest)
    if os.path.exists(so):
        return so
    hdr = os.path.join(_BUILD, "prep_constants.h")
    with open(hdr, "w") as fh:
        fh.write(header)
    return so if _cc_build(src, so, _BUILD) else None


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            so = _compile()
            if so is None:
                return None
            lib = ctypes.CDLL(so)
            lib.sct_prepare_batch.restype = ctypes.c_int
            lib.sct_prepare_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p]
            lib.sct_cache_keys.restype = ctypes.c_int
            lib.sct_cache_keys.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def available() -> bool:
    return _load() is not None


def prepare_batch_native(pub_arr: np.ndarray, sig_arr: np.ndarray,
                         msgs: list) -> Optional[dict]:
    """(n,32)/(n,64) uint8 + message list → device-ready arrays, or None
    when the native library is unavailable. Rows with wrong-length keys or
    sigs must be pre-zeroed by the caller (same contract as _pack32)."""
    lib = _load()
    if lib is None:
        return None
    n = pub_arr.shape[0]
    blob = b"".join(msgs)
    off = np.zeros(n + 1, np.uint64)
    np.cumsum([len(m) for m in msgs], out=off[1:])
    ay = np.empty((n, 20), np.int32)
    ry = np.empty((n, 20), np.int32)
    a_sign = np.empty(n, np.int32)
    r_sign = np.empty(n, np.int32)
    s_nibs = np.empty((n, 64), np.int32)
    k_nibs = np.empty((n, 64), np.int32)
    pre_ok = np.empty(n, np.uint8)
    pub_c = np.ascontiguousarray(pub_arr)
    sig_c = np.ascontiguousarray(sig_arr)
    msg_c = np.frombuffer(blob, np.uint8) if blob else \
        np.zeros(1, np.uint8)
    lib.sct_prepare_batch(
        pub_c.ctypes.data, sig_c.ctypes.data, msg_c.ctypes.data,
        off.ctypes.data, n,
        ay.ctypes.data, a_sign.ctypes.data,
        ry.ctypes.data, r_sign.ctypes.data,
        s_nibs.ctypes.data, k_nibs.ctypes.data, pre_ok.ctypes.data)
    return {"ay": ay, "a_sign": a_sign, "ry": ry, "r_sign": r_sign,
            "s_nibs": s_nibs, "k_nibs": k_nibs,
            "pre_ok": pre_ok.astype(bool)}


def cache_keys_native(triples) -> Optional[list]:
    """[(key32, sig64, msg)] → [sha256(key‖sig‖msg)] in one C call, or
    None (malformed lengths / library unavailable — callers fall back to
    the per-triple hashlib path). One drain's worth of verify-cache keys
    is ~1/3 of the host-side prewarm cost when hashed in Python."""
    lib = _load()
    n = len(triples)
    if lib is None or n == 0:
        return None
    pubs = b"".join(t[0] for t in triples)
    sigs = b"".join(t[1] for t in triples)
    if len(pubs) != 32 * n or len(sigs) != 64 * n:
        return None
    msgs = b"".join(t[2] for t in triples)
    off = np.zeros(n + 1, np.uint64)
    np.cumsum([len(t[2]) for t in triples], out=off[1:])
    msg_c = np.frombuffer(msgs, np.uint8) if msgs else np.zeros(1, np.uint8)
    out = np.empty(32 * n, np.uint8)
    lib.sct_cache_keys(pubs, sigs, msg_c.ctypes.data, off.ctypes.data, n,
                       out.ctypes.data)
    ob = out.tobytes()
    return [ob[32 * i:32 * i + 32] for i in range(n)]


# --------------------------------------------------------------------------
# Native ed25519/X25519 (ed25519c.c): the CPU crypto floor when the
# `cryptography` package is absent. Loaded via ctypes like prep.c; shares
# the generated prep_constants.h. crypto/fallback.py holds the pure-Python
# oracle used when no compiler is available.

_ED_LIB = None
_ED_TRIED = False


class _Ed25519Native:
    """Thin ctypes wrapper; one instance per process."""

    def __init__(self, lib) -> None:
        self._lib = lib

    def public(self, seed: bytes) -> bytes:
        out = ctypes.create_string_buffer(32)
        self._lib.sct_ed25519_public(seed, out)
        return out.raw

    def sign(self, seed: bytes, msg: bytes) -> bytes:
        out = ctypes.create_string_buffer(64)
        self._lib.sct_ed25519_sign(seed, msg, len(msg), out)
        return out.raw

    def verify(self, pub: bytes, sig: bytes, msg: bytes) -> bool:
        if len(pub) != 32 or len(sig) != 64:
            return False
        return bool(self._lib.sct_ed25519_verify(pub, sig, msg, len(msg)))

    def verify_batch(self, triples) -> list:
        """[(key32, sig64, msg)] → [bool] in one C call."""
        n = len(triples)
        if n == 0:
            return []
        pubs = b"".join(t[0] for t in triples)
        sigs = b"".join(t[1] for t in triples)
        if len(pubs) != 32 * n or len(sigs) != 64 * n:
            # odd-length keys/sigs: per-item path handles rejections
            return [self.verify(k, s, m) for (k, s, m) in triples]
        msgs = b"".join(t[2] for t in triples)
        off = np.zeros(n + 1, np.uint64)
        np.cumsum([len(t[2]) for t in triples], out=off[1:])
        out = np.empty(n, np.uint8)
        self._lib.sct_ed25519_verify_batch(
            pubs, sigs, msgs or b"\x00",
            off.ctypes.data_as(ctypes.c_void_p), n,
            out.ctypes.data_as(ctypes.c_void_p))
        return out.astype(bool).tolist()

    def x25519(self, scalar: bytes, u: bytes) -> bytes:
        out = ctypes.create_string_buffer(32)
        self._lib.sct_x25519(scalar, u, out)
        return out.raw


def ed25519_native() -> Optional[_Ed25519Native]:
    """Build + load the native ed25519 library, or None (callers fall
    back to the pure-Python path). Gated by SCT_NATIVE_ED25519."""
    global _ED_LIB, _ED_TRIED
    if _ED_TRIED:
        return _ED_LIB
    with _LOCK:
        if _ED_TRIED:
            return _ED_LIB
        try:
            if os.environ.get("SCT_NATIVE_ED25519", "1") == "0":
                return None
            import hashlib
            os.makedirs(_BUILD, exist_ok=True)
            src = os.path.join(_DIR, "ed25519c.c")
            from .gen_constants import header_text
            header = header_text()
            with open(src, "rb") as fh:
                digest = hashlib.sha256(
                    fh.read() + header.encode()).hexdigest()[:16]
            so = os.path.join(_BUILD, "libscted25519-%s.so" % digest)
            if not os.path.exists(so):
                hdr = os.path.join(_BUILD, "prep_constants.h")
                with open(hdr, "w") as fh:
                    fh.write(header)
                if not _cc_build(src, so, _BUILD):
                    return None
            lib = ctypes.CDLL(so)
            lib.sct_ed25519_public.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p]
            lib.sct_ed25519_sign.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
                ctypes.c_char_p]
            lib.sct_ed25519_verify.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_uint64]
            lib.sct_ed25519_verify.restype = ctypes.c_int
            lib.sct_ed25519_verify_batch.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
            lib.sct_x25519.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
            if lib.sct_ed25519_init() != 0:
                return None
            _ED_LIB = _Ed25519Native(lib)
        except Exception:
            _ED_LIB = None
        finally:
            _ED_TRIED = True
        return _ED_LIB


# --------------------------------------------------------------------------
# Native transaction-apply engine (_sctapply extension, applyc.c): the
# replay-loop fast path. ledger/native_apply.py is the only caller; the
# Python apply path stays the fallback and the differential oracle
# (tests/test_native_apply.py).

_APPLY_MOD = None
_APPLY_TRIED = False


def apply_engine():
    """The _sctapply module, or None (gated by SCT_NATIVE_APPLY, absent
    compiler, or build failure — callers fall back to Python apply)."""
    global _APPLY_MOD, _APPLY_TRIED
    if _APPLY_TRIED:
        return _APPLY_MOD
    with _LOCK:
        if _APPLY_TRIED:
            return _APPLY_MOD
        _APPLY_TRIED = True
        if os.environ.get("SCT_NATIVE_APPLY", "1") == "0":
            return None
        import hashlib
        import importlib.util
        import sysconfig

        try:
            os.makedirs(_BUILD, exist_ok=True)
            src = os.path.join(_DIR, "applyc.c")
            with open(src, "rb") as fh:
                digest = hashlib.sha256(fh.read()).hexdigest()[:16]
            tag = getattr(sys.implementation, "cache_tag", "py")
            so = os.path.join(_BUILD, "_sctapply-%s-%s.so" % (tag, digest))
            if not os.path.exists(so):
                inc = sysconfig.get_paths()["include"]
                if not _cc_build(src, so, inc):
                    return None
            spec = importlib.util.spec_from_file_location("_sctapply", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _APPLY_MOD = mod
        except Exception:
            _APPLY_MOD = None
        return _APPLY_MOD


# --------------------------------------------------------------------------
# Native XDR serializer (_sctxdr extension): compiles codec type trees into
# flat programs interpreted in C. xdr_bytes() prefers this engine; the
# pure-Python fastcodec stays the fallback and the behavioral oracle.

_XDR_MOD = None
_XDR_TRIED = False


def _compile_xdr_ext() -> None:
    """Build native/xdrc.c into an importable CPython extension, cached
    under build/ keyed by (source hash, interpreter ABI tag) — extension
    modules are not ABI-stable across CPython versions, so a cached build
    must never be reused by a different interpreter."""
    global _XDR_MOD, _XDR_TRIED
    with _LOCK:
        if _XDR_TRIED:
            return
        if os.environ.get("SCT_NATIVE_XDR", "1") == "0":
            _XDR_TRIED = True
            return
        import hashlib
        import importlib.util
        import sysconfig

        os.makedirs(_BUILD, exist_ok=True)
        src = os.path.join(_DIR, "xdrc.c")
        with open(src, "rb") as fh:
            digest = hashlib.sha256(fh.read()).hexdigest()[:16]
        tag = getattr(sys.implementation, "cache_tag", "py")
        so = os.path.join(_BUILD, "_sctxdr-%s-%s.so" % (tag, digest))
        if not os.path.exists(so):
            inc = sysconfig.get_paths()["include"]
            if not _cc_build(src, so, inc):
                _XDR_TRIED = True
                return
        try:
            spec = importlib.util.spec_from_file_location("_sctxdr", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _XDR_MOD = mod
        except Exception:
            _XDR_MOD = None
        _XDR_TRIED = True


def _build_xdr_spec(t, nodes, memo):
    """Flatten a codec type combinator into the C program's node list;
    returns the node index. `memo` breaks recursion (SCPQuorumSet nests
    itself) by reserving an index before children compile."""
    from ..xdr import codec as C

    key = id(t)
    if key in memo:
        return memo[key]
    idx = len(nodes)
    memo[key] = idx
    nodes.append(None)  # reserve

    if isinstance(t, C._Int):
        size = t._s.size
        signed = 1 if t._lo < 0 else 0
        nodes[idx] = (0, size, signed)
    elif isinstance(t, C._Bool):
        nodes[idx] = (1, 0, 0)
    elif isinstance(t, C.Opaque):
        nodes[idx] = (2, t.n, 0)
    elif isinstance(t, C.VarOpaque):
        nodes[idx] = (3, t.maxn, 0)
    elif isinstance(t, C.XdrString):
        nodes[idx] = (4, t._o.maxn, 0)
    elif isinstance(t, C.FixedArray):
        c = _build_xdr_spec(t.elem, nodes, memo)
        nodes[idx] = (5, t.n, c)
    elif isinstance(t, C.VarArray):
        c = _build_xdr_spec(t.elem, nodes, memo)
        nodes[idx] = (6, t.maxn, c)
    elif isinstance(t, C.OptionalT):
        c = _build_xdr_spec(t.elem, nodes, memo)
        nodes[idx] = (7, 0, c)
    elif isinstance(t, C.EnumT):
        nodes[idx] = (8, 0, 0, tuple(sorted(t.values)))
    elif isinstance(t, type) and issubclass(t, C.XdrStruct):
        fields = tuple(
            (n, _build_xdr_spec(ft, nodes, memo)) for n, ft in t.xdr_fields)
        nodes[idx] = (9, 0, 0, fields, t)
    elif isinstance(t, type) and issubclass(t, C.XdrUnion):
        sw = _build_xdr_spec(t.xdr_switch_type, nodes, memo)
        arms = tuple(
            (d, -1 if at is None else _build_xdr_spec(at, nodes, memo))
            for d, (an, at) in t.xdr_arms.items())
        if t.xdr_default is None:
            default = -2
        elif t.xdr_default[1] is None:
            default = -1
        else:
            default = _build_xdr_spec(t.xdr_default[1], nodes, memo)
        nodes[idx] = (10, sw, 0, (arms, default), t)
    else:
        raise TypeError("no native program for %r" % (t,))
    return idx


def _xdr_program(t):
    """Compiled program for a type, memoized on the class (pack and
    unpack share one program)."""
    _compile_xdr_ext()
    if _XDR_MOD is None:
        return None
    cached = t.__dict__.get("_native_prog") if isinstance(t, type) \
        else getattr(t, "_native_prog", None)
    if cached is not None:
        return cached or None
    try:
        nodes = []
        _build_xdr_spec(t, nodes, {})
        prog = _XDR_MOD.compile(tuple(nodes))
    except TypeError:
        prog = None
    try:
        t._native_prog = prog if prog is not None else False
    except (AttributeError, TypeError):
        pass
    return prog


def xdr_pack_fn(t):
    """Native pack function for a codec type, or None when the extension
    is unavailable or the type has a combinator the program can't express
    (callers fall back to fastcodec)."""
    prog = _xdr_program(t)
    if prog is None:
        return None
    pack = _XDR_MOD.pack

    def f(v, prog=prog, pack=pack):
        return pack(prog, v)
    return f


def xdr_unpack_fn(t):
    """Native unpack: f(buf, pos=0) -> (value, end), or None (fallback)."""
    prog = _xdr_program(t)
    if prog is None:
        return None
    unpack = _XDR_MOD.unpack

    def f(buf, pos=0, prog=prog, unpack=unpack):
        return unpack(prog, buf, pos)
    return f
