/* Native transaction-apply fast path for catchup replay.
 *
 * docs/perf-replay.md proves the end-to-end replay ratio is Amdahl-capped
 * by ~2.2 ms/tx of Python apply cost once crypto is batched; this module
 * removes Python from the per-tx loop the same way xdrc.c removed it from
 * serialization. It implements the fee and apply phases of a ledger close
 * for the subset the replay workload consists of — plain v1 envelopes
 * whose operations are CREATE_ACCOUNT and PAYMENT (native or credit
 * assets), sources with ed25519-only signer sets, protocol >= 10 — and
 * returns None for anything else so the Python path (the semantics oracle,
 * tests/test_native_apply.py) handles the close instead.
 *
 * Contract: entry-for-entry identical output to the Python path — same
 * LedgerTxn delta (keys, pre-images, post-images, first-touch order), same
 * TransactionResult XDR, same fee/tx/op meta XDR — so header hashes are
 * bit-identical whichever path applied the close.
 *
 * Entry points (see native/__init__.py apply_engine()):
 *   apply_close(params, envs, hashes, lookup, verify) -> dict | None
 *     params: header scalars; envs/hashes: per-tx envelope XDR + contents
 *     hash; lookup(key_xdr)->entry_xdr|None reads close-start state;
 *     verify([(key32,sig,msg)])->[bool] is the batch crypto boundary
 *     (BatchSigVerifier.prewarm_many — cache-aware, one device batch).
 *     A successful close's dict carries "op_stats": {op_type: (count,
 *     ns)} — the close cockpit's per-op attribution (ISSUE 9). An
 *     unsupported input returns {"bail": "<reason>"} (classified:
 *     "op-<n>" names the first unsupported op type, "muxed-account",
 *     "multisig-shape", "signer-key-type", "entry-kind", ...) so
 *     ledger/native_apply.py can meter ledger.apply.native-bail.<reason>;
 *     None is kept for protocol-version ineligibility.
 *
 * State model: an overlay of parsed entries keyed by LedgerKey bytes.
 * Only balance/seqNum/existence ever mutate under the supported ops, so
 * updated entries serialize as byte patches of their original blobs —
 * byte-identical round-trips by construction. A 4-deep savepoint journal
 * (close / fee+tx / ops / op) mirrors the nested-LedgerTxn commit and
 * rollback semantics, including per-level first-touch-order deltas.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <time.h>

#define LET_ACCOUNT 0
#define LET_TRUSTLINE 1

/* TransactionResultCode */
#define txSUCCESS 0
#define txFAILED (-1)
#define txTOO_EARLY (-2)
#define txTOO_LATE (-3)
#define txMISSING_OPERATION (-4)
#define txBAD_SEQ (-5)
#define txBAD_AUTH (-6)
#define txNO_ACCOUNT (-8)
#define txINSUFFICIENT_FEE (-9)
#define txBAD_AUTH_EXTRA (-10)
#define txINTERNAL_ERROR (-11)

/* OperationResultCode */
#define opINNER 0
#define opNO_ACCOUNT (-2)

/* OperationType */
#define OP_CREATE_ACCOUNT 0
#define OP_PAYMENT 1
#define OP_SET_OPTIONS 5

/* SetOptionsResultCode */
#define SO_SUCCESS 0
#define SO_LOW_RESERVE (-1)
#define SO_TOO_MANY_SIGNERS (-2)
#define SO_INVALID_INFLATION (-4)
#define SO_CANT_CHANGE (-5)

/* AccountFlags */
#define AUTH_IMMUTABLE_FLAG 0x4
#define MAX_SUBENTRIES 1000

/* CreateAccountResultCode */
#define CA_SUCCESS 0
#define CA_UNDERFUNDED (-2)
#define CA_LOW_RESERVE (-3)
#define CA_ALREADY_EXIST (-4)

/* PaymentResultCode */
#define PAY_SUCCESS 0
#define PAY_UNDERFUNDED (-2)
#define PAY_SRC_NO_TRUST (-3)
#define PAY_SRC_NOT_AUTHORIZED (-4)
#define PAY_NO_DESTINATION (-5)
#define PAY_NO_TRUST (-6)
#define PAY_NOT_AUTHORIZED (-7)
#define PAY_LINE_FULL (-8)
#define PAY_NO_ISSUER (-9)

#define TL_AUTHORIZED 1
#define TL_AUTH_LEVELS_MASK 3

#define INT64_MAXV 0x7fffffffffffffffLL
#define MAXLEVEL 4
#define NBUCKETS 1024
#define MAX_SIGNERS 20
#define MAX_SIGS 20
#define MAX_OPTYPES 16 /* wire op types are 0..13; table rounded up */

typedef struct {
    char *data;
    Py_ssize_t len, cap;
} Buf;

static int buf_put(Buf *b, const void *src, Py_ssize_t n)
{
    if (b->len + n > b->cap) {
        Py_ssize_t cap = b->cap ? b->cap : 256;
        while (cap < b->len + n)
            cap *= 2;
        char *p = PyMem_Realloc(b->data, cap);
        if (!p)
            return -1;
        b->data = p;
        b->cap = cap;
    }
    memcpy(b->data + b->len, src, n);
    b->len += n;
    return 0;
}

static int buf_u32(Buf *b, uint32_t v)
{
    unsigned char w[4] = {(unsigned char)(v >> 24), (unsigned char)(v >> 16),
                          (unsigned char)(v >> 8), (unsigned char)v};
    return buf_put(b, w, 4);
}

static int buf_i32(Buf *b, int32_t v) { return buf_u32(b, (uint32_t)v); }

static int buf_u64(Buf *b, uint64_t v)
{
    unsigned char w[8];
    int i;
    for (i = 0; i < 8; i++)
        w[i] = (unsigned char)(v >> (56 - 8 * i));
    return buf_put(b, w, 8);
}

static int buf_i64(Buf *b, int64_t v) { return buf_u64(b, (uint64_t)v); }

static void wr_u32_at(uint8_t *p, uint32_t v)
{
    p[0] = (uint8_t)(v >> 24);
    p[1] = (uint8_t)(v >> 16);
    p[2] = (uint8_t)(v >> 8);
    p[3] = (uint8_t)v;
}

static void wr_i64_at(uint8_t *p, int64_t sv)
{
    uint64_t v = (uint64_t)sv;
    int i;
    for (i = 0; i < 8; i++)
        p[i] = (uint8_t)(v >> (56 - 8 * i));
}

/* ------------------------------------------------------------- reader */

typedef struct {
    const uint8_t *p;
    Py_ssize_t len, pos;
} Rd;

static int rd_u32(Rd *r, uint32_t *v)
{
    if (r->pos + 4 > r->len)
        return -1;
    const uint8_t *p = r->p + r->pos;
    *v = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | p[3];
    r->pos += 4;
    return 0;
}

static int rd_i64(Rd *r, int64_t *v)
{
    if (r->pos + 8 > r->len)
        return -1;
    const uint8_t *p = r->p + r->pos;
    uint64_t u = 0;
    int i;
    for (i = 0; i < 8; i++)
        u = (u << 8) | p[i];
    *v = (int64_t)u;
    r->pos += 8;
    return 0;
}

static int rd_u64(Rd *r, uint64_t *v)
{
    int64_t s;
    if (rd_i64(r, &s) < 0)
        return -1;
    *v = (uint64_t)s;
    return 0;
}

static const uint8_t *rd_take(Rd *r, Py_ssize_t n)
{
    if (n < 0 || r->pos + n > r->len)
        return NULL;
    const uint8_t *p = r->p + r->pos;
    r->pos += n;
    return p;
}

static int rd_skip_padded(Rd *r, Py_ssize_t n)
{
    Py_ssize_t pad = (4 - (n & 3)) & 3;
    return rd_take(r, n + pad) ? 0 : -1;
}

/* ------------------------------------------------------------- entries */

/* the structural (non-balance/seq) state of an entry — mutable since
   SET_OPTIONS joined the supported subset. Snapshotted whole per save
   level: an ~850-byte copy per first-touch is noise next to one
   signature verify, and byte-exact rollback/diff needs the pre-image
   (a dirty FLAG cannot reproduce Python's touched-but-unchanged
   filtering when an op writes identical values). */
typedef struct {
    uint32_t numSub, flags;
    uint8_t thresholds[4];
    int nsigners;
    uint8_t signer_keys[MAX_SIGNERS][32];
    uint32_t signer_weights[MAX_SIGNERS];
    int has_infl;
    uint8_t infl[32];
    int home_len;
    uint8_t home[32];
} StructState;

typedef struct {
    int seen, exists;
    int64_t balance, seqNum;
    StructState st;
} EntrySave;

typedef struct Entry {
    struct Entry *next;
    uint32_t hash;
    uint8_t *keyb;
    int keylen;
    uint8_t *base; /* close-start LedgerEntry blob (owned); NULL if absent */
    int baselen;
    int type; /* LET_ACCOUNT / LET_TRUSTLINE */
    int exists;
    int64_t balance, seqNum;
    StructState st;      /* live structural state */
    StructState base_st; /* as parsed from base (patch fast-path check) */
    uint32_t last_modified; /* base blob's lastModifiedLedgerSeq */
    int ext_v;              /* AccountEntryExt version in base (0/1) */
    /* parsed from base (accounts): */
    int64_t liab_buying, liab_selling;
    /* trustlines: */
    int64_t tl_limit;
    /* patch offsets into base blob: */
    int off_balance, off_seq;
    /* created accounts: */
    uint8_t acc_key[32];
    uint32_t created_seq;
    EntrySave save[MAXLEVEL];
} Entry;

static int struct_eq(const StructState *a, const StructState *b)
{
    int i;
    if (a->numSub != b->numSub || a->flags != b->flags ||
        memcmp(a->thresholds, b->thresholds, 4) != 0 ||
        a->nsigners != b->nsigners || a->has_infl != b->has_infl ||
        a->home_len != b->home_len)
        return 0;
    if (a->has_infl && memcmp(a->infl, b->infl, 32) != 0)
        return 0;
    if (a->home_len && memcmp(a->home, b->home, a->home_len) != 0)
        return 0;
    for (i = 0; i < a->nsigners; i++)
        if (memcmp(a->signer_keys[i], b->signer_keys[i], 32) != 0 ||
            a->signer_weights[i] != b->signer_weights[i])
            return 0;
    return 1;
}

typedef struct {
    Entry *buckets[NBUCKETS];
    Entry **all;
    int nall, capall;
    Entry **touched[MAXLEVEL];
    int ntouched[MAXLEVEL], captouched[MAXLEVEL];
    PyObject *lookup, *verify;
    int64_t feePool;
    uint32_t ledgerVersion, ledgerSeq;
    uint64_t closeTime;
    int64_t baseFee, baseReserve, effBase;
    int bail;  /* unsupported input: fall back to the Python path */
    int pyerr; /* a Python exception is set: propagate */
    /* bail forensics (ISSUE 9): first classified reason wins — the
       caller (ledger/native_apply.py) turns it into a
       ledger.apply.native-bail.<reason> meter + span tag so op-coverage
       work (ROADMAP item 2) is ordered by observed traffic */
    const char *bailmsg;
    char bailbuf[48];
    /* per-op-type attribution for the close: apply-loop count and
       CLOCK_MONOTONIC nanoseconds per wire op type, returned as the
       "op_stats" table so native closes attribute like Python ones */
    int64_t op_cnt[MAX_OPTYPES];
    int64_t op_ns[MAX_OPTYPES];
} Ctx;

static void set_bail_reason(Ctx *c, const char *msg)
{
    if (!c->bailmsg)
        c->bailmsg = msg;
}

static int64_t now_ns(void)
{
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + (int64_t)ts.tv_nsec;
}

static uint32_t fnv1a(const uint8_t *p, int n)
{
    uint32_t h = 2166136261u;
    int i;
    for (i = 0; i < n; i++) {
        h ^= p[i];
        h *= 16777619u;
    }
    return h;
}

static void ctx_free(Ctx *c)
{
    int i;
    for (i = 0; i < c->nall; i++) {
        Entry *e = c->all[i];
        PyMem_Free(e->keyb);
        PyMem_Free(e->base);
        PyMem_Free(e);
    }
    PyMem_Free(c->all);
    for (i = 0; i < MAXLEVEL; i++)
        PyMem_Free(c->touched[i]);
}

/* account LedgerEntry blob -> Entry fields; returns -1 on unsupported */
static int parse_account(Ctx *c, Entry *e, const uint8_t *blob, int len)
{
    Rd r = {blob, len, 0};
    uint32_t u, ktype, n;
    int i;
    if (rd_u32(&r, &e->last_modified) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u != LET_ACCOUNT)
        return -1;
    if (rd_u32(&r, &ktype) < 0 || ktype != 0)
        return -1;
    const uint8_t *key = rd_take(&r, 32);
    if (!key)
        return -1;
    memcpy(e->acc_key, key, 32);
    e->off_balance = (int)r.pos;
    if (rd_i64(&r, &e->balance) < 0)
        return -1;
    e->off_seq = (int)r.pos;
    if (rd_i64(&r, &e->seqNum) < 0)
        return -1;
    if (rd_u32(&r, &e->st.numSub) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u > 1) /* inflationDest optional */
        return -1;
    e->st.has_infl = (int)u;
    if (u == 1) {
        const uint8_t *ip;
        if (rd_u32(&r, &ktype) < 0 || ktype != 0 ||
            !(ip = rd_take(&r, 32)))
            return -1;
        memcpy(e->st.infl, ip, 32);
    }
    if (rd_u32(&r, &e->st.flags) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u > 32) /* homeDomain */
        return -1;
    e->st.home_len = (int)u;
    if (u) {
        Py_ssize_t at = r.pos;
        if (rd_skip_padded(&r, u) < 0)
            return -1;
        memcpy(e->st.home, blob + at, u);
    }
    const uint8_t *th = rd_take(&r, 4);
    if (!th)
        return -1;
    memcpy(e->st.thresholds, th, 4);
    if (rd_u32(&r, &n) < 0)
        return -1;
    if (n > MAX_SIGNERS) {
        set_bail_reason(c, "multisig-shape");
        return -1;
    }
    e->st.nsigners = (int)n;
    for (i = 0; i < e->st.nsigners; i++) {
        if (rd_u32(&r, &ktype) < 0)
            return -1;
        if (ktype != 0) { /* pre-auth-tx / hash-x signers: Python path */
            set_bail_reason(c, "signer-key-type");
            return -1;
        }
        const uint8_t *sk = rd_take(&r, 32);
        if (!sk)
            return -1;
        memcpy(e->st.signer_keys[i], sk, 32);
        if (rd_u32(&r, &e->st.signer_weights[i]) < 0)
            return -1;
    }
    if (rd_u32(&r, &u) < 0 || u > 1) /* AccountEntryExt */
        return -1;
    e->ext_v = (int)u;
    e->liab_buying = e->liab_selling = 0;
    if (u == 1) {
        if (rd_i64(&r, &e->liab_buying) < 0 ||
            rd_i64(&r, &e->liab_selling) < 0)
            return -1;
        if (rd_u32(&r, &u) < 0 || u != 0) /* v1 inner ext */
            return -1;
    }
    if (rd_u32(&r, &u) < 0 || u != 0) /* LedgerEntry ext */
        return -1;
    if (r.pos != r.len)
        return -1;
    e->base_st = e->st;
    return 0;
}

static int parse_trustline(Ctx *c, Entry *e, const uint8_t *blob, int len)
{
    Rd r = {blob, len, 0};
    uint32_t u, atype;
    if (rd_u32(&r, &u) < 0) /* lastModified */
        return -1;
    if (rd_u32(&r, &u) < 0 || u != LET_TRUSTLINE)
        return -1;
    if (rd_u32(&r, &u) < 0 || u != 0 || !rd_take(&r, 32))
        return -1;
    if (rd_u32(&r, &atype) < 0)
        return -1;
    if (atype == 1) {
        if (!rd_take(&r, 4 + 4 + 32))
            return -1;
    } else if (atype == 2) {
        if (!rd_take(&r, 12 + 4 + 32))
            return -1;
    } else
        return -1; /* native trustlines don't exist */
    e->off_balance = (int)r.pos;
    if (rd_i64(&r, &e->balance) < 0)
        return -1;
    if (rd_i64(&r, &e->tl_limit) < 0)
        return -1;
    if (rd_u32(&r, &e->st.flags) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u > 1)
        return -1;
    e->liab_buying = e->liab_selling = 0;
    if (u == 1) {
        if (rd_i64(&r, &e->liab_buying) < 0 ||
            rd_i64(&r, &e->liab_selling) < 0)
            return -1;
        if (rd_u32(&r, &u) < 0 || u != 0)
            return -1;
    }
    if (rd_u32(&r, &u) < 0 || u != 0)
        return -1;
    if (r.pos != r.len)
        return -1;
    e->base_st = e->st;
    return 0;
}

/* overlay get-or-load; NULL means bail/pyerr (check ctx flags) */
static Entry *get_entry(Ctx *c, const uint8_t *keyb, int keylen)
{
    uint32_t h = fnv1a(keyb, keylen);
    Entry *e = c->buckets[h & (NBUCKETS - 1)];
    for (; e; e = e->next)
        if (e->hash == h && e->keylen == keylen &&
            memcmp(e->keyb, keyb, keylen) == 0)
            return e;

    PyObject *kb = PyBytes_FromStringAndSize((const char *)keyb, keylen);
    if (!kb) {
        c->pyerr = 1;
        return NULL;
    }
    PyObject *blob = PyObject_CallFunctionObjArgs(c->lookup, kb, NULL);
    Py_DECREF(kb);
    if (!blob) {
        c->pyerr = 1;
        return NULL;
    }
    e = PyMem_Calloc(1, sizeof(Entry));
    if (!e) {
        Py_DECREF(blob);
        c->pyerr = 1;
        PyErr_NoMemory();
        return NULL;
    }
    e->hash = h;
    e->keylen = keylen;
    e->keyb = PyMem_Malloc(keylen);
    if (!e->keyb) {
        PyMem_Free(e);
        Py_DECREF(blob);
        c->pyerr = 1;
        PyErr_NoMemory();
        return NULL;
    }
    memcpy(e->keyb, keyb, keylen);
    {
        Rd kr = {keyb, keylen, 0};
        uint32_t kt = 0;
        rd_u32(&kr, &kt);
        e->type = (int)kt;
    }
    if (blob == Py_None) {
        e->exists = 0;
    } else if (PyBytes_Check(blob)) {
        Py_ssize_t bl = PyBytes_GET_SIZE(blob);
        e->base = PyMem_Malloc(bl > 0 ? bl : 1);
        if (!e->base) {
            PyMem_Free(e->keyb);
            PyMem_Free(e);
            Py_DECREF(blob);
            c->pyerr = 1;
            PyErr_NoMemory();
            return NULL;
        }
        memcpy(e->base, PyBytes_AS_STRING(blob), bl);
        e->baselen = (int)bl;
        e->exists = 1;
        int rc = (e->type == LET_ACCOUNT)
                     ? parse_account(c, e, e->base, e->baselen)
                     : (e->type == LET_TRUSTLINE)
                           ? parse_trustline(c, e, e->base, e->baselen)
                           : -1;
        if (rc < 0) {
            set_bail_reason(c, "entry-kind");
            c->bail = 1;
            PyMem_Free(e->keyb);
            PyMem_Free(e->base);
            PyMem_Free(e);
            Py_DECREF(blob);
            return NULL;
        }
    } else {
        set_bail_reason(c, "lookup-type");
        c->bail = 1;
        PyMem_Free(e->keyb);
        PyMem_Free(e);
        Py_DECREF(blob);
        return NULL;
    }
    Py_DECREF(blob);
    if (c->nall == c->capall) {
        int cap = c->capall ? c->capall * 2 : 64;
        Entry **p = PyMem_Realloc(c->all, cap * sizeof(Entry *));
        if (!p) {
            PyMem_Free(e->keyb);
            PyMem_Free(e->base);
            PyMem_Free(e);
            c->pyerr = 1;
            PyErr_NoMemory();
            return NULL;
        }
        c->all = p;
        c->capall = cap;
    }
    c->all[c->nall++] = e;
    e->next = c->buckets[h & (NBUCKETS - 1)];
    c->buckets[h & (NBUCKETS - 1)] = e;
    return e;
}

static Entry *get_account(Ctx *c, const uint8_t *accid)
{
    uint8_t keyb[40];
    wr_u32_at(keyb, LET_ACCOUNT);
    wr_u32_at(keyb + 4, 0); /* PUBLIC_KEY_TYPE_ED25519 */
    memcpy(keyb + 8, accid, 32);
    return get_entry(c, keyb, 40);
}

/* trustline key: u32 TRUSTLINE | AccountID | Asset (raw asset bytes) */
static Entry *get_trustline(Ctx *c, const uint8_t *accid,
                            const uint8_t *asset, int assetlen)
{
    uint8_t keyb[40 + 52];
    wr_u32_at(keyb, LET_TRUSTLINE);
    wr_u32_at(keyb + 4, 0);
    memcpy(keyb + 8, accid, 32);
    memcpy(keyb + 40, asset, assetlen);
    return get_entry(c, keyb, 40 + assetlen);
}

/* ----------------------------------------------------- savepoint journal */

static int touch(Ctx *c, Entry *e, int lv)
{
    if (e->save[lv].seen)
        return 0;
    e->save[lv].seen = 1;
    e->save[lv].exists = e->exists;
    e->save[lv].balance = e->balance;
    e->save[lv].seqNum = e->seqNum;
    e->save[lv].st = e->st;
    if (c->ntouched[lv] == c->captouched[lv]) {
        int cap = c->captouched[lv] ? c->captouched[lv] * 2 : 32;
        Entry **p = PyMem_Realloc(c->touched[lv], cap * sizeof(Entry *));
        if (!p) {
            c->pyerr = 1;
            PyErr_NoMemory();
            return -1;
        }
        c->touched[lv] = p;
        c->captouched[lv] = cap;
    }
    c->touched[lv][c->ntouched[lv]++] = e;
    return 0;
}

static int commit_level(Ctx *c, int lv)
{
    int i;
    for (i = 0; i < c->ntouched[lv]; i++) {
        Entry *e = c->touched[lv][i];
        if (!e->save[lv - 1].seen) {
            e->save[lv - 1] = e->save[lv]; /* pre-lv state becomes the
                                              parent's first-touch image */
            e->save[lv - 1].seen = 1;
            if (c->ntouched[lv - 1] == c->captouched[lv - 1]) {
                int cap = c->captouched[lv - 1] ? c->captouched[lv - 1] * 2
                                                : 32;
                Entry **p = PyMem_Realloc(c->touched[lv - 1],
                                          cap * sizeof(Entry *));
                if (!p) {
                    c->pyerr = 1;
                    PyErr_NoMemory();
                    return -1;
                }
                c->touched[lv - 1] = p;
                c->captouched[lv - 1] = cap;
            }
            c->touched[lv - 1][c->ntouched[lv - 1]++] = e;
        }
        e->save[lv].seen = 0;
    }
    c->ntouched[lv] = 0;
    return 0;
}

static void rollback_level(Ctx *c, int lv)
{
    int i;
    for (i = 0; i < c->ntouched[lv]; i++) {
        Entry *e = c->touched[lv][i];
        e->exists = e->save[lv].exists;
        e->balance = e->save[lv].balance;
        e->seqNum = e->save[lv].seqNum;
        e->st = e->save[lv].st;
        e->save[lv].seen = 0;
    }
    c->ntouched[lv] = 0;
}

/* -------------------------------------------------------- serialization */

/* append the LedgerEntry blob for state (exists assumed) */
static int ser_entry(Ctx *c, Entry *e, int64_t balance, int64_t seqNum,
                     const StructState *st, Buf *out)
{
    if (e->base && struct_eq(st, &e->base_st)) {
        /* structure untouched: reuse the base blob bitwise, patching
           only balance/seq — zero re-encode risk on the payment path */
        Py_ssize_t at = out->len;
        if (buf_put(out, e->base, e->baselen) < 0)
            return -1;
        uint8_t *p = (uint8_t *)out->data + at;
        wr_i64_at(p + e->off_balance, balance);
        if (e->type == LET_ACCOUNT)
            wr_i64_at(p + e->off_seq, seqNum);
        return 0;
    }
    if (e->type != LET_ACCOUNT)
        return -1; /* structural trustline change: unreachable */
    /* full AccountEntry build: structure changed (SET_OPTIONS) or the
       account was created this close. Byte layout mirrors
       xdr/ledger_entries.py AccountEntry / make_account_entry exactly;
       lastModified stays the base's value (the Python path never
       rewrites it on update). */
    uint32_t lm = e->base ? e->last_modified : e->created_seq;
    if (buf_u32(out, lm) < 0 || buf_u32(out, LET_ACCOUNT) < 0 ||
        buf_u32(out, 0) < 0 || buf_put(out, e->acc_key, 32) < 0 ||
        buf_i64(out, balance) < 0 || buf_i64(out, seqNum) < 0 ||
        buf_u32(out, st->numSub) < 0 ||
        buf_u32(out, (uint32_t)st->has_infl) < 0)
        return -1;
    if (st->has_infl &&
        (buf_u32(out, 0) < 0 || buf_put(out, st->infl, 32) < 0))
        return -1;
    if (buf_u32(out, st->flags) < 0 ||
        buf_u32(out, (uint32_t)st->home_len) < 0)
        return -1;
    if (st->home_len) {
        static const uint8_t zpad[4] = {0, 0, 0, 0};
        int pad = (4 - (st->home_len & 3)) & 3;
        if (buf_put(out, st->home, st->home_len) < 0 ||
            (pad && buf_put(out, zpad, pad) < 0))
            return -1;
    }
    if (buf_put(out, st->thresholds, 4) < 0 ||
        buf_u32(out, (uint32_t)st->nsigners) < 0)
        return -1;
    for (int i = 0; i < st->nsigners; i++) {
        if (buf_u32(out, 0) < 0 /* SIGNER_KEY_TYPE_ED25519 */ ||
            buf_put(out, st->signer_keys[i], 32) < 0 ||
            buf_u32(out, st->signer_weights[i]) < 0)
            return -1;
    }
    if (buf_u32(out, (uint32_t)e->ext_v) < 0)
        return -1;
    if (e->ext_v == 1 &&
        (buf_i64(out, e->liab_buying) < 0 ||
         buf_i64(out, e->liab_selling) < 0 ||
         buf_u32(out, 0) < 0 /* v1 inner ext */))
        return -1;
    if (buf_u32(out, 0) < 0 /* LedgerEntry ext v0 */)
        return -1;
    return 0;
}

static int entry_changed_since(Entry *e, EntrySave *s)
{
    if (s->exists != e->exists)
        return 1;
    if (!e->exists)
        return 0;
    if (s->balance != e->balance)
        return 1;
    if (e->type == LET_ACCOUNT && s->seqNum != e->seqNum)
        return 1;
    if (!struct_eq(&e->st, &s->st))
        return 1; /* signers/thresholds/flags/... (SET_OPTIONS) */
    return 0;
}

/* LedgerEntryChanges blob for level lv (does NOT commit/rollback).
   Mirrors LedgerTxn.get_delta + delta_to_changes: entries in first-touch
   order, touched-but-unchanged filtered, STATE before UPDATED, CREATED
   alone. Deletions cannot occur under the supported ops. */
static PyObject *delta_changes_blob(Ctx *c, int lv)
{
    Buf b = {NULL, 0, 0};
    uint32_t n = 0;
    int i;
    if (buf_u32(&b, 0) < 0)
        goto fail;
    for (i = 0; i < c->ntouched[lv]; i++) {
        Entry *e = c->touched[lv][i];
        EntrySave *s = &e->save[lv];
        if (!entry_changed_since(e, s))
            continue;
        if (s->exists && e->exists) {
            if (buf_u32(&b, 3) < 0 || /* LEDGER_ENTRY_STATE */
                ser_entry(c, e, s->balance, s->seqNum, &s->st, &b) < 0)
                goto fail;
            if (buf_u32(&b, 1) < 0 || /* LEDGER_ENTRY_UPDATED */
                ser_entry(c, e, e->balance, e->seqNum, &e->st, &b) < 0)
                goto fail;
            n += 2;
        } else if (!s->exists && e->exists) {
            if (buf_u32(&b, 0) < 0 || /* LEDGER_ENTRY_CREATED */
                ser_entry(c, e, e->balance, e->seqNum, &e->st, &b) < 0)
                goto fail;
            n += 1;
        } else {
            goto fail; /* deletion: unreachable in the supported subset */
        }
    }
    wr_u32_at((uint8_t *)b.data, n);
    {
        PyObject *r = PyBytes_FromStringAndSize(b.data, b.len);
        PyMem_Free(b.data);
        if (!r)
            c->pyerr = 1;
        return r;
    }
fail:
    PyMem_Free(b.data);
    if (!PyErr_Occurred()) {
        set_bail_reason(c, "delta");
        c->bail = 1;
    } else
        c->pyerr = 1;
    return NULL;
}

/* ------------------------------------------------------------ tx parsing */

typedef struct {
    int has_src;
    uint8_t src[32];
    int optype;
    uint8_t dest[32];
    int64_t amount; /* PAYMENT amount / CREATE_ACCOUNT startingBalance */
    int asset_native;
    uint8_t asset[52]; /* raw Asset XDR bytes */
    int assetlen;
    const uint8_t *issuer; /* into asset[] */
    /* SET_OPTIONS (every field optional on the wire) */
    int so_has_infl, so_has_clear, so_has_set;
    int so_has_mw, so_has_lt, so_has_mt, so_has_ht;
    int so_has_home, so_has_signer;
    uint8_t so_infl[32];
    uint32_t so_clear, so_set, so_mw, so_lt, so_mt, so_ht;
    int so_home_len;
    uint8_t so_home[32];
    uint8_t so_signer_key[32];
    uint32_t so_signer_w;
} Op;

typedef struct {
    uint8_t src[32];
    uint32_t fee;
    int64_t seqNum;
    int has_tb;
    uint64_t minTime, maxTime;
    int nops;
    Op *ops;
    int nsigs;
    struct {
        uint8_t hint[4];
        const uint8_t *sig;
        int siglen;
        PyObject *sig_obj; /* lazily-built bytes for the verify callback */
        int used;
    } sigs[MAX_SIGS];
    const uint8_t *hash; /* borrowed from hashes list */
    PyObject *hash_obj;  /* borrowed */
    int64_t feeCharged;
} Tx;

/* MuxedAccount, ed25519 arm only (muxed sub-ids: Python path) */
static int rd_muxed(Ctx *c, Rd *r, uint8_t *out32)
{
    uint32_t kt;
    if (rd_u32(r, &kt) < 0)
        return -1;
    if (kt != 0) {
        if (kt == 0x100) /* KEY_TYPE_MUXED_ED25519 */
            set_bail_reason(c, "muxed-account");
        return -1;
    }
    const uint8_t *p = rd_take(r, 32);
    if (!p)
        return -1;
    memcpy(out32, p, 32);
    return 0;
}

static int rd_asset(Rd *r, Op *op)
{
    Py_ssize_t at = r->pos;
    uint32_t atype;
    if (rd_u32(r, &atype) < 0)
        return -1;
    if (atype == 0) {
        op->asset_native = 1;
        op->assetlen = 4;
    } else if (atype == 1 || atype == 2) {
        int codelen = (atype == 1) ? 4 : 12;
        uint32_t kt;
        if (!rd_take(r, codelen))
            return -1;
        if (rd_u32(r, &kt) < 0 || kt != 0)
            return -1;
        if (!rd_take(r, 32))
            return -1;
        op->asset_native = 0;
        op->assetlen = (int)(r->pos - at);
    } else
        return -1;
    memcpy(op->asset, r->p + at, r->pos - at);
    op->issuer = op->asset + op->assetlen - 32;
    return 0;
}

static int parse_envelope(Ctx *c, const uint8_t *blob, Py_ssize_t len,
                          Tx *t)
{
    Rd r = {blob, len, 0};
    uint32_t u, n;
    int i;
    if (rd_u32(&r, &u) < 0)
        return -1;
    if (u != 2) { /* ENVELOPE_TYPE_TX (fee bumps etc.: Python path) */
        set_bail_reason(c, u == 5 ? "fee-bump" : "envelope-type");
        return -1;
    }
    if (rd_muxed(c, &r, t->src) < 0)
        return -1;
    if (rd_u32(&r, &t->fee) < 0 || rd_i64(&r, &t->seqNum) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u > 1)
        return -1;
    t->has_tb = (int)u;
    if (t->has_tb &&
        (rd_u64(&r, &t->minTime) < 0 || rd_u64(&r, &t->maxTime) < 0))
        return -1;
    if (rd_u32(&r, &u) < 0) /* memo */
        return -1;
    switch (u) {
    case 0:
        break;
    case 1: {
        uint32_t sl;
        if (rd_u32(&r, &sl) < 0 || sl > 28 || rd_skip_padded(&r, sl) < 0)
            return -1;
        break;
    }
    case 2:
        if (!rd_take(&r, 8))
            return -1;
        break;
    case 3:
    case 4:
        if (!rd_take(&r, 32))
            return -1;
        break;
    default:
        return -1;
    }
    if (rd_u32(&r, &n) < 0 || n > 100)
        return -1;
    t->nops = (int)n;
    t->ops = PyMem_Calloc(n ? n : 1, sizeof(Op));
    if (!t->ops) {
        c->pyerr = 1;
        PyErr_NoMemory();
        return -1;
    }
    for (i = 0; i < t->nops; i++) {
        Op *op = &t->ops[i];
        if (rd_u32(&r, &u) < 0 || u > 1)
            return -1;
        op->has_src = (int)u;
        if (op->has_src && rd_muxed(c, &r, op->src) < 0)
            return -1;
        if (rd_u32(&r, &u) < 0)
            return -1;
        op->optype = (int)u;
        if (op->optype == OP_CREATE_ACCOUNT) {
            uint32_t kt;
            if (rd_u32(&r, &kt) < 0 || kt != 0)
                return -1;
            const uint8_t *p = rd_take(&r, 32);
            if (!p)
                return -1;
            memcpy(op->dest, p, 32);
            if (rd_i64(&r, &op->amount) < 0)
                return -1;
        } else if (op->optype == OP_PAYMENT) {
            if (rd_muxed(c, &r, op->dest) < 0)
                return -1;
            if (rd_asset(&r, op) < 0)
                return -1;
            if (rd_i64(&r, &op->amount) < 0)
                return -1;
        } else if (op->optype == OP_SET_OPTIONS) {
            uint32_t kt;
            /* inflationDest: optional AccountID */
            if (rd_u32(&r, &u) < 0 || u > 1)
                return -1;
            op->so_has_infl = (int)u;
            if (u) {
                const uint8_t *p;
                if (rd_u32(&r, &kt) < 0 || kt != 0 ||
                    !(p = rd_take(&r, 32)))
                    return -1;
                memcpy(op->so_infl, p, 32);
            }
            /* clearFlags / setFlags / the four weights: optional u32 */
            struct {
                int *has;
                uint32_t *val;
            } ou32[6] = {
                {&op->so_has_clear, &op->so_clear},
                {&op->so_has_set, &op->so_set},
                {&op->so_has_mw, &op->so_mw},
                {&op->so_has_lt, &op->so_lt},
                {&op->so_has_mt, &op->so_mt},
                {&op->so_has_ht, &op->so_ht},
            };
            for (int k = 0; k < 6; k++) {
                if (rd_u32(&r, &u) < 0 || u > 1)
                    return -1;
                *ou32[k].has = (int)u;
                if (u && rd_u32(&r, ou32[k].val) < 0)
                    return -1;
            }
            /* thresholds > 255 make the Python oracle raise mid-close
               (bytearray assignment); keep it the oracle */
            if ((op->so_has_mw && op->so_mw > 255) ||
                (op->so_has_lt && op->so_lt > 255) ||
                (op->so_has_mt && op->so_mt > 255) ||
                (op->so_has_ht && op->so_ht > 255)) {
                set_bail_reason(c, "threshold-range");
                return -1;
            }
            /* homeDomain: optional string32 */
            if (rd_u32(&r, &u) < 0 || u > 1)
                return -1;
            op->so_has_home = (int)u;
            if (u) {
                uint32_t sl;
                if (rd_u32(&r, &sl) < 0 || sl > 32)
                    return -1;
                Py_ssize_t at = r.pos;
                if (rd_skip_padded(&r, sl) < 0)
                    return -1;
                op->so_home_len = (int)sl;
                memcpy(op->so_home, r.p + at, sl);
            }
            /* signer: optional; ed25519 keys only (pre-auth-tx / hash-x
               signers keep the whole close on the Python path, like
               parse_account) */
            if (rd_u32(&r, &u) < 0 || u > 1)
                return -1;
            op->so_has_signer = (int)u;
            if (u) {
                const uint8_t *p;
                if (rd_u32(&r, &kt) < 0)
                    return -1;
                if (kt != 0) {
                    set_bail_reason(c, "signer-key-type");
                    return -1;
                }
                if (!(p = rd_take(&r, 32)))
                    return -1;
                memcpy(op->so_signer_key, p, 32);
                if (rd_u32(&r, &op->so_signer_w) < 0)
                    return -1;
            }
        } else {
            /* other op types: Python path — record WHICH one, so the
               op-coverage order of ROADMAP item 2 follows traffic */
            snprintf(c->bailbuf, sizeof(c->bailbuf), "op-%d", op->optype);
            set_bail_reason(c, c->bailbuf);
            return -1;
        }
    }
    if (rd_u32(&r, &u) < 0 || u != 0) /* tx ext */
        return -1;
    if (rd_u32(&r, &n) < 0)
        return -1;
    if (n > MAX_SIGS) {
        set_bail_reason(c, "multisig-shape");
        return -1;
    }
    t->nsigs = (int)n;
    for (i = 0; i < t->nsigs; i++) {
        const uint8_t *h = rd_take(&r, 4);
        if (!h)
            return -1;
        memcpy(t->sigs[i].hint, h, 4);
        uint32_t sl;
        if (rd_u32(&r, &sl) < 0 || sl > 64)
            return -1;
        Py_ssize_t pad = (4 - (sl & 3)) & 3;
        const uint8_t *sp = rd_take(&r, sl + pad);
        if (!sp)
            return -1;
        t->sigs[i].sig = sp;
        t->sigs[i].siglen = (int)sl;
    }
    if (r.pos != r.len)
        return -1;
    return 0;
}

/* ---------------------------------------------------- signature checking */

typedef struct {
    uint8_t key[32];
    int sigidx;
    int ok;
} VPair;

typedef struct {
    VPair *pairs;
    int n, cap;
} VSet;

static int vset_add(Ctx *c, VSet *vs, const uint8_t *key, int sigidx)
{
    int i;
    for (i = 0; i < vs->n; i++)
        if (vs->pairs[i].sigidx == sigidx &&
            memcmp(vs->pairs[i].key, key, 32) == 0)
            return 0;
    if (vs->n == vs->cap) {
        int cap = vs->cap ? vs->cap * 2 : 32;
        VPair *p = PyMem_Realloc(vs->pairs, cap * sizeof(VPair));
        if (!p) {
            c->pyerr = 1;
            PyErr_NoMemory();
            return -1;
        }
        vs->pairs = p;
        vs->cap = cap;
    }
    memcpy(vs->pairs[vs->n].key, key, 32);
    vs->pairs[vs->n].sigidx = sigidx;
    vs->pairs[vs->n].ok = 0;
    vs->n++;
    return 0;
}

static int vset_ok(VSet *vs, const uint8_t *key, int sigidx)
{
    int i;
    for (i = 0; i < vs->n; i++)
        if (vs->pairs[i].sigidx == sigidx &&
            memcmp(vs->pairs[i].key, key, 32) == 0)
            return vs->pairs[i].ok;
    return 0;
}

/* signer key set of one account as the checker sees it: account signers
   in stored order, master key appended iff master weight > 0; for a
   missing account, the raw key with weight 1 */
static int account_signers(Entry *a, const uint8_t *accid,
                           const uint8_t *keys[MAX_SIGNERS + 1],
                           uint32_t weights[MAX_SIGNERS + 1])
{
    int n = 0, i;
    if (a && a->exists) {
        for (i = 0; i < a->st.nsigners; i++) {
            keys[n] = a->st.signer_keys[i];
            weights[n++] = a->st.signer_weights[i];
        }
        if (a->st.thresholds[0] > 0) {
            keys[n] = a->acc_key;
            weights[n++] = a->st.thresholds[0];
        }
    } else {
        keys[n] = accid;
        weights[n++] = 1;
    }
    return n;
}

/* collect hint-matching (key, sig) pairs for one account's signer set */
static int vset_collect(Ctx *c, VSet *vs, Tx *t, Entry *a,
                        const uint8_t *accid)
{
    const uint8_t *keys[MAX_SIGNERS + 1];
    uint32_t weights[MAX_SIGNERS + 1];
    int n = account_signers(a, accid, keys, weights);
    int i, j;
    for (j = 0; j < n; j++)
        for (i = 0; i < t->nsigs; i++)
            if (memcmp(t->sigs[i].hint, keys[j] + 28, 4) == 0)
                if (vset_add(c, vs, keys[j], i) < 0)
                    return -1;
    return 0;
}

/* one batch verify callback for the whole tx's candidate pairs */
static int vset_verify(Ctx *c, VSet *vs, Tx *t)
{
    if (vs->n == 0)
        return 0;
    PyObject *lst = PyList_New(vs->n);
    int i;
    if (!lst) {
        c->pyerr = 1;
        return -1;
    }
    for (i = 0; i < vs->n; i++) {
        int si = vs->pairs[i].sigidx;
        if (!t->sigs[si].sig_obj) {
            t->sigs[si].sig_obj = PyBytes_FromStringAndSize(
                (const char *)t->sigs[si].sig, t->sigs[si].siglen);
            if (!t->sigs[si].sig_obj) {
                Py_DECREF(lst);
                c->pyerr = 1;
                return -1;
            }
        }
        PyObject *key = PyBytes_FromStringAndSize(
            (const char *)vs->pairs[i].key, 32);
        if (!key) {
            Py_DECREF(lst);
            c->pyerr = 1;
            return -1;
        }
        PyObject *tup = PyTuple_Pack(3, key, t->sigs[si].sig_obj,
                                     t->hash_obj);
        Py_DECREF(key);
        if (!tup) {
            Py_DECREF(lst);
            c->pyerr = 1;
            return -1;
        }
        PyList_SET_ITEM(lst, i, tup);
    }
    PyObject *res = PyObject_CallFunctionObjArgs(c->verify, lst, NULL);
    Py_DECREF(lst);
    if (!res) {
        c->pyerr = 1;
        return -1;
    }
    PyObject *seq = PySequence_Fast(res, "verify() must return a sequence");
    Py_DECREF(res);
    if (!seq) {
        c->pyerr = 1;
        return -1;
    }
    if (PySequence_Fast_GET_SIZE(seq) != vs->n) {
        Py_DECREF(seq);
        set_bail_reason(c, "verify-shape");
        c->bail = 1;
        return -1;
    }
    for (i = 0; i < vs->n; i++)
        vs->pairs[i].ok =
            PyObject_IsTrue(PySequence_Fast_GET_ITEM(seq, i)) == 1;
    Py_DECREF(seq);
    return 0;
}

/* SignatureChecker.check_signature over ed25519 signers only (the bail
   rules keep pre-auth-tx / hash-x signers off this path). Mirrors the
   Python loop exactly: signatures in order, each consuming the first
   remaining hint-matched verified signer; weights capped at 255; zero
   thresholds still need one valid signer. */
static int check_sig(Tx *t, VSet *vs, Entry *a, const uint8_t *accid,
                     int level)
{
    const uint8_t *keys[MAX_SIGNERS + 1];
    uint32_t weights[MAX_SIGNERS + 1];
    int n = account_signers(a, accid, keys, weights);
    uint32_t needed =
        (a && a->exists) ? a->st.thresholds[1 + level] : 0;
    uint32_t total = 0;
    int i, j;
    for (i = 0; i < t->nsigs; i++) {
        for (j = 0; j < n; j++) {
            if (memcmp(t->sigs[i].hint, keys[j] + 28, 4) != 0)
                continue;
            if (!vset_ok(vs, keys[j], i))
                continue;
            t->sigs[i].used = 1;
            total += weights[j] > 255 ? 255 : weights[j];
            if (total >= needed)
                return 1;
            /* consume signer j */
            memmove(&keys[j], &keys[j + 1],
                    (n - j - 1) * sizeof(keys[0]));
            memmove(&weights[j], &weights[j + 1],
                    (n - j - 1) * sizeof(weights[0]));
            n--;
            break;
        }
    }
    return 0;
}

/* ------------------------------------------------------- balance helpers */

/* transactions/account_helpers.py add_balance, protocol >= 10.
   delta is 128-bit: Python's unbounded ints make -INT64_MIN well-defined
   (the range checks reject it), so the C arithmetic must too. */
static int add_balance(Ctx *c, Entry *e, __int128 delta)
{
    __int128 newb = (__int128)e->balance + delta;
    if (newb < 0 || newb > INT64_MAXV)
        return 0;
    if (delta < 0) {
        __int128 minb = (__int128)(2 + e->st.numSub) * c->baseReserve;
        if (newb - minb < e->liab_selling)
            return 0;
    }
    if (newb > (__int128)INT64_MAXV - e->liab_buying)
        return 0;
    e->balance = (int64_t)newb;
    return 1;
}

/* transactions/account_helpers.py add_trust_balance, protocol >= 10 */
static int add_trust_balance(Entry *e, __int128 delta)
{
    if (delta == 0)
        return 1;
    if (!(e->st.flags & TL_AUTH_LEVELS_MASK))
        return 0;
    __int128 newb = (__int128)e->balance + delta;
    if (newb < 0 || newb > e->tl_limit)
        return 0;
    if (newb < e->liab_selling)
        return 0;
    if (newb > (__int128)e->tl_limit - e->liab_buying)
        return 0;
    e->balance = (int64_t)newb;
    return 1;
}

/* ----------------------------------------------------------- op results */

typedef struct {
    int code;       /* OperationResultCode */
    int optype;     /* valid when code == opINNER */
    int inner_code; /* op-specific result code */
} OpRes;

static int buf_op_result(Buf *b, OpRes *r)
{
    if (buf_i32(b, r->code) < 0)
        return -1;
    if (r->code != opINNER)
        return 0;
    if (buf_i32(b, r->optype) < 0 || buf_i32(b, r->inner_code) < 0)
        return -1;
    return 0; /* both supported ops have void success arms */
}

static PyObject *build_result(Ctx *c, int64_t fee, int code, int nops,
                              OpRes *ops)
{
    Buf b = {NULL, 0, 0};
    int i;
    if (buf_i64(&b, fee) < 0 || buf_i32(&b, code) < 0)
        goto fail;
    if (code == txSUCCESS || code == txFAILED) {
        if (buf_u32(&b, (uint32_t)nops) < 0)
            goto fail;
        for (i = 0; i < nops; i++)
            if (buf_op_result(&b, &ops[i]) < 0)
                goto fail;
    }
    if (buf_u32(&b, 0) < 0) /* TransactionResult ext */
        goto fail;
    {
        PyObject *r = PyBytes_FromStringAndSize(b.data, b.len);
        PyMem_Free(b.data);
        if (!r)
            c->pyerr = 1;
        return r;
    }
fail:
    PyMem_Free(b.data);
    c->pyerr = 1;
    if (!PyErr_Occurred())
        PyErr_NoMemory();
    return NULL;
}

/* TransactionMeta v1 from the tx-level changes + per-op changes blobs */
static PyObject *build_meta(Ctx *c, PyObject *tx_changes, int nops,
                            PyObject **op_changes)
{
    Buf b = {NULL, 0, 0};
    int i;
    if (buf_u32(&b, 1) < 0) /* TransactionMeta disc v1 */
        goto fail;
    if (buf_put(&b, PyBytes_AS_STRING(tx_changes),
                PyBytes_GET_SIZE(tx_changes)) < 0)
        goto fail;
    if (buf_u32(&b, (uint32_t)nops) < 0)
        goto fail;
    for (i = 0; i < nops; i++) {
        if (op_changes && op_changes[i]) {
            if (buf_put(&b, PyBytes_AS_STRING(op_changes[i]),
                        PyBytes_GET_SIZE(op_changes[i])) < 0)
                goto fail;
        } else if (buf_u32(&b, 0) < 0)
            goto fail;
    }
    {
        PyObject *r = PyBytes_FromStringAndSize(b.data, b.len);
        PyMem_Free(b.data);
        if (!r)
            c->pyerr = 1;
        return r;
    }
fail:
    PyMem_Free(b.data);
    c->pyerr = 1;
    if (!PyErr_Occurred())
        PyErr_NoMemory();
    return NULL;
}

static PyObject *empty_changes(Ctx *c)
{
    static const char z[4] = {0, 0, 0, 0};
    PyObject *r = PyBytes_FromStringAndSize(z, 4);
    if (!r)
        c->pyerr = 1;
    return r;
}

/* ------------------------------------------------------------ op applies */

static int apply_create_account(Ctx *c, Tx *t, Op *op,
                                const uint8_t *src_id, OpRes *res)
{
    res->code = opINNER;
    res->optype = OP_CREATE_ACCOUNT;
    Entry *dest = get_account(c, op->dest); /* load_without_record */
    if (!dest)
        return -1;
    if (dest->exists) {
        res->inner_code = CA_ALREADY_EXIST;
        return 0;
    }
    if ((__int128)op->amount < (__int128)2 * c->baseReserve) {
        res->inner_code = CA_LOW_RESERVE;
        return 0;
    }
    Entry *src = get_account(c, src_id);
    if (!src)
        return -1;
    if (touch(c, src, 3) < 0)
        return -1;
    if (!add_balance(c, src, -(__int128)op->amount)) {
        res->inner_code = CA_UNDERFUNDED;
        return 0;
    }
    if (touch(c, dest, 3) < 0)
        return -1;
    dest->exists = 1;
    dest->type = LET_ACCOUNT;
    memcpy(dest->acc_key, op->dest, 32);
    dest->balance = op->amount;
    dest->seqNum = (int64_t)((uint64_t)c->ledgerSeq << 32);
    dest->created_seq = c->ledgerSeq;
    memset(&dest->st, 0, sizeof(dest->st));
    dest->st.thresholds[0] = 1;
    dest->ext_v = 0;
    dest->liab_buying = dest->liab_selling = 0;
    res->inner_code = CA_SUCCESS;
    return 0;
}

static int apply_payment(Ctx *c, Tx *t, Op *op, const uint8_t *src_id,
                         OpRes *res)
{
    res->code = opINNER;
    res->optype = OP_PAYMENT;
    Entry *dest_acc = get_account(c, op->dest);
    if (!dest_acc)
        return -1;
    if (touch(c, dest_acc, 3) < 0) /* ltx.load records before the check */
        return -1;
    if (!dest_acc->exists) {
        res->inner_code = PAY_NO_DESTINATION;
        return 0;
    }
    if (op->asset_native) {
        Entry *src = get_account(c, src_id);
        if (!src)
            return -1;
        if (touch(c, src, 3) < 0)
            return -1;
        if (memcmp(src_id, op->dest, 32) != 0) {
            if (!add_balance(c, src, -(__int128)op->amount)) {
                res->inner_code = PAY_UNDERFUNDED;
                return 0;
            }
            if (!add_balance(c, dest_acc, op->amount)) {
                res->inner_code = PAY_LINE_FULL;
                return 0;
            }
        }
        res->inner_code = PAY_SUCCESS;
        return 0;
    }
    /* credit asset: source side */
    if (memcmp(src_id, op->issuer, 32) != 0) {
        Entry *stl = get_trustline(c, src_id, op->asset, op->assetlen);
        if (!stl)
            return -1;
        if (touch(c, stl, 3) < 0)
            return -1;
        if (!stl->exists) {
            res->inner_code = PAY_SRC_NO_TRUST;
            return 0;
        }
        if (!(stl->st.flags & TL_AUTHORIZED)) {
            res->inner_code = PAY_SRC_NOT_AUTHORIZED;
            return 0;
        }
        if (!add_trust_balance(stl, -(__int128)op->amount)) {
            res->inner_code = PAY_UNDERFUNDED;
            return 0;
        }
    } else {
        Entry *iss = get_account(c, op->issuer);
        if (!iss)
            return -1;
        if (touch(c, iss, 3) < 0)
            return -1;
        if (!iss->exists) {
            res->inner_code = PAY_NO_ISSUER;
            return 0;
        }
    }
    /* destination side */
    if (memcmp(op->dest, op->issuer, 32) != 0) {
        Entry *dtl = get_trustline(c, op->dest, op->asset, op->assetlen);
        if (!dtl)
            return -1;
        if (touch(c, dtl, 3) < 0)
            return -1;
        if (!dtl->exists) {
            res->inner_code = PAY_NO_TRUST;
            return 0;
        }
        if (!(dtl->st.flags & TL_AUTHORIZED)) {
            res->inner_code = PAY_NOT_AUTHORIZED;
            return 0;
        }
        if (!add_trust_balance(dtl, op->amount)) {
            res->inner_code = PAY_LINE_FULL;
            return 0;
        }
    }
    res->inner_code = PAY_SUCCESS;
    return 0;
}

/* account_helpers.py change_subentries: reserve check (incl. selling
   liabilities at v10+) on add; the remove arm cannot fail and Python
   ignores its return value there */
static int change_subentries(Ctx *c, Entry *e, int delta)
{
    int64_t nc = (int64_t)e->st.numSub + delta;
    if (nc < 0 || nc > MAX_SUBENTRIES)
        return 0;
    __int128 effmin = (__int128)(2 + nc) * c->baseReserve;
    if (c->ledgerVersion >= 10)
        effmin += e->liab_selling;
    if (delta > 0 && (__int128)e->balance < effmin)
        return 0;
    e->st.numSub = (uint32_t)nc;
    return 1;
}

/* SetOptionsOpFrame.do_apply, arm for arm and in the same order.
   do_check_valid does NOT run at apply (OperationFrame.apply), so no
   validity checks here beyond what the Python apply itself would do. */
static int apply_set_options(Ctx *c, Tx *t, Op *op, const uint8_t *src_id,
                             OpRes *res)
{
    res->code = opINNER;
    res->optype = OP_SET_OPTIONS;
    Entry *src = get_account(c, src_id); /* exists checked by caller */
    if (!src)
        return -1;
    if (touch(c, src, 3) < 0)
        return -1;
    if (op->so_has_infl) {
        Entry *d = get_account(c, op->so_infl); /* load_without_record */
        if (!d)
            return -1;
        if (!d->exists) {
            res->inner_code = SO_INVALID_INFLATION;
            return 0;
        }
        src->st.has_infl = 1;
        memcpy(src->st.infl, op->so_infl, 32);
    }
    if (op->so_has_clear) {
        if (src->st.flags & AUTH_IMMUTABLE_FLAG) {
            res->inner_code = SO_CANT_CHANGE;
            return 0;
        }
        src->st.flags &= ~op->so_clear;
    }
    if (op->so_has_set) {
        if (src->st.flags & AUTH_IMMUTABLE_FLAG) {
            res->inner_code = SO_CANT_CHANGE;
            return 0;
        }
        src->st.flags |= op->so_set;
    }
    if (op->so_has_mw)
        src->st.thresholds[0] = (uint8_t)op->so_mw;
    if (op->so_has_lt)
        src->st.thresholds[1] = (uint8_t)op->so_lt;
    if (op->so_has_mt)
        src->st.thresholds[2] = (uint8_t)op->so_mt;
    if (op->so_has_ht)
        src->st.thresholds[3] = (uint8_t)op->so_ht;
    if (op->so_has_home) {
        src->st.home_len = op->so_home_len;
        if (op->so_home_len)
            memcpy(src->st.home, op->so_home, op->so_home_len);
    }
    if (op->so_has_signer) {
        StructState *st = &src->st;
        int idx = -1, i;
        for (i = 0; i < st->nsigners; i++)
            if (memcmp(st->signer_keys[i], op->so_signer_key, 32) == 0) {
                idx = i;
                break;
            }
        if (op->so_signer_w == 0) {
            if (idx >= 0) {
                memmove(st->signer_keys[idx], st->signer_keys[idx + 1],
                        (st->nsigners - idx - 1) * 32);
                memmove(&st->signer_weights[idx],
                        &st->signer_weights[idx + 1],
                        (st->nsigners - idx - 1) * sizeof(uint32_t));
                st->nsigners--;
                change_subentries(c, src, -1); /* rc ignored, like Python */
            }
        } else if (idx >= 0) {
            st->signer_weights[idx] = op->so_signer_w;
        } else {
            if (st->nsigners >= MAX_SIGNERS) {
                res->inner_code = SO_TOO_MANY_SIGNERS;
                return 0;
            }
            if (!change_subentries(c, src, +1)) {
                res->inner_code = SO_LOW_RESERVE;
                return 0;
            }
            memcpy(st->signer_keys[st->nsigners], op->so_signer_key, 32);
            st->signer_weights[st->nsigners] = op->so_signer_w;
            st->nsigners++;
        }
        /* Python re-sorts the WHOLE list after every signer arm (by
           key.to_xdr(); all keys share the ed25519 type prefix, so raw
           key bytes compare identically). Stable insertion sort. */
        for (i = 1; i < st->nsigners; i++) {
            uint8_t k[32];
            uint32_t w = st->signer_weights[i];
            int j = i;
            memcpy(k, st->signer_keys[i], 32);
            while (j > 0 &&
                   memcmp(k, st->signer_keys[j - 1], 32) < 0) {
                memcpy(st->signer_keys[j], st->signer_keys[j - 1], 32);
                st->signer_weights[j] = st->signer_weights[j - 1];
                j--;
            }
            memcpy(st->signer_keys[j], k, 32);
            st->signer_weights[j] = w;
        }
    }
    res->inner_code = SO_SUCCESS;
    return 0;
}

/* ----------------------------------------------------------- the close */

static int params_i64(PyObject *params, const char *name, int64_t *out)
{
    PyObject *v = PyDict_GetItemString(params, name);
    if (!v) {
        PyErr_Format(PyExc_KeyError, "params missing %s", name);
        return -1;
    }
    *out = PyLong_AsLongLong(v);
    if (*out == -1 && PyErr_Occurred())
        return -1;
    return 0;
}

static PyObject *apply_close(PyObject *self, PyObject *args)
{
    PyObject *params, *envs, *hashes, *lookup, *verify;
    if (!PyArg_ParseTuple(args, "OOOOO", &params, &envs, &hashes, &lookup,
                          &verify))
        return NULL;

    Ctx c;
    memset(&c, 0, sizeof(c));
    c.lookup = lookup;
    c.verify = verify;

    int64_t v;
    if (params_i64(params, "ledgerVersion", &v) < 0)
        return NULL;
    c.ledgerVersion = (uint32_t)v;
    if (params_i64(params, "ledgerSeq", &v) < 0)
        return NULL;
    c.ledgerSeq = (uint32_t)v;
    if (params_i64(params, "closeTime", &v) < 0)
        return NULL;
    c.closeTime = (uint64_t)v;
    if (params_i64(params, "baseFee", &c.baseFee) < 0 ||
        params_i64(params, "baseReserve", &c.baseReserve) < 0 ||
        params_i64(params, "effBaseFee", &c.effBase) < 0 ||
        params_i64(params, "feePool", &c.feePool) < 0)
        return NULL;

    if (c.ledgerVersion < 10) /* pre-10 fee/seq semantics: Python path */
        Py_RETURN_NONE;

    Py_ssize_t ntx = PySequence_Length(envs);
    if (ntx < 0)
        return NULL;
    if (PySequence_Length(hashes) != ntx) {
        PyErr_SetString(PyExc_ValueError, "envs/hashes length mismatch");
        return NULL;
    }

    Tx *txs = PyMem_Calloc(ntx ? ntx : 1, sizeof(Tx));
    if (!txs)
        return PyErr_NoMemory();

    PyObject *results = NULL, *fee_changes = NULL, *metas = NULL;
    PyObject *changes = NULL, *out = NULL;
    int bailing = 0;
    Py_ssize_t ti;
    int i;

    /* parse every envelope up front: one unsupported tx fails the whole
       close over to Python BEFORE any state mutates */
    for (ti = 0; ti < ntx; ti++) {
        PyObject *env = PySequence_GetItem(envs, ti);
        PyObject *h = PySequence_GetItem(hashes, ti);
        if (!env || !h || !PyBytes_Check(env) || !PyBytes_Check(h) ||
            PyBytes_GET_SIZE(h) != 32) {
            Py_XDECREF(env);
            Py_XDECREF(h);
            if (!PyErr_Occurred()) {
                set_bail_reason(&c, "input-shape");
                c.bail = 1;
            } else
                c.pyerr = 1;
            goto done;
        }
        /* keep borrowed views alive: envs/hashes lists own them for the
           duration of the call (caller holds the lists) */
        txs[ti].hash = (const uint8_t *)PyBytes_AS_STRING(h);
        txs[ti].hash_obj = h; /* borrow; DECREF now, list keeps it alive */
        int rc = parse_envelope(&c, (const uint8_t *)PyBytes_AS_STRING(env),
                                PyBytes_GET_SIZE(env), &txs[ti]);
        Py_DECREF(env);
        Py_DECREF(h);
        if (rc < 0) {
            if (!c.pyerr) {
                set_bail_reason(&c, "envelope");
                c.bail = 1;
            }
            goto done;
        }
    }

    results = PyList_New(0);
    fee_changes = PyList_New(0);
    metas = PyList_New(0);
    if (!results || !fee_changes || !metas) {
        c.pyerr = 1;
        goto done;
    }

    /* ---- phase 1: fees + (v10+: nothing else) per tx, in apply order */
    for (ti = 0; ti < ntx; ti++) {
        Tx *t = &txs[ti];
        __int128 fee128 = (__int128)c.effBase *
                          (t->nops > 1 ? t->nops : 1);
        int64_t fee = fee128 > (__int128)t->fee ? (int64_t)t->fee
                                                : (int64_t)fee128;
        Entry *src = get_account(&c, t->src);
        if (!src)
            goto done;
        if (!src->exists) {
            set_bail_reason(&c, "fee-source-missing");
            c.bail = 1; /* Python asserts here; let it */
            goto done;
        }
        if (touch(&c, src, 1) < 0)
            goto done;
        int64_t cap = src->balance > 0 ? src->balance : 0;
        if (fee > cap)
            fee = cap;
        src->balance -= fee;
        c.feePool += fee;
        t->feeCharged = fee;
        PyObject *fc = delta_changes_blob(&c, 1);
        if (!fc)
            goto done;
        if (PyList_Append(fee_changes, fc) < 0) {
            Py_DECREF(fc);
            c.pyerr = 1;
            goto done;
        }
        Py_DECREF(fc);
        if (commit_level(&c, 1) < 0)
            goto done;
    }

    /* ---- phase 2: apply each tx */
    for (ti = 0; ti < ntx; ti++) {
        Tx *t = &txs[ti];
        int code = txSUCCESS;
        Entry *src = NULL;
        VSet vs = {NULL, 0, 0};
        PyObject *txch = NULL, *meta = NULL, *resb = NULL;
        OpRes *opres = NULL;
        PyObject **opch = NULL;

        for (i = 0; i < t->nsigs; i++)
            t->sigs[i].used = 0;

        /* _common_valid (applying): TransactionFrame.cpp:443-502 order */
        if (t->has_tb && t->minTime && c.closeTime < t->minTime)
            code = txTOO_EARLY;
        else if (t->has_tb && t->maxTime && c.closeTime > t->maxTime)
            code = txTOO_LATE;
        else if (t->nops == 0)
            code = txMISSING_OPERATION;
        else {
            __int128 minfee = (__int128)c.baseFee *
                              (t->nops > 1 ? t->nops : 1);
            if ((__int128)t->fee < minfee)
                code = txINSUFFICIENT_FEE;
        }
        if (code == txSUCCESS) {
            src = get_account(&c, t->src);
            if (!src)
                goto txfail;
            if (!src->exists)
                code = txNO_ACCOUNT;
            else {
                if (touch(&c, src, 1) < 0) /* load_account records */
                    goto txfail;
                if (src->seqNum == INT64_MAXV ||
                    t->seqNum != src->seqNum + 1)
                    code = txBAD_SEQ;
                else {
                    /* collect + verify this tx's candidate pairs once;
                       covers the tx-level LOW check and every op check */
                    if (vset_collect(&c, &vs, t, src, t->src) < 0)
                        goto txfail;
                    for (i = 0; i < t->nops; i++) {
                        const uint8_t *osrc = t->ops[i].has_src
                                                  ? t->ops[i].src
                                                  : t->src;
                        Entry *oa = get_account(&c, osrc);
                        if (!oa)
                            goto txfail;
                        if (vset_collect(&c, &vs, t, oa, osrc) < 0)
                            goto txfail;
                    }
                    if (vset_verify(&c, &vs, t) < 0)
                        goto txfail;
                    if (!check_sig(t, &vs, src, t->src, 0 /* LOW */))
                        code = txBAD_AUTH;
                }
            }
        }

        int pre_seq = (code == txTOO_EARLY || code == txTOO_LATE ||
                       code == txMISSING_OPERATION ||
                       code == txINSUFFICIENT_FEE ||
                       code == txNO_ACCOUNT || code == txBAD_SEQ);
        if (!pre_seq) {
            if (src->seqNum > t->seqNum) {
                /* Python raises -> txINTERNAL_ERROR, tx txn rolled back */
                rollback_level(&c, 1);
                resb = build_result(&c, t->feeCharged, txINTERNAL_ERROR, 0,
                                    NULL);
                txch = empty_changes(&c);
                if (!resb || !txch)
                    goto txfail;
                meta = build_meta(&c, txch, 0, NULL);
                if (!meta)
                    goto txfail;
                goto txemit;
            }
            if (touch(&c, src, 1) < 0)
                goto txfail;
            src->seqNum = t->seqNum;
        }

        int sigs_ok = 1;
        if (code == txSUCCESS) {
            /* processSignatures: every op's source at its threshold.
               Any op-level failure leaves sibling result slots unset in
               the Python frame (unserializable mix) — bail to the oracle
               rather than guess. */
            for (i = 0; i < t->nops; i++) {
                Op *o = &t->ops[i];
                const uint8_t *osrc = o->has_src ? o->src : t->src;
                Entry *oa = get_account(&c, osrc);
                if (!oa)
                    goto txfail;
                /* SetOptionsOpFrame.threshold_level: HIGH when touching
                   thresholds or signers, else MEDIUM (all other
                   supported ops are MEDIUM) */
                int level = 1;
                if (o->optype == OP_SET_OPTIONS &&
                    (o->so_has_mw || o->so_has_lt || o->so_has_mt ||
                     o->so_has_ht || o->so_has_signer))
                    level = 2;
                if (!check_sig(t, &vs, oa->exists ? oa : NULL, osrc,
                               level)) {
                    set_bail_reason(&c, "op-auth");
                    c.bail = 1;
                    goto txfail;
                }
            }
            /* _remove_one_time_signer: no pre-auth signers on this path
               (parse_account bails on them) — a structural no-op */
            for (i = 0; i < t->nsigs; i++)
                if (!t->sigs[i].used) {
                    sigs_ok = 0;
                    break;
                }
        }

        txch = delta_changes_blob(&c, 1);
        if (!txch)
            goto txfail;
        if (commit_level(&c, 1) < 0)
            goto txfail;

        if (code != txSUCCESS) {
            resb = build_result(&c, t->feeCharged, code, 0, NULL);
            if (!resb)
                goto txfail;
            meta = build_meta(&c, txch, 0, NULL);
            if (!meta)
                goto txfail;
            goto txemit;
        }
        if (!sigs_ok) {
            resb = build_result(&c, t->feeCharged, txBAD_AUTH_EXTRA, 0,
                                NULL);
            if (!resb)
                goto txfail;
            meta = build_meta(&c, txch, 0, NULL);
            if (!meta)
                goto txfail;
            goto txemit;
        }

        /* ops phase: every op applies in its own nested txn; any failure
           rolls the whole ops txn back (fees/seq already committed) */
        opres = PyMem_Calloc(t->nops, sizeof(OpRes));
        opch = PyMem_Calloc(t->nops, sizeof(PyObject *));
        if (!opres || !opch) {
            c.pyerr = 1;
            PyErr_NoMemory();
            goto txfail;
        }
        int ok = 1;
        for (i = 0; i < t->nops; i++) {
            Op *op = &t->ops[i];
            const uint8_t *osrc = op->has_src ? op->src : t->src;
            /* per-op attribution: the whole op handling (state loads,
               apply, delta serialization, savepoint commit/rollback)
               charges to the op's wire type */
            int64_t t_op = now_ns();
            Entry *oa = get_account(&c, osrc);
            if (!oa)
                goto txfail;
            int op_ok = 0;
            if (!oa->exists) {
                opres[i].code = opNO_ACCOUNT;
            } else {
                int rc = (op->optype == OP_CREATE_ACCOUNT)
                             ? apply_create_account(&c, t, op, osrc,
                                                    &opres[i])
                             : (op->optype == OP_SET_OPTIONS)
                                   ? apply_set_options(&c, t, op, osrc,
                                                       &opres[i])
                                   : apply_payment(&c, t, op, osrc,
                                                   &opres[i]);
                if (rc < 0)
                    goto txfail;
                op_ok = (opres[i].code == opINNER &&
                         opres[i].inner_code == 0);
            }
            if (op_ok) {
                opch[i] = delta_changes_blob(&c, 3);
                if (!opch[i])
                    goto txfail;
                if (commit_level(&c, 3) < 0)
                    goto txfail;
            } else {
                rollback_level(&c, 3);
                ok = 0;
            }
            if (op->optype >= 0 && op->optype < MAX_OPTYPES) {
                c.op_cnt[op->optype]++;
                c.op_ns[op->optype] += now_ns() - t_op;
            }
        }
        if (ok) {
            if (commit_level(&c, 2) < 0 || commit_level(&c, 1) < 0)
                goto txfail;
            resb = build_result(&c, t->feeCharged, txSUCCESS, t->nops,
                                opres);
            if (!resb)
                goto txfail;
            meta = build_meta(&c, txch, t->nops, opch);
            if (!meta)
                goto txfail;
        } else {
            rollback_level(&c, 2);
            resb = build_result(&c, t->feeCharged, txFAILED, t->nops,
                                opres);
            if (!resb)
                goto txfail;
            meta = build_meta(&c, txch, t->nops, NULL); /* metas wiped */
            if (!meta)
                goto txfail;
        }

    txemit:
        if (PyList_Append(results, resb) < 0 ||
            PyList_Append(metas, meta) < 0) {
            c.pyerr = 1;
            goto txfail;
        }
        Py_CLEAR(resb);
        Py_CLEAR(meta);
        Py_CLEAR(txch);
        PyMem_Free(vs.pairs);
        PyMem_Free(opres);
        if (opch)
            for (i = 0; i < t->nops; i++)
                Py_XDECREF(opch[i]);
        PyMem_Free(opch);
        continue;

    txfail:
        Py_XDECREF(resb);
        Py_XDECREF(meta);
        Py_XDECREF(txch);
        PyMem_Free(vs.pairs);
        PyMem_Free(opres);
        if (opch)
            for (i = 0; i < t->nops; i++)
                Py_XDECREF(opch[i]);
        PyMem_Free(opch);
        goto done;
    }

    /* ---- outputs: close-level changed entries, first-touch order */
    changes = PyList_New(0);
    if (!changes) {
        c.pyerr = 1;
        goto done;
    }
    for (i = 0; i < c.ntouched[0]; i++) {
        Entry *e = c.touched[0][i];
        EntrySave *s = &e->save[0];
        if (!entry_changed_since(e, s))
            continue;
        PyObject *key = PyBytes_FromStringAndSize((const char *)e->keyb,
                                                  e->keylen);
        PyObject *prev = NULL, *cur = NULL;
        if (key && s->exists) {
            Buf b = {NULL, 0, 0};
            if (ser_entry(&c, e, s->balance, s->seqNum, &s->st, &b) == 0)
                prev = PyBytes_FromStringAndSize(b.data, b.len);
            PyMem_Free(b.data);
        } else if (key) {
            prev = Py_None;
            Py_INCREF(prev);
        }
        if (key && prev && e->exists) {
            Buf b = {NULL, 0, 0};
            if (ser_entry(&c, e, e->balance, e->seqNum, &e->st, &b) == 0)
                cur = PyBytes_FromStringAndSize(b.data, b.len);
            PyMem_Free(b.data);
        } else if (key && prev) {
            cur = Py_None;
            Py_INCREF(cur);
        }
        PyObject *tup = (key && prev && cur)
                            ? PyTuple_Pack(3, key, prev, cur)
                            : NULL;
        Py_XDECREF(key);
        Py_XDECREF(prev);
        Py_XDECREF(cur);
        if (!tup || PyList_Append(changes, tup) < 0) {
            Py_XDECREF(tup);
            c.pyerr = 1;
            goto done;
        }
        Py_DECREF(tup);
    }

    {
        /* per-op-type attribution table: {op_type: (count, ns)} — the
           close cockpit's native-path per-op breakdown (ISSUE 9) */
        PyObject *op_stats = PyDict_New();
        if (!op_stats) {
            c.pyerr = 1;
            goto done;
        }
        for (i = 0; i < MAX_OPTYPES; i++) {
            if (!c.op_cnt[i])
                continue;
            PyObject *k = PyLong_FromLong(i);
            PyObject *v2 = Py_BuildValue(
                "(LL)", (long long)c.op_cnt[i], (long long)c.op_ns[i]);
            if (!k || !v2 || PyDict_SetItem(op_stats, k, v2) < 0) {
                Py_XDECREF(k);
                Py_XDECREF(v2);
                Py_DECREF(op_stats);
                c.pyerr = 1;
                goto done;
            }
            Py_DECREF(k);
            Py_DECREF(v2);
        }
        out = Py_BuildValue("{s:L,s:O,s:O,s:O,s:O,s:O}", "feePool",
                            (long long)c.feePool, "changes", changes,
                            "results", results, "fee_changes", fee_changes,
                            "meta", metas, "op_stats", op_stats);
        Py_DECREF(op_stats);
        if (!out)
            c.pyerr = 1;
    }

done:
    bailing = c.bail && !c.pyerr;
    for (ti = 0; ti < ntx; ti++) {
        PyMem_Free(txs[ti].ops);
        for (i = 0; i < txs[ti].nsigs; i++)
            Py_XDECREF(txs[ti].sigs[i].sig_obj);
    }
    PyMem_Free(txs);
    Py_XDECREF(results);
    Py_XDECREF(fee_changes);
    Py_XDECREF(metas);
    Py_XDECREF(changes);
    ctx_free(&c);
    if (c.pyerr)
        return NULL;
    if (bailing)
        /* classified bail: the caller marks
           ledger.apply.native-bail.<reason> and falls back to Python
           (c.bailbuf lives in the stack Ctx — still valid here) */
        return Py_BuildValue("{s:s}", "bail",
                             c.bailmsg ? c.bailmsg : "unsupported");
    if (!out)
        Py_RETURN_NONE;
    return out;
}

static PyMethodDef methods[] = {
    {"apply_close", apply_close, METH_VARARGS,
     "apply_close(params, envs, hashes, lookup, verify) -> dict | None"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_sctapply",
    "Native transaction-apply fast path (see module docstring in source).",
    -1, methods,
};

PyMODINIT_FUNC PyInit__sctapply(void)
{
    return PyModule_Create(&moduledef);
}
