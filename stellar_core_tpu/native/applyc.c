/* Native transaction-apply fast path — full op coverage + conflict-graph
 * parallel close (ISSUE 13).
 *
 * This module implements the fee and apply phases of a ledger close for
 * every wire operation type (CREATE_ACCOUNT..PATH_PAYMENT_STRICT_SEND),
 * plain v1 AND fee-bump envelopes, muxed (med25519) account references,
 * protocol >= 10 — and returns {"bail": reason} for the residual inputs
 * the Python path (the semantics oracle, tests/test_native_apply.py)
 * still owns: non-ed25519 signer keys, >255 thresholds on the wire,
 * inflation payouts (protocol < 12 with the weekly timer due),
 * malformed-at-apply op shapes whose Python behavior is an exception.
 *
 * Contract: entry-for-entry identical output to the Python path — same
 * LedgerTxn delta (keys, pre-images, post-images, first-touch order),
 * same TransactionResult XDR, same fee/tx/op meta XDR — so header
 * hashes are bit-identical whichever path applied the close.
 *
 * Concurrency model (the conflict-graph parallel close):
 *   1. parse + prefetch: every statically-knowable LedgerKey a tx can
 *      touch is loaded through the Python lookup callback up front.
 *      Ops whose key set is state-dependent (offers, path payments,
 *      allow-trust revokes — they walk the order book) mark the close
 *      "dynamic": it still applies natively, but serially with the GIL.
 *   2. pre-verify: one batched verify() callback covers every
 *      (signer-key, signature, contents-hash) pair any tx could consume
 *      — live signer sets plus the statically-knowable additions
 *      (set-options signers, created-account master keys), so apply
 *      never needs Python again. Signer-set MEMBERSHIP is still
 *      evaluated against live state at apply time; the prepass only
 *      fixes the pure (key, sig, msg) verify results.
 *   3. fees: serial, in tx order (cheap; the per-tx fee deltas are the
 *      txfeehistory rows).
 *   4. apply: txs are union-found into clusters by touched entries;
 *      disjoint clusters apply concurrently on pthreads with the GIL
 *      released (malloc-only, no CPython calls). A fully-static close
 *      that doesn't parallelize still drops the GIL for the serial
 *      apply loop, so the catchup pipeline can verify ledger N+1
 *      underneath. Serial-equivalence: each entry's first level-0 touch
 *      is stamped (tx index, within-tx ordinal) and the merged
 *      close-level delta is sorted by stamp, reproducing the serial
 *      first-touch order exactly.
 *   5. emit: results / fee / meta XDR and the close-level delta are
 *      materialized into Python objects with the GIL, from the plain-C
 *      buffers the apply phase produced.
 *
 * Entry point: apply_close(params, envs, hashes, lookup, verify, book,
 * acct_offers, opts) -> dict | None. `book(selling, buying)` and
 * `acct_offers(account)` return root-state offer blobs for the order
 * book and per-seller offer scans; the overlay merges its own
 * created/modified/erased offers on top. `hashes[i]` is the tx
 * contents hash — 64 bytes (outer||inner) for fee bumps. opts:
 * {"workers": N, "mode": "auto"|"serial"|"parallel"}.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <stddef.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#define LET_ACCOUNT 0
#define LET_TRUSTLINE 1
#define LET_OFFER 2
#define LET_DATA 3

/* TransactionResultCode */
#define txFEE_BUMP_INNER_SUCCESS 1
#define txSUCCESS 0
#define txFAILED (-1)
#define txTOO_EARLY (-2)
#define txTOO_LATE (-3)
#define txMISSING_OPERATION (-4)
#define txBAD_SEQ (-5)
#define txBAD_AUTH (-6)
#define txNO_ACCOUNT (-8)
#define txINSUFFICIENT_FEE (-9)
#define txBAD_AUTH_EXTRA (-10)
#define txINTERNAL_ERROR (-11)
#define txNOT_SUPPORTED (-12)
#define txFEE_BUMP_INNER_FAILED (-13)

/* OperationResultCode */
#define opINNER 0
#define opBAD_AUTH (-1)
#define opNO_ACCOUNT (-2)
#define opNOT_SUPPORTED (-3)

/* OperationType (all 14) */
#define OP_CREATE_ACCOUNT 0
#define OP_PAYMENT 1
#define OP_PATH_PAYMENT_RECV 2
#define OP_MANAGE_SELL_OFFER 3
#define OP_CREATE_PASSIVE_OFFER 4
#define OP_SET_OPTIONS 5
#define OP_CHANGE_TRUST 6
#define OP_ALLOW_TRUST 7
#define OP_ACCOUNT_MERGE 8
#define OP_INFLATION 9
#define OP_MANAGE_DATA 10
#define OP_BUMP_SEQUENCE 11
#define OP_MANAGE_BUY_OFFER 12
#define OP_PATH_PAYMENT_SEND 13

/* SetOptionsResultCode */
#define SO_SUCCESS 0
#define SO_LOW_RESERVE (-1)
#define SO_TOO_MANY_SIGNERS (-2)
#define SO_INVALID_INFLATION (-4)
#define SO_CANT_CHANGE (-5)

/* CreateAccountResultCode */
#define CA_SUCCESS 0
#define CA_UNDERFUNDED (-2)
#define CA_LOW_RESERVE (-3)
#define CA_ALREADY_EXIST (-4)

/* PaymentResultCode */
#define PAY_SUCCESS 0
#define PAY_UNDERFUNDED (-2)
#define PAY_SRC_NO_TRUST (-3)
#define PAY_SRC_NOT_AUTHORIZED (-4)
#define PAY_NO_DESTINATION (-5)
#define PAY_NO_TRUST (-6)
#define PAY_NOT_AUTHORIZED (-7)
#define PAY_LINE_FULL (-8)
#define PAY_NO_ISSUER (-9)

/* PathPaymentResultCode (shared by both strictness arms) */
#define PP_SUCCESS 0
#define PP_UNDERFUNDED (-2)
#define PP_SRC_NO_TRUST (-3)
#define PP_SRC_NOT_AUTHORIZED (-4)
#define PP_NO_DESTINATION (-5)
#define PP_NO_TRUST (-6)
#define PP_NOT_AUTHORIZED (-7)
#define PP_LINE_FULL (-8)
#define PP_NO_ISSUER (-9)
#define PP_TOO_FEW_OFFERS (-10)
#define PP_OFFER_CROSS_SELF (-11)
#define PP_OVER_LIMIT (-12)  /* OVER_SENDMAX / UNDER_DESTMIN */

/* ManageOfferResultCode */
#define MO_SUCCESS 0
#define MO_SELL_NO_TRUST (-2)
#define MO_SELL_NOT_AUTHORIZED (-3)
#define MO_BUY_NO_TRUST (-4)
#define MO_BUY_NOT_AUTHORIZED (-5)
#define MO_LINE_FULL (-6)
#define MO_UNDERFUNDED (-7)
#define MO_CROSS_SELF (-8)
#define MO_SELL_NO_ISSUER (-9)
#define MO_BUY_NO_ISSUER (-10)
#define MO_NOT_FOUND (-11)
#define MO_LOW_RESERVE (-12)

/* ChangeTrustResultCode */
#define CT_SUCCESS 0
#define CT_NO_ISSUER (-2)
#define CT_INVALID_LIMIT (-3)
#define CT_LOW_RESERVE (-4)
#define CT_SELF_NOT_ALLOWED (-5)

/* AllowTrustResultCode */
#define AT_SUCCESS 0
#define AT_NO_TRUST_LINE (-2)
#define AT_TRUST_NOT_REQUIRED (-3)
#define AT_CANT_REVOKE (-4)
#define AT_SELF_NOT_ALLOWED (-5)

/* AccountMergeResultCode */
#define AM_SUCCESS 0
#define AM_NO_ACCOUNT (-2)
#define AM_IMMUTABLE_SET (-3)
#define AM_HAS_SUB_ENTRIES (-4)
#define AM_SEQNUM_TOO_FAR (-5)
#define AM_DEST_FULL (-6)

/* InflationResultCode */
#define INF_SUCCESS 0
#define INF_NOT_TIME (-1)
#define INFLATION_FREQUENCY 604800LL

/* ManageDataResultCode */
#define MD_SUCCESS 0
#define MD_NAME_NOT_FOUND (-2)
#define MD_LOW_RESERVE (-3)

/* BumpSequenceResultCode */
#define BS_SUCCESS 0

/* AccountFlags / TrustLineFlags / OfferEntryFlags */
#define AUTH_REQUIRED_FLAG 0x1
#define AUTH_REVOCABLE_FLAG 0x2
#define AUTH_IMMUTABLE_FLAG 0x4
#define TL_AUTHORIZED 1
#define TL_MAINTAIN 2
#define TL_AUTH_LEVELS_MASK 3
#define OFFER_PASSIVE_FLAG 1

#define MAX_SUBENTRIES 1000
#define INT64_MAXV 0x7fffffffffffffffLL
#define MAXLEVEL 4
#define NBUCKETS 4096
#define MAX_SIGNERS 20
#define MAX_SIGS 20
#define MAX_OPTYPES 16 /* wire op types are 0..13; table rounded up */
#define MAX_ASSET 52   /* alphanum12 asset XDR: 4+12+4+32 */
#define MAX_PATH 5
#define MAX_WORKERS 32

/* ------------------------------------------------- arena + buffer */

/* Bump allocator: the apply phase's per-op buffers (delta blobs, op
   payloads) live until emission, so per-buffer malloc/free churns the
   allocator from every worker thread at once — under sandboxed kernels
   (gVisor) that contention costs more than the apply work itself. Each
   apply context owns an arena; blocks free wholesale at close end. */
typedef struct ABlock {
    struct ABlock *next;
    size_t used, cap;
    /* data follows */
} ABlock;

typedef struct {
    ABlock *head;
} Arena;

#define ARENA_BLOCK (256 * 1024)

static void *arena_alloc(Arena *a, size_t n)
{
    n = (n + 15) & ~(size_t)15;
    ABlock *b = a->head;
    if (!b || b->used + n > b->cap) {
        size_t cap = n > ARENA_BLOCK ? n : ARENA_BLOCK;
        b = malloc(sizeof(ABlock) + cap);
        if (!b)
            return NULL;
        b->cap = cap;
        b->used = 0;
        b->next = a->head;
        a->head = b;
    }
    void *p = (char *)(b + 1) + b->used;
    b->used += n;
    return p;
}

static void arena_free_all(Arena *a)
{
    ABlock *b = a->head;
    while (b) {
        ABlock *n = b->next;
        free(b);
        b = n;
    }
    a->head = NULL;
}

typedef struct {
    char *data;
    Py_ssize_t len, cap;
    Arena *ar; /* NULL: plain malloc/realloc ownership */
} Buf;

static int buf_put(Buf *b, const void *src, Py_ssize_t n)
{
    if (b->len + n > b->cap) {
        Py_ssize_t cap = b->cap ? b->cap : 256;
        while (cap < b->len + n)
            cap *= 2;
        char *p;
        if (b->ar) {
            p = arena_alloc(b->ar, cap);
            if (p && b->len)
                memcpy(p, b->data, b->len);
        } else
            p = realloc(b->data, cap);
        if (!p)
            return -1;
        b->data = p;
        b->cap = cap;
    }
    if (n) /* UBSan: memcpy src must be non-null even for n==0 */
        memcpy(b->data + b->len, src, n);
    b->len += n;
    return 0;
}

static int buf_u32(Buf *b, uint32_t v)
{
    unsigned char w[4] = {(unsigned char)(v >> 24), (unsigned char)(v >> 16),
                          (unsigned char)(v >> 8), (unsigned char)v};
    return buf_put(b, w, 4);
}

static int buf_i32(Buf *b, int32_t v) { return buf_u32(b, (uint32_t)v); }

static int buf_u64(Buf *b, uint64_t v)
{
    unsigned char w[8];
    int i;
    for (i = 0; i < 8; i++)
        w[i] = (unsigned char)(v >> (56 - 8 * i));
    return buf_put(b, w, 8);
}

static int buf_i64(Buf *b, int64_t v) { return buf_u64(b, (uint64_t)v); }

static int buf_padded(Buf *b, const uint8_t *p, int n)
{
    static const uint8_t z[4] = {0, 0, 0, 0};
    int pad = (4 - (n & 3)) & 3;
    if (buf_put(b, p, n) < 0)
        return -1;
    if (pad && buf_put(b, z, pad) < 0)
        return -1;
    return 0;
}

static void buf_free(Buf *b)
{
    if (!b->ar)
        free(b->data);
    b->data = NULL;
    b->len = b->cap = 0;
}

static void wr_u32_at(uint8_t *p, uint32_t v)
{
    p[0] = (uint8_t)(v >> 24);
    p[1] = (uint8_t)(v >> 16);
    p[2] = (uint8_t)(v >> 8);
    p[3] = (uint8_t)v;
}

static void wr_i64_at(uint8_t *p, int64_t sv)
{
    uint64_t v = (uint64_t)sv;
    int i;
    for (i = 0; i < 8; i++)
        p[i] = (uint8_t)(v >> (56 - 8 * i));
}

/* ------------------------------------------------------------- reader */

typedef struct {
    const uint8_t *p;
    Py_ssize_t len, pos;
} Rd;

static int rd_u32(Rd *r, uint32_t *v)
{
    if (r->pos + 4 > r->len)
        return -1;
    const uint8_t *p = r->p + r->pos;
    *v = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | p[3];
    r->pos += 4;
    return 0;
}

static int rd_i64(Rd *r, int64_t *v)
{
    if (r->pos + 8 > r->len)
        return -1;
    const uint8_t *p = r->p + r->pos;
    uint64_t u = 0;
    int i;
    for (i = 0; i < 8; i++)
        u = (u << 8) | p[i];
    *v = (int64_t)u;
    r->pos += 8;
    return 0;
}

static int rd_u64(Rd *r, uint64_t *v)
{
    int64_t s;
    if (rd_i64(r, &s) < 0)
        return -1;
    *v = (uint64_t)s;
    return 0;
}

static const uint8_t *rd_take(Rd *r, Py_ssize_t n)
{
    if (n < 0 || r->pos + n > r->len)
        return NULL;
    const uint8_t *p = r->p + r->pos;
    r->pos += n;
    return p;
}

static int rd_skip_padded(Rd *r, Py_ssize_t n)
{
    Py_ssize_t pad = (4 - (n & 3)) & 3;
    return rd_take(r, n + pad) ? 0 : -1;
}

/* ------------------------------------------------------------- entries */

/* The COMPLETE mutable state of one ledger entry under the supported
   ops, snapshotted whole per savepoint level. One struct for all four
   entry kinds keeps the journal a single struct copy; at ~1KB per
   first-touch per level that is still noise next to one signature
   verify. Byte-exact rollback/diff needs the full pre-image — a dirty
   flag cannot reproduce Python's touched-but-unchanged filtering when
   an op writes identical values. */
typedef struct {
    int exists;
    int64_t balance, seqNum;
    /* account */
    uint32_t numSub, flags; /* flags shared with trustline/offer */
    uint8_t thresholds[4];
    int nsigners;
    int has_infl;
    int home_len;
    int ext_v; /* AccountEntryExt / TrustLineEntryExt version (0/1) */
    int64_t liab_buying, liab_selling;
    /* trustline */
    int64_t tl_limit;
    /* offer */
    int64_t o_amount;
    int32_t o_pn, o_pd;
    /* data */
    int d_len;
    /* lastModifiedLedgerSeq this state serializes with (the base
       blob's value for loaded entries; the creating close's seq for
       entries created/recreated this close) */
    uint32_t lm;
    /* ---- variable-occupancy tails: everything below is only LIVE up
       to the counters above, and mut_copy() moves only the live part —
       the ~1KB whole-struct copy per savepoint touch was the close's
       memory-bandwidth ceiling (and what capped parallel scaling) */
    uint8_t infl[32];
    uint8_t home[32];
    uint8_t d_val[64];
    uint32_t signer_weights[MAX_SIGNERS];
    uint8_t signer_keys[MAX_SIGNERS][32];
} MutState;

/* copy only the live bytes of one MutState. Inactive tail slots keep
   stale bytes — every reader (mut_struct_eq, ser_entry, check_sig)
   bounds itself by the counters, so the garbage is never observed. */
static void mut_copy(MutState *dst, const MutState *src)
{
    memcpy(dst, src, offsetof(MutState, infl));
    if (src->has_infl)
        memcpy(dst->infl, src->infl, 32);
    if (src->home_len)
        memcpy(dst->home, src->home, src->home_len);
    if (src->d_len)
        memcpy(dst->d_val, src->d_val, src->d_len);
    if (src->nsigners) {
        memcpy(dst->signer_weights, src->signer_weights,
               src->nsigners * sizeof(uint32_t));
        memcpy(dst->signer_keys, src->signer_keys, src->nsigners * 32);
    }
}

typedef struct {
    int seen;
    MutState st;
} EntrySave;

typedef struct Entry {
    struct Entry *next;
    uint32_t hash;
    uint8_t *keyb;
    int keylen;
    uint8_t *base; /* close-start LedgerEntry blob (owned); NULL if absent */
    int baselen;
    int type;        /* LET_* */
    MutState st;     /* live state */
    MutState base_st; /* as parsed from base (patch fast-path + deltas) */
    /* identity (immutable once set): account id / trustline holder /
       offer seller / data holder */
    uint8_t acc_key[32];
    /* offers only: */
    int64_t offer_id;
    uint8_t o_sell[MAX_ASSET];
    int o_sell_len;
    uint8_t o_buy[MAX_ASSET];
    int o_buy_len;
    /* patch offsets into base blob: */
    int off_balance, off_seq;
    EntrySave save[MAXLEVEL];
    int64_t order0; /* (txidx<<24)|ordinal stamp of first level-0 touch
                       in a parallel cluster (serial-order merge key) */
    int uf_tx;      /* union-find scratch: first tx to claim this entry */
    int in_created; /* already on the created_offers list */
} Entry;

/* field-wise equality of everything EXCEPT balance/seqNum/lm — the
   patch fast-path test (balance/seq byte-patch the base blob) */
static int mut_struct_eq(const MutState *a, const MutState *b)
{
    int i;
    if (a->exists != b->exists || a->numSub != b->numSub ||
        a->flags != b->flags ||
        memcmp(a->thresholds, b->thresholds, 4) != 0 ||
        a->nsigners != b->nsigners || a->has_infl != b->has_infl ||
        a->home_len != b->home_len || a->ext_v != b->ext_v ||
        a->liab_buying != b->liab_buying ||
        a->liab_selling != b->liab_selling ||
        a->tl_limit != b->tl_limit || a->o_amount != b->o_amount ||
        a->o_pn != b->o_pn || a->o_pd != b->o_pd || a->d_len != b->d_len)
        return 0;
    if (a->has_infl && memcmp(a->infl, b->infl, 32) != 0)
        return 0;
    if (a->home_len && memcmp(a->home, b->home, a->home_len) != 0)
        return 0;
    if (a->d_len && memcmp(a->d_val, b->d_val, a->d_len) != 0)
        return 0;
    for (i = 0; i < a->nsigners; i++)
        if (memcmp(a->signer_keys[i], b->signer_keys[i], 32) != 0 ||
            a->signer_weights[i] != b->signer_weights[i])
            return 0;
    return 1;
}

static int mut_eq(const MutState *a, const MutState *b)
{
    if (a->exists != b->exists)
        return 0;
    if (!a->exists)
        return 1; /* both absent: equal regardless of residue */
    if (a->balance != b->balance || a->seqNum != b->seqNum ||
        a->lm != b->lm)
        return 0;
    return mut_struct_eq(a, b);
}

typedef struct {
    Entry **v;
    int n, cap;
} EList;

static int elist_push(EList *l, Entry *e)
{
    if (l->n == l->cap) {
        int cap = l->cap ? l->cap * 2 : 32;
        Entry **p = realloc(l->v, cap * sizeof(Entry *));
        if (!p)
            return -1;
        l->v = p;
        l->cap = cap;
    }
    l->v[l->n++] = e;
    return 0;
}

/* order-book cache: one root fetch per (selling, buying) pair per close */
typedef struct {
    uint8_t sell[MAX_ASSET], buy[MAX_ASSET];
    int sell_len, buy_len;
    EList offers; /* root-order Entry views (overlay state is live) */
} Book;

typedef struct {
    uint8_t acct[32];
    EList offers; /* root-order per-seller offers */
} AcctBook;

/* statically-knowable signer additions: (account, key) pairs from every
   SET_OPTIONS signer arm in the txset — the pre-verify superset */
typedef struct {
    uint8_t acct[32];
    uint8_t key[32];
} StaticSigner;

typedef struct {
    Entry *buckets[NBUCKETS];
    Entry **all;
    int nall, capall;
    EList closed0;          /* global level-0 first-touch order (fee
                               phase + serial apply) */
    EList created_offers;   /* offers created this close, creation order */
    PyObject *lookup, *verify, *book_cb, *acct_cb;
    int64_t feePool, idPool;
    uint32_t ledgerVersion, ledgerSeq, inflationSeq;
    uint64_t closeTime;
    int64_t baseFee, baseReserve, effBase;
    int bail;  /* unsupported input: fall back to the Python path */
    int pyerr; /* a Python exception is set: propagate */
    const char *bailmsg;
    char bailbuf[48];
    Book *books;
    int nbooks, capbooks;
    AcctBook *abooks;
    int nabooks, capabooks;
    StaticSigner *sadds;
    int nsadds, capsadds;
    int nopy; /* GIL released: any Python need is an engine bug -> bail */
    int abort_flag; /* parallel: some cluster bailed/oomed. Written by
        any worker, polled by the rest with no lock in between, so
        access goes through ctx_abort/ctx_aborted (__atomic) ONLY: a
        plain — even volatile — access racing an atomic one is a data
        race under ThreadSanitizer and UB per the C11 memory model. */
} Ctx;

/* per-apply-context view: the journal + attribution one tx stream (the
   serial loop, the fee phase, or one parallel cluster) mutates. Entries
   are disjoint across concurrently-live AEnvs by construction. */
typedef struct {
    Ctx *c;
    EList lv[MAXLEVEL]; /* lv[0] used only when use_local0 */
    int use_local0;     /* parallel cluster: stamp + collect locally */
    int txidx;          /* current global tx index (order stamps) */
    int ord0;           /* within-tx level-0 ordinal */
    int bail, oom;
    const char *bailmsg;
    char bailbuf[48];
    int64_t op_cnt[MAX_OPTYPES];
    int64_t op_ns[MAX_OPTYPES];
    Arena ar; /* owns every deferred-output buffer this context built */
} AEnv;

/* cross-thread abort latch: relaxed is enough — the flag only asks
   workers to stop early; the authoritative bail/oom state merges after
   the pool join (which is the synchronization point). */
static void ctx_abort(Ctx *c)
{
    __atomic_store_n(&c->abort_flag, 1, __ATOMIC_RELAXED);
}

static int ctx_aborted(Ctx *c)
{
    return __atomic_load_n(&c->abort_flag, __ATOMIC_RELAXED);
}

static void env_bail(AEnv *env, const char *msg)
{
    if (!env->bail) {
        env->bail = 1;
        env->bailmsg = msg;
    }
    ctx_abort(env->c);
}

static void ctx_bail(Ctx *c, const char *msg)
{
    if (!c->bailmsg)
        c->bailmsg = msg;
    c->bail = 1;
}

static int64_t now_ns(void)
{
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + (int64_t)ts.tv_nsec;
}

/* per-op attribution clock: two reads per applied op. clock_gettime is
   a real syscall under gVisor-style sandboxes (no vDSO) and its ~µs
   cost both dominates the ~1µs native ops AND serializes parallel
   workers; rdtsc is a register read. Ticks are converted to ns once
   per close against a CLOCK_MONOTONIC bracket (constant_tsc keeps the
   ratio stable; attribution-grade accuracy is all that's needed). */
#if defined(__x86_64__) || defined(__i386__)
static int64_t now_ticks(void)
{
    uint32_t lo, hi;
    __asm__ __volatile__("rdtsc" : "=a"(lo), "=d"(hi));
    return (int64_t)(((uint64_t)hi << 32) | lo);
}
#else
static int64_t now_ticks(void) { return now_ns(); }
#endif

static uint32_t fnv1a(const uint8_t *p, int n)
{
    uint32_t h = 2166136261u;
    int i;
    for (i = 0; i < n; i++) {
        h ^= p[i];
        h *= 16777619u;
    }
    return h;
}

static void ctx_free(Ctx *c)
{
    int i;
    for (i = 0; i < c->nall; i++) {
        Entry *e = c->all[i];
        free(e->keyb);
        free(e->base);
        free(e);
    }
    free(c->all);
    free(c->closed0.v);
    free(c->created_offers.v);
    for (i = 0; i < c->nbooks; i++)
        free(c->books[i].offers.v);
    free(c->books);
    for (i = 0; i < c->nabooks; i++)
        free(c->abooks[i].offers.v);
    free(c->abooks);
    free(c->sadds);
}

/* -------------------------------------------------------- entry parsing */

/* optional entry extension with liabilities: u32 disc {0,1}; v1 carries
   {i64 buying, i64 selling, u32 inner-ext 0} */
static int rd_liab_ext(Rd *r, MutState *st)
{
    uint32_t u;
    if (rd_u32(r, &u) < 0 || u > 1)
        return -1;
    st->ext_v = (int)u;
    st->liab_buying = st->liab_selling = 0;
    if (u == 1) {
        if (rd_i64(r, &st->liab_buying) < 0 ||
            rd_i64(r, &st->liab_selling) < 0)
            return -1;
        if (rd_u32(r, &u) < 0 || u != 0)
            return -1;
    }
    return 0;
}

/* account LedgerEntry blob -> Entry; returns -1 on unsupported */
static int parse_account(Ctx *c, Entry *e, const uint8_t *blob, int len)
{
    Rd r = {blob, len, 0};
    MutState *st = &e->st;
    uint32_t u, ktype, n;
    int i;
    if (rd_u32(&r, &st->lm) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u != LET_ACCOUNT)
        return -1;
    if (rd_u32(&r, &ktype) < 0 || ktype != 0)
        return -1;
    const uint8_t *key = rd_take(&r, 32);
    if (!key)
        return -1;
    memcpy(e->acc_key, key, 32);
    e->off_balance = (int)r.pos;
    if (rd_i64(&r, &st->balance) < 0)
        return -1;
    e->off_seq = (int)r.pos;
    if (rd_i64(&r, &st->seqNum) < 0)
        return -1;
    if (rd_u32(&r, &st->numSub) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u > 1) /* inflationDest optional */
        return -1;
    st->has_infl = (int)u;
    if (u == 1) {
        const uint8_t *ip;
        if (rd_u32(&r, &ktype) < 0 || ktype != 0 ||
            !(ip = rd_take(&r, 32)))
            return -1;
        memcpy(st->infl, ip, 32);
    }
    if (rd_u32(&r, &st->flags) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u > 32) /* homeDomain */
        return -1;
    st->home_len = (int)u;
    if (u) {
        Py_ssize_t at = r.pos;
        if (rd_skip_padded(&r, u) < 0)
            return -1;
        memcpy(st->home, blob + at, u);
    }
    const uint8_t *th = rd_take(&r, 4);
    if (!th)
        return -1;
    memcpy(st->thresholds, th, 4);
    if (rd_u32(&r, &n) < 0)
        return -1;
    if (n > MAX_SIGNERS) {
        ctx_bail(c, "multisig-shape");
        return -1;
    }
    st->nsigners = (int)n;
    for (i = 0; i < st->nsigners; i++) {
        if (rd_u32(&r, &ktype) < 0)
            return -1;
        if (ktype != 0) { /* pre-auth-tx / hash-x signers: Python path */
            ctx_bail(c, "signer-key-type");
            return -1;
        }
        const uint8_t *sk = rd_take(&r, 32);
        if (!sk)
            return -1;
        memcpy(st->signer_keys[i], sk, 32);
        if (rd_u32(&r, &st->signer_weights[i]) < 0)
            return -1;
    }
    if (rd_liab_ext(&r, st) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u != 0) /* LedgerEntry ext */
        return -1;
    if (r.pos != r.len)
        return -1;
    st->exists = 1;
    e->base_st = *st;
    return 0;
}

static int parse_trustline(Ctx *c, Entry *e, const uint8_t *blob, int len)
{
    Rd r = {blob, len, 0};
    MutState *st = &e->st;
    uint32_t u, atype;
    (void)c;
    if (rd_u32(&r, &st->lm) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u != LET_TRUSTLINE)
        return -1;
    const uint8_t *acct;
    if (rd_u32(&r, &u) < 0 || u != 0 || !(acct = rd_take(&r, 32)))
        return -1;
    memcpy(e->acc_key, acct, 32);
    if (rd_u32(&r, &atype) < 0)
        return -1;
    if (atype == 1) {
        if (!rd_take(&r, 4 + 4 + 32))
            return -1;
    } else if (atype == 2) {
        if (!rd_take(&r, 12 + 4 + 32))
            return -1;
    } else
        return -1; /* native trustlines don't exist */
    e->off_balance = (int)r.pos;
    if (rd_i64(&r, &st->balance) < 0)
        return -1;
    if (rd_i64(&r, &st->tl_limit) < 0)
        return -1;
    if (rd_u32(&r, &st->flags) < 0)
        return -1;
    if (rd_liab_ext(&r, st) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u != 0)
        return -1;
    if (r.pos != r.len)
        return -1;
    st->exists = 1;
    e->base_st = *st;
    return 0;
}

/* raw Asset XDR at the reader head -> out[], returns length or -1 */
static int rd_asset_raw(Rd *r, uint8_t *out)
{
    Py_ssize_t at = r->pos;
    uint32_t atype, kt;
    if (rd_u32(r, &atype) < 0)
        return -1;
    if (atype == 0) {
        /* native */
    } else if (atype == 1 || atype == 2) {
        if (!rd_take(r, atype == 1 ? 4 : 12))
            return -1;
        if (rd_u32(r, &kt) < 0 || kt != 0 || !rd_take(r, 32))
            return -1;
    } else
        return -1;
    int n = (int)(r->pos - at);
    memcpy(out, r->p + at, n);
    return n;
}

static int parse_offer(Ctx *c, Entry *e, const uint8_t *blob, int len)
{
    Rd r = {blob, len, 0};
    MutState *st = &e->st;
    uint32_t u;
    (void)c;
    if (rd_u32(&r, &st->lm) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u != LET_OFFER)
        return -1;
    const uint8_t *acct;
    if (rd_u32(&r, &u) < 0 || u != 0 || !(acct = rd_take(&r, 32)))
        return -1;
    memcpy(e->acc_key, acct, 32);
    if (rd_i64(&r, &e->offer_id) < 0)
        return -1;
    e->o_sell_len = rd_asset_raw(&r, e->o_sell);
    if (e->o_sell_len < 0)
        return -1;
    e->o_buy_len = rd_asset_raw(&r, e->o_buy);
    if (e->o_buy_len < 0)
        return -1;
    if (rd_i64(&r, &st->o_amount) < 0)
        return -1;
    uint32_t pn, pd;
    if (rd_u32(&r, &pn) < 0 || rd_u32(&r, &pd) < 0)
        return -1;
    st->o_pn = (int32_t)pn;
    st->o_pd = (int32_t)pd;
    if (rd_u32(&r, &st->flags) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u != 0) /* OfferEntry ext */
        return -1;
    if (rd_u32(&r, &u) < 0 || u != 0) /* LedgerEntry ext */
        return -1;
    if (r.pos != r.len)
        return -1;
    st->exists = 1;
    e->base_st = *st;
    return 0;
}

static int parse_data(Ctx *c, Entry *e, const uint8_t *blob, int len)
{
    Rd r = {blob, len, 0};
    MutState *st = &e->st;
    uint32_t u, n;
    (void)c;
    if (rd_u32(&r, &st->lm) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u != LET_DATA)
        return -1;
    const uint8_t *acct;
    if (rd_u32(&r, &u) < 0 || u != 0 || !(acct = rd_take(&r, 32)))
        return -1;
    memcpy(e->acc_key, acct, 32);
    if (rd_u32(&r, &n) < 0 || n > 64) /* dataName */
        return -1;
    if (rd_skip_padded(&r, n) < 0) /* name lives in keyb; skip here */
        return -1;
    if (rd_u32(&r, &n) < 0 || n > 64) /* dataValue */
        return -1;
    st->d_len = (int)n;
    {
        Py_ssize_t at = r.pos;
        if (rd_skip_padded(&r, n) < 0)
            return -1;
        memcpy(st->d_val, blob + at, n);
    }
    if (rd_u32(&r, &u) < 0 || u != 0) /* DataEntry ext */
        return -1;
    if (rd_u32(&r, &u) < 0 || u != 0) /* LedgerEntry ext */
        return -1;
    if (r.pos != r.len)
        return -1;
    st->exists = 1;
    e->base_st = *st;
    return 0;
}

/* ------------------------------------------------------- overlay access */

static Entry *find_entry(Ctx *c, const uint8_t *keyb, int keylen,
                         uint32_t *hout)
{
    uint32_t h = fnv1a(keyb, keylen);
    if (hout)
        *hout = h;
    Entry *e = c->buckets[h & (NBUCKETS - 1)];
    for (; e; e = e->next)
        if (e->hash == h && e->keylen == keylen &&
            memcmp(e->keyb, keyb, keylen) == 0)
            return e;
    return NULL;
}

/* allocate + index a fresh Entry shell for keyb (state unset) */
static Entry *insert_entry(AEnv *env, const uint8_t *keyb, int keylen,
                           uint32_t h)
{
    Ctx *c = env->c;
    Entry *e = calloc(1, sizeof(Entry));
    if (!e) {
        env->oom = 1;
        return NULL;
    }
    e->hash = h;
    e->keylen = keylen;
    e->keyb = malloc(keylen);
    if (!e->keyb) {
        free(e);
        env->oom = 1;
        return NULL;
    }
    memcpy(e->keyb, keyb, keylen);
    e->uf_tx = -1;
    {
        Rd kr = {keyb, keylen, 0};
        uint32_t kt = 0;
        rd_u32(&kr, &kt);
        e->type = (int)kt;
    }
    if (c->nall == c->capall) {
        int cap = c->capall ? c->capall * 2 : 64;
        Entry **p = realloc(c->all, cap * sizeof(Entry *));
        if (!p) {
            free(e->keyb);
            free(e);
            env->oom = 1;
            return NULL;
        }
        c->all = p;
        c->capall = cap;
    }
    c->all[c->nall++] = e;
    e->next = c->buckets[h & (NBUCKETS - 1)];
    c->buckets[h & (NBUCKETS - 1)] = e;
    return e;
}

/* parse a base blob into a freshly-inserted entry */
static int entry_adopt_blob(AEnv *env, Entry *e, const uint8_t *blob,
                            int len)
{
    Ctx *c = env->c;
    e->base = malloc(len > 0 ? len : 1);
    if (!e->base) {
        env->oom = 1;
        return -1;
    }
    memcpy(e->base, blob, len);
    e->baselen = len;
    int rc;
    switch (e->type) {
    case LET_ACCOUNT:
        rc = parse_account(c, e, e->base, len);
        break;
    case LET_TRUSTLINE:
        rc = parse_trustline(c, e, e->base, len);
        break;
    case LET_OFFER:
        rc = parse_offer(c, e, e->base, len);
        break;
    case LET_DATA:
        rc = parse_data(c, e, e->base, len);
        break;
    default:
        rc = -1;
    }
    if (rc < 0) {
        if (!c->bailmsg)
            ctx_bail(c, "entry-kind");
        env->bail = 1;
        env->bailmsg = c->bailmsg;
        ctx_abort(c);
        return -1;
    }
    return 0;
}

/* overlay get-or-load; NULL means bail/oom/pyerr (check env/ctx flags).
   A miss calls the Python lookup callback — illegal when the GIL is
   released (c->nopy): that is an engine bug (incomplete static prefetch),
   surfaced as a bail so the close re-runs on the Python path. */
static Entry *get_entry(AEnv *env, const uint8_t *keyb, int keylen)
{
    Ctx *c = env->c;
    uint32_t h;
    Entry *e = find_entry(c, keyb, keylen, &h);
    if (e)
        return e;
    if (c->nopy) {
        env_bail(env, "prefetch-miss");
        return NULL;
    }

    PyObject *kb = PyBytes_FromStringAndSize((const char *)keyb, keylen);
    if (!kb) {
        c->pyerr = 1;
        return NULL;
    }
    PyObject *blob = PyObject_CallFunctionObjArgs(c->lookup, kb, NULL);
    Py_DECREF(kb);
    if (!blob) {
        c->pyerr = 1;
        return NULL;
    }
    e = insert_entry(env, keyb, keylen, h);
    if (!e) {
        Py_DECREF(blob);
        return NULL;
    }
    if (blob == Py_None) {
        /* absent: exists stays 0 */
    } else if (PyBytes_Check(blob)) {
        if (entry_adopt_blob(env, e, (const uint8_t *)PyBytes_AS_STRING(blob),
                             (int)PyBytes_GET_SIZE(blob)) < 0) {
            Py_DECREF(blob);
            return NULL;
        }
    } else {
        ctx_bail(c, "lookup-type");
        env->bail = 1;
        Py_DECREF(blob);
        return NULL;
    }
    Py_DECREF(blob);
    return e;
}

static Entry *get_account(AEnv *env, const uint8_t *accid)
{
    uint8_t keyb[40];
    wr_u32_at(keyb, LET_ACCOUNT);
    wr_u32_at(keyb + 4, 0); /* PUBLIC_KEY_TYPE_ED25519 */
    memcpy(keyb + 8, accid, 32);
    return get_entry(env, keyb, 40);
}

/* trustline key: u32 TRUSTLINE | AccountID | Asset (raw asset bytes) */
static Entry *get_trustline(AEnv *env, const uint8_t *accid,
                            const uint8_t *asset, int assetlen)
{
    uint8_t keyb[40 + MAX_ASSET];
    wr_u32_at(keyb, LET_TRUSTLINE);
    wr_u32_at(keyb + 4, 0);
    memcpy(keyb + 8, accid, 32);
    memcpy(keyb + 40, asset, assetlen);
    return get_entry(env, keyb, 40 + assetlen);
}

/* data key: u32 DATA | AccountID | string64 name */
static Entry *get_data(AEnv *env, const uint8_t *accid,
                       const uint8_t *name, int namelen)
{
    uint8_t keyb[40 + 4 + 64 + 4];
    int pad = (4 - (namelen & 3)) & 3;
    wr_u32_at(keyb, LET_DATA);
    wr_u32_at(keyb + 4, 0);
    memcpy(keyb + 8, accid, 32);
    wr_u32_at(keyb + 40, (uint32_t)namelen);
    memcpy(keyb + 44, name, namelen);
    memset(keyb + 44 + namelen, 0, pad);
    return get_entry(env, keyb, 44 + namelen + pad);
}

static void offer_key(uint8_t *keyb, const uint8_t *seller, int64_t oid)
{
    wr_u32_at(keyb, LET_OFFER);
    wr_u32_at(keyb + 4, 0);
    memcpy(keyb + 8, seller, 32);
    wr_i64_at(keyb + 40, oid);
}

/* ----------------------------------------------------- savepoint journal */

static int touch(AEnv *env, Entry *e, int lv)
{
    if (e->save[lv].seen)
        return 0;
    e->save[lv].seen = 1;
    mut_copy(&e->save[lv].st, &e->st);
    if (elist_push(&env->lv[lv], e) < 0) {
        env->oom = 1;
        ctx_abort(env->c);
        return -1;
    }
    return 0;
}

/* commit level lv into lv-1. Level-0 destination is the global
   closed0 list in serial mode, or the cluster-local stamped list in
   parallel mode (sorted back into serial first-touch order after the
   join). */
static int commit_level(AEnv *env, int lv)
{
    int i;
    EList *from = &env->lv[lv];
    for (i = 0; i < from->n; i++) {
        Entry *e = from->v[i];
        if (!e->save[lv - 1].seen) {
            mut_copy(&e->save[lv - 1].st, &e->save[lv].st);
            e->save[lv - 1].seen = 1;
            if (lv == 1) {
                if (env->use_local0) {
                    e->order0 = ((int64_t)env->txidx << 24) |
                                (int64_t)env->ord0++;
                    if (elist_push(&env->lv[0], e) < 0) {
                        env->oom = 1;
                        ctx_abort(env->c);
                        return -1;
                    }
                } else {
                    if (elist_push(&env->c->closed0, e) < 0) {
                        env->oom = 1;
                        ctx_abort(env->c);
                        return -1;
                    }
                }
            } else {
                if (elist_push(&env->lv[lv - 1], e) < 0) {
                    env->oom = 1;
                    ctx_abort(env->c);
                    return -1;
                }
            }
        }
        e->save[lv].seen = 0;
    }
    from->n = 0;
    return 0;
}

static void rollback_level(AEnv *env, int lv)
{
    int i;
    EList *from = &env->lv[lv];
    for (i = 0; i < from->n; i++) {
        Entry *e = from->v[i];
        mut_copy(&e->st, &e->save[lv].st);
        e->save[lv].seen = 0;
    }
    from->n = 0;
}

/* -------------------------------------------------------- serialization */

/* append the LedgerEntry blob for state `st` of entry e (st->exists
   assumed). Patch fast-path: when only balance/seqNum moved against the
   base parse, the base blob is reused bitwise with the two fields
   patched — zero re-encode risk on the payment path. */
static int ser_entry(Entry *e, const MutState *st, Buf *out)
{
    if (e->base && st->lm == e->base_st.lm &&
        (e->type == LET_ACCOUNT || e->type == LET_TRUSTLINE) &&
        mut_struct_eq(st, &e->base_st)) {
        Py_ssize_t at = out->len;
        if (buf_put(out, e->base, e->baselen) < 0)
            return -1;
        uint8_t *p = (uint8_t *)out->data + at;
        wr_i64_at(p + e->off_balance, st->balance);
        if (e->type == LET_ACCOUNT)
            wr_i64_at(p + e->off_seq, st->seqNum);
        return 0;
    }
    if (buf_u32(out, st->lm) < 0 || buf_u32(out, (uint32_t)e->type) < 0)
        return -1;
    switch (e->type) {
    case LET_ACCOUNT:
        if (buf_u32(out, 0) < 0 || buf_put(out, e->acc_key, 32) < 0 ||
            buf_i64(out, st->balance) < 0 || buf_i64(out, st->seqNum) < 0 ||
            buf_u32(out, st->numSub) < 0 ||
            buf_u32(out, (uint32_t)st->has_infl) < 0)
            return -1;
        if (st->has_infl &&
            (buf_u32(out, 0) < 0 || buf_put(out, st->infl, 32) < 0))
            return -1;
        if (buf_u32(out, st->flags) < 0 ||
            buf_u32(out, (uint32_t)st->home_len) < 0)
            return -1;
        if (st->home_len && buf_padded(out, st->home, st->home_len) < 0)
            return -1;
        if (buf_put(out, st->thresholds, 4) < 0 ||
            buf_u32(out, (uint32_t)st->nsigners) < 0)
            return -1;
        for (int i = 0; i < st->nsigners; i++) {
            if (buf_u32(out, 0) < 0 /* SIGNER_KEY_TYPE_ED25519 */ ||
                buf_put(out, st->signer_keys[i], 32) < 0 ||
                buf_u32(out, st->signer_weights[i]) < 0)
                return -1;
        }
        break;
    case LET_TRUSTLINE:
        /* holder + asset are the key's bytes (keyb+8 / keyb+40..) */
        if (buf_u32(out, 0) < 0 || buf_put(out, e->keyb + 8, 32) < 0 ||
            buf_put(out, e->keyb + 40, e->keylen - 40) < 0 ||
            buf_i64(out, st->balance) < 0 ||
            buf_i64(out, st->tl_limit) < 0 ||
            buf_u32(out, st->flags) < 0)
            return -1;
        break;
    case LET_OFFER:
        if (buf_u32(out, 0) < 0 || buf_put(out, e->acc_key, 32) < 0 ||
            buf_i64(out, e->offer_id) < 0 ||
            buf_put(out, e->o_sell, e->o_sell_len) < 0 ||
            buf_put(out, e->o_buy, e->o_buy_len) < 0 ||
            buf_i64(out, st->o_amount) < 0 ||
            buf_i32(out, st->o_pn) < 0 || buf_i32(out, st->o_pd) < 0 ||
            buf_u32(out, st->flags) < 0 ||
            buf_u32(out, 0) < 0 /* OfferEntry ext */)
            return -1;
        break;
    case LET_DATA:
        /* holder + name are the key's bytes */
        if (buf_u32(out, 0) < 0 || buf_put(out, e->keyb + 8, 32) < 0 ||
            buf_put(out, e->keyb + 40, e->keylen - 40) < 0 ||
            buf_u32(out, (uint32_t)st->d_len) < 0 ||
            (st->d_len && buf_padded(out, st->d_val, st->d_len) < 0) ||
            buf_u32(out, 0) < 0 /* DataEntry ext */)
            return -1;
        break;
    default:
        return -1;
    }
    if (e->type == LET_ACCOUNT || e->type == LET_TRUSTLINE) {
        /* AccountEntryExt / TrustLineEntryExt (+ liabilities at v1) */
        if (buf_u32(out, (uint32_t)st->ext_v) < 0)
            return -1;
        if (st->ext_v == 1 &&
            (buf_i64(out, st->liab_buying) < 0 ||
             buf_i64(out, st->liab_selling) < 0 ||
             buf_u32(out, 0) < 0 /* v1 inner ext */))
            return -1;
    }
    if (buf_u32(out, 0) < 0 /* LedgerEntry ext v0 */)
        return -1;
    return 0;
}

/* LedgerEntryChanges blob for level lv of env (does NOT commit).
   Mirrors LedgerTxn.get_delta + delta_to_changes: entries in
   first-touch order, touched-but-unchanged filtered, STATE before
   UPDATED/REMOVED, CREATED alone. Returns a malloc Buf (caller owns). */
static int delta_changes_buf(AEnv *env, int lv, Buf *b)
{
    uint32_t n = 0;
    int i;
    if (buf_u32(b, 0) < 0)
        goto oom;
    for (i = 0; i < env->lv[lv].n; i++) {
        Entry *e = env->lv[lv].v[i];
        EntrySave *s = &e->save[lv];
        if (mut_eq(&e->st, &s->st))
            continue;
        if (s->st.exists && e->st.exists) {
            if (buf_u32(b, 3) < 0 || /* LEDGER_ENTRY_STATE */
                ser_entry(e, &s->st, b) < 0)
                goto oom;
            if (buf_u32(b, 1) < 0 || /* LEDGER_ENTRY_UPDATED */
                ser_entry(e, &e->st, b) < 0)
                goto oom;
            n += 2;
        } else if (!s->st.exists && e->st.exists) {
            if (buf_u32(b, 0) < 0 || /* LEDGER_ENTRY_CREATED */
                ser_entry(e, &e->st, b) < 0)
                goto oom;
            n += 1;
        } else { /* s exists, e doesn't: deletion */
            if (buf_u32(b, 3) < 0 || ser_entry(e, &s->st, b) < 0)
                goto oom;
            if (buf_u32(b, 2) < 0 || /* LEDGER_ENTRY_REMOVED: the key */
                buf_put(b, e->keyb, e->keylen) < 0)
                goto oom;
            n += 2;
        }
    }
    wr_u32_at((uint8_t *)b->data, n);
    return 0;
oom:
    env->oom = 1;
    ctx_abort(env->c);
    return -1;
}

static int empty_changes_buf(Buf *b)
{
    return buf_u32(b, 0);
}

/* ------------------------------------------------------------ tx parsing */

typedef struct {
    int has_src;
    uint8_t src[32];
    int optype;
    int dynamic; /* touches the order book: close stays serial+GIL */
    /* create-account / payment / account-merge destination */
    uint8_t dest[32];
    int64_t amount;
    int asset_native;
    uint8_t asset[MAX_ASSET]; /* raw Asset XDR (payment / change-trust) */
    int assetlen;
    const uint8_t *issuer; /* into asset[] (credit assets) */
    /* SET_OPTIONS (every field optional on the wire) */
    int so_has_infl, so_has_clear, so_has_set;
    int so_has_mw, so_has_lt, so_has_mt, so_has_ht;
    int so_has_home, so_has_signer;
    uint8_t so_infl[32];
    uint32_t so_clear, so_set, so_mw, so_lt, so_mt, so_ht;
    int so_home_len;
    uint8_t so_home[32];
    uint8_t so_signer_key[32];
    uint32_t so_signer_w;
    /* CHANGE_TRUST */
    int64_t ct_limit;
    /* ALLOW_TRUST */
    uint8_t at_trustor[32];
    uint32_t at_auth;
    uint8_t at_asset[MAX_ASSET]; /* derived credit asset (issuer = src) */
    int at_assetlen;
    /* MANAGE_DATA */
    int md_name_len, md_has_val, md_val_len;
    uint8_t md_name[64], md_val[64];
    /* BUMP_SEQUENCE */
    int64_t bs_to;
    /* offers (sell-side normal form; buy offers are converted) */
    uint8_t o_sell[MAX_ASSET], o_buy[MAX_ASSET];
    int o_sell_len, o_buy_len;
    __int128 o_amount;     /* sell amount (buyAmount*n/d can exceed i64) */
    int64_t o_buy_amount;  /* ManageBuyOffer wire buyAmount */
    int32_t o_pn, o_pd;    /* effective sell-side price */
    int64_t o_offer_id;
    int o_passive, o_is_buy;
    /* path payments */
    uint8_t pp_send[MAX_ASSET], pp_dest[MAX_ASSET];
    int pp_send_len, pp_dest_len;
    int64_t pp_amount; /* destAmount (recv) / sendAmount (send) */
    int64_t pp_limit;  /* sendMax (recv) / destMin (send) */
    int pp_npath;
    uint8_t pp_path[MAX_PATH][MAX_ASSET];
    int pp_path_len[MAX_PATH];
} Op;

typedef struct {
    uint8_t hint[4];
    const uint8_t *sig;
    int siglen;
    PyObject *sig_obj; /* lazily-built bytes for the verify callback */
    int used;
} Sig;

/* one (signer-key, signature-index) candidate with its batch-verified
   result */
typedef struct {
    uint8_t key[32];
    int sigidx;
    int ok;
} VPair;

typedef struct {
    VPair *pairs;
    int n, cap;
} VSet;

/* per-tx deferred outputs: built (malloc-only) during apply, turned
   into Python objects at emission with the GIL */
typedef struct {
    int code;      /* optype when code==opINNER */
    int optype;
    int inner_code;
    int has_payload;
    Buf payload;   /* serialized success payload (merge/offers/paths) */
} OpRes;

typedef struct Tx {
    int is_fee_bump;
    uint8_t src[32];
    int64_t fee_bid; /* u32 for v1 txs, i64 for fee-bump outers */
    int64_t seqNum;
    int has_tb;
    uint64_t minTime, maxTime;
    int nops;
    Op *ops;
    int nsigs;
    Sig sigs[MAX_SIGS];
    const uint8_t *hash; /* contents hash (borrowed from hashes list) */
    PyObject *hash_obj;  /* borrowed bytes object for that hash */
    int64_t feeCharged;
    VSet vs;             /* pre-verified candidate pairs */
    struct Tx *inner;    /* fee bumps: the wrapped v1 tx */
    int dynamic;         /* any op needs the order book */
    /* deferred outputs */
    int out_have;        /* outputs below are valid */
    int out_code;        /* tx-level TransactionResultCode */
    int out_ok;          /* ops committed (SUCCESS) */
    int out_empty_txch;  /* INTERNAL_ERROR arm: empty tx changes */
    int out_meta_ops;    /* op slots in the meta (0 on pre-ops failures) */
    int out_res_ops;     /* op results in the result (SUCCESS/FAILED) */
    OpRes *opres;
    int opres_in_arena; /* opres/opch live in the applying env's arena */
    Buf txch;
    Buf *opch;           /* per-op changes (valid when out_ok) */
    Buf out_rb, out_mb;  /* result / meta XDR, pre-emitted on the
                            applying thread (pure C; the GIL-held
                            emission pass only wraps PyBytes) */
} Tx;

static void tx_free(Tx *t)
{
    int i;
    if (!t)
        return;
    free(t->ops);
    for (i = 0; i < t->nsigs; i++)
        Py_XDECREF(t->sigs[i].sig_obj);
    free(t->vs.pairs);
    if (t->opres) {
        for (i = 0; i < t->nops; i++)
            buf_free(&t->opres[i].payload);
        if (!t->opres_in_arena)
            free(t->opres);
    }
    buf_free(&t->txch);
    buf_free(&t->out_rb);
    buf_free(&t->out_mb);
    if (t->opch) {
        for (i = 0; i < t->nops; i++)
            buf_free(&t->opch[i]);
        if (!t->opres_in_arena)
            free(t->opch);
    }
    if (t->inner) {
        tx_free(t->inner);
        free(t->inner);
    }
}

/* MuxedAccount: ed25519 or med25519 (sub-id stripped — the repo's
   frames resolve .account_id everywhere state or results are built) */
static int rd_muxed(Rd *r, uint8_t *out32)
{
    uint32_t kt;
    if (rd_u32(r, &kt) < 0)
        return -1;
    if (kt == 0x100) { /* KEY_TYPE_MUXED_ED25519: u64 id + key */
        if (!rd_take(r, 8))
            return -1;
    } else if (kt != 0)
        return -1;
    const uint8_t *p = rd_take(r, 32);
    if (!p)
        return -1;
    memcpy(out32, p, 32);
    return 0;
}

static int rd_asset_op(Rd *r, Op *op)
{
    Py_ssize_t at = r->pos;
    uint32_t atype;
    if (rd_u32(r, &atype) < 0)
        return -1;
    if (atype == 0) {
        op->asset_native = 1;
        op->assetlen = 4;
    } else if (atype == 1 || atype == 2) {
        uint32_t kt;
        if (!rd_take(r, atype == 1 ? 4 : 12))
            return -1;
        if (rd_u32(r, &kt) < 0 || kt != 0)
            return -1;
        if (!rd_take(r, 32))
            return -1;
        op->asset_native = 0;
        op->assetlen = (int)(r->pos - at);
    } else
        return -1;
    memcpy(op->asset, r->p + at, r->pos - at);
    op->issuer = op->asset + op->assetlen - 32;
    return 0;
}

static int asset_is_native(const uint8_t *a, int n)
{
    return n == 4 && a[0] == 0 && a[1] == 0 && a[2] == 0 && a[3] == 0;
}

static const uint8_t *asset_issuer(const uint8_t *a, int n)
{
    return a + n - 32; /* credit assets only */
}

static int asset_eq(const uint8_t *a, int an, const uint8_t *b, int bn)
{
    return an == bn && memcmp(a, b, an) == 0;
}

/* parse one Operation body; returns -1 on malformed/bailed input */
static int parse_op_body(Ctx *c, Rd *r, Op *op)
{
    uint32_t u, kt;
    switch (op->optype) {
    case OP_CREATE_ACCOUNT: {
        const uint8_t *p;
        if (rd_u32(r, &kt) < 0 || kt != 0 || !(p = rd_take(r, 32)))
            return -1;
        memcpy(op->dest, p, 32);
        if (rd_i64(r, &op->amount) < 0)
            return -1;
        return 0;
    }
    case OP_PAYMENT:
        if (rd_muxed(r, op->dest) < 0 || rd_asset_op(r, op) < 0 ||
            rd_i64(r, &op->amount) < 0)
            return -1;
        return 0;
    case OP_PATH_PAYMENT_RECV:
    case OP_PATH_PAYMENT_SEND: {
        int recv = (op->optype == OP_PATH_PAYMENT_RECV);
        op->pp_send_len = rd_asset_raw(r, op->pp_send);
        if (op->pp_send_len < 0)
            return -1;
        /* recv: sendMax then dest/destAsset/destAmount;
           send: sendAmount then dest/destAsset/destMin */
        int64_t first;
        if (rd_i64(r, &first) < 0)
            return -1;
        if (rd_muxed(r, op->dest) < 0)
            return -1;
        op->pp_dest_len = rd_asset_raw(r, op->pp_dest);
        if (op->pp_dest_len < 0)
            return -1;
        int64_t second;
        if (rd_i64(r, &second) < 0)
            return -1;
        if (recv) {
            op->pp_limit = first;   /* sendMax */
            op->pp_amount = second; /* destAmount */
        } else {
            op->pp_amount = first;  /* sendAmount */
            op->pp_limit = second;  /* destMin */
        }
        if (rd_u32(r, &u) < 0 || u > MAX_PATH)
            return -1;
        op->pp_npath = (int)u;
        for (int k = 0; k < op->pp_npath; k++) {
            op->pp_path_len[k] = rd_asset_raw(r, op->pp_path[k]);
            if (op->pp_path_len[k] < 0)
                return -1;
        }
        op->dynamic = 1;
        return 0;
    }
    case OP_MANAGE_SELL_OFFER:
    case OP_CREATE_PASSIVE_OFFER:
    case OP_MANAGE_BUY_OFFER: {
        op->o_sell_len = rd_asset_raw(r, op->o_sell);
        if (op->o_sell_len < 0)
            return -1;
        op->o_buy_len = rd_asset_raw(r, op->o_buy);
        if (op->o_buy_len < 0)
            return -1;
        int64_t amt;
        uint32_t pn, pd;
        if (rd_i64(r, &amt) < 0 || rd_u32(r, &pn) < 0 ||
            rd_u32(r, &pd) < 0)
            return -1;
        if (op->optype == OP_CREATE_PASSIVE_OFFER) {
            op->o_offer_id = 0;
            op->o_passive = 1;
        } else if (rd_i64(r, &op->o_offer_id) < 0)
            return -1;
        if ((int32_t)pn <= 0 || (int32_t)pd <= 0) {
            /* zero/negative price at apply is a Python exception
               (ZeroDivisionError in exchange) — keep it the oracle */
            ctx_bail(c, "op-shape");
            return -1;
        }
        if (op->optype == OP_MANAGE_BUY_OFFER) {
            op->o_is_buy = 1;
            op->o_buy_amount = amt;
            /* equivalent sell offer: amount = buyAmount*n/d (floor,
               may exceed int64 — Python ints are unbounded), price
               inverted (ManageBuyOfferOpFrame._params) */
            op->o_amount = amt > 0
                               ? ((__int128)amt * (int32_t)pn) /
                                     (int32_t)pd
                               : 0;
            op->o_pn = (int32_t)pd;
            op->o_pd = (int32_t)pn;
        } else {
            op->o_amount = amt;
            op->o_pn = (int32_t)pn;
            op->o_pd = (int32_t)pd;
        }
        op->dynamic = 1;
        return 0;
    }
    case OP_SET_OPTIONS: {
        if (rd_u32(r, &u) < 0 || u > 1)
            return -1;
        op->so_has_infl = (int)u;
        if (u) {
            const uint8_t *p;
            if (rd_u32(r, &kt) < 0 || kt != 0 || !(p = rd_take(r, 32)))
                return -1;
            memcpy(op->so_infl, p, 32);
        }
        struct {
            int *has;
            uint32_t *val;
        } ou32[6] = {
            {&op->so_has_clear, &op->so_clear},
            {&op->so_has_set, &op->so_set},
            {&op->so_has_mw, &op->so_mw},
            {&op->so_has_lt, &op->so_lt},
            {&op->so_has_mt, &op->so_mt},
            {&op->so_has_ht, &op->so_ht},
        };
        for (int k = 0; k < 6; k++) {
            if (rd_u32(r, &u) < 0 || u > 1)
                return -1;
            *ou32[k].has = (int)u;
            if (u && rd_u32(r, ou32[k].val) < 0)
                return -1;
        }
        /* thresholds > 255 make the Python oracle raise mid-close
           (bytearray assignment); keep it the oracle */
        if ((op->so_has_mw && op->so_mw > 255) ||
            (op->so_has_lt && op->so_lt > 255) ||
            (op->so_has_mt && op->so_mt > 255) ||
            (op->so_has_ht && op->so_ht > 255)) {
            ctx_bail(c, "threshold-range");
            return -1;
        }
        if (rd_u32(r, &u) < 0 || u > 1)
            return -1;
        op->so_has_home = (int)u;
        if (u) {
            uint32_t sl;
            if (rd_u32(r, &sl) < 0 || sl > 32)
                return -1;
            Py_ssize_t at = r->pos;
            if (rd_skip_padded(r, sl) < 0)
                return -1;
            op->so_home_len = (int)sl;
            memcpy(op->so_home, r->p + at, sl);
        }
        if (rd_u32(r, &u) < 0 || u > 1)
            return -1;
        op->so_has_signer = (int)u;
        if (u) {
            const uint8_t *p;
            if (rd_u32(r, &kt) < 0)
                return -1;
            if (kt != 0) { /* pre-auth-tx / hash-x: Python path */
                ctx_bail(c, "signer-key-type");
                return -1;
            }
            if (!(p = rd_take(r, 32)))
                return -1;
            memcpy(op->so_signer_key, p, 32);
            if (rd_u32(r, &op->so_signer_w) < 0)
                return -1;
        }
        return 0;
    }
    case OP_CHANGE_TRUST:
        if (rd_asset_op(r, op) < 0 || rd_i64(r, &op->ct_limit) < 0)
            return -1;
        if (op->asset_native) {
            /* Python do_apply would build LedgerKey.account(None) and
               raise — keep it the oracle */
            ctx_bail(c, "op-shape");
            return -1;
        }
        return 0;
    case OP_ALLOW_TRUST: {
        const uint8_t *p;
        if (rd_u32(r, &kt) < 0 || kt != 0 || !(p = rd_take(r, 32)))
            return -1;
        memcpy(op->at_trustor, p, 32);
        uint32_t atype;
        const uint8_t *code;
        int codelen;
        if (rd_u32(r, &atype) < 0)
            return -1;
        if (atype == 1)
            codelen = 4;
        else if (atype == 2)
            codelen = 12;
        else
            return -1;
        if (!(code = rd_take(r, codelen)))
            return -1;
        if (rd_u32(r, &op->at_auth) < 0)
            return -1;
        /* Python derives Asset.credit(code.rstrip(b"\0").decode()) with
           the op SOURCE as issuer — a 12-byte arm with a short code
           becomes ALPHANUM4, exactly like the frame does. The issuer
           bytes are filled at apply (op source resolved there). */
        int trimmed = codelen;
        while (trimmed > 0 && code[trimmed - 1] == 0)
            trimmed--;
        if (trimmed == 0) {
            ctx_bail(c, "op-shape"); /* Asset.credit("") raises */
            return -1;
        }
        for (int k = 0; k < trimmed; k++)
            if (code[k] >= 0x80) {
                ctx_bail(c, "op-shape"); /* non-ascii code raises */
                return -1;
            }
        int outcode = trimmed <= 4 ? 4 : 12;
        wr_u32_at(op->at_asset, outcode == 4 ? 1 : 2);
        memset(op->at_asset + 4, 0, outcode);
        memcpy(op->at_asset + 4, code, trimmed);
        wr_u32_at(op->at_asset + 4 + outcode, 0);
        /* issuer placeholder zeroed; patched per-apply with op source */
        memset(op->at_asset + 8 + outcode, 0, 32);
        op->at_assetlen = 8 + outcode + 32;
        /* a full revoke pulls the trustor's offers (order-book walk) */
        if (op->at_auth == 0)
            op->dynamic = 1;
        return 0;
    }
    case OP_ACCOUNT_MERGE:
        if (rd_muxed(r, op->dest) < 0)
            return -1;
        return 0;
    case OP_INFLATION:
        return 0; /* void body */
    case OP_MANAGE_DATA: {
        uint32_t nl;
        if (rd_u32(r, &nl) < 0 || nl > 64)
            return -1;
        Py_ssize_t at = r->pos;
        if (rd_skip_padded(r, nl) < 0)
            return -1;
        op->md_name_len = (int)nl;
        memcpy(op->md_name, r->p + at, nl);
        if (rd_u32(r, &u) < 0 || u > 1)
            return -1;
        op->md_has_val = (int)u;
        if (u) {
            uint32_t vl;
            if (rd_u32(r, &vl) < 0 || vl > 64)
                return -1;
            at = r->pos;
            if (rd_skip_padded(r, vl) < 0)
                return -1;
            op->md_val_len = (int)vl;
            memcpy(op->md_val, r->p + at, vl);
        }
        return 0;
    }
    case OP_BUMP_SEQUENCE:
        if (rd_i64(r, &op->bs_to) < 0)
            return -1;
        return 0;
    default:
        /* unknown wire op type: Python path names it */
        snprintf(c->bailbuf, sizeof(c->bailbuf), "op-%d", op->optype);
        ctx_bail(c, c->bailbuf);
        return -1;
    }
}

/* parse a TransactionV1Envelope BODY (after the outer disc) into t */
static int parse_v1_body(Ctx *c, Rd *r, Tx *t)
{
    uint32_t u, n;
    int i;
    if (rd_muxed(r, t->src) < 0)
        return -1;
    uint32_t fee32;
    if (rd_u32(r, &fee32) < 0 || rd_i64(r, &t->seqNum) < 0)
        return -1;
    t->fee_bid = (int64_t)fee32;
    if (rd_u32(r, &u) < 0 || u > 1)
        return -1;
    t->has_tb = (int)u;
    if (t->has_tb &&
        (rd_u64(r, &t->minTime) < 0 || rd_u64(r, &t->maxTime) < 0))
        return -1;
    if (rd_u32(r, &u) < 0) /* memo */
        return -1;
    switch (u) {
    case 0:
        break;
    case 1: {
        uint32_t sl;
        if (rd_u32(r, &sl) < 0 || sl > 28 || rd_skip_padded(r, sl) < 0)
            return -1;
        break;
    }
    case 2:
        if (!rd_take(r, 8))
            return -1;
        break;
    case 3:
    case 4:
        if (!rd_take(r, 32))
            return -1;
        break;
    default:
        return -1;
    }
    if (rd_u32(r, &n) < 0 || n > 100)
        return -1;
    t->nops = (int)n;
    t->ops = calloc(n ? n : 1, sizeof(Op));
    if (!t->ops) {
        c->pyerr = 1;
        PyErr_NoMemory();
        return -1;
    }
    for (i = 0; i < t->nops; i++) {
        Op *op = &t->ops[i];
        if (rd_u32(r, &u) < 0 || u > 1)
            return -1;
        op->has_src = (int)u;
        if (op->has_src && rd_muxed(r, op->src) < 0)
            return -1;
        if (rd_u32(r, &u) < 0)
            return -1;
        op->optype = (int)u;
        if (parse_op_body(c, r, op) < 0)
            return -1;
        /* version-retired ops are opNOT_SUPPORTED at apply: no book
           access happens, so they don't force the serial path */
        if ((op->optype == OP_MANAGE_BUY_OFFER && c->ledgerVersion < 11) ||
            (op->optype == OP_PATH_PAYMENT_SEND && c->ledgerVersion < 12))
            op->dynamic = 0;
        if (op->dynamic)
            t->dynamic = 1;
    }
    if (rd_u32(r, &u) < 0 || u != 0) /* tx ext */
        return -1;
    if (rd_u32(r, &n) < 0)
        return -1;
    if (n > MAX_SIGS) {
        ctx_bail(c, "multisig-shape");
        return -1;
    }
    t->nsigs = (int)n;
    for (i = 0; i < t->nsigs; i++) {
        const uint8_t *h = rd_take(r, 4);
        if (!h)
            return -1;
        memcpy(t->sigs[i].hint, h, 4);
        uint32_t sl;
        if (rd_u32(r, &sl) < 0 || sl > 64)
            return -1;
        Py_ssize_t pad = (4 - (sl & 3)) & 3;
        const uint8_t *sp = rd_take(r, sl + pad);
        if (!sp)
            return -1;
        t->sigs[i].sig = sp;
        t->sigs[i].siglen = (int)sl;
    }
    return 0;
}

/* whole TransactionEnvelope (v1 or fee bump). `hash` is 32 bytes for
   v1, 64 (outer||inner) for fee bumps. */
static int parse_envelope(Ctx *c, const uint8_t *blob, Py_ssize_t len,
                          const uint8_t *hash, Py_ssize_t hashlen,
                          PyObject *hash_obj, Tx *t)
{
    Rd r = {blob, len, 0};
    uint32_t u, n;
    int i;
    if (rd_u32(&r, &u) < 0)
        return -1;
    if (u == 2) { /* ENVELOPE_TYPE_TX */
        if (hashlen != 32) {
            ctx_bail(c, "input-shape");
            return -1;
        }
        t->hash = hash;
        t->hash_obj = hash_obj;
        if (parse_v1_body(c, &r, t) < 0)
            return -1;
        if (r.pos != r.len)
            return -1;
        return 0;
    }
    if (u != 5) { /* not ENVELOPE_TYPE_TX_FEE_BUMP either */
        ctx_bail(c, "envelope-type");
        return -1;
    }
    if (hashlen != 64) {
        ctx_bail(c, "input-shape");
        return -1;
    }
    t->is_fee_bump = 1;
    t->hash = hash; /* outer contents hash */
    t->hash_obj = hash_obj;
    if (rd_muxed(&r, t->src) < 0) /* feeSource */
        return -1;
    if (rd_i64(&r, &t->fee_bid) < 0)
        return -1;
    if (rd_u32(&r, &u) < 0 || u != 2) /* innerTx disc: ENVELOPE_TYPE_TX */
        return -1;
    t->inner = calloc(1, sizeof(Tx));
    if (!t->inner) {
        c->pyerr = 1;
        PyErr_NoMemory();
        return -1;
    }
    t->inner->hash = hash + 32; /* inner contents hash */
    t->inner->hash_obj = hash_obj;
    if (parse_v1_body(c, &r, t->inner) < 0)
        return -1;
    t->dynamic = t->inner->dynamic;
    if (rd_u32(&r, &u) < 0 || u != 0) /* FeeBumpTransaction ext */
        return -1;
    if (rd_u32(&r, &n) < 0) /* outer signatures */
        return -1;
    if (n > MAX_SIGS) {
        ctx_bail(c, "multisig-shape");
        return -1;
    }
    t->nsigs = (int)n;
    for (i = 0; i < t->nsigs; i++) {
        const uint8_t *h = rd_take(&r, 4);
        if (!h)
            return -1;
        memcpy(t->sigs[i].hint, h, 4);
        uint32_t sl;
        if (rd_u32(&r, &sl) < 0 || sl > 64)
            return -1;
        Py_ssize_t pad = (4 - (sl & 3)) & 3;
        const uint8_t *sp = rd_take(&r, sl + pad);
        if (!sp)
            return -1;
        t->sigs[i].sig = sp;
        t->sigs[i].siglen = (int)sl;
    }
    if (r.pos != r.len)
        return -1;
    return 0;
}

/* ---------------------------------------------------- signature checking */

static int vset_add(Ctx *c, VSet *vs, const uint8_t *key, int sigidx)
{
    int i;
    for (i = 0; i < vs->n; i++)
        if (vs->pairs[i].sigidx == sigidx &&
            memcmp(vs->pairs[i].key, key, 32) == 0)
            return 0;
    if (vs->n == vs->cap) {
        int cap = vs->cap ? vs->cap * 2 : 32;
        VPair *p = realloc(vs->pairs, cap * sizeof(VPair));
        if (!p) {
            c->pyerr = 1;
            PyErr_NoMemory();
            return -1;
        }
        vs->pairs = p;
        vs->cap = cap;
    }
    memcpy(vs->pairs[vs->n].key, key, 32);
    vs->pairs[vs->n].sigidx = sigidx;
    vs->pairs[vs->n].ok = 0;
    vs->n++;
    return 0;
}

static int vset_ok(const VSet *vs, const uint8_t *key, int sigidx)
{
    int i;
    for (i = 0; i < vs->n; i++)
        if (vs->pairs[i].sigidx == sigidx &&
            memcmp(vs->pairs[i].key, key, 32) == 0)
            return vs->pairs[i].ok;
    return 0;
}

/* record one statically-knowable signer addition (set-options arms) */
static int sadd_push(Ctx *c, const uint8_t *acct, const uint8_t *key)
{
    if (c->nsadds == c->capsadds) {
        int cap = c->capsadds ? c->capsadds * 2 : 16;
        StaticSigner *p = realloc(c->sadds, cap * sizeof(StaticSigner));
        if (!p) {
            c->pyerr = 1;
            PyErr_NoMemory();
            return -1;
        }
        c->sadds = p;
        c->capsadds = cap;
    }
    memcpy(c->sadds[c->nsadds].acct, acct, 32);
    memcpy(c->sadds[c->nsadds].key, key, 32);
    c->nsadds++;
    return 0;
}

/* candidate (key, sig) pairs for one account against one sig list:
   live signer set at PREPASS time ∪ the master key (always — weight
   edits are dynamic but the key itself is fixed) ∪ statically-added
   signer keys. Membership is re-checked live at apply; this only
   decides which pure (key, sig, msg) verifies happen up front. */
static int vset_collect(AEnv *env, VSet *vs, Sig *sigs, int nsigs,
                        const uint8_t *accid)
{
    Ctx *c = env->c;
    Entry *a = get_account(env, accid);
    int i, j;
    if (!a)
        return -1;
    for (i = 0; i < nsigs; i++) {
        /* master key / raw key of a missing account */
        if (memcmp(sigs[i].hint, accid + 28, 4) == 0)
            if (vset_add(c, vs, accid, i) < 0)
                return -1;
        if (a->st.exists)
            for (j = 0; j < a->st.nsigners; j++)
                if (memcmp(sigs[i].hint, a->st.signer_keys[j] + 28,
                           4) == 0)
                    if (vset_add(c, vs, a->st.signer_keys[j], i) < 0)
                        return -1;
        for (j = 0; j < c->nsadds; j++)
            if (memcmp(c->sadds[j].acct, accid, 32) == 0 &&
                memcmp(sigs[i].hint, c->sadds[j].key + 28, 4) == 0)
                if (vset_add(c, vs, c->sadds[j].key, i) < 0)
                    return -1;
    }
    return 0;
}

/* append one vset's (key, sig, msg) tuples to the global verify list */
static int vset_append_batch(Ctx *c, PyObject *lst, VSet *vs, Sig *sigs,
                             const uint8_t *hash)
{
    int i;
    PyObject *msg = NULL;
    for (i = 0; i < vs->n; i++) {
        int si = vs->pairs[i].sigidx;
        if (!sigs[si].sig_obj) {
            sigs[si].sig_obj = PyBytes_FromStringAndSize(
                (const char *)sigs[si].sig, sigs[si].siglen);
            if (!sigs[si].sig_obj)
                goto fail;
        }
        if (!msg) {
            msg = PyBytes_FromStringAndSize((const char *)hash, 32);
            if (!msg)
                goto fail;
        }
        PyObject *key = PyBytes_FromStringAndSize(
            (const char *)vs->pairs[i].key, 32);
        if (!key)
            goto fail;
        PyObject *tup = PyTuple_Pack(3, key, sigs[si].sig_obj, msg);
        Py_DECREF(key);
        if (!tup)
            goto fail;
        int rc = PyList_Append(lst, tup);
        Py_DECREF(tup);
        if (rc < 0)
            goto fail;
    }
    Py_XDECREF(msg);
    return 0;
fail:
    Py_XDECREF(msg);
    c->pyerr = 1;
    return -1;
}

/* read one vset's results back from the global verify result sequence */
static int vset_read_results(Ctx *c, PyObject *seq, Py_ssize_t *pos,
                             VSet *vs)
{
    int i;
    for (i = 0; i < vs->n; i++) {
        if (*pos >= PySequence_Fast_GET_SIZE(seq)) {
            ctx_bail(c, "verify-shape");
            return -1;
        }
        vs->pairs[i].ok =
            PyObject_IsTrue(PySequence_Fast_GET_ITEM(seq, *pos)) == 1;
        (*pos)++;
    }
    return 0;
}

/* SignatureChecker.check_signature over ed25519 signers, against LIVE
   account state. Mirrors the Python loop exactly: signatures in order,
   each consuming the first remaining hint-matched verified signer;
   weights capped at 255; zero thresholds still need one valid signer. */
static int check_sig(Sig *sigs, int nsigs, const VSet *vs, Entry *a,
                     const uint8_t *accid, int level)
{
    const uint8_t *keys[MAX_SIGNERS + 1];
    uint32_t weights[MAX_SIGNERS + 1];
    int n = 0, i, j;
    if (a && a->st.exists) {
        for (i = 0; i < a->st.nsigners; i++) {
            keys[n] = a->st.signer_keys[i];
            weights[n++] = a->st.signer_weights[i];
        }
        if (a->st.thresholds[0] > 0) {
            keys[n] = a->acc_key;
            weights[n++] = a->st.thresholds[0];
        }
    } else {
        keys[n] = accid;
        weights[n++] = 1;
    }
    uint32_t needed =
        (a && a->st.exists) ? a->st.thresholds[1 + level] : 0;
    uint32_t total = 0;
    for (i = 0; i < nsigs; i++) {
        for (j = 0; j < n; j++) {
            if (memcmp(sigs[i].hint, keys[j] + 28, 4) != 0)
                continue;
            if (!vset_ok(vs, keys[j], i))
                continue;
            sigs[i].used = 1;
            total += weights[j] > 255 ? 255 : weights[j];
            if (total >= needed)
                return 1;
            memmove(&keys[j], &keys[j + 1], (n - j - 1) * sizeof(keys[0]));
            memmove(&weights[j], &weights[j + 1],
                    (n - j - 1) * sizeof(weights[0]));
            n--;
            break;
        }
    }
    return 0;
}

/* ------------------------------------------------------- balance helpers */

/* transactions/account_helpers.py add_balance, protocol >= 10 (the
   engine requires >= 10). delta is 128-bit: Python's unbounded ints
   make -INT64_MIN well-defined (range checks reject it). */
static int add_balance(Ctx *c, Entry *e, __int128 delta)
{
    MutState *st = &e->st;
    __int128 newb = (__int128)st->balance + delta;
    if (newb < 0 || newb > INT64_MAXV)
        return 0;
    if (delta < 0) {
        __int128 minb = (__int128)(2 + st->numSub) * c->baseReserve;
        if (newb - minb < st->liab_selling)
            return 0;
    }
    if (newb > (__int128)INT64_MAXV - st->liab_buying)
        return 0;
    st->balance = (int64_t)newb;
    return 1;
}

/* add_trust_balance, protocol >= 10 */
static int add_trust_balance(Entry *e, __int128 delta)
{
    MutState *st = &e->st;
    if (delta == 0)
        return 1;
    if (!(st->flags & TL_AUTH_LEVELS_MASK))
        return 0;
    __int128 newb = (__int128)st->balance + delta;
    if (newb < 0 || newb > st->tl_limit)
        return 0;
    if (newb < st->liab_selling)
        return 0;
    if (newb > (__int128)st->tl_limit - st->liab_buying)
        return 0;
    st->balance = (int64_t)newb;
    return 1;
}

/* add_buying_liabilities (TransactionUtils.cpp:285 role) */
static int add_buying_liab(Entry *e, __int128 delta)
{
    MutState *st = &e->st;
    if (delta == 0)
        return 1;
    __int128 max_liab;
    if (e->type == LET_ACCOUNT)
        max_liab = (__int128)INT64_MAXV - st->balance;
    else {
        if (!(st->flags & TL_AUTH_LEVELS_MASK))
            return 0;
        max_liab = (__int128)st->tl_limit - st->balance;
    }
    __int128 newv = (__int128)st->liab_buying + delta;
    if (newv < 0 || newv > max_liab)
        return 0;
    st->liab_buying = (int64_t)newv;
    st->ext_v = 1; /* _prepare_liabilities promotes the extension */
    return 1;
}

/* add_selling_liabilities */
static int add_selling_liab(Ctx *c, Entry *e, __int128 delta)
{
    MutState *st = &e->st;
    if (delta == 0)
        return 1;
    __int128 max_liab;
    if (e->type == LET_ACCOUNT) {
        max_liab = (__int128)st->balance -
                   (__int128)(2 + st->numSub) * c->baseReserve;
        if (max_liab < 0)
            return 0;
    } else {
        if (!(st->flags & TL_AUTH_LEVELS_MASK))
            return 0;
        max_liab = st->balance;
    }
    __int128 newv = (__int128)st->liab_selling + delta;
    if (newv < 0 || newv > max_liab)
        return 0;
    st->liab_selling = (int64_t)newv;
    st->ext_v = 1;
    return 1;
}

/* account_helpers.py change_subentries: reserve check (incl. selling
   liabilities at v10+) on add; the remove arm cannot fail there */
static int change_subentries(Ctx *c, Entry *e, int delta)
{
    MutState *st = &e->st;
    int64_t nc = (int64_t)st->numSub + delta;
    if (nc < 0 || nc > MAX_SUBENTRIES)
        return 0;
    __int128 effmin = (__int128)(2 + nc) * c->baseReserve;
    effmin += st->liab_selling;
    if (delta > 0 && (__int128)st->balance < effmin)
        return 0;
    st->numSub = (uint32_t)nc;
    return 1;
}

/* max_amount_receive: headroom below the ceiling minus buying liab */
static __int128 max_amount_receive(Entry *e)
{
    const MutState *st = &e->st;
    if (e->type == LET_ACCOUNT)
        return (__int128)INT64_MAXV - st->balance - st->liab_buying;
    if (!(st->flags & TL_AUTH_LEVELS_MASK))
        return 0;
    __int128 out = (__int128)st->tl_limit - st->balance - st->liab_buying;
    return out;
}

/* ---------------------------------------------------------- order books */

/* fetch + index the root's offers for one (selling, buying) pair (the
   Python `book` callback); GIL required. Entries already in the overlay
   keep their live state — dedupe by key. */
static Book *get_book(AEnv *env, const uint8_t *sell, int sell_len,
                      const uint8_t *buy, int buy_len)
{
    Ctx *c = env->c;
    int i;
    for (i = 0; i < c->nbooks; i++)
        if (asset_eq(c->books[i].sell, c->books[i].sell_len, sell,
                     sell_len) &&
            asset_eq(c->books[i].buy, c->books[i].buy_len, buy, buy_len))
            return &c->books[i];
    if (c->nopy) {
        env_bail(env, "prefetch-miss");
        return NULL;
    }
    if (c->nbooks == c->capbooks) {
        int cap = c->capbooks ? c->capbooks * 2 : 8;
        Book *p = realloc(c->books, cap * sizeof(Book));
        if (!p) {
            env->oom = 1;
            return NULL;
        }
        c->books = p;
        c->capbooks = cap;
    }
    Book *bk = &c->books[c->nbooks];
    memset(bk, 0, sizeof(*bk));
    memcpy(bk->sell, sell, sell_len);
    bk->sell_len = sell_len;
    memcpy(bk->buy, buy, buy_len);
    bk->buy_len = buy_len;

    PyObject *sb = PyBytes_FromStringAndSize((const char *)sell, sell_len);
    PyObject *bb = PyBytes_FromStringAndSize((const char *)buy, buy_len);
    PyObject *res = NULL, *seq = NULL;
    if (!sb || !bb)
        goto pyfail;
    res = PyObject_CallFunctionObjArgs(c->book_cb, sb, bb, NULL);
    if (!res)
        goto pyfail;
    seq = PySequence_Fast(res, "book() must return a sequence");
    if (!seq)
        goto pyfail;
    for (Py_ssize_t k = 0; k < PySequence_Fast_GET_SIZE(seq); k++) {
        PyObject *blob = PySequence_Fast_GET_ITEM(seq, k);
        if (!PyBytes_Check(blob)) {
            ctx_bail(c, "lookup-type");
            env->bail = 1;
            goto out;
        }
        /* derive the offer key from the blob: lastModified(4) type(4)
           keytype(4) seller(32) offerID(8) */
        const uint8_t *p = (const uint8_t *)PyBytes_AS_STRING(blob);
        Py_ssize_t bl = PyBytes_GET_SIZE(blob);
        if (bl < 52) {
            ctx_bail(c, "lookup-type");
            env->bail = 1;
            goto out;
        }
        uint8_t keyb[48];
        wr_u32_at(keyb, LET_OFFER);
        wr_u32_at(keyb + 4, 0);
        memcpy(keyb + 8, p + 12, 32);  /* seller */
        memcpy(keyb + 40, p + 44, 8);  /* offerID (big-endian already) */
        uint32_t h;
        Entry *e = find_entry(c, keyb, 48, &h);
        if (!e) {
            e = insert_entry(env, keyb, 48, h);
            if (!e)
                goto out;
            if (entry_adopt_blob(env, e, p, (int)bl) < 0)
                goto out;
        }
        if (elist_push(&bk->offers, e) < 0) {
            env->oom = 1;
            goto out;
        }
    }
    Py_DECREF(seq);
    Py_DECREF(res);
    Py_DECREF(sb);
    Py_DECREF(bb);
    c->nbooks++;
    return bk;
pyfail:
    c->pyerr = 1;
out:
    Py_XDECREF(seq);
    Py_XDECREF(res);
    Py_XDECREF(sb);
    Py_XDECREF(bb);
    free(bk->offers.v);
    return NULL;
}

/* exact fraction compare: a.price < b.price, tie-break by offerID
   (ledgertxn.price_less) */
static int price_less(const Entry *a, const Entry *b)
{
    int64_t lhs = (int64_t)a->st.o_pn * b->st.o_pd;
    int64_t rhs = (int64_t)b->st.o_pn * a->st.o_pd;
    if (lhs != rhs)
        return lhs < rhs;
    return a->offer_id < b->offer_id;
}

/* best (lowest-price) live offer selling `sell` for `buy`, merged view:
   the root book plus overlay-created offers for the pair */
static Entry *best_offer(AEnv *env, const uint8_t *sell, int sell_len,
                         const uint8_t *buy, int buy_len)
{
    Ctx *c = env->c;
    Book *bk = get_book(env, sell, sell_len, buy, buy_len);
    if (!bk)
        return NULL;
    Entry *best = NULL;
    int i;
    for (i = 0; i < bk->offers.n; i++) {
        Entry *e = bk->offers.v[i];
        if (!e->st.exists)
            continue;
        if (!best || price_less(e, best))
            best = e;
    }
    for (i = 0; i < c->created_offers.n; i++) {
        Entry *e = c->created_offers.v[i];
        if (!e->st.exists || e->base)
            continue; /* base offers are already in the book list */
        if (!asset_eq(e->o_sell, e->o_sell_len, sell, sell_len) ||
            !asset_eq(e->o_buy, e->o_buy_len, buy, buy_len))
            continue;
        if (!best || price_less(e, best))
            best = e;
    }
    return best;
}

/* the root's per-seller offer list (the `acct_offers` callback),
   cached per account */
static AcctBook *get_acct_book(AEnv *env, const uint8_t *acct)
{
    Ctx *c = env->c;
    int i;
    for (i = 0; i < c->nabooks; i++)
        if (memcmp(c->abooks[i].acct, acct, 32) == 0)
            return &c->abooks[i];
    if (c->nopy) {
        env_bail(env, "prefetch-miss");
        return NULL;
    }
    if (c->nabooks == c->capabooks) {
        int cap = c->capabooks ? c->capabooks * 2 : 4;
        AcctBook *p = realloc(c->abooks, cap * sizeof(AcctBook));
        if (!p) {
            env->oom = 1;
            return NULL;
        }
        c->abooks = p;
        c->capabooks = cap;
    }
    AcctBook *ab = &c->abooks[c->nabooks];
    memset(ab, 0, sizeof(*ab));
    memcpy(ab->acct, acct, 32);
    PyObject *ao = PyBytes_FromStringAndSize((const char *)acct, 32);
    PyObject *res = NULL, *seq = NULL;
    if (!ao)
        goto pyfail;
    res = PyObject_CallFunctionObjArgs(c->acct_cb, ao, NULL);
    if (!res)
        goto pyfail;
    seq = PySequence_Fast(res, "acct_offers() must return a sequence");
    if (!seq)
        goto pyfail;
    for (Py_ssize_t k = 0; k < PySequence_Fast_GET_SIZE(seq); k++) {
        PyObject *blob = PySequence_Fast_GET_ITEM(seq, k);
        if (!PyBytes_Check(blob) || PyBytes_GET_SIZE(blob) < 52) {
            ctx_bail(c, "lookup-type");
            env->bail = 1;
            goto out;
        }
        const uint8_t *p = (const uint8_t *)PyBytes_AS_STRING(blob);
        Py_ssize_t bl = PyBytes_GET_SIZE(blob);
        uint8_t keyb[48];
        wr_u32_at(keyb, LET_OFFER);
        wr_u32_at(keyb + 4, 0);
        memcpy(keyb + 8, p + 12, 32);
        memcpy(keyb + 40, p + 44, 8);
        uint32_t h;
        Entry *e = find_entry(c, keyb, 48, &h);
        if (!e) {
            e = insert_entry(env, keyb, 48, h);
            if (!e)
                goto out;
            if (entry_adopt_blob(env, e, p, (int)bl) < 0)
                goto out;
        }
        if (elist_push(&ab->offers, e) < 0) {
            env->oom = 1;
            goto out;
        }
    }
    Py_DECREF(seq);
    Py_DECREF(res);
    Py_DECREF(ao);
    c->nabooks++;
    return ab;
pyfail:
    c->pyerr = 1;
out:
    Py_XDECREF(seq);
    Py_XDECREF(res);
    Py_XDECREF(ao);
    free(ab->offers.v);
    return NULL;
}

/* -------------------------------------------------- offer exchange math */

typedef struct {
    __int128 wheat, sheep;
} Exch;

static __int128 i128min(__int128 a, __int128 b) { return a < b ? a : b; }

static __int128 ceil_div128(__int128 a, __int128 b)
{
    /* Python -(-a // b) with b > 0 */
    if (a >= 0)
        return (a + b - 1) / b;
    return -((-a) / b);
}

static __int128 floor_div128(__int128 a, __int128 b)
{
    /* Python floor division, b > 0 */
    if (a >= 0)
        return a / b;
    return -ceil_div128(-a, b);
}

/* offer_exchange.exchange: exact crossing amounts */
static Exch exchange(__int128 offer_amount, int32_t n, int32_t d,
                     __int128 max_wheat_receive, __int128 max_sheep_send)
{
    Exch out = {0, 0};
    __int128 wheat = i128min(offer_amount, max_wheat_receive);
    if (wheat <= 0 || max_sheep_send <= 0)
        return out;
    __int128 sheep = ceil_div128(wheat * n, d);
    if (sheep > max_sheep_send) {
        wheat = floor_div128(max_sheep_send * d, n);
        wheat = i128min(wheat, i128min(offer_amount, max_wheat_receive));
        sheep = ceil_div128(wheat * n, d);
    }
    if (wheat <= 0 || sheep <= 0 || sheep > max_sheep_send)
        return out;
    out.wheat = wheat;
    out.sheep = sheep;
    return out;
}

/* offer_exchange.adjust_offer */
static __int128 adjust_offer(int32_t n, int32_t d, __int128 max_sell,
                             __int128 max_receive)
{
    if (max_sell <= 0 || max_receive <= 0)
        return 0;
    __int128 wheat_value = i128min(max_sell * n, max_receive * d);
    __int128 wheat, sheep;
    if (n > d) {
        wheat = floor_div128(wheat_value, n);
        sheep = floor_div128(wheat * n, d);
    } else {
        sheep = floor_div128(wheat_value, d);
        wheat = ceil_div128(sheep * d, n);
    }
    if (wheat <= 0 || sheep <= 0)
        return 0;
    __int128 err = 100 * (__int128)n * wheat - 100 * (__int128)d * sheep;
    if (err < 0)
        err = -err;
    if (err > (__int128)n * wheat)
        return 0;
    return wheat;
}

/* offer_liabilities: (buying, selling) a resting offer encumbers */
static void offer_liabilities(int32_t n, int32_t d, __int128 amount,
                              __int128 *buying, __int128 *selling)
{
    Exch e = exchange(amount, n, d, INT64_MAXV, INT64_MAXV);
    *buying = e.sheep;
    *selling = e.wheat;
}

/* canSellAtMost: available balance net of reserve/limit and SELLING
   liabilities. Loads via the overlay without recording. */
static __int128 available_to_sell(AEnv *env, const uint8_t *acct,
                                  const uint8_t *asset, int assetlen,
                                  int *err)
{
    Ctx *c = env->c;
    if (asset_is_native(asset, assetlen)) {
        Entry *a = get_account(env, acct);
        if (!a) {
            *err = 1;
            return 0;
        }
        if (!a->st.exists)
            return 0;
        __int128 avail = (__int128)a->st.balance -
                         (__int128)(2 + a->st.numSub) * c->baseReserve -
                         a->st.liab_selling;
        return avail > 0 ? avail : 0;
    }
    if (memcmp(acct, asset_issuer(asset, assetlen), 32) == 0)
        return INT64_MAXV;
    Entry *tl = get_trustline(env, acct, asset, assetlen);
    if (!tl) {
        *err = 1;
        return 0;
    }
    if (!tl->st.exists || !(tl->st.flags & TL_AUTH_LEVELS_MASK))
        return 0;
    __int128 avail = (__int128)tl->st.balance - tl->st.liab_selling;
    return avail > 0 ? avail : 0;
}

/* canBuyAtMost: headroom net of BUYING liabilities */
static __int128 available_to_receive(AEnv *env, const uint8_t *acct,
                                     const uint8_t *asset, int assetlen,
                                     int *err)
{
    if (asset_is_native(asset, assetlen)) {
        Entry *a = get_account(env, acct);
        if (!a) {
            *err = 1;
            return 0;
        }
        if (!a->st.exists)
            return 0;
        __int128 out = (__int128)INT64_MAXV - a->st.balance -
                       a->st.liab_buying;
        return out > 0 ? out : 0;
    }
    if (memcmp(acct, asset_issuer(asset, assetlen), 32) == 0)
        return INT64_MAXV;
    Entry *tl = get_trustline(env, acct, asset, assetlen);
    if (!tl) {
        *err = 1;
        return 0;
    }
    if (!tl->st.exists || !(tl->st.flags & TL_AUTH_LEVELS_MASK))
        return 0;
    __int128 out = (__int128)tl->st.tl_limit - tl->st.balance -
                   tl->st.liab_buying;
    return out > 0 ? out : 0;
}

/* _credit: returns 0 on failure, -1 on engine error, 1 ok */
static int xfer_credit(AEnv *env, const uint8_t *acct, const uint8_t *asset,
                       int assetlen, __int128 amount, int lv)
{
    Ctx *c = env->c;
    if (amount == 0)
        return 1;
    if (asset_is_native(asset, assetlen)) {
        Entry *a = get_account(env, acct);
        if (!a)
            return -1;
        if (!a->st.exists)
            return 0;
        if (touch(env, a, lv) < 0)
            return -1;
        return add_balance(c, a, amount);
    }
    if (memcmp(acct, asset_issuer(asset, assetlen), 32) == 0)
        return 1; /* issuer receiving its own asset burns it */
    Entry *tl = get_trustline(env, acct, asset, assetlen);
    if (!tl)
        return -1;
    if (!tl->st.exists)
        return 0;
    if (touch(env, tl, lv) < 0)
        return -1;
    return add_trust_balance(tl, amount);
}

static int xfer_debit(AEnv *env, const uint8_t *acct, const uint8_t *asset,
                      int assetlen, __int128 amount, int lv)
{
    return xfer_credit(env, acct, asset, assetlen,
                       amount == 0 ? 0 : -amount, lv);
}

/* acquireOrReleaseLiabilities over one offer's owner (sign = ±1).
   `amount`/`pn`/`pd` describe the offer being (re)encumbered. */
static int apply_offer_liab(AEnv *env, Entry *offer, __int128 amount,
                            int sign, int lv, int *err)
{
    __int128 buying, selling;
    offer_liabilities(offer->st.o_pn, offer->st.o_pd, amount, &buying,
                      &selling);
    const uint8_t *seller = offer->acc_key;
    int ok = 1;
    if (asset_is_native(offer->o_buy, offer->o_buy_len)) {
        Entry *a = get_account(env, seller);
        if (!a) {
            *err = 1;
            return 0;
        }
        if (!a->st.exists)
            ok = 0;
        else {
            if (touch(env, a, lv) < 0) {
                *err = 1;
                return 0;
            }
            ok = add_buying_liab(a, sign * buying);
        }
    } else if (memcmp(seller, asset_issuer(offer->o_buy, offer->o_buy_len),
                      32) != 0) {
        Entry *tl = get_trustline(env, seller, offer->o_buy,
                                  offer->o_buy_len);
        if (!tl) {
            *err = 1;
            return 0;
        }
        if (!tl->st.exists)
            ok = 0;
        else {
            if (touch(env, tl, lv) < 0) {
                *err = 1;
                return 0;
            }
            ok = add_buying_liab(tl, sign * buying);
        }
    }
    if (!ok)
        return 0;
    if (asset_is_native(offer->o_sell, offer->o_sell_len)) {
        Entry *a = get_account(env, seller);
        if (!a) {
            *err = 1;
            return 0;
        }
        if (!a->st.exists)
            ok = 0;
        else {
            if (touch(env, a, lv) < 0) {
                *err = 1;
                return 0;
            }
            ok = add_selling_liab(env->c, a, sign * selling);
        }
    } else if (memcmp(seller,
                      asset_issuer(offer->o_sell, offer->o_sell_len),
                      32) != 0) {
        Entry *tl = get_trustline(env, seller, offer->o_sell,
                                  offer->o_sell_len);
        if (!tl) {
            *err = 1;
            return 0;
        }
        if (!tl->st.exists)
            ok = 0;
        else {
            if (touch(env, tl, lv) < 0) {
                *err = 1;
                return 0;
            }
            ok = add_selling_liab(env->c, tl, sign * selling);
        }
    }
    return ok;
}

/* _erase_offer: erase + give back the seller's subentry */
static int erase_offer(AEnv *env, Entry *offer, int lv)
{
    if (touch(env, offer, lv) < 0)
        return -1;
    offer->st.exists = 0;
    Entry *acc = get_account(env, offer->acc_key);
    if (!acc)
        return -1;
    if (acc->st.exists) {
        if (touch(env, acc, lv) < 0)
            return -1;
        change_subentries(env->c, acc, -1);
    }
    return 0;
}

/* ------------------------------------------------------- cross_offers */

#define CROSS_SUCCESS 0
#define CROSS_PARTIAL 1
#define CROSS_SELF 2
#define CROSS_BAD_PRICE 3
#define CROSS_ERR (-1)

/* one ClaimOfferAtom appended to `claims` (pre-serialized) */
static int claim_append(Buf *claims, int *nclaims, const uint8_t *seller,
                        int64_t offer_id, const uint8_t *sold_asset,
                        int sold_len, __int128 sold,
                        const uint8_t *bought_asset, int bought_len,
                        __int128 bought)
{
    if (buf_u32(claims, 0) < 0 || buf_put(claims, seller, 32) < 0 ||
        buf_i64(claims, offer_id) < 0 ||
        buf_put(claims, sold_asset, sold_len) < 0 ||
        buf_i64(claims, (int64_t)sold) < 0 ||
        buf_put(claims, bought_asset, bought_len) < 0 ||
        buf_i64(claims, (int64_t)bought) < 0)
        return -1;
    (*nclaims)++;
    return 0;
}

/* offer_exchange.cross_offers: cross the (selling=buy_asset,
   buying=sell_asset) book until the taker has bought max_buy, spent
   max_sell, hit the price limit, or emptied the book. Offer owners'
   balances adjust in place; the taker's do NOT. Claims are serialized
   ClaimOfferAtom bytes appended to `claims` (count in *nclaims). */
static int cross_offers(AEnv *env, const uint8_t *taker,
                        const uint8_t *sell_asset, int sell_len,
                        const uint8_t *buy_asset, int buy_len,
                        __int128 max_buy, __int128 max_sell,
                        int has_limit, int32_t ln, int32_t ld,
                        int passive_taker, __int128 *bought_out,
                        __int128 *sold_out, Buf *claims, int *nclaims,
                        int lv)
{
    Ctx *c = env->c;
    __int128 bought = 0, sold = 0;
    int err = 0;
    while (bought < max_buy && sold < max_sell) {
        Entry *best = best_offer(env, buy_asset, buy_len, sell_asset,
                                 sell_len);
        if (env->bail || env->oom || c->pyerr)
            return CROSS_ERR;
        if (!best) {
            *bought_out = bought;
            *sold_out = sold;
            return CROSS_PARTIAL;
        }
        int32_t n = best->st.o_pn, d = best->st.o_pd;
        if (has_limit) {
            int64_t lhs = (int64_t)n * ln;
            int64_t rhs = (int64_t)d * ld;
            if (lhs > rhs || (lhs == rhs &&
                              (passive_taker ||
                               (best->st.flags & OFFER_PASSIVE_FLAG)))) {
                *bought_out = bought;
                *sold_out = sold;
                return CROSS_BAD_PRICE;
            }
        }
        if (memcmp(best->acc_key, taker, 32) == 0) {
            *bought_out = bought;
            *sold_out = sold;
            return CROSS_SELF;
        }
        const uint8_t *owner = best->acc_key;
        int64_t pre_amount = best->st.o_amount; /* Python reads the
            parent-copy's amount after mutating the live one */
        /* release the resting offer's liabilities up front */
        int ok = apply_offer_liab(env, best, pre_amount, -1, lv, &err);
        if (err)
            return CROSS_ERR;
        if (!ok) {
            env_bail(env, "liab-release"); /* Python asserts here */
            return CROSS_ERR;
        }
        __int128 wheat_cap =
            i128min(pre_amount, available_to_sell(env, owner, buy_asset,
                                                  buy_len, &err));
        if (err)
            return CROSS_ERR;
        __int128 recv_cap = available_to_receive(env, owner, sell_asset,
                                                 sell_len, &err);
        if (err)
            return CROSS_ERR;
        if (recv_cap < INT64_MAXV)
            wheat_cap = i128min(wheat_cap, floor_div128(recv_cap * d, n));
        if (wheat_cap <= 0) {
            /* unfunded/unreceivable offer: garbage-collect it */
            if (erase_offer(env, best, lv) < 0)
                return CROSS_ERR;
            continue;
        }
        Exch ex = exchange(wheat_cap, n, d, max_buy - bought,
                           max_sell - sold);
        if (ex.wheat == 0) {
            /* taker exhausted; restore the resting offer's liabilities */
            ok = apply_offer_liab(env, best, pre_amount, +1, lv, &err);
            if (err)
                return CROSS_ERR;
            if (!ok) {
                env_bail(env, "liab-reacquire");
                return CROSS_ERR;
            }
            *bought_out = bought;
            *sold_out = sold;
            return CROSS_SUCCESS;
        }
        /* settle the owner's side */
        int ok1 = xfer_debit(env, owner, buy_asset, buy_len, ex.wheat, lv);
        int ok2 = xfer_credit(env, owner, sell_asset, sell_len, ex.sheep,
                              lv);
        if (ok1 < 0 || ok2 < 0)
            return CROSS_ERR;
        if (!ok1 || !ok2) {
            env_bail(env, "owner-settle"); /* Python asserts */
            return CROSS_ERR;
        }
        if (touch(env, best, lv) < 0)
            return CROSS_ERR;
        best->st.o_amount -= (int64_t)ex.wheat;
        if (best->st.o_amount <= 0 ||
            (ex.wheat == wheat_cap && ex.wheat < pre_amount)) {
            if (erase_offer(env, best, lv) < 0)
                return CROSS_ERR;
        } else {
            /* clamp the residual to what the owner can still back,
               then re-encumber (v10+ — the engine requires v10) */
            __int128 can_sell = available_to_sell(env, owner, buy_asset,
                                                  buy_len, &err);
            if (err)
                return CROSS_ERR;
            __int128 can_recv = available_to_receive(env, owner,
                                                     sell_asset, sell_len,
                                                     &err);
            if (err)
                return CROSS_ERR;
            __int128 adj = adjust_offer(
                n, d, i128min(best->st.o_amount, can_sell), can_recv);
            best->st.o_amount = (int64_t)adj;
            if (best->st.o_amount <= 0) {
                if (erase_offer(env, best, lv) < 0)
                    return CROSS_ERR;
            } else {
                ok = apply_offer_liab(env, best, best->st.o_amount, +1,
                                      lv, &err);
                if (err)
                    return CROSS_ERR;
                if (!ok) {
                    env_bail(env, "liab-reacquire");
                    return CROSS_ERR;
                }
            }
        }
        bought += ex.wheat;
        sold += ex.sheep;
        if (claim_append(claims, nclaims, owner, best->offer_id,
                         buy_asset, buy_len, ex.wheat, sell_asset,
                         sell_len, ex.sheep) < 0) {
            env->oom = 1;
            return CROSS_ERR;
        }
    }
    *bought_out = bought;
    *sold_out = sold;
    return CROSS_SUCCESS;
}

/* ------------------------------------------------------------ op applies */

static int apply_create_account(AEnv *env, Op *op, const uint8_t *src_id,
                                OpRes *res)
{
    Ctx *c = env->c;
    res->code = opINNER;
    res->optype = OP_CREATE_ACCOUNT;
    Entry *dest = get_account(env, op->dest); /* load_without_record */
    if (!dest)
        return -1;
    if (dest->st.exists) {
        res->inner_code = CA_ALREADY_EXIST;
        return 0;
    }
    if ((__int128)op->amount < (__int128)2 * c->baseReserve) {
        res->inner_code = CA_LOW_RESERVE;
        return 0;
    }
    Entry *src = get_account(env, src_id);
    if (!src)
        return -1;
    if (touch(env, src, 3) < 0)
        return -1;
    if (!add_balance(c, src, -(__int128)op->amount)) {
        res->inner_code = CA_UNDERFUNDED;
        return 0;
    }
    if (touch(env, dest, 3) < 0)
        return -1;
    MutState *st = &dest->st;
    memset(st, 0, sizeof(*st));
    st->exists = 1;
    dest->type = LET_ACCOUNT;
    memcpy(dest->acc_key, op->dest, 32);
    st->balance = op->amount;
    st->seqNum = (int64_t)((uint64_t)c->ledgerSeq << 32);
    st->thresholds[0] = 1;
    st->lm = c->ledgerSeq;
    res->inner_code = CA_SUCCESS;
    return 0;
}

static int apply_payment(AEnv *env, Op *op, const uint8_t *src_id,
                         OpRes *res)
{
    res->code = opINNER;
    res->optype = OP_PAYMENT;
    Entry *dest_acc = get_account(env, op->dest);
    if (!dest_acc)
        return -1;
    if (touch(env, dest_acc, 3) < 0) /* ltx.load records before check */
        return -1;
    if (!dest_acc->st.exists) {
        res->inner_code = PAY_NO_DESTINATION;
        return 0;
    }
    if (op->asset_native) {
        Entry *src = get_account(env, src_id);
        if (!src)
            return -1;
        if (touch(env, src, 3) < 0)
            return -1;
        if (memcmp(src_id, op->dest, 32) != 0) {
            if (!add_balance(env->c, src, -(__int128)op->amount)) {
                res->inner_code = PAY_UNDERFUNDED;
                return 0;
            }
            if (!add_balance(env->c, dest_acc, op->amount)) {
                res->inner_code = PAY_LINE_FULL;
                return 0;
            }
        }
        res->inner_code = PAY_SUCCESS;
        return 0;
    }
    /* credit asset: source side */
    if (memcmp(src_id, op->issuer, 32) != 0) {
        Entry *stl = get_trustline(env, src_id, op->asset, op->assetlen);
        if (!stl)
            return -1;
        if (!stl->st.exists) {
            res->inner_code = PAY_SRC_NO_TRUST;
            return 0;
        }
        if (touch(env, stl, 3) < 0)
            return -1;
        if (!(stl->st.flags & TL_AUTHORIZED)) {
            res->inner_code = PAY_SRC_NOT_AUTHORIZED;
            return 0;
        }
        if (!add_trust_balance(stl, -(__int128)op->amount)) {
            res->inner_code = PAY_UNDERFUNDED;
            return 0;
        }
    } else {
        Entry *iss = get_account(env, op->issuer);
        if (!iss)
            return -1;
        if (!iss->st.exists) {
            res->inner_code = PAY_NO_ISSUER;
            return 0;
        }
        if (touch(env, iss, 3) < 0)
            return -1;
    }
    /* destination side */
    if (memcmp(op->dest, op->issuer, 32) != 0) {
        Entry *dtl = get_trustline(env, op->dest, op->asset, op->assetlen);
        if (!dtl)
            return -1;
        if (!dtl->st.exists) {
            res->inner_code = PAY_NO_TRUST;
            return 0;
        }
        if (touch(env, dtl, 3) < 0)
            return -1;
        if (!(dtl->st.flags & TL_AUTHORIZED)) {
            res->inner_code = PAY_NOT_AUTHORIZED;
            return 0;
        }
        if (!add_trust_balance(dtl, op->amount)) {
            res->inner_code = PAY_LINE_FULL;
            return 0;
        }
    }
    res->inner_code = PAY_SUCCESS;
    return 0;
}

static int apply_set_options(AEnv *env, Op *op, const uint8_t *src_id,
                             OpRes *res)
{
    res->code = opINNER;
    res->optype = OP_SET_OPTIONS;
    Entry *src = get_account(env, src_id); /* exists checked by caller */
    if (!src)
        return -1;
    if (touch(env, src, 3) < 0)
        return -1;
    MutState *st = &src->st;
    if (op->so_has_infl) {
        Entry *d = get_account(env, op->so_infl); /* load_without_record */
        if (!d)
            return -1;
        if (!d->st.exists) {
            res->inner_code = SO_INVALID_INFLATION;
            return 0;
        }
        st->has_infl = 1;
        memcpy(st->infl, op->so_infl, 32);
    }
    if (op->so_has_clear) {
        if (st->flags & AUTH_IMMUTABLE_FLAG) {
            res->inner_code = SO_CANT_CHANGE;
            return 0;
        }
        st->flags &= ~op->so_clear;
    }
    if (op->so_has_set) {
        if (st->flags & AUTH_IMMUTABLE_FLAG) {
            res->inner_code = SO_CANT_CHANGE;
            return 0;
        }
        st->flags |= op->so_set;
    }
    if (op->so_has_mw)
        st->thresholds[0] = (uint8_t)op->so_mw;
    if (op->so_has_lt)
        st->thresholds[1] = (uint8_t)op->so_lt;
    if (op->so_has_mt)
        st->thresholds[2] = (uint8_t)op->so_mt;
    if (op->so_has_ht)
        st->thresholds[3] = (uint8_t)op->so_ht;
    if (op->so_has_home) {
        st->home_len = op->so_home_len;
        if (op->so_home_len)
            memcpy(st->home, op->so_home, op->so_home_len);
    }
    if (op->so_has_signer) {
        int idx = -1, i;
        for (i = 0; i < st->nsigners; i++)
            if (memcmp(st->signer_keys[i], op->so_signer_key, 32) == 0) {
                idx = i;
                break;
            }
        if (op->so_signer_w == 0) {
            if (idx >= 0) {
                memmove(st->signer_keys[idx], st->signer_keys[idx + 1],
                        (st->nsigners - idx - 1) * 32);
                memmove(&st->signer_weights[idx],
                        &st->signer_weights[idx + 1],
                        (st->nsigners - idx - 1) * sizeof(uint32_t));
                st->nsigners--;
                change_subentries(env->c, src, -1); /* rc ignored */
            }
        } else if (idx >= 0) {
            st->signer_weights[idx] = op->so_signer_w;
        } else {
            if (st->nsigners >= MAX_SIGNERS) {
                res->inner_code = SO_TOO_MANY_SIGNERS;
                return 0;
            }
            if (!change_subentries(env->c, src, +1)) {
                res->inner_code = SO_LOW_RESERVE;
                return 0;
            }
            memcpy(st->signer_keys[st->nsigners], op->so_signer_key, 32);
            st->signer_weights[st->nsigners] = op->so_signer_w;
            st->nsigners++;
        }
        /* Python re-sorts the WHOLE list after every signer arm (by
           key.to_xdr(); all keys share the ed25519 type prefix, so raw
           key bytes compare identically). Stable insertion sort. */
        for (i = 1; i < st->nsigners; i++) {
            uint8_t k[32];
            uint32_t w = st->signer_weights[i];
            int j = i;
            memcpy(k, st->signer_keys[i], 32);
            while (j > 0 && memcmp(k, st->signer_keys[j - 1], 32) < 0) {
                memcpy(st->signer_keys[j], st->signer_keys[j - 1], 32);
                st->signer_weights[j] = st->signer_weights[j - 1];
                j--;
            }
            memcpy(st->signer_keys[j], k, 32);
            st->signer_weights[j] = w;
        }
    }
    res->inner_code = SO_SUCCESS;
    return 0;
}

static int apply_change_trust(AEnv *env, Op *op, const uint8_t *src_id,
                              OpRes *res)
{
    Ctx *c = env->c;
    res->code = opINNER;
    res->optype = OP_CHANGE_TRUST;
    if (memcmp(src_id, op->issuer, 32) == 0) {
        res->inner_code = CT_SELF_NOT_ALLOWED;
        return 0;
    }
    Entry *tl = get_trustline(env, src_id, op->asset, op->assetlen);
    if (!tl)
        return -1;
    if (tl->st.exists) {
        if (touch(env, tl, 3) < 0) /* ltx.load records */
            return -1;
        /* limit floor: balance + buying liabilities (v10+) */
        if ((__int128)op->ct_limit <
            (__int128)tl->st.balance + tl->st.liab_buying) {
            res->inner_code = CT_INVALID_LIMIT;
            return 0;
        }
        if (op->ct_limit == 0) {
            tl->st.exists = 0; /* erase */
            Entry *src = get_account(env, src_id);
            if (!src)
                return -1;
            if (touch(env, src, 3) < 0)
                return -1;
            change_subentries(c, src, -1); /* rc ignored, like Python */
            res->inner_code = CT_SUCCESS;
            return 0;
        }
        Entry *iss = get_account(env, op->issuer); /* without_record */
        if (!iss)
            return -1;
        if (!iss->st.exists) {
            res->inner_code = CT_NO_ISSUER;
            return 0;
        }
        tl->st.tl_limit = op->ct_limit;
        res->inner_code = CT_SUCCESS;
        return 0;
    }
    if (op->ct_limit == 0) {
        res->inner_code = CT_INVALID_LIMIT;
        return 0;
    }
    Entry *iss = get_account(env, op->issuer); /* load_without_record */
    if (!iss)
        return -1;
    if (!iss->st.exists) {
        res->inner_code = CT_NO_ISSUER;
        return 0;
    }
    Entry *src = get_account(env, src_id);
    if (!src)
        return -1;
    if (touch(env, src, 3) < 0)
        return -1;
    if (!change_subentries(c, src, +1)) {
        res->inner_code = CT_LOW_RESERVE;
        return 0;
    }
    if (touch(env, tl, 3) < 0)
        return -1;
    MutState *st = &tl->st;
    memset(st, 0, sizeof(*st));
    st->exists = 1;
    st->tl_limit = op->ct_limit;
    st->flags = (iss->st.flags & AUTH_REQUIRED_FLAG) ? 0 : TL_AUTHORIZED;
    st->lm = c->ledgerSeq;
    res->inner_code = CT_SUCCESS;
    return 0;
}

static int apply_bump_sequence(AEnv *env, Op *op, const uint8_t *src_id,
                               OpRes *res)
{
    res->code = opINNER;
    res->optype = OP_BUMP_SEQUENCE;
    Entry *src = get_account(env, src_id);
    if (!src)
        return -1;
    if (touch(env, src, 3) < 0)
        return -1;
    if (op->bs_to > src->st.seqNum)
        src->st.seqNum = op->bs_to;
    res->inner_code = BS_SUCCESS;
    return 0;
}

static int apply_manage_data(AEnv *env, Op *op, const uint8_t *src_id,
                             OpRes *res)
{
    Ctx *c = env->c;
    res->code = opINNER;
    res->optype = OP_MANAGE_DATA;
    Entry *d = get_data(env, src_id, op->md_name, op->md_name_len);
    if (!d)
        return -1;
    if (d->st.exists && touch(env, d, 3) < 0) /* ltx.load records */
        return -1;
    if (!op->md_has_val) {
        if (!d->st.exists) {
            res->inner_code = MD_NAME_NOT_FOUND;
            return 0;
        }
        d->st.exists = 0;
        Entry *src = get_account(env, src_id);
        if (!src)
            return -1;
        if (touch(env, src, 3) < 0)
            return -1;
        change_subentries(c, src, -1);
        res->inner_code = MD_SUCCESS;
        return 0;
    }
    if (d->st.exists) {
        d->st.d_len = op->md_val_len;
        if (op->md_val_len)
            memcpy(d->st.d_val, op->md_val, op->md_val_len);
        res->inner_code = MD_SUCCESS;
        return 0;
    }
    Entry *src = get_account(env, src_id);
    if (!src)
        return -1;
    if (touch(env, src, 3) < 0)
        return -1;
    if (!change_subentries(c, src, +1)) {
        res->inner_code = MD_LOW_RESERVE;
        return 0;
    }
    if (touch(env, d, 3) < 0)
        return -1;
    MutState *st = &d->st;
    memset(st, 0, sizeof(*st));
    st->exists = 1;
    st->d_len = op->md_val_len;
    if (op->md_val_len)
        memcpy(st->d_val, op->md_val, op->md_val_len);
    st->lm = c->ledgerSeq;
    res->inner_code = MD_SUCCESS;
    return 0;
}

static int apply_account_merge(AEnv *env, Op *op, const uint8_t *src_id,
                               OpRes *res)
{
    Ctx *c = env->c;
    res->code = opINNER;
    res->optype = OP_ACCOUNT_MERGE;
    Entry *dest = get_account(env, op->dest);
    if (!dest)
        return -1;
    if (dest->st.exists && touch(env, dest, 3) < 0)
        return -1;
    if (!dest->st.exists) {
        res->inner_code = AM_NO_ACCOUNT;
        return 0;
    }
    Entry *src = get_account(env, src_id);
    if (!src)
        return -1;
    if (touch(env, src, 3) < 0)
        return -1;
    if (src->st.flags & AUTH_IMMUTABLE_FLAG) {
        res->inner_code = AM_IMMUTABLE_SET;
        return 0;
    }
    /* only OWNED subentries (trustlines/offers/data) block a merge */
    if (src->st.numSub != (uint32_t)src->st.nsigners) {
        res->inner_code = AM_HAS_SUB_ENTRIES;
        return 0;
    }
    if (src->st.seqNum >= (int64_t)((uint64_t)c->ledgerSeq << 32)) {
        res->inner_code = AM_SEQNUM_TOO_FAR;
        return 0;
    }
    int64_t balance = src->st.balance;
    if (!add_balance(c, dest, balance)) {
        res->inner_code = AM_DEST_FULL;
        return 0;
    }
    src->st.exists = 0;
    res->inner_code = AM_SUCCESS;
    res->has_payload = 1;
    if (buf_i64(&res->payload, balance) < 0) {
        env->oom = 1;
        return -1;
    }
    return 0;
}

static int apply_inflation(AEnv *env, OpRes *res)
{
    Ctx *c = env->c;
    res->code = opINNER;
    res->optype = OP_INFLATION;
    /* caller gated version < 12 */
    if ((int64_t)c->closeTime <
        ((int64_t)c->inflationSeq + 1) * INFLATION_FREQUENCY) {
        res->inner_code = INF_NOT_TIME;
        return 0;
    }
    /* a due payout needs the balance-weighted vote query over ALL
       accounts (merged with the open txn chain) plus strkey-ordered
       tie-breaks — the Python path stays the oracle for this */
    env_bail(env, "inflation-payout");
    return -1;
}

/* AllowTrustOpFrame.do_apply (+ _remove_offers on a full revoke) */
static int apply_allow_trust(AEnv *env, Op *op, const uint8_t *src_id,
                             OpRes *res)
{
    Ctx *c = env->c;
    res->code = opINNER;
    res->optype = OP_ALLOW_TRUST;
    if (memcmp(op->at_trustor, src_id, 32) == 0) {
        res->inner_code = AT_SELF_NOT_ALLOWED;
        return 0;
    }
    Entry *issuer = get_account(env, src_id); /* load_account records */
    if (!issuer)
        return -1;
    if (touch(env, issuer, 3) < 0)
        return -1;
    if (!(issuer->st.flags & AUTH_REQUIRED_FLAG)) {
        res->inner_code = AT_TRUST_NOT_REQUIRED;
        return 0;
    }
    int not_revocable = !(issuer->st.flags & AUTH_REVOCABLE_FLAG);
    if (not_revocable && op->at_auth == 0) {
        res->inner_code = AT_CANT_REVOKE;
        return 0;
    }
    /* the derived asset's issuer is the op source */
    uint8_t asset[MAX_ASSET];
    int assetlen = op->at_assetlen;
    memcpy(asset, op->at_asset, assetlen);
    memcpy(asset + assetlen - 32, src_id, 32);
    Entry *tl = get_trustline(env, op->at_trustor, asset, assetlen);
    if (!tl)
        return -1;
    if (!tl->st.exists) {
        res->inner_code = AT_NO_TRUST_LINE;
        return 0;
    }
    if (touch(env, tl, 3) < 0)
        return -1;
    int fully = !!(tl->st.flags & TL_AUTHORIZED);
    int maintain_or_more = !!(tl->st.flags & TL_AUTH_LEVELS_MASK);
    if (not_revocable && fully && (op->at_auth & TL_MAINTAIN)) {
        res->inner_code = AT_CANT_REVOKE;
        return 0;
    }
    if (maintain_or_more && op->at_auth == 0) {
        /* _remove_offers: pull the trustor's offers in this asset and
           release their liabilities. Python loads the whole filtered
           list first (each load records), then processes per offer. */
        AcctBook *ab = get_acct_book(env, op->at_trustor);
        if (!ab)
            return -1;
        EList matched = {NULL, 0, 0};
        int i;
        for (i = 0; i < ab->offers.n; i++) {
            Entry *e = ab->offers.v[i];
            if (!e->st.exists)
                continue;
            if (!asset_eq(e->o_sell, e->o_sell_len, asset, assetlen) &&
                !asset_eq(e->o_buy, e->o_buy_len, asset, assetlen))
                continue;
            if (elist_push(&matched, e) < 0) {
                env->oom = 1;
                free(matched.v);
                return -1;
            }
        }
        for (i = 0; i < c->created_offers.n; i++) {
            Entry *e = c->created_offers.v[i];
            if (!e->st.exists || e->base)
                continue;
            if (memcmp(e->acc_key, op->at_trustor, 32) != 0)
                continue;
            if (!asset_eq(e->o_sell, e->o_sell_len, asset, assetlen) &&
                !asset_eq(e->o_buy, e->o_buy_len, asset, assetlen))
                continue;
            if (elist_push(&matched, e) < 0) {
                env->oom = 1;
                free(matched.v);
                return -1;
            }
        }
        for (i = 0; i < matched.n; i++) /* the load() pass records */
            if (touch(env, matched.v[i], 3) < 0) {
                free(matched.v);
                return -1;
            }
        for (i = 0; i < matched.n; i++) {
            Entry *e = matched.v[i];
            int lerr = 0;
            int ok = apply_offer_liab(env, e, e->st.o_amount, -1, 3,
                                      &lerr);
            if (lerr || !ok) {
                if (!lerr)
                    env_bail(env, "liab-release");
                free(matched.v);
                return -1;
            }
            Entry *acct = get_account(env, op->at_trustor);
            if (!acct) {
                free(matched.v);
                return -1;
            }
            if (touch(env, acct, 3) < 0) {
                free(matched.v);
                return -1;
            }
            change_subentries(c, acct, -1);
            e->st.exists = 0; /* erase */
        }
        free(matched.v);
    }
    tl->st.flags = op->at_auth;
    res->inner_code = AT_SUCCESS;
    return 0;
}

/* serialize one OfferEntry BODY (the manage-offer result arm) */
static int ser_offer_body(Buf *b, const uint8_t *seller, int64_t oid,
                          const uint8_t *sell, int sell_len,
                          const uint8_t *buy, int buy_len, int64_t amount,
                          int32_t pn, int32_t pd, uint32_t flags)
{
    if (buf_u32(b, 0) < 0 || buf_put(b, seller, 32) < 0 ||
        buf_i64(b, oid) < 0 || buf_put(b, sell, sell_len) < 0 ||
        buf_put(b, buy, buy_len) < 0 || buf_i64(b, amount) < 0 ||
        buf_i32(b, pn) < 0 || buf_i32(b, pd) < 0 ||
        buf_u32(b, flags) < 0 || buf_u32(b, 0) < 0 /* ext */)
        return -1;
    return 0;
}

/* assemble a ManageOfferSuccessResult payload:
   claims array + offer union arm */
static int mo_success_payload(OpRes *res, const Buf *claims, int nclaims,
                              int arm /* 0 created / 1 updated / 2 del */,
                              const Buf *offer_body)
{
    res->has_payload = 1;
    if (buf_u32(&res->payload, (uint32_t)nclaims) < 0 ||
        buf_put(&res->payload, claims->data, claims->len) < 0 ||
        buf_u32(&res->payload, (uint32_t)arm) < 0)
        return -1;
    if (arm != 2 &&
        buf_put(&res->payload, offer_body->data, offer_body->len) < 0)
        return -1;
    return 0;
}

/* _ManageOfferBase.do_apply for all three offer op flavors */
static int apply_manage_offer(AEnv *env, Op *op, const uint8_t *src_id,
                              OpRes *res)
{
    Ctx *c = env->c;
    res->code = opINNER;
    res->optype = op->optype;
    Buf claims = {NULL, 0, 0, &env->ar};
    Buf offer_body = {NULL, 0, 0, &env->ar};
    int nclaims = 0;
    int rc = -1;
    int err = 0;

    int is_delete = op->o_is_buy
                        ? (op->o_buy_amount == 0 && op->o_offer_id != 0)
                        : (op->o_amount == 0 && op->o_offer_id != 0);
    if (!is_delete) {
        /* checkOfferValid: FULL authorization on both lines; issuer
           existence checks only pre-13 */
        const uint8_t *legs[2] = {op->o_sell, op->o_buy};
        const int lens[2] = {op->o_sell_len, op->o_buy_len};
        const int no_issuer[2] = {MO_SELL_NO_ISSUER, MO_BUY_NO_ISSUER};
        const int no_trust[2] = {MO_SELL_NO_TRUST, MO_BUY_NO_TRUST};
        const int not_auth[2] = {MO_SELL_NOT_AUTHORIZED,
                                 MO_BUY_NOT_AUTHORIZED};
        for (int leg = 0; leg < 2; leg++) {
            if (asset_is_native(legs[leg], lens[leg]))
                continue;
            if (memcmp(src_id, asset_issuer(legs[leg], lens[leg]), 32) ==
                0)
                continue;
            if (c->ledgerVersion < 13) {
                Entry *iss = get_account(
                    env, asset_issuer(legs[leg], lens[leg]));
                if (!iss)
                    goto out;
                if (!iss->st.exists) {
                    res->inner_code = no_issuer[leg];
                    rc = 0;
                    goto out;
                }
            }
            Entry *tl = get_trustline(env, src_id, legs[leg], lens[leg]);
            if (!tl)
                goto out;
            if (!tl->st.exists) {
                res->inner_code = no_trust[leg];
                rc = 0;
                goto out;
            }
            if (!(tl->st.flags & TL_AUTHORIZED)) {
                res->inner_code = not_auth[leg];
                rc = 0;
                goto out;
            }
        }
    }

    uint32_t existing_flags = 0;
    int is_update = 0;
    if (op->o_offer_id != 0) {
        uint8_t keyb[48];
        offer_key(keyb, src_id, op->o_offer_id);
        Entry *e = get_entry(env, keyb, 48);
        if (!e)
            goto out;
        if (!e->st.exists) {
            res->inner_code = MO_NOT_FOUND;
            rc = 0;
            goto out;
        }
        if (touch(env, e, 3) < 0)
            goto out;
        int ok = apply_offer_liab(env, e, e->st.o_amount, -1, 3, &err);
        if (err)
            goto out;
        if (!ok) {
            env_bail(env, "liab-release");
            goto out;
        }
        existing_flags = e->st.flags;
        e->st.exists = 0; /* pulled from the book; subentry kept */
        is_update = 1;
    }

    if (is_delete) {
        Entry *src = get_account(env, src_id);
        if (!src)
            goto out;
        if (touch(env, src, 3) < 0)
            goto out;
        change_subentries(c, src, -1);
        res->inner_code = MO_SUCCESS;
        if (mo_success_payload(res, &claims, 0, 2, NULL) < 0) {
            env->oom = 1;
            goto out;
        }
        rc = 0;
        goto out;
    }

    if (!is_update) {
        Entry *src = get_account(env, src_id);
        if (!src)
            goto out;
        if (touch(env, src, 3) < 0)
            goto out;
        if (!change_subentries(c, src, +1)) {
            res->inner_code = MO_LOW_RESERVE;
            rc = 0;
            goto out;
        }
    }
    __int128 buy_liab, sell_liab;
    offer_liabilities(op->o_pn, op->o_pd, op->o_amount, &buy_liab,
                      &sell_liab);
    __int128 max_sell_funds =
        available_to_sell(env, src_id, op->o_sell, op->o_sell_len, &err);
    if (err)
        goto out;
    __int128 recv_cap = available_to_receive(env, src_id, op->o_buy,
                                             op->o_buy_len, &err);
    if (err)
        goto out;
    if (recv_cap < buy_liab || recv_cap <= 0) {
        res->inner_code = MO_LINE_FULL;
        rc = 0;
        goto out;
    }
    if (max_sell_funds < sell_liab ||
        (max_sell_funds <= 0 && op->o_amount > 0)) {
        res->inner_code = MO_UNDERFUNDED;
        rc = 0;
        goto out;
    }
    __int128 wheat_cap =
        op->o_is_buy
            ? (op->o_buy_amount > 0 ? (__int128)op->o_buy_amount
                                    : (__int128)INT64_MAXV)
            : (__int128)INT64_MAXV;
    __int128 max_sell = op->o_is_buy
                            ? max_sell_funds
                            : i128min(op->o_amount, max_sell_funds);
    __int128 bought = 0, sold = 0;
    int code = cross_offers(env, src_id, op->o_sell, op->o_sell_len,
                            op->o_buy, op->o_buy_len,
                            i128min(recv_cap, wheat_cap), max_sell, 1,
                            op->o_pn, op->o_pd, op->o_passive, &bought,
                            &sold, &claims, &nclaims, 3);
    if (code == CROSS_ERR)
        goto out;
    if (code == CROSS_SELF) {
        res->inner_code = MO_CROSS_SELF;
        rc = 0;
        goto out;
    }
    /* settle taker net amounts (Python asserts both) */
    int ok1 = xfer_debit(env, src_id, op->o_sell, op->o_sell_len, sold, 3);
    int ok2 =
        xfer_credit(env, src_id, op->o_buy, op->o_buy_len, bought, 3);
    if (ok1 < 0 || ok2 < 0)
        goto out;
    if (!ok1 || !ok2) {
        env_bail(env, "taker-settle");
        goto out;
    }
    __int128 sheep_resid =
        op->o_is_buy ? (__int128)INT64_MAXV : (op->o_amount - sold);
    __int128 can_sell =
        available_to_sell(env, src_id, op->o_sell, op->o_sell_len, &err);
    if (err)
        goto out;
    __int128 can_recv = available_to_receive(env, src_id, op->o_buy,
                                             op->o_buy_len, &err);
    if (err)
        goto out;
    __int128 remaining =
        adjust_offer(op->o_pn, op->o_pd, i128min(sheep_resid, can_sell),
                     i128min(can_recv, wheat_cap - bought));
    int arm;
    if (remaining > 0) {
        int64_t new_id;
        if (is_update)
            new_id = op->o_offer_id;
        else {
            c->idPool += 1;
            new_id = c->idPool;
        }
        uint32_t flags =
            (op->o_passive || (existing_flags & OFFER_PASSIVE_FLAG))
                ? OFFER_PASSIVE_FLAG
                : 0;
        uint8_t keyb[48];
        offer_key(keyb, src_id, new_id);
        Entry *e = get_entry(env, keyb, 48);
        if (!e)
            goto out;
        if (touch(env, e, 3) < 0)
            goto out;
        MutState *st = &e->st;
        memset(st, 0, sizeof(*st));
        st->exists = 1;
        e->type = LET_OFFER;
        memcpy(e->acc_key, src_id, 32);
        e->offer_id = new_id;
        memcpy(e->o_sell, op->o_sell, op->o_sell_len);
        e->o_sell_len = op->o_sell_len;
        memcpy(e->o_buy, op->o_buy, op->o_buy_len);
        e->o_buy_len = op->o_buy_len;
        st->o_amount = (int64_t)remaining;
        st->o_pn = op->o_pn;
        st->o_pd = op->o_pd;
        st->flags = flags;
        st->lm = c->ledgerSeq;
        if (!e->base && !e->in_created) {
            if (elist_push(&c->created_offers, e) < 0) {
                env->oom = 1;
                goto out;
            }
            e->in_created = 1;
        }
        int ok = apply_offer_liab(env, e, st->o_amount, +1, 3, &err);
        if (err)
            goto out;
        if (!ok) {
            env_bail(env, "liab-acquire");
            goto out;
        }
        arm = is_update ? 1 : 0;
        if (ser_offer_body(&offer_body, src_id, new_id, op->o_sell,
                           op->o_sell_len, op->o_buy, op->o_buy_len,
                           st->o_amount, st->o_pn, st->o_pd, flags) < 0) {
            env->oom = 1;
            goto out;
        }
    } else {
        Entry *src = get_account(env, src_id);
        if (!src)
            goto out;
        if (touch(env, src, 3) < 0)
            goto out;
        change_subentries(c, src, -1);
        arm = 2;
    }
    res->inner_code = MO_SUCCESS;
    if (mo_success_payload(res, &claims, nclaims, arm, &offer_body) < 0) {
        env->oom = 1;
        goto out;
    }
    rc = 0;
out:
    buf_free(&claims);
    buf_free(&offer_body);
    return rc;
}

/* _PathPaymentBase credit/debit capability codes (0 = ok) */
static int pp_dest_credit_code(AEnv *env, const uint8_t *dest,
                               const uint8_t *asset, int assetlen,
                               __int128 amount, int *err)
{
    if (asset_is_native(asset, assetlen)) {
        if (available_to_receive(env, dest, asset, assetlen, err) < amount)
            return *err ? 0 : PP_LINE_FULL;
        return 0;
    }
    if (memcmp(dest, asset_issuer(asset, assetlen), 32) == 0)
        return 0;
    Entry *iss = get_account(env, asset_issuer(asset, assetlen));
    if (!iss) {
        *err = 1;
        return 0;
    }
    if (!iss->st.exists)
        return PP_NO_ISSUER;
    Entry *tl = get_trustline(env, dest, asset, assetlen);
    if (!tl) {
        *err = 1;
        return 0;
    }
    if (!tl->st.exists)
        return PP_NO_TRUST;
    if (!(tl->st.flags & TL_AUTHORIZED))
        return PP_NOT_AUTHORIZED;
    if (available_to_receive(env, dest, asset, assetlen, err) < amount)
        return *err ? 0 : PP_LINE_FULL;
    return 0;
}

static int pp_src_debit_code(AEnv *env, const uint8_t *src,
                             const uint8_t *asset, int assetlen,
                             __int128 amount, int *err)
{
    if (asset_is_native(asset, assetlen)) {
        if (available_to_sell(env, src, asset, assetlen, err) < amount)
            return *err ? 0 : PP_UNDERFUNDED;
        return 0;
    }
    if (memcmp(src, asset_issuer(asset, assetlen), 32) == 0)
        return 0;
    Entry *iss = get_account(env, asset_issuer(asset, assetlen));
    if (!iss) {
        *err = 1;
        return 0;
    }
    if (!iss->st.exists)
        return PP_NO_ISSUER;
    Entry *tl = get_trustline(env, src, asset, assetlen);
    if (!tl) {
        *err = 1;
        return 0;
    }
    if (!tl->st.exists)
        return PP_SRC_NO_TRUST;
    if (!(tl->st.flags & TL_AUTHORIZED))
        return PP_SRC_NOT_AUTHORIZED;
    if (available_to_sell(env, src, asset, assetlen, err) < amount)
        return *err ? 0 : PP_UNDERFUNDED;
    return 0;
}

/* PathPaymentSuccess payload: claims + SimplePaymentResult */
static int pp_success_payload(OpRes *res, const Buf *claims, int nclaims,
                              const uint8_t *dest, const uint8_t *asset,
                              int assetlen, __int128 amount)
{
    res->has_payload = 1;
    if (buf_u32(&res->payload, (uint32_t)nclaims) < 0 ||
        buf_put(&res->payload, claims->data, claims->len) < 0 ||
        buf_u32(&res->payload, 0) < 0 ||
        buf_put(&res->payload, dest, 32) < 0 ||
        buf_put(&res->payload, asset, assetlen) < 0 ||
        buf_i64(&res->payload, (int64_t)amount) < 0)
        return -1;
    return 0;
}

static int apply_path_payment(AEnv *env, Op *op, const uint8_t *src_id,
                              OpRes *res)
{
    res->code = opINNER;
    res->optype = op->optype;
    int strict_send = (op->optype == OP_PATH_PAYMENT_SEND);
    int err = 0, rc = -1, i;
    /* the asset chain: send + path + dest */
    const uint8_t *chain[2 + MAX_PATH];
    int chain_len[2 + MAX_PATH];
    int nchain = 0;
    chain[nchain] = op->pp_send;
    chain_len[nchain++] = op->pp_send_len;
    for (i = 0; i < op->pp_npath; i++) {
        chain[nchain] = op->pp_path[i];
        chain_len[nchain++] = op->pp_path_len[i];
    }
    chain[nchain] = op->pp_dest;
    chain_len[nchain++] = op->pp_dest_len;

    Buf hop_claims[1 + MAX_PATH];
    int hop_n[1 + MAX_PATH];
    int nhops = 0;
    memset(hop_claims, 0, sizeof(hop_claims));
    memset(hop_n, 0, sizeof(hop_n));
    for (i = 0; i < 1 + MAX_PATH; i++)
        hop_claims[i].ar = &env->ar;

    Entry *dest = get_account(env, op->dest);
    if (!dest)
        goto out;
    if (dest->st.exists && touch(env, dest, 3) < 0)
        goto out;
    if (!dest->st.exists) {
        res->inner_code = PP_NO_DESTINATION;
        rc = 0;
        goto out;
    }

    if (!strict_send) {
        /* strict receive: check the destination leg up front */
        int code = pp_dest_credit_code(env, op->dest, op->pp_dest,
                                       op->pp_dest_len, op->pp_amount,
                                       &err);
        if (err)
            goto out;
        if (code) {
            res->inner_code = code;
            rc = 0;
            goto out;
        }
        __int128 needed = op->pp_amount;
        /* walk backwards: acquire `needed` of chain[i+1] with chain[i] */
        for (i = nchain - 2; i >= 0; i--) {
            if (asset_eq(chain[i], chain_len[i], chain[i + 1],
                         chain_len[i + 1]))
                continue;
            __int128 bought = 0, sold = 0;
            Buf *cb = &hop_claims[nhops];
            int cr = cross_offers(env, src_id, chain[i], chain_len[i],
                                  chain[i + 1], chain_len[i + 1], needed,
                                  INT64_MAXV, 0, 0, 0, 0, &bought, &sold,
                                  cb, &hop_n[nhops], 3);
            nhops++;
            if (cr == CROSS_ERR)
                goto out;
            if (cr == CROSS_SELF) {
                res->inner_code = PP_OFFER_CROSS_SELF;
                rc = 0;
                goto out;
            }
            if (bought < needed) {
                res->inner_code = PP_TOO_FEW_OFFERS;
                rc = 0;
                goto out;
            }
            needed = sold;
        }
        if (needed > op->pp_limit) {
            res->inner_code = PP_OVER_LIMIT; /* OVER_SENDMAX */
            rc = 0;
            goto out;
        }
        int dcode = pp_src_debit_code(env, src_id, op->pp_send,
                                      op->pp_send_len, needed, &err);
        if (err)
            goto out;
        if (dcode) {
            res->inner_code = dcode;
            rc = 0;
            goto out;
        }
        int ok1 = xfer_debit(env, src_id, op->pp_send, op->pp_send_len,
                             needed, 3);
        int ok2 = xfer_credit(env, op->dest, op->pp_dest, op->pp_dest_len,
                              op->pp_amount, 3);
        if (ok1 < 0 || ok2 < 0)
            goto out;
        if (!ok1 || !ok2) {
            env_bail(env, "pp-settle");
            goto out;
        }
        res->inner_code = PP_SUCCESS;
        /* claims: hops were gathered backwards; the result wants the
           chain order (claims prepend per hop) */
        Buf all = {NULL, 0, 0, &env->ar};
        int total = 0;
        for (i = nhops - 1; i >= 0; i--) {
            if (buf_put(&all, hop_claims[i].data, hop_claims[i].len) < 0) {
                buf_free(&all);
                env->oom = 1;
                goto out;
            }
            total += hop_n[i];
        }
        int prc = pp_success_payload(res, &all, total, op->dest,
                                     op->pp_dest, op->pp_dest_len,
                                     op->pp_amount);
        buf_free(&all);
        if (prc < 0) {
            env->oom = 1;
            goto out;
        }
        rc = 0;
        goto out;
    }

    /* strict send */
    {
        int code = pp_src_debit_code(env, src_id, op->pp_send,
                                     op->pp_send_len, op->pp_amount, &err);
        if (err)
            goto out;
        if (code) {
            res->inner_code = code;
            rc = 0;
            goto out;
        }
        int okd = xfer_debit(env, src_id, op->pp_send, op->pp_send_len,
                             op->pp_amount, 3);
        if (okd < 0)
            goto out;
        if (!okd) {
            env_bail(env, "pp-settle");
            goto out;
        }
        __int128 have = op->pp_amount;
        for (i = 0; i < nchain - 1; i++) {
            if (asset_eq(chain[i], chain_len[i], chain[i + 1],
                         chain_len[i + 1]))
                continue;
            __int128 bought = 0, sold = 0;
            Buf *cb = &hop_claims[nhops];
            int cr = cross_offers(env, src_id, chain[i], chain_len[i],
                                  chain[i + 1], chain_len[i + 1],
                                  INT64_MAXV, have, 0, 0, 0, 0, &bought,
                                  &sold, cb, &hop_n[nhops], 3);
            nhops++;
            if (cr == CROSS_ERR)
                goto out;
            if (cr == CROSS_SELF) {
                res->inner_code = PP_OFFER_CROSS_SELF;
                rc = 0;
                goto out;
            }
            if (bought == 0 || sold < have) {
                res->inner_code = PP_TOO_FEW_OFFERS;
                rc = 0;
                goto out;
            }
            have = bought;
        }
        if (have < op->pp_limit) {
            res->inner_code = PP_OVER_LIMIT; /* UNDER_DESTMIN */
            rc = 0;
            goto out;
        }
        int ccode = pp_dest_credit_code(env, op->dest, op->pp_dest,
                                        op->pp_dest_len, have, &err);
        if (err)
            goto out;
        if (ccode) {
            res->inner_code = ccode;
            rc = 0;
            goto out;
        }
        int okc = xfer_credit(env, op->dest, op->pp_dest, op->pp_dest_len,
                              have, 3);
        if (okc < 0)
            goto out;
        if (!okc) {
            env_bail(env, "pp-settle");
            goto out;
        }
        res->inner_code = PP_SUCCESS;
        Buf all = {NULL, 0, 0, &env->ar};
        int total = 0;
        for (i = 0; i < nhops; i++) {
            if (buf_put(&all, hop_claims[i].data, hop_claims[i].len) < 0) {
                buf_free(&all);
                env->oom = 1;
                goto out;
            }
            total += hop_n[i];
        }
        int prc = pp_success_payload(res, &all, total, op->dest,
                                     op->pp_dest, op->pp_dest_len, have);
        buf_free(&all);
        if (prc < 0) {
            env->oom = 1;
            goto out;
        }
        rc = 0;
    }
out:
    for (i = 0; i < 1 + MAX_PATH; i++)
        buf_free(&hop_claims[i]);
    return rc;
}

/* ------------------------------------------------------- op dispatching */

/* OperationFrame.is_version_supported */
static int op_version_supported(Ctx *c, int optype)
{
    switch (optype) {
    case OP_INFLATION:
        return c->ledgerVersion < 12;
    case OP_BUMP_SEQUENCE:
        return c->ledgerVersion >= 10;
    case OP_MANAGE_BUY_OFFER:
        return c->ledgerVersion >= 11;
    case OP_PATH_PAYMENT_SEND:
        return c->ledgerVersion >= 12;
    default:
        return 1;
    }
}

/* threshold level for processSignatures (reference per-frame
   getThresholdLevel) */
static int op_threshold_level(const Op *op)
{
    switch (op->optype) {
    case OP_ALLOW_TRUST:
    case OP_INFLATION:
    case OP_BUMP_SEQUENCE:
        return 0; /* LOW */
    case OP_ACCOUNT_MERGE:
        return 2; /* HIGH */
    case OP_SET_OPTIONS:
        if (op->so_has_mw || op->so_has_lt || op->so_has_mt ||
            op->so_has_ht || op->so_has_signer)
            return 2;
        return 1;
    default:
        return 1; /* MEDIUM */
    }
}

/* OperationFrame.apply: version gate, op-source existence, do_apply */
static int apply_one_op(AEnv *env, Op *op, const uint8_t *osrc,
                        OpRes *res)
{
    if (!op_version_supported(env->c, op->optype)) {
        res->code = opNOT_SUPPORTED;
        return 0;
    }
    Entry *oa = get_account(env, osrc); /* load_without_record */
    if (!oa)
        return -1;
    if (!oa->st.exists) {
        res->code = opNO_ACCOUNT;
        return 0;
    }
    switch (op->optype) {
    case OP_CREATE_ACCOUNT:
        return apply_create_account(env, op, osrc, res);
    case OP_PAYMENT:
        return apply_payment(env, op, osrc, res);
    case OP_PATH_PAYMENT_RECV:
    case OP_PATH_PAYMENT_SEND:
        return apply_path_payment(env, op, osrc, res);
    case OP_MANAGE_SELL_OFFER:
    case OP_CREATE_PASSIVE_OFFER:
    case OP_MANAGE_BUY_OFFER:
        return apply_manage_offer(env, op, osrc, res);
    case OP_SET_OPTIONS:
        return apply_set_options(env, op, osrc, res);
    case OP_CHANGE_TRUST:
        return apply_change_trust(env, op, osrc, res);
    case OP_ALLOW_TRUST:
        return apply_allow_trust(env, op, osrc, res);
    case OP_ACCOUNT_MERGE:
        return apply_account_merge(env, op, osrc, res);
    case OP_INFLATION:
        return apply_inflation(env, res);
    case OP_MANAGE_DATA:
        return apply_manage_data(env, op, osrc, res);
    case OP_BUMP_SEQUENCE:
        return apply_bump_sequence(env, op, osrc, res);
    default:
        env_bail(env, "op-dispatch");
        return -1;
    }
}

/* --------------------------------------------------------- tx apply */

/* the v1 apply phase for one tx (fees already charged). Mirrors
   TransactionFrame.apply exactly; stores every output (result code,
   op results, changes blobs) as plain C data for later emission.
   `fee_for_result` is the feeCharged every emitted result carries
   (the fee-phase value, or 0 for a fee bump's inner tx). */
static int apply_tx_v1(AEnv *env, Tx *t, int64_t fee_for_result)
{
    Ctx *c = env->c;
    int code = txSUCCESS;
    Entry *src = NULL;
    int i;
    (void)fee_for_result;

    t->txch.ar = &env->ar;
    for (i = 0; i < t->nsigs; i++)
        t->sigs[i].used = 0;

    /* _common_valid (applying), reference order */
    if (t->has_tb && t->minTime && c->closeTime < t->minTime)
        code = txTOO_EARLY;
    else if (t->has_tb && t->maxTime && c->closeTime > t->maxTime)
        code = txTOO_LATE;
    else if (t->nops == 0)
        code = txMISSING_OPERATION;
    else {
        __int128 minfee = (__int128)c->baseFee *
                          (t->nops > 1 ? t->nops : 1);
        if ((__int128)t->fee_bid < minfee)
            code = txINSUFFICIENT_FEE;
    }
    if (code == txSUCCESS) {
        src = get_account(env, t->src);
        if (!src)
            return -1;
        if (!src->st.exists)
            code = txNO_ACCOUNT;
        else {
            if (touch(env, src, 1) < 0) /* load_account records */
                return -1;
            if (src->st.seqNum == INT64_MAXV ||
                t->seqNum != src->st.seqNum + 1)
                code = txBAD_SEQ;
            else if (!check_sig(t->sigs, t->nsigs, &t->vs, src, t->src,
                                0 /* LOW */))
                code = txBAD_AUTH;
        }
    }

    int pre_seq = (code == txTOO_EARLY || code == txTOO_LATE ||
                   code == txMISSING_OPERATION ||
                   code == txINSUFFICIENT_FEE || code == txNO_ACCOUNT ||
                   code == txBAD_SEQ);
    if (!pre_seq) {
        if (src->st.seqNum > t->seqNum) {
            /* Python raises -> txINTERNAL_ERROR, tx txn rolled back */
            rollback_level(env, 1);
            t->out_have = 1;
            t->out_code = txINTERNAL_ERROR;
            t->out_empty_txch = 1;
            t->out_meta_ops = 0;
            t->out_res_ops = 0;
            return 0;
        }
        if (touch(env, src, 1) < 0)
            return -1;
        src->st.seqNum = t->seqNum;
    }

    int sigs_ok = 1;
    if (code == txSUCCESS) {
        /* processSignatures: every op's source at its threshold level.
           Any op-level failure leaves sibling result slots unset in the
           Python frame (unserializable mix) — bail to the oracle. */
        for (i = 0; i < t->nops; i++) {
            Op *o = &t->ops[i];
            const uint8_t *osrc = o->has_src ? o->src : t->src;
            Entry *oa = get_account(env, osrc);
            if (!oa)
                return -1;
            if (!check_sig(t->sigs, t->nsigs, &t->vs,
                           oa->st.exists ? oa : NULL, osrc,
                           op_threshold_level(o))) {
                env_bail(env, "op-auth");
                return -1;
            }
        }
        /* _remove_one_time_signer: no pre-auth signers on this path */
        for (i = 0; i < t->nsigs; i++)
            if (!t->sigs[i].used) {
                sigs_ok = 0;
                break;
            }
    }

    if (delta_changes_buf(env, 1, &t->txch) < 0)
        return -1;
    if (commit_level(env, 1) < 0)
        return -1;

    if (code != txSUCCESS || !sigs_ok) {
        t->out_have = 1;
        t->out_code = (code != txSUCCESS) ? code : txBAD_AUTH_EXTRA;
        t->out_meta_ops = 0;
        t->out_res_ops = 0;
        return 0;
    }

    /* ops phase: every op applies in its own nested txn; any failure
       rolls the whole ops txn back (fees/seq already committed) */
    t->opres = arena_alloc(&env->ar, t->nops * sizeof(OpRes));
    t->opch = arena_alloc(&env->ar, t->nops * sizeof(Buf));
    if (!t->opres || !t->opch) {
        env->oom = 1;
        return -1;
    }
    memset(t->opres, 0, t->nops * sizeof(OpRes));
    memset(t->opch, 0, t->nops * sizeof(Buf));
    t->opres_in_arena = 1;
    for (i = 0; i < t->nops; i++) {
        t->opch[i].ar = &env->ar;
        t->opres[i].payload.ar = &env->ar;
    }
    int ok = 1;
    /* header.idPool is transactional in Python (each nested LedgerTxn
       copies the header): a failed op/ops-phase must roll back any ids
       its offers consumed */
    int64_t tx_idpool = c->idPool;
    for (i = 0; i < t->nops; i++) {
        Op *op = &t->ops[i];
        const uint8_t *osrc = op->has_src ? op->src : t->src;
        int64_t t_op = now_ticks();
        int64_t op_idpool = c->idPool;
        int rc = apply_one_op(env, op, osrc, &t->opres[i]);
        if (rc < 0)
            return -1;
        int op_ok =
            (t->opres[i].code == opINNER && t->opres[i].inner_code == 0);
        if (op_ok) {
            if (delta_changes_buf(env, 3, &t->opch[i]) < 0)
                return -1;
            if (commit_level(env, 3) < 0)
                return -1;
        } else {
            rollback_level(env, 3);
            c->idPool = op_idpool;
            ok = 0;
        }
        if (op->optype >= 0 && op->optype < MAX_OPTYPES) {
            env->op_cnt[op->optype]++;
            env->op_ns[op->optype] += now_ticks() - t_op; /* ticks:
                converted to ns once per close (see apply_close) */
        }
    }
    if (ok) {
        if (commit_level(env, 2) < 0 || commit_level(env, 1) < 0)
            return -1;
    } else {
        rollback_level(env, 2);
        c->idPool = tx_idpool;
    }
    t->out_have = 1;
    t->out_code = ok ? txSUCCESS : txFAILED;
    t->out_ok = ok;
    t->out_meta_ops = t->nops;
    t->out_res_ops = t->nops;
    return 0;
}

/* FeeBumpTransactionFrame.apply: outer commonValid (reads only,
   rolled back), then the inner tx applies as a plain v1 tx whose
   results carry feeCharged 0; the wrapper is built at emission. */
static int emit_result(Tx *t, Buf *b);
static int emit_meta(Tx *t, Buf *b);

/* pre-emit the tx's result/meta XDR on the applying thread */
static int tx_preemit(AEnv *env, Tx *t)
{
    t->out_rb.ar = &env->ar;
    t->out_mb.ar = &env->ar;
    if (emit_result(t, &t->out_rb) < 0 || emit_meta(t, &t->out_mb) < 0) {
        env->oom = 1;
        return -1;
    }
    return 0;
}

static int apply_tx(AEnv *env, Tx *t)
{
    if (!t->is_fee_bump) {
        if (apply_tx_v1(env, t, t->feeCharged) < 0)
            return -1;
        return tx_preemit(env, t);
    }

    Ctx *c = env->c;
    int i;
    for (i = 0; i < t->nsigs; i++)
        t->sigs[i].used = 0;
    int code = txSUCCESS;
    if (c->ledgerVersion < 13)
        code = txNOT_SUPPORTED; /* fee bumps are CAP-0015 / protocol 13 */
    else {
        __int128 minfee = (__int128)c->baseFee * (t->inner->nops + 1);
        if ((__int128)t->fee_bid < minfee ||
            t->fee_bid < t->inner->fee_bid)
            code = txINSUFFICIENT_FEE;
    }
    if (code == txSUCCESS) {
        Entry *src = get_account(env, t->src);
        if (!src)
            return -1;
        if (!src->st.exists)
            code = txNO_ACCOUNT;
        else if (!check_sig(t->sigs, t->nsigs, &t->vs, src, t->src,
                            0 /* LOW */))
            code = txBAD_AUTH;
        else {
            for (i = 0; i < t->nsigs; i++)
                if (!t->sigs[i].used) {
                    code = txBAD_AUTH_EXTRA;
                    break;
                }
        }
    }
    if (code != txSUCCESS) {
        /* outer failure: no inner apply, no state mutated, empty meta */
        t->out_have = 1;
        t->out_code = code;
        t->out_empty_txch = 1;
        t->out_meta_ops = 0;
        t->out_res_ops = 0;
        return tx_preemit(env, t);
    }
    if (apply_tx_v1(env, t->inner, 0) < 0)
        return -1;
    t->out_have = 1;
    t->out_code = (t->inner->out_code == txSUCCESS)
                      ? txFEE_BUMP_INNER_SUCCESS
                      : txFEE_BUMP_INNER_FAILED;
    return tx_preemit(env, t);
}

/* ------------------------------------------------------------ fee phase */

/* processFeeSeqNum for every tx in apply order (v10+: fees only; the
   sequence number is consumed during apply). Emits the per-tx
   fee-changes blob (the txfeehistory row). */
static int fee_phase(AEnv *env, Tx **txs, int ntx, Buf *fee_bufs)
{
    Ctx *c = env->c;
    int ti;
    for (ti = 0; ti < ntx; ti++) {
        Tx *t = txs[ti];
        int nops_for_fee =
            t->is_fee_bump ? t->inner->nops + 1
                           : (t->nops > 1 ? t->nops : 1);
        __int128 fee128 = (__int128)c->effBase * nops_for_fee;
        int64_t fee = fee128 > (__int128)t->fee_bid ? t->fee_bid
                                                    : (int64_t)fee128;
        Entry *src = get_account(env, t->src);
        if (!src)
            return -1;
        if (!src->st.exists) {
            env_bail(env, "fee-source-missing"); /* Python asserts */
            return -1;
        }
        if (touch(env, src, 1) < 0)
            return -1;
        int64_t cap = src->st.balance > 0 ? src->st.balance : 0;
        if (fee > cap)
            fee = cap;
        src->st.balance -= fee;
        c->feePool += fee;
        t->feeCharged = fee;
        fee_bufs[ti].ar = &env->ar;
        if (delta_changes_buf(env, 1, &fee_bufs[ti]) < 0)
            return -1;
        if (commit_level(env, 1) < 0)
            return -1;
    }
    return 0;
}

/* ----------------------------------------------- static keys / prefetch */

/* load every statically-knowable entry one tx can touch (apply +
   signature phases), so a GIL-free apply never needs the lookup
   callback. Returns -1 on engine error only; dynamic ops contribute
   their statically-known keys too (cheap cache warm). */
static int prefetch_tx_v1(AEnv *env, Tx *t)
{
    int i;
    if (!get_account(env, t->src))
        return -1;
    for (i = 0; i < t->nops; i++) {
        Op *op = &t->ops[i];
        const uint8_t *osrc = op->has_src ? op->src : t->src;
        if (!get_account(env, osrc))
            return -1;
        switch (op->optype) {
        case OP_CREATE_ACCOUNT:
            if (!get_account(env, op->dest))
                return -1;
            break;
        case OP_PAYMENT:
            if (!get_account(env, op->dest))
                return -1;
            if (!op->asset_native) {
                /* the issuer account is only ever read when it IS the
                   op source or destination (apply_payment's issuer
                   arms) — both already enumerated; a blanket issuer
                   key would chain every same-asset payment into one
                   conflict cluster for no reason */
                if (memcmp(osrc, op->issuer, 32) != 0 &&
                    !get_trustline(env, osrc, op->asset, op->assetlen))
                    return -1;
                if (memcmp(op->dest, op->issuer, 32) != 0 &&
                    !get_trustline(env, op->dest, op->asset,
                                   op->assetlen))
                    return -1;
            }
            break;
        case OP_SET_OPTIONS:
            if (op->so_has_infl && !get_account(env, op->so_infl))
                return -1;
            break;
        case OP_CHANGE_TRUST:
            if (!get_account(env, op->issuer) ||
                !get_trustline(env, osrc, op->asset, op->assetlen))
                return -1;
            break;
        case OP_ALLOW_TRUST: {
            uint8_t asset[MAX_ASSET];
            memcpy(asset, op->at_asset, op->at_assetlen);
            memcpy(asset + op->at_assetlen - 32, osrc, 32);
            if (!get_trustline(env, op->at_trustor, asset,
                               op->at_assetlen))
                return -1;
            break;
        }
        case OP_ACCOUNT_MERGE:
            if (!get_account(env, op->dest))
                return -1;
            break;
        case OP_MANAGE_DATA:
            if (!get_data(env, osrc, op->md_name, op->md_name_len))
                return -1;
            break;
        case OP_PATH_PAYMENT_RECV:
        case OP_PATH_PAYMENT_SEND:
            if (op_version_supported(env->c, op->optype) &&
                !get_account(env, op->dest))
                return -1;
            break;
        default:
            break;
        }
    }
    return 0;
}

static int prefetch_tx(AEnv *env, Tx *t)
{
    if (!get_account(env, t->src)) /* fee source / tx source */
        return -1;
    if (t->is_fee_bump)
        return prefetch_tx_v1(env, t->inner);
    return prefetch_tx_v1(env, t);
}

/* gather the statically-knowable signer additions across the txset */
static int collect_static_adds_v1(Ctx *c, Tx *t)
{
    int i;
    for (i = 0; i < t->nops; i++) {
        Op *op = &t->ops[i];
        if (op->optype == OP_SET_OPTIONS && op->so_has_signer) {
            const uint8_t *osrc = op->has_src ? op->src : t->src;
            if (sadd_push(c, osrc, op->so_signer_key) < 0)
                return -1;
        }
    }
    return 0;
}

/* candidate collection for one tx (and its inner, for fee bumps) */
static int collect_tx_candidates(AEnv *env, Tx *t)
{
    int i;
    if (vset_collect(env, &t->vs, t->sigs, t->nsigs, t->src) < 0)
        return -1;
    Tx *v1 = t->is_fee_bump ? t->inner : t;
    if (t->is_fee_bump &&
        vset_collect(env, &v1->vs, v1->sigs, v1->nsigs, v1->src) < 0)
        return -1;
    for (i = 0; i < v1->nops; i++) {
        Op *op = &v1->ops[i];
        const uint8_t *osrc = op->has_src ? op->src : v1->src;
        if (vset_collect(env, &v1->vs, v1->sigs, v1->nsigs, osrc) < 0)
            return -1;
    }
    return 0;
}

/* one verify() callback for every candidate pair in the close */
static int preverify_all(Ctx *c, AEnv *env, Tx **txs, int ntx)
{
    PyObject *lst = PyList_New(0);
    int ti;
    if (!lst) {
        c->pyerr = 1;
        return -1;
    }
    for (ti = 0; ti < ntx; ti++) {
        Tx *t = txs[ti];
        if (collect_tx_candidates(env, t) < 0)
            goto fail;
        if (vset_append_batch(c, lst, &t->vs, t->sigs, t->hash) < 0)
            goto fail;
        if (t->is_fee_bump &&
            vset_append_batch(c, lst, &t->inner->vs, t->inner->sigs,
                              t->inner->hash) < 0)
            goto fail;
    }
    if (PyList_GET_SIZE(lst) == 0) {
        Py_DECREF(lst);
        return 0;
    }
    PyObject *res = PyObject_CallFunctionObjArgs(c->verify, lst, NULL);
    Py_DECREF(lst);
    lst = NULL;
    if (!res) {
        c->pyerr = 1;
        return -1;
    }
    PyObject *seq = PySequence_Fast(res, "verify() must return a sequence");
    Py_DECREF(res);
    if (!seq) {
        c->pyerr = 1;
        return -1;
    }
    Py_ssize_t pos = 0;
    for (ti = 0; ti < ntx; ti++) {
        Tx *t = txs[ti];
        if (vset_read_results(c, seq, &pos, &t->vs) < 0)
            goto fail_seq;
        if (t->is_fee_bump &&
            vset_read_results(c, seq, &pos, &t->inner->vs) < 0)
            goto fail_seq;
    }
    if (pos != PySequence_Fast_GET_SIZE(seq)) {
        ctx_bail(c, "verify-shape");
        goto fail_seq;
    }
    Py_DECREF(seq);
    return 0;
fail_seq:
    Py_DECREF(seq);
    return -1;
fail:
    Py_XDECREF(lst);
    return -1;
}

/* ------------------------------------------------- conflict clustering */

/* the same static-key walk as prefetch, but recording Entry pointers
   (pure hash hits after prefetch — the GIL is still held, so a stray
   miss is handled, not fatal) */
static int tx_entries_v1(AEnv *env, Tx *t, EList *out)
{
    int i;
    Entry *e;
#define REC(expr)                                                        \
    do {                                                                 \
        e = (expr);                                                      \
        if (!e)                                                          \
            return -1;                                                   \
        if (elist_push(out, e) < 0) {                                    \
            env->oom = 1;                                                \
            return -1;                                                   \
        }                                                                \
    } while (0)
    REC(get_account(env, t->src));
    for (i = 0; i < t->nops; i++) {
        Op *op = &t->ops[i];
        const uint8_t *osrc = op->has_src ? op->src : t->src;
        REC(get_account(env, osrc));
        switch (op->optype) {
        case OP_CREATE_ACCOUNT:
        case OP_ACCOUNT_MERGE:
            REC(get_account(env, op->dest));
            break;
        case OP_PAYMENT:
            REC(get_account(env, op->dest));
            if (!op->asset_native) {
                /* issuer key omitted: read only in the issuer-source/
                   issuer-dest arms, whose account is already recorded */
                if (memcmp(osrc, op->issuer, 32) != 0)
                    REC(get_trustline(env, osrc, op->asset,
                                      op->assetlen));
                if (memcmp(op->dest, op->issuer, 32) != 0)
                    REC(get_trustline(env, op->dest, op->asset,
                                      op->assetlen));
            }
            break;
        case OP_SET_OPTIONS:
            if (op->so_has_infl)
                REC(get_account(env, op->so_infl));
            break;
        case OP_CHANGE_TRUST:
            REC(get_account(env, op->issuer));
            REC(get_trustline(env, osrc, op->asset, op->assetlen));
            break;
        case OP_ALLOW_TRUST: {
            uint8_t asset[MAX_ASSET];
            memcpy(asset, op->at_asset, op->at_assetlen);
            memcpy(asset + op->at_assetlen - 32, osrc, 32);
            REC(get_trustline(env, op->at_trustor, asset,
                              op->at_assetlen));
            break;
        }
        case OP_MANAGE_DATA:
            REC(get_data(env, osrc, op->md_name, op->md_name_len));
            break;
        default:
            break;
        }
    }
#undef REC
    return 0;
}

static int tx_entries(AEnv *env, Tx *t, EList *out)
{
    Entry *e = get_account(env, t->src);
    if (!e)
        return -1;
    if (elist_push(out, e) < 0) {
        env->oom = 1;
        return -1;
    }
    return tx_entries_v1(env, t->is_fee_bump ? t->inner : t, out);
}

static int uf_find(int *parent, int x)
{
    while (parent[x] != x) {
        parent[x] = parent[parent[x]];
        x = parent[x];
    }
    return x;
}

static void uf_union(int *parent, int a, int b)
{
    a = uf_find(parent, a);
    b = uf_find(parent, b);
    if (a != b)
        parent[b < a ? a : b] = b < a ? b : a; /* smaller index wins */
}

/* one parallel worker: applies its assigned txs (grouped by cluster,
   ascending tx index within each) on a private journal. Pure C — the
   GIL is released; any Python need trips the nopy bail. */
typedef struct {
    AEnv env;
    Tx **txs;    /* global tx array */
    int *order;  /* tx indices this worker applies, in order */
    int n;
    int failed;
} Worker;

static void *worker_main(void *arg)
{
    Worker *w = (Worker *)arg;
    /* run on a STACK-local env: the Worker array is contiguous, and
       the per-op attribution counters are written on every op — false
       sharing across adjacent workers' cache lines costs ~5x per-op
       when they live in the shared array */
    AEnv env = w->env;
    int k;
    /* buffers built here record &env.ar (this stack frame); the arena
       HEAD is copied back into w->env below and only ever freed through
       it — buf_free never dereferences the stale pointer */
    for (k = 0; k < w->n; k++) {
        if (ctx_aborted(env.c))
            break;
        int ti = w->order[k];
        env.txidx = ti;
        env.ord0 = 0;
        if (apply_tx(&env, w->txs[ti]) < 0) {
            w->failed = 1;
            ctx_abort(env.c);
            break;
        }
    }
    w->env = env;
    return NULL;
}

static int cmp_order0(const void *pa, const void *pb)
{
    const Entry *a = *(Entry *const *)pa;
    const Entry *b = *(Entry *const *)pb;
    if (a->order0 < b->order0)
        return -1;
    if (a->order0 > b->order0)
        return 1;
    return 0;
}

/* merge one finished env back into the context (op attribution +
   bail state); level-0 lists are merged separately (sorted) */
static void env_merge(AEnv *dst, const AEnv *src)
{
    int i;
    for (i = 0; i < MAX_OPTYPES; i++) {
        dst->op_cnt[i] += src->op_cnt[i];
        dst->op_ns[i] += src->op_ns[i];
    }
    if (src->bail && !dst->bail) {
        dst->bail = 1;
        dst->bailmsg = src->bailmsg;
        if (src->bailmsg == src->bailbuf) {
            memcpy(dst->bailbuf, src->bailbuf, sizeof(dst->bailbuf));
            dst->bailmsg = dst->bailbuf;
        }
    }
    if (src->oom)
        dst->oom = 1;
}

static void env_free_lists(AEnv *env)
{
    int i;
    for (i = 0; i < MAXLEVEL; i++)
        free(env->lv[i].v);
    arena_free_all(&env->ar);
}

/* ------------------------------------------------ persistent worker pool
 *
 * pthread_create costs ~200µs under sandboxed kernels — several ms per
 * close at 8 workers, which would eat the whole parallel win. The pool
 * threads persist for the process lifetime (detached; they park on the
 * condvar between closes and die with the process). */
static struct {
    pthread_mutex_t mu;
    pthread_cond_t work_cv, done_cv;
    Worker *ws;
    int n, next, done;
    uint64_t gen;
    int nthreads;
    int inited;
} POOL = {PTHREAD_MUTEX_INITIALIZER, PTHREAD_COND_INITIALIZER,
          PTHREAD_COND_INITIALIZER, NULL, 0, 0, 0, 0, 0, 0};

static void *pool_thread(void *arg)
{
    uint64_t my_gen = (uint64_t)(uintptr_t)arg;
    pthread_mutex_lock(&POOL.mu);
    for (;;) {
        /* brief lock-free spin before blocking: condvar wakeups are
           slow syscalls under sandboxed kernels, and back-to-back
           closes re-dispatch within microseconds */
        pthread_mutex_unlock(&POOL.mu);
        for (int spin = 0; spin < 400000; spin++) {
            if (__atomic_load_n(&POOL.gen, __ATOMIC_ACQUIRE) != my_gen)
                break;
        }
        pthread_mutex_lock(&POOL.mu);
        while (POOL.gen == my_gen || POOL.ws == NULL)
            pthread_cond_wait(&POOL.work_cv, &POOL.mu);
        my_gen = POOL.gen;
        while (POOL.next < POOL.n) {
            Worker *w = &POOL.ws[POOL.next++];
            pthread_mutex_unlock(&POOL.mu);
            worker_main(w);
            pthread_mutex_lock(&POOL.mu);
            POOL.done++;
            if (POOL.done == POOL.n)
                pthread_cond_signal(&POOL.done_cv);
        }
    }
    return NULL;
}

/* run all workers on the pool; returns 0, or -1 if threads could not
   be spawned (caller falls back to serial). Call with the GIL released. */
static int pool_run(Worker *ws, int n)
{
    pthread_mutex_lock(&POOL.mu);
    while (POOL.nthreads < n && POOL.nthreads < MAX_WORKERS) {
        pthread_t t;
        pthread_attr_t at;
        pthread_attr_init(&at);
        pthread_attr_setdetachstate(&at, PTHREAD_CREATE_DETACHED);
        if (pthread_create(&t, &at, pool_thread,
                           (void *)(uintptr_t)POOL.gen) != 0) {
            pthread_attr_destroy(&at);
            break;
        }
        pthread_attr_destroy(&at);
        POOL.nthreads++;
    }
    if (POOL.nthreads == 0) {
        pthread_mutex_unlock(&POOL.mu);
        return -1;
    }
    POOL.ws = ws;
    POOL.n = n;
    POOL.next = 0;
    POOL.done = 0;
    /* pool threads spin on gen OUTSIDE the mutex (atomic acquire
       loads); the publishing store must be atomic too — a plain
       increment racing those loads is a TSan-reportable data race.
       The mutex still orders the plain gen reads in pool_thread. */
    __atomic_store_n(&POOL.gen, POOL.gen + 1, __ATOMIC_RELEASE);
    pthread_cond_broadcast(&POOL.work_cv);
    while (POOL.done < POOL.n)
        pthread_cond_wait(&POOL.done_cv, &POOL.mu);
    POOL.ws = NULL;
    pthread_mutex_unlock(&POOL.mu);
    return 0;
}

/* -------------------------------------------------------------- emission */

/* TransactionResult XDR for one applied v1 tx (fee bumps wrap this) */
static int emit_v1_result(Tx *t, int64_t fee, Buf *b)
{
    int i;
    if (buf_i64(b, fee) < 0 || buf_i32(b, t->out_code) < 0)
        return -1;
    if (t->out_code == txSUCCESS || t->out_code == txFAILED) {
        if (buf_u32(b, (uint32_t)t->out_res_ops) < 0)
            return -1;
        for (i = 0; i < t->out_res_ops; i++) {
            OpRes *r = &t->opres[i];
            if (buf_i32(b, r->code) < 0)
                return -1;
            if (r->code != opINNER)
                continue;
            if (buf_i32(b, r->optype) < 0 ||
                buf_i32(b, r->inner_code) < 0)
                return -1;
            if (r->has_payload &&
                buf_put(b, r->payload.data, r->payload.len) < 0)
                return -1;
        }
    }
    if (buf_u32(b, 0) < 0) /* TransactionResult ext */
        return -1;
    return 0;
}

static int emit_result(Tx *t, Buf *b)
{
    if (!t->is_fee_bump)
        return emit_v1_result(t, t->feeCharged, b);
    if (buf_i64(b, t->feeCharged) < 0 || buf_i32(b, t->out_code) < 0)
        return -1;
    if (t->out_code == txFEE_BUMP_INNER_SUCCESS ||
        t->out_code == txFEE_BUMP_INNER_FAILED) {
        /* InnerTransactionResultPair: inner hash + inner result (the
           inner's feeCharged is 0 — FeeBumpTransactionFrame.apply
           initializes it so) */
        if (buf_put(b, t->inner->hash, 32) < 0 ||
            emit_v1_result(t->inner, 0, b) < 0)
            return -1;
    }
    if (buf_u32(b, 0) < 0)
        return -1;
    return 0;
}

/* TransactionMeta v1 from the stored changes blobs */
static int emit_meta(Tx *t, Buf *b)
{
    Tx *v1 = t->is_fee_bump ? t->inner : t;
    if (buf_u32(b, 1) < 0) /* TransactionMeta disc v1 */
        return -1;
    if (t->is_fee_bump && !v1->out_have) {
        /* outer envelope failed: inner never applied — empty meta */
        return (buf_u32(b, 0) < 0 || buf_u32(b, 0) < 0) ? -1 : 0;
    }
    if (v1->out_empty_txch || v1->txch.len == 0) {
        if (buf_u32(b, 0) < 0)
            return -1;
    } else if (buf_put(b, v1->txch.data, v1->txch.len) < 0)
        return -1;
    if (buf_u32(b, (uint32_t)v1->out_meta_ops) < 0)
        return -1;
    for (int i = 0; i < v1->out_meta_ops; i++) {
        if (v1->out_ok && v1->opch && v1->opch[i].len) {
            if (buf_put(b, v1->opch[i].data, v1->opch[i].len) < 0)
                return -1;
        } else if (buf_u32(b, 0) < 0)
            return -1;
    }
    return 0;
}

static PyObject *buf_to_pybytes(Buf *b)
{
    return PyBytes_FromStringAndSize(b->data ? b->data : "", b->len);
}

/* ----------------------------------------------------------- the close */

static int params_i64(PyObject *params, const char *name, int64_t *out,
                      int required, int64_t dflt)
{
    PyObject *v = PyDict_GetItemString(params, name);
    if (!v) {
        if (!required) {
            *out = dflt;
            return 0;
        }
        PyErr_Format(PyExc_KeyError, "params missing %s", name);
        return -1;
    }
    *out = PyLong_AsLongLong(v);
    if (*out == -1 && PyErr_Occurred())
        return -1;
    return 0;
}

static PyObject *apply_close(PyObject *self, PyObject *args)
{
    PyObject *params, *envs, *hashes, *lookup, *verify, *book_cb,
        *acct_cb, *opts = Py_None;
    if (!PyArg_ParseTuple(args, "OOOOOOO|O", &params, &envs, &hashes,
                          &lookup, &verify, &book_cb, &acct_cb, &opts))
        return NULL;

    Ctx c;
    memset(&c, 0, sizeof(c));
    c.lookup = lookup;
    c.verify = verify;
    c.book_cb = book_cb;
    c.acct_cb = acct_cb;

    int64_t v;
    if (params_i64(params, "ledgerVersion", &v, 1, 0) < 0)
        return NULL;
    c.ledgerVersion = (uint32_t)v;
    if (params_i64(params, "ledgerSeq", &v, 1, 0) < 0)
        return NULL;
    c.ledgerSeq = (uint32_t)v;
    if (params_i64(params, "closeTime", &v, 1, 0) < 0)
        return NULL;
    c.closeTime = (uint64_t)v;
    if (params_i64(params, "baseFee", &c.baseFee, 1, 0) < 0 ||
        params_i64(params, "baseReserve", &c.baseReserve, 1, 0) < 0 ||
        params_i64(params, "effBaseFee", &c.effBase, 1, 0) < 0 ||
        params_i64(params, "feePool", &c.feePool, 1, 0) < 0 ||
        params_i64(params, "idPool", &c.idPool, 1, 0) < 0)
        return NULL;
    if (params_i64(params, "inflationSeq", &v, 0, 0) < 0)
        return NULL;
    c.inflationSeq = (uint32_t)v;

    if (c.ledgerVersion < 10) /* pre-10 fee/seq semantics: Python path */
        Py_RETURN_NONE;

    int workers = 0, force_serial = 0, force_parallel = 0;
    if (opts != Py_None && PyDict_Check(opts)) {
        PyObject *w = PyDict_GetItemString(opts, "workers");
        if (w) {
            workers = (int)PyLong_AsLong(w);
            if (workers == -1 && PyErr_Occurred())
                return NULL;
        }
        PyObject *m = PyDict_GetItemString(opts, "mode");
        if (m && PyUnicode_Check(m)) {
            const char *ms = PyUnicode_AsUTF8(m);
            if (!ms)
                return NULL;
            if (strcmp(ms, "serial") == 0)
                force_serial = 1;
            else if (strcmp(ms, "parallel") == 0)
                force_parallel = 1;
        }
    }
    if (workers <= 0)
        workers = 1;
    if (workers > MAX_WORKERS)
        workers = MAX_WORKERS;

    Py_ssize_t ntx = PySequence_Length(envs);
    if (ntx < 0)
        return NULL;
    if (PySequence_Length(hashes) != ntx) {
        PyErr_SetString(PyExc_ValueError, "envs/hashes length mismatch");
        return NULL;
    }

    Tx *txs = calloc(ntx ? ntx : 1, sizeof(Tx));
    Tx **txp = calloc(ntx ? ntx : 1, sizeof(Tx *));
    Buf *fee_bufs = calloc(ntx ? ntx : 1, sizeof(Buf));
    if (!txs || !txp || !fee_bufs) {
        free(txs);
        free(txp);
        free(fee_bufs);
        return PyErr_NoMemory();
    }

    AEnv env0;
    memset(&env0, 0, sizeof(env0));
    env0.c = &c;

    PyObject *results = NULL, *fee_changes = NULL, *metas = NULL;
    PyObject *changes = NULL, *out = NULL;
    int bailing = 0, any_dynamic = 0, used_parallel = 0;
    int nclusters = 0, max_cluster = 0, nworkers_used = 1;
    Py_ssize_t ti;
    int i;
    int *parent = NULL, *cl_of = NULL, *cl_sizes = NULL, *cl_order = NULL;
    int *tx_by_cluster = NULL, *cl_off = NULL;
    Worker *ws = NULL;

    /* ---- parse every envelope up front: one unsupported tx fails the
       whole close over to Python BEFORE any state mutates */
    for (ti = 0; ti < ntx; ti++) {
        PyObject *env = PySequence_GetItem(envs, ti);
        PyObject *h = PySequence_GetItem(hashes, ti);
        if (!env || !h || !PyBytes_Check(env) || !PyBytes_Check(h)) {
            Py_XDECREF(env);
            Py_XDECREF(h);
            if (!PyErr_Occurred())
                ctx_bail(&c, "input-shape");
            else
                c.pyerr = 1;
            goto done;
        }
        txp[ti] = &txs[ti];
        int rc = parse_envelope(
            &c, (const uint8_t *)PyBytes_AS_STRING(env),
            PyBytes_GET_SIZE(env), (const uint8_t *)PyBytes_AS_STRING(h),
            PyBytes_GET_SIZE(h), h, &txs[ti]);
        /* envs/hashes lists own the buffers the parsed tx borrows
           (sig/hash pointers); the caller holds the lists alive */
        Py_DECREF(env);
        Py_DECREF(h);
        if (rc < 0) {
            if (!c.pyerr && !c.bail)
                ctx_bail(&c, "envelope");
            goto done;
        }
        if (txs[ti].dynamic)
            any_dynamic = 1;
    }

    /* statically-knowable signer additions feed the pre-verify superset */
    for (ti = 0; ti < ntx; ti++) {
        Tx *t = txp[ti];
        if (collect_static_adds_v1(&c, t->is_fee_bump ? t->inner : t) < 0)
            goto done;
    }

    /* ---- prefetch + pre-verify (GIL held, Python callbacks allowed).
       Fully-static closes skip the dedicated prefetch walk: the
       clustering pass below enumerates the same keys through the same
       lazy-loading accessors, so one walk does both jobs. */
    if (any_dynamic) {
        for (ti = 0; ti < ntx; ti++) {
            if (prefetch_tx(&env0, txp[ti]) < 0 || env0.bail ||
                env0.oom || c.pyerr || c.bail)
                goto done;
        }
    }
    if (preverify_all(&c, &env0, txp, (int)ntx) < 0 || env0.bail ||
        env0.oom || c.pyerr || c.bail)
        goto done;

    /* ---- phase 1: fees, serial and in tx order */
    if (fee_phase(&env0, txp, (int)ntx, fee_bufs) < 0 || env0.bail ||
        env0.oom || c.pyerr || c.bail)
        goto done;

    /* ---- phase 2: apply. Conflict clustering first (cheap), so even
       the serial path reports cluster telemetry. */
    int64_t cal_ns0 = now_ns(), cal_t0 = now_ticks();
    int64_t apply_phase_ns = 0; /* the tx-execution wall (phase 2 only:
        cluster scheduling + apply), the quantity the conflict-graph
        parallelism accelerates — parse/verify/fees/emission excluded */
    int want_parallel = !force_serial && !any_dynamic && ntx > 1 &&
                        (workers > 1 || force_parallel);
    if (ntx > 0 && !any_dynamic) {
        parent = malloc(ntx * sizeof(int));
        cl_of = malloc(ntx * sizeof(int));
        cl_sizes = calloc(ntx, sizeof(int));
        if (!parent || !cl_of || !cl_sizes) {
            env0.oom = 1;
            goto done;
        }
        for (ti = 0; ti < ntx; ti++)
            parent[ti] = (int)ti;
        EList keys = {NULL, 0, 0};
        for (ti = 0; ti < ntx; ti++) {
            keys.n = 0;
            if (tx_entries(&env0, txp[ti], &keys) < 0 || env0.bail ||
                env0.oom || c.pyerr || c.bail) {
                free(keys.v);
                goto done;
            }
            for (i = 0; i < keys.n; i++) {
                Entry *e = keys.v[i];
                if (e->uf_tx < 0)
                    e->uf_tx = (int)ti;
                else
                    uf_union(parent, e->uf_tx, (int)ti);
            }
        }
        free(keys.v);
        /* label clusters 0..n-1 by first-seen root */
        for (ti = 0; ti < ntx; ti++)
            cl_of[ti] = -1;
        for (ti = 0; ti < ntx; ti++) {
            int root = uf_find(parent, (int)ti);
            if (cl_of[root] < 0)
                cl_of[root] = nclusters++;
            cl_of[ti] = cl_of[root];
            cl_sizes[cl_of[ti]]++;
        }
        for (i = 0; i < nclusters; i++)
            if (cl_sizes[i] > max_cluster)
                max_cluster = cl_sizes[i];
    }

    if (want_parallel && nclusters > 1) {
        /* group tx indices by cluster (ascending within each) */
        cl_off = calloc(nclusters + 1, sizeof(int));
        tx_by_cluster = malloc(ntx * sizeof(int));
        cl_order = malloc(nclusters * sizeof(int));
        if (!cl_off || !tx_by_cluster || !cl_order) {
            env0.oom = 1;
            goto done;
        }
        for (i = 0; i < nclusters; i++)
            cl_off[i + 1] = cl_off[i] + cl_sizes[i];
        {
            int *fill = calloc(nclusters, sizeof(int));
            if (!fill) {
                env0.oom = 1;
                goto done;
            }
            for (ti = 0; ti < ntx; ti++) {
                int cl = cl_of[ti];
                tx_by_cluster[cl_off[cl] + fill[cl]++] = (int)ti;
            }
            free(fill);
        }
        /* LPT: clusters descending by size onto the least-loaded worker */
        for (i = 0; i < nclusters; i++)
            cl_order[i] = i;
        for (i = 1; i < nclusters; i++) { /* insertion sort, desc */
            int k = cl_order[i], j = i;
            while (j > 0 && cl_sizes[cl_order[j - 1]] < cl_sizes[k]) {
                cl_order[j] = cl_order[j - 1];
                j--;
            }
            cl_order[j] = k;
        }
        nworkers_used = workers < nclusters ? workers : nclusters;
        ws = calloc(nworkers_used, sizeof(Worker));
        if (!ws) {
            env0.oom = 1;
            goto done;
        }
        int64_t *load = calloc(nworkers_used, sizeof(int64_t));
        int *wcount = calloc(nworkers_used, sizeof(int));
        int *assign = malloc(nclusters * sizeof(int));
        if (!load || !wcount || !assign) {
            free(load);
            free(wcount);
            free(assign);
            env0.oom = 1;
            goto done;
        }
        for (i = 0; i < nclusters; i++) {
            int best = 0, w;
            for (w = 1; w < nworkers_used; w++)
                if (load[w] < load[best])
                    best = w;
            assign[cl_order[i]] = best;
            load[best] += cl_sizes[cl_order[i]];
            wcount[best] += cl_sizes[cl_order[i]];
        }
        int w, ok = 1;
        for (w = 0; w < nworkers_used; w++) {
            ws[w].env.c = &c;
            ws[w].env.use_local0 = 1;
            ws[w].txs = txp;
            ws[w].order = malloc((wcount[w] ? wcount[w] : 1) *
                                 sizeof(int));
            if (!ws[w].order) {
                ok = 0;
                break;
            }
            ws[w].n = 0;
        }
        if (ok) {
            /* clusters in LPT order so each worker's stream is fixed */
            for (i = 0; i < nclusters; i++) {
                int cl = cl_order[i];
                int w2 = assign[cl];
                for (int k = cl_off[cl]; k < cl_off[cl + 1]; k++)
                    ws[w2].order[ws[w2].n++] = tx_by_cluster[k];
            }
        }
        free(load);
        free(wcount);
        free(assign);
        if (!ok) {
            env0.oom = 1;
            goto done;
        }

        c.nopy = 1;
        int pool_rc;
        int64_t t_apply0 = now_ns();
        Py_BEGIN_ALLOW_THREADS
        pool_rc = pool_run(ws, nworkers_used);
        Py_END_ALLOW_THREADS
        apply_phase_ns = now_ns() - t_apply0;
        c.nopy = 0;
        if (pool_rc != 0) {
            ctx_bail(&c, "thread-spawn");
            goto done;
        }
        used_parallel = 1;
        /* merge: attribution + failure flags, then the stamped level-0
           entries back into serial first-touch order */
        EList all0 = {NULL, 0, 0};
        for (w = 0; w < nworkers_used; w++) {
            env_merge(&env0, &ws[w].env);
            for (i = 0; i < ws[w].env.lv[0].n; i++)
                if (elist_push(&all0, ws[w].env.lv[0].v[i]) < 0) {
                    env0.oom = 1;
                    break;
                }
        }
        if (env0.bail || env0.oom || c.bail || c.pyerr) {
            free(all0.v);
            goto done;
        }
        if (all0.n) /* UBSan: qsort base must be non-null even for n==0 */
            qsort(all0.v, all0.n, sizeof(Entry *), cmp_order0);
        for (i = 0; i < all0.n; i++)
            if (elist_push(&c.closed0, all0.v[i]) < 0) {
                env0.oom = 1;
                break;
            }
        free(all0.v);
        if (env0.oom)
            goto done;
    } else {
        /* serial apply — GIL-free when the whole txset is static */
        int64_t t_apply0 = now_ns();
        if (!any_dynamic) {
            c.nopy = 1;
            int failed = 0;
            Py_BEGIN_ALLOW_THREADS
            for (ti = 0; ti < ntx; ti++) {
                env0.txidx = (int)ti;
                if (apply_tx(&env0, txp[ti]) < 0) {
                    failed = 1;
                    break;
                }
            }
            Py_END_ALLOW_THREADS
            c.nopy = 0;
            if (failed || env0.bail || env0.oom || c.bail || c.pyerr)
                goto done;
        } else {
            for (ti = 0; ti < ntx; ti++) {
                env0.txidx = (int)ti;
                if (apply_tx(&env0, txp[ti]) < 0 || env0.bail ||
                    env0.oom || c.bail || c.pyerr)
                    goto done;
            }
        }
        apply_phase_ns = now_ns() - t_apply0;
    }

    /* convert the per-op tick attribution to nanoseconds against the
       apply phase's CLOCK_MONOTONIC bracket */
    {
        int64_t cal_ns1 = now_ns(), cal_t1 = now_ticks();
        if (cal_t1 > cal_t0 && cal_ns1 > cal_ns0) {
            double scale = (double)(cal_ns1 - cal_ns0) /
                           (double)(cal_t1 - cal_t0);
            for (i = 0; i < MAX_OPTYPES; i++)
                env0.op_ns[i] = (int64_t)(env0.op_ns[i] * scale);
        }
    }

    /* ---- outputs */
    results = PyList_New(0);
    fee_changes = PyList_New(0);
    metas = PyList_New(0);
    changes = PyList_New(0);
    if (!results || !fee_changes || !metas || !changes) {
        c.pyerr = 1;
        goto done;
    }
    for (ti = 0; ti < ntx; ti++) {
        Tx *t = txp[ti];
        PyObject *o;
        o = buf_to_pybytes(&t->out_rb);
        if (!o || PyList_Append(results, o) < 0) {
            Py_XDECREF(o);
            c.pyerr = 1;
            goto done;
        }
        Py_DECREF(o);
        o = buf_to_pybytes(&t->out_mb);
        if (!o || PyList_Append(metas, o) < 0) {
            Py_XDECREF(o);
            c.pyerr = 1;
            goto done;
        }
        Py_DECREF(o);
        o = buf_to_pybytes(&fee_bufs[ti]);
        if (!o || PyList_Append(fee_changes, o) < 0) {
            Py_XDECREF(o);
            c.pyerr = 1;
            goto done;
        }
        Py_DECREF(o);
    }

    /* close-level changed entries, serial first-touch order */
    for (i = 0; i < c.closed0.n; i++) {
        Entry *e = c.closed0.v[i];
        EntrySave *s = &e->save[0];
        if (mut_eq(&e->st, &s->st))
            continue;
        PyObject *key = PyBytes_FromStringAndSize((const char *)e->keyb,
                                                  e->keylen);
        PyObject *prev = NULL, *cur = NULL;
        if (key && s->st.exists) {
            Buf b = {NULL, 0, 0};
            if (ser_entry(e, &s->st, &b) == 0)
                prev = PyBytes_FromStringAndSize(b.data, b.len);
            buf_free(&b);
        } else if (key) {
            prev = Py_None;
            Py_INCREF(prev);
        }
        if (key && prev && e->st.exists) {
            Buf b = {NULL, 0, 0};
            if (ser_entry(e, &e->st, &b) == 0)
                cur = PyBytes_FromStringAndSize(b.data, b.len);
            buf_free(&b);
        } else if (key && prev) {
            cur = Py_None;
            Py_INCREF(cur);
        }
        PyObject *tup = (key && prev && cur)
                            ? PyTuple_Pack(3, key, prev, cur)
                            : NULL;
        Py_XDECREF(key);
        Py_XDECREF(prev);
        Py_XDECREF(cur);
        if (!tup || PyList_Append(changes, tup) < 0) {
            Py_XDECREF(tup);
            c.pyerr = 1;
            goto done;
        }
        Py_DECREF(tup);
    }

    {
        PyObject *op_stats = PyDict_New();
        if (!op_stats) {
            c.pyerr = 1;
            goto done;
        }
        for (i = 0; i < MAX_OPTYPES; i++) {
            if (!env0.op_cnt[i])
                continue;
            PyObject *k = PyLong_FromLong(i);
            PyObject *v2 =
                Py_BuildValue("(LL)", (long long)env0.op_cnt[i],
                              (long long)env0.op_ns[i]);
            if (!k || !v2 || PyDict_SetItem(op_stats, k, v2) < 0) {
                Py_XDECREF(k);
                Py_XDECREF(v2);
                Py_DECREF(op_stats);
                c.pyerr = 1;
                goto done;
            }
            Py_DECREF(k);
            Py_DECREF(v2);
        }
        out = Py_BuildValue(
            "{s:L,s:L,s:O,s:O,s:O,s:O,s:O,"
            "s:{s:i,s:i,s:i,s:i,s:L}}",
            "feePool", (long long)c.feePool, "idPool",
            (long long)c.idPool, "changes", changes, "results", results,
            "fee_changes", fee_changes, "meta", metas, "op_stats",
            op_stats, "clusters", "count", nclusters, "max_txs",
            max_cluster, "parallel", used_parallel, "workers",
            used_parallel ? nworkers_used : 1, "apply_ns",
            (long long)apply_phase_ns);
        Py_DECREF(op_stats);
        if (!out)
            c.pyerr = 1;
    }

done:
    if (env0.bail && !c.bailmsg) {
        if (env0.bailmsg == env0.bailbuf) {
            memcpy(c.bailbuf, env0.bailbuf, sizeof(c.bailbuf));
            c.bailmsg = c.bailbuf;
        } else
            c.bailmsg = env0.bailmsg;
        c.bail = 1;
    }
    if (env0.bail)
        c.bail = 1;
    if (env0.oom && !c.pyerr && !PyErr_Occurred())
        PyErr_NoMemory();
    if (env0.oom)
        c.pyerr = 1;
    bailing = c.bail && !c.pyerr;
    for (ti = 0; ti < ntx; ti++)
        tx_free(&txs[ti]);
    free(txs);
    free(txp);
    for (ti = 0; ti < (fee_bufs ? ntx : 0); ti++)
        buf_free(&fee_bufs[ti]);
    free(fee_bufs);
    free(parent);
    free(cl_of);
    free(cl_sizes);
    free(cl_order);
    free(tx_by_cluster);
    free(cl_off);
    if (ws) {
        for (i = 0; i < nworkers_used; i++) {
            free(ws[i].order);
            env_free_lists(&ws[i].env);
        }
        free(ws);
    }
    env_free_lists(&env0);
    Py_XDECREF(results);
    Py_XDECREF(fee_changes);
    Py_XDECREF(metas);
    Py_XDECREF(changes);
    ctx_free(&c);
    if (c.pyerr) {
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_RuntimeError, "native apply failed");
        return NULL;
    }
    if (bailing)
        return Py_BuildValue("{s:s}", "bail",
                             c.bailmsg ? c.bailmsg : "unsupported");
    if (!out)
        Py_RETURN_NONE;
    return out;
}

static PyMethodDef methods[] = {
    {"apply_close", apply_close, METH_VARARGS,
     "apply_close(params, envs, hashes, lookup, verify, book, "
     "acct_offers[, opts]) -> dict | None"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_sctapply",
    "Native transaction-apply fast path (see module docstring in source).",
    -1, methods,
};

PyMODINIT_FUNC PyInit__sctapply(void)
{
    return PyModule_Create(&moduledef);
}
