"""StrKey: human-readable base32 key encoding with version byte + CRC16.

Role parity: reference `src/crypto/StrKey.cpp` (G... account IDs, S... seeds,
T/X... pre-auth/hash-x signers).
"""

from __future__ import annotations

import base64
import struct


class StrKeyVersion:
    PUBKEY = 6 << 3       # 'G'
    SEED = 18 << 3        # 'S'
    PRE_AUTH_TX = 19 << 3  # 'T'
    HASH_X = 23 << 3      # 'X'


def _crc16_xmodem(data: bytes) -> int:
    crc = 0
    for byte in data:
        crc ^= byte << 8
        for _ in range(8):
            crc = ((crc << 1) ^ 0x1021) if crc & 0x8000 else (crc << 1)
            crc &= 0xFFFF
    return crc


def encode(version: int, payload: bytes) -> str:
    body = bytes([version]) + payload
    chk = struct.pack("<H", _crc16_xmodem(body))
    return base64.b32encode(body + chk).decode("ascii").rstrip("=")


def decode(version: int, s: str) -> bytes:
    pad = "=" * ((8 - len(s) % 8) % 8)
    raw = base64.b32decode(s + pad)
    if len(raw) < 3:
        raise ValueError("strkey too short")
    body, chk = raw[:-2], raw[-2:]
    if struct.pack("<H", _crc16_xmodem(body)) != chk:
        raise ValueError("strkey checksum mismatch")
    if body[0] != version:
        raise ValueError("strkey wrong version byte")
    return body[1:]


def encode_public_key(raw32: bytes) -> str:
    return encode(StrKeyVersion.PUBKEY, raw32)


def decode_public_key(s: str) -> bytes:
    v = decode(StrKeyVersion.PUBKEY, s)
    if len(v) != 32:
        raise ValueError("bad public key length")
    return v


def encode_seed(raw32: bytes) -> str:
    return encode(StrKeyVersion.SEED, raw32)


def decode_seed(s: str) -> bytes:
    v = decode(StrKeyVersion.SEED, s)
    if len(v) != 32:
        raise ValueError("bad seed length")
    return v
