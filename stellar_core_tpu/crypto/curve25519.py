"""X25519 ECDH for peer session keys.

Role parity: reference `src/crypto/Curve25519.{h,cpp}:47-71` — random scalar,
derive public, ECDH → HKDF shared key; used by overlay PeerAuth.
"""

from __future__ import annotations

import os

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey, X25519PublicKey,
)

from .hashing import hkdf_expand, hkdf_extract


def curve25519_random_secret() -> bytes:
    sk = X25519PrivateKey.generate()
    return sk.private_bytes(serialization.Encoding.Raw,
                            serialization.PrivateFormat.Raw,
                            serialization.NoEncryption())


def curve25519_derive_public(secret32: bytes) -> bytes:
    sk = X25519PrivateKey.from_private_bytes(secret32)
    return sk.public_key().public_bytes(serialization.Encoding.Raw,
                                        serialization.PublicFormat.Raw)


def curve25519_derive_shared(local_secret32: bytes, remote_public32: bytes,
                             public_a: bytes, public_b: bytes) -> bytes:
    """ECDH then HKDF-extract over (shared ‖ publicA ‖ publicB) — the caller
    fixes the A/B ordering so both sides derive the same key
    (reference Curve25519.cpp:47-71)."""
    sk = X25519PrivateKey.from_private_bytes(local_secret32)
    shared = sk.exchange(X25519PublicKey.from_public_bytes(remote_public32))
    return hkdf_extract(shared + public_a + public_b)


def hkdf_expand_key(key32: bytes, info: bytes) -> bytes:
    return hkdf_expand(key32, info, 32)


def curve25519_seal(recipient_public32: bytes, plaintext: bytes) -> bytes:
    """Anonymous sealed box (libsodium crypto_box_seal role, reference
    SurveyManager encrypted responses): ephemeral X25519 + ChaCha20-
    Poly1305, key = HKDF(ECDH ‖ epk ‖ recipient), nonce derived from the
    public halves. Output: epk(32) ‖ ciphertext."""
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from .hashing import sha256
    esk = curve25519_random_secret()
    epk = curve25519_derive_public(esk)
    key = curve25519_derive_shared(esk, recipient_public32, epk,
                                   recipient_public32)
    nonce = sha256(b"sealed-box-nonce" + epk + recipient_public32)[:12]
    return epk + ChaCha20Poly1305(key).encrypt(nonce, plaintext, b"")


def curve25519_unseal(secret32: bytes, blob: bytes) -> bytes:
    """Inverse of curve25519_seal; raises on tamper/garbage."""
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from .hashing import sha256
    epk, ct = blob[:32], blob[32:]
    pub = curve25519_derive_public(secret32)
    key = curve25519_derive_shared(secret32, epk, epk, pub)
    nonce = sha256(b"sealed-box-nonce" + epk + pub)[:12]
    return ChaCha20Poly1305(key).decrypt(nonce, ct, b"")
