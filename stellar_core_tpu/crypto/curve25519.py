"""X25519 ECDH for peer session keys.

Role parity: reference `src/crypto/Curve25519.{h,cpp}:47-71` — random scalar,
derive public, ECDH → HKDF shared key; used by overlay PeerAuth.
"""

from __future__ import annotations

import os

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey, X25519PublicKey,
)

from .hashing import hkdf_expand, hkdf_extract


def curve25519_random_secret() -> bytes:
    sk = X25519PrivateKey.generate()
    return sk.private_bytes(serialization.Encoding.Raw,
                            serialization.PrivateFormat.Raw,
                            serialization.NoEncryption())


def curve25519_derive_public(secret32: bytes) -> bytes:
    sk = X25519PrivateKey.from_private_bytes(secret32)
    return sk.public_key().public_bytes(serialization.Encoding.Raw,
                                        serialization.PublicFormat.Raw)


def curve25519_derive_shared(local_secret32: bytes, remote_public32: bytes,
                             public_a: bytes, public_b: bytes) -> bytes:
    """ECDH then HKDF-extract over (shared ‖ publicA ‖ publicB) — the caller
    fixes the A/B ordering so both sides derive the same key
    (reference Curve25519.cpp:47-71)."""
    sk = X25519PrivateKey.from_private_bytes(local_secret32)
    shared = sk.exchange(X25519PublicKey.from_public_bytes(remote_public32))
    return hkdf_extract(shared + public_a + public_b)


def hkdf_expand_key(key32: bytes, info: bytes) -> bytes:
    return hkdf_expand(key32, info, 32)
