"""X25519 ECDH for peer session keys.

Role parity: reference `src/crypto/Curve25519.{h,cpp}:47-71` — random scalar,
derive public, ECDH → HKDF shared key; used by overlay PeerAuth.
"""

from __future__ import annotations

import os

try:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey,
    )
except ImportError:  # hermetic container: crypto/fallback.py supplies
    # the same X25519 + AEAD primitives (native C or pure Python)
    serialization = X25519PrivateKey = X25519PublicKey = None

from .hashing import hkdf_expand, hkdf_extract


def _aead_cls():
    try:
        from cryptography.hazmat.primitives.ciphers.aead import (
            ChaCha20Poly1305,
        )
        return ChaCha20Poly1305
    except ImportError:
        from .fallback import ChaCha20Poly1305
        return ChaCha20Poly1305


def curve25519_random_secret() -> bytes:
    if X25519PrivateKey is None:
        return os.urandom(32)
    sk = X25519PrivateKey.generate()
    return sk.private_bytes(serialization.Encoding.Raw,
                            serialization.PrivateFormat.Raw,
                            serialization.NoEncryption())


def curve25519_derive_public(secret32: bytes) -> bytes:
    if X25519PrivateKey is None:
        from .fallback import x25519_public
        return x25519_public(secret32)
    sk = X25519PrivateKey.from_private_bytes(secret32)
    return sk.public_key().public_bytes(serialization.Encoding.Raw,
                                        serialization.PublicFormat.Raw)


def curve25519_derive_shared(local_secret32: bytes, remote_public32: bytes,
                             public_a: bytes, public_b: bytes) -> bytes:
    """ECDH then HKDF-extract over (shared ‖ publicA ‖ publicB) — the caller
    fixes the A/B ordering so both sides derive the same key
    (reference Curve25519.cpp:47-71)."""
    if X25519PrivateKey is None:
        from .fallback import x25519_shared
        shared = x25519_shared(local_secret32, remote_public32)
    else:
        sk = X25519PrivateKey.from_private_bytes(local_secret32)
        shared = sk.exchange(
            X25519PublicKey.from_public_bytes(remote_public32))
    return hkdf_extract(shared + public_a + public_b)


def hkdf_expand_key(key32: bytes, info: bytes) -> bytes:
    return hkdf_expand(key32, info, 32)


def curve25519_seal(recipient_public32: bytes, plaintext: bytes) -> bytes:
    """Anonymous sealed box (libsodium crypto_box_seal role, reference
    SurveyManager encrypted responses): ephemeral X25519 + ChaCha20-
    Poly1305, key = HKDF(ECDH ‖ epk ‖ recipient), nonce derived from the
    public halves. Output: epk(32) ‖ ciphertext."""
    ChaCha20Poly1305 = _aead_cls()
    from .hashing import sha256
    esk = curve25519_random_secret()
    epk = curve25519_derive_public(esk)
    key = curve25519_derive_shared(esk, recipient_public32, epk,
                                   recipient_public32)
    nonce = sha256(b"sealed-box-nonce" + epk + recipient_public32)[:12]
    return epk + ChaCha20Poly1305(key).encrypt(nonce, plaintext, b"")


def curve25519_unseal(secret32: bytes, blob: bytes) -> bytes:
    """Inverse of curve25519_seal; raises on tamper/garbage."""
    ChaCha20Poly1305 = _aead_cls()
    from .hashing import sha256
    epk, ct = blob[:32], blob[32:]
    pub = curve25519_derive_public(secret32)
    key = curve25519_derive_shared(secret32, epk, epk, pub)
    nonce = sha256(b"sealed-box-nonce" + epk + pub)[:12]
    return ChaCha20Poly1305(key).decrypt(nonce, ct, b"")
