"""BatchHasher: the config-gated batched-SHA-256 boundary (ISSUE 12).

Crypto verify moved to the device in PRs 1/10; every hash in the
measured close wall — txset hashing, bucket hashing, result-set
hashing, header hashing — stayed serial host `hashlib.sha256`
(`crypto/hashing.py`). This module is the hashing twin of
`crypto/batch_verifier.py`: same bucketed-batch-shape machinery, same
persistent-XLA-cache AOT warmup, same circuit-breaker degradation, its
own cockpit (`HasherStats`, admin `hasher` endpoint) — the
accelerator-side proof-pipeline direction of ACE Runtime (PAPERS.md,
2603.10242) and SZKP's batched-hash accelerator (2408.05890).

The boundary has two call shapes, because SHA-256 has two traffic
shapes in a ledger close:

    hash_many(msgs, site)   -> [digest]   (one digest PER message: the
        bucket entry-leaf blocks the Merkle state commitment absorbs by
        the thousand — the device-batchable load, one padded fixed-shape
        dispatch per bucket of lanes)
    hash_stream(chunks, site) -> digest   (ONE digest over a
        concatenated stream: txset contents, result sets, bucket file
        identity, header bytes — sequential by construction, served on
        the host but streamed through bounded join groups so peak
        memory stays flat and per-chunk Python overhead is amortized)

Backends:
- CpuBatchHasher — hashlib per message; the default and the fallback.
- TpuBatchHasher — ships message batches to the JAX SHA-256 kernel
  (ops/sha256.py) in padded (lanes × blocks) bucket shapes so the
  kernel compiles once per shape; oversize messages split out to the
  host (`hasher.oversize`). Multi-chunk drains double-buffer host
  padding + host→device transfer on the `crypto.hash-staging` worker
  while the device runs the previous chunk.
- ResilientBatchHasher — circuit breaker between a primary (device)
  backend and the CPU fallback: N consecutive dispatch failures trip to
  the fallback for a cooldown window with a half-open reprobe, so a
  lost device degrades hashing throughput instead of killing a close.
  Digests are SHA-256 on both sides, so a mid-drain trip is
  byte-invisible to consensus (pinned by tests/test_batch_hasher.py).

Fault sites (docs/robustness.md): `hash.device-lost` fires inside the
device backend's drain (the dispatch raises as if the device vanished;
the breaker counts it), `hash.dispatch-fail` fires in the resilient
layer before the primary dispatch (the device-agnostic failure the
chaos soaks arm).

Threading: `hash_many` device dispatches run on the caller's thread
(the close path — main loop — and the admin proof path, which posts to
main); only the short-lived staging job (`crypto.hash-staging`) and
the startup warmup thread (`crypto.hash-warmup`) leave it, and both
touch host buffers + JAX state only — never ledger/consensus objects.
Both spawn through util.threads.spawn_worker under registered names,
so the static T1 walk follows them like any Thread(target=...) site.
Bucket-identity hashing from the merge worker pool stays on the plain
`stream_digest` host path below (no shared device state).
"""

from __future__ import annotations

import hashlib
import threading
from typing import List, Optional, Sequence

from ..util.log import get_logger
from ..util.metrics import MetricsRegistry
from ..util.threads import TrackedLock, spawn_worker
from ..util.timer import real_monotonic
from ..util.tracing import tracer_instant
from .batch_verifier import CircuitBreaker

log = get_logger("Perf")

# bounded join group for streamed digests: one C-level update per ~1 MiB
# keeps per-chunk Python overhead amortized AND peak memory flat on
# large txsets/buckets (the ISSUE 12 result-set streaming fix)
_STREAM_GROUP_BYTES = 1 << 20

# the cockpit's bounded call-site ladder: every hash drain is attributed
# to the close-path site that issued it (docs/observability.md#hash-cockpit)
KNOWN_SITES = ("txset", "result-set", "header", "bucket-entries",
               "bench", "other")


def stream_digest(chunks) -> bytes:
    """One SHA-256 over an iterable of byte chunks, grouped into bounded
    joins (see _STREAM_GROUP_BYTES). The registry-free hot path for
    bucket identity hashing on the merge worker pool; the app-level
    boundary (`hash_stream`) wraps this with cockpit attribution."""
    h = hashlib.sha256()
    buf: List[bytes] = []
    size = 0
    for c in chunks:
        buf.append(c)
        size += len(c)
        if size >= _STREAM_GROUP_BYTES:
            h.update(b"".join(buf))
            buf = []
            size = 0
    if buf:
        h.update(b"".join(buf))
    return h.digest()


class HasherStats:
    """Cockpit aggregation for the batch-hash boundary — the fourth
    cockpit, same pattern as VerifierStats / ApplyStats / OverlayStats:
    ONE instance per make_hasher() stack, shared by every layer so
    drains are attributed to the backend that actually SERVED them, and
    the same aggregates feed the admin `hasher` endpoint (`to_json`),
    the metrics registry (`hasher.*`, scrapeable via
    `metrics?format=prometheus`) and the tracer.

    Clocks: event stamps read the injected app clock (`now_fn`), warmup
    compile DURATIONS read util.timer.real_monotonic (sanctioned: an
    XLA compile takes real time under a frozen virtual clock).
    Recording happens on the caller's thread, the staging worker and
    the warmup thread under `_lock`; registry metric objects are
    individually thread-safe."""

    def __init__(self, metrics=None, tracer=None, now_fn=None,
                 flight_recorder=None) -> None:
        self._now = now_fn or real_monotonic
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.tracer = tracer
        self.flight_recorder = flight_recorder
        self._lock = TrackedLock("crypto.hasher-stats")
        self.backends: dict = {}   # name -> {drains, msgs, bytes, pad_blocks}
        self.buckets: dict = {}    # "LxB" -> counts + histograms
        self.sites: dict = {}      # site -> {drains, msgs, bytes}
        self.oversize = 0
        self.staging = {"chunks": 0, "staged_s": 0.0, "overlap_s": 0.0,
                        "last_overlap_pct": None, "stalls": 0}
        self.warmup = {"state": "idle", "planned": [], "begun_t": None,
                       "done_t": None, "error": None, "shapes": {}}
        self.compile_cache = {"enabled": None, "dir": None, "hits": 0,
                              "misses": 0, "unknown": 0, "error": None}
        m = self.metrics
        self._h_batch = m.new_histogram("hasher.drain.batch-size")
        self._h_bytes = m.new_histogram("hasher.drain.bytes")
        self._h_pad = m.new_histogram("hasher.drain.pad-waste")
        self._h_occ = m.new_histogram("hasher.drain.occupancy-pct")
        self._h_splits = m.new_histogram("hasher.drain.splits")
        self._g_overlap = m.new_gauge("hasher.staging.overlap-pct")
        self._g_wstate = m.new_gauge("hasher.warmup.state")
        self._g_wdone = m.new_gauge("hasher.warmup.shapes-done")
        self._h_wsec = m.new_histogram("hasher.warmup.shape-seconds")
        self._g_cc = m.new_gauge("hasher.compile-cache.enabled")
        self._c_hit = m.new_counter("hasher.compile-cache.hit")
        self._c_miss = m.new_counter("hasher.compile-cache.miss")

    # -- drains --------------------------------------------------------------
    def record_drain(self, backend: str, msgs: int, nbytes: int,
                     pad_blocks: int = 0, real_blocks: int = 0,
                     splits: int = 1) -> None:
        """One hash_many drain attributed to the serving backend.
        `pad_blocks` is the total padding waste in 64-byte block units
        across every padded dispatch of the drain (structurally 0 on
        host drains); occupancy is real blocks over padded capacity."""
        total = real_blocks + pad_blocks
        occ = 100.0 * real_blocks / total if total else 100.0
        with self._lock:
            d = self.backends.setdefault(
                backend, {"drains": 0, "msgs": 0, "bytes": 0,
                          "pad_blocks": 0})
            d["drains"] += 1
            d["msgs"] += msgs
            d["bytes"] += nbytes
            d["pad_blocks"] += pad_blocks
        self._h_batch.update(msgs)
        self._h_bytes.update(nbytes)
        self._h_pad.update(pad_blocks)
        self._h_occ.update(occ)
        self._h_splits.update(splits)
        self.metrics.new_meter("hasher.drains.%s" % backend).mark()

    def record_bucket_dispatch(self, lanes: int, blocks: int, msgs: int,
                               real_blocks: int) -> None:
        """One padded device dispatch into the fixed (lanes × blocks)
        shape — names come from the backend's static ladder, so the
        dynamic `hasher.bucket.<b>.*` name space stays bounded."""
        key = "%dx%d" % (lanes, blocks)
        cap = lanes * blocks
        pad = cap - real_blocks
        occ = 100.0 * real_blocks / cap if cap else 100.0
        with self._lock:
            b = self.buckets.get(key)
            if b is None:
                b = self.buckets[key] = {
                    "dispatches": 0, "msgs": 0, "pad_blocks": 0,
                    "_occ": self.metrics.new_histogram(
                        "hasher.bucket.%s.occupancy-pct" % key),
                    "_pad": self.metrics.new_histogram(
                        "hasher.bucket.%s.pad-waste" % key),
                    "_m": self.metrics.new_meter(
                        "hasher.bucket.%s.drains" % key)}
            b["dispatches"] += 1
            b["msgs"] += msgs
            b["pad_blocks"] += pad
        b["_occ"].update(occ)
        b["_pad"].update(pad)
        b["_m"].mark()

    def record_site(self, site: str, msgs: int, nbytes: int) -> None:
        """Close-path attribution: which hashing CONSUMER issued the
        drain. `site` comes from the bounded KNOWN_SITES ladder."""
        if site not in KNOWN_SITES:
            site = "other"
        with self._lock:
            s = self.sites.setdefault(site, {"drains": 0, "msgs": 0,
                                             "bytes": 0})
            s["drains"] += 1
            s["msgs"] += msgs
            s["bytes"] += nbytes
        self.metrics.new_meter("hasher.site.%s.drains" % site).mark()

    def record_oversize(self, n: int) -> None:
        """Messages whose padded block count exceeds the largest device
        shape: hashed on the host instead (split out of the dispatch)."""
        with self._lock:
            self.oversize += n
        self.metrics.new_meter("hasher.oversize").mark(n)

    # -- staging -------------------------------------------------------------
    def record_staging(self, staged_s: float, overlap_s: float,
                       chunks: int) -> None:
        pct = round(100.0 * overlap_s / staged_s, 1) if staged_s > 0 \
            else 100.0
        with self._lock:
            s = self.staging
            s["chunks"] += chunks
            s["staged_s"] = round(s["staged_s"] + staged_s, 6)
            s["overlap_s"] = round(s["overlap_s"] + overlap_s, 6)
            s["last_overlap_pct"] = pct
        self._g_overlap.set(pct)

    def record_staging_stall(self) -> None:
        with self._lock:
            self.staging["stalls"] += 1
        self.metrics.new_meter("hasher.staging.stall").mark()
        tracer_instant(self.tracer, "hasher.staging.stall", cat="crypto")

    # -- compile cache + warmup ---------------------------------------------
    def compile_cache_enabled(self, path: str) -> None:
        self.compile_cache.update(
            {"enabled": True, "dir": path, "error": None})
        self._g_cc.set(1)

    def compile_cache_error(self, err: str) -> None:
        self.compile_cache.update({"enabled": False, "error": err})
        self._g_cc.set(0)
        self.metrics.new_meter("hasher.compile-cache.unavailable").mark()
        tracer_instant(self.tracer, "hasher.compile-cache.unavailable",
                       cat="crypto", error=err)
        if self.flight_recorder is not None:
            self.flight_recorder.dump("hash-compile-cache-unavailable",
                                      extra={"error": err})

    WARMUP_STATE_CODE = {"idle": 0, "running": 1, "done": 2, "failed": 3}

    def warmup_begin(self, shapes) -> None:
        with self._lock:
            self.warmup.update({"state": "running", "begun_t": self._now(),
                                "done_t": None, "error": None,
                                "planned": ["%dx%d" % s for s in shapes]})
        self._g_wstate.set(self.WARMUP_STATE_CODE["running"])
        tracer_instant(self.tracer, "hasher.warmup.begin", cat="crypto",
                       shapes=["%dx%d" % s for s in shapes])

    def warmup_shape_done(self, shape, seconds: float, cache_hit) -> None:
        cache = ("hit" if cache_hit is True else
                 "miss" if cache_hit is False else "unknown")
        key = "%dx%d" % shape
        with self._lock:
            self.warmup["shapes"][key] = {
                "seconds": round(seconds, 3), "cache": cache,
                "t": self._now()}
            done = len(self.warmup["shapes"])
            self.compile_cache[
                {"hit": "hits", "miss": "misses",
                 "unknown": "unknown"}[cache]] += 1
        self._h_wsec.update(seconds)
        self._g_wdone.set(done)
        if cache_hit is True:
            self._c_hit.inc()
        elif cache_hit is False:
            self._c_miss.inc()
        tracer_instant(self.tracer, "hasher.warmup.shape", cat="crypto",
                       shape=key, seconds=round(seconds, 3), cache=cache)

    def warmup_done(self) -> None:
        with self._lock:
            self.warmup.update({"state": "done", "done_t": self._now()})
        self._g_wstate.set(self.WARMUP_STATE_CODE["done"])
        tracer_instant(self.tracer, "hasher.warmup.end", cat="crypto",
                       shapes=len(self.warmup["shapes"]))

    def warmup_failed(self, err: str) -> None:
        with self._lock:
            self.warmup.update({"state": "failed", "done_t": self._now(),
                                "error": err})
        self._g_wstate.set(self.WARMUP_STATE_CODE["failed"])
        self.metrics.new_meter("hasher.warmup.failure").mark()
        tracer_instant(self.tracer, "hasher.warmup.failed", cat="crypto",
                       error=err)
        if self.flight_recorder is not None:
            self.flight_recorder.dump("hash-warmup-failed",
                                      extra={"error": err})

    # -- export --------------------------------------------------------------
    def to_json(self) -> dict:
        with self._lock:
            backends = {k: dict(v) for k, v in self.backends.items()}
            buckets = {
                k: {"dispatches": d["dispatches"], "msgs": d["msgs"],
                    "pad_blocks_total": d["pad_blocks"],
                    "occupancy_pct": d["_occ"].snapshot(),
                    "pad_waste": d["_pad"].snapshot()}
                for k, d in sorted(self.buckets.items())}
            sites = {k: dict(v) for k, v in sorted(self.sites.items())}
            staging = dict(self.staging)
            warm = dict(self.warmup)
            warm["shapes"] = {k: dict(v)
                              for k, v in self.warmup["shapes"].items()}
            cc = dict(self.compile_cache)
            oversize = self.oversize
        return {
            "drains": {"by_backend": backends,
                       "batch_size": self._h_batch.snapshot(),
                       "bytes": self._h_bytes.snapshot(),
                       "pad_waste": self._h_pad.snapshot(),
                       "occupancy_pct": self._h_occ.snapshot(),
                       "splits": self._h_splits.snapshot()},
            "buckets": buckets,
            "sites": sites,
            "oversize_msgs": oversize,
            "staging": staging,
            "warmup": warm,
            "compile_cache": cc,
        }


class BatchHasher:
    """Abstract backend; see module docstring. `tracer`/`metrics`/
    `faults`/`stats` are installed by make_hasher; None keeps direct
    constructions (tests, bench children) silent."""

    name = "abstract"
    wants_warmup = False
    tracer = None
    metrics = None
    faults = None
    stats = None

    def _span(self, name: str, **tags):
        from ..util.tracing import tracer_span
        return tracer_span(self.tracer, name, cat="crypto", **tags)

    def hash_many(self, msgs: Sequence[bytes],
                  site: str = "other") -> List[bytes]:
        raise NotImplementedError

    def digest_one(self, data: bytes, site: str = "other") -> bytes:
        """Single-digest convenience (header hash, txset identity):
        always host-served — a one-lane device dispatch would pay the
        round trip for nothing — but attributed to the cockpit like any
        drain, so the close path's hashing is fully accounted."""
        if self.stats is not None:
            self.stats.record_site(site, 1, len(data))
            self.stats.record_drain("host-stream", 1, len(data))
        return hashlib.sha256(data).digest()

    def hash_stream(self, chunks, site: str = "other") -> bytes:
        """One digest over a concatenated stream (txset contents,
        result sets, bucket identity): sequential by construction, so
        it is served on the host via `stream_digest`'s bounded join
        groups — ONE implementation of the grouping algorithm, this
        wrapper only counts chunks/bytes for cockpit attribution under
        `site`."""
        counted = {"n": 0, "bytes": 0}

        def walk():
            for c in chunks:
                counted["n"] += 1
                counted["bytes"] += len(c)
                yield c

        out = stream_digest(walk())
        if self.stats is not None:
            self.stats.record_site(site, counted["n"], counted["bytes"])
            self.stats.record_drain("host-stream", counted["n"],
                                    counted["bytes"])
        return out


class CpuBatchHasher(BatchHasher):
    """Synchronous hashlib backend: the default and the breaker
    fallback."""

    name = "cpu"

    def hash_many(self, msgs: Sequence[bytes],
                  site: str = "other") -> List[bytes]:
        nbytes = sum(len(m) for m in msgs)
        with self._span("crypto.hash_many", backend=self.name,
                        site=site, n=len(msgs), bytes=nbytes):
            out = [hashlib.sha256(m).digest() for m in msgs]
            if self.stats is not None:
                self.stats.record_site(site, len(msgs), nbytes)
                self.stats.record_drain(self.name, len(msgs), nbytes)
            return out


class TpuBatchHasher(BatchHasher):
    """JAX batched backend over ops/sha256.py.

    Dispatch shapes are (lane bucket × block bucket) pairs from the
    static ladders below, so the kernel compiles once per shape and a
    drain of thousands of entry-leaf messages becomes a handful of
    fixed-shape device calls. Messages are stably sorted by block count
    before chunking so a chunk's block bucket fits its longest member
    tightly (pad waste is lanes-bucket rounding, not worst-case blocks);
    digests are returned in the caller's order. Oversize messages
    (beyond the largest block bucket) split out to the host and are
    counted (`hasher.oversize`).

    Double-buffered staging: while the device hashes chunk K, chunk K+1
    pads + device_puts on the `crypto.hash-staging` worker — same
    overlap contract (and stall fallback) as the verify fleet's staging.
    """

    name = "tpu"
    wants_warmup = True
    LANE_BUCKETS = (256, 1024, 4096)
    BLOCK_BUCKETS = (1, 2, 4, 8, 16)
    # shapes the AOT warmup compiles: the small-drain shape the live
    # close path uses plus the bulk entry-leaf shapes
    WARM_SHAPES = ((256, 2), (4096, 2), (4096, 4))
    CACHE_PERSIST_MIN_S = 0.5

    def __init__(self, compile_cache_dir: Optional[str] = None) -> None:
        self._compile_cache_dir = compile_cache_dir
        self._cache_path: Optional[str] = None
        self._warmed = False
        self._warmup_thread: Optional[threading.Thread] = None
        self._platform: Optional[str] = None

    # -- buckets -------------------------------------------------------------
    def _lane_bucket(self, n: int) -> int:
        for b in self.LANE_BUCKETS:
            if n <= b:
                return b
        return self.LANE_BUCKETS[-1]

    def _block_bucket(self, blocks: int) -> int:
        for b in self.BLOCK_BUCKETS:
            if blocks <= b:
                return b
        return self.BLOCK_BUCKETS[-1]

    # -- persistent compile cache (mirrors TpuSigVerifier) -------------------
    def _resolve_cache_dir(self) -> str:
        import os
        return self._compile_cache_dir or os.environ.get(
            "JAX_COMPILATION_CACHE_DIR") or os.path.expanduser(
            "~/.cache/stellar_core_tpu/jax_cache")

    def _enable_compile_cache(self) -> None:
        import os
        path = self._resolve_cache_dir()
        try:
            import jax
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              self.CACHE_PERSIST_MIN_S)
            self._cache_path = path
            if self.stats is not None:
                self.stats.compile_cache_enabled(path)
        except Exception as e:   # cache is an optimization, never fatal
            log.warning("hash compile cache unavailable: %s", e)
            if self.stats is not None:
                self.stats.compile_cache_error(repr(e))

    def _cache_entry_count(self) -> int:
        import os
        if self._cache_path is None:
            return -1
        try:
            n = 0
            for _dir, _sub, files in os.walk(self._cache_path):
                n += len(files)
            return n
        except OSError:
            return -1

    # -- warmup --------------------------------------------------------------
    def warmup(self, wait: bool = False) -> None:
        """AOT-compile every warm shape off the consensus path (startup
        background thread); idempotent."""
        if self._warmed:
            return
        if self._warmup_thread is None:
            self._warmup_thread = spawn_worker(
                "crypto.hash-warmup", self._hash_warmup_impl)
        if wait:
            self._warmup_thread.join()

    def _compile_shape(self, lanes: int, blocks: int) -> None:
        import numpy as np
        from ..ops.sha256 import hash_blocks_jit
        np.asarray(hash_blocks_jit(
            np.zeros((lanes, blocks, 16), np.uint32),
            np.ones((lanes,), np.int32)))

    def _hash_warmup_impl(self) -> None:
        st = self.stats
        try:
            self._enable_compile_cache()
            if st is not None:
                st.warmup_begin(self.WARM_SHAPES)
            for shape in self.WARM_SHAPES:
                before = self._cache_entry_count()
                t0 = real_monotonic()
                self._compile_shape(*shape)
                dt = real_monotonic() - t0
                after = self._cache_entry_count()
                if before < 0 or after < 0:
                    hit = None
                elif after > before:
                    hit = False
                elif dt >= self.CACHE_PERSIST_MIN_S:
                    hit = True
                else:
                    hit = None     # fast compile below the persistence
                    # threshold writes no entry either way
                if st is not None:
                    st.warmup_shape_done(shape, dt, hit)
            self._warmed = True
            if st is not None:
                st.warmup_done()
            log.info("hash kernel warmup complete (%d shapes)",
                     len(self.WARM_SHAPES))
        except Exception as e:
            log.warning("hash kernel warmup failed: %s", e)
            if st is not None:
                st.warmup_failed(repr(e))

    # -- staging + dispatch --------------------------------------------------
    def _stage_hash_chunk(self, msgs: Sequence[bytes],
                          lanes: int, blocks: int) -> dict:
        """Pad one chunk into its device shape and move it to the
        device; runs on the staging worker when double-buffered."""
        import jax
        from ..ops.sha256 import pad_messages_np
        words, counts = pad_messages_np(msgs, blocks)
        if len(msgs) < lanes:
            import numpy as np
            padw = np.zeros((lanes, blocks, 16), np.uint32)
            padw[:len(msgs)] = words
            padc = np.zeros((lanes,), np.int32)
            padc[:len(msgs)] = counts
            words, counts = padw, padc
        real_blocks = int(counts.sum())
        return {"words": jax.device_put(words),
                "counts": jax.device_put(counts),
                "n": len(msgs), "lanes": lanes, "blocks": blocks,
                "real_blocks": real_blocks}

    def hash_many(self, msgs: Sequence[bytes],
                  site: str = "other") -> List[bytes]:
        import numpy as np
        import jax
        from ..ops.sha256 import (
            blocks_for_len, digests_to_bytes, hash_blocks_jit,
        )
        if self._platform is None:
            self._platform = jax.devices()[0].platform
        if self.faults is not None:
            # the device vanishing mid-drain: the dispatch raises, the
            # resilient layer's breaker counts it and the drain
            # completes on the CPU fallback with identical digests
            self.faults.fire_point("hash.device-lost")
        nbytes = sum(len(m) for m in msgs)
        st = self.stats
        out: List[Optional[bytes]] = [None] * len(msgs)
        with self._span("crypto.hash_many", backend=self.name,
                        platform=self._platform, site=site,
                        n=len(msgs), bytes=nbytes) as sp:
            blocks = [blocks_for_len(len(m)) for m in msgs]
            max_dev = self.BLOCK_BUCKETS[-1]
            dev_idx = [i for i, b in enumerate(blocks) if b <= max_dev]
            over_idx = [i for i, b in enumerate(blocks) if b > max_dev]
            if over_idx:
                # oversize lanes hash on the host, split out of the
                # padded dispatch entirely
                if st is not None:
                    st.record_oversize(len(over_idx))
                for i in over_idx:
                    out[i] = hashlib.sha256(msgs[i]).digest()
            # stable sort by block count: a chunk's block bucket fits
            # its longest member tightly
            dev_idx.sort(key=lambda i: blocks[i])
            chunks: List[List[int]] = []
            k = 0
            while k < len(dev_idx):
                chunks.append(dev_idx[k:k + self.LANE_BUCKETS[-1]])
                k += len(chunks[-1])

            def route(idx_chunk):
                lanes = self._lane_bucket(len(idx_chunk))
                blk = self._block_bucket(
                    max(blocks[i] for i in idx_chunk))
                return lanes, blk

            pad_blocks = 0
            real_total = 0
            batches = 0
            staged_s = overlap_s = 0.0
            staged_chunks = 0
            staged = None
            if chunks:
                lanes, blk = route(chunks[0])
                staged = self._stage_hash_chunk(
                    [msgs[i] for i in chunks[0]], lanes, blk)
            for c in range(len(chunks)):
                job = None
                if c + 1 < len(chunks):
                    nl, nb = route(chunks[c + 1])
                    job = _HashStagingJob(
                        self, [msgs[i] for i in chunks[c + 1]], nl, nb)
                with self._span("crypto.hash.dispatch",
                                backend=self.name, n=staged["n"],
                                lanes=staged["lanes"],
                                blocks=staged["blocks"]):
                    dig_dev = hash_blocks_jit(staged["words"],
                                              staged["counts"])  # async
                    wait_t0 = real_monotonic()
                    dig = np.asarray(dig_dev)    # blocks on the device
                    wait_t1 = real_monotonic()
                raw = digests_to_bytes(dig[:staged["n"]])
                for i, d in zip(chunks[c], raw):
                    out[i] = d
                cap = staged["lanes"] * staged["blocks"]
                pad_blocks += cap - staged["real_blocks"]
                real_total += staged["real_blocks"]
                batches += 1
                if st is not None:
                    st.record_bucket_dispatch(
                        staged["lanes"], staged["blocks"], staged["n"],
                        staged["real_blocks"])
                if job is not None:
                    staged, s_s, o_s, stalled = job.result(wait_t0,
                                                           wait_t1)
                    if stalled:
                        if st is not None:
                            st.record_staging_stall()
                        nl, nb = route(chunks[c + 1])
                        staged = self._stage_hash_chunk(
                            [msgs[i] for i in chunks[c + 1]], nl, nb)
                    else:
                        staged_s += s_s
                        overlap_s += o_s
                        staged_chunks += 1
            sp.set_tag("batches", batches)
            sp.set_tag("pad_blocks", pad_blocks)
            sp.set_tag("oversize", len(over_idx))
            if staged_chunks:
                sp.set_tag("staging_overlap_pct", round(
                    100.0 * overlap_s / staged_s, 1) if staged_s > 0
                    else 100.0)
            if st is not None:
                if staged_chunks:
                    st.record_staging(staged_s, overlap_s, staged_chunks)
                st.record_site(site, len(msgs), nbytes)
                st.record_drain(self.name, len(msgs), nbytes,
                                pad_blocks=pad_blocks,
                                real_blocks=real_total,
                                splits=max(1, batches))
        return out  # type: ignore[return-value]


class _HashStagingJob:
    """One double-buffer staging unit: pads + device_puts hash chunk
    K+1 on the `crypto.hash-staging` worker while the dispatch thread
    waits on chunk K. Timing is util.timer.real_monotonic (sanctioned:
    host/device overlap is real elapsed time). A staging failure is
    reported as `stalled`; the caller re-stages synchronously so the
    drain always completes."""

    __slots__ = ("h", "msgs", "lanes", "blocks", "staged", "error",
                 "t0", "t1", "thread")

    def __init__(self, hasher: "TpuBatchHasher", msgs: Sequence[bytes],
                 lanes: int, blocks: int) -> None:
        self.h = hasher
        self.msgs = msgs
        self.lanes = lanes
        self.blocks = blocks
        self.staged = None
        self.error: Optional[Exception] = None
        self.t0 = self.t1 = 0.0
        self.thread = spawn_worker("crypto.hash-staging", self._run)

    def _run(self) -> None:
        self.t0 = real_monotonic()
        try:
            self.staged = self.h._stage_hash_chunk(
                self.msgs, self.lanes, self.blocks)
        except Exception as e:
            self.error = e
        self.t1 = real_monotonic()

    def result(self, wait_t0: float, wait_t1: float):
        self.thread.join()
        staged_s = max(0.0, self.t1 - self.t0)
        overlap_s = max(0.0, min(self.t1, wait_t1) -
                        max(self.t0, wait_t0))
        if self.error is not None:
            log.warning("hash staging stalled (%s); re-staging chunk "
                        "synchronously", self.error)
            return None, staged_s, overlap_s, True
        return self.staged, staged_s, overlap_s, False


class ResilientBatchHasher(BatchHasher):
    """Primary backend behind a circuit breaker, CPU fallback beside it
    (the same closed → open → half-open machinery as the verify
    breaker, on the same injected app clock). A raising primary records
    a failure and the drain re-runs on the fallback — digests are
    SHA-256 either way, so degradation is byte-invisible. A trip emits
    metrics + a flight dump; the first successful half-open probe emits
    the recover marker."""

    name = "resilient"

    def __init__(self, primary: BatchHasher, fallback: BatchHasher,
                 breaker: Optional[CircuitBreaker] = None) -> None:
        self.primary = primary
        self.fallback = fallback
        self.breaker = breaker or CircuitBreaker()
        self.breaker.on_trip = self._on_trip
        self.breaker.on_recover = self._on_recover
        self.flight_recorder = None   # installed by make_hasher

    # -- breaker events ------------------------------------------------------
    def _breaker_mark(self, event: str) -> None:
        if self.metrics is not None:
            self.metrics.new_meter("hasher.breaker.%s" % event).mark()
            self.metrics.new_counter("hasher.breaker.state").set_count(
                self.breaker.state_code())
        tracer_instant(self.tracer, "hasher.breaker.%s" % event,
                       cat="crypto", primary=self.primary.name,
                       failures=self.breaker.consecutive_failures)

    def _on_trip(self) -> None:
        log.warning("hash breaker TRIPPED: %d consecutive %s-dispatch "
                    "failures; falling back to %s for %.0fs",
                    self.breaker.consecutive_failures, self.primary.name,
                    self.fallback.name, self.breaker.cooldown_s)
        self._breaker_mark("trip")
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                "hash-breaker-trip",
                extra={"primary": self.primary.name,
                       "breaker": self.breaker.to_json()})

    def _on_recover(self) -> None:
        log.info("hash breaker recovered: %s backend healthy again",
                 self.primary.name)
        self._breaker_mark("recover")

    # -- delegation ----------------------------------------------------------
    @property
    def wants_warmup(self) -> bool:
        return self.primary.wants_warmup

    @property
    def inner(self) -> BatchHasher:
        return self.primary

    def warmup(self, wait: bool = False) -> None:
        w = getattr(self.primary, "warmup", None)
        if w is not None:
            w(wait)

    def hash_many(self, msgs: Sequence[bytes],
                  site: str = "other") -> List[bytes]:
        if self.breaker.allow():
            try:
                with self._span("crypto.hash_dispatch_primary",
                                backend=self.primary.name,
                                n=len(msgs)):
                    if self.faults is not None:
                        self.faults.fire_point("hash.dispatch-fail")
                    out = self.primary.hash_many(msgs, site=site)
                self.breaker.record_success()
                return out
            except Exception as e:
                if self.metrics is not None:
                    self.metrics.new_meter(
                        "hasher.dispatch-failure").mark()
                tripped = self.breaker.record_failure()
                if not tripped:
                    log.warning("%s hash dispatch failed (%s): %d/%d "
                                "toward breaker trip", self.primary.name,
                                e, self.breaker.consecutive_failures,
                                self.breaker.threshold)
        if self.metrics is not None:
            self.metrics.new_meter("hasher.fallback-drain").mark()
        with self._span("crypto.hash_fallback", backend=self.name,
                        served_by=self.fallback.name, n=len(msgs),
                        breaker=self.breaker.state):
            return self.fallback.hash_many(msgs, site=site)


def make_hasher(backend: str = "cpu", clock=None,
                compile_cache_dir: Optional[str] = None,
                metrics=None, tracer=None, faults=None,
                flight_recorder=None,
                breaker_threshold: int = 3,
                breaker_cooldown: float = 30.0) -> BatchHasher:
    """Config-gated backend selection (Config.HASH_BACKEND).

    The device backend ("tpu") is always wrapped in a
    ResilientBatchHasher with a CPU fallback; "cpu-resilient" wraps the
    CPU backend in the same breaker machinery so chaos runs exercise
    the hash failure domain on device-less containers. Every layer
    shares ONE HasherStats cockpit, so fallback drains are attributed
    to the backend that served them."""
    now_fn = clock.now if clock is not None else None
    stats = HasherStats(metrics=metrics, tracer=tracer, now_fn=now_fn,
                        flight_recorder=flight_recorder)

    def resilient(primary: BatchHasher) -> ResilientBatchHasher:
        primary.tracer = tracer
        primary.metrics = metrics
        primary.stats = stats
        primary.faults = faults
        fb = CpuBatchHasher()
        fb.tracer = tracer
        fb.metrics = metrics
        fb.stats = stats
        r = ResilientBatchHasher(
            primary, fb,
            CircuitBreaker(threshold=breaker_threshold,
                           cooldown_s=breaker_cooldown, now_fn=now_fn))
        r.tracer = tracer
        r.flight_recorder = flight_recorder
        r.stats = stats
        return r

    if backend == "cpu":
        h: BatchHasher = CpuBatchHasher()
    elif backend == "cpu-resilient":
        h = resilient(CpuBatchHasher())
    elif backend == "tpu":
        h = resilient(TpuBatchHasher(compile_cache_dir=compile_cache_dir))
    else:
        raise ValueError("unknown hash backend %r" % backend)
    h.tracer = tracer
    h.metrics = metrics
    h.faults = faults
    h.stats = stats
    return h
