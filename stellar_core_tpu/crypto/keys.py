"""Key management and the synchronous signature boundary.

Role parity: reference `src/crypto/SecretKey.{h,cpp}`:
- SecretKey::sign (SecretKey.cpp:123), random/from-seed/pseudo keys
- PubKeyUtils::verifySig (SecretKey.cpp:310) with the global verify-result
  cache (SecretKey.cpp:27-51,320-337)
- KeyUtils strkey round-trips

CPU crypto is OpenSSL via the `cryptography` package (the libsodium stand-in:
RFC 8032 semantics — cofactorless verify, rejects non-canonical S and
non-canonical point encodings). The TPU batch path (crypto/batch_verifier.py)
implements the SAME accept/reject semantics so backends are interchangeable.
"""

from __future__ import annotations

import hashlib
from typing import Optional

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519 as _ed
except ImportError:  # hermetic container: self-contained fallback
    # (native C ed25519c.c when a compiler exists, pure-Python RFC 8032
    # otherwise — identical accept/reject semantics, see crypto/fallback)
    InvalidSignature = serialization = _ed = None

from ..util.cache import RandomEvictionCache
from ..xdr import PublicKey, SignatureHint
from . import strkey
from .hashing import sha256

VERIFY_CACHE_SIZE = 0xFFFF

# tracked: the verify cache is the one structure every thread touches
# (main loop, threaded dispatch worker, HTTP metrics reads) — the
# lock-order checker (util/threads.py) watches it under tests
from ..util.threads import TrackedLock  # noqa: E402

_cache_lock = TrackedLock("crypto.verify-cache")
_verify_cache: RandomEvictionCache = RandomEvictionCache(VERIFY_CACHE_SIZE)


def _cache_key(key32: bytes, sig: bytes, msg: bytes) -> bytes:
    h = hashlib.sha256()
    h.update(key32)
    h.update(sig)
    h.update(msg)
    return h.digest()


def verify_cache_stats() -> dict:
    with _cache_lock:
        return {"hits": _verify_cache.hits, "misses": _verify_cache.misses,
                "size": len(_verify_cache)}


def flush_verify_cache() -> None:
    with _cache_lock:
        _verify_cache.clear()
        _verify_cache.hits = 0
        _verify_cache.misses = 0


def raw_verify(key32: bytes, sig: bytes, msg: bytes) -> bool:
    """Uncached single ed25519 verify (OpenSSL, or the self-contained
    fallback when `cryptography` is absent)."""
    if len(sig) != 64:
        return False
    if _ed is None:
        from . import fallback as _fb
        return _fb.ed25519_verify(key32, sig, msg)
    try:
        pk = _ed.Ed25519PublicKey.from_public_bytes(key32)
        pk.verify(sig, msg)
        return True
    except (InvalidSignature, ValueError):
        return False


_CPU_VERIFY_THREADS = None


def _cpu_verify_threads() -> int:
    """Shard width for large CPU verify batches (ISSUE 13: the replay
    pipeline is verify-bound on the sync CPU backend; sharding the
    native batch call over threads — it drops the GIL — is the only CPU
    lever left). SCT_VERIFY_CPU_THREADS=1 disables."""
    global _CPU_VERIFY_THREADS
    if _CPU_VERIFY_THREADS is None:
        import os
        try:
            n = int(os.environ.get("SCT_VERIFY_CPU_THREADS", "0"))
        except ValueError:
            n = 0
        if n <= 0:
            n = min(8, os.cpu_count() or 1)
        _CPU_VERIFY_THREADS = max(1, n)
    return _CPU_VERIFY_THREADS


def _verify_batch_sharded(lib, triples, nthreads: int) -> list:
    """Split one big batch across ephemeral worker threads, each running
    the native verify_batch ctypes call (GIL released inside). Pure
    function of the inputs — shard boundaries cannot change results."""
    from ..util.threads import spawn_worker
    n = len(triples)
    chunk = (n + nthreads - 1) // nthreads
    bounds = [(i, min(i + chunk, n)) for i in range(0, n, chunk)]
    results: list = [None] * len(bounds)
    errors: list = [None] * len(bounds)

    def run(idx, lo, hi):
        try:
            results[idx] = lib.verify_batch(triples[lo:hi])
        except BaseException as e:  # re-raised on the caller below
            errors[idx] = e

    threads = []
    for idx, (lo, hi) in enumerate(bounds[1:], start=1):
        threads.append(spawn_worker(
            "crypto.cpu-verify-shard",
            (lambda idx=idx, lo=lo, hi=hi: run(idx, lo, hi))))
    run(0, bounds[0][0], bounds[0][1])
    for t in threads:
        t.join()
    for e in errors:
        if e is not None:
            raise e
    out: list = []
    for r in results:
        out.extend(r)
    return out


def raw_verify_batch(triples) -> list:
    """[(key32, sig, msg)] → [bool], one native call when the C library
    is available (CpuSigVerifier's whole-batch drain path); batches of
    256+ shard over worker threads."""
    if _ed is None:
        from ..native import ed25519_native
        lib = ed25519_native()
        if lib is not None:
            out = [False] * len(triples)
            good = [i for i, (k, s, _m) in enumerate(triples)
                    if len(k) == 32 and len(s) == 64]
            good_triples = [triples[i] for i in good]
            nthreads = _cpu_verify_threads()
            if len(good) >= 256 and nthreads > 1:
                oks = _verify_batch_sharded(lib, good_triples, nthreads)
            else:
                oks = lib.verify_batch(good_triples)
            for i, ok in zip(good, oks):
                out[i] = ok
            return out
    return [raw_verify(k, s, m) for (k, s, m) in triples]


class PubKeyUtils:
    @staticmethod
    def verify_sig(key: PublicKey, sig: bytes, msg: bytes) -> bool:
        """Cached verify — the L0 in front of any batch backend
        (reference SecretKey.cpp:310-337)."""
        ck = _cache_key(key.key_bytes, sig, msg)
        with _cache_lock:
            got = _verify_cache.maybe_get(ck)
        if got is not None:
            return got
        ok = raw_verify(key.key_bytes, sig, msg)
        with _cache_lock:
            _verify_cache.put(ck, ok)
        return ok

    @staticmethod
    def get_hint(key: PublicKey) -> bytes:
        """Last 4 bytes of the key (reference getHint)."""
        return key.key_bytes[-4:]


class SecretKey:
    """Ed25519 secret key (seed form)."""

    def __init__(self, seed32: bytes) -> None:
        assert len(seed32) == 32
        self._seed = seed32
        if _ed is not None:
            self._sk = _ed.Ed25519PrivateKey.from_private_bytes(seed32)
            pub = self._sk.public_key().public_bytes(
                serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        else:
            from . import fallback as _fb
            self._sk = None
            pub = _fb.ed25519_public(seed32)
        self._pub = PublicKey.ed25519(pub)

    # -- constructors -------------------------------------------------------
    @classmethod
    def random(cls) -> "SecretKey":
        import os
        return cls(os.urandom(32))

    @classmethod
    def from_seed(cls, seed32: bytes) -> "SecretKey":
        return cls(seed32)

    @classmethod
    def pseudo_random_for_testing(cls, rng=None) -> "SecretKey":
        from ..util import rnd
        r = rng or rnd.g_random
        return cls(bytes(r.getrandbits(8) for _ in range(32)))

    @classmethod
    def from_strkey_seed(cls, s: str) -> "SecretKey":
        return cls(strkey.decode_seed(s))

    # -- accessors ----------------------------------------------------------
    @property
    def public_key(self) -> PublicKey:
        return self._pub

    @property
    def seed(self) -> bytes:
        return self._seed

    def strkey_seed(self) -> str:
        return strkey.encode_seed(self._seed)

    def strkey_public(self) -> str:
        return strkey.encode_public_key(self._pub.key_bytes)

    # -- signing ------------------------------------------------------------
    def sign(self, msg: bytes) -> bytes:
        if self._sk is not None:
            return self._sk.sign(msg)
        from . import fallback as _fb
        return _fb.ed25519_sign(self._seed, msg)

    def sign_decorated(self, msg: bytes):
        from ..xdr import DecoratedSignature
        return DecoratedSignature(hint=PubKeyUtils.get_hint(self._pub),
                                  signature=self.sign(msg))

    def __repr__(self) -> str:
        return "SecretKey(%s)" % self.strkey_public()


class KeyUtils:
    @staticmethod
    def to_strkey(key: PublicKey) -> str:
        return strkey.encode_public_key(key.key_bytes)

    @staticmethod
    def from_strkey(s: str) -> PublicKey:
        return PublicKey.ed25519(strkey.decode_public_key(s))

    @staticmethod
    def short_name(key: PublicKey) -> str:
        return KeyUtils.to_strkey(key)[:5]
