"""Hashing: SHA-256 (one-shot + incremental), HMAC, HKDF, SipHash short hash.

Role parity: reference `src/crypto/SHA.cpp:14,37,88-129` (sha256, SHA256
incremental, hmacSha256, hkdf) and `src/crypto/ShortHash.cpp:18` (SipHash-2-4
keyed short hash used for in-memory hash maps).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import struct


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


class SHA256:
    """Incremental SHA-256 (reference SHA256 class, crypto/SHA.cpp:37)."""

    def __init__(self) -> None:
        self._h = hashlib.sha256()

    def add(self, data: bytes) -> "SHA256":
        self._h.update(data)
        return self

    def finish(self) -> bytes:
        return self._h.digest()


def hmac_sha256(key: bytes, data: bytes) -> bytes:
    return _hmac.new(key, data, hashlib.sha256).digest()


def hmac_sha256_verify(key: bytes, data: bytes, mac: bytes) -> bool:
    return _hmac.compare_digest(hmac_sha256(key, data), mac)


def hkdf_extract(ikm: bytes, salt: bytes = b"\x00" * 32) -> bytes:
    """HKDF-Extract with zero salt default (reference crypto/SHA.cpp:106)."""
    return hmac_sha256(salt, ikm)


def hkdf_expand(prk: bytes, info: bytes = b"", length: int = 32) -> bytes:
    """HKDF-Expand (single-block is all the reference needs,
    crypto/SHA.cpp:118)."""
    assert length <= 255 * 32
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = hmac_sha256(prk, t + info + bytes([i]))
        out += t
        i += 1
    return out[:length]


# --- SipHash-2-4 (short hash for hash maps; keyed per-process) -------------

def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & 0xFFFFFFFFFFFFFFFF


def siphash24(key16: bytes, data: bytes) -> int:
    k0, k1 = struct.unpack("<QQ", key16)
    v0 = k0 ^ 0x736F6D6570736575
    v1 = k1 ^ 0x646F72616E646F6D
    v2 = k0 ^ 0x6C7967656E657261
    v3 = k1 ^ 0x7465646279746573

    def rounds(n: int) -> None:
        nonlocal v0, v1, v2, v3
        for _ in range(n):
            v0 = (v0 + v1) & 0xFFFFFFFFFFFFFFFF
            v1 = _rotl(v1, 13) ^ v0
            v0 = _rotl(v0, 32)
            v2 = (v2 + v3) & 0xFFFFFFFFFFFFFFFF
            v3 = _rotl(v3, 16) ^ v2
            v0 = (v0 + v3) & 0xFFFFFFFFFFFFFFFF
            v3 = _rotl(v3, 21) ^ v0
            v2 = (v2 + v1) & 0xFFFFFFFFFFFFFFFF
            v1 = _rotl(v1, 17) ^ v2
            v2 = _rotl(v2, 32)

    b = len(data) & 0xFF
    i = 0
    while len(data) - i >= 8:
        m = struct.unpack_from("<Q", data, i)[0]
        v3 ^= m
        rounds(2)
        v0 ^= m
        i += 8
    tail = data[i:] + b"\x00" * (7 - (len(data) - i)) + bytes([b])
    m = struct.unpack("<Q", tail)[0]
    v3 ^= m
    rounds(2)
    v0 ^= m
    v2 ^= 0xFF
    rounds(4)
    return v0 ^ v1 ^ v2 ^ v3


class ShortHash:
    """Process-wide keyed short hash (reference crypto/ShortHash.cpp:18)."""

    _key = os.urandom(16)

    @classmethod
    def initialize(cls, key: bytes | None = None) -> None:
        cls._key = key if key is not None else os.urandom(16)

    @classmethod
    def compute(cls, data: bytes) -> int:
        return siphash24(cls._key, data)
