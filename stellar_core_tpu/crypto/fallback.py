"""Self-contained crypto fallbacks for containers without `cryptography`.

The CPU crypto boundary (keys.py, curve25519.py) prefers OpenSSL via the
`cryptography` package; when that package is absent this module supplies
the same primitives with identical accept/reject semantics:

- ed25519 sign/verify/public — RFC 8032 cofactorless, rejecting
  non-canonical S and non-canonical point encodings, byte-for-byte the
  decisions of `ops.ed25519.verify_oracle` (the repo's semantics oracle).
- X25519 ECDH (RFC 7748) for overlay peer session keys.
- ChaCha20-Poly1305 AEAD (RFC 8439) for sealed survey responses.

Dispatch order: the native C implementation (native/ed25519c.c, loaded
via ctypes like prep.c) when a compiler is available, else the pure-
Python ints below. The Python path deliberately does NOT import
ops.ed25519 (which would pull jax into processes — bench orchestrator,
scrubbed children — that must never touch it); the ~60 lines of curve
math are duplicated here against that constraint.

Not constant-time. The reference's production path is libsodium; this
fallback exists so the suite, the differential tests, and the bench's
CPU legs run in hermetic containers.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Optional

# --- curve constants (python ints; match ops/ed25519.py) -------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
B_Y = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> Optional[int]:
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


class _Pt:
    """Extended-coordinate point over python ints."""

    __slots__ = ("x", "y", "z", "t")

    def __init__(self, x, y, z=1, t=None):
        self.x, self.y, self.z = x % P, y % P, z % P
        self.t = (x * y * pow(z, P - 2, P)) % P if t is None else t % P

    @classmethod
    def identity(cls):
        return cls(0, 1, 1, 0)

    def add(self, o: "_Pt") -> "_Pt":
        a = (self.y - self.x) * (o.y - o.x) % P
        b = (self.y + self.x) * (o.y + o.x) % P
        c = self.t * D2 % P * o.t % P
        d = 2 * self.z * o.z % P
        e, f, g, h = b - a, d - c, d + c, b + a
        return _Pt(e * f % P, g * h % P, f * g % P, e * h % P)

    def dbl(self) -> "_Pt":
        a = self.x * self.x % P
        b = self.y * self.y % P
        c = 2 * self.z * self.z % P
        h = a + b
        e = h - (self.x + self.y) ** 2 % P
        g = a - b
        f = c + g
        return _Pt(e * f % P, g * h % P, f * g % P, e * h % P)

    def mul(self, n: int) -> "_Pt":
        q = _Pt.identity()
        p = self
        while n:
            if n & 1:
                q = q.add(p)
            p = p.dbl()
            n >>= 1
        return q

    def affine(self) -> tuple:
        zi = pow(self.z, P - 2, P)
        return (self.x * zi % P, self.y * zi % P)

    def compress(self) -> bytes:
        x, y = self.affine()
        return int.to_bytes(y | ((x & 1) << 255), 32, "little")


B_POINT = _Pt(_recover_x(B_Y, 0), B_Y)


# --- ed25519 ----------------------------------------------------------------

def _clamped_scalar(seed: bytes) -> tuple:
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def _py_public(seed: bytes) -> bytes:
    a, _prefix = _clamped_scalar(seed)
    return B_POINT.mul(a).compress()


def _py_sign(seed: bytes, msg: bytes) -> bytes:
    a, prefix = _clamped_scalar(seed)
    a_enc = B_POINT.mul(a).compress()
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    r_enc = B_POINT.mul(r).compress()
    k = int.from_bytes(hashlib.sha512(r_enc + a_enc + msg).digest(),
                       "little") % L
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little")


def _py_verify(pub: bytes, sig: bytes, msg: bytes) -> bool:
    if len(pub) != 32 or len(sig) != 64:
        return False
    r_bytes, s_bytes = sig[:32], sig[32:]
    s = int.from_bytes(s_bytes, "little")
    if s >= L:
        return False
    ay = int.from_bytes(pub, "little")
    a_sign, ay = ay >> 255, ay & ((1 << 255) - 1)
    ry = int.from_bytes(r_bytes, "little")
    r_sign, ry = ry >> 255, ry & ((1 << 255) - 1)
    ax = _recover_x(ay, a_sign)
    rx = _recover_x(ry, r_sign)
    if ax is None or rx is None:
        return False
    k = int.from_bytes(hashlib.sha512(r_bytes + pub + msg).digest(),
                       "little") % L
    a_neg = _Pt(P - ax if ax else 0, ay)
    q = B_POINT.mul(s).add(a_neg.mul(k))  # [S]B − [k]A
    qx, qy = q.affine()
    return qx == rx and qy == ry


def ed25519_public(seed: bytes) -> bytes:
    from ..native import ed25519_native
    lib = ed25519_native()
    if lib is not None:
        return lib.public(seed)
    return _py_public(seed)


def ed25519_sign(seed: bytes, msg: bytes) -> bytes:
    from ..native import ed25519_native
    lib = ed25519_native()
    if lib is not None:
        return lib.sign(seed, msg)
    return _py_sign(seed, msg)


def ed25519_verify(pub: bytes, sig: bytes, msg: bytes) -> bool:
    from ..native import ed25519_native
    lib = ed25519_native()
    if lib is not None:
        return lib.verify(pub, sig, msg)
    return _py_verify(pub, sig, msg)


# --- X25519 (RFC 7748) ------------------------------------------------------

_A24 = 121665


def _x25519_ladder(k_int: int, u_int: int) -> int:
    x1 = u_int % P
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in range(254, -1, -1):
        kt = (k_int >> t) & 1
        if swap ^ kt:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % P
        aa = a * a % P
        b = (x2 - z2) % P
        bb = b * b % P
        e = (aa - bb) % P
        c = (x3 + z3) % P
        d = (x3 - z3) % P
        da = d * a % P
        cb = c * b % P
        x3 = (da + cb) % P
        x3 = x3 * x3 % P
        z3 = (da - cb) % P
        z3 = z3 * z3 % P * x1 % P
        x2 = aa * bb % P
        z2 = e * (aa + _A24 * e) % P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return x2 * pow(z2, P - 2, P) % P


def _x25519(scalar32: bytes, u32: bytes) -> bytes:
    k = bytearray(scalar32)
    k[0] &= 248
    k[31] &= 127
    k[31] |= 64
    k_int = int.from_bytes(bytes(k), "little")
    u_int = int.from_bytes(u32, "little") & ((1 << 255) - 1)
    return _x25519_ladder(k_int, u_int).to_bytes(32, "little")


_X25519_BASE = (9).to_bytes(32, "little")


def x25519_public(secret32: bytes) -> bytes:
    from ..native import ed25519_native
    lib = ed25519_native()
    if lib is not None:
        return lib.x25519(secret32, _X25519_BASE)
    return _x25519(secret32, _X25519_BASE)


def x25519_shared(secret32: bytes, public32: bytes) -> bytes:
    """Raises ValueError on an all-zero shared secret (small-order peer
    point), matching `cryptography`'s X25519PrivateKey.exchange."""
    from ..native import ed25519_native
    lib = ed25519_native()
    out = (lib.x25519(secret32, public32) if lib is not None
           else _x25519(secret32, public32))
    if out == b"\x00" * 32:
        raise ValueError("X25519 shared secret is all zeros")
    return out


# --- ChaCha20-Poly1305 (RFC 8439) ------------------------------------------

def _rotl32(v: int, c: int) -> int:
    return ((v << c) | (v >> (32 - c))) & 0xFFFFFFFF


def _chacha_block(key: bytes, counter: int, nonce: bytes) -> bytes:
    st = list(struct.unpack("<4I", b"expand 32-byte k"))
    st += list(struct.unpack("<8I", key))
    st.append(counter & 0xFFFFFFFF)
    st += list(struct.unpack("<3I", nonce))
    ws = st[:]

    def qr(a, b, c, d):
        ws[a] = (ws[a] + ws[b]) & 0xFFFFFFFF
        ws[d] = _rotl32(ws[d] ^ ws[a], 16)
        ws[c] = (ws[c] + ws[d]) & 0xFFFFFFFF
        ws[b] = _rotl32(ws[b] ^ ws[c], 12)
        ws[a] = (ws[a] + ws[b]) & 0xFFFFFFFF
        ws[d] = _rotl32(ws[d] ^ ws[a], 8)
        ws[c] = (ws[c] + ws[d]) & 0xFFFFFFFF
        ws[b] = _rotl32(ws[b] ^ ws[c], 7)

    for _ in range(10):
        qr(0, 4, 8, 12)
        qr(1, 5, 9, 13)
        qr(2, 6, 10, 14)
        qr(3, 7, 11, 15)
        qr(0, 5, 10, 15)
        qr(1, 6, 11, 12)
        qr(2, 7, 8, 13)
        qr(3, 4, 9, 14)
    return struct.pack("<16I", *((w + s) & 0xFFFFFFFF
                                 for w, s in zip(ws, st)))


def _chacha20(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    out = bytearray()
    for i in range(0, len(data), 64):
        ks = _chacha_block(key, counter + i // 64, nonce)
        chunk = data[i:i + 64]
        out += bytes(a ^ b for a, b in zip(chunk, ks))
    return bytes(out)


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key32[:16], "little") & \
        0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:], "little")
    pp = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        blk = msg[i:i + 16]
        n = int.from_bytes(blk + b"\x01", "little")
        acc = (acc + n) * r % pp
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(b: bytes) -> bytes:
    return b"\x00" * (-len(b) % 16)


def _aead_tag(key: bytes, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
    poly_key = _chacha_block(key, 0, nonce)[:32]
    mac_data = (aad + _pad16(aad) + ct + _pad16(ct) +
                struct.pack("<QQ", len(aad), len(ct)))
    return _poly1305(poly_key, mac_data)


class ChaCha20Poly1305:
    """Drop-in for cryptography.hazmat.primitives.ciphers.aead's class
    (the two methods SurveyManager's sealed boxes use)."""

    def __init__(self, key: bytes) -> None:
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = key

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
        aad = aad or b""
        ct = _chacha20(self._key, 1, nonce, data)
        return ct + _aead_tag(self._key, nonce, aad, ct)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
        aad = aad or b""
        if len(data) < 16:
            raise ValueError("ciphertext too short")
        ct, tag = data[:-16], data[-16:]
        want = _aead_tag(self._key, nonce, aad, ct)
        if not _consteq(want, tag):
            raise ValueError("authentication tag mismatch")
        return _chacha20(self._key, 1, nonce, ct)


def _consteq(a: bytes, b: bytes) -> bool:
    import hmac
    return hmac.compare_digest(a, b)
