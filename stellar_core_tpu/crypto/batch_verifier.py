"""BatchSigVerifier: the config-gated crypto backend boundary.

North-star parity (BASELINE.json / SURVEY.md intro): the reference calls
libsodium synchronously one signature at a time
(/root/reference/src/crypto/SecretKey.cpp:310-337). Here the boundary is a
batch-oriented service from day one:

    enqueue(key, sig, msg) -> VerifyFuture     (accumulate)
    flush()                                    (dispatch one device batch)
    verify_many(triples) -> [bool]             (whole-ledger/checkpoint drain)

Backends:
- CpuSigVerifier — synchronous OpenSSL; the default (reference's libsodium
  role).
- TpuSigVerifier — ships accumulated triples to the JAX ed25519 kernel in
  one padded, fixed-shape device call (no recompiles); scales batch size
  from a few envelopes (live SCP) to whole checkpoints (catchup replay).
- ThreadedBatchVerifier — wraps either backend so dispatch happens off the
  main thread and futures complete on the VirtualClock main loop, keeping
  the single-threaded consensus invariant (docs/architecture.md:23-26).
- ResilientBatchVerifier — circuit breaker between a primary (device)
  backend and a fallback: N consecutive dispatch failures trip to the
  fallback for a cooldown window with periodic reprobe, so a lost TPU
  degrades throughput instead of killing a ledger close
  (docs/robustness.md; DSig-style degraded operating mode).

The global verify-result cache (keys.py) sits in front of every backend;
cache hits never enqueue.

Clock/threading audit (ISSUE 5 satellite — the 9 touch points):
1. CircuitBreaker.now_fn — injected app clock (make_verifier passes
   clock.now); default is util.timer.real_monotonic for direct
   constructions. Cooldown/reprobe advance deterministically under a
   virtual clock.
2-4. ThreadedBatchVerifier enqueue/dispatch/complete stamps — all three
   read the injected app clock, so the queue-wait span tags and the
   crypto.verify.latency timer are virtual-clock-deterministic in chaos
   soaks (module-level `time` is gone from this file; the D1 static
   rule keeps it out).
5. ThreadedBatchVerifier._lock — TrackedLock, watched by the lock-order
   checker (util/threads.py).
6. ThreadedBatchVerifier worker thread — dispatch off-main; futures
   complete via clock.post_to_main only (single-threaded consensus).
7. TpuSigVerifier._warmup_thread — startup-only, touches JAX state, no
   ledger/consensus objects.
8. keys._cache_lock — TrackedLock shared with the worker thread.
9. ResilientBatchVerifier breaker callbacks (_on_trip/_on_recover) —
   run on whichever thread dispatched (worker under tpu-async): they
   touch only metrics/tracer/flight-recorder, which are thread-safe.
10. VerifierStats (the ISSUE 6 cockpit) — event stamps read the
    injected app clock (now_fn), compile DURATIONS read
    util.timer.real_monotonic (sanctioned: an XLA compile takes real
    time under a frozen virtual clock); recorded from the main loop,
    the dispatch worker and the warmup thread under its own
    TrackedLock("crypto.verifier-stats").
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence, Tuple

from ..util.log import get_logger
from ..util.metrics import MetricsRegistry
from ..util.threads import TrackedLock
from ..util.timer import real_monotonic
from ..util.tracing import tracer_instant
from ..xdr import PublicKey
from . import keys as _keys

log = get_logger("Perf")

Triple = Tuple[bytes, bytes, bytes]  # (key32, sig, msg)


class VerifierStats:
    """Cockpit aggregation for the batch-verify boundary (ISSUE 6
    tentpole; docs/observability.md#device-cockpit).

    One instance per make_verifier() stack, shared by every layer —
    device backend, CPU fallback, resilient wrapper, threaded wrapper —
    so drains are attributed to the backend that actually SERVED them
    (a fallback drain while the breaker is open counts against "cpu",
    never against the device). The same aggregate objects feed three
    consumers:

    - the admin `verifier` endpoint (`to_json`): per-bucket occupancy /
      pad-waste histograms, warmup + compile-cache status, queue depth;
    - the metrics registry (`verifier.*` names) — which makes the whole
      cockpit scrapeable via `metrics?format=prometheus`;
    - the tracer: `verifier.warmup.*` instants, so compile/warmup
      progress appears in Chrome traces and flight dumps.

    Clocks: event STAMPS (`t` fields) read the injected app clock
    (`now_fn` = clock.now via make_verifier), so chaos soaks under a
    virtual clock stay deterministic; compile DURATIONS are real
    elapsed seconds via util.timer.real_monotonic — an XLA compile
    takes real time even while the app clock is frozen. Recording
    happens on the main loop, the threaded dispatch worker and the
    warmup thread; aggregate mutation is under `_lock`, registry
    metric objects are individually thread-safe."""

    def __init__(self, metrics=None, tracer=None, now_fn=None,
                 flight_recorder=None) -> None:
        self._now = now_fn or real_monotonic
        # a private registry when none is injected keeps direct
        # constructions (tests, bench children) app-registry-free while
        # letting every registration below use the new_* idiom the M1
        # metric-catalog scanner keys on
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.tracer = tracer
        self.flight_recorder = flight_recorder
        self._lock = TrackedLock("crypto.verifier-stats")
        self.backends: dict = {}      # name -> {drains, sigs, pad_total}
        self.buckets: dict = {}       # bucket -> counts + histograms
        self.queue = {"depth": 0, "inflight": 0,
                      "wait_last_mean_ms": None, "wait_last_max_ms": None}
        self.warmup = {"state": "idle", "planned": [], "begun_t": None,
                       "done_t": None, "error": None, "buckets": {}}
        self.compile_cache = {"enabled": None, "dir": None, "hits": 0,
                              "misses": 0, "unknown": 0, "error": None}
        # fixed-name registry metrics, created eagerly so the Prometheus
        # export carries the full cockpit shape from the first scrape
        m = self.metrics
        self._h_batch = m.new_histogram("verifier.drain.batch-size")
        self._h_pad = m.new_histogram("verifier.drain.pad-waste")
        self._h_occ = m.new_histogram("verifier.drain.occupancy-pct")
        self._h_splits = m.new_histogram("verifier.drain.splits")
        self._h_wsec = m.new_histogram("verifier.warmup.bucket-seconds")
        self._t_wait = m.new_timer("verifier.queue.wait")
        self._g_depth = m.new_gauge("verifier.queue.depth")
        self._g_inflight = m.new_gauge("verifier.queue.inflight")
        self._g_wstate = m.new_gauge("verifier.warmup.state")
        self._g_wdone = m.new_gauge("verifier.warmup.buckets-done")
        self._g_cc = m.new_gauge("verifier.compile-cache.enabled")
        self._c_hit = m.new_counter("verifier.compile-cache.hit")
        self._c_miss = m.new_counter("verifier.compile-cache.miss")

    # -- drains --------------------------------------------------------------
    def record_drain(self, backend: str, n: int, pad: int = 0,
                     splits: int = 1) -> None:
        """One verify_many drain, attributed to the backend that served
        it. `pad` is the total padding-lane waste (0 on unpadded CPU
        drains — which still count, so bucket-selection analysis sees
        ALL traffic, not just the device path)."""
        occ = 100.0 * n / (n + pad) if (n + pad) else 100.0
        with self._lock:
            d = self.backends.setdefault(
                backend, {"drains": 0, "sigs": 0, "pad_total": 0})
            d["drains"] += 1
            d["sigs"] += n
            d["pad_total"] += pad
        self._h_batch.update(n)
        self._h_pad.update(pad)
        self._h_occ.update(occ)
        self._h_splits.update(splits)
        self.metrics.new_meter("verifier.drains.%s" % backend).mark()

    def record_bucket_dispatch(self, bucket: int, n: int,
                               pad: int) -> None:
        """One padded device dispatch into a fixed bucket shape (the
        device path only — buckets come from TpuSigVerifier.BUCKETS, so
        the dynamic `verifier.bucket.<b>.*` name space stays bounded)."""
        occ = 100.0 * n / bucket if bucket else 100.0
        with self._lock:
            b = self.buckets.get(bucket)
            if b is None:
                b = self.buckets[bucket] = {
                    "drains": 0, "sigs": 0, "pad_total": 0,
                    "_occ": self.metrics.new_histogram(
                        "verifier.bucket.%d.occupancy-pct" % bucket),
                    "_pad": self.metrics.new_histogram(
                        "verifier.bucket.%d.pad-waste" % bucket),
                    "_m": self.metrics.new_meter(
                        "verifier.bucket.%d.drains" % bucket)}
            b["drains"] += 1
            b["sigs"] += n
            b["pad_total"] += pad
        b["_occ"].update(occ)
        b["_pad"].update(pad)
        b["_m"].mark()

    # -- queue ---------------------------------------------------------------
    def set_queue_depth(self, depth: int) -> None:
        self.queue["depth"] = depth
        self._g_depth.set(depth)

    def set_inflight(self, inflight: bool) -> None:
        self.queue["inflight"] = int(inflight)
        self._g_inflight.set(int(inflight))

    def record_queue_wait(self, mean_s: float, max_s: float) -> None:
        self.queue["wait_last_mean_ms"] = round(mean_s * 1e3, 3)
        self.queue["wait_last_max_ms"] = round(max_s * 1e3, 3)
        self._t_wait.update(mean_s)

    # -- compile cache + warmup ---------------------------------------------
    def compile_cache_enabled(self, path: str) -> None:
        self.compile_cache.update(
            {"enabled": True, "dir": path, "error": None})
        self._g_cc.set(1)

    def compile_cache_error(self, err: str) -> None:
        """The persistent-XLA-cache enable failed: previously a swallowed
        log.warning — now a meter, a tracer instant and a flight dump,
        because a node silently paying cold compiles on every restart is
        exactly the regression the cockpit exists to catch."""
        self.compile_cache.update({"enabled": False, "error": err})
        self._g_cc.set(0)
        self.metrics.new_meter("verifier.compile-cache.unavailable").mark()
        tracer_instant(self.tracer, "verifier.compile-cache.unavailable",
                       cat="crypto", error=err)
        if self.flight_recorder is not None:
            self.flight_recorder.dump("compile-cache-unavailable",
                                      extra={"error": err})

    WARMUP_STATE_CODE = {"idle": 0, "running": 1, "done": 2, "failed": 3}

    def warmup_begin(self, buckets) -> None:
        with self._lock:
            self.warmup.update({"state": "running", "begun_t": self._now(),
                                "done_t": None, "error": None,
                                "planned": list(buckets)})
        self._g_wstate.set(self.WARMUP_STATE_CODE["running"])
        tracer_instant(self.tracer, "verifier.warmup.begin", cat="crypto",
                       buckets=list(buckets))

    def warmup_bucket_done(self, bucket: int, seconds: float,
                           cache_hit) -> None:
        """One bucket shape compiled (or loaded). `cache_hit` is
        True/False from the compile-cache-entry diff, None when the
        cache dir is unreadable."""
        cache = ("hit" if cache_hit is True else
                 "miss" if cache_hit is False else "unknown")
        with self._lock:
            self.warmup["buckets"][str(bucket)] = {
                "seconds": round(seconds, 3), "cache": cache,
                "t": self._now()}
            done = len(self.warmup["buckets"])
            self.compile_cache[
                {"hit": "hits", "miss": "misses",
                 "unknown": "unknown"}[cache]] += 1
        self._h_wsec.update(seconds)
        self._g_wdone.set(done)
        if cache_hit is True:
            self._c_hit.inc()
        elif cache_hit is False:
            self._c_miss.inc()
        tracer_instant(self.tracer, "verifier.warmup.bucket", cat="crypto",
                       bucket=bucket, seconds=round(seconds, 3),
                       cache=cache)

    def warmup_done(self) -> None:
        with self._lock:
            self.warmup.update({"state": "done", "done_t": self._now()})
            total = sum(b["seconds"]
                        for b in self.warmup["buckets"].values())
            n = len(self.warmup["buckets"])
        self._g_wstate.set(self.WARMUP_STATE_CODE["done"])
        tracer_instant(self.tracer, "verifier.warmup.end", cat="crypto",
                       buckets=n, total_s=round(total, 3))

    def warmup_failed(self, err: str) -> None:
        with self._lock:
            self.warmup.update({"state": "failed", "done_t": self._now(),
                                "error": err})
        self._g_wstate.set(self.WARMUP_STATE_CODE["failed"])
        self.metrics.new_meter("verifier.warmup.failure").mark()
        tracer_instant(self.tracer, "verifier.warmup.failed", cat="crypto",
                       error=err)
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                "verify-warmup-failed",
                extra={"error": err, "warmup": self.warmup_json()})

    # -- export --------------------------------------------------------------
    def warmup_json(self) -> dict:
        with self._lock:
            w = dict(self.warmup)
            w["buckets"] = {k: dict(v)
                            for k, v in self.warmup["buckets"].items()}
        return w

    def to_json(self) -> dict:
        """The cockpit blob served by the admin `verifier` endpoint."""
        with self._lock:
            backends = {k: dict(v) for k, v in self.backends.items()}
            buckets = {
                str(b): {"drains": d["drains"], "sigs": d["sigs"],
                         "pad_waste_total": d["pad_total"],
                         "occupancy_pct": d["_occ"].snapshot(),
                         "pad_waste": d["_pad"].snapshot()}
                for b, d in sorted(self.buckets.items())}
            queue = dict(self.queue)
            cc = dict(self.compile_cache)
        return {
            "drains": {"by_backend": backends,
                       "batch_size": self._h_batch.snapshot(),
                       "pad_waste": self._h_pad.snapshot(),
                       "occupancy_pct": self._h_occ.snapshot(),
                       "splits": self._h_splits.snapshot()},
            "buckets": buckets,
            "warmup": self.warmup_json(),
            "compile_cache": cc,
            "queue": queue,
        }


class VerifyFuture:
    """Completion handle for one enqueued verify."""

    __slots__ = ("_done", "_result", "_callbacks")

    def __init__(self) -> None:
        self._done = False
        self._result = False
        self._callbacks: List[Callable[[bool], None]] = []

    def done(self) -> bool:
        return self._done

    def result(self) -> bool:
        assert self._done, "verify future not completed; call flush()"
        return self._result

    def add_done_callback(self, cb: Callable[[bool], None]) -> None:
        if self._done:
            cb(self._result)
        else:
            self._callbacks.append(cb)

    def _complete(self, ok: bool) -> None:
        self._done = True
        self._result = ok
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(ok)


class BatchSigVerifier:
    """Abstract backend; see module docstring."""

    name = "abstract"
    # True for backends where one big device dispatch beats many small
    # ones — TxSetFrame.check_or_trim prewarms the whole set's signatures
    # through verify_many before walking txs (two-phase validation).
    wants_prewarm = False
    # span tracer (util/tracing.py), metrics registry, fault injector
    # (util/faults.py) and the shared VerifierStats cockpit, installed
    # by make_verifier; None keeps direct constructions (tests,
    # native-apply fallback) silent
    tracer = None
    metrics = None
    faults = None
    stats = None

    def _span(self, name: str, **tags):
        from ..util.tracing import tracer_span
        return tracer_span(self.tracer, name, cat="crypto", **tags)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        raise NotImplementedError

    def prewarm_many(self, triples: Sequence[Triple]) -> List[bool]:
        """Whole-ledger/checkpoint drain (SURVEY.md §2.2): verify a large
        batch in one dispatch and seed the result cache so subsequent
        synchronous per-signature checks all hit. Already-cached triples
        are not re-dispatched. Cache keys for the whole drain hash in one
        native call (prep.c sct_cache_keys) when available."""
        with self._span("crypto.prewarm", backend=self.name,
                        n=len(triples)) as sp:
            cks = None
            if len(triples) >= 256:   # below this the fixed numpy/ctypes
                # marshalling cost exceeds hashlib's per-triple overhead
                # (the native apply engine calls here once per tx, ~20-ish
                # triples; checkpoint drains come in by the thousand)
                from ..native import cache_keys_native
                cks = cache_keys_native(triples)
            if cks is None:
                cks = [_keys._cache_key(k, s, m) for (k, s, m) in triples]
            out: List[Optional[bool]] = [None] * len(triples)
            todo: List[Tuple[int, Triple, bytes]] = []  # (idx, triple, key)
            with _keys._cache_lock:
                for i, (t, ck) in enumerate(zip(triples, cks)):
                    hit = _keys._verify_cache.maybe_get(ck)
                    if hit is not None:
                        out[i] = hit
                    else:
                        todo.append((i, t, ck))
            sp.set_tag("cache_hits", len(triples) - len(todo))
            if todo:
                results = self.verify_many([t for (_i, t, _ck) in todo])
                with _keys._cache_lock:
                    for ((i, _t, ck), ok) in zip(todo, results):
                        _keys._verify_cache.put(ck, ok)
                        out[i] = ok
            return out  # type: ignore[return-value]

    def pending(self) -> int:
        return 0

    # -- shared pending-queue machinery (batch backends) ---------------------
    # TpuSigVerifier and ResilientBatchVerifier share one accumulate/
    # dispatch protocol: cache-probe on enqueue, self-flush at
    # _max_pending, one verify_many per flush, futures completed and the
    # cache fed from the results; a raising dispatch re-completes the
    # batch on the synchronous CPU path instead of stranding futures.

    def _batch_enqueue(self, key: PublicKey, sig: bytes,
                       msg: bytes) -> VerifyFuture:
        ck = _keys._cache_key(key.key_bytes, sig, msg)
        with _keys._cache_lock:
            hit = _keys._verify_cache.maybe_get(ck)
        f = VerifyFuture()
        if hit is not None:
            f._complete(hit)
            return f
        self._pending.append(((key.key_bytes, sig, msg), f))
        if self.stats is not None:
            self.stats.set_queue_depth(len(self._pending))
        if len(self._pending) >= self._max_pending:
            self.flush()
        return f

    def _batch_flush(self) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        if self.stats is not None:
            self.stats.set_queue_depth(0)
        triples = [t for (t, _f) in batch]
        try:
            results = self.verify_many(triples)
        except Exception as e:
            log.warning("batch dispatch failed (%s); completing %d "
                        "verifies on CPU fallback", e, len(batch))
            results = _flush_fallback(self, triples)
        for ((k, s, m), f), ok in zip(batch, results):
            with _keys._cache_lock:
                _keys._verify_cache.put(_keys._cache_key(k, s, m), ok)
            f._complete(ok)


def _flush_fallback(verifier, triples: Sequence[Triple]) -> List[bool]:
    """Synchronous CPU re-verify used when a backend's dispatch raises
    mid-flush; counts the event so a silent degradation is visible."""
    m = getattr(verifier, "metrics", None)
    if m is not None:
        m.new_meter("crypto.verify.flush-fallback").mark(len(triples))
    st = getattr(verifier, "stats", None)
    if st is not None:
        # the CPU served this drain (the raising backend did not)
        st.record_drain("cpu", len(triples))
    return _keys.raw_verify_batch(triples)


class CpuSigVerifier(BatchSigVerifier):
    """Synchronous OpenSSL backend (libsodium role)."""

    name = "cpu"

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        f = VerifyFuture()
        f._complete(_keys.PubKeyUtils.verify_sig(key, sig, msg))
        return f

    def flush(self) -> None:
        pass

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        # CPU drains carry the same batch-shape tags as device drains
        # (pad_waste is structurally 0: no padding on the synchronous
        # path) so bucket-selection analysis sees ALL traffic, not just
        # what happened to reach the device
        with self._span("crypto.verify_many", backend=self.name,
                        n=len(triples), batches=1, pad_waste=0,
                        occupancy_pct=100.0):
            out = _keys.raw_verify_batch(triples)
            # recorded only after the verify returns: a raising drain is
            # re-run (and counted once) by _flush_fallback instead
            if self.stats is not None:
                self.stats.record_drain(self.name, len(triples))
            return out


class TpuSigVerifier(BatchSigVerifier):
    """JAX/TPU batched backend.

    Batches are padded up to fixed bucket sizes so the kernel compiles once
    per bucket; oversized batches are split. Correctness contract: identical
    accept/reject decisions to CpuSigVerifier (RFC 8032 cofactorless).
    """

    name = "tpu"
    wants_prewarm = True
    BUCKETS = (128, 512, 2048, 8192)
    # minimum compile duration the persistent cache stores (mirrors the
    # jax_persistent_cache_min_compile_time_secs value set below): a
    # compile faster than this writes no entry, so "no new cache file"
    # proves nothing about it — warmup classifies those "unknown",
    # never "hit"
    CACHE_PERSIST_MIN_S = 0.5

    # batches below this size stay on one device: sharding a handful of
    # sigs over a pod slice buys nothing and costs a sharded compile
    SHARD_MIN_BATCH = 1024

    def __init__(self, max_pending: int = 8192,
                 compile_cache_dir: Optional[str] = None,
                 shard_threshold: Optional[int] = None) -> None:
        self._pending: List[Tuple[Triple, VerifyFuture]] = []
        self._max_pending = max_pending
        self.batches_dispatched = 0
        self.sigs_verified = 0
        self._compile_cache_dir = compile_cache_dir
        self._cache_path: Optional[str] = None  # resolved on enable
        self._warmed = False
        self._warmup_thread: Optional[threading.Thread] = None
        self._sharded_fn = None  # lazy; multi-device dp dispatch
        self._platform: Optional[str] = None  # actual jax platform, lazy
        if shard_threshold is not None:
            self.SHARD_MIN_BATCH = shard_threshold

    def _device_fn(self, batch_size: int):
        """Single-device jit, or the dp-sharded jit when the process sees
        more than one chip and the batch is worth sharding (VERDICT r2 #3:
        the production path must use the mesh, not just the dryrun).
        Cached after first use."""
        import jax
        if jax.device_count() <= 1 or batch_size < self.SHARD_MIN_BATCH:
            from ..ops.ed25519 import verify_batch_jit
            return verify_batch_jit, 1
        if self._sharded_fn is None:
            from ..parallel.mesh import make_mesh, sharded_verify_fn
            self._sharded_fn = sharded_verify_fn(make_mesh())
        return self._sharded_fn, jax.device_count()

    def _enable_compile_cache(self) -> None:
        """Persistent XLA compilation cache: a node restart never re-pays
        kernel compilation (VERDICT r1: lazy compile on the consensus path
        stalls a validator for the compile duration)."""
        import os
        path = self._compile_cache_dir or os.environ.get(
            "JAX_COMPILATION_CACHE_DIR") or os.path.expanduser(
            "~/.cache/stellar_core_tpu/jax_cache")
        try:
            import jax
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              self.CACHE_PERSIST_MIN_S)
            self._cache_path = path
            if self.stats is not None:
                self.stats.compile_cache_enabled(path)
        except Exception as e:  # cache is an optimization, never fatal
            log.warning("compile cache unavailable: %s", e)
            if self.stats is not None:
                # ...but an operator must be able to SEE it (tracer
                # instant + meter + flight dump), or every restart
                # silently pays cold compiles
                self.stats.compile_cache_error(repr(e))

    def _cache_entry_count(self) -> int:
        """Files under the persistent XLA cache dir (-1 = unknown).
        Warmup diffs this around each bucket compile: no new entry means
        the executable came from the cache (a warm restart), a new entry
        means a cold compile just got paid."""
        import os
        if self._cache_path is None:
            return -1
        try:
            n = 0
            for _dir, _sub, files in os.walk(self._cache_path):
                n += len(files)
            return n
        except OSError:
            return -1

    def warmup(self, wait: bool = False) -> None:
        """AOT-compile every bucket shape off the consensus path (startup
        background thread; reference analog: no lazy work on first
        envelope). Idempotent."""
        if self._warmed:
            return
        if self._warmup_thread is None:
            self._warmup_thread = threading.Thread(
                target=self._warmup_impl, daemon=True)
            self._warmup_thread.start()
        if wait:
            self._warmup_thread.join()

    def _compile_bucket(self, b: int) -> None:
        """AOT-compile (or cache-load) one bucket shape."""
        import numpy as np
        import jax.numpy as jnp
        fn, ndev = self._device_fn(b)
        b = -(-b // ndev) * ndev
        args = (jnp.zeros((b, 20), jnp.int32),
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b, 20), jnp.int32),
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b, 64), jnp.int32),
                jnp.zeros((b, 64), jnp.int32))
        np.asarray(fn(*args))

    def _warmup_impl(self) -> None:
        st = self.stats
        try:
            self._enable_compile_cache()
            if st is not None:
                st.warmup_begin(self.BUCKETS)
            for b in self.BUCKETS:
                before = self._cache_entry_count()
                t0 = real_monotonic()
                self._compile_bucket(b)
                dt = real_monotonic() - t0
                after = self._cache_entry_count()
                if before < 0 or after < 0:
                    hit = None            # cache dir unreadable
                elif after > before:
                    hit = False           # a cold compile just persisted
                elif dt >= self.CACHE_PERSIST_MIN_S:
                    hit = True            # long compile, no new entry:
                    # the executable came from the cache
                else:
                    # fast compile below the persistence threshold
                    # writes no entry either way — unclassifiable, and
                    # nothing worth caching was at stake
                    hit = None
                if st is not None:
                    st.warmup_bucket_done(b, dt, hit)
            self._warmed = True
            if st is not None:
                st.warmup_done()
            log.info("verify kernel warmup complete (%s buckets)",
                     len(self.BUCKETS))
        except Exception as e:
            log.warning("verify kernel warmup failed: %s", e)
            if st is not None:
                st.warmup_failed(repr(e))

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        return self._batch_enqueue(key, sig, msg)

    def pending(self) -> int:
        return len(self._pending)

    def flush(self) -> None:
        self._batch_flush()

    def _bucket(self, n: int) -> int:
        for b in self.BUCKETS:
            if n <= b:
                return b
        return self.BUCKETS[-1]

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        from ..ops import ed25519 as _e
        from ..parallel.mesh import pad_batch_to
        import numpy as np
        import jax
        import jax.numpy as jnp

        if self._platform is None:
            # the ACTUAL backing platform ("tpu"/"cpu"/…): a jax-on-CPU
            # run of this verifier is a fallback and must trace as one
            self._platform = jax.devices()[0].platform
        out: List[bool] = []
        with self._span("crypto.verify_many", backend=self.name,
                        platform=self._platform, n=len(triples)) as sp:
            i = 0
            batches = 0
            pad_waste = 0
            while i < len(triples):
                chunk = triples[i:i + self.BUCKETS[-1]]
                n = len(chunk)
                fn, ndev = self._device_fn(self._bucket(n))
                b = -(-self._bucket(n) // ndev) * ndev
                with self._span("crypto.dispatch", backend=self.name,
                                n=n, bucket=b, pad=b - n):
                    prep = _e.prepare_batch(
                        [t[0] for t in chunk], [t[1] for t in chunk],
                        [t[2] for t in chunk])
                    padded = pad_batch_to(prep, b)  # pad lanes pre_ok=False
                    ok = np.asarray(fn(
                        jnp.asarray(padded["ay"]),
                        jnp.asarray(padded["a_sign"]),
                        jnp.asarray(padded["ry"]),
                        jnp.asarray(padded["r_sign"]),
                        jnp.asarray(padded["s_nibs"]),
                        jnp.asarray(padded["k_nibs"])))
                out.extend((ok[:n] & prep["pre_ok"]).tolist())
                self.batches_dispatched += 1
                self.sigs_verified += n
                batches += 1
                pad_waste += b - n
                if self.stats is not None:
                    self.stats.record_bucket_dispatch(b, n, b - n)
                i += n
            sp.set_tag("batches", batches)
            sp.set_tag("pad_waste", pad_waste)
            total = len(triples)
            sp.set_tag("occupancy_pct", round(
                100.0 * total / (total + pad_waste), 1)
                if total + pad_waste else 100.0)
            if self.stats is not None:
                self.stats.record_drain(self.name, total, pad=pad_waste,
                                        splits=batches)
        return out


class CircuitBreaker:
    """closed → open → half-open → closed over the device-dispatch path.

    CLOSED: dispatches flow to the primary; `threshold` CONSECUTIVE
    failures trip to OPEN. OPEN: primary is bypassed until `cooldown_s`
    elapses on the injected clock, then the next allow() becomes the
    HALF-OPEN probe. HALF-OPEN: one success re-closes (recover), one
    failure re-opens for another cooldown. Time comes from `now_fn`
    (virtual clock in tests/simulation) so trips and reprobes are
    deterministic."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"
    _STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 now_fn: Optional[Callable[[], float]] = None,
                 on_trip: Optional[Callable[[], None]] = None,
                 on_recover: Optional[Callable[[], None]] = None) -> None:
        self.threshold = max(1, threshold)
        self.cooldown_s = cooldown_s
        self._now = now_fn or real_monotonic
        self.on_trip = on_trip
        self.on_recover = on_recover
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.trips = 0
        self.recoveries = 0
        self._retry_at = 0.0

    def allow(self) -> bool:
        """May the next dispatch try the primary?"""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN and self._now() >= self._retry_at:
            self.state = self.HALF_OPEN
            return True
        return self.state == self.HALF_OPEN

    def record_success(self) -> None:
        recovered = self.state == self.HALF_OPEN
        self.state = self.CLOSED
        self.consecutive_failures = 0
        if recovered:
            self.recoveries += 1
            if self.on_recover is not None:
                self.on_recover()

    def record_failure(self) -> bool:
        """Returns True when this failure tripped (or re-opened) the
        breaker."""
        self.consecutive_failures += 1
        if self.state == self.HALF_OPEN or \
                self.consecutive_failures >= self.threshold:
            reopened = self.state != self.CLOSED
            self.state = self.OPEN
            self._retry_at = self._now() + self.cooldown_s
            if not reopened:
                self.trips += 1
                if self.on_trip is not None:
                    self.on_trip()
            return True
        return False

    def state_code(self) -> int:
        return self._STATE_CODE[self.state]

    def to_json(self) -> dict:
        return {"state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "trips": self.trips, "recoveries": self.recoveries,
                "threshold": self.threshold, "cooldown_s": self.cooldown_s,
                "retry_at": self._retry_at}


class ResilientBatchVerifier(BatchSigVerifier):
    """Primary backend behind a circuit breaker, CPU fallback beside it.

    Every dispatch-shaped call (verify_many; flush routes through it)
    asks the breaker whether the primary may be tried; a raising primary
    records a failure and the batch re-runs on the fallback, so callers
    always get results. A trip emits metrics + a flight-recorder dump;
    recovery (first successful half-open probe) emits the matching
    recover marker — the signals the chaos soak asserts on."""

    name = "resilient"

    def __init__(self, primary: BatchSigVerifier,
                 fallback: BatchSigVerifier,
                 breaker: Optional[CircuitBreaker] = None,
                 max_pending: int = 8192) -> None:
        self.primary = primary
        self.fallback = fallback
        self.breaker = breaker or CircuitBreaker()
        self.breaker.on_trip = self._on_trip
        self.breaker.on_recover = self._on_recover
        self.flight_recorder = None   # installed by make_verifier
        self._pending: List[Tuple[Triple, VerifyFuture]] = []
        self._max_pending = max_pending

    # -- breaker events ------------------------------------------------------
    def _breaker_mark(self, event: str) -> None:
        if self.metrics is not None:
            self.metrics.new_meter("crypto.breaker.%s" % event).mark()
            self.metrics.new_counter("crypto.breaker.state").set_count(
                self.breaker.state_code())
        from ..util.tracing import tracer_instant
        tracer_instant(self.tracer, "crypto.breaker.%s" % event,
                       cat="crypto", primary=self.primary.name,
                       failures=self.breaker.consecutive_failures)

    def _on_trip(self) -> None:
        log.warning("verify breaker TRIPPED: %d consecutive %s-dispatch "
                    "failures; falling back to %s for %.0fs",
                    self.breaker.consecutive_failures, self.primary.name,
                    self.fallback.name, self.breaker.cooldown_s)
        self._breaker_mark("trip")
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                "verify-breaker-trip",
                extra={"primary": self.primary.name,
                       "breaker": self.breaker.to_json()})

    def _on_recover(self) -> None:
        log.info("verify breaker recovered: %s backend healthy again",
                 self.primary.name)
        self._breaker_mark("recover")

    # -- delegation ----------------------------------------------------------
    @property
    def wants_prewarm(self) -> bool:
        return self.primary.wants_prewarm

    @property
    def inner(self) -> BatchSigVerifier:
        return self.primary

    @property
    def batches_dispatched(self) -> int:
        return getattr(self.primary, "batches_dispatched", 0)

    @property
    def sigs_verified(self) -> int:
        return getattr(self.primary, "sigs_verified", 0)

    def warmup(self, wait: bool = False) -> None:
        w = getattr(self.primary, "warmup", None)
        if w is not None:
            w(wait)

    # -- verify paths --------------------------------------------------------
    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        if self.breaker.allow():
            try:
                # the primary attempt gets its own span so an injected
                # (or real) dispatch failure is tagged on the drain it
                # killed, not floating free on the timeline
                with self._span("crypto.dispatch_primary",
                                backend=self.primary.name,
                                n=len(triples)):
                    if self.faults is not None:
                        self.faults.fire_point("device.dispatch")
                    out = self.primary.verify_many(triples)
                self.breaker.record_success()
                return out
            except Exception as e:
                if self.metrics is not None:
                    self.metrics.new_meter(
                        "crypto.verify.dispatch-failure").mark()
                tripped = self.breaker.record_failure()
                if not tripped:
                    log.warning("%s dispatch failed (%s): %d/%d toward "
                                "breaker trip", self.primary.name, e,
                                self.breaker.consecutive_failures,
                                self.breaker.threshold)
        if self.metrics is not None:
            # drains served by the fallback while the primary is failing
            # or the breaker is open — the "completed on fallback" signal
            # the chaos soak asserts on
            self.metrics.new_meter("crypto.verify.fallback-drain").mark()
        # served_by names the backend that actually ran the drain — the
        # fallback's own verify_many records the drain stats under its
        # name, so cockpit attribution follows the server, not the wrapper
        with self._span("crypto.verify_fallback", backend=self.name,
                        served_by=self.fallback.name,
                        n=len(triples), breaker=self.breaker.state):
            return self.fallback.verify_many(triples)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        return self._batch_enqueue(key, sig, msg)

    def pending(self) -> int:
        return len(self._pending)

    def flush(self) -> None:
        # verify_many (almost) never raises here: a primary failure is
        # absorbed by the breaker and the batch re-runs on the fallback —
        # a trip mid-drain still completes every future correctly
        self._batch_flush()


class ThreadedBatchVerifier(BatchSigVerifier):
    """Async wrapper: dispatch runs on a worker thread, futures complete on
    the main loop via clock.post_to_main — the enqueue-and-continue protocol
    SURVEY.md §7 requires at the verifyEnvelope/checkValid boundary."""

    name = "threaded"

    def __init__(self, inner: BatchSigVerifier, clock,
                 metrics=None) -> None:
        self._inner = inner
        self._clock = clock
        self._metrics = metrics
        self._lock = TrackedLock("crypto.threaded-pending")
        # (triple, future, enqueue app-clock stamp): the timestamp feeds
        # the crypto.verify.latency enqueue-to-complete timer (the
        # p50/p99 the live SCP path actually feels); the app clock, not
        # wall time, so chaos soaks under a virtual clock stay
        # deterministic
        self._pending: List[Tuple[Triple, VerifyFuture, float]] = []
        self._inflight = False

    @property
    def wants_prewarm(self) -> bool:
        return self._inner.wants_prewarm

    @property
    def inner(self) -> BatchSigVerifier:
        """The DEVICE verifier (unwrapping a resilient layer): callers
        tune BUCKETS / read dispatch counters on the actual backend."""
        return getattr(self._inner, "inner", self._inner)

    @property
    def breaker(self):
        return getattr(self._inner, "breaker", None)

    def warmup(self, wait: bool = False) -> None:
        w = getattr(self._inner, "warmup", None)
        if w is not None:
            w(wait)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        ck = _keys._cache_key(key.key_bytes, sig, msg)
        with _keys._cache_lock:
            hit = _keys._verify_cache.maybe_get(ck)
        f = VerifyFuture()
        if hit is not None:
            f._complete(hit)
            return f
        with self._lock:
            self._pending.append(
                ((key.key_bytes, sig, msg), f, self._clock.now()))
            depth = len(self._pending)
        if self.stats is not None:
            self.stats.set_queue_depth(depth)
        return f

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def flush(self) -> None:
        with self._lock:
            if not self._pending or self._inflight:
                return
            batch, self._pending = self._pending, []
            self._inflight = True
        st = self.stats
        if st is not None:
            st.set_queue_depth(0)
            st.set_inflight(True)

        def work() -> None:
            triples = [t for (t, _f, _t0) in batch]
            # queue-wait: enqueue → dispatch start, per batch; dispatch
            # time is the span's own duration (inner verify_many nests)
            t_disp = self._clock.now()
            waits = [t_disp - t0 for (_t, _f, t0) in batch]
            if st is not None:
                st.record_queue_wait(sum(waits) / len(waits), max(waits))
            with self._span("crypto.batch_dispatch",
                            backend="threaded:%s" % self._inner.name,
                            n=len(batch),
                            queue_wait_max_ms=round(max(waits) * 1e3, 3),
                            queue_wait_mean_ms=round(
                                sum(waits) / len(waits) * 1e3, 3)):
                try:
                    results = self._inner.verify_many(triples)
                except Exception as e:
                    # the worker thread must neither die with futures
                    # pending nor leave _inflight latched (that would
                    # no-op every later flush — a permanent wedge)
                    log.warning("threaded dispatch failed (%s); completing "
                                "%d verifies on CPU fallback", e, len(batch))
                    results = _flush_fallback(self, triples)

            def complete() -> None:
                done = self._clock.now()
                lat = (self._metrics.new_timer("crypto.verify.latency")
                       if self._metrics is not None else None)
                for ((k, s, m), f, t0), ok in zip(batch, results):
                    with _keys._cache_lock:
                        _keys._verify_cache.put(_keys._cache_key(k, s, m), ok)
                    if lat is not None:
                        lat.update(done - t0)
                    f._complete(ok)
                with self._lock:
                    self._inflight = False
                    more = bool(self._pending)
                if st is not None:
                    st.set_inflight(False)
                if more:
                    # verifies enqueued while the batch was in flight form
                    # the next batch immediately
                    self.flush()

            self._clock.post_to_main(complete)

        threading.Thread(target=work, daemon=True).start()

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        return self._inner.verify_many(triples)


def make_verifier(backend: str = "cpu", clock=None,
                  max_pending: int = 8192,
                  compile_cache_dir: Optional[str] = None,
                  metrics=None, tracer=None, faults=None,
                  flight_recorder=None,
                  breaker_threshold: int = 3,
                  breaker_cooldown: float = 30.0) -> BatchSigVerifier:
    """Config-gated backend selection (Config.SIG_VERIFY_BACKEND).

    Device backends ("tpu", "tpu-async") are always wrapped in a
    ResilientBatchVerifier with a CPU fallback; "cpu-resilient" wraps the
    CPU backend in the same breaker machinery so chaos runs exercise the
    device failure domain on device-less containers.

    Every layer of the stack shares ONE VerifierStats cockpit
    (`<verifier>.stats`), so fallback drains are attributed to the
    backend that served them and the admin `verifier` endpoint sees the
    whole boundary regardless of wrapping."""
    now_fn = clock.now if clock is not None else None
    stats = VerifierStats(metrics=metrics, tracer=tracer, now_fn=now_fn,
                          flight_recorder=flight_recorder)

    def resilient(primary: BatchSigVerifier) -> ResilientBatchVerifier:
        primary.tracer = tracer
        primary.metrics = metrics
        primary.stats = stats
        fb = CpuSigVerifier()
        fb.tracer = tracer
        fb.metrics = metrics
        fb.stats = stats
        r = ResilientBatchVerifier(
            primary, fb,
            CircuitBreaker(threshold=breaker_threshold,
                           cooldown_s=breaker_cooldown, now_fn=now_fn),
            max_pending=max_pending)
        r.tracer = tracer
        r.flight_recorder = flight_recorder
        r.stats = stats
        return r

    if backend == "cpu":
        v: BatchSigVerifier = CpuSigVerifier()
    elif backend == "cpu-resilient":
        v = resilient(CpuSigVerifier())
    elif backend == "tpu":
        v = resilient(TpuSigVerifier(max_pending=max_pending,
                                     compile_cache_dir=compile_cache_dir))
    elif backend == "tpu-async":
        assert clock is not None
        inner = resilient(TpuSigVerifier(max_pending=max_pending,
                                         compile_cache_dir=compile_cache_dir))
        inner.metrics = metrics
        inner.faults = faults
        v = ThreadedBatchVerifier(inner, clock, metrics=metrics)
    else:
        raise ValueError("unknown sig verify backend %r" % backend)
    v.tracer = tracer
    v.metrics = metrics
    v.faults = faults
    v.stats = stats
    return v
