"""BatchSigVerifier: the config-gated crypto backend boundary.

North-star parity (BASELINE.json / SURVEY.md intro): the reference calls
libsodium synchronously one signature at a time
(/root/reference/src/crypto/SecretKey.cpp:310-337). Here the boundary is a
batch-oriented service from day one:

    enqueue(key, sig, msg) -> VerifyFuture     (accumulate)
    flush()                                    (dispatch one device batch)
    verify_many(triples) -> [bool]             (whole-ledger/checkpoint drain)

Backends:
- CpuSigVerifier — synchronous OpenSSL; the default (reference's libsodium
  role).
- TpuSigVerifier — ships accumulated triples to the JAX ed25519 kernel in
  one padded, fixed-shape device call (no recompiles); scales batch size
  from a few envelopes (live SCP) to whole checkpoints (catchup replay).
- ThreadedBatchVerifier — wraps either backend so dispatch happens off the
  main thread and futures complete on the VirtualClock main loop, keeping
  the single-threaded consensus invariant (docs/architecture.md:23-26).

The global verify-result cache (keys.py) sits in front of every backend;
cache hits never enqueue.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from ..util.log import get_logger
from ..xdr import PublicKey
from . import keys as _keys

log = get_logger("Perf")

Triple = Tuple[bytes, bytes, bytes]  # (key32, sig, msg)


class VerifyFuture:
    """Completion handle for one enqueued verify."""

    __slots__ = ("_done", "_result", "_callbacks")

    def __init__(self) -> None:
        self._done = False
        self._result = False
        self._callbacks: List[Callable[[bool], None]] = []

    def done(self) -> bool:
        return self._done

    def result(self) -> bool:
        assert self._done, "verify future not completed; call flush()"
        return self._result

    def add_done_callback(self, cb: Callable[[bool], None]) -> None:
        if self._done:
            cb(self._result)
        else:
            self._callbacks.append(cb)

    def _complete(self, ok: bool) -> None:
        self._done = True
        self._result = ok
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(ok)


class BatchSigVerifier:
    """Abstract backend; see module docstring."""

    name = "abstract"
    # True for backends where one big device dispatch beats many small
    # ones — TxSetFrame.check_or_trim prewarms the whole set's signatures
    # through verify_many before walking txs (two-phase validation).
    wants_prewarm = False
    # span tracer (util/tracing.py), installed by make_verifier; None
    # keeps direct constructions (tests, native-apply fallback) silent
    tracer = None

    def _span(self, name: str, **tags):
        from ..util.tracing import tracer_span
        return tracer_span(self.tracer, name, cat="crypto", **tags)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        raise NotImplementedError

    def prewarm_many(self, triples: Sequence[Triple]) -> List[bool]:
        """Whole-ledger/checkpoint drain (SURVEY.md §2.2): verify a large
        batch in one dispatch and seed the result cache so subsequent
        synchronous per-signature checks all hit. Already-cached triples
        are not re-dispatched. Cache keys for the whole drain hash in one
        native call (prep.c sct_cache_keys) when available."""
        with self._span("crypto.prewarm", backend=self.name,
                        n=len(triples)) as sp:
            cks = None
            if len(triples) >= 256:   # below this the fixed numpy/ctypes
                # marshalling cost exceeds hashlib's per-triple overhead
                # (the native apply engine calls here once per tx, ~20-ish
                # triples; checkpoint drains come in by the thousand)
                from ..native import cache_keys_native
                cks = cache_keys_native(triples)
            if cks is None:
                cks = [_keys._cache_key(k, s, m) for (k, s, m) in triples]
            out: List[Optional[bool]] = [None] * len(triples)
            todo: List[Tuple[int, Triple, bytes]] = []  # (idx, triple, key)
            with _keys._cache_lock:
                for i, (t, ck) in enumerate(zip(triples, cks)):
                    hit = _keys._verify_cache.maybe_get(ck)
                    if hit is not None:
                        out[i] = hit
                    else:
                        todo.append((i, t, ck))
            sp.set_tag("cache_hits", len(triples) - len(todo))
            if todo:
                results = self.verify_many([t for (_i, t, _ck) in todo])
                with _keys._cache_lock:
                    for ((i, _t, ck), ok) in zip(todo, results):
                        _keys._verify_cache.put(ck, ok)
                        out[i] = ok
            return out  # type: ignore[return-value]

    def pending(self) -> int:
        return 0


class CpuSigVerifier(BatchSigVerifier):
    """Synchronous OpenSSL backend (libsodium role)."""

    name = "cpu"

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        f = VerifyFuture()
        f._complete(_keys.PubKeyUtils.verify_sig(key, sig, msg))
        return f

    def flush(self) -> None:
        pass

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        with self._span("crypto.verify_many", backend=self.name,
                        n=len(triples)):
            return _keys.raw_verify_batch(triples)


class TpuSigVerifier(BatchSigVerifier):
    """JAX/TPU batched backend.

    Batches are padded up to fixed bucket sizes so the kernel compiles once
    per bucket; oversized batches are split. Correctness contract: identical
    accept/reject decisions to CpuSigVerifier (RFC 8032 cofactorless).
    """

    name = "tpu"
    wants_prewarm = True
    BUCKETS = (128, 512, 2048, 8192)

    # batches below this size stay on one device: sharding a handful of
    # sigs over a pod slice buys nothing and costs a sharded compile
    SHARD_MIN_BATCH = 1024

    def __init__(self, max_pending: int = 8192,
                 compile_cache_dir: Optional[str] = None,
                 shard_threshold: Optional[int] = None) -> None:
        self._pending: List[Tuple[Triple, VerifyFuture]] = []
        self._max_pending = max_pending
        self.batches_dispatched = 0
        self.sigs_verified = 0
        self._compile_cache_dir = compile_cache_dir
        self._warmed = False
        self._warmup_thread: Optional[threading.Thread] = None
        self._sharded_fn = None  # lazy; multi-device dp dispatch
        self._platform: Optional[str] = None  # actual jax platform, lazy
        if shard_threshold is not None:
            self.SHARD_MIN_BATCH = shard_threshold

    def _device_fn(self, batch_size: int):
        """Single-device jit, or the dp-sharded jit when the process sees
        more than one chip and the batch is worth sharding (VERDICT r2 #3:
        the production path must use the mesh, not just the dryrun).
        Cached after first use."""
        import jax
        if jax.device_count() <= 1 or batch_size < self.SHARD_MIN_BATCH:
            from ..ops.ed25519 import verify_batch_jit
            return verify_batch_jit, 1
        if self._sharded_fn is None:
            from ..parallel.mesh import make_mesh, sharded_verify_fn
            self._sharded_fn = sharded_verify_fn(make_mesh())
        return self._sharded_fn, jax.device_count()

    def _enable_compile_cache(self) -> None:
        """Persistent XLA compilation cache: a node restart never re-pays
        kernel compilation (VERDICT r1: lazy compile on the consensus path
        stalls a validator for the compile duration)."""
        import os
        path = self._compile_cache_dir or os.environ.get(
            "JAX_COMPILATION_CACHE_DIR") or os.path.expanduser(
            "~/.cache/stellar_core_tpu/jax_cache")
        try:
            import jax
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
        except Exception as e:  # cache is an optimization, never fatal
            log.warning("compile cache unavailable: %s", e)

    def warmup(self, wait: bool = False) -> None:
        """AOT-compile every bucket shape off the consensus path (startup
        background thread; reference analog: no lazy work on first
        envelope). Idempotent."""
        if self._warmed:
            return
        if self._warmup_thread is None:
            self._warmup_thread = threading.Thread(
                target=self._warmup_impl, daemon=True)
            self._warmup_thread.start()
        if wait:
            self._warmup_thread.join()

    def _warmup_impl(self) -> None:
        try:
            self._enable_compile_cache()
            import numpy as np
            import jax.numpy as jnp
            for b in self.BUCKETS:
                fn, ndev = self._device_fn(b)
                b = -(-b // ndev) * ndev
                args = (jnp.zeros((b, 20), jnp.int32),
                        jnp.zeros((b,), jnp.int32),
                        jnp.zeros((b, 20), jnp.int32),
                        jnp.zeros((b,), jnp.int32),
                        jnp.zeros((b, 64), jnp.int32),
                        jnp.zeros((b, 64), jnp.int32))
                np.asarray(fn(*args))
            self._warmed = True
            log.info("verify kernel warmup complete (%s buckets)",
                     len(self.BUCKETS))
        except Exception as e:
            log.warning("verify kernel warmup failed: %s", e)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        # L0: result cache
        ck = _keys._cache_key(key.key_bytes, sig, msg)
        with _keys._cache_lock:
            hit = _keys._verify_cache.maybe_get(ck)
        f = VerifyFuture()
        if hit is not None:
            f._complete(hit)
            return f
        self._pending.append(((key.key_bytes, sig, msg), f))
        if len(self._pending) >= self._max_pending:
            self.flush()
        return f

    def pending(self) -> int:
        return len(self._pending)

    def flush(self) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        triples = [t for (t, _f) in batch]
        results = self.verify_many(triples)
        for ((k, s, m), f), ok in zip(batch, results):
            with _keys._cache_lock:
                _keys._verify_cache.put(_keys._cache_key(k, s, m), ok)
            f._complete(ok)

    def _bucket(self, n: int) -> int:
        for b in self.BUCKETS:
            if n <= b:
                return b
        return self.BUCKETS[-1]

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        from ..ops import ed25519 as _e
        from ..parallel.mesh import pad_batch_to
        import numpy as np
        import jax
        import jax.numpy as jnp

        if self._platform is None:
            # the ACTUAL backing platform ("tpu"/"cpu"/…): a jax-on-CPU
            # run of this verifier is a fallback and must trace as one
            self._platform = jax.devices()[0].platform
        out: List[bool] = []
        with self._span("crypto.verify_many", backend=self.name,
                        platform=self._platform, n=len(triples)) as sp:
            i = 0
            batches = 0
            pad_waste = 0
            while i < len(triples):
                chunk = triples[i:i + self.BUCKETS[-1]]
                n = len(chunk)
                fn, ndev = self._device_fn(self._bucket(n))
                b = -(-self._bucket(n) // ndev) * ndev
                with self._span("crypto.dispatch", backend=self.name,
                                n=n, bucket=b, pad=b - n):
                    prep = _e.prepare_batch(
                        [t[0] for t in chunk], [t[1] for t in chunk],
                        [t[2] for t in chunk])
                    padded = pad_batch_to(prep, b)  # pad lanes pre_ok=False
                    ok = np.asarray(fn(
                        jnp.asarray(padded["ay"]),
                        jnp.asarray(padded["a_sign"]),
                        jnp.asarray(padded["ry"]),
                        jnp.asarray(padded["r_sign"]),
                        jnp.asarray(padded["s_nibs"]),
                        jnp.asarray(padded["k_nibs"])))
                out.extend((ok[:n] & prep["pre_ok"]).tolist())
                self.batches_dispatched += 1
                self.sigs_verified += n
                batches += 1
                pad_waste += b - n
                i += n
            sp.set_tag("batches", batches)
            sp.set_tag("pad_waste", pad_waste)
        return out


class ThreadedBatchVerifier(BatchSigVerifier):
    """Async wrapper: dispatch runs on a worker thread, futures complete on
    the main loop via clock.post_to_main — the enqueue-and-continue protocol
    SURVEY.md §7 requires at the verifyEnvelope/checkValid boundary."""

    name = "threaded"

    def __init__(self, inner: BatchSigVerifier, clock,
                 metrics=None) -> None:
        self._inner = inner
        self._clock = clock
        self._metrics = metrics
        self._lock = threading.Lock()
        # (triple, future, enqueue perf_counter): the timestamp feeds the
        # crypto.verify.latency enqueue-to-complete timer (the p50/p99
        # the live SCP path actually feels)
        self._pending: List[Tuple[Triple, VerifyFuture, float]] = []
        self._inflight = False

    @property
    def wants_prewarm(self) -> bool:
        return self._inner.wants_prewarm

    @property
    def inner(self) -> BatchSigVerifier:
        return self._inner

    def warmup(self, wait: bool = False) -> None:
        w = getattr(self._inner, "warmup", None)
        if w is not None:
            w(wait)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        ck = _keys._cache_key(key.key_bytes, sig, msg)
        with _keys._cache_lock:
            hit = _keys._verify_cache.maybe_get(ck)
        f = VerifyFuture()
        if hit is not None:
            f._complete(hit)
            return f
        with self._lock:
            self._pending.append(
                ((key.key_bytes, sig, msg), f, time.perf_counter()))
        return f

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def flush(self) -> None:
        with self._lock:
            if not self._pending or self._inflight:
                return
            batch, self._pending = self._pending, []
            self._inflight = True

        def work() -> None:
            triples = [t for (t, _f, _t0) in batch]
            # queue-wait: enqueue → dispatch start, per batch; dispatch
            # time is the span's own duration (inner verify_many nests)
            t_disp = time.perf_counter()
            waits = [t_disp - t0 for (_t, _f, t0) in batch]
            with self._span("crypto.batch_dispatch",
                            backend="threaded:%s" % self._inner.name,
                            n=len(batch),
                            queue_wait_max_ms=round(max(waits) * 1e3, 3),
                            queue_wait_mean_ms=round(
                                sum(waits) / len(waits) * 1e3, 3)):
                results = self._inner.verify_many(triples)

            def complete() -> None:
                done = time.perf_counter()
                lat = (self._metrics.new_timer("crypto.verify.latency")
                       if self._metrics is not None else None)
                for ((k, s, m), f, t0), ok in zip(batch, results):
                    with _keys._cache_lock:
                        _keys._verify_cache.put(_keys._cache_key(k, s, m), ok)
                    if lat is not None:
                        lat.update(done - t0)
                    f._complete(ok)
                with self._lock:
                    self._inflight = False
                    more = bool(self._pending)
                if more:
                    # verifies enqueued while the batch was in flight form
                    # the next batch immediately
                    self.flush()

            self._clock.post_to_main(complete)

        threading.Thread(target=work, daemon=True).start()

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        return self._inner.verify_many(triples)


def make_verifier(backend: str = "cpu", clock=None,
                  max_pending: int = 8192,
                  compile_cache_dir: Optional[str] = None,
                  metrics=None, tracer=None) -> BatchSigVerifier:
    """Config-gated backend selection (Config.SIG_VERIFY_BACKEND)."""
    if backend == "cpu":
        v: BatchSigVerifier = CpuSigVerifier()
    elif backend == "tpu":
        v = TpuSigVerifier(max_pending=max_pending,
                           compile_cache_dir=compile_cache_dir)
    elif backend == "tpu-async":
        assert clock is not None
        inner = TpuSigVerifier(max_pending=max_pending,
                               compile_cache_dir=compile_cache_dir)
        inner.tracer = tracer
        v = ThreadedBatchVerifier(inner, clock, metrics=metrics)
    else:
        raise ValueError("unknown sig verify backend %r" % backend)
    v.tracer = tracer
    return v
