"""BatchSigVerifier: the config-gated crypto backend boundary.

North-star parity (BASELINE.json / SURVEY.md intro): the reference calls
libsodium synchronously one signature at a time
(/root/reference/src/crypto/SecretKey.cpp:310-337). Here the boundary is a
batch-oriented service from day one:

    enqueue(key, sig, msg) -> VerifyFuture     (accumulate)
    flush()                                    (dispatch one device batch)
    verify_many(triples) -> [bool]             (whole-ledger/checkpoint drain)

Backends:
- CpuSigVerifier — synchronous OpenSSL; the default (reference's libsodium
  role).
- TpuSigVerifier — ships accumulated triples to the JAX ed25519 kernel in
  one padded, fixed-shape device call (no recompiles); scales batch size
  from a few envelopes (live SCP) to whole checkpoints (catchup replay).
- ThreadedBatchVerifier — wraps either backend so dispatch happens off the
  main thread and futures complete on the VirtualClock main loop, keeping
  the single-threaded consensus invariant (docs/architecture.md:23-26).
- ResilientBatchVerifier — circuit breaker between a primary (device)
  backend and a fallback: N consecutive dispatch failures trip to the
  fallback for a cooldown window with periodic reprobe, so a lost TPU
  degrades throughput instead of killing a ledger close
  (docs/robustness.md; DSig-style degraded operating mode).

The global verify-result cache (keys.py) sits in front of every backend;
cache hits never enqueue.

Clock/threading audit (ISSUE 5 satellite — the 9 touch points):
1. CircuitBreaker.now_fn — injected app clock (make_verifier passes
   clock.now); default is util.timer.real_monotonic for direct
   constructions. Cooldown/reprobe advance deterministically under a
   virtual clock.
2-4. ThreadedBatchVerifier enqueue/dispatch/complete stamps — all three
   read the injected app clock, so the queue-wait span tags and the
   crypto.verify.latency timer are virtual-clock-deterministic in chaos
   soaks (module-level `time` is gone from this file; the D1 static
   rule keeps it out).
5. ThreadedBatchVerifier._lock — TrackedLock, watched by the lock-order
   checker (util/threads.py).
6. ThreadedBatchVerifier worker thread — dispatch off-main; futures
   complete via clock.post_to_main only (single-threaded consensus).
7. TpuSigVerifier._warmup_thread — startup-only, touches JAX state, no
   ledger/consensus objects.
8. keys._cache_lock — TrackedLock shared with the worker thread.
9. ResilientBatchVerifier breaker callbacks (_on_trip/_on_recover) —
   run on whichever thread dispatched (worker under tpu-async): they
   touch only metrics/tracer/flight-recorder, which are thread-safe.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence, Tuple

from ..util.log import get_logger
from ..util.threads import TrackedLock
from ..util.timer import real_monotonic
from ..xdr import PublicKey
from . import keys as _keys

log = get_logger("Perf")

Triple = Tuple[bytes, bytes, bytes]  # (key32, sig, msg)


class VerifyFuture:
    """Completion handle for one enqueued verify."""

    __slots__ = ("_done", "_result", "_callbacks")

    def __init__(self) -> None:
        self._done = False
        self._result = False
        self._callbacks: List[Callable[[bool], None]] = []

    def done(self) -> bool:
        return self._done

    def result(self) -> bool:
        assert self._done, "verify future not completed; call flush()"
        return self._result

    def add_done_callback(self, cb: Callable[[bool], None]) -> None:
        if self._done:
            cb(self._result)
        else:
            self._callbacks.append(cb)

    def _complete(self, ok: bool) -> None:
        self._done = True
        self._result = ok
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(ok)


class BatchSigVerifier:
    """Abstract backend; see module docstring."""

    name = "abstract"
    # True for backends where one big device dispatch beats many small
    # ones — TxSetFrame.check_or_trim prewarms the whole set's signatures
    # through verify_many before walking txs (two-phase validation).
    wants_prewarm = False
    # span tracer (util/tracing.py), metrics registry and fault injector
    # (util/faults.py), installed by make_verifier; None keeps direct
    # constructions (tests, native-apply fallback) silent
    tracer = None
    metrics = None
    faults = None

    def _span(self, name: str, **tags):
        from ..util.tracing import tracer_span
        return tracer_span(self.tracer, name, cat="crypto", **tags)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        raise NotImplementedError

    def prewarm_many(self, triples: Sequence[Triple]) -> List[bool]:
        """Whole-ledger/checkpoint drain (SURVEY.md §2.2): verify a large
        batch in one dispatch and seed the result cache so subsequent
        synchronous per-signature checks all hit. Already-cached triples
        are not re-dispatched. Cache keys for the whole drain hash in one
        native call (prep.c sct_cache_keys) when available."""
        with self._span("crypto.prewarm", backend=self.name,
                        n=len(triples)) as sp:
            cks = None
            if len(triples) >= 256:   # below this the fixed numpy/ctypes
                # marshalling cost exceeds hashlib's per-triple overhead
                # (the native apply engine calls here once per tx, ~20-ish
                # triples; checkpoint drains come in by the thousand)
                from ..native import cache_keys_native
                cks = cache_keys_native(triples)
            if cks is None:
                cks = [_keys._cache_key(k, s, m) for (k, s, m) in triples]
            out: List[Optional[bool]] = [None] * len(triples)
            todo: List[Tuple[int, Triple, bytes]] = []  # (idx, triple, key)
            with _keys._cache_lock:
                for i, (t, ck) in enumerate(zip(triples, cks)):
                    hit = _keys._verify_cache.maybe_get(ck)
                    if hit is not None:
                        out[i] = hit
                    else:
                        todo.append((i, t, ck))
            sp.set_tag("cache_hits", len(triples) - len(todo))
            if todo:
                results = self.verify_many([t for (_i, t, _ck) in todo])
                with _keys._cache_lock:
                    for ((i, _t, ck), ok) in zip(todo, results):
                        _keys._verify_cache.put(ck, ok)
                        out[i] = ok
            return out  # type: ignore[return-value]

    def pending(self) -> int:
        return 0

    # -- shared pending-queue machinery (batch backends) ---------------------
    # TpuSigVerifier and ResilientBatchVerifier share one accumulate/
    # dispatch protocol: cache-probe on enqueue, self-flush at
    # _max_pending, one verify_many per flush, futures completed and the
    # cache fed from the results; a raising dispatch re-completes the
    # batch on the synchronous CPU path instead of stranding futures.

    def _batch_enqueue(self, key: PublicKey, sig: bytes,
                       msg: bytes) -> VerifyFuture:
        ck = _keys._cache_key(key.key_bytes, sig, msg)
        with _keys._cache_lock:
            hit = _keys._verify_cache.maybe_get(ck)
        f = VerifyFuture()
        if hit is not None:
            f._complete(hit)
            return f
        self._pending.append(((key.key_bytes, sig, msg), f))
        if len(self._pending) >= self._max_pending:
            self.flush()
        return f

    def _batch_flush(self) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        triples = [t for (t, _f) in batch]
        try:
            results = self.verify_many(triples)
        except Exception as e:
            log.warning("batch dispatch failed (%s); completing %d "
                        "verifies on CPU fallback", e, len(batch))
            results = _flush_fallback(self, triples)
        for ((k, s, m), f), ok in zip(batch, results):
            with _keys._cache_lock:
                _keys._verify_cache.put(_keys._cache_key(k, s, m), ok)
            f._complete(ok)


def _flush_fallback(verifier, triples: Sequence[Triple]) -> List[bool]:
    """Synchronous CPU re-verify used when a backend's dispatch raises
    mid-flush; counts the event so a silent degradation is visible."""
    m = getattr(verifier, "metrics", None)
    if m is not None:
        m.new_meter("crypto.verify.flush-fallback").mark(len(triples))
    return _keys.raw_verify_batch(triples)


class CpuSigVerifier(BatchSigVerifier):
    """Synchronous OpenSSL backend (libsodium role)."""

    name = "cpu"

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        f = VerifyFuture()
        f._complete(_keys.PubKeyUtils.verify_sig(key, sig, msg))
        return f

    def flush(self) -> None:
        pass

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        with self._span("crypto.verify_many", backend=self.name,
                        n=len(triples)):
            return _keys.raw_verify_batch(triples)


class TpuSigVerifier(BatchSigVerifier):
    """JAX/TPU batched backend.

    Batches are padded up to fixed bucket sizes so the kernel compiles once
    per bucket; oversized batches are split. Correctness contract: identical
    accept/reject decisions to CpuSigVerifier (RFC 8032 cofactorless).
    """

    name = "tpu"
    wants_prewarm = True
    BUCKETS = (128, 512, 2048, 8192)

    # batches below this size stay on one device: sharding a handful of
    # sigs over a pod slice buys nothing and costs a sharded compile
    SHARD_MIN_BATCH = 1024

    def __init__(self, max_pending: int = 8192,
                 compile_cache_dir: Optional[str] = None,
                 shard_threshold: Optional[int] = None) -> None:
        self._pending: List[Tuple[Triple, VerifyFuture]] = []
        self._max_pending = max_pending
        self.batches_dispatched = 0
        self.sigs_verified = 0
        self._compile_cache_dir = compile_cache_dir
        self._warmed = False
        self._warmup_thread: Optional[threading.Thread] = None
        self._sharded_fn = None  # lazy; multi-device dp dispatch
        self._platform: Optional[str] = None  # actual jax platform, lazy
        if shard_threshold is not None:
            self.SHARD_MIN_BATCH = shard_threshold

    def _device_fn(self, batch_size: int):
        """Single-device jit, or the dp-sharded jit when the process sees
        more than one chip and the batch is worth sharding (VERDICT r2 #3:
        the production path must use the mesh, not just the dryrun).
        Cached after first use."""
        import jax
        if jax.device_count() <= 1 or batch_size < self.SHARD_MIN_BATCH:
            from ..ops.ed25519 import verify_batch_jit
            return verify_batch_jit, 1
        if self._sharded_fn is None:
            from ..parallel.mesh import make_mesh, sharded_verify_fn
            self._sharded_fn = sharded_verify_fn(make_mesh())
        return self._sharded_fn, jax.device_count()

    def _enable_compile_cache(self) -> None:
        """Persistent XLA compilation cache: a node restart never re-pays
        kernel compilation (VERDICT r1: lazy compile on the consensus path
        stalls a validator for the compile duration)."""
        import os
        path = self._compile_cache_dir or os.environ.get(
            "JAX_COMPILATION_CACHE_DIR") or os.path.expanduser(
            "~/.cache/stellar_core_tpu/jax_cache")
        try:
            import jax
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
        except Exception as e:  # cache is an optimization, never fatal
            log.warning("compile cache unavailable: %s", e)

    def warmup(self, wait: bool = False) -> None:
        """AOT-compile every bucket shape off the consensus path (startup
        background thread; reference analog: no lazy work on first
        envelope). Idempotent."""
        if self._warmed:
            return
        if self._warmup_thread is None:
            self._warmup_thread = threading.Thread(
                target=self._warmup_impl, daemon=True)
            self._warmup_thread.start()
        if wait:
            self._warmup_thread.join()

    def _warmup_impl(self) -> None:
        try:
            self._enable_compile_cache()
            import numpy as np
            import jax.numpy as jnp
            for b in self.BUCKETS:
                fn, ndev = self._device_fn(b)
                b = -(-b // ndev) * ndev
                args = (jnp.zeros((b, 20), jnp.int32),
                        jnp.zeros((b,), jnp.int32),
                        jnp.zeros((b, 20), jnp.int32),
                        jnp.zeros((b,), jnp.int32),
                        jnp.zeros((b, 64), jnp.int32),
                        jnp.zeros((b, 64), jnp.int32))
                np.asarray(fn(*args))
            self._warmed = True
            log.info("verify kernel warmup complete (%s buckets)",
                     len(self.BUCKETS))
        except Exception as e:
            log.warning("verify kernel warmup failed: %s", e)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        return self._batch_enqueue(key, sig, msg)

    def pending(self) -> int:
        return len(self._pending)

    def flush(self) -> None:
        self._batch_flush()

    def _bucket(self, n: int) -> int:
        for b in self.BUCKETS:
            if n <= b:
                return b
        return self.BUCKETS[-1]

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        from ..ops import ed25519 as _e
        from ..parallel.mesh import pad_batch_to
        import numpy as np
        import jax
        import jax.numpy as jnp

        if self._platform is None:
            # the ACTUAL backing platform ("tpu"/"cpu"/…): a jax-on-CPU
            # run of this verifier is a fallback and must trace as one
            self._platform = jax.devices()[0].platform
        out: List[bool] = []
        with self._span("crypto.verify_many", backend=self.name,
                        platform=self._platform, n=len(triples)) as sp:
            i = 0
            batches = 0
            pad_waste = 0
            while i < len(triples):
                chunk = triples[i:i + self.BUCKETS[-1]]
                n = len(chunk)
                fn, ndev = self._device_fn(self._bucket(n))
                b = -(-self._bucket(n) // ndev) * ndev
                with self._span("crypto.dispatch", backend=self.name,
                                n=n, bucket=b, pad=b - n):
                    prep = _e.prepare_batch(
                        [t[0] for t in chunk], [t[1] for t in chunk],
                        [t[2] for t in chunk])
                    padded = pad_batch_to(prep, b)  # pad lanes pre_ok=False
                    ok = np.asarray(fn(
                        jnp.asarray(padded["ay"]),
                        jnp.asarray(padded["a_sign"]),
                        jnp.asarray(padded["ry"]),
                        jnp.asarray(padded["r_sign"]),
                        jnp.asarray(padded["s_nibs"]),
                        jnp.asarray(padded["k_nibs"])))
                out.extend((ok[:n] & prep["pre_ok"]).tolist())
                self.batches_dispatched += 1
                self.sigs_verified += n
                batches += 1
                pad_waste += b - n
                i += n
            sp.set_tag("batches", batches)
            sp.set_tag("pad_waste", pad_waste)
        return out


class CircuitBreaker:
    """closed → open → half-open → closed over the device-dispatch path.

    CLOSED: dispatches flow to the primary; `threshold` CONSECUTIVE
    failures trip to OPEN. OPEN: primary is bypassed until `cooldown_s`
    elapses on the injected clock, then the next allow() becomes the
    HALF-OPEN probe. HALF-OPEN: one success re-closes (recover), one
    failure re-opens for another cooldown. Time comes from `now_fn`
    (virtual clock in tests/simulation) so trips and reprobes are
    deterministic."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"
    _STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 now_fn: Optional[Callable[[], float]] = None,
                 on_trip: Optional[Callable[[], None]] = None,
                 on_recover: Optional[Callable[[], None]] = None) -> None:
        self.threshold = max(1, threshold)
        self.cooldown_s = cooldown_s
        self._now = now_fn or real_monotonic
        self.on_trip = on_trip
        self.on_recover = on_recover
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.trips = 0
        self.recoveries = 0
        self._retry_at = 0.0

    def allow(self) -> bool:
        """May the next dispatch try the primary?"""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN and self._now() >= self._retry_at:
            self.state = self.HALF_OPEN
            return True
        return self.state == self.HALF_OPEN

    def record_success(self) -> None:
        recovered = self.state == self.HALF_OPEN
        self.state = self.CLOSED
        self.consecutive_failures = 0
        if recovered:
            self.recoveries += 1
            if self.on_recover is not None:
                self.on_recover()

    def record_failure(self) -> bool:
        """Returns True when this failure tripped (or re-opened) the
        breaker."""
        self.consecutive_failures += 1
        if self.state == self.HALF_OPEN or \
                self.consecutive_failures >= self.threshold:
            reopened = self.state != self.CLOSED
            self.state = self.OPEN
            self._retry_at = self._now() + self.cooldown_s
            if not reopened:
                self.trips += 1
                if self.on_trip is not None:
                    self.on_trip()
            return True
        return False

    def state_code(self) -> int:
        return self._STATE_CODE[self.state]

    def to_json(self) -> dict:
        return {"state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "trips": self.trips, "recoveries": self.recoveries,
                "threshold": self.threshold, "cooldown_s": self.cooldown_s,
                "retry_at": self._retry_at}


class ResilientBatchVerifier(BatchSigVerifier):
    """Primary backend behind a circuit breaker, CPU fallback beside it.

    Every dispatch-shaped call (verify_many; flush routes through it)
    asks the breaker whether the primary may be tried; a raising primary
    records a failure and the batch re-runs on the fallback, so callers
    always get results. A trip emits metrics + a flight-recorder dump;
    recovery (first successful half-open probe) emits the matching
    recover marker — the signals the chaos soak asserts on."""

    name = "resilient"

    def __init__(self, primary: BatchSigVerifier,
                 fallback: BatchSigVerifier,
                 breaker: Optional[CircuitBreaker] = None,
                 max_pending: int = 8192) -> None:
        self.primary = primary
        self.fallback = fallback
        self.breaker = breaker or CircuitBreaker()
        self.breaker.on_trip = self._on_trip
        self.breaker.on_recover = self._on_recover
        self.flight_recorder = None   # installed by make_verifier
        self._pending: List[Tuple[Triple, VerifyFuture]] = []
        self._max_pending = max_pending

    # -- breaker events ------------------------------------------------------
    def _breaker_mark(self, event: str) -> None:
        if self.metrics is not None:
            self.metrics.new_meter("crypto.breaker.%s" % event).mark()
            self.metrics.new_counter("crypto.breaker.state").set_count(
                self.breaker.state_code())
        from ..util.tracing import tracer_instant
        tracer_instant(self.tracer, "crypto.breaker.%s" % event,
                       cat="crypto", primary=self.primary.name,
                       failures=self.breaker.consecutive_failures)

    def _on_trip(self) -> None:
        log.warning("verify breaker TRIPPED: %d consecutive %s-dispatch "
                    "failures; falling back to %s for %.0fs",
                    self.breaker.consecutive_failures, self.primary.name,
                    self.fallback.name, self.breaker.cooldown_s)
        self._breaker_mark("trip")
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                "verify-breaker-trip",
                extra={"primary": self.primary.name,
                       "breaker": self.breaker.to_json()})

    def _on_recover(self) -> None:
        log.info("verify breaker recovered: %s backend healthy again",
                 self.primary.name)
        self._breaker_mark("recover")

    # -- delegation ----------------------------------------------------------
    @property
    def wants_prewarm(self) -> bool:
        return self.primary.wants_prewarm

    @property
    def inner(self) -> BatchSigVerifier:
        return self.primary

    @property
    def batches_dispatched(self) -> int:
        return getattr(self.primary, "batches_dispatched", 0)

    @property
    def sigs_verified(self) -> int:
        return getattr(self.primary, "sigs_verified", 0)

    def warmup(self, wait: bool = False) -> None:
        w = getattr(self.primary, "warmup", None)
        if w is not None:
            w(wait)

    # -- verify paths --------------------------------------------------------
    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        if self.breaker.allow():
            try:
                # the primary attempt gets its own span so an injected
                # (or real) dispatch failure is tagged on the drain it
                # killed, not floating free on the timeline
                with self._span("crypto.dispatch_primary",
                                backend=self.primary.name,
                                n=len(triples)):
                    if self.faults is not None:
                        self.faults.fire_point("device.dispatch")
                    out = self.primary.verify_many(triples)
                self.breaker.record_success()
                return out
            except Exception as e:
                if self.metrics is not None:
                    self.metrics.new_meter(
                        "crypto.verify.dispatch-failure").mark()
                tripped = self.breaker.record_failure()
                if not tripped:
                    log.warning("%s dispatch failed (%s): %d/%d toward "
                                "breaker trip", self.primary.name, e,
                                self.breaker.consecutive_failures,
                                self.breaker.threshold)
        if self.metrics is not None:
            # drains served by the fallback while the primary is failing
            # or the breaker is open — the "completed on fallback" signal
            # the chaos soak asserts on
            self.metrics.new_meter("crypto.verify.fallback-drain").mark()
        with self._span("crypto.verify_fallback", backend=self.name,
                        n=len(triples), breaker=self.breaker.state):
            return self.fallback.verify_many(triples)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        return self._batch_enqueue(key, sig, msg)

    def pending(self) -> int:
        return len(self._pending)

    def flush(self) -> None:
        # verify_many (almost) never raises here: a primary failure is
        # absorbed by the breaker and the batch re-runs on the fallback —
        # a trip mid-drain still completes every future correctly
        self._batch_flush()


class ThreadedBatchVerifier(BatchSigVerifier):
    """Async wrapper: dispatch runs on a worker thread, futures complete on
    the main loop via clock.post_to_main — the enqueue-and-continue protocol
    SURVEY.md §7 requires at the verifyEnvelope/checkValid boundary."""

    name = "threaded"

    def __init__(self, inner: BatchSigVerifier, clock,
                 metrics=None) -> None:
        self._inner = inner
        self._clock = clock
        self._metrics = metrics
        self._lock = TrackedLock("crypto.threaded-pending")
        # (triple, future, enqueue app-clock stamp): the timestamp feeds
        # the crypto.verify.latency enqueue-to-complete timer (the
        # p50/p99 the live SCP path actually feels); the app clock, not
        # wall time, so chaos soaks under a virtual clock stay
        # deterministic
        self._pending: List[Tuple[Triple, VerifyFuture, float]] = []
        self._inflight = False

    @property
    def wants_prewarm(self) -> bool:
        return self._inner.wants_prewarm

    @property
    def inner(self) -> BatchSigVerifier:
        """The DEVICE verifier (unwrapping a resilient layer): callers
        tune BUCKETS / read dispatch counters on the actual backend."""
        return getattr(self._inner, "inner", self._inner)

    @property
    def breaker(self):
        return getattr(self._inner, "breaker", None)

    def warmup(self, wait: bool = False) -> None:
        w = getattr(self._inner, "warmup", None)
        if w is not None:
            w(wait)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        ck = _keys._cache_key(key.key_bytes, sig, msg)
        with _keys._cache_lock:
            hit = _keys._verify_cache.maybe_get(ck)
        f = VerifyFuture()
        if hit is not None:
            f._complete(hit)
            return f
        with self._lock:
            self._pending.append(
                ((key.key_bytes, sig, msg), f, self._clock.now()))
        return f

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def flush(self) -> None:
        with self._lock:
            if not self._pending or self._inflight:
                return
            batch, self._pending = self._pending, []
            self._inflight = True

        def work() -> None:
            triples = [t for (t, _f, _t0) in batch]
            # queue-wait: enqueue → dispatch start, per batch; dispatch
            # time is the span's own duration (inner verify_many nests)
            t_disp = self._clock.now()
            waits = [t_disp - t0 for (_t, _f, t0) in batch]
            with self._span("crypto.batch_dispatch",
                            backend="threaded:%s" % self._inner.name,
                            n=len(batch),
                            queue_wait_max_ms=round(max(waits) * 1e3, 3),
                            queue_wait_mean_ms=round(
                                sum(waits) / len(waits) * 1e3, 3)):
                try:
                    results = self._inner.verify_many(triples)
                except Exception as e:
                    # the worker thread must neither die with futures
                    # pending nor leave _inflight latched (that would
                    # no-op every later flush — a permanent wedge)
                    log.warning("threaded dispatch failed (%s); completing "
                                "%d verifies on CPU fallback", e, len(batch))
                    results = _flush_fallback(self, triples)

            def complete() -> None:
                done = self._clock.now()
                lat = (self._metrics.new_timer("crypto.verify.latency")
                       if self._metrics is not None else None)
                for ((k, s, m), f, t0), ok in zip(batch, results):
                    with _keys._cache_lock:
                        _keys._verify_cache.put(_keys._cache_key(k, s, m), ok)
                    if lat is not None:
                        lat.update(done - t0)
                    f._complete(ok)
                with self._lock:
                    self._inflight = False
                    more = bool(self._pending)
                if more:
                    # verifies enqueued while the batch was in flight form
                    # the next batch immediately
                    self.flush()

            self._clock.post_to_main(complete)

        threading.Thread(target=work, daemon=True).start()

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        return self._inner.verify_many(triples)


def make_verifier(backend: str = "cpu", clock=None,
                  max_pending: int = 8192,
                  compile_cache_dir: Optional[str] = None,
                  metrics=None, tracer=None, faults=None,
                  flight_recorder=None,
                  breaker_threshold: int = 3,
                  breaker_cooldown: float = 30.0) -> BatchSigVerifier:
    """Config-gated backend selection (Config.SIG_VERIFY_BACKEND).

    Device backends ("tpu", "tpu-async") are always wrapped in a
    ResilientBatchVerifier with a CPU fallback; "cpu-resilient" wraps the
    CPU backend in the same breaker machinery so chaos runs exercise the
    device failure domain on device-less containers."""
    now_fn = clock.now if clock is not None else None

    def resilient(primary: BatchSigVerifier) -> ResilientBatchVerifier:
        primary.tracer = tracer
        primary.metrics = metrics
        fb = CpuSigVerifier()
        fb.tracer = tracer
        r = ResilientBatchVerifier(
            primary, fb,
            CircuitBreaker(threshold=breaker_threshold,
                           cooldown_s=breaker_cooldown, now_fn=now_fn),
            max_pending=max_pending)
        r.tracer = tracer
        r.flight_recorder = flight_recorder
        return r

    if backend == "cpu":
        v: BatchSigVerifier = CpuSigVerifier()
    elif backend == "cpu-resilient":
        v = resilient(CpuSigVerifier())
    elif backend == "tpu":
        v = resilient(TpuSigVerifier(max_pending=max_pending,
                                     compile_cache_dir=compile_cache_dir))
    elif backend == "tpu-async":
        assert clock is not None
        inner = resilient(TpuSigVerifier(max_pending=max_pending,
                                         compile_cache_dir=compile_cache_dir))
        inner.metrics = metrics
        inner.faults = faults
        v = ThreadedBatchVerifier(inner, clock, metrics=metrics)
    else:
        raise ValueError("unknown sig verify backend %r" % backend)
    v.tracer = tracer
    v.metrics = metrics
    v.faults = faults
    return v
