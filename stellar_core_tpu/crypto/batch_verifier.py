"""BatchSigVerifier: the config-gated crypto backend boundary.

North-star parity (BASELINE.json / SURVEY.md intro): the reference calls
libsodium synchronously one signature at a time
(/root/reference/src/crypto/SecretKey.cpp:310-337). Here the boundary is a
batch-oriented service from day one:

    enqueue(key, sig, msg) -> VerifyFuture     (accumulate)
    flush()                                    (dispatch one device batch)
    verify_many(triples) -> [bool]             (whole-ledger/checkpoint drain)

Backends:
- CpuSigVerifier — synchronous OpenSSL; the default (reference's libsodium
  role).
- TpuSigVerifier — ships accumulated triples to the JAX ed25519 kernel in
  one padded, fixed-shape device call (no recompiles); scales batch size
  from a few envelopes (live SCP) to whole checkpoints (catchup replay).
- ThreadedBatchVerifier — wraps either backend so dispatch happens off the
  main thread and futures complete on the VirtualClock main loop, keeping
  the single-threaded consensus invariant (docs/architecture.md:23-26).
- ResilientBatchVerifier — circuit breaker between a primary (device)
  backend and a fallback: N consecutive dispatch failures trip to the
  fallback for a cooldown window with periodic reprobe, so a lost TPU
  degrades throughput instead of killing a ledger close
  (docs/robustness.md; DSig-style degraded operating mode).

The global verify-result cache (keys.py) sits in front of every backend;
cache hits never enqueue.

Clock/threading audit (ISSUE 5 satellite — the 9 touch points):
1. CircuitBreaker.now_fn — injected app clock (make_verifier passes
   clock.now); default is util.timer.real_monotonic for direct
   constructions. Cooldown/reprobe advance deterministically under a
   virtual clock.
2-4. ThreadedBatchVerifier enqueue/dispatch/complete stamps — all three
   read the injected app clock, so the queue-wait span tags and the
   crypto.verify.latency timer are virtual-clock-deterministic in chaos
   soaks (module-level `time` is gone from this file; the D1 static
   rule keeps it out).
5. ThreadedBatchVerifier._lock — TrackedLock, watched by the lock-order
   checker (util/threads.py).
6. ThreadedBatchVerifier worker thread — dispatch off-main; futures
   complete via clock.post_to_main only (single-threaded consensus).
7. TpuSigVerifier._warmup_thread — startup-only, touches JAX state, no
   ledger/consensus objects.
8. keys._cache_lock — TrackedLock shared with the worker thread.
9. ResilientBatchVerifier breaker callbacks (_on_trip/_on_recover) —
   run on whichever thread dispatched (worker under tpu-async): they
   touch only metrics/tracer/flight-recorder, which are thread-safe.
10. VerifierStats (the ISSUE 6 cockpit) — event stamps read the
    injected app clock (now_fn), compile DURATIONS read
    util.timer.real_monotonic (sanctioned: an XLA compile takes real
    time under a frozen virtual clock); recorded from the main loop,
    the dispatch worker, the staging worker and the warmup thread under
    its own TrackedLock("crypto.verifier-stats").
11. _StagingJob worker ("crypto.verify-staging", ISSUE 11) — packs and
    device_puts the next drain chunk while the fleet executes the
    current one; touches only host numpy buffers, JAX transfer APIs and
    VerifierStats (thread-safe), never ledger/consensus objects.
    Overlap DURATIONS read util.timer.real_monotonic (sanctioned: the
    host/device overlap being measured is real elapsed time).
12. DeviceFleetHealth per-device breakers — same injected app clock as
    the resilient layer's breaker (make_verifier passes clock.now), so
    per-chip cooldown/reprobe advance deterministically under a
    virtual clock; callbacks touch only metrics/tracer/flight-recorder.

All three crypto workers (dispatch, staging, warmup) spawn through
util.threads.spawn_worker under names registered in
WORKER_THREAD_REGISTRY; the static T1 rule follows spawn_worker targets
like any Thread(target=...) site.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence, Tuple

from ..util.log import get_logger
from ..util.metrics import MetricsRegistry
from ..util.threads import TrackedLock, spawn_worker
from ..util.timer import real_monotonic
from ..util.tracing import tracer_instant
from ..xdr import PublicKey
from . import keys as _keys

log = get_logger("Perf")

Triple = Tuple[bytes, bytes, bytes]  # (key32, sig, msg)


class VerifierStats:
    """Cockpit aggregation for the batch-verify boundary (ISSUE 6
    tentpole; docs/observability.md#device-cockpit).

    One instance per make_verifier() stack, shared by every layer —
    device backend, CPU fallback, resilient wrapper, threaded wrapper —
    so drains are attributed to the backend that actually SERVED them
    (a fallback drain while the breaker is open counts against "cpu",
    never against the device). The same aggregate objects feed three
    consumers:

    - the admin `verifier` endpoint (`to_json`): per-bucket occupancy /
      pad-waste histograms, warmup + compile-cache status, queue depth;
    - the metrics registry (`verifier.*` names) — which makes the whole
      cockpit scrapeable via `metrics?format=prometheus`;
    - the tracer: `verifier.warmup.*` instants, so compile/warmup
      progress appears in Chrome traces and flight dumps.

    Clocks: event STAMPS (`t` fields) read the injected app clock
    (`now_fn` = clock.now via make_verifier), so chaos soaks under a
    virtual clock stay deterministic; compile DURATIONS are real
    elapsed seconds via util.timer.real_monotonic — an XLA compile
    takes real time even while the app clock is frozen. Recording
    happens on the main loop, the threaded dispatch worker and the
    warmup thread; aggregate mutation is under `_lock`, registry
    metric objects are individually thread-safe."""

    def __init__(self, metrics=None, tracer=None, now_fn=None,
                 flight_recorder=None) -> None:
        self._now = now_fn or real_monotonic
        # a private registry when none is injected keeps direct
        # constructions (tests, bench children) app-registry-free while
        # letting every registration below use the new_* idiom the M1
        # metric-catalog scanner keys on
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.tracer = tracer
        self.flight_recorder = flight_recorder
        self._lock = TrackedLock("crypto.verifier-stats")
        self.backends: dict = {}      # name -> {drains, sigs, pad_total}
        self.buckets: dict = {}       # bucket -> counts + histograms
        # per-device fleet attribution (ISSUE 11): device index ->
        # {drains, sigs, pad_total, inflight} for every padded dispatch
        # the device participated in
        self.devices: dict = {}
        # non-bucketed (CPU-path) drain sizes, power-of-two quantized so
        # the dict stays bounded: the raw material bucket_traffic() maps
        # onto the candidate ladder for cockpit-driven warm start
        self.drain_sizes: dict = {}   # backend -> {quantized_n: drains}
        # double-buffer staging aggregate (host pack/device_put overlap
        # with device execution, ISSUE 11 tentpole)
        self.staging = {"chunks": 0, "staged_s": 0.0, "overlap_s": 0.0,
                        "last_overlap_pct": None, "stalls": 0}
        self.queue = {"depth": 0, "inflight": 0,
                      "wait_last_mean_ms": None, "wait_last_max_ms": None}
        self.warmup = {"state": "idle", "planned": [], "source": None,
                       "begun_t": None, "done_t": None, "error": None,
                       "buckets": {}}
        self.compile_cache = {"enabled": None, "dir": None, "hits": 0,
                              "misses": 0, "unknown": 0, "error": None}
        # fixed-name registry metrics, created eagerly so the Prometheus
        # export carries the full cockpit shape from the first scrape
        m = self.metrics
        self._h_batch = m.new_histogram("verifier.drain.batch-size")
        self._h_pad = m.new_histogram("verifier.drain.pad-waste")
        self._h_occ = m.new_histogram("verifier.drain.occupancy-pct")
        self._h_splits = m.new_histogram("verifier.drain.splits")
        self._h_wsec = m.new_histogram("verifier.warmup.bucket-seconds")
        self._t_wait = m.new_timer("verifier.queue.wait")
        self._g_depth = m.new_gauge("verifier.queue.depth")
        self._g_inflight = m.new_gauge("verifier.queue.inflight")
        self._g_overlap = m.new_gauge("verifier.staging.overlap-pct")
        self._g_wstate = m.new_gauge("verifier.warmup.state")
        self._g_wdone = m.new_gauge("verifier.warmup.buckets-done")
        self._g_wsource = m.new_gauge("verifier.warmup.source")
        self._g_cc = m.new_gauge("verifier.compile-cache.enabled")
        self._c_hit = m.new_counter("verifier.compile-cache.hit")
        self._c_miss = m.new_counter("verifier.compile-cache.miss")

    # -- drains --------------------------------------------------------------
    def record_drain(self, backend: str, n: int, pad: int = 0,
                     splits: int = 1, bucketed: bool = False) -> None:
        """One verify_many drain, attributed to the backend that served
        it. `pad` is the total padding-lane waste (0 on unpadded CPU
        drains — which still count, so bucket-selection analysis sees
        ALL traffic, not just the device path). `bucketed=True` means the
        drain's traffic already landed in the exact per-bucket dispatch
        stats (record_bucket_dispatch) — unbucketed drains additionally
        feed `drain_sizes`, the CPU-side half of bucket_traffic()."""
        occ = 100.0 * n / (n + pad) if (n + pad) else 100.0
        with self._lock:
            d = self.backends.setdefault(
                backend, {"drains": 0, "sigs": 0, "pad_total": 0})
            d["drains"] += 1
            d["sigs"] += n
            d["pad_total"] += pad
            if not bucketed and n > 0:
                q = 1 << (n - 1).bit_length()   # next power of two
                sizes = self.drain_sizes.setdefault(backend, {})
                sizes[q] = sizes.get(q, 0) + 1
        self._h_batch.update(n)
        self._h_pad.update(pad)
        self._h_occ.update(occ)
        self._h_splits.update(splits)
        self.metrics.new_meter("verifier.drains.%s" % backend).mark()

    def record_bucket_dispatch(self, bucket: int, n: int,
                               pad: int) -> None:
        """One padded device dispatch into a fixed bucket shape (the
        device path only — buckets come from TpuSigVerifier.BUCKETS, so
        the dynamic `verifier.bucket.<b>.*` name space stays bounded)."""
        occ = 100.0 * n / bucket if bucket else 100.0
        with self._lock:
            b = self.buckets.get(bucket)
            if b is None:
                b = self.buckets[bucket] = {
                    "drains": 0, "sigs": 0, "pad_total": 0,
                    "_occ": self.metrics.new_histogram(
                        "verifier.bucket.%d.occupancy-pct" % bucket),
                    "_pad": self.metrics.new_histogram(
                        "verifier.bucket.%d.pad-waste" % bucket),
                    "_m": self.metrics.new_meter(
                        "verifier.bucket.%d.drains" % bucket)}
            b["drains"] += 1
            b["sigs"] += n
            b["pad_total"] += pad
        b["_occ"].update(occ)
        b["_pad"].update(pad)
        b["_m"].mark()

    # -- fleet: per-device attribution (ISSUE 11) ----------------------------
    def record_device_dispatch(self, idx: int, n: int, pad: int) -> None:
        """One device's share of a padded dispatch (its lanes on a
        sharded mesh drain, or the whole bucket on a single-device
        dispatch): per-device throughput attribution for the admin
        `verifier` endpoint's fleet rows."""
        with self._lock:
            d = self.devices.setdefault(
                idx, {"drains": 0, "sigs": 0, "pad_total": 0,
                      "inflight": 0})
            d["drains"] += 1
            d["sigs"] += n
            d["pad_total"] += pad
        self.metrics.new_meter("verifier.device.%d.drains" % idx).mark()

    def set_device_inflight(self, idx: int, inflight: bool) -> None:
        with self._lock:
            d = self.devices.setdefault(
                idx, {"drains": 0, "sigs": 0, "pad_total": 0,
                      "inflight": 0})
            d["inflight"] = int(inflight)
        self.metrics.new_gauge(
            "verifier.device.%d.inflight" % idx).set(int(inflight))

    def set_device_breaker(self, idx: int, code: int) -> None:
        self.metrics.new_gauge("verifier.device.%d.breaker" % idx).set(code)

    def device_trip(self, idx: int, breaker_json: dict) -> None:
        self.metrics.new_meter("verifier.device.trip").mark()
        tracer_instant(self.tracer, "verifier.device.trip", cat="crypto",
                       device=idx)
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                "verify-device-trip",
                extra={"device": idx, "breaker": breaker_json})

    def device_recover(self, idx: int) -> None:
        self.metrics.new_meter("verifier.device.recover").mark()
        tracer_instant(self.tracer, "verifier.device.recover",
                       cat="crypto", device=idx)

    # -- fleet: double-buffer staging ----------------------------------------
    def record_staging(self, staged_s: float, overlap_s: float,
                       chunks: int) -> None:
        """One drain's staging totals: `staged_s` of host pack +
        host→device transfer ran on the staging worker, `overlap_s` of
        it concurrent with device execution of the previous chunk. The
        overlap-pct gauge is the headline: near 100 means the device
        never idles on host marshalling."""
        pct = round(100.0 * overlap_s / staged_s, 1) if staged_s > 0 \
            else 100.0
        with self._lock:
            s = self.staging
            s["chunks"] += chunks
            s["staged_s"] = round(s["staged_s"] + staged_s, 6)
            s["overlap_s"] = round(s["overlap_s"] + overlap_s, 6)
            s["last_overlap_pct"] = pct
        self._g_overlap.set(pct)

    def record_staging_stall(self) -> None:
        """The staging worker failed (or the verify.staging-stall fault
        fired): the chunk re-staged synchronously on the dispatch
        thread — the drain completed, but the device idled."""
        with self._lock:
            self.staging["stalls"] += 1
        self.metrics.new_meter("verifier.staging.stall").mark()
        tracer_instant(self.tracer, "verifier.staging.stall", cat="crypto")

    # -- cockpit-driven bucket selection -------------------------------------
    def bucket_traffic(self, candidates) -> dict:
        """Observed drain traffic mapped onto a candidate bucket ladder:
        exact per-bucket device dispatch counts plus every non-bucketed
        (CPU-path) drain size mapped to the smallest candidate that
        holds it. This is the evidence warmup_plan() ranks — CPU drains
        included, so bucket selection sees ALL traffic."""
        cands = sorted(candidates)

        def fit(n: int) -> int:
            for c in cands:
                if n <= c:
                    return c
            return cands[-1]

        out: dict = {}
        with self._lock:
            for b, d in self.buckets.items():
                out[fit(b)] = out.get(fit(b), 0) + d["drains"]
            for sizes in self.drain_sizes.values():
                for n, drains in sizes.items():
                    out[fit(n)] = out.get(fit(n), 0) + drains
        return out

    def bucket_occupancy_p50(self) -> dict:
        """Median occupancy-% per device bucket (None until sampled) —
        the pad-waste signal warmup_plan() uses to pre-warm the next
        smaller shape under a mostly-padding bucket."""
        out = {}
        with self._lock:
            for b, d in self.buckets.items():
                snap = d["_occ"].snapshot()
                out[b] = snap["median"] if snap["count"] else None
        return out

    # -- queue ---------------------------------------------------------------
    def set_queue_depth(self, depth: int) -> None:
        self.queue["depth"] = depth
        self._g_depth.set(depth)

    def set_inflight(self, inflight: bool) -> None:
        self.queue["inflight"] = int(inflight)
        self._g_inflight.set(int(inflight))

    def record_queue_wait(self, mean_s: float, max_s: float) -> None:
        self.queue["wait_last_mean_ms"] = round(mean_s * 1e3, 3)
        self.queue["wait_last_max_ms"] = round(max_s * 1e3, 3)
        self._t_wait.update(mean_s)

    # -- compile cache + warmup ---------------------------------------------
    def compile_cache_enabled(self, path: str) -> None:
        self.compile_cache.update(
            {"enabled": True, "dir": path, "error": None})
        self._g_cc.set(1)

    def compile_cache_error(self, err: str) -> None:
        """The persistent-XLA-cache enable failed: previously a swallowed
        log.warning — now a meter, a tracer instant and a flight dump,
        because a node silently paying cold compiles on every restart is
        exactly the regression the cockpit exists to catch."""
        self.compile_cache.update({"enabled": False, "error": err})
        self._g_cc.set(0)
        self.metrics.new_meter("verifier.compile-cache.unavailable").mark()
        tracer_instant(self.tracer, "verifier.compile-cache.unavailable",
                       cat="crypto", error=err)
        if self.flight_recorder is not None:
            self.flight_recorder.dump("compile-cache-unavailable",
                                      extra={"error": err})

    WARMUP_STATE_CODE = {"idle": 0, "running": 1, "done": 2, "failed": 3}
    # where the warm-start bucket set came from: the hardcoded default
    # ladder, or the cockpit-derived plan persisted beside the XLA cache
    WARMUP_SOURCE_CODE = {"default": 0, "cockpit": 1}

    def warmup_begin(self, buckets, source: str = "default") -> None:
        with self._lock:
            self.warmup.update({"state": "running", "begun_t": self._now(),
                                "done_t": None, "error": None,
                                "source": source,
                                "planned": list(buckets)})
        self._g_wstate.set(self.WARMUP_STATE_CODE["running"])
        self._g_wsource.set(self.WARMUP_SOURCE_CODE.get(source, 0))
        tracer_instant(self.tracer, "verifier.warmup.begin", cat="crypto",
                       buckets=list(buckets), source=source)

    def warmup_bucket_done(self, bucket: int, seconds: float,
                           cache_hit) -> None:
        """One bucket shape compiled (or loaded). `cache_hit` is
        True/False from the compile-cache-entry diff, None when the
        cache dir is unreadable."""
        cache = ("hit" if cache_hit is True else
                 "miss" if cache_hit is False else "unknown")
        with self._lock:
            self.warmup["buckets"][str(bucket)] = {
                "seconds": round(seconds, 3), "cache": cache,
                "t": self._now()}
            done = len(self.warmup["buckets"])
            self.compile_cache[
                {"hit": "hits", "miss": "misses",
                 "unknown": "unknown"}[cache]] += 1
        self._h_wsec.update(seconds)
        self._g_wdone.set(done)
        if cache_hit is True:
            self._c_hit.inc()
        elif cache_hit is False:
            self._c_miss.inc()
        tracer_instant(self.tracer, "verifier.warmup.bucket", cat="crypto",
                       bucket=bucket, seconds=round(seconds, 3),
                       cache=cache)

    def warmup_done(self) -> None:
        with self._lock:
            self.warmup.update({"state": "done", "done_t": self._now()})
            total = sum(b["seconds"]
                        for b in self.warmup["buckets"].values())
            n = len(self.warmup["buckets"])
        self._g_wstate.set(self.WARMUP_STATE_CODE["done"])
        tracer_instant(self.tracer, "verifier.warmup.end", cat="crypto",
                       buckets=n, total_s=round(total, 3))

    def warmup_failed(self, err: str) -> None:
        with self._lock:
            self.warmup.update({"state": "failed", "done_t": self._now(),
                                "error": err})
        self._g_wstate.set(self.WARMUP_STATE_CODE["failed"])
        self.metrics.new_meter("verifier.warmup.failure").mark()
        tracer_instant(self.tracer, "verifier.warmup.failed", cat="crypto",
                       error=err)
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                "verify-warmup-failed",
                extra={"error": err, "warmup": self.warmup_json()})

    # -- export --------------------------------------------------------------
    def warmup_json(self) -> dict:
        with self._lock:
            w = dict(self.warmup)
            w["buckets"] = {k: dict(v)
                            for k, v in self.warmup["buckets"].items()}
        return w

    def to_json(self) -> dict:
        """The cockpit blob served by the admin `verifier` endpoint."""
        with self._lock:
            backends = {k: dict(v) for k, v in self.backends.items()}
            buckets = {
                str(b): {"drains": d["drains"], "sigs": d["sigs"],
                         "pad_waste_total": d["pad_total"],
                         "occupancy_pct": d["_occ"].snapshot(),
                         "pad_waste": d["_pad"].snapshot()}
                for b, d in sorted(self.buckets.items())}
            devices = {str(i): dict(d)
                       for i, d in sorted(self.devices.items())}
            staging = dict(self.staging)
            queue = dict(self.queue)
            cc = dict(self.compile_cache)
        return {
            "drains": {"by_backend": backends,
                       "batch_size": self._h_batch.snapshot(),
                       "pad_waste": self._h_pad.snapshot(),
                       "occupancy_pct": self._h_occ.snapshot(),
                       "splits": self._h_splits.snapshot()},
            "buckets": buckets,
            "devices": devices,
            "staging": staging,
            "warmup": self.warmup_json(),
            "compile_cache": cc,
            "queue": queue,
        }


def warmup_plan(stats, candidates):
    """Cockpit-driven warm-start bucket selection (ISSUE 11 tentpole):
    derive the AOT warmup set from the `verifier.bucket.<b>.drains` /
    `pad-waste` histograms the cockpit aggregates — CPU drains included
    via `drain_sizes`, so selection sees ALL traffic.

    Rules, in order:
    - only candidate shapes with observed traffic are warmed, hottest
      (most drains) first, so the first compile serves the most load;
    - a device bucket whose median occupancy is below 50% mostly pays
      padding: the next smaller candidate is appended too, so the
      dispatcher can split down without a cold compile;
    - no cockpit evidence at all (fresh node, stats=None) falls back to
      the full candidate ladder.

    Returns (buckets, info) where info carries `source`
    ("cockpit"/"default") and the evidence the choice was made from —
    persisted beside the XLA cache by save_warmup_plan() so a warm
    restart compiles only the shapes real traffic uses."""
    cands = sorted(candidates)
    if stats is None:
        return list(cands), {"source": "default",
                             "reason": "no cockpit stats"}
    traffic = stats.bucket_traffic(cands)
    if not traffic:
        return list(cands), {"source": "default",
                             "reason": "no recorded drains"}
    chosen = sorted(traffic, key=lambda b: (-traffic[b], b))
    extra = []
    for b, occ_p50 in sorted(stats.bucket_occupancy_p50().items()):
        if occ_p50 is None or occ_p50 >= 50.0 or b not in cands:
            continue
        i = cands.index(b)
        if i > 0 and cands[i - 1] not in chosen and \
                cands[i - 1] not in extra:
            extra.append(cands[i - 1])
    return chosen + extra, {"source": "cockpit", "traffic": traffic,
                            "low_occupancy_extra": extra}


class VerifyFuture:
    """Completion handle for one enqueued verify."""

    __slots__ = ("_done", "_result", "_callbacks")

    def __init__(self) -> None:
        self._done = False
        self._result = False
        self._callbacks: List[Callable[[bool], None]] = []

    def done(self) -> bool:
        return self._done

    def result(self) -> bool:
        assert self._done, "verify future not completed; call flush()"
        return self._result

    def add_done_callback(self, cb: Callable[[bool], None]) -> None:
        if self._done:
            cb(self._result)
        else:
            self._callbacks.append(cb)

    def _complete(self, ok: bool) -> None:
        self._done = True
        self._result = ok
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(ok)


class BatchSigVerifier:
    """Abstract backend; see module docstring."""

    name = "abstract"
    # True for backends where one big device dispatch beats many small
    # ones — TxSetFrame.check_or_trim prewarms the whole set's signatures
    # through verify_many before walking txs (two-phase validation).
    wants_prewarm = False
    # span tracer (util/tracing.py), metrics registry, fault injector
    # (util/faults.py) and the shared VerifierStats cockpit, installed
    # by make_verifier; None keeps direct constructions (tests,
    # native-apply fallback) silent
    tracer = None
    metrics = None
    faults = None
    stats = None

    def _span(self, name: str, **tags):
        from ..util.tracing import tracer_span
        return tracer_span(self.tracer, name, cat="crypto", **tags)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        raise NotImplementedError

    def prewarm_many(self, triples: Sequence[Triple]) -> List[bool]:
        """Whole-ledger/checkpoint drain (SURVEY.md §2.2): verify a large
        batch in one dispatch and seed the result cache so subsequent
        synchronous per-signature checks all hit. Already-cached triples
        are not re-dispatched. Cache keys for the whole drain hash in one
        native call (prep.c sct_cache_keys) when available."""
        with self._span("crypto.prewarm", backend=self.name,
                        n=len(triples)) as sp:
            cks = None
            if len(triples) >= 256:   # below this the fixed numpy/ctypes
                # marshalling cost exceeds hashlib's per-triple overhead
                # (the native apply engine calls here once per tx, ~20-ish
                # triples; checkpoint drains come in by the thousand)
                from ..native import cache_keys_native
                cks = cache_keys_native(triples)
            if cks is None:
                cks = [_keys._cache_key(k, s, m) for (k, s, m) in triples]
            out: List[Optional[bool]] = [None] * len(triples)
            todo: List[Tuple[int, Triple, bytes]] = []  # (idx, triple, key)
            with _keys._cache_lock:
                for i, (t, ck) in enumerate(zip(triples, cks)):
                    hit = _keys._verify_cache.maybe_get(ck)
                    if hit is not None:
                        out[i] = hit
                    else:
                        todo.append((i, t, ck))
            sp.set_tag("cache_hits", len(triples) - len(todo))
            if todo:
                results = self.verify_many([t for (_i, t, _ck) in todo])
                with _keys._cache_lock:
                    for ((i, _t, ck), ok) in zip(todo, results):
                        _keys._verify_cache.put(ck, ok)
                        out[i] = ok
            return out  # type: ignore[return-value]

    def pending(self) -> int:
        return 0

    # -- shared pending-queue machinery (batch backends) ---------------------
    # TpuSigVerifier and ResilientBatchVerifier share one accumulate/
    # dispatch protocol: cache-probe on enqueue, self-flush at
    # _max_pending, one verify_many per flush, futures completed and the
    # cache fed from the results; a raising dispatch re-completes the
    # batch on the synchronous CPU path instead of stranding futures.

    def _batch_enqueue(self, key: PublicKey, sig: bytes,
                       msg: bytes) -> VerifyFuture:
        ck = _keys._cache_key(key.key_bytes, sig, msg)
        with _keys._cache_lock:
            hit = _keys._verify_cache.maybe_get(ck)
        f = VerifyFuture()
        if hit is not None:
            f._complete(hit)
            return f
        self._pending.append(((key.key_bytes, sig, msg), f))
        if self.stats is not None:
            self.stats.set_queue_depth(len(self._pending))
        if len(self._pending) >= self._max_pending:
            self.flush()
        return f

    def _batch_flush(self) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        if self.stats is not None:
            self.stats.set_queue_depth(0)
        triples = [t for (t, _f) in batch]
        try:
            results = self.verify_many(triples)
        except Exception as e:
            log.warning("batch dispatch failed (%s); completing %d "
                        "verifies on CPU fallback", e, len(batch))
            results = _flush_fallback(self, triples)
        for ((k, s, m), f), ok in zip(batch, results):
            with _keys._cache_lock:
                _keys._verify_cache.put(_keys._cache_key(k, s, m), ok)
            f._complete(ok)


def _flush_fallback(verifier, triples: Sequence[Triple]) -> List[bool]:
    """Synchronous CPU re-verify used when a backend's dispatch raises
    mid-flush; counts the event so a silent degradation is visible."""
    m = getattr(verifier, "metrics", None)
    if m is not None:
        m.new_meter("crypto.verify.flush-fallback").mark(len(triples))
    st = getattr(verifier, "stats", None)
    if st is not None:
        # the CPU served this drain (the raising backend did not)
        st.record_drain("cpu", len(triples))
    return _keys.raw_verify_batch(triples)


class CpuSigVerifier(BatchSigVerifier):
    """Synchronous OpenSSL backend (libsodium role)."""

    name = "cpu"

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        f = VerifyFuture()
        f._complete(_keys.PubKeyUtils.verify_sig(key, sig, msg))
        return f

    def flush(self) -> None:
        pass

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        # CPU drains carry the same batch-shape tags as device drains
        # (pad_waste is structurally 0: no padding on the synchronous
        # path) so bucket-selection analysis sees ALL traffic, not just
        # what happened to reach the device
        with self._span("crypto.verify_many", backend=self.name,
                        n=len(triples), batches=1, pad_waste=0,
                        occupancy_pct=100.0):
            out = _keys.raw_verify_batch(triples)
            # recorded only after the verify returns: a raising drain is
            # re-run (and counted once) by _flush_fallback instead
            if self.stats is not None:
                self.stats.record_drain(self.name, len(triples))
            return out


class TpuSigVerifier(BatchSigVerifier):
    """JAX/TPU batched backend with a device-fleet shard scheduler
    (ISSUE 11 tentpole).

    Batches are padded up to fixed bucket sizes so the kernel compiles
    once per bucket; oversized batches are split. Correctness contract:
    identical accept/reject decisions to CpuSigVerifier (RFC 8032
    cofactorless).

    Fleet dispatch: a drain is split into bucket-shaped sub-batches;
    sub-batches at or above SHARD_MIN_BATCH shard pure-data-parallel
    over the healthy devices' mesh (one compiled executable per
    (bucket, mesh) — XLA's SPMD runtime drives every chip in parallel),
    while straggler tails keep their own smaller bucket on one device
    instead of padding the whole mesh up. Host→device staging is
    double-buffered: while the fleet verifies chunk K, chunk K+1 is
    packed and device_put on the `crypto.verify-staging` worker, so the
    device never idles on host marshalling (`verifier.staging.
    overlap-pct`). Per-device health is a ring of circuit breakers
    (DeviceFleetHealth): a sick chip drops out of the mesh and the
    drain continues on N-1 devices — the all-or-nothing CPU fallback is
    the ResilientBatchVerifier layer above, reserved for whole-backend
    failures.
    """

    name = "tpu"
    wants_prewarm = True
    BUCKETS = (128, 512, 2048, 8192)
    # minimum compile duration the persistent cache stores (mirrors the
    # jax_persistent_cache_min_compile_time_secs value set below): a
    # compile faster than this writes no entry, so "no new cache file"
    # proves nothing about it — warmup classifies those "unknown",
    # never "hit"
    CACHE_PERSIST_MIN_S = 0.5

    # batches below this size stay on one device: sharding a handful of
    # sigs over a pod slice buys nothing and costs a sharded compile
    SHARD_MIN_BATCH = 1024

    # device drains between cockpit-plan autosaves (save_warmup_plan)
    PLAN_AUTOSAVE_DRAINS = 32

    # the kernel's device argument order (prepare_batch dict keys)
    ARG_KEYS = ("ay", "a_sign", "ry", "r_sign", "s_nibs", "k_nibs")

    def __init__(self, max_pending: int = 8192,
                 compile_cache_dir: Optional[str] = None,
                 shard_threshold: Optional[int] = None,
                 devices: Optional[Sequence] = None,
                 now_fn: Optional[Callable[[], float]] = None,
                 device_breaker_threshold: int = 3,
                 device_breaker_cooldown: float = 30.0) -> None:
        self._pending: List[Tuple[Triple, VerifyFuture]] = []
        self._max_pending = max_pending
        self.batches_dispatched = 0
        self.sigs_verified = 0
        self._compile_cache_dir = compile_cache_dir
        self._cache_path: Optional[str] = None  # resolved on enable
        self._warmed = False
        self._warmup_thread: Optional[threading.Thread] = None
        self._sharded_fn = None  # full-mesh dp fn (set on first build)
        self._platform: Optional[str] = None  # actual jax platform, lazy
        self._devices_override = devices
        self._devices: Optional[list] = None  # resolved on first jax use
        self._now = now_fn
        self._dev_threshold = device_breaker_threshold
        self._dev_cooldown = device_breaker_cooldown
        self._fleet_health: Optional[DeviceFleetHealth] = None
        self._mesh_fns: dict = {}   # tuple(device idxs) -> (fn, mesh)
        self._drains_since_plan_save = 0
        if shard_threshold is not None:
            self.SHARD_MIN_BATCH = shard_threshold

    # -- fleet topology ------------------------------------------------------
    def _fleet(self):
        """(devices, health), resolved lazily on first jax touch."""
        if self._devices is None:
            import jax
            self._devices = list(self._devices_override
                                 if self._devices_override is not None
                                 else jax.devices())
            self._fleet_health = DeviceFleetHealth(
                len(self._devices), threshold=self._dev_threshold,
                cooldown_s=self._dev_cooldown, now_fn=self._now,
                owner=self)
        return self._devices, self._fleet_health

    @property
    def fleet_health(self) -> "DeviceFleetHealth":
        return self._fleet()[1]

    def _mesh_fn(self, idxs: tuple):
        """dp-sharded verify fn over the devices at `idxs` — one
        compiled executable per (bucket shape, mesh membership). A mesh
        rebuild after a breaker trip/recover is a real recompile on new
        shapes; it is counted so degraded-fleet compile cost is never
        invisible."""
        got = self._mesh_fns.get(idxs)
        if got is None:
            from ..parallel.mesh import make_mesh, sharded_verify_fn
            devs, _health = self._fleet()
            mesh = make_mesh([devs[i] for i in idxs])
            got = (sharded_verify_fn(mesh), mesh)
            if self._mesh_fns and self.metrics is not None:
                self.metrics.new_meter("verifier.fleet.mesh-rebuild").mark()
            self._mesh_fns[idxs] = got
            if len(idxs) == len(devs):
                self._sharded_fn = got[0]   # full-mesh alias
        return got

    def _single_fn(self):
        from ..ops.ed25519 import verify_batch_jit
        return verify_batch_jit

    def _route(self, n: int):
        """(fn, padded bucket, device idxs) for an n-sig sub-batch.

        Mesh membership is the healthy device set at route time; the
        verify.device-lost fault point simulates losing the first
        healthy device for this dispatch (its breaker counts the
        failure, so repeated fires trip it and the fleet degrades to
        N-1)."""
        devs, health = self._fleet()
        idxs = health.healthy() if len(devs) > 1 else [0]
        if len(idxs) > 1 and self.faults is not None and \
                self.faults.should_fire("verify.device-lost"):
            lost = idxs[0]
            health.record_failure(lost)
            idxs = [i for i in idxs if i != lost]
        if not idxs:
            idxs = list(range(len(devs)))
        if len(idxs) > 1 and n >= self.SHARD_MIN_BATCH:
            fn, _mesh = self._mesh_fn(tuple(idxs))
            ndev = len(idxs)
        else:
            # sub-batch bucketing: a straggler tail keeps its own small
            # bucket on ONE device instead of serializing (and padding)
            # the whole mesh — the first HEALTHY device, so a tripped
            # device 0 doesn't keep eating every small live-SCP batch
            # (the per-device compile a non-default device costs only
            # happens in that degraded state)
            fn = self._single_fn()
            idxs = idxs[:1]
            ndev = 1
        b = -(-self._bucket(n) // ndev) * ndev
        return fn, b, tuple(idxs)

    # -- staging (host pack + host→device transfer) --------------------------
    def _stage_chunk(self, chunk: Sequence[Triple], route) -> dict:
        """Pack one sub-batch and move it to its device(s). Runs on the
        staging worker when double-buffered; the returned blob is
        everything dispatch needs, so the dispatch thread never touches
        host marshalling."""
        from ..ops import ed25519 as _e
        from ..parallel.mesh import pad_batch_to
        fn, b, idxs = route
        prep = _e.prepare_batch(
            [t[0] for t in chunk], [t[1] for t in chunk],
            [t[2] for t in chunk])
        padded = pad_batch_to(prep, b)
        return {"args": self._device_args(padded, idxs),
                "pre_ok": prep["pre_ok"], "n": len(chunk), "b": b,
                "fn": fn, "idxs": idxs}

    def _device_args(self, padded: dict, idxs: tuple) -> tuple:
        """Explicit host→device placement: sharded over the mesh for a
        fleet dispatch, committed to the default device otherwise — the
        transfer happens here (on the staging thread when overlapped),
        not inside the jit call."""
        import jax
        devs, _health = self._fleet()
        if len(idxs) > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            _fn, mesh = self._mesh_fns[idxs]
            target = NamedSharding(mesh, P("dp"))
        else:
            target = devs[idxs[0]] if idxs else devs[0]
        return tuple(jax.device_put(padded[k], target)
                     for k in self.ARG_KEYS)

    def _enable_compile_cache(self) -> None:
        """Persistent XLA compilation cache: a node restart never re-pays
        kernel compilation (VERDICT r1: lazy compile on the consensus path
        stalls a validator for the compile duration)."""
        import os
        path = self._resolve_cache_dir()
        try:
            import jax
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              self.CACHE_PERSIST_MIN_S)
            self._cache_path = path
            if self.stats is not None:
                self.stats.compile_cache_enabled(path)
        except Exception as e:  # cache is an optimization, never fatal
            log.warning("compile cache unavailable: %s", e)
            if self.stats is not None:
                # ...but an operator must be able to SEE it (tracer
                # instant + meter + flight dump), or every restart
                # silently pays cold compiles
                self.stats.compile_cache_error(repr(e))

    def _cache_entry_count(self) -> int:
        """Files under the persistent XLA cache dir (-1 = unknown).
        Warmup diffs this around each bucket compile: no new entry means
        the executable came from the cache (a warm restart), a new entry
        means a cold compile just got paid. The persisted warmup plan
        lives beside the executables and is excluded from the diff."""
        import os
        if self._cache_path is None:
            return -1
        try:
            n = 0
            for _dir, _sub, files in os.walk(self._cache_path):
                # PLAN_BASENAME and its .tmp write-staging sibling: a
                # concurrent plan autosave must not make a cache-hit
                # bucket classify as a cold compile
                n += sum(1 for f in files
                         if not f.startswith(self.PLAN_BASENAME))
            return n
        except OSError:
            return -1

    # -- cockpit-driven warm start (ISSUE 11 tentpole) -----------------------
    PLAN_BASENAME = "warmup_buckets.json"

    def _resolve_cache_dir(self) -> str:
        import os
        return self._compile_cache_dir or os.environ.get(
            "JAX_COMPILATION_CACHE_DIR") or os.path.expanduser(
            "~/.cache/stellar_core_tpu/jax_cache")

    def warmup_plan_path(self) -> str:
        """The cockpit-derived bucket plan persists beside the XLA
        compile cache: the same restart that finds warm executables
        finds the bucket set real traffic uses."""
        import os
        return os.path.join(self._cache_path or self._resolve_cache_dir(),
                            self.PLAN_BASENAME)

    def _load_warmup_plan(self):
        """(buckets, source): the persisted cockpit plan when present
        and still valid against the candidate ladder, else the full
        default BUCKETS."""
        import json
        try:
            with open(self.warmup_plan_path()) as fh:
                blob = json.load(fh)
            buckets = [int(b) for b in blob["buckets"]]
            if buckets and all(b in self.BUCKETS for b in buckets):
                return buckets, "cockpit"
            log.warning("persisted warmup plan %r does not fit the "
                        "candidate ladder %r; using the default set",
                        buckets, tuple(self.BUCKETS))
        except (OSError, ValueError, KeyError, TypeError):
            pass
        return list(self.BUCKETS), "default"

    def save_warmup_plan(self) -> Optional[str]:
        """Persist the cockpit-derived bucket plan (warmup_plan over the
        shared VerifierStats) beside the XLA cache. No-op until the
        cockpit has seen traffic — a default plan is not evidence worth
        persisting. Returns the path written, or None."""
        if self.stats is None:
            return None
        buckets, info = warmup_plan(self.stats, self.BUCKETS)
        if info.get("source") != "cockpit":
            return None
        import json
        import os
        path = self.warmup_plan_path()
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"version": 1, "buckets": buckets,
                           "candidates": sorted(self.BUCKETS),
                           "traffic": {str(k): v for k, v in
                                       sorted(info["traffic"].items())},
                           "low_occupancy_extra":
                               info["low_occupancy_extra"]}, fh)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("could not persist warmup plan: %s", e)
            return None
        return path

    def warmup(self, wait: bool = False) -> None:
        """AOT-compile every bucket shape off the consensus path (startup
        background thread; reference analog: no lazy work on first
        envelope). Idempotent."""
        if self._warmed:
            return
        if self._warmup_thread is None:
            self._warmup_thread = spawn_worker(
                "crypto.verify-warmup", self._warmup_impl)
        if wait:
            self._warmup_thread.join()

    def _compile_bucket(self, b: int) -> None:
        """AOT-compile (or cache-load) one bucket shape, routed exactly
        like live traffic (mesh-sharded at or above SHARD_MIN_BATCH) so
        warmup compiles the executables dispatch will actually use."""
        import numpy as np
        fn, bb, idxs = self._route(b)
        zeros = {
            "ay": np.zeros((bb, 20), np.int32),
            "a_sign": np.zeros((bb,), np.int32),
            "ry": np.zeros((bb, 20), np.int32),
            "r_sign": np.zeros((bb,), np.int32),
            "s_nibs": np.zeros((bb, 64), np.int32),
            "k_nibs": np.zeros((bb, 64), np.int32),
        }
        np.asarray(fn(*self._device_args(zeros, idxs)))

    def _warmup_impl(self) -> None:
        st = self.stats
        try:
            self._enable_compile_cache()
            planned, source = self._load_warmup_plan()
            if st is not None:
                st.warmup_begin(planned, source=source)
            for b in planned:
                before = self._cache_entry_count()
                t0 = real_monotonic()
                self._compile_bucket(b)
                dt = real_monotonic() - t0
                after = self._cache_entry_count()
                if before < 0 or after < 0:
                    hit = None            # cache dir unreadable
                elif after > before:
                    hit = False           # a cold compile just persisted
                elif dt >= self.CACHE_PERSIST_MIN_S:
                    hit = True            # long compile, no new entry:
                    # the executable came from the cache
                else:
                    # fast compile below the persistence threshold
                    # writes no entry either way — unclassifiable, and
                    # nothing worth caching was at stake
                    hit = None
                if st is not None:
                    st.warmup_bucket_done(b, dt, hit)
            self._warmed = True
            if st is not None:
                st.warmup_done()
            log.info("verify kernel warmup complete (%s buckets, "
                     "%s plan)", len(planned), source)
        except Exception as e:
            log.warning("verify kernel warmup failed: %s", e)
            if st is not None:
                st.warmup_failed(repr(e))

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        return self._batch_enqueue(key, sig, msg)

    def pending(self) -> int:
        return len(self._pending)

    def flush(self) -> None:
        self._batch_flush()

    def _bucket(self, n: int) -> int:
        for b in self.BUCKETS:
            if n <= b:
                return b
        return self.BUCKETS[-1]

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        import numpy as np
        import jax

        if self._platform is None:
            # the ACTUAL backing platform ("tpu"/"cpu"/…): a jax-on-CPU
            # run of this verifier is a fallback and must trace as one
            self._platform = jax.devices()[0].platform
        out: List[bool] = []
        st = self.stats
        with self._span("crypto.verify_many", backend=self.name,
                        platform=self._platform, n=len(triples)) as sp:
            chunks: List[Sequence[Triple]] = []
            i = 0
            while i < len(triples):
                chunks.append(triples[i:i + self.BUCKETS[-1]])
                i += len(chunks[-1])
            batches = 0
            pad_waste = 0
            staged_s = overlap_s = 0.0
            staged_chunks = 0
            staged = self._stage_chunk(chunks[0],
                                       self._route(len(chunks[0]))) \
                if chunks else None
            for k in range(len(chunks)):
                # double buffer: chunk K+1 packs + device_puts on the
                # staging worker while the device executes chunk K
                job = _StagingJob(self, chunks[k + 1]) \
                    if k + 1 < len(chunks) else None
                n, b, idxs = staged["n"], staged["b"], staged["idxs"]
                if st is not None:
                    for di in idxs:
                        st.set_device_inflight(di, True)
                try:
                    with self._span("crypto.dispatch", backend=self.name,
                                    n=n, bucket=b, pad=b - n,
                                    devices=len(idxs)):
                        ok_dev = staged["fn"](*staged["args"])  # async
                        wait_t0 = real_monotonic()
                        ok = np.asarray(ok_dev)   # blocks on the fleet
                        wait_t1 = real_monotonic()
                except Exception:
                    # a raising fleet dispatch counts against every
                    # participating device's breaker (attribution to ONE
                    # chip needs the fault-injection path); the batch
                    # itself is completed by the resilient layer above
                    health = self._fleet_health
                    if health is not None:
                        for di in idxs:
                            health.record_failure(di)
                    raise
                finally:
                    if st is not None:
                        for di in idxs:
                            st.set_device_inflight(di, False)
                # every participant's breaker sees the success — single-
                # device dispatches included, so transient failures
                # spread over time never read as consecutive and a
                # half-open device can recover via small drains too
                health = self._fleet_health
                if health is not None:
                    for di in idxs:
                        health.record_success(di)
                out.extend((ok[:n] & staged["pre_ok"]).tolist())
                self.batches_dispatched += 1
                self.sigs_verified += n
                batches += 1
                pad_waste += b - n
                if st is not None:
                    # keyed by the LADDER shape, not the mesh-rounded
                    # padded size: a degraded 3-device fleet rounds 8192
                    # to 8193, and an off-ladder key would both escape
                    # warmup_plan's pad-waste rule and mint unbounded
                    # verifier.bucket.<b>.* metric families
                    st.record_bucket_dispatch(self._bucket(n), n, b - n)
                    lanes = b // len(idxs)
                    for j, di in enumerate(idxs):
                        real = min(max(n - j * lanes, 0), lanes)
                        st.record_device_dispatch(di, real, lanes - real)
                if job is not None:
                    staged, s_s, o_s, stalled = job.result(wait_t0,
                                                           wait_t1)
                    if stalled:
                        # staging stalled: re-stage synchronously so the
                        # drain still completes (the device idles for
                        # one chunk; the stall meter says so). The
                        # failed attempt does NOT count toward the
                        # overlap headline — a drain that stalled every
                        # chunk must not report near-100% overlap.
                        if st is not None:
                            st.record_staging_stall()
                        staged = self._stage_chunk(
                            chunks[k + 1], self._route(len(chunks[k + 1])))
                    else:
                        staged_s += s_s
                        overlap_s += o_s
                        staged_chunks += 1
            sp.set_tag("batches", batches)
            sp.set_tag("pad_waste", pad_waste)
            total = len(triples)
            sp.set_tag("occupancy_pct", round(
                100.0 * total / (total + pad_waste), 1)
                if total + pad_waste else 100.0)
            if staged_chunks:
                sp.set_tag("staging_overlap_pct", round(
                    100.0 * overlap_s / staged_s, 1) if staged_s > 0
                    else 100.0)
            if st is not None:
                if staged_chunks:
                    st.record_staging(staged_s, overlap_s, staged_chunks)
                st.record_drain(self.name, total, pad=pad_waste,
                                splits=batches, bucketed=True)
            self._drains_since_plan_save += 1
            if self._drains_since_plan_save >= self.PLAN_AUTOSAVE_DRAINS:
                self._drains_since_plan_save = 0
                self.save_warmup_plan()
        return out


class _StagingJob:
    """One double-buffer staging unit: packs + device_puts drain chunk
    K+1 on the `crypto.verify-staging` worker while the dispatch thread
    waits on chunk K. Timing uses util.timer.real_monotonic (sanctioned:
    host/device overlap is real elapsed time even under a frozen virtual
    clock). A staging failure (including the verify.staging-stall fault
    point) is reported as `stalled` — the caller re-stages synchronously
    so the drain always completes."""

    __slots__ = ("v", "chunk", "staged", "error", "t0", "t1", "thread")

    def __init__(self, verifier: "TpuSigVerifier",
                 chunk: Sequence[Triple]) -> None:
        self.v = verifier
        self.chunk = chunk
        self.staged = None
        self.error: Optional[Exception] = None
        self.t0 = self.t1 = 0.0
        self.thread = spawn_worker("crypto.verify-staging", self._run)

    def _run(self) -> None:
        self.t0 = real_monotonic()
        try:
            if self.v.faults is not None:
                self.v.faults.fire_point("verify.staging-stall")
            self.staged = self.v._stage_chunk(
                self.chunk, self.v._route(len(self.chunk)))
        except Exception as e:
            self.error = e
        self.t1 = real_monotonic()

    def result(self, wait_t0: float, wait_t1: float):
        """(staged, staged_s, overlap_s, stalled): overlap is the
        intersection of the staging window with the caller's
        device-wait window [wait_t0, wait_t1]."""
        self.thread.join()
        staged_s = max(0.0, self.t1 - self.t0)
        overlap_s = max(0.0, min(self.t1, wait_t1) -
                        max(self.t0, wait_t0))
        if self.error is not None:
            log.warning("verify staging stalled (%s); re-staging chunk "
                        "synchronously", self.error)
            return None, staged_s, overlap_s, True
        return self.staged, staged_s, overlap_s, False


class DeviceFleetHealth:
    """Per-device circuit breakers over the verify fleet (ISSUE 11
    satellite): the ResilientBatchVerifier's single breaker treats the
    whole backend as one unit; this ring trips and recovers per chip,
    so one sick device degrades the mesh to N-1 devices instead of
    dropping every drain to the CPU fallback. State is exported as
    `verifier.device.<i>.breaker` gauges (0 closed / 1 open / 2
    half-open) plus trip/recover meters and a flight dump per trip.

    Attribution honesty: a whole-mesh dispatch failure cannot name the
    guilty chip, so it counts against every participant (and, via the
    resilient layer, the global breaker); single-chip attribution comes
    from the verify.device-lost fault point and device-identifiable
    runtime errors."""

    def __init__(self, n_devices: int, threshold: int = 3,
                 cooldown_s: float = 30.0,
                 now_fn: Optional[Callable[[], float]] = None,
                 owner=None) -> None:
        self.owner = owner     # verifier; stats read dynamically
        # the ring is mutated from the dispatch thread AND the staging
        # worker (_route runs on both): one lock makes allow()/record_*
        # transitions atomic, so a just-tripped chip can never race its
        # own cooldown back into the mesh. Lock order: fleet-health ->
        # verifier-stats (the trip/recover callbacks record telemetry);
        # nothing acquires them in reverse.
        self._lock = TrackedLock("crypto.fleet-health")
        self.breakers: List[CircuitBreaker] = []
        for i in range(n_devices):
            self.breakers.append(CircuitBreaker(
                threshold=threshold, cooldown_s=cooldown_s, now_fn=now_fn,
                on_trip=(lambda i=i: self._on_trip(i)),
                on_recover=(lambda i=i: self._on_recover(i))))

    def _stats(self):
        return getattr(self.owner, "stats", None) \
            if self.owner is not None else None

    def healthy(self) -> List[int]:
        """Device indices whose breaker admits a dispatch right now
        (open breakers past their cooldown flip to half-open here —
        the next fleet dispatch is their reprobe)."""
        with self._lock:
            return [i for i, br in enumerate(self.breakers)
                    if br.allow()]

    def record_failure(self, idx: int) -> bool:
        with self._lock:
            tripped = self.breakers[idx].record_failure()
        self._sync_gauge(idx)
        return tripped

    def record_success(self, idx: int) -> None:
        with self._lock:
            self.breakers[idx].record_success()
        self._sync_gauge(idx)

    def _sync_gauge(self, idx: int) -> None:
        st = self._stats()
        if st is not None:
            st.set_device_breaker(idx, self.breakers[idx].state_code())

    def _on_trip(self, idx: int) -> None:
        log.warning("verify device %d breaker TRIPPED; fleet degrades "
                    "to %d device(s)", idx,
                    sum(1 for br in self.breakers
                        if br.state == CircuitBreaker.CLOSED))
        st = self._stats()
        if st is not None:
            st.device_trip(idx, self.breakers[idx].to_json())

    def _on_recover(self, idx: int) -> None:
        log.info("verify device %d breaker recovered; fleet back to "
                 "full mesh", idx)
        st = self._stats()
        if st is not None:
            st.device_recover(idx)

    def to_json(self) -> dict:
        with self._lock:
            return {"devices": {str(i): br.to_json()
                                for i, br in enumerate(self.breakers)}}


class CircuitBreaker:
    """closed → open → half-open → closed over the device-dispatch path.

    CLOSED: dispatches flow to the primary; `threshold` CONSECUTIVE
    failures trip to OPEN. OPEN: primary is bypassed until `cooldown_s`
    elapses on the injected clock, then the next allow() becomes the
    HALF-OPEN probe. HALF-OPEN: one success re-closes (recover), one
    failure re-opens for another cooldown. Time comes from `now_fn`
    (virtual clock in tests/simulation) so trips and reprobes are
    deterministic."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"
    _STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 now_fn: Optional[Callable[[], float]] = None,
                 on_trip: Optional[Callable[[], None]] = None,
                 on_recover: Optional[Callable[[], None]] = None) -> None:
        self.threshold = max(1, threshold)
        self.cooldown_s = cooldown_s
        self._now = now_fn or real_monotonic
        self.on_trip = on_trip
        self.on_recover = on_recover
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.trips = 0
        self.recoveries = 0
        self._retry_at = 0.0

    def allow(self) -> bool:
        """May the next dispatch try the primary?"""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN and self._now() >= self._retry_at:
            self.state = self.HALF_OPEN
            return True
        return self.state == self.HALF_OPEN

    def record_success(self) -> None:
        recovered = self.state == self.HALF_OPEN
        self.state = self.CLOSED
        self.consecutive_failures = 0
        if recovered:
            self.recoveries += 1
            if self.on_recover is not None:
                self.on_recover()

    def record_failure(self) -> bool:
        """Returns True when this failure tripped (or re-opened) the
        breaker."""
        self.consecutive_failures += 1
        if self.state == self.HALF_OPEN or \
                self.consecutive_failures >= self.threshold:
            reopened = self.state != self.CLOSED
            self.state = self.OPEN
            self._retry_at = self._now() + self.cooldown_s
            if not reopened:
                self.trips += 1
                if self.on_trip is not None:
                    self.on_trip()
            return True
        return False

    def state_code(self) -> int:
        return self._STATE_CODE[self.state]

    def to_json(self) -> dict:
        return {"state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "trips": self.trips, "recoveries": self.recoveries,
                "threshold": self.threshold, "cooldown_s": self.cooldown_s,
                "retry_at": self._retry_at}


class ResilientBatchVerifier(BatchSigVerifier):
    """Primary backend behind a circuit breaker, CPU fallback beside it.

    Every dispatch-shaped call (verify_many; flush routes through it)
    asks the breaker whether the primary may be tried; a raising primary
    records a failure and the batch re-runs on the fallback, so callers
    always get results. A trip emits metrics + a flight-recorder dump;
    recovery (first successful half-open probe) emits the matching
    recover marker — the signals the chaos soak asserts on."""

    name = "resilient"

    def __init__(self, primary: BatchSigVerifier,
                 fallback: BatchSigVerifier,
                 breaker: Optional[CircuitBreaker] = None,
                 max_pending: int = 8192) -> None:
        self.primary = primary
        self.fallback = fallback
        self.breaker = breaker or CircuitBreaker()
        self.breaker.on_trip = self._on_trip
        self.breaker.on_recover = self._on_recover
        self.flight_recorder = None   # installed by make_verifier
        self._pending: List[Tuple[Triple, VerifyFuture]] = []
        self._max_pending = max_pending

    # -- breaker events ------------------------------------------------------
    def _breaker_mark(self, event: str) -> None:
        if self.metrics is not None:
            self.metrics.new_meter("crypto.breaker.%s" % event).mark()
            self.metrics.new_counter("crypto.breaker.state").set_count(
                self.breaker.state_code())
        from ..util.tracing import tracer_instant
        tracer_instant(self.tracer, "crypto.breaker.%s" % event,
                       cat="crypto", primary=self.primary.name,
                       failures=self.breaker.consecutive_failures)

    def _on_trip(self) -> None:
        log.warning("verify breaker TRIPPED: %d consecutive %s-dispatch "
                    "failures; falling back to %s for %.0fs",
                    self.breaker.consecutive_failures, self.primary.name,
                    self.fallback.name, self.breaker.cooldown_s)
        self._breaker_mark("trip")
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                "verify-breaker-trip",
                extra={"primary": self.primary.name,
                       "breaker": self.breaker.to_json()})

    def _on_recover(self) -> None:
        log.info("verify breaker recovered: %s backend healthy again",
                 self.primary.name)
        self._breaker_mark("recover")

    # -- delegation ----------------------------------------------------------
    @property
    def wants_prewarm(self) -> bool:
        return self.primary.wants_prewarm

    @property
    def inner(self) -> BatchSigVerifier:
        return self.primary

    @property
    def batches_dispatched(self) -> int:
        return getattr(self.primary, "batches_dispatched", 0)

    @property
    def sigs_verified(self) -> int:
        return getattr(self.primary, "sigs_verified", 0)

    def warmup(self, wait: bool = False) -> None:
        w = getattr(self.primary, "warmup", None)
        if w is not None:
            w(wait)

    def save_warmup_plan(self):
        f = getattr(self.primary, "save_warmup_plan", None)
        return f() if f is not None else None

    @property
    def fleet_health(self):
        return getattr(self.primary, "_fleet_health", None)

    # -- verify paths --------------------------------------------------------
    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        if self.breaker.allow():
            try:
                # the primary attempt gets its own span so an injected
                # (or real) dispatch failure is tagged on the drain it
                # killed, not floating free on the timeline
                with self._span("crypto.dispatch_primary",
                                backend=self.primary.name,
                                n=len(triples)):
                    if self.faults is not None:
                        self.faults.fire_point("device.dispatch")
                    out = self.primary.verify_many(triples)
                self.breaker.record_success()
                return out
            except Exception as e:
                if self.metrics is not None:
                    self.metrics.new_meter(
                        "crypto.verify.dispatch-failure").mark()
                tripped = self.breaker.record_failure()
                if not tripped:
                    log.warning("%s dispatch failed (%s): %d/%d toward "
                                "breaker trip", self.primary.name, e,
                                self.breaker.consecutive_failures,
                                self.breaker.threshold)
        if self.metrics is not None:
            # drains served by the fallback while the primary is failing
            # or the breaker is open — the "completed on fallback" signal
            # the chaos soak asserts on
            self.metrics.new_meter("crypto.verify.fallback-drain").mark()
        # served_by names the backend that actually ran the drain — the
        # fallback's own verify_many records the drain stats under its
        # name, so cockpit attribution follows the server, not the wrapper
        with self._span("crypto.verify_fallback", backend=self.name,
                        served_by=self.fallback.name,
                        n=len(triples), breaker=self.breaker.state):
            return self.fallback.verify_many(triples)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        return self._batch_enqueue(key, sig, msg)

    def pending(self) -> int:
        return len(self._pending)

    def flush(self) -> None:
        # verify_many (almost) never raises here: a primary failure is
        # absorbed by the breaker and the batch re-runs on the fallback —
        # a trip mid-drain still completes every future correctly
        self._batch_flush()


class ThreadedBatchVerifier(BatchSigVerifier):
    """Async wrapper: dispatch runs on a worker thread, futures complete on
    the main loop via clock.post_to_main — the enqueue-and-continue protocol
    SURVEY.md §7 requires at the verifyEnvelope/checkValid boundary."""

    name = "threaded"

    def __init__(self, inner: BatchSigVerifier, clock,
                 metrics=None) -> None:
        self._inner = inner
        self._clock = clock
        self._metrics = metrics
        self._lock = TrackedLock("crypto.threaded-pending")
        # (triple, future, enqueue app-clock stamp): the timestamp feeds
        # the crypto.verify.latency enqueue-to-complete timer (the
        # p50/p99 the live SCP path actually feels); the app clock, not
        # wall time, so chaos soaks under a virtual clock stay
        # deterministic
        self._pending: List[Tuple[Triple, VerifyFuture, float]] = []
        self._inflight = False

    @property
    def wants_prewarm(self) -> bool:
        return self._inner.wants_prewarm

    @property
    def inner(self) -> BatchSigVerifier:
        """The DEVICE verifier (unwrapping a resilient layer): callers
        tune BUCKETS / read dispatch counters on the actual backend."""
        return getattr(self._inner, "inner", self._inner)

    @property
    def breaker(self):
        return getattr(self._inner, "breaker", None)

    def warmup(self, wait: bool = False) -> None:
        w = getattr(self._inner, "warmup", None)
        if w is not None:
            w(wait)

    def save_warmup_plan(self):
        f = getattr(self._inner, "save_warmup_plan", None)
        return f() if f is not None else None

    @property
    def fleet_health(self):
        return getattr(self._inner, "fleet_health", None)

    def enqueue(self, key: PublicKey, sig: bytes, msg: bytes) -> VerifyFuture:
        ck = _keys._cache_key(key.key_bytes, sig, msg)
        with _keys._cache_lock:
            hit = _keys._verify_cache.maybe_get(ck)
        f = VerifyFuture()
        if hit is not None:
            f._complete(hit)
            return f
        with self._lock:
            self._pending.append(
                ((key.key_bytes, sig, msg), f, self._clock.now()))
            depth = len(self._pending)
        if self.stats is not None:
            self.stats.set_queue_depth(depth)
        return f

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def flush(self) -> None:
        with self._lock:
            if not self._pending or self._inflight:
                return
            batch, self._pending = self._pending, []
            self._inflight = True
        st = self.stats
        if st is not None:
            st.set_queue_depth(0)
            st.set_inflight(True)

        def work() -> None:
            triples = [t for (t, _f, _t0) in batch]
            # queue-wait: enqueue → dispatch start, per batch; dispatch
            # time is the span's own duration (inner verify_many nests)
            t_disp = self._clock.now()
            waits = [t_disp - t0 for (_t, _f, t0) in batch]
            if st is not None:
                st.record_queue_wait(sum(waits) / len(waits), max(waits))
            with self._span("crypto.batch_dispatch",
                            backend="threaded:%s" % self._inner.name,
                            n=len(batch),
                            queue_wait_max_ms=round(max(waits) * 1e3, 3),
                            queue_wait_mean_ms=round(
                                sum(waits) / len(waits) * 1e3, 3)):
                try:
                    results = self._inner.verify_many(triples)
                except Exception as e:
                    # the worker thread must neither die with futures
                    # pending nor leave _inflight latched (that would
                    # no-op every later flush — a permanent wedge)
                    log.warning("threaded dispatch failed (%s); completing "
                                "%d verifies on CPU fallback", e, len(batch))
                    results = _flush_fallback(self, triples)

            def complete() -> None:
                done = self._clock.now()
                lat = (self._metrics.new_timer("crypto.verify.latency")
                       if self._metrics is not None else None)
                for ((k, s, m), f, t0), ok in zip(batch, results):
                    with _keys._cache_lock:
                        _keys._verify_cache.put(_keys._cache_key(k, s, m), ok)
                    if lat is not None:
                        lat.update(done - t0)
                    f._complete(ok)
                with self._lock:
                    self._inflight = False
                    more = bool(self._pending)
                if st is not None:
                    st.set_inflight(False)
                if more:
                    # verifies enqueued while the batch was in flight form
                    # the next batch immediately
                    self.flush()

            self._clock.post_to_main(complete)

        spawn_worker("crypto.verify-dispatch", work)

    def verify_many(self, triples: Sequence[Triple]) -> List[bool]:
        return self._inner.verify_many(triples)


def make_verifier(backend: str = "cpu", clock=None,
                  max_pending: int = 8192,
                  compile_cache_dir: Optional[str] = None,
                  metrics=None, tracer=None, faults=None,
                  flight_recorder=None,
                  breaker_threshold: int = 3,
                  breaker_cooldown: float = 30.0) -> BatchSigVerifier:
    """Config-gated backend selection (Config.SIG_VERIFY_BACKEND).

    Device backends ("tpu", "tpu-async") are always wrapped in a
    ResilientBatchVerifier with a CPU fallback; "cpu-resilient" wraps the
    CPU backend in the same breaker machinery so chaos runs exercise the
    device failure domain on device-less containers.

    Every layer of the stack shares ONE VerifierStats cockpit
    (`<verifier>.stats`), so fallback drains are attributed to the
    backend that served them and the admin `verifier` endpoint sees the
    whole boundary regardless of wrapping."""
    now_fn = clock.now if clock is not None else None
    stats = VerifierStats(metrics=metrics, tracer=tracer, now_fn=now_fn,
                          flight_recorder=flight_recorder)

    def resilient(primary: BatchSigVerifier) -> ResilientBatchVerifier:
        primary.tracer = tracer
        primary.metrics = metrics
        primary.stats = stats
        primary.faults = faults   # verify.device-lost / .staging-stall
        # fire inside the device backend's route/staging, not just the
        # resilient layer's device.dispatch point
        fb = CpuSigVerifier()
        fb.tracer = tracer
        fb.metrics = metrics
        fb.stats = stats
        r = ResilientBatchVerifier(
            primary, fb,
            CircuitBreaker(threshold=breaker_threshold,
                           cooldown_s=breaker_cooldown, now_fn=now_fn),
            max_pending=max_pending)
        r.tracer = tracer
        r.flight_recorder = flight_recorder
        r.stats = stats
        return r

    def device() -> TpuSigVerifier:
        # the per-device breaker ring shares the resilient layer's
        # threshold/cooldown knobs and the injected app clock, so a
        # chip's trip/reprobe schedule is as deterministic under a
        # virtual clock as the whole-backend breaker's
        return TpuSigVerifier(max_pending=max_pending,
                              compile_cache_dir=compile_cache_dir,
                              now_fn=now_fn,
                              device_breaker_threshold=breaker_threshold,
                              device_breaker_cooldown=breaker_cooldown)

    if backend == "cpu":
        v: BatchSigVerifier = CpuSigVerifier()
    elif backend == "cpu-resilient":
        v = resilient(CpuSigVerifier())
    elif backend == "tpu":
        v = resilient(device())
    elif backend == "tpu-async":
        assert clock is not None
        inner = resilient(device())
        inner.metrics = metrics
        inner.faults = faults
        v = ThreadedBatchVerifier(inner, clock, metrics=metrics)
    else:
        raise ValueError("unknown sig verify backend %r" % backend)
    v.tracer = tracer
    v.metrics = metrics
    v.faults = faults
    v.stats = stats
    return v
