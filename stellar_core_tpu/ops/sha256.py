"""Batched SHA-256 on the device: the hashing half of the crypto plane.

Design (mirrors ops/ed25519.py's split of labor; ROADMAP item 5 — the
accelerator-side proof-pipeline direction of ACE Runtime
(PAPERS.md, 2603.10242) and the batched-hash accelerator of SZKP
(2408.05890)):

- One LANE per message: the batch rides the TPU lane dimension, each
  lane runs the standard FIPS 180-4 compression over ITS OWN padded
  message blocks. All state is uint32; adds wrap mod 2^32 and shifts
  discard overflow bits natively, so the kernel is pure jnp bitwise/add
  traffic on the VPU — no MXU, no transcendentals.
- LAYOUT: device arrays are block-first / batch-last ((max_blocks, 16, B)
  words) so every word of a block is a full-lane vector; the public
  `hash_blocks_kernel` takes batch-first arrays (the host/byte layout)
  and transposes once at the jit boundary, exactly like verify_kernel.
- Variable lengths inside one fixed shape: the host pads every message
  to the dispatch's block bucket and passes per-lane true block counts;
  the block loop masks state updates with `i < n_blocks`, so a lane
  simply stops absorbing once its own message ends. Identical digests
  to hashlib for every length, asserted by the oracle tests.
- Host does the byte work TPUs are bad at: FIPS padding + big-endian
  word packing, numpy-vectorized per message via frombuffer (C speed).

The pure-hashlib oracle lives alongside; `crypto/batch_hasher.py` wraps
this kernel in the bucketed-dispatch / circuit-breaker machinery.
"""

from __future__ import annotations

import hashlib
from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

# FIPS 180-4 round constants and initial state
_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19], dtype=np.uint32)


def blocks_for_len(n: int) -> int:
    """FIPS padded 64-byte block count for an n-byte message (the 0x80
    marker plus the 8-byte bit length always fit, so empty = 1 block)."""
    return (n + 9 + 63) // 64


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state: tuple, blk: jnp.ndarray) -> tuple:
    """One compression round over a (16, B) block; state is 8 × (B,)
    uint32. The 48 schedule extensions and 64 rounds run in fori_loops
    over small per-step dynamic indexing — the per-step work is a
    handful of full-lane VPU ops, so the loop carries no reshuffles."""
    kdev = jnp.asarray(_K)
    nsteps = 64
    w0 = jnp.zeros((nsteps,) + blk.shape[1:], jnp.uint32)
    w0 = jax.lax.dynamic_update_slice_in_dim(w0, blk, 0, axis=0)

    def sched(t, w):
        wt15 = jax.lax.dynamic_index_in_dim(w, t - 15, 0, keepdims=False)
        wt2 = jax.lax.dynamic_index_in_dim(w, t - 2, 0, keepdims=False)
        wt16 = jax.lax.dynamic_index_in_dim(w, t - 16, 0, keepdims=False)
        wt7 = jax.lax.dynamic_index_in_dim(w, t - 7, 0, keepdims=False)
        s0 = _rotr(wt15, 7) ^ _rotr(wt15, 18) ^ (wt15 >> np.uint32(3))
        s1 = _rotr(wt2, 17) ^ _rotr(wt2, 19) ^ (wt2 >> np.uint32(10))
        wt = wt16 + s0 + wt7 + s1
        return jax.lax.dynamic_update_index_in_dim(w, wt, t, axis=0)

    w = jax.lax.fori_loop(16, nsteps, sched, w0)

    def round_body(t, carry):
        a, b, c, d, e, f, g, h = carry
        wt = jax.lax.dynamic_index_in_dim(w, t, 0, keepdims=False)
        kt = jax.lax.dynamic_index_in_dim(kdev, t, 0, keepdims=False)
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (jnp.bitwise_not(e) & g)
        t1 = h + s1 + ch + kt + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g)

    out = jax.lax.fori_loop(0, nsteps, round_body, state)
    return tuple(s + o for s, o in zip(state, out))


def hash_blocks_kernel(words: jnp.ndarray,
                       n_blocks: jnp.ndarray) -> jnp.ndarray:
    """Batched SHA-256 core. `words`: (B, max_blocks, 16) uint32
    big-endian message words, FIPS-padded per lane; `n_blocks`: (B,)
    int32 true block counts. Returns (B, 8) uint32 digest words.

    The transpose below is the only layout shuffle in the kernel; block
    `i` only updates the lanes whose message actually extends to it."""
    w = jnp.moveaxis(words, 0, -1)                  # (max_blocks, 16, B)
    batch = w.shape[-1]
    state = tuple(jnp.full((batch,), _H0[i], jnp.uint32)
                  for i in range(8))

    def block_body(i, st):
        blk = jax.lax.dynamic_index_in_dim(w, i, 0, keepdims=False)
        new = _compress(st, blk)
        active = i < n_blocks                        # (B,) bool
        return tuple(jnp.where(active, n, o) for n, o in zip(new, st))

    state = jax.lax.fori_loop(0, w.shape[0], block_body, state)
    return jnp.stack(state, axis=-1)                # (B, 8)


@partial(jax.jit, static_argnames=())
def hash_blocks_jit(words, n_blocks):
    return hash_blocks_kernel(words, n_blocks)


# --- host-side batch preparation (numpy / C-speed per message) -------------

def pad_messages_np(msgs: Sequence[bytes],
                    max_blocks: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """FIPS-pad a batch into device-ready arrays: (B, max_blocks, 16)
    uint32 big-endian words + (B,) int32 true block counts. max_blocks=0
    sizes the array to the longest message; an explicit bucket shape
    must hold every message (asserted — routing splits oversize lanes
    out before prep)."""
    n = len(msgs)
    counts = np.array([blocks_for_len(len(m)) for m in msgs], np.int32) \
        if n else np.zeros((0,), np.int32)
    need = int(counts.max()) if n else 1
    if max_blocks <= 0:
        max_blocks = need
    assert need <= max_blocks, (need, max_blocks)
    words = np.zeros((n, max_blocks, 16), np.uint32)
    for i, m in enumerate(msgs):
        padded = m + b"\x80" + b"\x00" * ((-(len(m) + 9)) % 64) + \
            (8 * len(m)).to_bytes(8, "big")
        arr = np.frombuffer(padded, dtype=">u4").astype(np.uint32)
        words[i, :len(arr) // 16] = arr.reshape(-1, 16)
    return words, counts


def digests_to_bytes(digests: np.ndarray) -> List[bytes]:
    """(B, 8) uint32 digest words -> 32-byte big-endian digests."""
    blob = np.ascontiguousarray(np.asarray(digests, np.uint32)) \
        .astype(">u4").tobytes()
    return [blob[32 * i:32 * i + 32] for i in range(len(digests))]


def sha256_batch_device(msgs: Sequence[bytes],
                        max_blocks: int = 0) -> List[bytes]:
    """End-to-end batched hash (host prep + device kernel); the
    convenience path tests and bench use — production dispatch goes
    through crypto/batch_hasher.py's bucketed shapes."""
    if not msgs:
        return []
    words, counts = pad_messages_np(msgs, max_blocks)
    out = np.asarray(hash_blocks_jit(jnp.asarray(words),
                                     jnp.asarray(counts)))
    return digests_to_bytes(out)


def sha256_batch_host(msgs: Sequence[bytes]) -> List[bytes]:
    """The hashlib oracle both backends must match byte-for-byte."""
    return [hashlib.sha256(m).digest() for m in msgs]
