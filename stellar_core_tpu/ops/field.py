"""GF(2^255-19) arithmetic in int32 limbs, designed for the TPU VPU.

TPU-first design notes (this is the compute plane of the batched ed25519
verifier; see SURVEY.md §2.2 "batch-verify service"):

- LAYOUT: limbs on the LEADING axis, batch on the TRAILING axes — a field
  element batch is (20, B). TPU vector registers are (8 sublanes, 128
  lanes) tiled over the two minor dims; with the batch minormost, every
  elementwise op runs at full lane utilization. (The previous (B, 20)
  layout padded the 20-limb axis to 128 lanes — ~16% utilization — and
  was the round-2 bottleneck: 17.9K sigs/s vs the 100K target.)
- No 64-bit integers: TPUs have no native s64, so a field element is 20
  limbs of radix 2^13 held in int32. 13-bit limbs keep every product
  < 2^26 and every 20-term column sum < 2^31, so schoolbook
  multiplication accumulates safely in int32.
- Multiplication is 20 shifted partial products summed into 39 columns —
  per limb one (20, B)·broadcast multiply plus a zero-pad, all fusable
  into a single vector loop by XLA (no gather, no (B, 20, 39) blowup).
- Squaring uses the symmetric half-product: 210 column terms instead of
  400 (diagonal + doubled upper triangle). The scalar-mult ladder and the
  sqrt/inversion addition chains are ~70% squarings, so this matters.
- Carries are PARALLEL, not sequential: k rounds of (mask, shift, add)
  bound limbs at 2^13 + eps rather than fully normalizing. The invariant
  maintained between ops is limbs <= LIMB_BOUND (10100); a full
  sequential normalization (`fe_freeze`) happens only at equality checks.
- The wrap at 2^260: limb 20 would carry weight 2^260 ≡ 19·2^5 = 608
  (mod p), so high columns fold back with a multiply by 608.

Everything is shape-static and jit/vmap-friendly; batch dims broadcast.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

NLIMBS = 20
LIMB_BITS = 13
LIMB_MASK = (1 << LIMB_BITS) - 1
FOLD = 19 * 32  # 2^260 ≡ 19·2^5 (mod p)
LIMB_BOUND = 10100  # loose per-limb bound maintained between ops
# Bound audit (every op must keep limbs <= LIMB_BOUND and intermediate
# column sums < 2^31):
#   mul columns:  20 * 10100^2            = 2.04e9  < 2^31 (5% margin)
#   sq columns:   diagonal a_i^2 plus doubled pairs 2·a_i·a_j — the same
#                 value as the 20x20 ordered sum, so the same 2.04e9 bound;
#                 each doubled term 2·10100^2 = 2.04e8 < 2^31
#   fe_sub/neg:   10100 + 16382           = 26482; 1 carry round ->
#                 8191 + 3 + 3*608        = 10015  <= LIMB_BOUND
#   fe_add/x2:    2*10100 = 20200; 1 round -> 8191 + 2 + 2*608 = 9409
#   mul/sq tail:  post-round cols <= 2.57e5; fold <= 1.57e8; two carry
#                 rounds -> <= 10015

P = 2**255 - 19

# 64·p as a limb vector: every limb exceeds LIMB_BOUND, so a + _K64P - b is
# non-negative limb-wise whenever b's limbs are within bound.
# 32p = 2^260 - 608 = [8192-608, 8191, ..., 8191]; doubled below.
_K64P_NP = np.array([2 * (8192 - 608)] + [2 * 8191] * 19, np.int32)


def limbs_from_int(x: int) -> np.ndarray:
    out = np.zeros(NLIMBS, np.int32)
    for i in range(NLIMBS):
        out[i] = (x >> (LIMB_BITS * i)) & LIMB_MASK
    return out


def int_from_limbs(a) -> int:
    a = np.asarray(a)
    return sum(int(a[i, ...]) << (LIMB_BITS * i) for i in range(NLIMBS))


def _bcast(v: np.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """Static (20,) limb vector broadcast against (20, ...batch)."""
    return jnp.asarray(v).reshape((NLIMBS,) + (1,) * (like.ndim - 1))


def _carry_round_20(c: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry round over 20 limbs with top fold (2^260 wrap)."""
    lo = c & LIMB_MASK
    hi = c >> LIMB_BITS
    wrapped = jnp.concatenate([hi[19:20] * FOLD, hi[:19]], axis=0)
    return lo + wrapped


def fe_carry(c: jnp.ndarray, rounds: int = 2) -> jnp.ndarray:
    for _ in range(rounds):
        c = _carry_round_20(c)
    return c


def fe_add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return fe_carry(a + b, rounds=1)


def fe_sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return fe_carry(a + _bcast(_K64P_NP, a) - b, rounds=1)


def fe_neg(a: jnp.ndarray) -> jnp.ndarray:
    return fe_carry(_bcast(_K64P_NP, a) - a, rounds=1)


def fe_mul_small(a: jnp.ndarray, c: int) -> jnp.ndarray:
    """Multiply by a small constant (c·LIMB_BOUND must stay < 2^31);
    c <= 2 for the 1-round carry bound to hold."""
    assert c <= 2
    return fe_carry(a * c, rounds=1)


def _pad39(p: jnp.ndarray, lo: int) -> jnp.ndarray:
    """Place a (k, ...) strip at column offset `lo` inside (39, ...)."""
    hi = 39 - lo - p.shape[0]
    return jnp.pad(p, ((lo, hi),) + ((0, 0),) * (p.ndim - 1))


def _columns_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Product columns c[k] = Σ_{i+j=k} a_i·b_j as (39, ...): 20 shifted
    broadcast partial products, summed. All terms < 2^31 (bound audit)."""
    terms = [_pad39(a[i][None] * b, i) for i in range(NLIMBS)]
    return sum(terms)


def _columns_sq(a: jnp.ndarray) -> jnp.ndarray:
    """Squaring columns via symmetry: diagonal a_i² at column 2i plus
    doubled upper-triangle strips — 210 products instead of 400."""
    diag = a * a                                   # (20, ...) at cols 0,2,..38
    z = jnp.zeros_like(diag)
    inter = jnp.stack([diag, z], axis=1).reshape(
        (2 * NLIMBS,) + a.shape[1:])[:39]          # interleave with zeros
    terms = [inter]
    for i in range(NLIMBS - 1):
        strip = (a[i] * 2)[None] * a[i + 1:]       # cols 2i+1 .. i+19
        terms.append(_pad39(strip, 2 * i + 1))
    return sum(terms)


def _reduce39(c: jnp.ndarray) -> jnp.ndarray:
    """Columns (39, ...) → field element: one widening carry round (cols
    drop to <= 2^13 + 2^31>>13 ~ 2.6e5, so the 608-fold stays in int32:
    2.6e5 * 609 ~ 1.6e8), fold the high 20 columns (2^(260+13j) ≡ 608·2^13j
    mod p; col 39 starts at zero so a single round leaves no 2^520 wrap),
    then two parallel carry rounds."""
    lo = c & LIMB_MASK
    hi = c >> LIMB_BITS
    z1 = jnp.zeros_like(c[:1])
    c = jnp.concatenate([lo, z1], axis=0) + jnp.concatenate([z1, hi], axis=0)
    low = c[:NLIMBS] + FOLD * c[NLIMBS:]
    return fe_carry(low, rounds=2)


def fe_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _reduce39(_columns_mul(a, b))


def fe_sq(a: jnp.ndarray) -> jnp.ndarray:
    return _reduce39(_columns_sq(a))


def fe_one(batch_shape=()) -> jnp.ndarray:
    one = np.zeros(NLIMBS, np.int32)
    one[0] = 1
    return jnp.broadcast_to(
        jnp.asarray(one).reshape((NLIMBS,) + (1,) * len(batch_shape)),
        (NLIMBS, *batch_shape))


def fe_zero(batch_shape=()) -> jnp.ndarray:
    return jnp.zeros((NLIMBS, *batch_shape), jnp.int32)


def fe_pow(x: jnp.ndarray, exp_bits_msb_first) -> jnp.ndarray:
    """x^e via square-and-multiply inside a fori_loop (compiles once,
    no 250-deep unrolled trace). exp_bits is a static 0/1 numpy array."""
    bits = jnp.asarray(np.asarray(exp_bits_msb_first, np.int32))
    n = bits.shape[0]

    def body(i, r):
        r = fe_sq(r)
        rx = fe_mul(r, x)
        return jnp.where(bits[i] != 0, rx, r)

    # start from x for the leading 1 bit
    return jax.lax.fori_loop(1, n, body, x)


def _sqn(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """x^(2^n) via a fori_loop of squarings."""
    if n == 1:
        return fe_sq(x)
    return jax.lax.fori_loop(0, n, lambda i, v: fe_sq(v), x)


def fe_pow_p58(x: jnp.ndarray) -> jnp.ndarray:
    """x^((p-5)/8) = x^(2^252 - 3) via the standard curve25519 addition
    chain (ref10 pow22523 structure): 252 squarings + 12 multiplies,
    instead of square-and-multiply's ~250 multiplies — decompression is
    2 of these per signature, so this cuts ~15% of total verify work."""
    z2 = fe_sq(x)                      # 2
    z8 = _sqn(z2, 2)                   # 8
    z9 = fe_mul(x, z8)                 # 9
    z11 = fe_mul(z2, z9)               # 11
    z22 = fe_sq(z11)                   # 22
    z_5_0 = fe_mul(z9, z22)            # 2^5 - 1
    z_10_0 = fe_mul(_sqn(z_5_0, 5), z_5_0)      # 2^10 - 1
    z_20_0 = fe_mul(_sqn(z_10_0, 10), z_10_0)   # 2^20 - 1
    z_40_0 = fe_mul(_sqn(z_20_0, 20), z_20_0)   # 2^40 - 1
    z_50_0 = fe_mul(_sqn(z_40_0, 10), z_10_0)   # 2^50 - 1
    z_100_0 = fe_mul(_sqn(z_50_0, 50), z_50_0)  # 2^100 - 1
    z_200_0 = fe_mul(_sqn(z_100_0, 100), z_100_0)  # 2^200 - 1
    z_250_0 = fe_mul(_sqn(z_200_0, 50), z_50_0)    # 2^250 - 1
    return fe_mul(_sqn(z_250_0, 2), x)  # 2^252 - 3


def fe_freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Full canonical reduction to the unique representative in [0, p),
    with exact 13-bit limbs. Sequential carries — used only for equality
    tests and output encoding, a handful of times per verify."""
    # 1) exact sequential carry over 20 limbs, folding the top twice
    def seq_carry(v):
        limbs = []
        carry = jnp.zeros_like(v[0])
        for i in range(NLIMBS):
            t = v[i] + carry
            limbs.append(t & LIMB_MASK)
            carry = t >> LIMB_BITS
        return jnp.stack(limbs, axis=0), carry

    v, c = seq_carry(a)
    v = v.at[0].add(c * FOLD)
    v, c = seq_carry(v)  # c == 0 now; value < 2^260
    # 2) fold bits 255..259: hi = limb19 >> 8, v mod 2^255 + 19*hi
    for _ in range(2):
        hi = v[19] >> 8
        v = v.at[19].set(v[19] & 0xFF)
        v = v.at[0].add(19 * hi)
        v, _ = seq_carry(v)
    # 3) value < 2^255 + eps; conditional subtract p via the +19 trick:
    #    v >= p  <=>  v + 19 >= 2^255
    t = v.at[0].add(19)
    t, _ = seq_carry(t)
    ge = (t[19] >> 8) > 0
    t = t.at[19].set(t[19] & 0xFF)
    return jnp.where(ge[None], t, v)


def fe_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Constant-shape equality over the canonical forms: (...,) bool."""
    return jnp.all(fe_freeze(a) == fe_freeze(b), axis=0)


def fe_is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(fe_freeze(a) == 0, axis=0)


def fe_parity(a: jnp.ndarray) -> jnp.ndarray:
    """Low bit of the canonical representative."""
    return fe_freeze(a)[0] & 1
