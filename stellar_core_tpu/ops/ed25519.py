"""Batched ed25519 verification on TPU: the hot compute path.

Design (TPU-first; replaces the reference's per-call libsodium
`crypto_sign_verify_detached`, /root/reference/src/crypto/SecretKey.cpp:332):

- Verification equation (RFC 8032, cofactorless — matching the OpenSSL CPU
  backend semantics exactly): [S]B == R + [k]A with k = SHA512(R‖A‖M) mod L.
  We compute Q = [S]B + [k](−A) on-device and compare with the decompressed
  R projectively (no inversion).
- LAYOUT: all device arrays are limb-first / batch-last ((20, B) field
  elements, (64, B) scalar digits) so the batch rides the TPU lane
  dimension at full width; see ops/field.py header. The public
  `verify_kernel` still takes batch-first arrays (the host/byte layout)
  and transposes once at the jit boundary.
- Points are (x, y, z, t) TUPLES of (20, B) field elements — no stacked
  (4, 20) axis for XLA to pad; each coordinate is an independent
  full-lane array.
- Host does the byte-level work that TPUs are bad at: SHA-512 (tiny
  messages), canonicality prechecks (S < L, y < p), bit-slicing keys into
  13-bit limbs and scalars into 4-bit windows — all numpy-vectorized
  across the batch except the per-item SHA-512 + mod L (C-speed hashlib).
- Scalars use SIGNED radix-16 digits in [−8, 8) (wNAF-style recoding on
  the host): table magnitudes only span 0..8, so both lookup tables are
  9-wide instead of 16-wide (≈44% less masked-select traffic — the
  select is pure data movement on the VPU) and the per-item table build
  shrinks from 14 point ops to 7. Negation is a cheap conditional on the
  selected point (Edwards negation: x/T flip for extended, y±x swap for
  Niels).
- Fixed-base [S]B uses a precomputed 64×9 signed-radix-16 table of B
  multiples in Niels form (y+x, y−x, 2dxy): 64 masked-lookup additions,
  zero doublings.
- Variable-base [k](−A) builds a per-item 9-entry extended-coordinate
  table (4 doublings + 3 additions) then runs 63 iterations of 4
  doublings + 1 table addition inside a fori_loop.
- Point formulas: extended coordinates, a=−1 twisted Edwards unified
  add/double (complete on the prime-order subgroup); doublings skip the
  T output unless the next step reads it.

A pure-Python (int) implementation lives alongside for table generation and
as a test oracle.
"""

from __future__ import annotations

import hashlib
import os
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from .field import (
    NLIMBS, LIMB_BITS, LIMB_MASK, P, _bcast, fe_add, fe_carry, fe_eq,
    fe_freeze, fe_is_zero, fe_mul, fe_mul_small, fe_neg, fe_one, fe_parity,
    fe_pow_p58, fe_sq, fe_sub, fe_zero, int_from_limbs, limbs_from_int,
)

# --- curve constants (python ints) ----------------------------------------

L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
B_Y = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int | None:
    """Python-int point decompression (RFC 8032 §5.1.3 math)."""
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


B_X = _recover_x(B_Y, 0)


class _Pt:
    """Python-int extended-coordinate point (oracle + table generation)."""

    __slots__ = ("x", "y", "z", "t")

    def __init__(self, x, y, z=1, t=None):
        self.x, self.y, self.z = x % P, y % P, z % P
        self.t = (x * y * pow(z, P - 2, P)) % P if t is None else t % P

    @classmethod
    def identity(cls):
        return cls(0, 1, 1, 0)

    def add(self, o: "_Pt") -> "_Pt":
        a = (self.y - self.x) * (o.y - o.x) % P
        b = (self.y + self.x) * (o.y + o.x) % P
        c = self.t * D2 % P * o.t % P
        d = 2 * self.z * o.z % P
        e, f, g, h = b - a, d - c, d + c, b + a
        return _Pt(e * f % P, g * h % P, f * g % P, e * h % P)

    def dbl(self) -> "_Pt":
        a = self.x * self.x % P
        b = self.y * self.y % P
        c = 2 * self.z * self.z % P
        h = a + b
        e = h - (self.x + self.y) ** 2 % P
        g = a - b
        f = c + g
        return _Pt(e * f % P, g * h % P, f * g % P, e * h % P)

    def mul(self, n: int) -> "_Pt":
        q = _Pt.identity()
        p = self
        while n:
            if n & 1:
                q = q.add(p)
            p = p.dbl()
            n >>= 1
        return q

    def affine(self) -> tuple[int, int]:
        zi = pow(self.z, P - 2, P)
        return (self.x * zi % P, self.y * zi % P)

    def compress(self) -> bytes:
        x, y = self.affine()
        return int.to_bytes(y | ((x & 1) << 255), 32, "little")


B_POINT = _Pt(B_X, B_Y)


def verify_oracle(pub: bytes, sig: bytes, msg: bytes) -> bool:
    """Pure-Python RFC 8032 cofactorless verify — the semantics oracle both
    backends must match."""
    if len(pub) != 32 or len(sig) != 64:
        return False
    r_bytes, s_bytes = sig[:32], sig[32:]
    s = int.from_bytes(s_bytes, "little")
    if s >= L:
        return False
    ay = int.from_bytes(pub, "little")
    a_sign, ay = ay >> 255, ay & ((1 << 255) - 1)
    ry = int.from_bytes(r_bytes, "little")
    r_sign, ry = ry >> 255, ry & ((1 << 255) - 1)
    ax = _recover_x(ay, a_sign)
    rx = _recover_x(ry, r_sign)
    if ax is None or rx is None:
        return False
    k = int.from_bytes(hashlib.sha512(r_bytes + pub + msg).digest(),
                       "little") % L
    a_neg = _Pt(P - ax if ax else 0, ay)
    q = B_POINT.mul(s).add(a_neg.mul(k))  # [S]B − [k]A
    qx, qy = q.affine()
    return qx == rx and qy == ry


# --- precomputed fixed-base table (Niels form) -----------------------------

def _build_fixed_table() -> np.ndarray:
    """table[j, v] = Niels(v · 16^j · B) as 3×20 limbs: (y+x, y−x, 2dxy).
    Only magnitudes 0..8 are stored — scalars are recoded to signed
    radix-16 digits in [−8, 8) and the kernel negates the selected entry
    (a y±x swap plus an xy2d negation) when the digit is negative."""
    tab = np.zeros((64, 9, 3, NLIMBS), np.int32)
    base = B_POINT
    for j in range(64):
        acc = _Pt.identity()
        for v in range(9):
            x, y = acc.affine() if v else (0, 1)
            tab[j, v, 0] = limbs_from_int((y + x) % P)
            tab[j, v, 1] = limbs_from_int((y - x) % P)
            tab[j, v, 2] = limbs_from_int(2 * D * x % P * y % P)
            acc = acc.add(base)
        for _ in range(4):
            base = base.dbl()
    return tab


_FIXED_TABLE: np.ndarray | None = None


def fixed_table() -> np.ndarray:
    global _FIXED_TABLE
    if _FIXED_TABLE is None:
        _FIXED_TABLE = _build_fixed_table()
    return _FIXED_TABLE


# --- jax point ops: points are (x, y, z, t) tuples of (20, ...) limbs ------

Point = tuple  # (x, y, z, t)


def pt_identity(batch_shape=()) -> Point:
    return (fe_zero(batch_shape), fe_one(batch_shape),
            fe_one(batch_shape), fe_zero(batch_shape))


_D2_LIMBS = limbs_from_int(D2)
_SQRT_M1_LIMBS = limbs_from_int(SQRT_M1)
_D_LIMBS = limbs_from_int(D)


def pt_add(p: Point, q: Point) -> Point:
    """Unified a=−1 extended addition (add-2008-hwcd-3)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe_mul(fe_sub(y1, x1), fe_sub(y2, x2))
    b = fe_mul(fe_add(y1, x1), fe_add(y2, x2))
    c = fe_mul(fe_mul(t1, _bcast(_D2_LIMBS, t1)), t2)
    d = fe_mul_small(fe_mul(z1, z2), 2)
    e = fe_sub(b, a)
    f = fe_sub(d, c)
    g = fe_add(d, c)
    h = fe_add(b, a)
    return (fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_add_folded(p: Point, q: Point, need_t: bool = False) -> Point:
    """Extended add where q's T coordinate is pre-multiplied by 2d (table
    form). Ladder adds feed doublings, which never read T, so by default
    the output T (the e·h multiply) is skipped; the final window add
    passes need_t=True because the fixed-base Niels chain reads it."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2d = q
    a = fe_mul(fe_sub(y1, x1), fe_sub(y2, x2))
    b = fe_mul(fe_add(y1, x1), fe_add(y2, x2))
    c = fe_mul(t1, t2d)
    d = fe_mul_small(fe_mul(z1, z2), 2)
    e = fe_sub(b, a)
    f = fe_sub(d, c)
    g = fe_add(d, c)
    h = fe_add(b, a)
    t = fe_mul(e, h) if need_t else fe_zero(x1.shape[1:])
    return (fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), t)


def pt_add_niels(p: Point, n: tuple) -> Point:
    """Mixed addition with a precomputed Niels point (y+x, y−x, 2dxy)."""
    x1, y1, z1, t1 = p
    ypx, ymx, xy2d = n
    a = fe_mul(fe_sub(y1, x1), ymx)
    b = fe_mul(fe_add(y1, x1), ypx)
    c = fe_mul(t1, xy2d)
    d = fe_mul_small(z1, 2)
    e = fe_sub(b, a)
    f = fe_sub(d, c)
    g = fe_add(d, c)
    h = fe_add(b, a)
    return (fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_dbl(p: Point, need_t: bool = True) -> Point:
    """a=−1 extended doubling (dbl-2008-hwcd). Doubling never READS the
    T coordinate, so ladder doublings whose output feeds another doubling
    pass need_t=False and skip the e·h multiply (3 of every 4 ladder
    steps). The four squarings use the symmetric half-product."""
    x1, y1, z1, _ = p
    a = fe_sq(x1)
    b = fe_sq(y1)
    c = fe_mul_small(fe_sq(z1), 2)
    h = fe_add(a, b)
    e = fe_sub(h, fe_sq(fe_add(x1, y1)))
    g = fe_sub(a, b)
    f = fe_add(c, g)
    t = fe_mul(e, h) if need_t else fe_zero(x1.shape[1:])
    return (fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), t)


def pt_neg(p: Point) -> Point:
    x, y, z, t = p
    return (fe_neg(x), y, z, fe_neg(t))


def fe_decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray):
    """Decompress (y, sign) → (x, ok). y is canonical (host-checked y < p).

    x = sqrt((y²−1)/(dy²+1)); multiply by sqrt(−1) when the first candidate
    fails; reject when neither squares to the target or x=0 with sign=1.
    """
    one = fe_one(y_limbs.shape[1:])
    y2 = fe_sq(y_limbs)
    u = fe_sub(y2, one)
    v = fe_add(fe_mul(y2, _bcast(_D_LIMBS, y2)), one)
    v3 = fe_mul(fe_sq(v), v)
    v7 = fe_mul(fe_sq(v3), v)
    x = fe_mul(fe_mul(u, v3), fe_pow_p58(fe_mul(u, v7)))
    vx2 = fe_mul(v, fe_sq(x))
    ok1 = fe_eq(vx2, u)
    ok2 = fe_eq(vx2, fe_neg(u))
    x_alt = fe_mul(x, _bcast(_SQRT_M1_LIMBS, x))
    x = jnp.where((ok2 & ~ok1)[None], x_alt, x)
    ok = ok1 | ok2
    x_is_zero = fe_is_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    # fix parity
    flip = (fe_parity(x) != sign)
    x = jnp.where(flip[None], fe_neg(x), x)
    return x, ok


def _select_signed9(stacks: tuple, dig: jnp.ndarray) -> tuple:
    """Signed-digit select: each stack (9, 20, B) of extended coords with
    T pre-folded by 2d, dig (B,) in [−8, 8). Selects |dig| via a masked
    sum (XLA fuses it into vector selects) then conditionally negates the
    point — Edwards negation flips x and t only."""
    mag = jnp.abs(dig)
    neg = dig < 0
    oh = (jnp.arange(9, dtype=jnp.int32)[:, None] ==
          mag[None, :]).astype(jnp.int32)             # (9, B)
    ohc = oh[:, None, :]                              # (9, 1, B)
    x, y, z, t2d = tuple(jnp.sum(s * ohc, axis=0) for s in stacks)
    x = jnp.where(neg[None], fe_neg(x), x)
    t2d = jnp.where(neg[None], fe_neg(t2d), t2d)
    return (x, y, z, t2d)


def verify_kernel(ay: jnp.ndarray, a_sign: jnp.ndarray,
                  ry: jnp.ndarray, r_sign: jnp.ndarray,
                  s_nibs: jnp.ndarray, k_nibs: jnp.ndarray) -> jnp.ndarray:
    """Batched verify core. All inputs int32, batch-first (host layout):
    ay, ry: (B, 20) canonical y limbs; a_sign, r_sign: (B,);
    s_nibs, k_nibs: (B, 64) SIGNED radix-16 digits in [−8, 8)
    (LSB-first, host-recoded by signed_recode_nibs_np) of S and of
    k = SHA512(R‖A‖M) mod L. Returns (B,) bool.

    Internally everything is limb-first (20, B) / digit-first (64, B); the
    transposes below are the only layout shuffles in the whole kernel.
    """
    ay = jnp.moveaxis(ay, -1, 0)
    ry = jnp.moveaxis(ry, -1, 0)
    s_nibs = jnp.moveaxis(s_nibs, -1, 0)
    k_nibs = jnp.moveaxis(k_nibs, -1, 0)
    batch = ay.shape[1:]

    ax, a_ok = fe_decompress(ay, a_sign)
    rx, r_ok = fe_decompress(ry, r_sign)

    # A in extended coords, negated: Q = [S]B + [k](−A)
    neg_ax = fe_neg(ax)
    neg_at = fe_neg(fe_mul(ax, ay))
    a_pt = (neg_ax, ay, fe_one(batch), neg_at)

    # per-item table of v·(−A), v = 0..8 (signed digits select a
    # magnitude and negate), extended coords; entry T is pre-multiplied
    # by 2d so the ladder add does c = T1·(2d·T2) in ONE multiply
    # (Niels-style T folding)
    entries = [pt_identity(batch), a_pt]
    for v in range(2, 9):
        if v % 2 == 0:
            entries.append(pt_dbl(entries[v // 2]))
        else:
            entries.append(pt_add(entries[v - 1], a_pt))
    d2 = _bcast(_D2_LIMBS, ax)
    a_table = tuple(
        jnp.stack([e[c] if c < 3 else fe_mul(e[3], d2) for e in entries],
                  axis=0)
        for c in range(4))                       # 4 × (9, 20, B)

    # variable-base: MSB-first over 64 signed digits of k. The window
    # add's T output is never read (the next 4 doublings ignore T; the
    # 4th doubling regenerates it), so the add also skips its e·h
    # multiply.
    def vb_window(q, dig, need_t):
        q = pt_dbl(q, need_t=False)
        q = pt_dbl(q, need_t=False)
        q = pt_dbl(q, need_t=False)
        q = pt_dbl(q, need_t=True)
        return pt_add_folded(q, _select_signed9(a_table, dig),
                             need_t=need_t)

    def vb_body(i, q):
        return vb_window(q, k_nibs[63 - i], False)

    q = jax.lax.fori_loop(0, 63, vb_body, pt_identity(batch))
    # final window peeled: its add DOES produce T, which the fixed-base
    # Niels chain below consumes
    q = vb_window(q, k_nibs[0], True)

    # fixed-base: Σ_j table[j][s_dig_j], 64 Niels additions, no doublings
    ftab = jnp.asarray(fixed_table())  # (64, 9, 3, 20) static

    def fb_body(j, acc):
        row = jax.lax.dynamic_index_in_dim(ftab, j, axis=0,
                                           keepdims=False)  # (9, 3, 20)
        dig = s_nibs[j]                                     # (B,)
        mag = jnp.abs(dig)
        fneg = (dig < 0)[None]
        oh = (jnp.arange(9, dtype=jnp.int32)[:, None] ==
              mag[None, :]).astype(jnp.int32)               # (9, B)
        # (9, 3, 20, 1) * (9, 1, 1, B) summed over v → (3, 20, B)
        sel = jnp.sum(row[..., None] * oh[:, None, None, :], axis=0)
        # Niels negation: swap (y+x, y−x), negate 2dxy
        ypx = jnp.where(fneg, sel[1], sel[0])
        ymx = jnp.where(fneg, sel[0], sel[1])
        xy2d = jnp.where(fneg, fe_neg(sel[2]), sel[2])
        return pt_add_niels(acc, (ypx, ymx, xy2d))

    q = jax.lax.fori_loop(0, 64, fb_body, q)

    # projective compare with affine R: X == rx·Z and Y == ry·Z
    xq, yq, zq, _ = q
    eq = fe_eq(xq, fe_mul(rx, zq)) & fe_eq(yq, fe_mul(ry, zq))
    return a_ok & r_ok & eq


# --- host-side batch preparation (numpy-vectorized) ------------------------

_L_BYTES_BE = np.frombuffer(L.to_bytes(32, "big"), np.uint8)
_P_BYTES_BE = np.frombuffer(P.to_bytes(32, "big"), np.uint8)


def bytes_to_limbs_np(b: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 → (B, 20) int32 13-bit limbs (little-endian value)."""
    x = b.astype(np.int64)
    out = np.zeros((*b.shape[:-1], NLIMBS), np.int64)
    for i in range(NLIMBS):
        bit = LIMB_BITS * i
        k, r = bit >> 3, bit & 7
        v = x[..., k] >> r
        if k + 1 < 32:
            v = v | (x[..., k + 1] << (8 - r))
        if k + 2 < 32:
            v = v | (x[..., k + 2] << (16 - r))
        out[..., i] = v & LIMB_MASK
    return out.astype(np.int32)


def bytes_to_nibs_np(b: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 → (B, 64) int32 radix-16 digits, LSB-first."""
    lo = (b & 15).astype(np.int32)
    hi = (b >> 4).astype(np.int32)
    return np.stack([lo, hi], axis=-1).reshape(*b.shape[:-1], 64)


def signed_recode_nibs_np(nibs: np.ndarray) -> np.ndarray:
    """(…, 64) unsigned radix-16 digits → signed digits in [−8, 8) with
    the same value (carry-propagating recode, vectorized over the batch;
    the 64-step loop is over digit positions, not items). Values are
    < 2^253 (S and k are both < L), so digit 63 is ≤ 1 and the final
    carry is always absorbed — asserted, since an overflow here would
    silently verify a wrong equation."""
    d = nibs.astype(np.int32).copy()
    carry = np.zeros(d.shape[:-1], np.int32)
    for i in range(d.shape[-1]):
        v = d[..., i] + carry
        carry = (v >= 8).astype(np.int32)
        d[..., i] = v - (carry << 4)
    assert not carry.any(), "signed recode overflow: input >= 2^253"
    return d


def _lex_lt_be(a: np.ndarray, bound_be: np.ndarray) -> np.ndarray:
    """Vectorized big-endian lexicographic a < bound over (B, 32) uint8."""
    diff = a != bound_be[None, :]
    first = np.argmax(diff, axis=-1)
    rows = np.arange(a.shape[0])
    return np.where(diff.any(axis=-1),
                    a[rows, first] < bound_be[first], False)


def _pack32(items, n: int, width: int) -> np.ndarray:
    """List of bytes → (n, width) uint8, zero-filling wrong-length items
    and normalizing the list length to n (short lists pad with invalid
    zero rows; callers mark those pre_ok=False via the length check)."""
    items = list(items[:n]) + [b""] * (n - len(items))
    blob = b"".join(x if len(x) == width else b"\x00" * width for x in items)
    return np.frombuffer(blob, np.uint8).reshape(n, width)


def prepare_batch(pubs: list[bytes], sigs: list[bytes],
                  msgs: list[bytes]) -> dict:
    """Host preprocessing: hashing, canonicality prechecks, bit-slicing.
    Returns device-ready int32 arrays + a host-side precheck mask.

    Everything is numpy-vectorized across the batch except the per-item
    SHA-512 + 512-bit mod L (hashlib/CPython bignum — C speed, ~1.5 µs
    per item; at the 100K sigs/s north star this is ~15% of one core,
    and it overlaps the device batch in the async backend)."""
    n = len(pubs)
    good = np.zeros(n, bool)
    for i in range(min(n, len(sigs), len(msgs))):
        good[i] = len(pubs[i]) == 32 and len(sigs[i]) == 64
    msgs = list(msgs[:n]) + [b""] * (n - len(msgs))
    pub_arr = _pack32(pubs, n, 32)
    sig_arr = _pack32(sigs, n, 64)

    if os.environ.get("SCT_NATIVE_PREP", "1") != "0":
        from .. import native
        prep = native.prepare_batch_native(pub_arr, sig_arr, msgs)
        if prep is not None:
            prep["pre_ok"] = prep["pre_ok"] & good
            # the native layer keeps the plain unsigned-nibble contract;
            # the kernel wants signed digits
            prep["s_nibs"] = signed_recode_nibs_np(prep["s_nibs"])
            prep["k_nibs"] = signed_recode_nibs_np(prep["k_nibs"])
            return prep
    r_arr = sig_arr[:, :32]
    s_arr = sig_arr[:, 32:]

    a_sign = (pub_arr[:, 31] >> 7).astype(np.int32)
    r_sign = (r_arr[:, 31] >> 7).astype(np.int32)
    ay = pub_arr.copy()
    ay[:, 31] &= 0x7F
    ry = r_arr.copy()
    ry[:, 31] &= 0x7F

    # canonicality prechecks, big-endian lexicographic compare
    s_ok = _lex_lt_be(s_arr[:, ::-1], _L_BYTES_BE)
    ay_ok = _lex_lt_be(ay[:, ::-1], _P_BYTES_BE)
    ry_ok = _lex_lt_be(ry[:, ::-1], _P_BYTES_BE)
    pre_ok = good & s_ok & ay_ok & ry_ok

    # k = SHA512(R‖A‖M) mod L — the only per-item loop
    k_bytes = bytearray(32 * n)
    for i in range(n):
        if not pre_ok[i]:
            continue
        h = hashlib.sha512(
            sig_arr[i, :32].tobytes() + pub_arr[i].tobytes() +
            msgs[i]).digest()
        k = int.from_bytes(h, "little") % L
        k_bytes[32 * i:32 * i + 32] = k.to_bytes(32, "little")
    k_arr = np.frombuffer(bytes(k_bytes), np.uint8).reshape(n, 32)

    zero_bad = pre_ok[:, None].astype(np.uint8)
    return {
        "ay": bytes_to_limbs_np(ay * zero_bad), "a_sign": a_sign,
        "ry": bytes_to_limbs_np(ry * zero_bad), "r_sign": r_sign,
        "s_nibs": signed_recode_nibs_np(bytes_to_nibs_np(s_arr * zero_bad)),
        "k_nibs": signed_recode_nibs_np(bytes_to_nibs_np(k_arr)),
        "pre_ok": pre_ok,
    }


@partial(jax.jit, static_argnames=())
def verify_batch_jit(ay, a_sign, ry, r_sign, s_nibs, k_nibs):
    return verify_kernel(ay, a_sign, ry, r_sign, s_nibs, k_nibs)


def verify_batch(pubs: list[bytes], sigs: list[bytes],
                 msgs: list[bytes]) -> np.ndarray:
    """End-to-end batched verify (host prep + device kernel)."""
    prep = prepare_batch(pubs, sigs, msgs)
    ok = np.asarray(verify_batch_jit(
        jnp.asarray(prep["ay"]), jnp.asarray(prep["a_sign"]),
        jnp.asarray(prep["ry"]), jnp.asarray(prep["r_sign"]),
        jnp.asarray(prep["s_nibs"]), jnp.asarray(prep["k_nibs"])))
    return ok & prep["pre_ok"]
